#!/usr/bin/env python
"""Closed-loop load generator for the online serving runtime.

Spins up a :class:`ServingRuntime`, registers one synthetic model per
requested family, and drives it closed-loop — ``--threads`` workers each
keep ONE request outstanding (submit, block on the future, repeat) for
``--requests`` iterations — then prints the latency distribution
(p50/p95/p99, interpolated from the ``serving.request.latency_ms``
histogram in the metrics registry) and sustained rows/s, plus the shed /
deadline / batch counters the run produced. Closed-loop is the honest
serving-latency posture: each worker's next arrival waits for its last
answer, so queueing delay shows up in the numbers instead of in an
unbounded backlog.

Examples::

    python tools/tpuml_loadgen.py --family kmeans --threads 16 --requests 200
    python tools/tpuml_loadgen.py --family logreg --rows 4 --max-batch 128 \
        --delay-ms 2 --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(family: str, d: int, k: int, seed: int):
    """A synthetic fitted model of the requested family (no training —
    the load generator measures the serving path, not the solver)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if family == "kmeans":
        from spark_rapids_ml_tpu.models.kmeans import KMeansModel

        return KMeansModel("loadgen-km", rng.normal(size=(k, d)))
    if family == "logreg":
        from spark_rapids_ml_tpu.models.logistic_regression import (
            LogisticRegressionModel,
        )

        return LogisticRegressionModel(
            "loadgen-logreg", rng.normal(size=(d, 1)), rng.normal(size=1)
        )
    if family == "linreg":
        from spark_rapids_ml_tpu.models.linear_regression import (
            LinearRegressionModel,
        )

        return LinearRegressionModel("loadgen-linreg", rng.normal(size=d), 0.5)
    if family == "pca":
        from spark_rapids_ml_tpu.models.pca import PCAModel

        q, _ = np.linalg.qr(rng.normal(size=(d, min(k, d))))
        return PCAModel("loadgen-pca", q, np.full(q.shape[1], 1.0 / q.shape[1]))
    raise SystemExit(f"unknown --family {family!r}")


# The percentile math moved next to the histogram type it reads
# (observability/metrics.py) so the serving shed-backoff hint shares it;
# re-exported here because scripts import it from the loadgen.
from spark_rapids_ml_tpu.observability.metrics import (  # noqa: E402,F401
    percentile_from_histogram,
)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--family", default="kmeans",
                        choices=("kmeans", "logreg", "linreg", "pca"))
    parser.add_argument("--threads", type=int, default=16,
                        help="closed-loop workers (one outstanding request each)")
    parser.add_argument("--requests", type=int, default=200,
                        help="requests per worker")
    parser.add_argument("--rows", type=int, default=1,
                        help="rows per request (1 = single-row scoring)")
    parser.add_argument("--features", type=int, default=32)
    parser.add_argument("--k", type=int, default=8,
                        help="clusters / components for kmeans / pca")
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--delay-ms", type=float, default=None)
    parser.add_argument("--queue", type=int, default=None)
    parser.add_argument("--mem-budget", type=int, default=None)
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-request deadline in seconds")
    parser.add_argument("--warm", action="store_true",
                        help="pre-compile the expected row buckets before timing")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable one-line summary only")
    args = parser.parse_args()

    import numpy as np

    from spark_rapids_ml_tpu.serving import (
        DeadlineExceeded,
        Overloaded,
        ServingRuntime,
    )
    from spark_rapids_ml_tpu.serving.batcher import _latency_hist
    from spark_rapids_ml_tpu.utils.tracing import counter_value

    model = build_model(args.family, args.features, args.k, args.seed)
    rng = np.random.default_rng(args.seed + 1)
    probes = rng.normal(size=(args.threads, args.requests, args.rows, args.features))

    rt = ServingRuntime(
        max_batch=args.max_batch,
        max_delay_ms=args.delay_ms,
        queue_limit=args.queue,
        mem_budget=args.mem_budget,
    )
    rt.register(args.family, model)
    if args.warm:
        # Every bucket the run can hit: rows per request up to a full batch.
        rt.warm(args.family, buckets=(args.rows, rt.max_batch))

    errors = {"overloaded": 0, "deadline": 0, "other": 0}
    ok = [0] * args.threads
    err_lock = threading.Lock()

    def worker(tid: int) -> None:
        for j in range(args.requests):
            try:
                rt.submit(
                    args.family, probes[tid, j], timeout=args.timeout
                ).result()
                ok[tid] += 1
            except Overloaded as exc:
                with err_lock:
                    errors["overloaded"] += 1
                # Honor the server's backoff hint (p95 of the live
                # latency histogram ~= one queue residency), capped so a
                # pathological tail can't park the generator.
                if exc.retry_after_ms > 0:
                    time.sleep(min(exc.retry_after_ms, 100.0) / 1e3)
            except DeadlineExceeded:
                with err_lock:
                    errors["deadline"] += 1
            except Exception:  # noqa: BLE001 - loadgen keeps driving
                with err_lock:
                    errors["other"] += 1

    c_dispatch0 = counter_value("serving.batch.dispatch")
    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(args.threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    rt.close()

    completed = sum(ok)
    rows_done = completed * args.rows
    hist = _latency_hist().value()
    dispatches = counter_value("serving.batch.dispatch") - c_dispatch0
    summary = {
        "family": args.family,
        "threads": args.threads,
        "requests": args.threads * args.requests,
        "completed": completed,
        "rows_per_request": args.rows,
        "rows_per_s": round(rows_done / wall, 1) if wall > 0 else 0.0,
        "wall_s": round(wall, 3),
        "p50_ms": round(percentile_from_histogram(hist, 0.50), 3),
        "p95_ms": round(percentile_from_histogram(hist, 0.95), 3),
        "p99_ms": round(percentile_from_histogram(hist, 0.99), 3),
        "batches": dispatches,
        "mean_batch_requests": round(completed / dispatches, 2) if dispatches else 0,
        "shed_queue": counter_value("serving.shed.queue"),
        "shed_memory": counter_value("serving.shed.memory"),
        "deadline_expired": counter_value("serving.deadline.expired"),
        "errors": errors,
    }
    if args.json:
        print(json.dumps(summary))
        return
    print(f"loadgen [{args.family}] {summary['requests']} requests "
          f"x {args.rows} row(s), {args.threads} closed-loop workers")
    print(f"  rows/s:      {summary['rows_per_s']}")
    print(f"  latency ms:  p50={summary['p50_ms']}  "
          f"p95={summary['p95_ms']}  p99={summary['p99_ms']}")
    print(f"  batching:    {dispatches} dispatches, "
          f"{summary['mean_batch_requests']} requests/batch")
    print(f"  shed:        queue={summary['shed_queue']} "
          f"memory={summary['shed_memory']} "
          f"deadline={summary['deadline_expired']}")
    if any(errors.values()):
        print(f"  errors:      {errors}")


if __name__ == "__main__":
    main()
