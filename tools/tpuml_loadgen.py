#!/usr/bin/env python
"""Closed-loop load generator for the online serving runtime.

Spins up a :class:`ServingRuntime`, registers one synthetic model per
requested family, and drives it closed-loop — ``--threads`` workers each
keep ONE request outstanding (submit, block on the future, repeat) for
``--requests`` iterations — then prints the latency distribution
(p50/p95/p99, interpolated from the ``serving.request.latency_ms``
histogram in the metrics registry) and sustained rows/s, plus the shed /
deadline / batch counters the run produced. Closed-loop is the honest
serving-latency posture: each worker's next arrival waits for its last
answer, so queueing delay shows up in the numbers instead of in an
unbounded backlog.

``--workers N`` drives the DISTRIBUTED serving tier instead: a
:class:`RoutingRuntime` spreading the same closed loop across N worker
member processes (serving/router.py). The summary then adds a per-member
section (rows/s, shed count, routed/completed as the router saw them)
and reads the latency percentiles from the MERGED per-process metric
shards (each member flushes ``metrics-<pid>.json`` into the telemetry
dir on drain), so p50/p95/p99 cover every member's histogram, not just
the router process's.

``--ramp "rps1:s1,rps2:s2,..."`` switches to piecewise traffic phases
instead of the fixed per-worker request count: each phase OFFERS the
target rate for its duration (shared arrival pacer across the worker
pool; a slot whose turn has passed fires immediately, so a gang slower
than the offered rate shows the pressure as latency, never as a silent
backlog), and the report carries per-phase p50/p95/shed — the diurnal
ramp-up/ramp-down episodes the elastic serving tier scales to.

Examples::

    python tools/tpuml_loadgen.py --family kmeans --threads 16 --requests 200
    python tools/tpuml_loadgen.py --family logreg --rows 4 --max-batch 128 \
        --delay-ms 2 --json
    python tools/tpuml_loadgen.py --workers 4 --threads 16 --requests 100
    python tools/tpuml_loadgen.py --workers 2 --threads 8 \
        --ramp "50:5,400:10,50:5" --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(family: str, d: int, k: int, seed: int):
    """A synthetic fitted model of the requested family (no training —
    the load generator measures the serving path, not the solver)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    if family == "kmeans":
        from spark_rapids_ml_tpu.models.kmeans import KMeansModel

        return KMeansModel("loadgen-km", rng.normal(size=(k, d)))
    if family == "logreg":
        from spark_rapids_ml_tpu.models.logistic_regression import (
            LogisticRegressionModel,
        )

        return LogisticRegressionModel(
            "loadgen-logreg", rng.normal(size=(d, 1)), rng.normal(size=1)
        )
    if family == "linreg":
        from spark_rapids_ml_tpu.models.linear_regression import (
            LinearRegressionModel,
        )

        return LinearRegressionModel("loadgen-linreg", rng.normal(size=d), 0.5)
    if family == "pca":
        from spark_rapids_ml_tpu.models.pca import PCAModel

        q, _ = np.linalg.qr(rng.normal(size=(d, min(k, d))))
        return PCAModel("loadgen-pca", q, np.full(q.shape[1], 1.0 / q.shape[1]))
    raise SystemExit(f"unknown --family {family!r}")


# The percentile math moved next to the histogram type it reads
# (observability/metrics.py) so the serving shed-backoff hint shares it;
# re-exported here because scripts import it from the loadgen.
from spark_rapids_ml_tpu.observability.metrics import (  # noqa: E402,F401
    percentile_from_histogram,
)


def _round_pct(hist, q):
    """percentile_from_histogram returns None on an empty histogram; a
    zero-completion run reports null percentiles, not a crash."""
    p = percentile_from_histogram(hist, q)
    return round(p, 3) if p is not None else None


def _merged_member_metrics(telemetry_dir):
    """The gang's ``serving.request.latency_ms`` histogram and summed
    counters, merged across every member's flushed metric shard
    (``observability.trace.assemble`` does the bucket-wise merge; JSON
    round-trips bucket edges as strings, so they are floated back)."""
    from spark_rapids_ml_tpu.observability.trace import assemble

    merged = assemble(telemetry_dir)["metrics"]["merged"]
    hist = {"buckets": {}, "sum": 0.0, "count": 0}
    for cell in merged.get("histograms", {}).get(
        "serving.request.latency_ms", {}
    ).values():
        for le, cum in cell.get("buckets", {}).items():
            le = float(le)
            hist["buckets"][le] = hist["buckets"].get(le, 0) + cum
        hist["sum"] += cell.get("sum", 0.0)
        hist["count"] += cell.get("count", 0)
    return hist, merged.get("counters", {})


class FreshnessTable:
    """Requests answered per concrete ``(model, version)`` — the
    freshness column. Every completed future carries the version whose
    weights executed it (serving/batcher.py, serving/router.py), so a
    hot swap shows up here as version N's ``last_seen_s`` preceding
    version N+1's ``first_seen_s``: the oracle for "monotone model
    freshness, no mixed-version batch" during a refit→swap cycle."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._rows: dict = {}

    def note(self, fut) -> None:
        version = getattr(fut, "model_version", None)
        if version is None:
            return
        key = (str(getattr(fut, "model_name", "")), int(version))
        now = time.perf_counter() - self._t0
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                self._rows[key] = {
                    "requests": 1, "first_seen_s": now, "last_seen_s": now,
                }
            else:
                row["requests"] += 1
                row["last_seen_s"] = now

    def report(self) -> list:
        with self._lock:
            return [
                {
                    "model": name, "version": version,
                    "requests": row["requests"],
                    "first_seen_s": round(row["first_seen_s"], 3),
                    "last_seen_s": round(row["last_seen_s"], 3),
                }
                for (name, version), row in sorted(self._rows.items())
            ]


def _parse_ramp(spec: str):
    """``"rps1:s1,rps2:s2,..."`` -> [(rps, seconds), ...] with loud
    rejection of malformed phases (a typo'd ramp silently offering the
    wrong load would invalidate the whole measurement)."""
    phases = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        rps, sep, secs = part.partition(":")
        if not sep:
            raise SystemExit(
                f"malformed --ramp phase {part!r}: expected <rps>:<seconds>"
            )
        try:
            pair = (float(rps), float(secs))
        except ValueError:
            raise SystemExit(
                f"malformed --ramp phase {part!r}: expected <rps>:<seconds>"
            )
        if pair[0] <= 0 or pair[1] <= 0:
            raise SystemExit(
                f"--ramp phase {part!r}: rate and duration must be > 0"
            )
        phases.append(pair)
    if not phases:
        raise SystemExit("--ramp needs at least one <rps>:<seconds> phase")
    return phases


def _run_ramp(rt, args, phases, probe_pool, distributed: bool,
              freshness: FreshnessTable):
    """Drive the piecewise phases closed-loop: one shared arrival pacer
    hands out send slots at the phase's target rate; ``--threads``
    workers each carry one outstanding request, so in-flight never
    exceeds the pool and overload surfaces as latency/shed. Latencies
    are measured at the submit()->result() boundary (per-phase
    percentiles can't come from the cumulative registry histogram).
    Returns (per-phase report, completed, error totals)."""
    import numpy as np

    from spark_rapids_ml_tpu.serving import DeadlineExceeded, Overloaded
    from spark_rapids_ml_tpu.utils.tracing import counter_value

    def shed_total() -> int:
        if distributed:
            return int(
                counter_value("serving.router.shed")
                + counter_value("serving.router.rejected")
            )
        return int(
            counter_value("serving.shed.queue")
            + counter_value("serving.shed.memory")
        )

    report = []
    completed = 0
    totals = {"overloaded": 0, "deadline": 0, "other": 0}
    for i, (rps, secs) in enumerate(phases):
        interval = 1.0 / rps
        start = time.perf_counter()
        t_end = start + secs
        lock = threading.Lock()
        state = {"slot": start, "offered": 0, "ok": 0}
        lats: list = []
        errs = {"overloaded": 0, "deadline": 0, "other": 0}
        shed0 = shed_total()

        def worker(tid: int) -> None:
            while True:
                with lock:
                    slot = state["slot"]
                    if slot >= t_end:
                        return
                    state["slot"] = slot + interval
                    state["offered"] += 1
                    j = state["offered"]
                now = time.perf_counter()
                if slot > now:
                    time.sleep(slot - now)
                probe = probe_pool[(tid + j) % len(probe_pool)]
                t_req = time.perf_counter()
                try:
                    fut = rt.submit(args.family, probe, timeout=args.timeout)
                    fut.result()
                    freshness.note(fut)
                    dt_ms = (time.perf_counter() - t_req) * 1e3
                    with lock:
                        state["ok"] += 1
                        lats.append(dt_ms)
                except Overloaded as exc:
                    with lock:
                        errs["overloaded"] += 1
                    if exc.retry_after_ms > 0:
                        time.sleep(min(exc.retry_after_ms, 100.0) / 1e3)
                except DeadlineExceeded:
                    with lock:
                        errs["deadline"] += 1
                except Exception:  # noqa: BLE001 - loadgen keeps driving
                    with lock:
                        errs["other"] += 1

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(args.threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        arr = np.asarray(lats if lats else [0.0])
        report.append({
            "phase": i,
            "target_rps": rps,
            "duration_s": secs,
            "offered": state["offered"],
            "completed": state["ok"],
            "achieved_rps": round(state["ok"] / wall, 1) if wall > 0 else 0.0,
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p95_ms": round(float(np.percentile(arr, 95)), 3),
            "shed": shed_total() - shed0,
            "errors": dict(errs),
        })
        completed += state["ok"]
        for key in totals:
            totals[key] += errs[key]
    return report, completed, totals


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--family", default="kmeans",
                        choices=("kmeans", "logreg", "linreg", "pca"))
    parser.add_argument("--threads", type=int, default=16,
                        help="closed-loop workers (one outstanding request each)")
    parser.add_argument("--requests", type=int, default=200,
                        help="requests per worker")
    parser.add_argument("--ramp", default=None, metavar="RPS:SECS,...",
                        help="piecewise traffic phases, e.g. '50:5,400:10,"
                             "50:5' — offer each rate for its duration and "
                             "report per-phase p50/p95/shed (overrides "
                             "--requests)")
    parser.add_argument("--rows", type=int, default=1,
                        help="rows per request (1 = single-row scoring)")
    parser.add_argument("--features", type=int, default=32)
    parser.add_argument("--k", type=int, default=8,
                        help="clusters / components for kmeans / pca")
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--delay-ms", type=float, default=None)
    parser.add_argument("--queue", type=int, default=None)
    parser.add_argument("--mem-budget", type=int, default=None)
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-request deadline in seconds")
    parser.add_argument("--warm", action="store_true",
                        help="pre-compile the expected row buckets before timing")
    parser.add_argument("--workers", type=int, default=0,
                        help="serving member processes (0 = one in-process "
                             "runtime; N >= 1 drives a RoutingRuntime)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable one-line summary only")
    args = parser.parse_args()

    import numpy as np

    from spark_rapids_ml_tpu.serving import (
        DeadlineExceeded,
        Overloaded,
        ServingRuntime,
    )
    from spark_rapids_ml_tpu.serving.batcher import _latency_hist
    from spark_rapids_ml_tpu.utils.tracing import counter_value

    ramp_phases = _parse_ramp(args.ramp) if args.ramp else None

    model = build_model(args.family, args.features, args.k, args.seed)
    rng = np.random.default_rng(args.seed + 1)
    if ramp_phases is not None:
        # Ramp phases are open-ended in request count: cycle a fixed
        # probe pool instead of pre-allocating one array per request.
        probes = rng.normal(size=(256, args.rows, args.features))
    else:
        probes = rng.normal(
            size=(args.threads, args.requests, args.rows, args.features)
        )

    telemetry_dir = None
    if args.workers >= 1:
        from spark_rapids_ml_tpu.observability import events as _ev
        from spark_rapids_ml_tpu.serving.batcher import DEFAULT_MAX_BATCH
        from spark_rapids_ml_tpu.serving.router import RoutingRuntime

        # Member latency histograms live in the WORKER processes; a
        # telemetry dir is what brings them home as metric shards.
        telemetry_dir = _ev.telemetry_dir()
        if telemetry_dir is None:
            import tempfile

            telemetry_dir = tempfile.mkdtemp(prefix="tpuml-loadgen-")
            os.environ["TPUML_TELEMETRY_DIR"] = telemetry_dir
            _ev.configure()
        rt = RoutingRuntime(
            workers=args.workers,
            max_batch=args.max_batch,
            max_delay_ms=args.delay_ms,
            queue_limit=args.queue,
            mem_budget=args.mem_budget,
        )
        max_batch = args.max_batch or DEFAULT_MAX_BATCH
    else:
        rt = ServingRuntime(
            max_batch=args.max_batch,
            max_delay_ms=args.delay_ms,
            queue_limit=args.queue,
            mem_budget=args.mem_budget,
        )
        max_batch = rt.max_batch
    rt.register(args.family, model)
    if args.warm:
        # Every bucket the run can hit: rows per request up to a full batch.
        rt.warm(args.family, buckets=(args.rows, max_batch))

    errors = {"overloaded": 0, "deadline": 0, "other": 0}
    ok = [0] * args.threads
    err_lock = threading.Lock()
    freshness = FreshnessTable()

    def worker(tid: int) -> None:
        for j in range(args.requests):
            try:
                fut = rt.submit(
                    args.family, probes[tid, j], timeout=args.timeout
                )
                fut.result()
                freshness.note(fut)
                ok[tid] += 1
            except Overloaded as exc:
                with err_lock:
                    errors["overloaded"] += 1
                # Honor the server's backoff hint (p95 of the live
                # latency histogram ~= one queue residency), capped so a
                # pathological tail can't park the generator.
                if exc.retry_after_ms > 0:
                    time.sleep(min(exc.retry_after_ms, 100.0) / 1e3)
            except DeadlineExceeded:
                with err_lock:
                    errors["deadline"] += 1
            except Exception:  # noqa: BLE001 - loadgen keeps driving
                with err_lock:
                    errors["other"] += 1

    c_dispatch0 = counter_value("serving.batch.dispatch")
    ramp_report = None
    if ramp_phases is not None:
        t0 = time.perf_counter()
        ramp_report, completed, errors = _run_ramp(
            rt, args, ramp_phases, probes, distributed=args.workers >= 1,
            freshness=freshness,
        )
        wall = time.perf_counter() - t0
        requests_offered = sum(p["offered"] for p in ramp_report)
    else:
        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(args.threads)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        completed = sum(ok)
        requests_offered = args.threads * args.requests
    router_snapshot = rt.snapshot() if args.workers >= 1 else None
    rt.close()  # members drain and flush their metric shards

    rows_done = completed * args.rows
    if args.workers >= 1:
        hist, merged_counters = _merged_member_metrics(telemetry_dir)
        dispatches = merged_counters.get("serving.batch.dispatch", 0)
        shed_queue = merged_counters.get("serving.shed.queue", 0)
        shed_memory = merged_counters.get("serving.shed.memory", 0)
        deadline_expired = merged_counters.get("serving.deadline.expired", 0)
    else:
        hist = _latency_hist().value()
        dispatches = counter_value("serving.batch.dispatch") - c_dispatch0
        shed_queue = counter_value("serving.shed.queue")
        shed_memory = counter_value("serving.shed.memory")
        deadline_expired = counter_value("serving.deadline.expired")
    summary = {
        "family": args.family,
        "threads": args.threads,
        "requests": requests_offered,
        "completed": completed,
        "rows_per_request": args.rows,
        "rows_per_s": round(rows_done / wall, 1) if wall > 0 else 0.0,
        "wall_s": round(wall, 3),
        "p50_ms": _round_pct(hist, 0.50),
        "p95_ms": _round_pct(hist, 0.95),
        "p99_ms": _round_pct(hist, 0.99),
        "batches": dispatches,
        "mean_batch_requests": round(completed / dispatches, 2) if dispatches else 0,
        "shed_queue": shed_queue,
        "shed_memory": shed_memory,
        "deadline_expired": deadline_expired,
        "errors": errors,
        "freshness": freshness.report(),
    }
    if ramp_report is not None:
        summary["ramp"] = ramp_report
    if router_snapshot is not None:
        summary["workers"] = args.workers
        summary["router_shed"] = counter_value("serving.router.shed")
        summary["router_retries"] = counter_value("serving.router.retry")
        summary["router_rejected"] = counter_value("serving.router.rejected")
        summary["router_oversized"] = counter_value("serving.router.oversized")
        summary["per_member"] = [
            {
                "member": m["member"],
                "completed": m["completed"],
                "rows_per_s": round(m["completed"] * args.rows / wall, 1)
                if wall > 0
                else 0.0,
                "shed": m["shed"],
                "routed": m["routed"],
            }
            for m in router_snapshot["members"]
        ]
    if args.json:
        print(json.dumps(summary))
        return
    print(f"loadgen [{args.family}] {summary['requests']} requests "
          f"x {args.rows} row(s), {args.threads} closed-loop workers")
    print(f"  rows/s:      {summary['rows_per_s']}")
    print(f"  latency ms:  p50={summary['p50_ms']}  "
          f"p95={summary['p95_ms']}  p99={summary['p99_ms']}")
    print(f"  batching:    {dispatches} dispatches, "
          f"{summary['mean_batch_requests']} requests/batch")
    print(f"  shed:        queue={summary['shed_queue']} "
          f"memory={summary['shed_memory']} "
          f"deadline={summary['deadline_expired']}")
    if ramp_report is not None:
        for p in ramp_report:
            print(f"  phase {p['phase']}: target={p['target_rps']}rps "
                  f"x {p['duration_s']}s offered={p['offered']} "
                  f"completed={p['completed']} "
                  f"achieved={p['achieved_rps']}rps "
                  f"p50={p['p50_ms']}ms p95={p['p95_ms']}ms "
                  f"shed={p['shed']}")
    if router_snapshot is not None:
        print(f"  router:      {args.workers} workers, "
              f"shed={summary['router_shed']} "
              f"retries={summary['router_retries']} "
              f"rejected={summary['router_rejected']} "
              f"oversized={summary['router_oversized']}")
        for m in summary["per_member"]:
            print(f"    member {m['member']}: rows/s={m['rows_per_s']} "
                  f"completed={m['completed']} routed={m['routed']} "
                  f"shed={m['shed']}")
    for row in summary["freshness"]:
        print(f"  freshness:   {row['model']} v{row['version']}: "
              f"{row['requests']} requests, "
              f"first={row['first_seen_s']}s last={row['last_seen_s']}s")
    if any(errors.values()):
        print(f"  errors:      {errors}")


if __name__ == "__main__":
    main()
