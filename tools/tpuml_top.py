#!/usr/bin/env python
"""tpuml_top — a curses-free `top` over a live serving gang's /statusz.

Polls the router's gang-merged ``/statusz`` (served by the per-process
ops server, ``TPUML_OPS_PORT``; the router registers the endpoint when
it starts) and renders one plain-text frame per poll: per-member queue
depth / shed / retries / heartbeat age, the gang-merged p95 routed
latency, SLO error-budget burn per objective, and model freshness
(registered versions + alias pointers). No curses, no clearing — each
frame is append-only text, so it works piped to a file or a pager.

Examples::

    python tools/tpuml_top.py http://127.0.0.1:8321
    python tools/tpuml_top.py 8321 --interval 2 --iterations 5
    python tools/tpuml_top.py http://127.0.0.1:8321 --once --format json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from typing import List, Optional


def _import_percentile():
    """The shared interpolated-percentile helper — importable both with
    the package installed and straight from a checkout."""
    try:
        from spark_rapids_ml_tpu.observability.metrics import (
            percentile_from_histogram,
        )
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        from spark_rapids_ml_tpu.observability.metrics import (
            percentile_from_histogram,
        )
    return percentile_from_histogram


def normalize_url(target: str) -> str:
    """Accept a full URL, ``host:port``, or a bare port."""
    if target.isdigit():
        target = f"127.0.0.1:{target}"
    if not target.startswith("http://") and not target.startswith("https://"):
        target = f"http://{target}"
    return target.rstrip("/") + "/statusz"


def fetch_statusz(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _merged_pct(doc: dict, name: str, q: float) -> Optional[float]:
    """An interpolated percentile over the gang-MERGED histogram (bucket
    keys arrive stringified in snapshots: "inf" -> +Inf)."""
    series = doc.get("merged", {}).get("histograms", {}).get(name)
    if not series:
        return None
    percentile = _import_percentile()
    buckets: dict = {}
    count = 0
    for cell in series.values():
        for le, c in cell.get("buckets", {}).items():
            fle = (
                float("inf") if le in ("inf", "Infinity", "+Inf")
                else float(le)
            )
            buckets[fle] = buckets.get(fle, 0) + c
        count += cell.get("count", 0)
    return percentile({"buckets": buckets, "count": count}, q)


def _counter_total(doc: dict, name: str) -> float:
    """Sum every label-series of a merged counter family."""
    total = 0.0
    for series, v in doc.get("merged", {}).get("counters", {}).items():
        if series == name or series.startswith(name + "{"):
            total += v
    return total


def render_frame(doc: dict) -> str:
    router = doc.get("router", {})
    lines: List[str] = []
    lines.append(
        f"=== {router.get('router', '?')}  "
        f"{time.strftime('%H:%M:%S')}  "
        f"launch={router.get('launch')}  "
        f"rejected={router.get('rejected', 0)}  "
        f"oversized={router.get('oversized', 0)}"
    )
    p95 = _merged_pct(doc, "serving.router.latency_ms", 0.95)
    shed = _counter_total(doc, "serving.router.shed")
    routed = _counter_total(doc, "serving.router.requests")
    lines.append(
        "gang: "
        + (f"p95={p95:.1f}ms" if p95 is not None else "p95=–")
        + f"  requests={routed:.0f}  shed={shed:.0f}"
    )
    burns = doc.get("slo") or {}
    if burns:
        lines.append("slo budget burn:")
        for objective, burn in sorted(burns.items()):
            flag = "  BREACH" if burn > 1.0 else ""
            lines.append(f"  {objective:<28} burn={burn:6.3f}{flag}")
    lines.append(
        f"{'member':>6} {'pid':>8} {'depth':>6} {'outst':>6} {'shed':>6} "
        f"{'retry':>6} {'routed':>8} {'done':>8} {'hb_age':>8} state"
    )
    scraped = doc.get("members", {})
    for m in router.get("members", []):
        state = (
            "dead" if m.get("dead")
            else "joining" if m.get("joining")
            else "retiring" if m.get("retiring")
            else "live"
        )
        cell = scraped.get(str(m.get("member")), {})
        if cell and not cell.get("ok") and state == "live":
            state += f" (scrape: {cell.get('error')})"
        age = m.get("heartbeat_age_s")
        lines.append(
            f"{m.get('member'):>6} {m.get('pid') or '?':>8} "
            f"{m.get('depth', 0):>6} {m.get('outstanding', 0):>6} "
            f"{m.get('shed', 0):>6} {m.get('retries', 0):>6} "
            f"{m.get('routed', 0):>8} {m.get('completed', 0):>8} "
            f"{(f'{age:.2f}s' if age is not None else '–'):>8} {state}"
        )
    models = router.get("models", {})
    if isinstance(models, dict) and models:
        lines.append("models (freshness):")
        for name, cell in sorted(models.items()):
            if not isinstance(cell, dict):
                continue
            versions = cell.get("versions", cell.get("live", []))
            aliases = cell.get("aliases", {})
            alias_s = " ".join(
                f"{a}->v{v}" for a, v in sorted(aliases.items())
            ) if isinstance(aliases, dict) else str(aliases)
            lines.append(
                f"  {name:<24} versions={versions} {alias_s}"
            )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "target",
        help="router ops endpoint: full URL, host:port, or bare port",
    )
    parser.add_argument("--interval", type=float, default=2.0,
                        help="seconds between polls (default 2)")
    parser.add_argument("--iterations", type=int, default=0,
                        help="stop after N frames (0 = forever)")
    parser.add_argument("--once", action="store_true",
                        help="one frame, then exit (== --iterations 1)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="json dumps the raw /statusz document")
    parser.add_argument("--timeout", type=float, default=5.0)
    args = parser.parse_args(argv)

    url = normalize_url(args.target)
    iterations = 1 if args.once else args.iterations
    n = 0
    while True:
        try:
            doc = fetch_statusz(url, timeout=args.timeout)
        except Exception as exc:  # noqa: BLE001 - a dead gang is an answer
            print(f"tpuml_top: scrape of {url} failed: {exc}",
                  file=sys.stderr)
            return 1
        if args.format == "json":
            print(json.dumps(doc, indent=2, default=str))
        else:
            print(render_frame(doc))
            print()
        n += 1
        if iterations and n >= iterations:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
