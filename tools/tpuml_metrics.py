#!/usr/bin/env python
"""Dump/inspect spark-tpu-ml telemetry: event logs and metric snapshots.

Two subcommands:

``events`` — parse a ``TPUML_EVENT_LOG`` JSONL stream, schema-validate
every record (the same :func:`observability.events.validate_record` the
tests use), and summarize per run: event counts by type, span count and
total span seconds, counters flushed at run end. ``--validate`` exits
non-zero on the first malformed line (the CI gate); ``--run`` restricts
to one run id; ``--format json`` emits the summary machine-readable.

``snapshot`` — render a ``TPUML_METRICS_DUMP`` JSON snapshot (or one
written via ``observability.metrics.dump_snapshot``) as Prometheus-style
text, or pretty-print it.

Examples::

    python tools/tpuml_metrics.py events /tmp/run.jsonl
    python tools/tpuml_metrics.py events /tmp/run.jsonl --validate
    python tools/tpuml_metrics.py snapshot /tmp/metrics.json --format prom
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple


def _import_validate_record():
    """The shared schema validator — importable both with the package
    installed and when this script runs straight from a checkout."""
    try:
        from spark_rapids_ml_tpu.observability.events import validate_record
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        from spark_rapids_ml_tpu.observability.events import validate_record
    return validate_record


def parse_lines(lines: Iterable[str]) -> Tuple[List[dict], List[str]]:
    """Decode + schema-validate a JSONL stream. Returns
    ``(records, problems)`` where each problem names its line number."""
    validate_record = _import_validate_record()

    records: List[dict] = []
    problems: List[str] = []
    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            problems.append(f"line {i}: not JSON ({exc})")
            continue
        for p in validate_record(rec):
            problems.append(f"line {i}: {p}")
        records.append(rec)
    return records, problems


def summarize(records: List[dict], run: Optional[str] = None) -> dict:
    """Per-run rollup: event counts by type, span totals, the end-of-run
    counter flush, and any failed spans."""
    runs: Dict[str, dict] = {}
    for rec in records:
        rid = rec.get("run_id") or "<no-run>"
        if run is not None and rid != run:
            continue
        cell = runs.setdefault(
            rid,
            {"events": {}, "spans": 0, "span_seconds": 0.0,
             "failed_spans": [], "counters": {}, "processes": set()},
        )
        etype = rec.get("event", "?")
        cell["events"][etype] = cell["events"].get(etype, 0) + 1
        cell["processes"].add(rec.get("process"))
        if etype == "span":
            cell["spans"] += 1
            cell["span_seconds"] += float(rec.get("dur", 0.0))
            if rec.get("ok") is False:
                cell["failed_spans"].append(
                    {"name": rec.get("name"), "exc": rec.get("exc")}
                )
        elif etype == "counters":
            cell["counters"].update(rec.get("counters") or {})
    for cell in runs.values():
        cell["processes"] = sorted(
            p for p in cell["processes"] if p is not None
        )
    return {"runs": runs, "total_records": sum(
        sum(c["events"].values()) for c in runs.values()
    )}


def _render_summary(summary: dict) -> str:
    lines = [f"{summary['total_records']} records"]
    for rid, cell in summary["runs"].items():
        lines.append(f"run {rid}  (processes {cell['processes'] or [0]})")
        ev = ", ".join(f"{k}={v}" for k, v in sorted(cell["events"].items()))
        lines.append(f"  events: {ev}")
        lines.append(
            f"  spans: {cell['spans']} totaling {cell['span_seconds']:.3f}s"
        )
        for f in cell["failed_spans"]:
            lines.append(f"  FAILED span {f['name']}: {f['exc']}")
        for k, v in sorted(cell["counters"].items()):
            lines.append(f"  counter {k} = {v}")
    return "\n".join(lines)


def render_snapshot_prometheus(snapshot: dict) -> str:
    """A ``metrics.Registry.snapshot()`` JSON dict as Prometheus text.

    Delegates to THE exposition renderer
    (``metrics.render_prometheus_snapshot``) — the same function behind
    the live ``/metrics`` endpoint and ``TPUML_METRICS_DUMP``, so every
    surface emits byte-identical series for the same snapshot."""
    try:
        from spark_rapids_ml_tpu.observability.metrics import (
            render_prometheus_snapshot,
        )
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        from spark_rapids_ml_tpu.observability.metrics import (
            render_prometheus_snapshot,
        )

    return render_prometheus_snapshot(snapshot)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_ev = sub.add_parser("events", help="summarize/validate a JSONL event log")
    p_ev.add_argument("path")
    p_ev.add_argument("--run", default=None, help="restrict to one run_id")
    p_ev.add_argument("--validate", action="store_true",
                      help="exit 1 if any line is malformed")
    p_ev.add_argument("--format", choices=("text", "json"), default="text")

    p_sn = sub.add_parser("snapshot", help="render a metrics snapshot")
    p_sn.add_argument("path")
    p_sn.add_argument("--format", choices=("prom", "json"), default="prom")

    args = parser.parse_args(argv)

    if args.cmd == "events":
        with open(args.path) as f:
            records, problems = parse_lines(f)
        for p in problems:
            print(f"INVALID {p}", file=sys.stderr)
        summary = summarize(records, run=args.run)
        if args.format == "json":
            print(json.dumps(summary, indent=2, default=str))
        else:
            print(_render_summary(summary))
        return 1 if (args.validate and problems) else 0

    with open(args.path) as f:
        snapshot = json.load(f)
    if args.format == "json":
        print(json.dumps(snapshot, indent=2))
    else:
        print(render_snapshot_prometheus(snapshot), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
