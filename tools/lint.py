"""Self-contained static quality gate (the `-Xfatal-warnings` / apache-rat
analogue of the reference's build, pom.xml:194,361-397 — the image ships no
ruff/mypy/pyflakes, so the checks are implemented on the stdlib ast).

Checks per file:
  - parses (syntax)
  - module docstring present (the rat-style header gate; this repo's
    convention documents every module instead of license boilerplate)
  - no unused imports (module scope)
  - no bare `except:`
  - no mutable default arguments
  - no `import *`

Run: ``python tools/lint.py [paths...]`` — exits non-zero on findings.
The test suite runs it over the package + tests (tests/test_quality.py),
so the gate fails the build like the reference's fatal warnings did.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
DEFAULT_PATHS = [REPO / "spark_rapids_ml_tpu", REPO / "tests", REPO / "benchmarks"]

# Names whose import is intentionally "unused" at module scope.
_IMPORT_SIDE_EFFECT_OK = {"annotations"}


def _imported_names(tree: ast.Module):
    """(bound-name, lineno) for every import binding, in ANY scope —
    a binding unused anywhere in the file is flagged regardless of where
    the import statement sits."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                out.append((name, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "*":
                    continue
                out.append((a.asname or a.name, node.lineno))
    return out


def _used_names(tree: ast.Module):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
    # Names referenced in __all__ strings count as used (re-export files).
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    used.add(elt.value)
    return used


def lint_file(path: Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    findings = []

    if ast.get_docstring(tree) is None and path.name != "__init__.py":
        findings.append(f"{path}:1: missing module docstring")

    used = _used_names(tree)
    noqa_lines = {
        i + 1 for i, line in enumerate(src.splitlines()) if "# noqa" in line
    }
    for name, lineno in _imported_names(tree):
        if name in _IMPORT_SIDE_EFFECT_OK or lineno in noqa_lines:
            continue
        if name not in used:
            findings.append(f"{path}:{lineno}: unused import {name!r}")

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(f"{path}:{node.lineno}: bare except")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    findings.append(
                        f"{path}:{node.lineno}: mutable default argument "
                        f"in {node.name}()"
                    )
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            findings.append(f"{path}:{node.lineno}: import *")
    return findings


def main(argv: list[str]) -> int:
    paths = [Path(p) for p in argv] if argv else DEFAULT_PATHS
    files = []
    for p in paths:
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    findings = []
    for f in files:
        findings.extend(lint_file(f))
    for line in findings:
        print(line)
    print(f"lint: {len(files)} files, {len(findings)} findings")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
