"""Compatibility shim — the lint gate moved to ``tools/tpuml_lint/``.

The seed's six generic checks (docstring, unused imports, bare except,
mutable defaults, ``import *``, syntax) now live in
``tools/tpuml_lint/generic.py`` as one of five checker families of a
plugin analyzer (JAX retrace/sync hazards, guarded-by lock discipline,
the ``TPUML_*`` knob registry, observability drift). Run the real
thing::

    python -m tools.tpuml_lint [--format json] [--validate-baseline]

This wrapper keeps ``python tools/lint.py`` working for muscle memory
and old scripts; it delegates to the package CLI (baseline applied).
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def main(argv) -> int:
    if str(REPO) not in sys.path:
        sys.path.insert(0, str(REPO))
    from tools.tpuml_lint.__main__ import main as lint_main

    return lint_main(list(argv))


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
