"""The analyzer engine: per-module context, repo-wide context, runner.

Checkers are plain functions ``check(module, repo) -> list[Finding]``
registered in :data:`CHECKERS` (tpuml_lint/__init__.py). The engine owns
everything they share:

  - parsing + parent links (``ModuleContext.parent_of``),
  - module-level string constants (``FAULTS_ENV = "TPUML_FAULTS"`` style),
  - import bindings (who is ``emit`` in THIS module?),
  - docstring positions (so string-literal scans skip prose),
  - ``# tpuml: noqa[rule-a,rule-b]`` suppression, applied AFTER checkers
    run so a suppressed line suppresses every rule named on it,
  - repo-wide facts parsed once: the ``envknobs.KNOBS`` table, the
    ``events.py::SCHEMA`` record types, and the PARITY.md knob docs.

Everything is stdlib ``ast`` — the image ships no ruff/mypy/pyflakes
(the reference enforced quality with ``-Xfatal-warnings`` + apache-rat;
this is that gate, grown domain-aware).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tools.tpuml_lint.findings import Finding

_NOQA_RE = re.compile(r"#\s*tpuml:\s*noqa(?:\[([a-z0-9_,\s-]*)\])?")

#: Directories never linted (vendored stubs model a foreign API surface).
SKIP_DIR_NAMES = {"pyspark_stub", "__pycache__", ".git"}


class ModuleContext:
    """One parsed file + the derived maps every checker needs."""

    def __init__(self, root: Path, path: Path, source: str,
                 tree: Optional[ast.Module], syntax_error=None):
        self.root = root
        self.path = path
        try:
            self.rel = path.relative_to(root).as_posix()
        except ValueError:  # outside the root (temp fixtures, abs targets)
            self.rel = path.as_posix()
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.syntax_error = syntax_error
        self._parents: Dict[int, ast.AST] = {}
        self.constants: Dict[str, str] = {}
        self.import_bindings: Dict[str, str] = {}
        self.docstring_nodes: Set[int] = set()
        if tree is not None:
            self._index()

    # --- derived maps ---

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        # Module-level NAME = "literal" constants (lets the knob checker
        # resolve os.environ.get(FAULTS_ENV)).
        for stmt in self.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)
            ):
                self.constants[stmt.targets[0].id] = stmt.value.value
            elif (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)
            ):
                self.constants[stmt.target.id] = stmt.value.value
        # Import bindings: local name -> dotted origin.
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.import_bindings[local] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    self.import_bindings[local] = f"{node.module}.{a.name}"
        # Docstring constants (module/class/function first-statement strings).
        scopes = [self.tree] + [
            n for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
        ]
        for scope in scopes:
            body = getattr(scope, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                self.docstring_nodes.add(id(body[0].value))

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def binds_to(self, local: str, *origins: str) -> bool:
        """True when ``local`` was imported from one of ``origins``
        (exact dotted-origin match)."""
        return self.import_bindings.get(local) in origins

    def resolve_str(self, node: ast.AST) -> Optional[str]:
        """The string a key expression holds: a literal, or a module-level
        constant Name."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.constants.get(node.id)
        return None

    # --- suppression ---

    def suppressed_rules(self, line: int) -> Optional[Set[str]]:
        """The rule ids a ``# tpuml: noqa[...]`` comment on ``line``
        names; an empty set means "all rules"; None means no comment."""
        if not (1 <= line <= len(self.lines)):
            return None
        m = _NOQA_RE.search(self.lines[line - 1])
        if m is None:
            return None
        if m.group(1) is None:
            return set()
        return {r.strip() for r in m.group(1).split(",") if r.strip()}


def iter_scopes(tree: ast.Module):
    """The module's analyzable scopes: ``(class_name, fn)`` for every
    method defined directly in a top-level class body, and ``(None, fn)``
    for every top-level function. This is the node set interprocedural
    checkers (the guarded-by lock pass) build their per-class/module
    call graphs over; nested defs stay part of their enclosing scope."""
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, stmt
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield stmt.name, sub


def call_target(node: ast.Call) -> Optional[Tuple[str, str]]:
    """Resolve an intra-module call edge: ``self.helper(...)`` ->
    ``("self", "helper")``, ``helper(...)`` -> ``("local", "helper")``,
    anything else (imported names resolve elsewhere, attribute chains
    cross object boundaries) -> None."""
    fn = node.func
    if (
        isinstance(fn, ast.Attribute)
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "self"
    ):
        return ("self", fn.attr)
    if isinstance(fn, ast.Name):
        return ("local", fn.id)
    return None


class RepoContext:
    """Facts parsed once per run from the repo's own source of truth."""

    ENVKNOBS_REL = "spark_rapids_ml_tpu/utils/envknobs.py"
    EVENTS_REL = "spark_rapids_ml_tpu/observability/events.py"
    PARITY_REL = "docs/PARITY.md"

    def __init__(self, root: Path):
        self.root = Path(root)
        self.knobs: Optional[Dict[str, int]] = self._parse_knobs()
        self.event_schema: Optional[Dict[str, Set[str]]] = self._parse_schema()
        parity = self.root / self.PARITY_REL
        self.parity_text: Optional[str] = (
            parity.read_text() if parity.is_file() else None
        )

    def _parse_knobs(self) -> Optional[Dict[str, int]]:
        """{knob name: declaration line} from the ``KNOBS`` table —
        textual AST parse, so linting never imports the package."""
        path = self.root / self.ENVKNOBS_REL
        if not path.is_file():
            return None
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            return None
        for node in ast.walk(tree):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AnnAssign) else []
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "KNOBS" for t in targets
            ):
                continue
            out: Dict[str, int] = {}
            for call in ast.walk(node.value):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id == "Knob"
                    and call.args
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                ):
                    out[call.args[0].value] = call.lineno
            return out
        return None

    def _parse_schema(self) -> Optional[Dict[str, Set[str]]]:
        """{event type: required fields} from ``events.py::SCHEMA``."""
        path = self.root / self.EVENTS_REL
        if not path.is_file():
            return None
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            return None
        for node in ast.walk(tree):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AnnAssign) else []
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "SCHEMA" for t in targets
            ):
                continue
            value = node.value
            if not isinstance(value, ast.Dict):
                return None
            out: Dict[str, Set[str]] = {}
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                fields: Set[str] = set()
                for c in ast.walk(v):
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        fields.add(c.value)
                out[k.value] = fields
            return out
        return None


def iter_python_files(paths: List[Path]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in SKIP_DIR_NAMES for part in f.parts):
                    files.append(f)
        elif p.suffix == ".py":
            files.append(p)
    return files


def lint_file(root: Path, path: Path, checkers) -> List[Finding]:
    """All findings for one file, suppression already applied."""
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
        module = ModuleContext(root, path, source, tree)
    except SyntaxError as e:
        module = ModuleContext(root, path, source, None, syntax_error=e)
    repo = RepoContext(root)
    return _run_checkers(module, repo, checkers)


def _run_checkers(module: ModuleContext, repo: RepoContext, checkers) -> List[Finding]:
    if module.syntax_error is not None:
        e = module.syntax_error
        return [
            Finding(module.rel, e.lineno or 1, e.offset or 0, "syntax-error",
                    f"syntax error: {e.msg}")
        ]
    findings: List[Finding] = []
    for check in checkers:
        findings.extend(check(module, repo))
    kept = []
    for f in findings:
        rules = module.suppressed_rules(f.line)
        if rules is not None and (not rules or f.rule in rules):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def run_paths(root: Path, paths: List[Path], checkers,
              repo_checkers=()) -> Tuple[List[Finding], int]:
    """Lint every file under ``paths``; returns (findings, file count).
    ``repo_checkers`` run once against the :class:`RepoContext` (e.g.
    the knob-undocumented docs cross-check)."""
    root = Path(root)
    repo = RepoContext(root)
    findings: List[Finding] = []
    files = iter_python_files([Path(p) for p in paths])
    for path in files:
        source = path.read_text()
        try:
            tree = ast.parse(source, filename=str(path))
            module = ModuleContext(root, path, source, tree)
        except SyntaxError as e:
            module = ModuleContext(root, path, source, None, syntax_error=e)
        findings.extend(_run_checkers(module, repo, checkers))
    for check in repo_checkers:
        findings.extend(check(repo))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(files)
