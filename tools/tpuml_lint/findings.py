"""Finding model + the rule catalog for the tpuml-lint analyzer.

Every rule has a stable kebab-case id (the name used in baselines, in
``# tpuml: noqa[rule]`` suppressions, and in CONTRIBUTING.md's rule
table) and a severity: ``error`` findings gate CI; ``warning`` findings
print but do not fail the run unless ``--strict-warnings``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class Rule:
    id: str
    family: str
    severity: str
    rationale: str


#: The rule catalog — single source of truth for ids, severities, and the
#: one-line rationales CONTRIBUTING.md lists.
RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        # generic (the seed tools/lint.py checks)
        Rule("syntax-error", "generic", ERROR,
             "the file must parse before anything else can be checked"),
        Rule("missing-docstring", "generic", ERROR,
             "every module documents itself (the apache-rat header analogue)"),
        Rule("unused-import", "generic", ERROR,
             "dead imports hide real dependencies and slow cold starts"),
        Rule("bare-except", "generic", ERROR,
             "swallowing BaseException hides KeyboardInterrupt and worker kills"),
        Rule("mutable-default", "generic", ERROR,
             "mutable default arguments alias state across calls"),
        Rule("import-star", "generic", ERROR,
             "star imports defeat the unused-import and name-resolution checks"),
        # (a) JAX retrace/sync hazards
        Rule("jax-host-sync", "jax", ERROR,
             "a host conversion inside a jitted function blocks the device "
             "pipeline (or silently runs at trace time only)"),
        Rule("jax-traced-branch", "jax", ERROR,
             "Python control flow on a traced value raises ConcretizationError "
             "or silently specializes the program to one trace"),
        Rule("jax-static-loop-arg", "jax", ERROR,
             "a static argument that varies per loop iteration compiles a new "
             "program every pass — the retrace bait PR 2/5 exist to kill"),
        Rule("jax-whole-dataset-put", "jax", ERROR,
             "a model fit path uploading the raw extracted dataset with a "
             "bare jnp.asarray/jax.device_put bypasses the ingest "
             "chokepoint (fault point, OOM retry, cache reclaim) the "
             "memory-safe data plane gates fits through — use "
             "prepare_rows or ingest.place_array"),
        # (b) lock discipline
        Rule("lock-guarded", "locks", ERROR,
             "an attribute annotated '# guarded-by: <lock>' was touched "
             "outside a 'with <lock>:' block in its owning scope"),
        Rule("lock-unknown", "locks", ERROR,
             "a guarded-by annotation names a lock the owning scope never "
             "defines — the convention must stay checkable"),
        Rule("lock-order", "locks", ERROR,
             "two locks in one module are acquired in both nesting orders "
             "— a cycle in the static acquisition-order graph is a "
             "potential deadlock the moment two threads interleave"),
        Rule("lock-leak", "locks", ERROR,
             "a lock acquired via .acquire() without a guaranteed-release "
             "path (no try/finally release, no with) stays held forever "
             "on the first exception — use 'with lock:'"),
        # (c) envknob registry
        Rule("knob-raw-environ", "knobs", ERROR,
             "TPUML_* knobs must go through utils/envknobs accessors so "
             "malformed values raise a named error and the registry stays "
             "the single source of truth"),
        Rule("knob-unregistered", "knobs", ERROR,
             "every TPUML_* name must have a Knob entry in envknobs.KNOBS "
             "(TPUML_TEST_* harness inputs are exempt)"),
        Rule("knob-undocumented", "knobs", ERROR,
             "every registered knob must appear in docs/PARITY.md's knob "
             "tables — docs that can drift are docs that will"),
        # (d) observability drift
        Rule("event-unknown-type", "drift", ERROR,
             "emit() with a record type events.py::SCHEMA does not declare "
             "writes lines the validator (and the CI gate) will reject"),
        Rule("event-missing-field", "drift", ERROR,
             "emit() must pass every required field its record type declares"),
        Rule("metric-name", "drift", ERROR,
             "metric names are lowercase dotted (subsystem.metric[.detail]) "
             "so the Prometheus exposition and dashboards stay uniform"),
        Rule("telemetry-dir-raw-read", "drift", ERROR,
             "TPUML_TELEMETRY_DIR reads must go through utils/envknobs "
             "(events.telemetry_dir): a layer resolving the shard dir on "
             "its own can split one gang's shards across two places"),
    )
}


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    path: str  # repo-relative, "/"-separated
    line: int
    col: int
    rule: str
    message: str
    severity: str = field(default=ERROR)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def baseline_key(self) -> tuple:
        # Line/col excluded: a baseline must survive unrelated edits above
        # the finding.
        return (self.path, self.rule, self.message)

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
        }
