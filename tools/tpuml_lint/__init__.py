"""tpuml-lint — the plugin static-analysis gate for this repo.

Grown from the six generic checks in the seed ``tools/lint.py`` into a
domain-aware analyzer ("Memory Safe Computations with XLA Compiler"
argues this class of defect belongs to static program analysis, not
post-hoc profiling; the reference build's analogue was
``-Xfatal-warnings`` + apache-rat). Four checker families ride one
stdlib-ast engine:

  - **generic**  — docstrings, unused imports, bare except, mutable
    defaults, ``import *`` (the seed checks, unchanged in spirit).
  - **jax**      — host-sync calls and Python-branch-on-traced-value
    inside jitted/segment functions; static args that vary per loop
    iteration (retrace bait).
  - **locks**    — the ``# guarded-by: <lock>`` convention: guarded
    attributes may only be touched under their lock.
  - **knobs**    — every ``TPUML_*`` knob reads through
    ``utils/envknobs``, is registered in ``envknobs.KNOBS``, and is
    documented in ``docs/PARITY.md``.
  - **drift**    — ``emit()`` callsites conform to
    ``events.py::SCHEMA``; metric names follow the dotted rule.

Suppression: ``# tpuml: noqa[rule-id]`` on the flagged line (bare
``# tpuml: noqa`` suppresses every rule there). Legacy findings live in
the committed ``tools/tpuml_lint/baseline.json``; ``--validate-baseline``
(the CI mode) fails on stale entries so the baseline can only shrink.

Run: ``python -m tools.tpuml_lint [--format json] [--validate-baseline]``.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Tuple

from tools.tpuml_lint import (  # noqa: F401 - re-exported submodules
    baseline,
    drift,
    generic,
    jax_hazards,
    knobs,
    locks,
)
from tools.tpuml_lint.engine import (  # noqa: F401
    ModuleContext,
    RepoContext,
    iter_python_files,
    lint_file,
    run_paths,
)
from tools.tpuml_lint.findings import RULES, Finding  # noqa: F401

#: Per-module checkers, in report order.
CHECKERS = (
    generic.check,
    jax_hazards.check,
    locks.check,
    knobs.check,
    drift.check,
)

#: Once-per-run repo-level checkers.
REPO_CHECKERS = (knobs.check_repo,)

REPO_ROOT = Path(__file__).resolve().parents[2]

#: The acceptance surface: every tree the CI gate sweeps.
DEFAULT_PATHS = ("spark_rapids_ml_tpu", "tests", "benchmarks", "tools")

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def run(root: Path = REPO_ROOT, paths=None) -> Tuple[List[Finding], int]:
    """Lint ``paths`` (default: the full acceptance surface) under
    ``root``; returns (findings, files checked). Baseline NOT applied —
    callers split with :func:`baseline.apply`."""
    root = Path(root)
    targets = [
        root / p if not Path(p).is_absolute() else Path(p)
        for p in (paths or DEFAULT_PATHS)
    ]
    targets = [t for t in targets if t.exists()]
    return run_paths(root, targets, CHECKERS, REPO_CHECKERS)
