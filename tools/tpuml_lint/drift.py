"""Checker family (d): observability drift.

The event log's consumers (``tools/tpuml_metrics.py --validate``, the CI
gates, dashboards) trust two contracts the type system cannot see:

  - every ``emit(<type>, ...)`` callsite names a record type declared in
    ``events.py::SCHEMA`` and passes every required field that type
    declares (``event-unknown-type`` / ``event-missing-field``) — a
    drifted callsite would write lines the validator rejects AFTER the
    run that produced them;
  - every literal metric name at a ``counter()`` / ``gauge()`` /
    ``histogram()`` / ``bump_counter()`` callsite follows the dotted
    naming rule ``subsystem.metric[.detail]`` (``metric-name``), so the
    Prometheus exposition stays uniform;
  - the telemetry-dir knob is only ever READ through ``utils/envknobs``
    (``telemetry-dir-raw-read``): shard wiring must stay uniform — a
    layer that resolves ``TPUML_TELEMETRY_DIR`` on its own can disagree
    with ``events.configure`` about where shards land, and a gang whose
    members shard into two places is two gangs to the merger. (Writes
    are allowed: the barrier launcher EXPORTS the dir to members.)

Callsites are matched through import bindings (``from ...events import
emit``, ``import ... as``), so a local function that happens to be
called ``emit`` — the benchmarks have one — is never confused with the
event-log entry point. Dynamic names (f-strings, variables) are skipped:
the rule is about literals drifting, not about proving dataflow.
"""

from __future__ import annotations

import ast
import re
from typing import List

from tools.tpuml_lint.engine import ModuleContext, RepoContext
from tools.tpuml_lint.findings import Finding
from tools.tpuml_lint.knobs import _environ_read_key

_EVENTS_MOD = "spark_rapids_ml_tpu.observability.events"
_METRICS_MOD = "spark_rapids_ml_tpu.observability.metrics"
_TRACING_MOD = "spark_rapids_ml_tpu.utils.tracing"

_TELEMETRY_KNOB = "TPUML_TELEMETRY_DIR"
_TELEMETRY_CONSTANT = "TELEMETRY_DIR_ENV"

_METRIC_NAME = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")

_METRIC_FACTORIES = {
    f"{_METRICS_MOD}.counter",
    f"{_METRICS_MOD}.gauge",
    f"{_METRICS_MOD}.histogram",
    f"{_TRACING_MOD}.bump_counter",
    f"{_TRACING_MOD}.counter_value",
}


def _emit_call(node: ast.Call, module: ModuleContext) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        if module.binds_to(f.id, f"{_EVENTS_MOD}.emit"):
            return True
        # Inside events.py itself, emit is a local definition.
        return (
            module.rel == RepoContext.EVENTS_REL and f.id == "emit"
        )
    if isinstance(f, ast.Attribute) and f.attr == "emit":
        return (
            isinstance(f.value, ast.Name)
            and module.import_bindings.get(f.value.id) == _EVENTS_MOD
        )
    return False


def _metric_call(node: ast.Call, module: ModuleContext) -> bool:
    f = node.func
    local_names = {"counter", "gauge", "histogram", "bump_counter"}
    if isinstance(f, ast.Name):
        origin = module.import_bindings.get(f.id)
        if origin in _METRIC_FACTORIES:
            return True
        # The defining modules call their own factories by local name.
        defining = module.rel in (
            "spark_rapids_ml_tpu/observability/metrics.py",
            "spark_rapids_ml_tpu/utils/tracing.py",
        )
        return defining and f.id in local_names
    if isinstance(f, ast.Attribute) and f.attr in local_names:
        return (
            isinstance(f.value, ast.Name)
            and module.import_bindings.get(f.value.id)
            in (_METRICS_MOD, _TRACING_MOD)
        )
    return False


def _telemetry_read_key(node: ast.AST, module: ModuleContext):
    """The key expression when ``node`` reads the environment (either
    call form or a ``Load``-context subscript), else None."""
    if isinstance(node, ast.Call):
        return _environ_read_key(node)
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.ctx, ast.Load)
        and isinstance(node.value, ast.Attribute)
        and node.value.attr == "environ"
        and isinstance(node.value.value, ast.Name)
        and node.value.value.id == "os"
    ):
        return node.slice
    return None


def _is_telemetry_knob(key: ast.AST, module: ModuleContext) -> bool:
    if module.resolve_str(key) == _TELEMETRY_KNOB:
        return True
    if isinstance(key, ast.Name) and key.id == _TELEMETRY_CONSTANT:
        return True
    return (
        isinstance(key, ast.Attribute) and key.attr == _TELEMETRY_CONSTANT
    )


def check(module: ModuleContext, repo: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    rel = module.rel
    for node in ast.walk(module.tree):
        if rel != RepoContext.ENVKNOBS_REL:
            key = _telemetry_read_key(node, module)
            if key is not None and _is_telemetry_knob(key, module):
                findings.append(Finding(
                    rel, node.lineno, node.col_offset,
                    "telemetry-dir-raw-read",
                    f"raw os.environ read of {_TELEMETRY_KNOB} — resolve "
                    "the shard dir through utils/envknobs (events."
                    "telemetry_dir) so every layer shards to one place",
                ))
        if not isinstance(node, ast.Call):
            continue
        if _emit_call(node, module) and repo.event_schema is not None:
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            etype = node.args[0].value
            if etype not in repo.event_schema:
                findings.append(Finding(
                    rel, node.lineno, node.col_offset, "event-unknown-type",
                    f"emit({etype!r}, ...) — no such record type in "
                    "events.py::SCHEMA",
                ))
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs splat: fields not statically known
            provided = {kw.arg for kw in node.keywords}
            missing = sorted(repo.event_schema[etype] - provided)
            if missing:
                findings.append(Finding(
                    rel, node.lineno, node.col_offset, "event-missing-field",
                    f"emit({etype!r}, ...) is missing required field(s) "
                    f"{', '.join(missing)} (events.py::SCHEMA)",
                ))
        elif _metric_call(node, module):
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if not _METRIC_NAME.match(name):
                findings.append(Finding(
                    rel, node.lineno, node.col_offset, "metric-name",
                    f"metric name {name!r} does not match the dotted "
                    "naming rule (lowercase 'subsystem.metric[.detail]')",
                ))
    return findings
