"""CLI entry: ``python -m tools.tpuml_lint [paths...]``.

Exit codes: 0 clean (no non-baselined findings; with
``--validate-baseline`` also no stale baseline entries), 1 findings,
2 bad invocation. ``--format json`` emits one machine-readable document
(the CI artifact); text mode prints one finding per line plus a summary.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _dump_lock_graph(root: Path, paths, out: Path) -> None:
    """Write the static acquisition-order graph (the edge set the
    lock-order rule cycles over) for the CI artifact / post-mortems."""
    import ast as _ast

    from tools.tpuml_lint import engine, locks

    edges = []
    for f in engine.iter_python_files(
        [root / p if not Path(p).is_absolute() else Path(p) for p in paths]
    ):
        src = f.read_text()
        try:
            tree = _ast.parse(src, filename=str(f))
        except SyntaxError:
            continue
        module = engine.ModuleContext(root, f, src, tree)
        edges.extend(locks.order_edges(module))
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(
        {"kind": "tpuml-lock-order-graph", "edges": edges}, indent=2))


def main(argv=None) -> int:
    import tools.tpuml_lint as tl
    from tools.tpuml_lint import baseline as bl

    ap = argparse.ArgumentParser(
        prog="tools.tpuml_lint",
        description="Static quality + domain-invariant gate "
                    "(JAX hazards, lock discipline, knob registry, "
                    "observability drift).",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: "
                         f"{', '.join(tl.DEFAULT_PATHS)})")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {tl.DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--validate-baseline", action="store_true",
                    help="CI mode: also fail on stale baseline entries")
    ap.add_argument("--write-baseline", action="store_true",
                    help="adopt the current findings as the new baseline")
    ap.add_argument("--lock-graph", default=None, metavar="PATH",
                    help="also dump the static lock acquisition-order "
                         "graph (every nested-with edge the guarded-by "
                         "pass derived, call graph included) as JSON")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else tl.REPO_ROOT
    findings, n_files = tl.run(root=root, paths=args.paths or None)

    if args.lock_graph:
        _dump_lock_graph(root, args.paths or list(tl.DEFAULT_PATHS),
                         Path(args.lock_graph))

    baseline_path = Path(args.baseline) if args.baseline else tl.DEFAULT_BASELINE
    if args.write_baseline:
        bl.save(baseline_path, findings)
        print(f"tpuml-lint: wrote {len(findings)} baseline entries to "
              f"{baseline_path}")
        return 0

    entries = [] if args.no_baseline else bl.load(baseline_path)
    new, baselined, stale = bl.apply(findings, entries)

    failed = bool(new) or (args.validate_baseline and bool(stale))
    if args.format == "json":
        doc = {
            "files": n_files,
            "new": [f.to_json() for f in new],
            "baselined": [f.to_json() for f in baselined],
            "stale": stale,
            "ok": not failed,
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in new:
            print(f.render())
        if args.validate_baseline:
            for e in stale:
                print(
                    f"{e.get('path')}: stale baseline entry for rule "
                    f"{e.get('rule')!r}: {e.get('message')} — remove it "
                    f"from {baseline_path}"
                )
        print(
            f"tpuml-lint: {n_files} files, {len(new)} new finding(s), "
            f"{len(baselined)} baselined, {len(stale)} stale"
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
