"""The generic hygiene family — the seed ``tools/lint.py`` checks, as
engine plugins: module docstring, unused imports, bare except, mutable
defaults, ``import *``. (Syntax errors are reported by the engine itself
so every other checker can assume a parse tree.)
"""

from __future__ import annotations

import ast
from typing import List

from tools.tpuml_lint.engine import ModuleContext, RepoContext
from tools.tpuml_lint.findings import Finding

# Names whose import is intentionally "unused" at module scope.
_IMPORT_SIDE_EFFECT_OK = {"annotations"}


def _imported_names(tree: ast.Module):
    """(bound-name, lineno, col) for every import binding, in ANY scope —
    a binding unused anywhere in the file is flagged regardless of where
    the import statement sits."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                out.append((name, node.lineno, node.col_offset))
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "*":
                    continue
                out.append((a.asname or a.name, node.lineno, node.col_offset))
    return out


def _used_names(tree: ast.Module):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
    # Names referenced in __all__ strings count as used (re-export files).
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    used.add(elt.value)
    return used


def check(module: ModuleContext, repo: RepoContext) -> List[Finding]:
    tree = module.tree
    findings: List[Finding] = []
    rel = module.rel

    if ast.get_docstring(tree) is None and module.path.name != "__init__.py":
        findings.append(
            Finding(rel, 1, 0, "missing-docstring", "missing module docstring")
        )

    used = _used_names(tree)
    # The historic `# noqa` marker (any flavor) keeps suppressing unused
    # imports — re-export modules carry `# noqa: F401` from the seed.
    noqa_lines = {
        i + 1 for i, line in enumerate(module.lines) if "# noqa" in line
    }
    for name, lineno, col in _imported_names(tree):
        if name in _IMPORT_SIDE_EFFECT_OK or lineno in noqa_lines:
            continue
        if name not in used:
            findings.append(
                Finding(rel, lineno, col, "unused-import",
                        f"unused import {name!r}")
            )

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(
                Finding(rel, node.lineno, node.col_offset, "bare-except",
                        "bare except")
            )
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    findings.append(
                        Finding(rel, node.lineno, node.col_offset,
                                "mutable-default",
                                f"mutable default argument in {node.name}()")
                    )
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            findings.append(
                Finding(rel, node.lineno, node.col_offset, "import-star",
                        "import *")
            )
    return findings
