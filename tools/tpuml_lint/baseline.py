"""Baseline file handling — adopt-then-ratchet for legacy findings.

The baseline is a committed JSON list of finding keys (path, rule,
message — line numbers excluded so unrelated edits above a finding do
not invalidate it). A run fails only on findings NOT in the baseline;
``--validate-baseline`` additionally fails on STALE entries (baselined
findings that no longer occur), so the baseline can only shrink.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Tuple

from tools.tpuml_lint.findings import Finding


def load(path: Path) -> List[dict]:
    if not Path(path).is_file():
        return []
    data = json.loads(Path(path).read_text() or "[]")
    if not isinstance(data, list):
        raise ValueError(f"baseline {path} must hold a JSON list")
    return data


def save(path: Path, findings: List[Finding]) -> None:
    entries = [
        {"path": f.path, "rule": f.rule, "message": f.message}
        for f in sorted(findings, key=lambda f: f.baseline_key())
    ]
    Path(path).write_text(json.dumps(entries, indent=2) + "\n")


def _key(entry: dict) -> Tuple[str, str, str]:
    return (entry.get("path", ""), entry.get("rule", ""),
            entry.get("message", ""))


def apply(findings: List[Finding], entries: List[dict]
          ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split ``findings`` against the baseline: (new, baselined, stale).
    Multiplicity counts — two identical findings need two entries."""
    budget: Dict[Tuple[str, str, str], int] = Counter(
        _key(e) for e in entries
    )
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        k = f.baseline_key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = []
    for e in entries:
        k = _key(e)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            stale.append(e)
    return new, baselined, stale
