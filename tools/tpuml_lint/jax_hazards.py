"""Checker family (a): JAX retrace / host-sync hazards.

The serving stack's perf story (PR 2/5) rests on two invariants a single
stray line can erase: jitted code must never force a host sync, and
program caches must be keyed by a small, stable set of static values.
Three rules, all scoped to functions this checker can PROVE are traced —
``@jax.jit``-decorated (directly or through ``partial``) or
segment-registered (the ``*_segment`` programs the preemption-tolerant
drivers re-enter):

  - ``jax-host-sync``: ``print()`` (trace-time only — use
    ``jax.debug.print``), ``numpy.asarray/array`` on traced values,
    ``.item()`` / ``.tolist()``, and ``float()/int()/bool()`` applied to
    a non-static parameter.
  - ``jax-traced-branch``: Python ``if``/``while``/conditional
    expressions whose test references a non-static parameter — shape/
    dtype/ndim reads are static under tracing and exempt, as are
    ``is``/``is not`` identity tests (static per trace).
  - ``jax-static-loop-arg``: a callsite of a module-known jitted
    function passing a loop variable for one of its STATIC arguments —
    every distinct value compiles a fresh program.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.tpuml_lint.engine import ModuleContext, RepoContext
from tools.tpuml_lint.findings import Finding

#: Attribute reads that are static under tracing (safe in Python branches).
SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "itemsize"}

#: Method calls that force a device->host copy.
SYNC_METHODS = {"item", "tolist"}

#: Builtins that concretize a traced value.
SYNC_BUILTINS = {"float", "int", "bool"}

_NUMPY_ORIGINS = ("numpy",)


def _is_jit_ref(node: ast.AST, module: ModuleContext) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return (
            isinstance(node.value, ast.Name)
            and module.import_bindings.get(node.value.id) == "jax"
        )
    if isinstance(node, ast.Name):
        return module.binds_to(node.id, "jax.jit")
    return False


def _is_partial_ref(node: ast.AST, module: ModuleContext) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "partial":
        return (
            isinstance(node.value, ast.Name)
            and module.import_bindings.get(node.value.id) == "functools"
        )
    if isinstance(node, ast.Name):
        return module.binds_to(node.id, "functools.partial")
    return False


def _static_names_from_call(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                return {
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return set()


def jit_static_names(fn: ast.FunctionDef, module: ModuleContext) -> Optional[Set[str]]:
    """None if ``fn`` is not provably traced; else its static argnames."""
    for dec in fn.decorator_list:
        if _is_jit_ref(dec, module):
            return set()
        if isinstance(dec, ast.Call):
            if _is_jit_ref(dec.func, module):
                return _static_names_from_call(dec)
            if (
                _is_partial_ref(dec.func, module)
                and dec.args
                and _is_jit_ref(dec.args[0], module)
            ):
                return _static_names_from_call(dec)
    if fn.name.endswith("_segment"):
        # Segment-registered programs (the checkpointable solver bodies)
        # are traced by contract even when the jit wrapper lives at the
        # driver; parameters annotated with plain Python types are the
        # static configuration.
        static = set()
        for a in fn.args.args + fn.args.kwonlyargs:
            ann = a.annotation
            if isinstance(ann, ast.Name) and ann.id in ("int", "str", "bool"):
                static.add(a.arg)
        return static
    return None


def _param_names(fn: ast.FunctionDef) -> List[str]:
    return [
        a.arg
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    ]


def _traced_name_refs(node: ast.AST, traced: Set[str]) -> List[ast.Name]:
    """Name nodes in ``node`` referring to traced params, skipping
    static-safe attribute reads and identity comparisons."""
    out: List[ast.Name] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in SAFE_ATTRS:
            return
        if isinstance(n, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
        ):
            return
        if isinstance(n, ast.Name) and n.id in traced:
            out.append(n)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def _check_traced_region(fn: ast.FunctionDef, module: ModuleContext,
                         static: Set[str]) -> List[Finding]:
    rel = module.rel
    findings: List[Finding] = []
    traced = {p for p in _param_names(fn) if p not in static}

    # Host syncs: anywhere in the traced region, nested helpers included.
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "print":
            findings.append(Finding(
                rel, node.lineno, node.col_offset, "jax-host-sync",
                f"print() inside jitted {fn.name}() runs at trace time "
                "only — use jax.debug.print",
            ))
        elif (
            isinstance(f, ast.Attribute)
            and f.attr in ("asarray", "array")
            and isinstance(f.value, ast.Name)
            and module.import_bindings.get(f.value.id) in _NUMPY_ORIGINS
        ):
            findings.append(Finding(
                rel, node.lineno, node.col_offset, "jax-host-sync",
                f"numpy.{f.attr}() inside jitted {fn.name}() forces a "
                "host round trip (use jnp)",
            ))
        elif isinstance(f, ast.Attribute) and f.attr in SYNC_METHODS:
            findings.append(Finding(
                rel, node.lineno, node.col_offset, "jax-host-sync",
                f".{f.attr}() inside jitted {fn.name}() blocks on a "
                "device->host copy",
            ))
        elif (
            isinstance(f, ast.Name)
            and f.id in SYNC_BUILTINS
            and node.args
            and _traced_name_refs(node.args[0], traced)
        ):
            findings.append(Finding(
                rel, node.lineno, node.col_offset, "jax-host-sync",
                f"{f.id}() concretizes traced value inside jitted "
                f"{fn.name}()",
            ))

    # Python control flow on traced values: direct statements only (a
    # nested def rebinds its own parameter namespace).
    def visit(n: ast.AST) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(n, (ast.If, ast.While, ast.IfExp)):
            refs = _traced_name_refs(n.test, traced)
            if refs:
                names = ", ".join(sorted({r.id for r in refs}))
                kind = "while" if isinstance(n, ast.While) else "if"
                findings.append(Finding(
                    rel, n.test.lineno, n.test.col_offset, "jax-traced-branch",
                    f"Python {kind} on traced value(s) {names} inside "
                    f"jitted {fn.name}() — use lax.cond/lax.while_loop "
                    "or mark the argument static",
                ))
        for child in ast.iter_child_nodes(n):
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    return findings


def _static_positions(fn: ast.FunctionDef, static: Set[str]) -> Dict[int, str]:
    out: Dict[int, str] = {}
    params = fn.args.posonlyargs + fn.args.args
    for i, a in enumerate(params):
        if a.arg in static:
            out[i] = a.arg
    return out


def _check_retrace_bait(module: ModuleContext,
                        jitted: Dict[str, Tuple[ast.FunctionDef, Set[str]]]
                        ) -> List[Finding]:
    findings: List[Finding] = []
    rel = module.rel

    def loop_targets(target: ast.AST) -> Sequence[str]:
        if isinstance(target, ast.Name):
            return (target.id,)
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for e in target.elts:
                out.extend(loop_targets(e))
            return out
        return ()

    def visit(node: ast.AST, loops: Set[str]) -> None:
        if isinstance(node, (ast.For, ast.AsyncFor)):
            loops = loops | set(loop_targets(node.target))
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            entry = jitted.get(node.func.id)
            if entry is not None:
                fn, static = entry
                positions = _static_positions(fn, static)
                suspects: List[Tuple[str, ast.AST]] = []
                for i, arg in enumerate(node.args):
                    if i in positions:
                        suspects.append((positions[i], arg))
                for kw in node.keywords:
                    if kw.arg in static:
                        suspects.append((kw.arg, kw.value))
                for pname, expr in suspects:
                    hit = [
                        n.id
                        for n in ast.walk(expr)
                        if isinstance(n, ast.Name) and n.id in loops
                    ]
                    if hit:
                        findings.append(Finding(
                            rel, node.lineno, node.col_offset,
                            "jax-static-loop-arg",
                            f"static argument {pname!r} of jitted "
                            f"{node.func.id}() varies with loop "
                            f"variable(s) {', '.join(sorted(set(hit)))} — "
                            "every distinct value compiles a new program",
                        ))
        for child in ast.iter_child_nodes(node):
            visit(child, loops)

    visit(module.tree, set())
    return findings


#: Extractors whose result is the WHOLE dataset (O(n*d) host memory):
#: placing it with a bare put bypasses the memory-safe fit chokepoint.
#: extract_weights is deliberately absent — an (n,) weight vector is
#: O(n), not the allocation the admission gate prices.
DATASET_EXTRACTORS = {
    "extract_features",
    "extract_column",
    "as_matrix",
    "matrix_like",
    "_extract_xy",
}


def _call_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
    return None


def _check_whole_dataset_put(fn: ast.FunctionDef,
                             module: ModuleContext) -> List[Finding]:
    """``jax-whole-dataset-put``: inside a model ``_fit*`` path, a bare
    ``jnp.asarray`` / ``jax.device_put`` whose argument is the raw
    dataset — a function parameter, or a name assigned from one of the
    dataset extractors — uploads O(n*d) bytes around the guarded ingest
    funnel (``prepare_rows`` / ``ingest.place_array``)."""
    findings: List[Finding] = []
    tainted: Set[str] = {
        p for p in _param_names(fn) if p not in ("self", "params")
    }
    # One forward pass: taint flows through extractor assignments in
    # source order before the puts below them are judged.
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            name = _call_name(node.value)
            target = node.targets[0]
            if name in DATASET_EXTRACTORS:
                if isinstance(target, ast.Name):
                    tainted.add(target.id)
                elif isinstance(target, ast.Tuple) and target.elts:
                    # (X, y) unpack: only the matrix side is O(n*d).
                    first = target.elts[0]
                    if isinstance(first, ast.Name):
                        tainted.add(first.id)
            elif isinstance(target, ast.Name):
                # Reassignment from anything else clears the taint
                # (e.g. a bounded sample drawn FROM the stream).
                tainted.discard(target.id)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        is_put = (
            isinstance(f, ast.Attribute)
            and (
                (f.attr == "asarray"
                 and isinstance(f.value, ast.Name)
                 and module.import_bindings.get(f.value.id) == "jax.numpy")
                or (f.attr == "device_put"
                    and isinstance(f.value, ast.Name)
                    and module.import_bindings.get(f.value.id) == "jax")
            )
        )
        if not is_put:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Name) and arg.id in tainted:
            fname = f"{f.value.id}.{f.attr}"  # type: ignore[union-attr]
            findings.append(Finding(
                module.rel, node.lineno, node.col_offset,
                "jax-whole-dataset-put",
                f"{fname}({arg.id}) in {fn.name}() uploads the whole "
                "dataset around the guarded ingest funnel — route it "
                "through prepare_rows or ingest.place_array",
            ))
    return findings


def check(module: ModuleContext, repo: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    jitted: Dict[str, Tuple[ast.FunctionDef, Set[str]]] = {}
    in_models = module.rel.startswith("spark_rapids_ml_tpu/models/")
    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            static = jit_static_names(node, module)
            if static is not None:
                jitted[node.name] = (node, static)
                findings.extend(_check_traced_region(node, module, static))
            if in_models and node.name.startswith("_fit"):
                findings.extend(_check_whole_dataset_put(node, module))
    findings.extend(_check_retrace_bait(module, jitted))
    return findings
