"""Checker family (b): guarded-by lock discipline — interprocedural.

Convention: the line that first assigns a shared attribute carries a
trailing ``# guarded-by: <lock>`` comment::

    self._versions = {}      # guarded-by: _lock      (class attribute)
    _PROGRAMS = OrderedDict() # guarded-by: _LOCK     (module global)

The checker enforces what the comment promises across method
boundaries: every read or write of the guarded attribute in the owning
class (inheritance within the module included) — or, for a module
global, inside any function of the module — must happen while the lock
is held, either lexically (``with self.<lock>:`` / ``with <lock>:``) or
*provably at every call site*: a private helper (leading underscore,
non-dunder) whose intra-module callers ALL hold the lock inherits that
lock context (must-analysis: the intersection of the held sets at its
call sites, computed to a fixpoint over the per-class/per-module call
graph from :func:`engine.iter_scopes`). ``__init__``/``__new__`` are
exempt for ``self`` attributes (the object is not shared during
construction — construction-time call sites are likewise ignored), as
is module top-level code (imports run single-threaded by convention).

Lock aliases are resolved within a scope: ``self._cv = self._cond`` or
``_MUTEX = _LOCK`` makes either name satisfy a guard declared under the
other (union-find, canonical = smallest name). An attribute assigned
from *another object's* lock (``self._lock = registry._lock``) counts
as a defined lock here; cross-object identity is the runtime
sanitizer's job (``spark_rapids_ml_tpu/utils/lockcheck.py``).

Three more rules ride on the same analysis:

- ``lock-unknown``: an annotation names a lock the owning scope never
  defines, so a typo'd annotation cannot silently check nothing.
- ``lock-order``: nested ``with`` scopes (call graph included, using
  may-held sets so every potential nesting counts) build a static
  acquisition-order graph per module; any cycle — lock A taken under B
  somewhere and B under A somewhere else — is a potential deadlock.
  Reentrant self-nesting (RLock) is not an edge. Cross-class and
  cross-module ordering is invisible statically; the runtime
  sanitizer's global order graph covers that half.
- ``lock-leak``: ``<lock>.acquire()`` without a guaranteed release —
  no enclosing (or immediately following) ``try/finally`` that releases
  the same lock — leaves the lock held forever on the first exception.

A helper the analysis cannot prove (public, or called lock-free from
anywhere) still gets flagged; restructure it, add a lexical ``with``,
or assert the invariant at runtime with ``lockcheck.guarded()`` and
document the exception with ``# tpuml: noqa[lock-guarded]``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.tpuml_lint.engine import (
    ModuleContext,
    RepoContext,
    call_target,
    iter_scopes,
)
from tools.tpuml_lint.findings import Finding

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: threading constructors and the lockcheck factory fronts for them.
_LOCK_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "make_lock", "make_rlock", "make_condition",
}

_CONSTRUCTORS = ("__init__", "__new__")


def _annotation_on(module: ModuleContext, lineno: int) -> Optional[str]:
    if 1 <= lineno <= len(module.lines):
        m = _GUARDED_RE.search(module.lines[lineno - 1])
        if m:
            return m.group(1)
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _LOCK_CTORS
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_CTORS
    return False


class _Aliases:
    """Union-find over lock names; canonical = smallest name, so runs
    are deterministic regardless of declaration order."""

    def __init__(self):
        self._parent: Dict[str, str] = {}

    def find(self, name: str) -> str:
        path = []
        while self._parent.get(name, name) != name:
            path.append(name)
            name = self._parent[name]
        for p in path:
            self._parent[p] = name
        return name

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            keep, drop = sorted((ra, rb))
            self._parent[drop] = keep


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        self.guarded: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.assigned_attrs: Set[str] = set()
        self.lock_attrs: Set[str] = set()
        self.aliases = _Aliases()
        self.alias_pairs: List[Tuple[str, str]] = []


def _scan_class(module: ModuleContext, node: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(node)
    for sub in ast.walk(node):
        targets: List[ast.AST] = []
        if isinstance(sub, ast.Assign):
            targets = list(sub.targets)
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
            targets = [sub.target]
        value = getattr(sub, "value", None)
        for t in targets:
            attr = _self_attr(t)
            if attr is None:
                continue
            info.assigned_attrs.add(attr)
            lock = _annotation_on(module, sub.lineno)
            if lock is not None:
                info.guarded[attr] = (lock, sub.lineno)
            if _is_lock_ctor(value):
                info.lock_attrs.add(attr)
            elif isinstance(value, ast.Attribute):
                other = _self_attr(value)
                if other is not None:
                    info.alias_pairs.append((attr, other))
                else:
                    # self._lock = registry._lock — an adopted lock.
                    # Identity across objects is the runtime half's job;
                    # statically it is "a lock this class defines".
                    info.lock_attrs.add(attr)
    return info


def _resolve_aliases(aliases: _Aliases, pairs: List[Tuple[str, str]],
                     lockish: Set[str]) -> Set[str]:
    """Union alias pairs that touch a known lock name; two passes pick
    up chains declared in either order. Returns the canonical lock set."""
    for _ in range(2):
        canon = {aliases.find(n) for n in lockish}
        for a, b in pairs:
            if aliases.find(a) in canon or aliases.find(b) in canon:
                aliases.union(a, b)
    return {aliases.find(n) for n in lockish}


def _effective(info: _ClassInfo, classes: Dict[str, _ClassInfo],
               seen: Optional[Set[str]] = None
               ) -> Tuple[Dict[str, Tuple[str, int]], Set[str], Set[str],
                          List[Tuple[str, str]]]:
    """(guarded map, attrs-assigned, lock attrs, alias pairs) including
    same-module base classes."""
    seen = seen or set()
    guarded = dict(info.guarded)
    assigned = set(info.assigned_attrs)
    locks = set(info.lock_attrs)
    pairs = list(info.alias_pairs)
    for base in info.bases:
        b = classes.get(base)
        if b is None or base in seen:
            continue
        g, a, lk, pr = _effective(b, classes, seen | {info.node.name})
        for attr, v in g.items():
            guarded.setdefault(attr, v)
        assigned |= a
        locks |= lk
        pairs += pr
    return guarded, assigned, locks, pairs


class _Scope:
    """One resolved class context: effective guarded map, aliases,
    canonical lock-attr set."""

    def __init__(self, name: str, info: _ClassInfo,
                 classes: Dict[str, _ClassInfo]):
        self.name = name
        self.info = info
        guarded, assigned, locks, pairs = _effective(info, classes)
        self.guarded = guarded
        self.assigned = assigned
        self.aliases = info.aliases
        lockish = locks | {lock for lock, _ in guarded.values()
                           if lock in assigned}
        self.lock_canon = _resolve_aliases(self.aliases, pairs, lockish)
        self.methods = {
            s.name: s for s in info.node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }


class _Analysis:
    """The whole-module pass: alias-resolved guard checking with
    interprocedural must-held propagation, may-held lock-order edge
    collection, and leak detection."""

    def __init__(self, module: ModuleContext):
        self.module = module
        self.findings: List[Finding] = []
        # (src, dst) -> (line, scope qualname); src/dst are module-scoped
        # lock tokens ("_LOCK" or "ClassName._lock", alias-canonical).
        self.edges: Dict[Tuple[str, str], Tuple[int, str]] = {}

        # --- module-level declarations ---------------------------------
        self.mod_guarded: Dict[str, Tuple[str, int]] = {}
        self.mod_names: Set[str] = set()
        self.mod_aliases = _Aliases()
        mod_locks: Set[str] = set()
        mod_pairs: List[Tuple[str, str]] = []
        for stmt in module.tree.body:
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target] if isinstance(stmt, ast.AnnAssign)
                else []
            )
            value = getattr(stmt, "value", None)
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                self.mod_names.add(t.id)
                lock = _annotation_on(module, stmt.lineno)
                if lock is not None:
                    self.mod_guarded[t.id] = (lock, stmt.lineno)
                if _is_lock_ctor(value):
                    mod_locks.add(t.id)
                elif isinstance(value, ast.Name):
                    mod_pairs.append((t.id, value.id))
        lockish = mod_locks | {
            lock for lock, _ in self.mod_guarded.values()
            if lock in self.mod_names
        }
        self.mod_lock_canon = _resolve_aliases(
            self.mod_aliases, mod_pairs, lockish)

        # --- classes (inheritance resolved within the module) -----------
        infos: Dict[str, _ClassInfo] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                infos[node.name] = _scan_class(module, node)
        self.scopes: Dict[str, _Scope] = {
            name: _Scope(name, info, infos) for name, info in infos.items()
        }

        # --- the call-graph node set ------------------------------------
        # fid: ("cls", ClassName, meth) | ("mod", fname); jobs carry the
        # fn node + owning scope. Only scopes from iter_scopes are nodes;
        # nested defs are analyzed as part of their enclosing scope.
        self.jobs: List[Tuple[tuple, Optional[_Scope], ast.AST]] = []
        self.mod_funcs: Set[str] = set()
        for cls_name, fn in iter_scopes(module.tree):
            if cls_name is None:
                self.jobs.append((("mod", fn.name), None, fn))
                self.mod_funcs.add(fn.name)
            else:
                self.jobs.append(
                    (("cls", cls_name, fn.name), self.scopes[cls_name], fn))
        # fid -> (frozenset of self-canon locks, frozenset of mod-canon
        # locks) a private helper provably/possibly enters with.
        self.entry_must: Dict[tuple, tuple] = {}
        self.entry_may: Dict[tuple, tuple] = {}

    # --- helpers --------------------------------------------------------

    def has_locks(self) -> bool:
        return bool(
            self.mod_guarded or self.mod_lock_canon
            or any(s.guarded or s.lock_canon for s in self.scopes.values())
        )

    @staticmethod
    def _creditable(fid: tuple) -> bool:
        name = fid[-1]
        return name.startswith("_") and not name.startswith("__")

    def _entry_sets(self, fid: tuple, scope: Optional[_Scope]
                    ) -> Tuple[Set[str], Set[str], List[str]]:
        """(must self, must mod, may order tokens) for one job under the
        current fixpoint state. Absent entries are bottom (no credit):
        the fixpoint climbs from below, so a helper is only ever
        credited with locks provably held at EVERY call site."""
        self_must, mod_must = self.entry_must.get(
            fid, (frozenset(), frozenset()))
        may_s, may_m = self.entry_may.get(fid, (frozenset(), frozenset()))
        tokens = sorted(
            f"{scope.name}.{a}" for a in may_s
            if scope is not None and a in scope.lock_canon
        ) + sorted(m for m in may_m if m in self.mod_lock_canon)
        return set(self_must), set(mod_must), tokens

    # --- the traversal (shared by fixpoint + final check) ---------------

    def _walk(self, fid: tuple, scope: Optional[_Scope], fn: ast.AST,
              on_call, check: bool) -> None:
        check_self = check and fn.name not in _CONSTRUCTORS
        self_must, mod_must, may0 = self._entry_sets(fid, scope)
        qual = f"{scope.name}.{fn.name}" if scope else fn.name

        def tokens_for(item_expr: ast.AST) -> Tuple[Optional[str],
                                                    Optional[str],
                                                    Optional[str]]:
            """(self canon, mod canon, order token) for one with-item."""
            attr = _self_attr(item_expr)
            if attr is not None and scope is not None:
                c = scope.aliases.find(attr)
                tok = f"{scope.name}.{c}" if c in scope.lock_canon else None
                return c, None, tok
            if isinstance(item_expr, ast.Name):
                c = self.mod_aliases.find(item_expr.id)
                tok = c if c in self.mod_lock_canon else None
                return None, c, tok
            return None, None, None

        def visit(node: ast.AST, s_held: Set[str], m_held: Set[str],
                  order: List[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                s2, m2, o2 = set(s_held), set(m_held), list(order)
                for item in node.items:
                    s_c, m_c, tok = tokens_for(item.context_expr)
                    if s_c is not None:
                        s2.add(s_c)
                    if m_c is not None:
                        m2.add(m_c)
                    if tok is not None and tok not in o2:
                        for prev in o2:
                            self.edges.setdefault(
                                (prev, tok), (node.lineno, qual))
                        o2.append(tok)
                for child in node.body:
                    visit(child, s2, m2, o2)
                return
            if isinstance(node, ast.Call):
                tgt = call_target(node)
                if tgt is not None:
                    if tgt[0] == "self" and scope is not None:
                        on_call(("cls", scope.name, tgt[1]), fn.name,
                                s_held, m_held, order)
                    elif tgt[0] == "local" and tgt[1] in self.mod_funcs:
                        on_call(("mod", tgt[1]), fn.name,
                                s_held, m_held, order)
            if check:
                attr = _self_attr(node)
                if (
                    check_self and scope is not None
                    and attr is not None and attr in scope.guarded
                ):
                    lock = scope.guarded[attr][0]
                    if scope.aliases.find(lock) not in s_held:
                        ctx = getattr(node, "ctx", None)
                        verb = ("written"
                                if isinstance(ctx, (ast.Store, ast.Del))
                                else "read")
                        self.findings.append(Finding(
                            self.module.rel, node.lineno, node.col_offset,
                            "lock-guarded",
                            f"self.{attr} is {verb} in {qual}() outside "
                            f"'with self.{lock}:' (declared guarded-by "
                            f"{lock})",
                        ))
                if isinstance(node, ast.Name) and node.id in self.mod_guarded:
                    lock = self.mod_guarded[node.id][0]
                    if self.mod_aliases.find(lock) not in m_held:
                        verb = ("written"
                                if isinstance(node.ctx, (ast.Store, ast.Del))
                                else "read")
                        self.findings.append(Finding(
                            self.module.rel, node.lineno, node.col_offset,
                            "lock-guarded",
                            f"module global {node.id} is {verb} outside "
                            f"'with {lock}:' (declared guarded-by {lock})",
                        ))
            for child in ast.iter_child_nodes(node):
                visit(child, s_held, m_held, order)

        for stmt in fn.body:
            visit(stmt, set(self_must), set(mod_must), list(may0))

    # --- the fixpoint ---------------------------------------------------

    def solve(self) -> None:
        """Iterate to the held-set fixpoint. Each round re-derives every
        creditable helper's entry sets from the held sets observed at
        its call sites (must = intersection, may = union) under the
        previous round's entries. Starting from bottom, must-sets only
        grow toward what is provable at every site, so this converges;
        the bound is a backstop for pathological graphs."""
        creditable = {
            fid for fid, _, _ in self.jobs if self._creditable(fid)
        }
        for _ in range(len(self.jobs) + 2):
            contrib_must: Dict[tuple, tuple] = {}
            contrib_may: Dict[tuple, tuple] = {}

            def on_call(callee, caller_name, s_held, m_held, order):
                if callee not in creditable:
                    return
                if callee[0] == "cls" and caller_name in _CONSTRUCTORS:
                    return  # construction-time call: object unshared
                s, m = frozenset(s_held), frozenset(m_held)
                prev = contrib_must.get(callee)
                contrib_must[callee] = (
                    (s, m) if prev is None else (prev[0] & s, prev[1] & m))
                pm = contrib_may.get(callee, (frozenset(), frozenset()))
                contrib_may[callee] = (pm[0] | s, pm[1] | m)

            for fid, scope, fn in self.jobs:
                self._walk(fid, scope, fn, on_call, check=False)

            if (contrib_must == self.entry_must
                    and contrib_may == self.entry_may):
                break
            self.entry_must = contrib_must
            self.entry_may = contrib_may

    # --- final passes ---------------------------------------------------

    def report_guards(self) -> None:
        def on_call(*_args):
            pass

        for fid, scope, fn in self.jobs:
            self._walk(fid, scope, fn, on_call, check=True)
        # Top-level code outside any function is exempt (single-threaded
        # import convention) but still contributes no findings — matching
        # the seed checker's behavior.

    def report_unknown(self) -> None:
        for name, (lock, line) in self.mod_guarded.items():
            if lock not in self.mod_names:
                self.findings.append(Finding(
                    self.module.rel, line, 0, "lock-unknown",
                    f"guarded-by names {lock!r}, which this module never "
                    "assigns at top level",
                ))
        for name, scope in self.scopes.items():
            for attr, (lock, line) in sorted(scope.guarded.items()):
                if attr in scope.info.guarded and lock not in scope.assigned:
                    self.findings.append(Finding(
                        self.module.rel, line, 0, "lock-unknown",
                        f"guarded-by names self.{lock}, which {name} (and "
                        "its bases here) never assigns",
                    ))

    def report_cycles(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (src, dst), _ in self.edges.items():
            adj.setdefault(src, set()).add(dst)
        seen_cycles: Set[frozenset] = set()
        for (src, dst), (line, qual) in sorted(
            self.edges.items(), key=lambda kv: (kv[1][0], kv[0])
        ):
            # Does dst reach src? Then this edge closes a cycle.
            path = self._find_path(adj, dst, src)
            if path is None:
                continue
            nodes = [src] + path[:-1]  # path runs dst..src inclusive
            key = frozenset(nodes)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            self.findings.append(Finding(
                self.module.rel, line, 0, "lock-order",
                "lock acquisition-order cycle: "
                + " -> ".join(nodes + [src])
                + f" (edge {src} -> {dst} added in {qual}); two threads "
                "taking these locks in opposite orders deadlock",
            ))

    @staticmethod
    def _find_path(adj: Dict[str, Set[str]], start: str, goal: str
                   ) -> Optional[List[str]]:
        parent: Dict[str, Optional[str]] = {start: None}
        queue = [start]
        while queue:
            cur = queue.pop(0)
            if cur == goal:
                path = [cur]
                while parent[cur] is not None:
                    cur = parent[cur]
                    path.append(cur)
                return list(reversed(path))
            for nxt in sorted(adj.get(cur, ())):
                if nxt not in parent:
                    parent[nxt] = cur
                    queue.append(nxt)
        return None

    def report_leaks(self) -> None:
        all_cls_locks: Set[str] = set()
        for scope in self.scopes.values():
            all_cls_locks |= {
                a for a in scope.assigned
                if scope.aliases.find(a) in scope.lock_canon
            }
        for node in ast.walk(self.module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                continue
            base = node.func.value
            attr = _self_attr(base)
            if attr is not None and attr in all_cls_locks:
                desc = f"self.{attr}"
            elif (
                isinstance(base, ast.Name)
                and self.mod_aliases.find(base.id) in self.mod_lock_canon
            ):
                desc = base.id
            else:
                continue
            if self._has_release_path(node, base):
                continue
            self.findings.append(Finding(
                self.module.rel, node.lineno, node.col_offset, "lock-leak",
                f"{desc}.acquire() without a guaranteed release path "
                "(no try/finally releasing it) — the first exception "
                f"leaves it held forever; use 'with {desc}:'",
            ))

    def _has_release_path(self, call: ast.Call, base: ast.AST) -> bool:
        def releases(body: List[ast.stmt]) -> bool:
            for sub in body:
                for n in ast.walk(sub):
                    if (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "release"
                        and ast.dump(n.func.value) == ast.dump(base)
                    ):
                        return True
            return False

        # (a) an enclosing try/finally that releases the same lock
        node: ast.AST = call
        while True:
            parent = self.module.parent_of(node)
            if parent is None:
                break
            if isinstance(parent, ast.Try) and releases(parent.finalbody):
                return True
            node = parent
        # (b) lock.acquire() immediately followed by try/finally release
        stmt: ast.AST = call
        parent = self.module.parent_of(stmt)
        while parent is not None and not isinstance(stmt, ast.stmt):
            stmt = parent
            parent = self.module.parent_of(stmt)
        if parent is not None:
            for field in ("body", "orelse", "finalbody"):
                body = getattr(parent, field, None)
                if isinstance(body, list) and stmt in body:
                    i = body.index(stmt)
                    if (
                        i + 1 < len(body)
                        and isinstance(body[i + 1], ast.Try)
                        and releases(body[i + 1].finalbody)
                    ):
                        return True
        return False


def analyze(module: ModuleContext) -> _Analysis:
    """Run the full pass; exposed so ``--lock-graph`` can dump the
    acquisition-order edges the checker derived."""
    a = _Analysis(module)
    if not a.has_locks():
        return a
    a.solve()
    a.report_unknown()
    a.report_guards()
    a.report_cycles()
    a.report_leaks()
    return a


def order_edges(module: ModuleContext) -> List[dict]:
    """The module's static acquisition-order edges as JSON-able rows."""
    a = _Analysis(module)
    if not a.has_locks():
        return []
    a.solve()
    a.report_guards()  # the edge-collecting traversal
    return [
        {"module": module.rel, "src": src, "dst": dst,
         "line": line, "scope": qual}
        for (src, dst), (line, qual) in sorted(a.edges.items())
    ]


def check(module: ModuleContext, repo: RepoContext) -> List[Finding]:
    if module.tree is None:
        return []
    return analyze(module).findings
