"""Checker family (b): guarded-by lock discipline.

Convention: the line that first assigns a shared attribute carries a
trailing ``# guarded-by: <lock>`` comment::

    self._versions = {}      # guarded-by: _lock      (class attribute)
    _PROGRAMS = OrderedDict() # guarded-by: _LOCK     (module global)

The checker then enforces what the comment promises, lexically: every
subsequent read or write of the guarded attribute in the owning class
(inheritance within the module included) — or, for a module global,
inside any function of the module — must sit inside a ``with
self.<lock>:`` / ``with <lock>:`` block. ``__init__``/``__new__`` are
exempt (the object is not shared during construction), as is module
top-level code (imports run single-threaded by convention).

A helper that is only ever CALLED with the lock held still gets flagged
— that is deliberate: the convention is lexical so it can be machine-
checked; restructure the helper to take values as arguments, or
document the exception with ``# tpuml: noqa[lock-guarded]``.

``lock-unknown`` fires when an annotation names a lock the owning scope
never defines, so a typo'd annotation cannot silently check nothing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.tpuml_lint.engine import ModuleContext, RepoContext
from tools.tpuml_lint.findings import Finding

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _annotation_on(module: ModuleContext, lineno: int) -> Optional[str]:
    if 1 <= lineno <= len(module.lines):
        m = _GUARDED_RE.search(module.lines[lineno - 1])
        if m:
            return m.group(1)
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        self.guarded: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.assigned_attrs: Set[str] = set()


def _scan_class(module: ModuleContext, node: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(node)
    for sub in ast.walk(node):
        targets: List[ast.AST] = []
        if isinstance(sub, ast.Assign):
            targets = list(sub.targets)
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
            targets = [sub.target]
        for t in targets:
            attr = _self_attr(t)
            if attr is None:
                continue
            info.assigned_attrs.add(attr)
            lock = _annotation_on(module, sub.lineno)
            if lock is not None:
                info.guarded[attr] = (lock, sub.lineno)
    return info


def _effective(info: _ClassInfo, classes: Dict[str, _ClassInfo],
               seen: Optional[Set[str]] = None
               ) -> Tuple[Dict[str, Tuple[str, int]], Set[str]]:
    """(guarded map, attrs-assigned) including same-module base classes."""
    seen = seen or set()
    guarded = dict(info.guarded)
    assigned = set(info.assigned_attrs)
    for base in info.bases:
        b = classes.get(base)
        if b is None or base in seen:
            continue
        g, a = _effective(b, classes, seen | {info.node.name})
        for attr, v in g.items():
            guarded.setdefault(attr, v)
        assigned |= a
    return guarded, assigned


def _check_method(module: ModuleContext, cls: str, fn: ast.FunctionDef,
                  guarded: Dict[str, Tuple[str, int]]) -> List[Finding]:
    findings: List[Finding] = []

    def visit(node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    inner.add(attr)
            for child in node.body:
                visit(child, inner)
            return
        attr = _self_attr(node)
        if attr is not None and attr in guarded:
            lock = guarded[attr][0]
            if lock not in held:
                ctx = getattr(node, "ctx", None)
                verb = "written" if isinstance(ctx, (ast.Store, ast.Del)) else "read"
                findings.append(Finding(
                    module.rel, node.lineno, node.col_offset, "lock-guarded",
                    f"self.{attr} is {verb} in {cls}.{fn.name}() outside "
                    f"'with self.{lock}:' (declared guarded-by {lock})",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, set())
    return findings


def _check_module_globals(module: ModuleContext,
                          guarded: Dict[str, Tuple[str, int]]) -> List[Finding]:
    findings: List[Finding] = []

    def visit(node: ast.AST, held: Set[str], in_fn: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in node.body:
                visit(child, held, True)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                if isinstance(item.context_expr, ast.Name):
                    inner.add(item.context_expr.id)
            for child in node.body:
                visit(child, inner, in_fn)
            return
        if in_fn and isinstance(node, ast.Name) and node.id in guarded:
            lock = guarded[node.id][0]
            if lock not in held:
                verb = (
                    "written"
                    if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                findings.append(Finding(
                    module.rel, node.lineno, node.col_offset, "lock-guarded",
                    f"module global {node.id} is {verb} outside "
                    f"'with {lock}:' (declared guarded-by {lock})",
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, held, in_fn)

    for stmt in module.tree.body:
        visit(stmt, set(), False)
    return findings


def check(module: ModuleContext, repo: RepoContext) -> List[Finding]:
    findings: List[Finding] = []

    # Module globals: annotated top-level assignments.
    module_guarded: Dict[str, Tuple[str, int]] = {}
    module_names: Set[str] = set()
    for stmt in module.tree.body:
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign)
            else [stmt.target] if isinstance(stmt, ast.AnnAssign)
            else []
        )
        for t in targets:
            if isinstance(t, ast.Name):
                module_names.add(t.id)
                lock = _annotation_on(module, stmt.lineno)
                if lock is not None:
                    module_guarded[t.id] = (lock, stmt.lineno)
    for name, (lock, line) in module_guarded.items():
        if lock not in module_names:
            findings.append(Finding(
                module.rel, line, 0, "lock-unknown",
                f"guarded-by names {lock!r}, which this module never "
                "assigns at top level",
            ))
    if module_guarded:
        findings.extend(_check_module_globals(module, module_guarded))

    # Classes (inheritance resolved within the module).
    classes: Dict[str, _ClassInfo] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = _scan_class(module, node)
    for name, info in classes.items():
        guarded, assigned = _effective(info, classes)
        if not guarded:
            continue
        for attr, (lock, line) in sorted(guarded.items()):
            if attr in info.guarded and lock not in assigned:
                findings.append(Finding(
                    module.rel, line, 0, "lock-unknown",
                    f"guarded-by names self.{lock}, which {name} (and its "
                    "bases here) never assigns",
                ))
        for stmt in info.node.body:
            if (
                isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name not in ("__init__", "__new__")
            ):
                findings.extend(
                    _check_method(module, name, stmt, guarded)
                )
    return findings
