"""Checker family (c): the ``TPUML_*`` environment-knob registry.

Three rules close the loop between code, registry, and docs:

  - ``knob-raw-environ``: reading a ``TPUML_*`` variable through
    ``os.environ`` / ``os.getenv`` instead of the ``utils/envknobs``
    accessors. Keys are resolved through module-level string constants
    (``FAULTS_ENV = "TPUML_FAULTS"``), and any ``*_ENV``-named constant
    read is treated as a knob read even when the value is imported from
    another module. Writes (``os.environ[X] = ...`` for subprocess
    launches) are allowed.
  - ``knob-unregistered``: a ``TPUML_*`` string literal (docstrings and
    prefix strings ending in ``_`` excluded) with no ``Knob`` entry in
    ``envknobs.KNOBS``.
  - ``knob-undocumented`` (repo-level): a registered knob missing from
    the knob tables in ``docs/PARITY.md``.

``TPUML_TEST_*`` names are harness inputs, not runtime knobs, and are
exempt everywhere; ``utils/envknobs.py`` itself is exempt from the raw-
read rule (it IS the accessor layer).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from tools.tpuml_lint.engine import ModuleContext, RepoContext
from tools.tpuml_lint.findings import Finding

_KNOB_NAME = re.compile(r"^TPUML_[A-Z0-9]+(?:_[A-Z0-9]+)*$")


def _is_test_knob(name: str) -> bool:
    return name.startswith("TPUML_TEST_")


def _environ_read_key(node: ast.Call) -> Optional[ast.AST]:
    """The key expression when ``node`` reads the environment:
    ``os.environ.get(k, ...)`` or ``os.getenv(k, ...)``."""
    f = node.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "get"
        and isinstance(f.value, ast.Attribute)
        and f.value.attr == "environ"
        and isinstance(f.value.value, ast.Name)
        and f.value.value.id == "os"
    ):
        return node.args[0] if node.args else None
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "getenv"
        and isinstance(f.value, ast.Name)
        and f.value.id == "os"
    ):
        return node.args[0] if node.args else None
    return None


def _env_constant_name(key: ast.AST) -> Optional[str]:
    """The ``*_ENV`` constant a key expression names, if any."""
    if isinstance(key, ast.Name) and key.id.endswith("_ENV"):
        return key.id
    if isinstance(key, ast.Attribute) and key.attr.endswith("_ENV"):
        return key.attr
    return None


def check(module: ModuleContext, repo: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    rel = module.rel
    is_accessor_layer = rel == RepoContext.ENVKNOBS_REL

    # --- raw environment reads ---
    if not is_accessor_layer:
        for node in ast.walk(module.tree):
            key = None
            if isinstance(node, ast.Call):
                key = _environ_read_key(node)
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "environ"
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "os"
            ):
                key = node.slice
            if key is None:
                continue
            resolved = module.resolve_str(key)
            knob = None
            if resolved is not None:
                if resolved.startswith("TPUML_") and not _is_test_knob(resolved):
                    knob = resolved
            else:
                knob = _env_constant_name(key)
            if knob is not None:
                findings.append(Finding(
                    rel, node.lineno, node.col_offset, "knob-raw-environ",
                    f"raw os.environ read of {knob} — use the "
                    "utils/envknobs accessors (env_int/env_float/"
                    "env_str/env_choice)",
                ))

    # --- unregistered literals ---
    if repo.knobs is not None and not is_accessor_layer:
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in module.docstring_nodes
            ):
                continue
            name = node.value
            if not _KNOB_NAME.match(name):
                continue
            if _is_test_knob(name) or name in repo.knobs:
                continue
            findings.append(Finding(
                rel, node.lineno, node.col_offset, "knob-unregistered",
                f"{name} has no Knob entry in envknobs.KNOBS — register "
                "it (and document it in docs/PARITY.md)",
            ))
    return findings


def check_repo(repo: RepoContext) -> List[Finding]:
    """Repo-level docs cross-check: every registered knob must appear in
    PARITY.md's knob tables."""
    findings: List[Finding] = []
    if repo.knobs is None or repo.parity_text is None:
        return findings
    for name, line in sorted(repo.knobs.items()):
        if name not in repo.parity_text:
            findings.append(Finding(
                RepoContext.ENVKNOBS_REL, line, 0, "knob-undocumented",
                f"registered knob {name} is missing from "
                f"{RepoContext.PARITY_REL}'s knob tables",
            ))
    return findings
