"""Developer tooling: the static quality gate (``tools.tpuml_lint``),
telemetry CLIs (``tpuml_metrics``), and the serving load generator
(``tpuml_loadgen``). A package so ``python -m tools.tpuml_lint`` works
from a checkout with no install step."""
