#!/usr/bin/env python
"""Assemble per-process telemetry shards into one distributed trace.

A run under ``TPUML_TELEMETRY_DIR=<dir>`` leaves one event-log shard,
one metrics snapshot and one manifest per process. This CLI merges them
(``spark_rapids_ml_tpu/observability/trace.py``): every record is
schema-validated, every process is put on one mono-aligned clock, spans
are joined into per-trace trees across process boundaries, the critical
path per trace is computed, counters/histograms/gauges are merged
gang-wide, and the whole thing can be rendered as Chrome/Perfetto
trace-event JSON.

Examples::

    python tools/tpuml_trace.py /tmp/telemetry
    python tools/tpuml_trace.py /tmp/telemetry --out trace.json   # Perfetto
    python tools/tpuml_trace.py /tmp/telemetry --validate         # CI gate
    python tools/tpuml_trace.py /tmp/telemetry --validate --strict
    python tools/tpuml_trace.py /tmp/telemetry --metrics-out merged.json

``--validate`` exits non-zero on malformed shards/records; ``--strict``
additionally fails on orphan spans (a span whose parent resolves to no
shard — the cross-process-join oracle the gang tests assert with). A
shard with no manifest — a member killed before its atexit flush — is
reported as a WARNING, never a failure: that shard is exactly the
evidence a post-mortem needs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _import_trace_lib():
    """The shared assembly library — importable both with the package
    installed and when this script runs straight from a checkout.

    The READER must not become a member: importing the package wires the
    event sink from the environment, and an inherited TPUML_TELEMETRY_DIR
    would make this process drop its own (manifest-less) shard into the
    very dir it is assembling. Empty reads as unset, so blank it first."""
    os.environ["TPUML_TELEMETRY_DIR"] = ""
    os.environ["TPUML_EVENT_LOG"] = ""
    try:
        from spark_rapids_ml_tpu.observability import trace
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        from spark_rapids_ml_tpu.observability import trace
    return trace


def _render_text(merged: dict) -> str:
    lines = [
        f"{merged['record_count']} records from "
        f"{len(merged['manifests'])} manifest(s) under {merged['dir']}"
    ]
    for m in merged["manifests"]:
        lines.append(
            f"  member pid={m.get('pid')} process={m.get('process')} "
            f"shard={m.get('shard')} emitted={m.get('emitted')} "
            f"trace_roots={len(m.get('trace_roots', []))}"
            + (f" [flight: {m['flight']}]" if m.get("flight") else "")
        )
    for f in merged.get("flights", []):
        lines.append(f"  flight recorder dump merged: {f}")
    for tid, cell in sorted(merged["traces"].items(), key=lambda kv: str(kv[0])):
        lines.append(
            f"trace {tid}  spans={cell['spans']} events={cell['events']} "
            f"roots={cell['roots']} orphans={len(cell['orphans'])} "
            f"processes={cell['processes']}"
        )
        cp = cell["critical_path"]
        if cp:
            lines.append("  critical path:")
            for hop in cp:
                dur = hop.get("dur")
                dur_s = f"{dur * 1e3:9.2f} ms" if dur is not None else "        ?"
                lines.append(
                    f"    {dur_s}  {hop.get('name')}  "
                    f"(process {hop.get('process')})"
                )
    counters = {
        k: v for k, v in sorted(merged["metrics"]["merged"]["counters"].items())
        if v
    }
    if counters:
        lines.append("merged counters:")
        for k, v in counters.items():
            lines.append(f"  {k} = {v}")
    for p in merged["problems"]:
        lines.append(f"PROBLEM {p}")
    for p in merged["warnings"]:
        lines.append(f"WARNING {p}")
    for p in merged["orphan_problems"]:
        lines.append(f"ORPHAN {p}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("dir", help="the TPUML_TELEMETRY_DIR to assemble")
    parser.add_argument("--out", default=None,
                        help="write Chrome/Perfetto trace-event JSON here")
    parser.add_argument("--metrics-out", default=None,
                        help="write the merged metrics snapshot here")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--validate", action="store_true",
                        help="exit 1 on malformed shards/records")
    parser.add_argument("--strict", action="store_true",
                        help="with --validate, also fail on orphan spans")

    args = parser.parse_args(argv)
    trace = _import_trace_lib()

    merged = trace.assemble(args.dir)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace.chrome_trace(merged["records"]), f)
            f.write("\n")
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(merged["metrics"]["merged"], f, indent=2, default=str)
            f.write("\n")

    if args.format == "json":
        out = {
            k: merged[k]
            for k in ("dir", "record_count", "manifests", "flights",
                      "traces", "problems", "warnings", "orphan_problems")
        }
        out["merged_metrics"] = merged["metrics"]["merged"]
        print(json.dumps(out, indent=2, default=str))
    else:
        print(_render_text(merged))

    if args.validate:
        failures = list(merged["problems"])
        if args.strict:
            failures += merged["orphan_problems"]
        if failures:
            for p in failures:
                print(f"INVALID {p}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
