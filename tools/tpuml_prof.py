#!/usr/bin/env python
"""Render, validate, merge, and diff spark-tpu-ml program cost ledgers.

A ledger document is what ``TPUML_COST_LEDGER=1`` captures
(``observability/costs.py``): per compiled program, XLA's
``cost_analysis`` / ``memory_analysis`` plus cumulative invocation and
wall counters. Sources: a single JSON file (``TPUML_COST_LEDGER_DUMP``)
or a telemetry directory of per-process ``costs-<pid>.json`` shards
(``TPUML_TELEMETRY_DIR``), which are merged first (counters sum, HBM
watermarks max).

Modes::

    tpuml_prof.py LEDGER                 # top-K programs + family rollup
    tpuml_prof.py LEDGER --sort flops    # order by flops|bytes|wall
    tpuml_prof.py LEDGER --validate      # schema gate: exit 1 on problems
    tpuml_prof.py --diff OLD NEW --max-regress 25
                                         # CI perf gate: exit 1 when a
                                         # family's total flops or bytes
                                         # grew more than 25%
    tpuml_prof.py tune STORE             # the autotuner's accepted
                                         # decisions: per-knob incumbent
                                         # vs rejected candidates with
                                         # measured deltas
    tpuml_prof.py tune STORE --explain FAMILY --ledger LEDGER
                                         # fitted cost-model coefficients
                                         # + the evidence entries behind
                                         # each committed decision

``--diff`` compares per-family TOTALS (analyzed flops/bytes × run
invocations) so it gates what the workload actually executed, not just
what got compiled; wall seconds are reported but never gated (they
measure the machine, not the program). Families that appear or
disappear are reported as notes, not failures.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple


def _import_costs():
    """The ledger helpers — importable both with the package installed
    and when this script runs straight from a checkout."""
    try:
        from spark_rapids_ml_tpu.observability import costs
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        from spark_rapids_ml_tpu.observability import costs
    return costs


def load_ledger(path: str) -> Tuple[dict, List[str]]:
    """Decode a ledger source: a JSON document, or a directory of
    ``costs-*.json`` shards (merged). Returns (doc, problems)."""
    costs = _import_costs()
    if os.path.isdir(path):
        docs = costs.load_ledger_dir(path)
        if not docs:
            return {}, [f"no costs-*.json shards under {path}"]
        problems: List[str] = []
        for i, doc in enumerate(docs):
            problems.extend(f"shard {i}: {p}" for p in costs.validate_ledger(doc))
        return costs.merge_ledger_docs(docs), problems
    with open(path) as f:
        doc = json.load(f)
    return doc, costs.validate_ledger(doc)


_SORT_FIELDS = {
    "flops": lambda e: (e.get("flops") or 0.0) * (e.get("invocations") or 0),
    "bytes": lambda e: (e.get("bytes_accessed") or 0.0)
    * (e.get("invocations") or 0),
    "wall": lambda e: e.get("wall_seconds") or 0.0,
}


def render(doc: dict, sort: str = "wall", top: int = 20) -> str:
    """Human dump: top-K programs by the sort key + per-family rollup,
    watermarks, and retrace-watchdog summary."""
    costs = _import_costs()
    key_fn = _SORT_FIELDS[sort]
    entries = sorted(doc.get("entries", []), key=key_fn, reverse=True)
    lines = [
        f"{len(entries)} programs"
        + (f" (merged from {doc['merged_from']} shards)"
           if doc.get("merged_from") else ""),
        f"top {min(top, len(entries))} by {sort}:",
        f"  {'program':<46s} {'kind':<8s} {'calls':>6s} {'flops/call':>11s} "
        f"{'bytes/call':>11s} {'wall s':>8s} {'compile s':>9s}",
    ]
    for e in entries[:top]:
        flops = e.get("flops")
        byts = e.get("bytes_accessed")
        marker = " !" + ",".join(e["unavailable"]) if e.get("unavailable") else ""
        lines.append(
            f"  {str(e.get('key'))[:46]:<46s} {str(e.get('kind')):<8s} "
            f"{e.get('invocations', 0):>6d} "
            f"{(f'{flops:.3g}' if flops is not None else 'n/a'):>11s} "
            f"{(f'{byts:.3g}' if byts is not None else 'n/a'):>11s} "
            f"{e.get('wall_seconds', 0.0):>8.3f} "
            f"{e.get('compile_seconds', 0.0):>9.3f}{marker}"
        )
    rollup = costs.family_rollup(doc)
    # Resolved precision policy per family (ops/precision.py), recorded
    # into the dump at snapshot time: a family running a cheaper mode is
    # priced against that mode's peak, so its utilization column here is
    # comparable across modes.
    modes = doc.get("precision_modes") or {}
    passes = {"f32": 6, "highest": 6, "high": 3, "bf16x3": 3,
              "default": 1, "bf16": 1}
    if rollup:
        lines.append("per-family rollup:")
        lines.append(
            f"  {'family':<28s} {'progs':>5s} {'compiles':>8s} {'calls':>7s} "
            f"{'total flops':>12s} {'total bytes':>12s} {'wall s':>8s} "
            f"{'prec':>6s} {'util':>6s}"
        )
        peak = (doc.get("peaks") or {}).get("flops_per_sec")
        for fam, cell in sorted(
            rollup.items(), key=lambda kv: -kv[1]["wall_seconds"]
        ):
            # Forward-pass programs (x.predict / x.transform) run under
            # the serving policy; other dotted families fall back to
            # their fit-family prefix (mirrors precision.active_mode).
            mode = modes.get(fam)
            if mode is None and "." in fam:
                if fam.rsplit(".", 1)[1] in ("predict", "transform", "serve"):
                    mode = modes.get("serving")
                if mode is None:
                    mode = modes.get(fam.split(".", 1)[0])
            util = "n/a"
            if peak and cell["wall_seconds"] > 0 and cell["total_flops"]:
                scale = 6.0 / passes[mode] if mode in passes else 1.0
                frac = cell["total_flops"] / cell["wall_seconds"] / (peak * scale)
                util = f"{frac:>5.1%}"
            lines.append(
                f"  {fam[:28]:<28s} {cell['programs']:>5d} "
                f"{cell['compiles']:>8d} {cell['invocations']:>7d} "
                f"{cell['total_flops']:>12.4g} {cell['total_bytes']:>12.4g} "
                f"{cell['wall_seconds']:>8.3f} "
                f"{(mode or '-'):>6s} {util:>6s}"
            )
    watermarks = doc.get("watermarks") or {}
    for dev, cell in sorted(watermarks.items()):
        lines.append(
            f"device {dev}: peak {cell.get('peak_bytes', 0)} bytes, "
            f"in-use watermark {cell.get('in_use', 0)} bytes"
        )
    retraces = doc.get("retraces") or {}
    if retraces.get("total"):
        lines.append(f"RETRACES: {retraces['total']} unexpected recompiles")
        for fam, n in sorted((retraces.get("families") or {}).items()):
            lines.append(f"  {fam}: {n}")
    return "\n".join(lines)


#: Family-rollup dimensions the diff GATES (deterministic program
#: analyses × workload invocations); wall time is report-only.
GATED_DIMS = ("total_flops", "total_bytes")


def diff_ledgers(
    old_doc: dict, new_doc: dict, max_regress_pct: float
) -> Tuple[List[str], List[str]]:
    """Compare per-family totals. Returns (regressions, notes):
    ``regressions`` non-empty means the gate fails."""
    costs = _import_costs()
    old = costs.family_rollup(old_doc)
    new = costs.family_rollup(new_doc)
    regressions: List[str] = []
    notes: List[str] = []
    for fam in sorted(set(old) | set(new)):
        if fam not in old:
            notes.append(f"new family {fam!r} (no baseline)")
            continue
        if fam not in new:
            notes.append(f"family {fam!r} disappeared")
            continue
        for dim in GATED_DIMS:
            o, n = old[fam][dim], new[fam][dim]
            if o <= 0:
                if n > 0:
                    notes.append(f"{fam}.{dim}: baseline 0, now {n:.4g}")
                continue
            growth = (n - o) / o * 100.0
            if growth > max_regress_pct:
                regressions.append(
                    f"{fam}.{dim}: {o:.4g} -> {n:.4g} "
                    f"(+{growth:.1f}% > {max_regress_pct:g}%)"
                )
        o_w, n_w = old[fam]["wall_seconds"], new[fam]["wall_seconds"]
        if o_w > 0 and n_w > o_w * 2:
            notes.append(
                f"{fam}.wall_seconds: {o_w:.3f} -> {n_w:.3f} (not gated)"
            )
    return regressions, notes


def _import_autotune():
    """Checkout-safe import of the autotuner (same seam as _import_costs)."""
    try:
        from spark_rapids_ml_tpu.observability import autotune
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        from spark_rapids_ml_tpu.observability import autotune
    return autotune


def load_tune_store(path: str) -> Tuple[List[dict], List[str]]:
    """Decode a tune store JSON into its decision list. Returns
    (decisions, problems)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        return [], [f"unreadable tune store {path}: {exc}"]
    decisions = doc.get("decisions")
    if not isinstance(decisions, dict):
        return [], [f"{path}: 'decisions' missing or not an object"]
    return list(decisions.values()), []


def _fmt_metric(value, name) -> str:
    if value is None:
        return "n/a"
    return f"{value:.4g} {name or ''}".rstrip()


def render_tune(decisions: List[dict]) -> str:
    """Per-knob incumbent vs candidates with measured deltas."""
    if not decisions:
        return "tune store is empty (no accepted decisions yet)"
    lines = [f"{len(decisions)} accepted decision(s):"]
    for dec in sorted(
        decisions, key=lambda d: (str(d.get("knob")), str(d.get("key")))
    ):
        lines.append(
            f"  {dec.get('knob')}[{dec.get('key')}] = {dec.get('value')!r}"
            f"  ({_fmt_metric(dec.get('metric'), dec.get('metric_name'))}, "
            f"{dec.get('trials', 0)} trial(s))"
        )
        inc_metric = dec.get("metric")
        for cand in dec.get("rejected") or []:
            delta = ""
            c_metric = cand.get("metric")
            if inc_metric and c_metric is not None:
                delta = f" ({(c_metric - inc_metric) / inc_metric * 100.0:+.1f}% vs incumbent)"
            lines.append(
                f"    rejected {cand.get('value')!r}: "
                f"{_fmt_metric(c_metric, dec.get('metric_name'))}"
                f"{delta} [{cand.get('reason', '?')}]"
            )
        ev = dec.get("evidence") or []
        if ev:
            lines.append(f"    evidence: {', '.join(str(e) for e in ev[:6])}"
                         + (f" … +{len(ev) - 6} more" if len(ev) > 6 else ""))
    return "\n".join(lines)


def render_explain(
    family: str, decisions: List[dict], ledger_doc: Optional[dict]
) -> str:
    """Fitted cost-model coefficients for ``family`` plus the evidence
    ledger entries behind each committed decision touching it."""
    autotune = _import_autotune()
    lines = [f"family {family!r}:"]
    if ledger_doc is not None:
        entries = [
            _EntryView(e) for e in ledger_doc.get("entries", [])
            if family in (e.get("family") or "")
            or (e.get("family") or "").startswith(family)
        ]
        models = autotune.fit_cost_models(entries)
        if not models:
            lines.append("  no fittable ledger entries (need rows + invocations)")
        for fam, m in sorted(models.items()):
            lines.append(f"  model {fam} ({m.points} point(s)):")
            if m.wall_a is not None:
                lines.append(
                    f"    wall(rows)  = {m.wall_a:.4g}·rows + {m.wall_b:.4g} s"
                    " (compile-amortized)"
                )
            if m.bytes_a is not None:
                lines.append(
                    f"    bytes(rows) = {m.bytes_a:.4g}·rows + {m.bytes_b:.4g}"
                )
            for key in m.evidence[:8]:
                lines.append(f"    evidence: {key}")
    else:
        lines.append("  (no --ledger given: coefficients unavailable)")
    hits = [
        d for d in decisions
        if family in str(d.get("key", "")) or str(d.get("key", "")) in family
    ]
    if hits:
        lines.append("  committed decisions:")
        lines.extend("  " + ln for ln in render_tune(hits).splitlines()[1:])
    else:
        lines.append("  no committed decisions touch this family")
    return "\n".join(lines)


class _EntryView:
    """Attribute view over a serialized ledger entry dict, so
    ``fit_cost_models`` (written against live ProgramCost objects) fits
    dumped documents too."""

    def __init__(self, d: dict):
        self._d = d

    def __getattr__(self, name):
        return self._d.get(name)


def tune_main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="tpuml_prof.py tune",
        description="Render the autotuner's accepted-decision store.",
    )
    parser.add_argument("store", help="TPUML_TUNE_STORE JSON path")
    parser.add_argument(
        "--explain", metavar="FAMILY", default=None,
        help="print fitted cost-model coefficients + evidence for FAMILY",
    )
    parser.add_argument(
        "--ledger", default=None,
        help="ledger file/telemetry dir to fit --explain models from",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    args = parser.parse_args(argv)

    decisions, problems = load_tune_store(args.store)
    for p in problems:
        print(f"INVALID {p}", file=sys.stderr)
    if problems:
        return 2
    if args.format == "json":
        print(json.dumps(decisions, indent=2, default=str))
        return 0
    if args.explain is not None:
        ledger_doc = None
        if args.ledger is not None:
            ledger_doc, lp = load_ledger(args.ledger)
            for p in lp:
                print(f"INVALID {p}", file=sys.stderr)
        print(render_explain(args.explain, decisions, ledger_doc))
        return 0
    print(render_tune(decisions))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # Subcommand-style dispatch for the tune store, keeping every legacy
    # flag invocation (a path is never literally "tune") untouched.
    if argv and argv[0] == "tune":
        return tune_main(argv[1:])
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "path", nargs="?", default=None,
        help="ledger JSON file or telemetry dir of costs-<pid>.json shards",
    )
    parser.add_argument("--sort", choices=sorted(_SORT_FIELDS), default="wall")
    parser.add_argument("--top", type=int, default=20)
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--validate", action="store_true",
        help="exit 1 when the document fails schema validation",
    )
    parser.add_argument(
        "--diff", nargs=2, metavar=("OLD", "NEW"), default=None,
        help="compare two ledgers' per-family totals",
    )
    parser.add_argument(
        "--max-regress", type=float, default=10.0,
        help="allowed per-family growth in gated dims, percent (with --diff)",
    )
    args = parser.parse_args(argv)

    if args.diff is not None:
        old_doc, old_problems = load_ledger(args.diff[0])
        new_doc, new_problems = load_ledger(args.diff[1])
        for p in old_problems + new_problems:
            print(f"INVALID {p}", file=sys.stderr)
        if old_problems or new_problems:
            return 2
        regressions, notes = diff_ledgers(old_doc, new_doc, args.max_regress)
        for n in notes:
            print(f"note: {n}")
        for r in regressions:
            print(f"REGRESSION {r}", file=sys.stderr)
        if not regressions:
            print(f"ok: no family regressed more than {args.max_regress:g}%")
        return 1 if regressions else 0

    if args.path is None:
        parser.error("a ledger path is required unless --diff is given")
    doc, problems = load_ledger(args.path)
    for p in problems:
        print(f"INVALID {p}", file=sys.stderr)
    if args.validate and problems:
        return 1
    if args.format == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(render(doc, sort=args.sort, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
