"""UMAP tests — oracle is structural (trustworthiness + cluster geometry).

Beyond-the-reference capability (reference ships only PCA — SURVEY.md §2).
UMAP has no exact numeric oracle (stochastic optimization), so the suite
checks the properties every correct implementation must deliver: local
structure preservation (sklearn's trustworthiness), cluster separation on
well-separated blobs, determinism for a fixed seed, and persistence.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.manifold import UMAP, UMAPModel
from spark_rapids_ml_tpu.ops.umap import find_ab_params, fuzzy_simplicial_set, smooth_knn_dist


def _three_blobs(rng, n_per=60, d=10, sep=12.0):
    centers = np.zeros((3, d))
    centers[0, 0] = sep
    centers[1, 1] = sep
    centers[2, 2] = sep
    x = np.concatenate(
        [rng.normal(size=(n_per, d)) + c for c in centers]
    )
    labels = np.repeat(np.arange(3), n_per)
    return x, labels


def _separation_ratio(emb, labels):
    """min inter-centroid distance / mean intra-cluster spread."""
    cents = np.stack([emb[labels == c].mean(axis=0) for c in np.unique(labels)])
    inter = np.inf
    for i in range(len(cents)):
        for j in range(i + 1, len(cents)):
            inter = min(inter, np.linalg.norm(cents[i] - cents[j]))
    intra = np.mean(
        [
            np.linalg.norm(emb[labels == c] - cents[ci], axis=1).mean()
            for ci, c in enumerate(np.unique(labels))
        ]
    )
    return inter / max(intra, 1e-12)


class TestOps:
    def test_smooth_knn_solves_target(self, rng):
        import jax.numpy as jnp

        d = jnp.asarray(np.abs(rng.normal(size=(50, 10))) + 0.1, dtype=jnp.float32)
        sigmas, rhos = smooth_knn_dist(d, 10.0)
        # The defining equation: sum exp(-(d - rho)/sigma) == log2(k).
        lhs = np.sum(
            np.exp(-np.maximum(np.asarray(d) - np.asarray(rhos)[:, None], 0)
                   / np.asarray(sigmas)[:, None]),
            axis=1,
        )
        np.testing.assert_allclose(lhs, np.log2(10.0), rtol=1e-3)
        assert np.all(np.asarray(rhos) > 0)

    def test_fuzzy_graph_symmetric_weights(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.umap import _knn_excluding_self

        x = jnp.asarray(rng.normal(size=(40, 5)), dtype=jnp.float32)
        dists, idx = _knn_excluding_self(x, 8, "euclidean")
        g = fuzzy_simplicial_set(idx, dists)
        w = np.asarray(g.weight)
        assert w.shape == (40, 8)
        assert np.all(w >= 0) and np.all(w <= 1.0 + 1e-6)
        # Reconstruct the dense symmetrized matrix: must be symmetric.
        dense = np.zeros((40, 40))
        src = np.repeat(np.arange(40), 8)
        dense[src, np.asarray(g.indices).ravel()] += w.ravel()
        dense = dense + dense.T
        np.testing.assert_allclose(dense, dense.T, atol=1e-6)

    def test_smooth_knn_large_scale(self, rng):
        import jax.numpy as jnp

        # Distances at O(1e5): the sigma bracket must expand past any fixed
        # cap or memberships collapse to zero.
        d = jnp.asarray(
            (np.abs(rng.normal(size=(20, 8))) + 1.0) * 1e5, dtype=jnp.float32
        )
        sigmas, rhos = smooth_knn_dist(d, 8.0)
        lhs = np.sum(
            np.exp(-np.maximum(np.asarray(d) - np.asarray(rhos)[:, None], 0)
                   / np.asarray(sigmas)[:, None]),
            axis=1,
        )
        np.testing.assert_allclose(lhs, np.log2(8.0), rtol=1e-3)

    def test_find_ab_params(self):
        a, b = find_ab_params(1.0, 0.1)
        # Known umap-learn values for the default (spread=1, min_dist=0.1).
        assert abs(a - 1.577) < 0.05
        assert abs(b - 0.895) < 0.05

    def test_knn_excluding_self(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.umap import _knn_excluding_self

        x = jnp.asarray(rng.normal(size=(30, 4)), dtype=jnp.float32)
        dists, idx = _knn_excluding_self(x, 5, "euclidean")
        rows = np.arange(30)[:, None]
        assert not np.any(np.asarray(idx) == rows)
        assert np.all(np.asarray(dists) > 0)


class TestBuildAlgo:
    def test_brute_approx_build_matches_exact_on_cpu(self, rng):
        # approx_min_k is exact on the CPU backend, so the approximate
        # graph build must give the identical embedding here; on TPU it
        # trades ~0.5% neighbor recall for the hardware top-k.
        x = rng.normal(size=(120, 6)).astype(np.float32)
        e1 = np.asarray(UMAP().setNEpochs(20).setSeed(1).fit(x).transform(x))
        e2 = np.asarray(
            UMAP().setNEpochs(20).setSeed(1).setBuildAlgo("brute_approx")
            .fit(x).transform(x)
        )
        np.testing.assert_allclose(e1, e2, atol=1e-5)

    def test_invalid_build_algo_rejected(self):
        with pytest.raises(ValueError, match="buildAlgo"):
            UMAP().setBuildAlgo("nn_descent")


class TestUMAP:
    def test_blobs_separate(self, rng):
        x, labels = _three_blobs(rng)
        model = UMAP().setNNeighbors(10).setNEpochs(150).setSeed(0).fit(x)
        emb = model.embedding
        assert emb.shape == (180, 2)
        assert np.all(np.isfinite(emb))
        assert _separation_ratio(emb, labels) > 2.0

    def test_trustworthiness(self, rng):
        manifold = pytest.importorskip("sklearn.manifold")
        x, _ = _three_blobs(rng, n_per=50)
        model = UMAP().setNNeighbors(10).setNEpochs(150).setSeed(1).fit(x)
        t = manifold.trustworthiness(x, model.embedding, n_neighbors=10)
        assert t > 0.85

    def test_determinism(self, rng):
        x, _ = _three_blobs(rng, n_per=30)
        e1 = UMAP().setNEpochs(50).setSeed(7).fit(x).embedding
        e2 = UMAP().setNEpochs(50).setSeed(7).fit(x).embedding
        np.testing.assert_allclose(e1, e2, atol=1e-6)

    def test_random_init_and_cosine(self, rng):
        x, labels = _three_blobs(rng, n_per=40)
        model = (
            UMAP()
            .setInit("random")
            .setMetric("cosine")
            .setNNeighbors(8)
            .setNEpochs(150)
            .setSeed(3)
            .fit(x)
        )
        assert _separation_ratio(model.embedding, labels) > 1.5

    def test_transform_new_points(self, rng):
        x, labels = _three_blobs(rng, n_per=50)
        model = UMAP().setNNeighbors(10).setNEpochs(150).setSeed(2).fit(x)
        # New points from blob 0 must land nearest blob 0's centroid.
        x_new = rng.normal(size=(20, x.shape[1]))
        x_new[:, 0] += 12.0
        emb_new = model.transform(x_new)
        assert emb_new.shape == (20, 2)
        cents = np.stack(
            [model.embedding[labels == c].mean(axis=0) for c in range(3)]
        )
        d = np.linalg.norm(emb_new[:, None, :] - cents[None, :, :], axis=2)
        assert np.mean(np.argmin(d, axis=1) == 0) >= 0.9

    def test_persistence_roundtrip(self, tmp_path, rng):
        x, _ = _three_blobs(rng, n_per=20)
        model = UMAP().setNEpochs(30).setSeed(4).fit(x)
        path = str(tmp_path / "umap")
        model.save(path)
        loaded = UMAPModel.load(path)
        np.testing.assert_allclose(model.embedding, loaded.embedding, atol=1e-12)
        np.testing.assert_allclose(
            model.transform(x[:5]), loaded.transform(x[:5]), atol=1e-6
        )

    def test_dataframe_shim(self, rng):
        from spark_rapids_ml_tpu.core.data import DataFrame

        x, _ = _three_blobs(rng, n_per=15)
        df = DataFrame({"features": list(x)})
        model = UMAP().setNEpochs(20).setSeed(5).fit(df)
        out = model.transform(df)
        assert "embedding" in out.columns
        assert len(out.select("embedding")) == len(x)

    def test_param_validation(self):
        with pytest.raises(ValueError):
            UMAP().setNNeighbors(1)
        with pytest.raises(ValueError):
            UMAP().setMetric("mahalanobis")
        with pytest.raises(ValueError):
            UMAP().setInit("pca")
        with pytest.raises(ValueError):
            UMAP().fit(np.zeros((2, 3)))

    def test_defaults(self):
        u = UMAP()
        assert u.getNNeighbors() == 15
        assert u.getNComponents() == 2
        assert u.getMinDist() == 0.1
        assert u.getInit() == "spectral"
        assert u._auto_epochs(5_000) == 500
        assert u._auto_epochs(50_000) == 200


class TestPooledNegatives:
    """The r5 epoch-shared negative pool (dense GEMM repulsion) must be an
    equivalent estimator to per-edge sampling: same embedding QUALITY, not
    the same stochastic trajectory (different RNG usage by design)."""

    def test_pooled_quality_matches_per_edge(self, rng):
        manifold = pytest.importorskip("sklearn.manifold")
        x, labels = _three_blobs(rng, n_per=50)
        pooled = UMAP().setNNeighbors(10).setNEpochs(150).setSeed(1).fit(x)
        per_edge = (
            UMAP().setNNeighbors(10).setNEpochs(150).setSeed(1)
            .setNegativePoolSize(0).fit(x)
        )
        t_pool = manifold.trustworthiness(x, pooled.embedding, n_neighbors=10)
        t_edge = manifold.trustworthiness(x, per_edge.embedding, n_neighbors=10)
        # Neighborhood preservation parity: pooled within 0.03 of per-edge
        # (both must clear the absolute bar the suite holds UMAP to).
        assert t_pool > 0.85, t_pool
        assert t_pool > t_edge - 0.03, (t_pool, t_edge)
        assert _separation_ratio(pooled.embedding, labels) > 2.0

    def test_pool_smaller_and_larger_than_n(self, rng):
        # Pool size is independent of n: oversampling (s > n) and heavy
        # subsampling both stay finite and separate the blobs.
        x, labels = _three_blobs(rng, n_per=30)  # n = 90
        for s in (32, 512):
            emb = (
                UMAP().setNNeighbors(8).setNEpochs(120).setSeed(2)
                .setNegativePoolSize(s).fit(x).embedding
            )
            assert np.all(np.isfinite(emb))
            assert _separation_ratio(emb, labels) > 1.5, s

    def test_per_edge_path_deterministic(self, rng):
        x, _ = _three_blobs(rng, n_per=20)
        kw = dict()
        e1 = (
            UMAP().setNEpochs(40).setSeed(9).setNegativePoolSize(0)
            .fit(x).embedding
        )
        e2 = (
            UMAP().setNEpochs(40).setSeed(9).setNegativePoolSize(0)
            .fit(x).embedding
        )
        np.testing.assert_allclose(e1, e2, atol=1e-6)

    def test_pool_param_validation(self):
        with pytest.raises(ValueError, match="negativePoolSize"):
            UMAP().setNegativePoolSize(-1)

    def test_transform_uses_pool(self, rng):
        # Transform-mode pooled repulsion draws from the FROZEN training
        # layout; new points must still land near their blob.
        x, labels = _three_blobs(rng, n_per=40)
        model = UMAP().setNNeighbors(10).setNEpochs(120).setSeed(3).fit(x)
        x_new = rng.normal(size=(15, x.shape[1]))
        x_new[:, 1] += 12.0  # blob 1
        emb_new = model.transform(x_new)
        cents = np.stack(
            [model.embedding[labels == c].mean(axis=0) for c in range(3)]
        )
        d = np.linalg.norm(emb_new[:, None, :] - cents[None, :, :], axis=2)
        assert np.mean(np.argmin(d, axis=1) == 1) >= 0.9


class TestResume:
    def test_init_embedding_resumes_optimization(self, rng):
        """An interrupted fit's embedding seeds a continuation that reaches
        the same separation quality as one long fit."""
        from spark_rapids_ml_tpu.manifold import UMAP

        x = np.concatenate(
            [rng.normal(size=(40, 6)) + off for off in (0.0, 12.0)]
        )
        def separation(emb):
            labels = np.repeat([0, 1], 40)
            c0, c1 = emb[labels == 0].mean(0), emb[labels == 1].mean(0)
            spread = np.mean(np.linalg.norm(emb[labels == 0] - c0, axis=1)) + 1e-9
            return np.linalg.norm(c0 - c1) / spread

        short = UMAP().setNNeighbors(8).setNEpochs(10).setSeed(0).fit(x)
        resumed = (
            UMAP()
            .setNNeighbors(8)
            .setNEpochs(150)
            .setSeed(0)
            .setInitEmbedding(short.embedding)
            .fit(x)
        )
        # Continuation genuinely improves on the interrupted layout and
        # reaches a well-separated embedding.
        assert separation(resumed.embedding) > max(2.0, separation(short.embedding))

    def test_shape_validation(self, rng):
        from spark_rapids_ml_tpu.manifold import UMAP

        x = rng.normal(size=(30, 5))
        with pytest.raises(ValueError, match="shape"):
            UMAP().setNNeighbors(5).setInitEmbedding(np.zeros((10, 2))).fit(x)


class TestTailScatterPallas:
    """Bucketed tail scatter-add kernel (VERDICT r5 #1): the per-epoch
    XLA scatter replaced by a static tail-sort + dense per-tile
    accumulation. Interpret mode on CPU; the TPU walls live in
    BASELINE.md's "UMAP tail scatter" entry."""

    @pytest.mark.parametrize(
        "n,k,dim",
        [(600, 8, 2), (257, 5, 3), (1024, 15, 2), (130, 3, 10)],
    )
    def test_tail_accumulate_matches_scatter(self, rng, n, k, dim):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.pallas.umap import (
            build_tail_plan,
            plan_feasible,
            tail_accumulate,
        )

        assert plan_feasible(n, k, dim)
        indices = rng.integers(0, n, size=(n, k))
        g = rng.normal(size=(n * k, dim)).astype(np.float32)
        plan, cfg = build_tail_plan(indices, n, dim)
        out = np.asarray(
            tail_accumulate(jnp.asarray(g), plan, cfg, interpret=True)
        )
        expected = np.zeros((n, dim), dtype=np.float64)
        np.add.at(expected, indices.reshape(-1), g.astype(np.float64))
        # In-tile accumulation order differs from the scatter order:
        # float tolerance, not bitwise (PARITY.md TPUML_UMAP_SCATTER).
        np.testing.assert_allclose(out, expected, atol=1e-4, rtol=1e-5)

    def test_plan_infeasible_wide_embedding(self):
        from spark_rapids_ml_tpu.ops.pallas.umap import plan_feasible

        assert not plan_feasible(1000, 15, 129)  # dim > one sublane tile
        assert not plan_feasible(0, 15, 2)  # empty edge stream

    def test_backend_one_epoch_matches_xla(self, rng, monkeypatch):
        """One SGD epoch: before chaotic divergence compounds, the two
        scatter implementations must agree tightly (measured 4.8e-7 at
        one epoch; 20 epochs diverge to O(1) — hence the structural
        oracle below, not a numeric one)."""
        x, _ = _three_blobs(rng, n_per=50)

        def fit(mode):
            monkeypatch.setenv("TPUML_UMAP_SCATTER", mode)
            return (
                UMAP().setNNeighbors(8).setNEpochs(1).setSeed(5).fit(x).embedding
            )

        np.testing.assert_allclose(fit("pallas"), fit("xla"), atol=1e-5)

    @pytest.mark.slow
    def test_backend_trustworthiness_at_scale(self, rng, monkeypatch):
        """Multi-epoch runs diverge numerically (per-epoch epsilon is
        amplified by the SGD's chaotic dynamics), so at scale the oracle
        is structural: both backends must embed equally trustworthily."""
        manifold = pytest.importorskip("sklearn.manifold")
        x = rng.normal(size=(50_000, 64)).astype(np.float32)
        x[:25_000, 0] += 8.0  # two far sheets: real structure to preserve

        def fit(mode):
            monkeypatch.setenv("TPUML_UMAP_SCATTER", mode)
            est = (
                UMAP()
                .setNNeighbors(10)
                .setNEpochs(10)
                .setBuildAlgo("brute_approx")
                .setInit("random")
                .setSeed(5)
            )
            return est.fit(x).embedding

        # Trustworthiness on a fixed subsample (the full 50k pairwise
        # matrix would need ~10 GB); same rows for both backends.
        sub = rng.choice(50_000, size=2_000, replace=False)
        t_pallas = manifold.trustworthiness(
            x[sub], fit("pallas")[sub], n_neighbors=10
        )
        t_xla = manifold.trustworthiness(x[sub], fit("xla")[sub], n_neighbors=10)
        assert abs(t_pallas - t_xla) < 0.05
