"""Precision-routing tests: the ``precision`` param on PCA/LinearRegression
and the dd (double-float fp64-emulation) fit paths.

The accuracy bar is the reference's all-``double[]`` JNI numerics
(JniRAPIDSML.java:64-69) checked at the PCASuite 1e-5 absolute tolerance
(PCASuite.scala:71), on ILL-CONDITIONED input (column means >> stddevs) where
a plain fp32 pipeline visibly fails: casting x to f32 before centering rounds
away the signal that dd centering (host fp64) + double-float GEMM keep.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix
from spark_rapids_ml_tpu.ops.doubledouble import (
    covariance_dd_blocks,
    normal_eq_stats_dd,
)
from spark_rapids_ml_tpu.ops.linalg import resolve_precision
from spark_rapids_ml_tpu.regression import LinearRegression


def _ill_conditioned(rng, n=20_000, d=8, mean_scale=1e4):
    """Columns with huge means and O(1) signal — fp32's nemesis."""
    stds = np.linspace(1.0, 2.0, d)
    means = mean_scale * (1.0 + np.arange(d, dtype=np.float64))
    return means + stds * rng.normal(size=(n, d))


class TestResolvePrecision:
    def test_auto_routes_dd_only_for_f64_without_x64(self):
        # dd auto-routing targets ACCELERATORS (no native fp64); on CPU
        # the fix for fp64 semantics is enabling x64, not emulation.
        kw = dict(x64_enabled=False, platform="tpu")
        assert resolve_precision("auto", np.float64, **kw) == "dd"
        assert resolve_precision("auto", np.float64, x64_enabled=True, platform="tpu") == "highest"
        assert resolve_precision("auto", np.float32, **kw) == "highest"
        assert resolve_precision("auto", None, **kw) == "highest"
        assert (
            resolve_precision("auto", np.float64, x64_enabled=False, platform="cpu")
            == "highest"
        )

    def test_explicit_passthrough(self):
        for p in ("default", "high", "highest", "dd"):
            assert resolve_precision(p, np.float32, x64_enabled=False) == p

    def test_invalid_rejected(self):
        with pytest.raises(ValueError, match="precision"):
            resolve_precision("fp64", np.float64)

    def test_infer_input_dtype_sees_raw_container(self, rng):
        """The auto gate must observe the dtype BEFORE densification
        coerces to float64 (r2 review: the gate was dead code otherwise)."""
        from spark_rapids_ml_tpu.core.data import Vectors, infer_input_dtype

        x32 = rng.normal(size=(4, 3)).astype(np.float32)
        assert infer_input_dtype(x32) == np.float32
        assert infer_input_dtype(x32.astype(np.float64)) == np.float64
        assert infer_input_dtype(list(x32)) == np.float32  # list of f32 rows
        assert infer_input_dtype([x32, x32]) == np.float32  # list of blocks
        assert infer_input_dtype(Vectors.dense(1.0, 2.0)) == np.float64
        assert infer_input_dtype([0.5, 1.5]) == np.float64  # python floats
        assert infer_input_dtype(iter([x32])) is None  # opaque iterator
        # Integer/bool data is never "genuinely double" — must not route dd.
        assert infer_input_dtype(np.ones((3, 2), dtype=np.int32)) is None
        assert infer_input_dtype(np.ones((3, 2), dtype=bool)) is None

    def test_pandas_extension_dtypes_do_not_crash(self, rng):
        """pandas extension dtypes (Float64Dtype etc.) are not numpy dtypes
        — the probe must classify, not crash (r2 review)."""
        pd = pytest.importorskip("pandas")
        from spark_rapids_ml_tpu.core.data import infer_input_dtype

        df = pd.DataFrame(
            {"a": pd.array([1.0, 2.0], dtype="Float64"), "label": [1.0, 2.0]}
        )
        assert infer_input_dtype(df) == np.float64
        assert infer_input_dtype(df["a"]) == np.float64
        f32 = pd.DataFrame({"a": np.ones(3, dtype=np.float32)})
        assert infer_input_dtype(f32) == np.float32
        # End to end: a fit on an extension-dtype frame must not crash.
        big = pd.DataFrame(
            {
                "label": rng.normal(size=50),
                "f0": pd.array(rng.normal(size=50), dtype="Float64"),
                "f1": rng.normal(size=50),
            }
        )
        model = LinearRegression().setLabelCol("label").fit(big)
        assert np.all(np.isfinite(model.coefficients))

    def test_rowmatrix_auto_with_mesh_defers_to_mesh_path(self, rng):
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        x = rng.normal(size=(64, 4))
        rm = RowMatrix([x], mesh=make_mesh(), precision="auto")
        assert rm.precision == "highest"  # no raise, no dd

    def test_rowmatrix_auto_without_hint_stays_highest(self, rng):
        # partitions are float64 post-coercion; without a raw-input dtype
        # hint, auto must NOT take that as evidence for dd routing.
        x = rng.normal(size=(16, 3)).astype(np.float32)
        assert RowMatrix([x], precision="auto").precision == "highest"


class TestCovarianceDD:
    def test_blocks_match_fp64_oracle_where_fp32_fails(self, rng):
        x = _ill_conditioned(rng)
        oracle = np.cov(x, rowvar=False)
        blocks = [x[:7000], x[7000:15000], x[15000:]]

        _, cov_dd, n = covariance_dd_blocks(blocks)
        assert n == x.shape[0]
        err_dd = np.max(np.abs(cov_dd - oracle))
        assert err_dd < 1e-5  # the PCASuite absTol bar

        # The same computation at fp32 (cast-then-center, what a no-x64
        # device pipeline does) misses the bar — dd is necessary, not
        # decorative.
        cov_f32 = np.asarray(
            RowMatrix(blocks, dtype=jnp.float32).compute_covariance()
        )
        err_f32 = np.max(np.abs(cov_f32 - oracle))
        assert err_f32 > 10 * err_dd

    def test_no_centering(self, rng):
        x = rng.normal(size=(500, 4)) + 50.0
        _, second_moment, _ = covariance_dd_blocks([x], center=False)
        oracle = x.T @ x / (x.shape[0] - 1)
        # dd's floor is a few f32 eps RELATIVE (the intra-chunk matmul
        # rounding) — on O(2500) second moments that is ~1e-3 absolute.
        np.testing.assert_allclose(second_moment, oracle, rtol=1e-6)

    def test_too_few_rows(self):
        with pytest.raises(ValueError, match="at least 2 rows"):
            covariance_dd_blocks([np.ones((1, 3))])

    def test_generator_input_single_pass(self, rng):
        """Blocks may come from a one-shot generator (NpyBlockReader
        style) — the covariance is a single streaming pass."""
        x = _ill_conditioned(rng, n=6_000, d=5)
        oracle = np.cov(x, rowvar=False)
        gen = (x[i : i + 1024] for i in range(0, 6_000, 1024))
        mean, cov, n = covariance_dd_blocks(gen)
        assert n == 6_000
        np.testing.assert_allclose(mean, x.mean(axis=0), rtol=1e-12)
        assert np.max(np.abs(cov - oracle)) < 1e-5


class TestPCAPrecisionDD:
    def test_ill_conditioned_fit_matches_fp64_oracle(self, rng):
        x = _ill_conditioned(rng, n=10_000)
        model = PCA().setK(3).setPrecision("dd").fit(x)

        cov = np.cov(x, rowvar=False)
        w, v = np.linalg.eigh(cov)
        w, v = w[::-1], v[:, ::-1]
        for j in range(3):
            ref = v[:, j] * np.sign(v[np.argmax(np.abs(v[:, j])), j])
            np.testing.assert_allclose(model.pc[:, j], ref, atol=1e-5)
        np.testing.assert_allclose(
            model.explainedVariance[:3], (w / w.sum())[:3], atol=1e-5
        )

    def test_dd_rejects_randomized_solver(self):
        with pytest.raises(ValueError, match="dd"):
            PCA().setK(2).setPrecision("dd").setSolver("randomized")\
                .fit(np.ones((10, 4)))

    def test_dd_rejects_mesh(self, rng):
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        x = rng.normal(size=(64, 4))
        # Single-process mesh fits have no dd route (dd + mesh is the
        # multi-process streaming deployment only).
        with pytest.raises(ValueError, match="multi-process streaming"):
            PCA(mesh=make_mesh()).setK(2).setPrecision("dd").fit(x)

    def test_invalid_precision_rejected(self):
        with pytest.raises(ValueError, match="precision"):
            PCA().setPrecision("quad")

    def test_pandas_frame_without_inputcol_probes_raw_frame(self, rng):
        """extract_column coerces a no-inputCol pandas frame to float64;
        the auto probe must look at the ORIGINAL frame (r2 review)."""
        pd = pytest.importorskip("pandas")
        df32 = pd.DataFrame(rng.normal(size=(64, 4)).astype(np.float32))
        model = PCA().setK(2).fit(df32)  # must not crash, auto-resolve runs
        assert model.pc.shape == (4, 2)


class TestLinearRegressionDD:
    def test_ill_conditioned_fit_matches_lstsq(self, rng):
        n, d = 20_000, 6
        x = _ill_conditioned(rng, n=n, d=d)
        beta = np.linspace(-1.0, 1.0, d)
        y = x @ beta + 3.0 + 0.01 * rng.normal(size=n)

        model = LinearRegression().setPrecision("dd").fit((x, y))

        xi = np.concatenate([x, np.ones((n, 1))], axis=1)
        ref = np.linalg.lstsq(xi, y, rcond=None)[0]
        np.testing.assert_allclose(model.coefficients, ref[:d], atol=1e-5)
        # The intercept absorbs mean_scale * beta errors; at column means of
        # ~1e4 a 1e-5 coefficient bar corresponds to ~1e-1 here.
        np.testing.assert_allclose(model.intercept, ref[d], atol=1e-1)

    def test_streaming_blocks_equal_dense(self, rng):
        x = _ill_conditioned(rng, n=5_000, d=5)
        beta = np.arange(1.0, 6.0)
        y = x @ beta + 0.1 * rng.normal(size=5_000)
        dense = LinearRegression().setPrecision("dd").fit((x, y))
        blocks = [x[:2000], x[2000:3500], x[3500:]]
        streamed = LinearRegression().setPrecision("dd").fit((blocks, y))
        # Different block splits shift by different first-block means, so
        # the two fits agree to the dd error floor, not bit-exactly.
        np.testing.assert_allclose(
            streamed.coefficients, dense.coefficients, atol=1e-5
        )
        assert streamed.intercept == pytest.approx(dense.intercept, abs=1e-1)

    def test_ridge_dd(self, rng):
        """dd covers the exact normal solve including L2."""
        x = _ill_conditioned(rng, n=3_000, d=4, mean_scale=1e3)
        y = x @ np.ones(4) + rng.normal(size=3_000)
        m_dd = LinearRegression().setPrecision("dd").setRegParam(0.1).fit((x, y))
        m_hi = LinearRegression().setPrecision("highest").setRegParam(0.1).fit((x, y))
        # x64 is on in tests, so "highest" computes in true fp64 — the dd
        # emulation must land on the same ridge solution.
        np.testing.assert_allclose(m_dd.coefficients, m_hi.coefficients, atol=1e-5)

    def test_explicit_dd_rejects_unsupported(self, rng):
        x = rng.normal(size=(50, 3))
        y = x.sum(axis=1)
        from spark_rapids_ml_tpu.core.data import DataFrame

        df = DataFrame(
            {"features": list(x), "label": list(y), "w": [1.0] * 50}
        )
        with pytest.raises(ValueError, match="weightCol"):
            LinearRegression().setPrecision("dd").setWeightCol("w").fit(df)
        with pytest.raises(ValueError, match="FISTA|elastic"):
            LinearRegression().setPrecision("dd").setRegParam(0.1)\
                .setElasticNetParam(0.5).fit((x, y))

        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        with pytest.raises(ValueError, match="mesh"):
            LinearRegression(mesh=make_mesh()).setPrecision("dd").fit((x, y))

    def test_invalid_precision_rejected(self):
        with pytest.raises(ValueError, match="precision"):
            LinearRegression().setPrecision("exact")

    def test_non_dd_precision_reaches_the_gemm(self, rng, monkeypatch):
        """setPrecision('default'/'high') must thread into the stats GEMMs,
        not be validated-then-ignored (r2 review)."""
        import spark_rapids_ml_tpu.models.linear_regression as lr_mod

        seen = {}
        real = lr_mod.normal_eq_stats

        def spy(x, y, mask, precision="highest"):
            seen["precision"] = precision
            return real(x, y, mask, precision=precision)

        monkeypatch.setattr(lr_mod, "normal_eq_stats", spy)
        x = rng.normal(size=(100, 3))
        y = x.sum(axis=1)
        LinearRegression().setPrecision("default").fit((x, y))
        assert seen["precision"] == "default"
        LinearRegression().setPrecision("high").fit((x, y))
        assert seen["precision"] == "high"


class TestNormalEqStatsDD:
    def test_moments_match_fp64(self, rng):
        x = _ill_conditioned(rng, n=4_000, d=5)
        y = rng.normal(size=4_000) + 100.0
        xtx, xty, x_sum, y_sum, yty, count = normal_eq_stats_dd(
            [(x[:1500], y[:1500]), (x[1500:], y[1500:])]
        )
        assert count == 4_000
        np.testing.assert_allclose(xtx, x.T @ x, rtol=1e-7)
        np.testing.assert_allclose(xty, x.T @ y, rtol=1e-7)
        np.testing.assert_allclose(x_sum, x.sum(axis=0), rtol=1e-12)
        assert y_sum == pytest.approx(y.sum(), rel=1e-12)
        assert yty == pytest.approx(np.dot(y, y), rel=1e-12)

    def test_row_mismatch_raises(self, rng):
        with pytest.raises(ValueError, match="mismatch"):
            normal_eq_stats_dd([(np.ones((4, 2)), np.ones(3))])

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="no rows"):
            normal_eq_stats_dd([])
