"""Re-iterable streaming fits for the ITERATIVE families (VERDICT r3 #6).

LinearRegression and PCA already stream (single-pass moments / sketch);
these tests pin the new multi-pass streaming paths: KMeans (one data pass
per Lloyd iteration) and LogisticRegression (one data pass per L-BFGS
evaluation), both at O(block + model) memory over the same re-iterable
block contract the streamed PCA sketch uses (iterator factory or
``NpyBlockReader``-style ``.iter_blocks()``).
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.clustering import KMeans

REPO = str(Path(__file__).resolve().parents[1])


def _blob_block(seed, n, d=8, k=4):
    rng = np.random.default_rng(seed)
    centers = np.arange(k)[:, None] * 10.0 + np.zeros((k, d))
    labels = rng.integers(0, k, size=n)
    return (centers[labels] + rng.normal(scale=0.5, size=(n, d))).astype(
        np.float64
    ), labels


class TestKMeansStreaming:
    def test_matches_materialized_fit(self):
        blocks = [_blob_block(s, 500)[0] for s in range(4)]

        def factory():
            return iter(blocks)

        streamed = KMeans().setK(4).setSeed(1).fit(factory)
        dense = KMeans().setK(4).setSeed(1).fit(np.concatenate(blocks))
        c_s = np.sort(streamed.clusterCenters(), axis=0)
        c_d = np.sort(dense.clusterCenters(), axis=0)
        assert np.allclose(c_s, c_d, atol=0.2)
        assert streamed.trainingCost == pytest.approx(
            dense.trainingCost, rel=0.02
        )
        assert streamed.numIter >= 1

    def test_one_shot_generator_rejected(self):
        gen = (b for b in [_blob_block(0, 100)[0]])
        with pytest.raises(ValueError, match="RE-ITERABLE"):
            KMeans().setK(2).fit(gen)

    def test_k_exceeds_rows_raises(self):
        def factory():
            return iter([_blob_block(0, 5)[0]])

        with pytest.raises(ValueError, match="exceeds"):
            KMeans().setK(7).fit(factory)

    def test_cosine_streaming(self):
        blocks = [_blob_block(s, 300)[0] + 5.0 for s in range(2)]

        def factory():
            return iter(blocks)

        streamed = (
            KMeans().setK(3).setSeed(2).setDistanceMeasure("cosine").fit(factory)
        )
        dense = (
            KMeans()
            .setK(3)
            .setSeed(2)
            .setDistanceMeasure("cosine")
            .fit(np.concatenate(blocks))
        )
        assert streamed.trainingCost == pytest.approx(dense.trainingCost, rel=0.05)

    def test_warm_start_streaming(self):
        blocks = [_blob_block(s, 400)[0] for s in range(2)]

        def factory():
            return iter(blocks)

        first = KMeans().setK(4).setSeed(0).fit(factory)
        resumed = KMeans().setK(4).setInitialModel(first).setMaxIter(3).fit(factory)
        assert resumed.trainingCost <= first.trainingCost * 1.01


class TestLogisticStreaming:
    def _pairs(self, n_blocks=4, n=400, classes=2, d=6, seed=0):
        rng = np.random.default_rng(seed)
        w = rng.normal(size=(d, classes))
        xs, ys = [], []
        for _ in range(n_blocks):
            x = rng.normal(size=(n, d))
            y = np.argmax(x @ w + rng.normal(scale=0.2, size=(n, classes)), axis=1)
            xs.append(x)
            ys.append(y.astype(np.float64))
        return xs, np.concatenate(ys)

    @pytest.mark.parametrize("classes", [2, 3])
    def test_matches_materialized_fit(self, classes):
        xs, y = self._pairs(classes=classes)

        def factory():
            return iter(xs)

        streamed = (
            LogisticRegression().setRegParam(0.05).fit((factory, y))
        )
        dense = (
            LogisticRegression()
            .setRegParam(0.05)
            .fit((np.concatenate(xs), y))
        )
        assert streamed.numClasses == dense.numClasses
        assert np.allclose(streamed.weights, dense.weights, atol=5e-3)
        assert np.allclose(streamed.intercepts, dense.intercepts, atol=5e-3)

    def test_one_shot_generator_rejected(self):
        xs, y = self._pairs()
        gen = (b for b in xs)
        with pytest.raises(ValueError, match="RE-ITERABLE"):
            LogisticRegression().fit((gen, y))

    def test_fractional_labels_raise(self):
        xs, y = self._pairs()
        y = y.copy()
        y[0] = 0.5

        def factory():
            return iter(xs)

        with pytest.raises(ValueError, match="integers"):
            LogisticRegression().fit((factory, y))

    def test_streaming_elastic_net_rejected(self):
        xs, y = self._pairs()

        def factory():
            return iter(xs)

        with pytest.raises(ValueError, match="elastic"):
            LogisticRegression().setRegParam(0.1).setElasticNetParam(0.5).fit(
                (factory, y)
            )

    def test_no_intercept_no_standardization(self):
        xs, y = self._pairs()

        def factory():
            return iter(xs)

        streamed = (
            LogisticRegression()
            .setFitIntercept(False)
            .setStandardization(False)
            .setRegParam(0.05)
            .fit((factory, y))
        )
        dense = (
            LogisticRegression()
            .setFitIntercept(False)
            .setStandardization(False)
            .setRegParam(0.05)
            .fit((np.concatenate(xs), y))
        )
        assert np.allclose(streamed.weights, dense.weights, atol=5e-3)
        assert np.all(streamed.intercepts == 0.0)


class TestStreamingBoundedMemory:
    """The r3 wide-features pattern: fit in a subprocess, assert RSS growth
    stays far below the materialized dataset size."""

    def _run(self, script):
        import os

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, env=env
        )
        assert out.returncode == 0, out.stderr.decode()[-3000:]
        growth_kb = int(out.stdout.decode().strip().splitlines()[-1].split()[-1])
        return growth_kb

    @pytest.mark.slow  # ~11 s; runs full-file in CI's Streamed-fit memory bounds step
    def test_kmeans_streaming_bounded_rss(self):
        # 48 x 32768 x 64 f64 = 0.75 GB if materialized; blocks are
        # recomputed on demand so RSS growth must stay a small multiple
        # of one block (16 MB) + compile workspace.
        script = f"""
import resource, sys
sys.path.insert(0, {REPO!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from spark_rapids_ml_tpu.clustering import KMeans

n_blocks, bs, d = 48, 32768, 64
def blocks():
    for i in range(n_blocks):
        rng = np.random.default_rng(200 + i)
        yield rng.normal(size=(bs, d)) + (i % 4) * 8.0

base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
model = KMeans().setK(4).setMaxIter(3).fit(blocks)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
assert model.clusterCenters().shape == (4, d)
print("GROWTH_KB", peak - base)
"""
        growth_kb = self._run(script)
        assert growth_kb < 400_000, (
            f"RSS grew {growth_kb} kB (dataset is 0.75 GB)"
        )

    @pytest.mark.slow  # ~31 s; runs full-file in CI's Streamed-fit memory bounds step
    def test_logreg_streaming_bounded_rss(self):
        # 48 x 32768 x 64 f64 = 0.75 GB if materialized; the L-BFGS path
        # re-streams every block per evaluation, so iteration count is
        # the wall-clock knob — 8 is past convergence on this separable
        # data and keeps the RSS property (growth << dataset) intact.
        script = f"""
import resource, sys
sys.path.insert(0, {REPO!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from spark_rapids_ml_tpu.classification import LogisticRegression

n_blocks, bs, d = 48, 32768, 64
rng_w = np.random.default_rng(0)
w = rng_w.normal(size=(d,))
def blocks():
    for i in range(n_blocks):
        rng = np.random.default_rng(300 + i)
        yield rng.normal(size=(bs, d))
def labels():
    out = []
    for i in range(n_blocks):
        rng = np.random.default_rng(300 + i)
        x = rng.normal(size=(bs, d))
        out.append((x @ w > 0).astype(float))
    return np.concatenate(out)

y = labels()
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
model = LogisticRegression().setRegParam(0.01).setMaxIter(8).fit((blocks, y))
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
assert model.weights.shape == (d, 1)
acc = model.evaluate((np.asarray(next(blocks())), y[:bs]))["accuracy"]
assert acc > 0.9, acc
print("GROWTH_KB", peak - base)
"""
        growth_kb = self._run(script)
        assert growth_kb < 400_000, (
            f"RSS grew {growth_kb} kB (dataset is 0.75 GB)"
        )
