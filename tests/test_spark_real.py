"""The SAME adapter contract suite, against GENUINE pyspark (VERDICT r2
#5b / advisor r2 medium): skipped wherever pyspark is not installed (this
CI image), and the complete proof the day an environment has it.

Smoke procedure for such an environment (documented here AND in
README.md):

    pip install "pyspark>=3.4,<4.0"
    python -m pytest tests/test_spark_real.py -q

Every assertion is shared with ``tests/test_spark_adapter.py`` via
``tests/spark_contract_suite.py`` — a behavior the stub models wrongly
shows up here as a real-cluster failure of the identical test. Tests
that instrument stub internals (the driver-fetch counter) self-skip.
"""

import pytest

pyspark = pytest.importorskip("pyspark")

import spark_contract_suite as _suite  # noqa: E402 - after importorskip

# Pull EVERY Test* class from the shared suite into this module's
# namespace so pytest collects it here — programmatic, so a class added
# to the suite can never be silently dropped by a stale import list.
for _name in dir(_suite):
    if _name.startswith("Test"):
        globals()[_name] = getattr(_suite, _name)

pytestmark = pytest.mark.spark


@pytest.fixture(scope="module")
def spark_env():
    """Genuine local[2] SparkSession + the adapter imported against real
    pyspark. Arrow is enabled for pandas_udf exchange (the production
    configuration; pyspark 3.5 'Apache Arrow in PySpark' guide)."""
    import importlib

    import spark_rapids_ml_tpu.spark.adapter as adapter

    adapter = importlib.reload(adapter)
    assert adapter.HAS_PYSPARK, "pyspark import failed inside the adapter"
    from pyspark.sql import SparkSession

    spark = (
        SparkSession.builder.master("local[2]")
        .appName("spark-rapids-ml-tpu-contract")
        .config("spark.sql.execution.arrow.pyspark.enabled", "true")
        .getOrCreate()
    )
    try:
        yield adapter, spark
    finally:
        spark.stop()
