"""Spark integration layer tests — what is testable without pyspark:
the discovery script, resource resolution, the picklable moments
accumulator (the adapter's executor-side unit of work), and the
adapter's import gate."""

import json
import os
import pickle
import subprocess

import numpy as np
import pytest

import spark_rapids_ml_tpu
from spark_rapids_ml_tpu.core.moments import ShiftedMoments
from spark_rapids_ml_tpu.spark import resolve_device_ordinal, task_tpu_address

_DISCOVERY_SCRIPT = os.path.join(
    os.path.dirname(spark_rapids_ml_tpu.__file__),
    "spark",
    "discovery",
    "get_tpus_resources.sh",
)


class TestDiscoveryScript:
    def test_emits_valid_resource_json(self, tmp_path):
        # Force the TPU_VISIBLE_DEVICES branch for determinism.
        out = subprocess.run(
            ["bash", _DISCOVERY_SCRIPT],
            capture_output=True,
            text=True,
            env={"PATH": "/usr/bin:/bin", "TPU_VISIBLE_DEVICES": "0,1,2,3"},
        )
        assert out.returncode == 0, out.stderr
        payload = json.loads(out.stdout)
        assert payload["name"] == "tpu"
        assert payload["addresses"] == ["0", "1", "2", "3"]

    def test_empty_when_no_tpus(self):
        out = subprocess.run(
            ["/bin/bash", _DISCOVERY_SCRIPT],
            capture_output=True,
            text=True,
            env={"PATH": "/nonexistent"},  # no python3, no /dev/accel*
        )
        assert out.returncode == 0
        assert json.loads(out.stdout) == {"name": "tpu", "addresses": []}


class TestResources:
    def test_explicit_ordinal_wins(self):
        assert resolve_device_ordinal(3) == 3

    def test_defaults_to_zero_outside_spark(self):
        assert task_tpu_address() is None  # no pyspark here
        assert resolve_device_ordinal(-1) == 0


class TestShiftedMoments:
    def test_matches_numpy_cov(self, rng):
        x = rng.normal(size=(500, 9)) * 1e-3 + 1e6  # adversarial offset
        acc = ShiftedMoments(9)
        for blk in np.array_split(x, 7):
            acc.add_block(blk)
        cov, mean = acc.finalize()
        np.testing.assert_allclose(mean, x.mean(0), rtol=1e-12)
        exact = np.cov(x.astype(np.longdouble), rowvar=False).astype(np.float64)
        np.testing.assert_allclose(cov, exact, rtol=1e-6)

    def test_merge_rebases_shifts(self, rng):
        x = rng.normal(size=(300, 5))
        a = ShiftedMoments(5).add_block(x[:100] + 100)  # shift ~100
        b = ShiftedMoments(5).add_block(x[100:] - 100)  # shift ~-100
        a.merge(b)
        whole = ShiftedMoments(5).add_block(np.concatenate([x[:100] + 100, x[100:] - 100]))
        cov_m, mean_m = a.finalize()
        cov_w, mean_w = whole.finalize()
        np.testing.assert_allclose(cov_m, cov_w, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(mean_m, mean_w, rtol=1e-12)

    def test_pickle_roundtrip_mid_stream(self, rng):
        """The treeAggregate contract: accumulators serialize between adds."""
        x = rng.normal(size=(100, 4))
        acc = ShiftedMoments(4).add_block(x[:50])
        acc = pickle.loads(pickle.dumps(acc))
        acc.add_block(x[50:])
        cov, _ = acc.finalize()
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), rtol=1e-10)

    def test_matches_native_accumulator(self, rng):
        from spark_rapids_ml_tpu import native

        if not native.available():
            pytest.skip("native unavailable")
        x = rng.normal(size=(200, 6)) + 50
        py_acc = ShiftedMoments(6).add_block(x)
        nat_acc = native.SprAccumulator(6).add_block(x)
        cov_py, mean_py = py_acc.finalize()
        cov_nat, mean_nat = nat_acc.finalize()
        # BLAS-order vs Kahan-order summation differ at the last few ulps
        np.testing.assert_allclose(cov_py, cov_nat, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(mean_py, mean_nat, rtol=1e-12)

    def test_empty_and_errors(self):
        acc = ShiftedMoments(3)
        acc.add_block(np.zeros((0, 3)))
        with pytest.raises(ValueError):
            acc.finalize()
        with pytest.raises(ValueError):
            acc.add_block(np.zeros((2, 4)))


class TestAdapterGate:
    def test_import_error_without_pyspark(self, monkeypatch):
        """Deterministic regardless of environment/suite order: block the
        pyspark import outright and re-import the adapter, so the gate is
        always exercised (previously skipped whenever some earlier test
        left pyspark importable)."""
        import importlib
        import sys

        monkeypatch.setitem(sys.modules, "pyspark", None)  # import -> error
        sys.modules.pop("spark_rapids_ml_tpu.spark.adapter", None)
        try:
            adapter = importlib.import_module("spark_rapids_ml_tpu.spark.adapter")
            assert not adapter.HAS_PYSPARK
            with pytest.raises(ImportError, match="pyspark"):
                _ = adapter.TpuPCA
        finally:
            sys.modules.pop("spark_rapids_ml_tpu.spark.adapter", None)
