"""Test harness configuration.

The reference's tests run on local[*] Spark with 2 RDD partitions standing in
for "distributed" (SURVEY.md §4). Here the analogue is a virtual 8-device CPU
mesh (xla_force_host_platform_device_count), which exercises the real sharded
code path — psum/all_gather collectives included — without TPU hardware, plus
x64 so the fp64 oracle tolerance (absTol 1e-5, PCASuite.scala:71) is
meaningful.
"""

import os

# Force the CPU platform for tests (the env may pre-select a TPU platform);
# set SPARK_TPU_ML_TEST_PLATFORM to override, e.g. to run the suite on-chip.
_platform = os.environ.get("SPARK_TPU_ML_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# jax may already be imported by interpreter-level site customization that
# captured the original JAX_PLATFORMS env; override via config as well.
jax.config.update("jax_platforms", _platform)
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: DISABLED (r5). XLA:CPU's executable
# (de)serialization is not reliable on this jaxlib: a cache populated by an
# earlier host SIGABRTed inside `compilation_cache.get_executable_and_time`
# ("Loading XLA:CPU AOT result. Target machine feature +prefer-no-scatter is
# not supported on the host machine" escalating from warning to abort), and
# even a FRESH cache segfaulted inside `put_executable_and_time` while
# serializing one of the L-BFGS while_loop executables — both ~96% into the
# suite, both unattributable to library code. Recompiling every run costs
# a few minutes; a mid-suite SIGSEGV costs the whole run. Re-enable only
# after jaxlib's CPU AOT serializer stabilizes, and key the directory by
# the host CPU flags if you do (cross-host replay was the first crash).

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# The 3x5 synthetic dataset from the reference suite (PCASuite.scala:42-46):
# one all-zero sparse row, one sparse row, one dense row.
REFERENCE_DATA = [
    ("sparse_zero", 5, [], []),
    ("sparse", 5, [1, 3], [1.0, 7.0]),
    ("dense", [2.0, 0.0, 3.0, 4.0, 5.0], None, None),
]


@pytest.fixture
def reference_rows():
    from spark_rapids_ml_tpu.core.data import Vectors

    return [
        Vectors.sparse(5, [], []),
        Vectors.sparse(5, [1, 3], [1.0, 7.0]),
        Vectors.dense(2.0, 0.0, 3.0, 4.0, 5.0),
    ]


def numpy_pca_oracle(x: np.ndarray, k: int):
    """CPU ground truth — the Spark mllib RowMatrix oracle analogue
    (PCASuite.scala:50-52): eigendecomposition of the sample covariance.
    """
    x = np.asarray(x, dtype=np.float64)
    n = x.shape[0]
    mean = x.mean(axis=0)
    b = x - mean
    cov = b.T @ b / (n - 1)
    # SVD of the symmetric PSD covariance (LAPACK, like breeze brzSvd in the
    # mllib oracle): singular values are its eigenvalues, descending. Using
    # LAPACK SVD on both sides keeps rank-deficient cases (null-space basis
    # is arbitrary) comparable — same reason the reference suite passes.
    v, w, _ = np.linalg.svd(cov)
    # deterministic sign flip: largest-|.| element of each column positive
    idx = np.argmax(np.abs(v), axis=0)
    signs = np.where(v[idx, np.arange(v.shape[1])] < 0, -1.0, 1.0)
    v = v * signs
    total = np.clip(w, 0, None).sum()
    explained = np.clip(w, 0, None) / total if total > 0 else w
    return v[:, :k], explained[:k]


# File-logging analogue of the reference's log4j.properties (SURVEY.md §2:
# tests append to target/unit-tests.log): jax/absl and framework loggers
# write to target/unit-tests.log so failing CI runs keep a artifact trail.
import logging as _logging
import pathlib as _pathlib

_log_dir = _pathlib.Path(__file__).resolve().parent.parent / "target"
_log_dir.mkdir(exist_ok=True)
_root = _logging.getLogger()
if not any(
    isinstance(h, _logging.FileHandler)
    and getattr(h, "baseFilename", "").endswith("unit-tests.log")
    for h in _root.handlers
):
    _handler = _logging.FileHandler(_log_dir / "unit-tests.log")
    _handler.setFormatter(
        _logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s")
    )
    _root.addHandler(_handler)
    if _root.level in (_logging.NOTSET, _logging.WARNING):
        # INFO so the jax/absl trail actually reaches the file (the
        # default WARNING threshold would filter the records this
        # artifact exists to keep); pytest still captures console output.
        _root.setLevel(_logging.INFO)


# Bound cumulative in-process XLA state: after ~480 tests in ONE process,
# XLA:CPU's compiler segfaulted compiling a routine logistic-fit program
# (reproduced 3x at the same suite position with the persistent cache
# reading, writing, and fully disabled — the crash is in
# backend_compile_and_load itself, not the cache). Split halves of the
# suite never crash, so the trigger is accumulated executables/live
# buffers. Clearing jax's caches between test MODULES frees compiled
# programs (tests are module-local; cross-module recompiles are a few
# seconds) and keeps the resident state far below the crash region.
@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    jax.clear_caches()
