"""PCA suite — mirrors the reference's 7 tests (PCASuite.scala, SURVEY.md §4)
plus the distributed/mesh tests the reference lacks.

Oracle pattern kept: CPU fp64 ground truth, absTol 1e-5, sign-invariant
comparison where the eigensolver's sign convention may differ
(PCASuite.scala:71,106,136-143).
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame, Vectors
from spark_rapids_ml_tpu.feature import PCA, PCAModel

from conftest import numpy_pca_oracle

ABS_TOL = 1e-5


def _fit_df(rows, **params):
    df = DataFrame({"features": rows})
    pca = PCA().setK(params.pop("k", 3)).setInputCol("features").setOutputCol("pca_features")
    for name, value in params.items():
        pca.set(pca.getParam(name), value)
    return pca, pca.fit(df), df


class TestParams:
    """Test 1: params smoke check (PCASuite.scala:33-39)."""

    def test_default_params(self):
        pca = PCA()
        assert pca.getMeanCentering() is True
        assert pca.getUseGemm() is True
        assert pca.getUseCuSolverSVD() is True
        assert pca.getGpuId() == -1
        assert not pca.isSet(pca.k)

    def test_param_surface(self):
        pca = PCA()
        for name in ("k", "inputCol", "outputCol", "meanCentering", "useGemm", "useCuSolverSVD", "gpuId"):
            assert pca.hasParam(name), name
        assert "number of principal components" in pca.explainParam("k")

    def test_setters_chain_and_validate(self):
        pca = PCA().setK(2).setMeanCentering(False).setUseGemm(False).setGpuId(0)
        assert pca.getK() == 2
        assert pca.getMeanCentering() is False
        with pytest.raises((TypeError, ValueError)):
            PCA().setK(0)
        with pytest.raises(TypeError):
            PCA().setMeanCentering("yes")

    def test_copy(self):
        pca = PCA().setK(4)
        clone = pca.copy()
        assert clone.getK() == 4
        assert clone.uid != pca.uid or clone is not pca


class TestPCAPaths:
    """Tests 2-4: spr path, gemm path, accelerated-SVD path vs oracle."""

    @staticmethod
    def _check_vs_oracle(model, x, k):
        """Compare against the CPU oracle. With 3 centered rows the
        covariance has rank 2, so components beyond the rank live in an
        arbitrary null-space basis (any tiny covariance perturbation picks a
        different one — the reference suite only dodges this because its spr
        path and oracle share bit-identical covariance code). Informative
        components must match at absTol 1e-5; null-space components are
        checked structurally: unit norm, orthogonal to the rest, and zero
        variance (B·v = 0 for centered B)."""
        expected_pc, expected_var = numpy_pca_oracle(x, k)
        rank = np.linalg.matrix_rank(np.cov(x, rowvar=False))
        r = min(rank, k)
        np.testing.assert_allclose(model.pc[:, :r], expected_pc[:, :r], atol=ABS_TOL)
        np.testing.assert_allclose(model.explainedVariance, expected_var, atol=ABS_TOL)
        b = x - x.mean(axis=0)
        for j in range(r, k):
            v = model.pc[:, j]
            assert abs(np.linalg.norm(v) - 1.0) < ABS_TOL
            np.testing.assert_allclose(b @ v, 0.0, atol=ABS_TOL)
        np.testing.assert_allclose(model.pc.T @ model.pc, np.eye(k), atol=ABS_TOL)

    def test_pca_using_spr(self, reference_rows):
        """useGemm=False packed path + host SVD (PCASuite.scala:41-74)."""
        x = np.stack([r.toArray() for r in reference_rows])
        _, model, df = _fit_df(reference_rows, k=3, useGemm=False, useCuSolverSVD=False)
        self._check_vs_oracle(model, x, 3)
        out = model.transform(df).select("pca_features")
        expected_pc, _ = numpy_pca_oracle(x, 3)
        rank = 2
        np.testing.assert_allclose(
            np.stack(out)[:, :rank], (x @ expected_pc)[:, :rank], atol=ABS_TOL
        )

    def test_pca_using_gemm(self, reference_rows):
        """useGemm=True covariance, host SVD (PCASuite.scala:76-109)."""
        x = np.stack([r.toArray() for r in reference_rows])
        _, model, _ = _fit_df(reference_rows, k=3, useGemm=True, useCuSolverSVD=False)
        self._check_vs_oracle(model, x, 3)

    def test_pca_using_accel_svd(self, rng):
        """100x100 uniform random, XLA eigensolver, sign-invariant |.|
        comparison (PCASuite.scala:111-153)."""
        x = rng.uniform(size=(100, 100))
        expected_pc, expected_var = numpy_pca_oracle(x, 10)
        _, model, _ = _fit_df(list(x), k=10, useGemm=True, useCuSolverSVD=True)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(expected_pc), atol=1e-4)
        np.testing.assert_allclose(model.explainedVariance, expected_var, atol=ABS_TOL)

    def test_gemm_and_spr_agree(self, rng):
        x = rng.normal(size=(50, 8))
        _, m_gemm, _ = _fit_df(list(x), k=5, useGemm=True, useCuSolverSVD=False)
        _, m_spr, _ = _fit_df(list(x), k=5, useGemm=False, useCuSolverSVD=False)
        np.testing.assert_allclose(m_gemm.pc, m_spr.pc, atol=ABS_TOL)

    def test_mean_centering_false(self, rng):
        x = rng.normal(size=(30, 6)) + 5.0
        _, model, _ = _fit_df(list(x), k=3, meanCentering=False, useCuSolverSVD=False)
        # Oracle without centering: eig of X^T X / (n-1)
        cov = x.T @ x / (x.shape[0] - 1)
        w, v = np.linalg.eigh(cov)
        v = v[:, ::-1]
        idx = np.argmax(np.abs(v), axis=0)
        v = v * np.where(v[idx, np.arange(v.shape[1])] < 0, -1.0, 1.0)
        np.testing.assert_allclose(model.pc, v[:, :3], atol=ABS_TOL)


class TestDenseSparseEquivalence:
    """Test 5: dense/sparse input variants give identical results
    (PCASuite.scala:155-190)."""

    def test_variants_identical(self, rng):
        x = rng.normal(size=(20, 5))
        x[x < 0] = 0.0  # make it sparse-ish
        import scipy.sparse as sp

        variants = [
            list(x),  # dense rows
            x,  # one dense block
            [Vectors.dense(row) for row in x],  # DenseVector rows
            [
                Vectors.sparse(5, np.nonzero(row)[0], row[np.nonzero(row)[0]])
                for row in x
            ],  # SparseVector rows
            sp.csr_matrix(x),  # scipy CSR
        ]
        results = []
        for rows in variants:
            _, model, _ = _fit_df(rows, k=3, useCuSolverSVD=False)
            results.append((model.pc, model.explainedVariance))
        for pc, var in results[1:]:
            np.testing.assert_allclose(pc, results[0][0], atol=1e-12)
            np.testing.assert_allclose(var, results[0][1], atol=1e-12)


class TestReadWrite:
    """Tests 6-7: estimator and model read/write round-trips
    (PCASuite.scala:192-206)."""

    def test_estimator_read_write(self, tmp_path):
        path = str(tmp_path / "pca")
        pca = PCA().setK(3).setInputCol("features").setOutputCol("out").setMeanCentering(False)
        pca.save(path)
        loaded = PCA.load(path)
        assert loaded.uid == pca.uid
        assert loaded.getK() == 3
        assert loaded.getInputCol() == "features"
        assert loaded.getOutputCol() == "out"
        assert loaded.getMeanCentering() is False
        assert loaded.getUseGemm() is True  # default survives round-trip

    def test_model_read_write(self, tmp_path, rng):
        path = str(tmp_path / "pca_model")
        x = rng.normal(size=(30, 6))
        _, model, _ = _fit_df(list(x), k=4, useCuSolverSVD=False)
        model.write.overwrite().save(path)
        loaded = PCAModel.load(path)
        assert loaded.uid == model.uid
        np.testing.assert_allclose(loaded.pc, model.pc, atol=0)
        np.testing.assert_allclose(loaded.explainedVariance, model.explainedVariance, atol=0)
        assert loaded.getInputCol() == "features"
        # loaded model transforms identically
        out_a = model.transform(x)
        out_b = loaded.transform(x)
        np.testing.assert_allclose(out_a, out_b, atol=0)

    def test_model_overwrite_guard(self, tmp_path, rng):
        path = str(tmp_path / "m")
        x = rng.normal(size=(10, 4))
        _, model, _ = _fit_df(list(x), k=2, useCuSolverSVD=False)
        model.save(path)
        with pytest.raises(FileExistsError):
            model.save(path)

    def test_parquet_schema_matches_spark_udt(self, tmp_path, rng):
        """The data file uses Spark's MatrixUDT/VectorUDT struct layout."""
        pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        path = str(tmp_path / "m")
        x = rng.normal(size=(10, 4))
        _, model, _ = _fit_df(list(x), k=2, useCuSolverSVD=False)
        model.save(path)
        table = pq.read_table(f"{path}/data/part-00000.parquet")
        pc = table.column("pc")[0].as_py()
        assert pc["type"] == 1 and pc["numRows"] == 4 and pc["numCols"] == 2
        ev = table.column("explainedVariance")[0].as_py()
        assert ev["type"] == 1 and ev["size"] == 2


class TestTransform:
    def test_transform_dataframe_shim(self, rng):
        x = rng.normal(size=(12, 5))
        pca, model, df = _fit_df(list(x), k=2, useCuSolverSVD=False)
        out = model.transform(df)
        assert "pca_features" in out.columns
        assert len(out.select("pca_features")) == 12
        assert out.select("pca_features")[0].shape == (2,)

    def test_transform_pandas(self, rng):
        import pandas as pd

        x = rng.normal(size=(12, 5))
        df = pd.DataFrame({"features": list(x)})
        model = PCA().setK(2).setInputCol("features").setOutputCol("out").fit(df)
        out = model.transform(df)
        assert "out" in out.columns
        np.testing.assert_allclose(np.stack(out["out"]), x @ model.pc, atol=1e-6)

    def test_transform_partitioned_matches_single(self, rng):
        x = rng.normal(size=(40, 7))
        _, model, _ = _fit_df(list(x), k=3, useCuSolverSVD=False)
        whole = model.transform(x)
        parts = model.transform([x[:15], x[15:]])
        np.testing.assert_allclose(whole, parts, atol=1e-10)


class TestRandomizedSolver:
    """Randomized (sketch) PCA must agree with the covariance path on the
    dominant subspace and the explained-variance ratios."""

    def test_matches_covariance_path(self, rng):
        from spark_rapids_ml_tpu.feature import PCA

        # Strong spectral decay so the sketch captures the subspace exactly.
        n, d, k = 500, 60, 5
        basis, _ = np.linalg.qr(rng.normal(size=(d, d)))
        scales = np.concatenate([[20, 15, 10, 6, 4], np.full(d - 5, 0.3)])
        x = rng.normal(size=(n, d)) @ (basis * scales).T

        full = PCA().setK(k).setSolver("covariance").fit(x)
        rand = PCA().setK(k).setSolver("randomized").fit(x)
        # Component-wise agreement up to sign (both sign-flip, so exact).
        for j in range(k):
            dot = abs(np.dot(full.pc[:, j], rand.pc[:, j]))
            assert dot > 0.999, (j, dot)
        np.testing.assert_allclose(
            rand.explainedVariance, full.explainedVariance, rtol=1e-3
        )

    def test_auto_routes_wide_features(self, rng):
        from spark_rapids_ml_tpu.feature import PCA

        # d >= the auto threshold: fit must succeed quickly without the
        # (d, d) eigh (n tiny, so the covariance would be rank-deficient
        # anyway — the sketch handles that via the CQR ridge).
        n, d = 300, 4096
        x = rng.normal(size=(n, d))
        model = PCA().setK(3).fit(x)
        assert model.pc.shape == (d, 3)
        assert np.all(np.isfinite(model.pc))
        assert float(np.sum(model.explainedVariance)) <= 1.0 + 1e-6

    def test_determinism(self, rng):
        from spark_rapids_ml_tpu.feature import PCA

        x = rng.normal(size=(200, 40))
        a = PCA().setK(4).setSolver("randomized").fit(x)
        b = PCA().setK(4).setSolver("randomized").fit(x)
        np.testing.assert_array_equal(a.pc, b.pc)

    def test_uncentered_variant(self, rng):
        from spark_rapids_ml_tpu.feature import PCA

        x = rng.normal(size=(300, 30)) + 5.0  # large mean
        cov = PCA().setK(3).setSolver("covariance").setMeanCentering(False).fit(x)
        rnd = PCA().setK(3).setSolver("randomized").setMeanCentering(False).fit(x)
        # Without centering the mean direction dominates; both paths must
        # agree on it.
        dot = abs(np.dot(cov.pc[:, 0], rnd.pc[:, 0]))
        assert dot > 0.999

    def test_solver_validation(self):
        from spark_rapids_ml_tpu.feature import PCA

        with pytest.raises(ValueError):
            PCA().setSolver("lanczos")

    def test_k_exceeds_rank_raises(self, rng):
        from spark_rapids_ml_tpu.feature import PCA

        x = rng.normal(size=(8, 50))
        with pytest.raises(ValueError, match="k must be in"):
            PCA().setK(10).setSolver("randomized").fit(x)

    def test_large_offset_total_variance(self, rng):
        from spark_rapids_ml_tpu.feature import PCA

        # Means ~1e4, std ~1: the ratio denominator must come from the
        # centered trace, not E[x^2] - mean^2 (fp32 cancellation).
        x = rng.normal(size=(300, 20)) + 1e4
        full = PCA().setK(3).setSolver("covariance").fit(x)
        rand = PCA().setK(3).setSolver("randomized").fit(x)
        # Flat spectra make the sketched singular values a slight
        # underestimate (a few %, and the exact margin moves with the
        # backend's RNG/GEMM version); the cancellation bug this guards
        # against produced order-of-magnitude-wrong or negative ratios.
        np.testing.assert_allclose(
            rand.explainedVariance, full.explainedVariance, rtol=8e-2
        )
        assert np.all(rand.explainedVariance > 0)
        assert float(np.sum(rand.explainedVariance)) <= 1.0

    def test_mesh_randomized_is_a_real_path(self, rng):
        # Round 3: the mesh restriction is gone — the sketch shards like
        # the covariance (full coverage in tests/test_wide_features.py).
        from spark_rapids_ml_tpu.feature import PCA
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        x = rng.normal(size=(256, 8)) * np.linspace(1, 3, 8)
        model = PCA(mesh=make_mesh((8, 1))).setK(2).setSolver("randomized").fit(x)
        assert model.pc.shape == (8, 2)


class TestTopkEigenSolver:
    """eigenSolver="topk": subspace iteration + Rayleigh-Ritz in place of
    the full O(d^3) eigh — for decaying spectra (PCA's regime) it must
    reproduce the exact solver's components and EXACT explained ratios."""

    def _decaying(self, rng, n=4000, d=128):
        # Strong spectral decay: a few dominant directions + noise floor.
        scales = np.concatenate([np.array([30.0, 20.0, 12.0, 8.0]), np.ones(d - 4)])
        return rng.normal(size=(n, d)) * scales

    def test_matches_full_solver(self, rng):
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        x = self._decaying(rng)
        m_full = PCA().setK(4).fit(x)
        m_topk = PCA().setK(4).setEigenSolver("topk").fit(x)
        assert_components_close(m_topk.pc, m_full.pc, 1e-5)
        # Explained ratios are trace-normalized: exact, not subspace-relative.
        np.testing.assert_allclose(
            m_topk.explainedVariance, m_full.explainedVariance, atol=1e-7
        )

    def test_ops_level_vs_numpy(self, rng):
        from spark_rapids_ml_tpu.ops.eigh import eigh_topk

        import jax.numpy as jnp

        x = self._decaying(rng, n=2000, d=64)
        cov = np.cov(x, rowvar=False)
        w, v = eigh_topk(jnp.asarray(cov), 3)
        w_ref, v_ref = np.linalg.eigh(cov)
        np.testing.assert_allclose(np.asarray(w), w_ref[::-1][:3], rtol=1e-8)
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        ref = v_ref[:, ::-1][:, :3]
        signs = np.sign(ref[np.argmax(np.abs(ref), axis=0), np.arange(3)])
        assert_components_close(np.asarray(v), ref * signs, 1e-6)

    def test_topk_with_mesh(self, rng):
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        x = self._decaying(rng, n=1000, d=32)
        m_mesh = PCA(mesh=make_mesh()).setK(3).setEigenSolver("topk").fit(x)
        m_full = PCA().setK(3).fit(x)
        assert_components_close(m_mesh.pc, m_full.pc, 1e-5)

    def test_invalid_rejected(self):
        with pytest.raises(ValueError, match="eigenSolver"):
            PCA().setEigenSolver("lanczos")

    def test_eigen_iters_knob_improves_weak_decay(self, rng):
        """Moderate eigengap: more iterations must tighten agreement with
        the exact solver (the knob exists for exactly this case)."""
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.eigh import eigh_topk

        d, k = 96, 4
        # Weak decay: top-k scales 1.6..1.2 over a 1.0 noise floor.
        scales = np.concatenate([np.linspace(1.6, 1.2, k), np.ones(d - k)])
        x = rng.normal(size=(20_000, d)) * scales
        cov = jnp.asarray(np.cov(x, rowvar=False))
        w_ref = np.linalg.eigvalsh(np.asarray(cov))[::-1][:k]

        def err(iters):
            w, _ = eigh_topk(cov, k, iters=iters)
            return float(np.max(np.abs(np.asarray(w) - w_ref)))

        assert err(40) < err(2)
        assert err(40) < 1e-6

    def test_eigen_iters_validation(self):
        with pytest.raises(ValueError, match="eigenIters"):
            PCA().setEigenIters(0)

    def test_topk_with_dd_precision(self, rng):
        """Explicit topk + dd is honored at fp64 (ARPACK), not silently
        downgraded to the full host eigh (r2 review)."""
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        x = self._decaying(rng, n=3000, d=64)
        m = PCA().setK(3).setPrecision("dd").setEigenSolver("topk").fit(x)
        m_ref = PCA().setK(3).setPrecision("dd").fit(x)
        assert_components_close(m.pc, m_ref.pc, 1e-6)
        np.testing.assert_allclose(
            m.explainedVariance, m_ref.explainedVariance, atol=1e-9
        )

