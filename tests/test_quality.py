"""Build quality gates — the ``-Xfatal-warnings`` / apache-rat analogue
(pom.xml:194,361-397). The image ships no ruff/mypy, so the gate is the
``tools/tpuml_lint`` plugin analyzer (generic hygiene + the four domain
checker families: JAX hazards, lock discipline, knob registry,
observability drift) plus an import sweep of every module (which catches
module-scope NameErrors, bad decorators, and circular imports the way a
compiler pass would). The analyzer's own unit suite (rule fixtures,
suppression, baseline round-trips) lives in tests/test_tpuml_lint.py."""

import importlib
import pkgutil
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def test_lint_clean():
    """The live tree is clean modulo the committed baseline — the same
    contract CI enforces via `python -m tools.tpuml_lint
    --validate-baseline` (stale baseline entries fail too, so the
    baseline can only shrink)."""
    import tools.tpuml_lint as tl
    from tools.tpuml_lint import baseline as bl

    findings, n_files = tl.run()
    assert n_files > 100  # the sweep really covered the tree
    entries = bl.load(tl.DEFAULT_BASELINE)
    new, _, stale = bl.apply(findings, entries)
    assert not new, "\n".join(f.render() for f in new)
    assert not stale, f"stale baseline entries: {stale}"


def test_every_module_imports():
    """Import every package module (catches decorator-time NameErrors etc.
    that only explode at import). spark.adapter self-gates on pyspark."""
    import spark_rapids_ml_tpu

    failures = []
    for mod in pkgutil.walk_packages(
        spark_rapids_ml_tpu.__path__, prefix="spark_rapids_ml_tpu."
    ):
        if mod.name.endswith("libtpuml_host"):
            continue  # ctypes shared library, not a Python extension module
        try:
            importlib.import_module(mod.name)
        except Exception as e:  # noqa: BLE001 - we want the full report
            failures.append(f"{mod.name}: {type(e).__name__}: {e}")
    assert not failures, "\n".join(failures)


def test_lint_catches_planted_defects(tmp_path):
    """The gate itself must work: plant each generic defect class and
    assert the analyzer flags it (the domain families have their own
    seeded-violation suite in tests/test_tpuml_lint.py)."""
    from tools.tpuml_lint import CHECKERS, lint_file

    cases = {
        "unused import": "'''doc'''\nimport os\n",
        "bare except": "'''doc'''\ntry:\n    pass\nexcept:\n    pass\n",
        "mutable default": "'''doc'''\ndef f(a=[]):\n    return a\n",
        "import *": "'''doc'''\nfrom os.path import *\n",
        "missing module docstring": "x = 1\n",
        "syntax error": "def broken(:\n",
    }
    for name, src in cases.items():
        f = tmp_path / "planted.py"
        f.write_text(src)
        assert lint_file(tmp_path, f, CHECKERS), f"lint missed: {name}"
    clean = tmp_path / "clean.py"
    clean.write_text("'''doc'''\nimport os\n\nprint(os.sep)\n")
    assert not lint_file(tmp_path, clean, CHECKERS)


def test_legacy_entry_point_still_works(tmp_path):
    """``python tools/lint.py`` (the seed entry) delegates to the
    package and keeps its exit-code contract."""
    import subprocess

    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")  # missing docstring
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"),
         "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "missing-docstring" in r.stdout
