"""Build quality gates — the ``-Xfatal-warnings`` / apache-rat analogue
(pom.xml:194,361-397). The image ships no ruff/mypy, so the gate is the
stdlib-ast lint in tools/lint.py plus an import sweep of every module
(which catches module-scope NameErrors, bad decorators, and circular
imports the way a compiler pass would)."""

import importlib
import pkgutil
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))


def test_lint_clean():
    import lint

    findings = []
    for root in (REPO / "spark_rapids_ml_tpu", REPO / "tests", REPO / "benchmarks"):
        for f in sorted(root.rglob("*.py")):
            findings.extend(lint.lint_file(f))
    assert not findings, "\n".join(findings)


def test_every_module_imports():
    """Import every package module (catches decorator-time NameErrors etc.
    that only explode at import). spark.adapter self-gates on pyspark."""
    import spark_rapids_ml_tpu

    failures = []
    for mod in pkgutil.walk_packages(
        spark_rapids_ml_tpu.__path__, prefix="spark_rapids_ml_tpu."
    ):
        if mod.name.endswith("libtpuml_host"):
            continue  # ctypes shared library, not a Python extension module
        try:
            importlib.import_module(mod.name)
        except Exception as e:  # noqa: BLE001 - we want the full report
            failures.append(f"{mod.name}: {type(e).__name__}: {e}")
    assert not failures, "\n".join(failures)


def test_lint_catches_planted_defects(tmp_path):
    """The gate itself must work: plant each defect class and assert the
    linter flags it."""
    import lint

    cases = {
        "unused import": "'''doc'''\nimport os\n",
        "bare except": "'''doc'''\ntry:\n    pass\nexcept:\n    pass\n",
        "mutable default": "'''doc'''\ndef f(a=[]):\n    return a\n",
        "import *": "'''doc'''\nfrom os.path import *\n",
        "missing module docstring": "x = 1\n",
    }
    for name, src in cases.items():
        f = tmp_path / "planted.py"
        f.write_text(src)
        assert lint.lint_file(f), f"lint missed: {name}"
    clean = tmp_path / "clean.py"
    clean.write_text("'''doc'''\nimport os\n\nprint(os.sep)\n")
    assert not lint.lint_file(clean)
