"""Fused pallas Lloyd kernel (VERDICT r3 #2): assignment + update stats
with zero (n, k) HBM temporaries, exact parity with the masked XLA
formulation (padding corrected in closed form). On CPU these run the
pallas interpreter; the TPU timings live in BASELINE.md's backend table.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.ops.kmeans import lloyd, random_init
from spark_rapids_ml_tpu.ops.pallas.kmeans import (
    assign_stats_fused,
    auto_block_n,
    lloyd_fused,
    pad_transposed,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    n, d, k = 1100, 16, 6
    x = (rng.normal(size=(n, d)) + rng.integers(0, k, n)[:, None] * 4).astype(
        np.float32
    )
    return x, k


class TestFusedOps:
    @pytest.mark.parametrize("precision", ["highest", "high", "default"])
    def test_lloyd_parity(self, data, precision):
        x, k = data
        xj = jnp.asarray(x)
        mask = jnp.ones(x.shape[0], jnp.float32)
        init = random_init(xj, mask, jax.random.key(1), k)
        xt, n_true = pad_transposed(xj, block_n=256)
        cf, costf, itf = lloyd_fused(
            xt, n_true, init, max_iter=8, tol=0.0, block_n=256,
            precision=precision, interpret=True,
        )
        cr, costr, itr = lloyd(xj, mask, init, max_iter=8, tol=0.0)
        assert np.abs(np.asarray(cf)[:, : x.shape[1]] - np.asarray(cr)).max() < 1e-4
        assert float(costf) == pytest.approx(float(costr), rel=1e-4)

    def test_odd_width_and_ragged_rows(self):
        rng = np.random.default_rng(5)
        n, d, k = 530, 13, 5  # d not a sublane multiple, n not a block multiple
        x = rng.normal(size=(n, d)).astype(np.float32)
        xj = jnp.asarray(x)
        mask = jnp.ones(n, jnp.float32)
        init = random_init(xj, mask, jax.random.key(2), k)
        xt, n_true = pad_transposed(xj, block_n=128)
        cf, costf, _ = lloyd_fused(
            xt, n_true, init, max_iter=6, tol=0.0, block_n=128, interpret=True
        )
        cr, costr, _ = lloyd(xj, mask, init, max_iter=6, tol=0.0)
        assert np.abs(np.asarray(cf)[:, :d] - np.asarray(cr)).max() < 1e-4
        assert float(costf) == pytest.approx(float(costr), rel=1e-5)

    def test_stats_padding_correction_exact(self, data):
        """Raw kernel stats include the zero-pad rows; the closed-form
        correction must remove exactly their count and cost."""
        x, k = data
        xj = jnp.asarray(x)
        init = random_init(xj, jnp.ones(x.shape[0], jnp.float32), jax.random.key(1), k)
        xt, n_true = pad_transposed(xj, block_n=256)
        s, c, cost, _ = assign_stats_fused(xt, init, block_n=256, interpret=True)
        pad_rows = xt.shape[1] - n_true
        assert float(jnp.sum(c)) == pytest.approx(n_true + pad_rows)

    def test_assign_clusters_blocked_parity(self):
        # The row-blocked assignment (used by the IVF coarse quantizer at
        # shapes whose full (n, k) distance matrix would blow HBM) must
        # match the unblocked op exactly, ragged final block included.
        from spark_rapids_ml_tpu.ops.kmeans import (
            assign_clusters,
            assign_clusters_blocked,
        )

        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.normal(size=(1001, 12)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(17, 12)).astype(np.float32))
        l_b, d_b = assign_clusters_blocked(x, c, block_rows=128)
        l_u, d_u = assign_clusters(x, c)
        assert np.array_equal(np.asarray(l_b), np.asarray(l_u))
        assert np.allclose(np.asarray(d_b), np.asarray(d_u), atol=1e-6)

    def test_auto_block_n_respects_vmem(self):
        bn_small = auto_block_n(16, 100)
        assert 4096 <= bn_small <= 8192 and bn_small % 128 == 0
        bn = auto_block_n(1024, 100)
        assert 128 <= bn < 8192 and bn % 128 == 0
        # Very wide d x large k: no feasible block — auto must decline.
        assert auto_block_n(16384, 100) is None

    def test_cosine_parity(self):
        rng = np.random.default_rng(7)
        from spark_rapids_ml_tpu.ops.kmeans import normalize_rows

        x = normalize_rows(jnp.asarray(rng.normal(size=(400, 16)).astype(np.float32)))
        mask = jnp.ones(400, jnp.float32)
        init = random_init(x, mask, jax.random.key(0), 4)
        xt, n_true = pad_transposed(x, block_n=128)
        cf, costf, _ = lloyd_fused(
            xt, n_true, init, max_iter=6, tol=0.0, block_n=128, cosine=True,
            interpret=True,
        )
        cr, costr, _ = lloyd(x, mask, init, max_iter=6, tol=0.0, cosine=True)
        assert np.abs(np.asarray(cf)[:, :16] - np.asarray(cr)).max() < 1e-4


class TestFusedEstimator:
    def test_explicit_fused_backend_matches_xla(self, data):
        x, k = data
        fused = (
            KMeans().setK(k).setSeed(3).setBackend("fused").setMaxIter(10).fit(x)
        )
        xla = KMeans().setK(k).setSeed(3).setBackend("xla").setMaxIter(10).fit(x)
        assert np.allclose(
            np.sort(fused.clusterCenters(), axis=0),
            np.sort(xla.clusterCenters(), axis=0),
            atol=1e-3,
        )
        assert fused.trainingCost == pytest.approx(xla.trainingCost, rel=1e-4)

    def test_fused_rejects_mesh_and_weights(self, data):
        from jax.sharding import Mesh
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        x, k = data
        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        with pytest.raises(ValueError, match="mesh"):
            KMeans(mesh=mesh).setK(k).setBackend("fused").fit(x)

    def test_auto_stays_xla_off_tpu(self, data):
        x, k = data
        est = KMeans().setK(k)
        # On the CPU test platform auto must never pick the interpreter.
        assert est._resolve_backend(None, 10**9) == "xla"

    def test_precision_param_validates(self):
        # "bf16"/"bf16x3"/"f32" are valid policy modes (ops/precision.py);
        # only genuinely unknown names must raise.
        with pytest.raises(ValueError, match="precision"):
            KMeans().setPrecision("fp8")
        with pytest.raises(ValueError, match="backend"):
            KMeans().setBackend("cuda")


class TestPackedOps:
    """Lane-packed assignment kernel (VERDICT r5 #3): P row groups share
    one 128-lane contraction at small d and k. Raw-stats parity with the
    unpacked fused kernel must hold at every packable geometry; the
    measured speedup lives in BASELINE.md ("KMeans lane packing")."""

    @pytest.mark.parametrize(
        "n,d,k",
        [(1100, 8, 4), (1100, 16, 7), (777, 16, 16), (1100, 64, 33), (513, 64, 4)],
    )
    def test_assign_stats_parity(self, n, d, k):
        from spark_rapids_ml_tpu.ops.pallas.kmeans import (
            assign_stats_packed,
            packed_feasible,
        )

        assert packed_feasible(d, k)
        rng = np.random.default_rng(n + d + k)
        x = jnp.asarray(
            (rng.normal(size=(n, d)) + rng.integers(0, k, n)[:, None]).astype(
                np.float32
            )
        )
        centers = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        xt, n_true = pad_transposed(x, block_n=256)
        d_pad = xt.shape[0]
        cpad = jnp.pad(centers, ((0, 0), (0, d_pad - d)))
        sf, cf, costf, c2f = assign_stats_fused(
            xt, cpad, block_n=256, interpret=True
        )
        sp, cp, costp, c2p = assign_stats_packed(
            xt, cpad, block_n=256, interpret=True
        )
        # Identical assignments (counts are integers), accumulation-order
        # epsilon on the float sums.
        np.testing.assert_array_equal(np.asarray(cf), np.asarray(cp))
        np.testing.assert_allclose(sp, sf, rtol=1e-5, atol=1e-4)
        assert float(costp) == pytest.approx(float(costf), rel=1e-5)
        np.testing.assert_allclose(c2p, c2f, rtol=1e-6)

    def test_feasibility_boundaries(self):
        from spark_rapids_ml_tpu.ops.pallas.kmeans import packed_feasible

        assert packed_feasible(8, 16)
        assert packed_feasible(16, 16)
        assert packed_feasible(64, 64)
        assert not packed_feasible(128, 4)  # lane tile already well used
        assert not packed_feasible(16, 32)  # scores overflow the group slot
        assert not packed_feasible(64, 65)
        assert not packed_feasible(65, 4)  # d_pad 72 > 64

    def test_lloyd_packed_matches_unpacked(self, data):
        """End-to-end Lloyd on both kernels: same assignments each pass,
        centers agree to accumulation tolerance."""
        x, k = data
        xj = jnp.asarray(x)
        mask = jnp.ones(x.shape[0], jnp.float32)
        init = random_init(xj, mask, jax.random.key(2), k)
        xt, n_true = pad_transposed(xj, block_n=256)

        def run(packed):
            return lloyd_fused(
                xt, n_true, init, max_iter=5, tol=0.0, block_n=256,
                interpret=True, packed=packed,
            )

        cu, costu, itu = run(False)
        cp, costp, itp = run(True)
        assert int(itu) == int(itp)
        np.testing.assert_allclose(cp, cu, rtol=1e-4, atol=1e-4)
        assert float(costp) == pytest.approx(float(costu), rel=1e-5)

    def test_estimator_fused_backend_packs_small_d(self, data, monkeypatch):
        """The model layer routes packable shapes onto the packed kernel;
        the fit must match the XLA backend regardless."""
        import spark_rapids_ml_tpu.ops.pallas.kmeans as pk

        x, k = data  # d=16, k=6: packable
        calls = {"packed": 0}
        real = pk.assign_stats_packed

        def spy(*a, **kw):
            calls["packed"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(pk, "assign_stats_packed", spy)

        def fit(backend):
            est = (
                KMeans()
                .setK(k)
                .setMaxIter(5)
                .setTol(0.0)
                .setInitMode("random")
                .setSeed(0)
                .setBackend(backend)
            )
            return est.fit(jnp.asarray(x))

        m_fused = fit("fused")
        assert calls["packed"] > 0  # the packed kernel actually ran
        m_xla = fit("xla")
        np.testing.assert_allclose(
            np.sort(m_fused.clusterCenters(), axis=0),
            np.sort(m_xla.clusterCenters(), axis=0),
            rtol=1e-4, atol=1e-4,
        )
