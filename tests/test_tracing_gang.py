"""Gang-wide distributed tracing (ISSUE 7): cross-process trace
propagation, per-member telemetry shards, and one merged view.

Three join scenarios are the contract:

  - a STUB-SPARK barrier gang fit — driver + members in one process,
    shards + manifests assemble into a single-trace_id tree with no
    orphan spans (``tools/tpuml_trace.py --validate --strict`` is the
    oracle);
  - a 16-THREAD serving burst — every request's submit→dispatcher-thread
    hop joins one per-request trace via the in-memory carrier, again
    orphan-free;
  - the ACCEPTANCE case: a REAL multiprocess gang fit (2 OS processes,
    jax.distributed) whose per-process shards merge into exactly one
    trace — one trace_id across all members, every span's parent
    resolvable, critical path reported, Chrome trace-event JSON renders,
    and merged counter totals equal to the per-member sums.

Satellites ride along: the heartbeat gauge retires when a gang member
finishes, and the RF host-label hole (negative label under a declared
numClasses) raises instead of wrapping.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.observability import trace as tracelib
from spark_rapids_ml_tpu.observability.metrics import default_registry
from spark_rapids_ml_tpu.observability.report import gang_report
from spark_rapids_ml_tpu.utils import tracing
from spark_rapids_ml_tpu.utils.envknobs import env_str

REPO = Path(__file__).resolve().parents[1]
TRACE_CLI = REPO / "tools" / "tpuml_trace.py"

_PREV_LOG = env_str(events.EVENT_LOG_ENV)


def _restore_sink():
    events.configure(_PREV_LOG if _PREV_LOG else None)


@pytest.fixture
def telemetry(tmp_path):
    """A fresh per-test telemetry dir wired as the active (shard) sink;
    teardown restores whatever the session runs under."""
    d = str(tmp_path / "telemetry")
    prev = env_str(events.TELEMETRY_DIR_ENV)
    os.environ[events.TELEMETRY_DIR_ENV] = d
    events.configure()
    try:
        yield Path(d)
    finally:
        if prev is None:
            os.environ.pop(events.TELEMETRY_DIR_ENV, None)
        else:
            os.environ[events.TELEMETRY_DIR_ENV] = prev
        _restore_sink()


_STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pyspark_stub")


@pytest.fixture
def stub_spark():
    """The pyspark stub installed as ``pyspark`` (the contract-suite
    arrangement — see tests/test_chaos.py)."""
    saved = {n: m for n, m in sys.modules.items() if n.startswith("pyspark")}
    for n in list(saved):
        del sys.modules[n]
    sys.path.insert(0, _STUB)
    try:
        from pyspark.sql import SparkSession

        yield SparkSession.builder.master("local[2]").getOrCreate()
    finally:
        sys.path.remove(_STUB)
        for n in [n for n in sys.modules if n.startswith("pyspark")]:
            del sys.modules[n]
        sys.modules.update(saved)


def _validate_cli(telemetry_dir, *extra):
    """Run tools/tpuml_trace.py --validate --strict over a dir."""
    return subprocess.run(
        [sys.executable, str(TRACE_CLI), str(telemetry_dir),
         "--validate", "--strict", *extra],
        capture_output=True, text=True, cwd=str(REPO),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


# --- the trace-context primitives ---------------------------------------


class TestTraceContext:
    def test_run_scope_roots_one_trace(self, telemetry):
        with events.run_scope("job", "root") as ctx:
            tc = events.current_trace()
            assert tc is not None
            with events.run_scope("fit", "nested"):
                assert events.current_trace().trace_id == tc.trace_id
            events.emit("fault", action="arm")
        assert events.current_trace() is None
        shard = next(Path(telemetry).glob("events-*.jsonl"))
        recs = [json.loads(l) for l in open(shard) if l.strip()]
        traced = [r for r in recs if r["run_id"] == ctx.run_id]
        assert traced and {r["trace"] for r in traced} == {tc.trace_id}

    def test_span_ids_globally_unique_strings(self, telemetry):
        with events.run_scope("job", "spans"):
            with tracing.TraceRange("outer"):
                with tracing.TraceRange("inner"):
                    pass
        shard = next(Path(telemetry).glob("events-*.jsonl"))
        spans = [
            json.loads(l) for l in open(shard)
            if l.strip() and '"span"' in l
        ]
        spans = [r for r in spans if r["event"] == "span"]
        assert len(spans) == 2
        inner, outer = spans[0], spans[1]  # inner exits first
        assert isinstance(inner["span"], str) and isinstance(outer["span"], str)
        assert inner["span"].startswith(f"{os.getpid():x}-")
        assert inner["parent"] == outer["span"]
        assert outer["parent"] is None

    def test_inject_extract_roundtrip(self, monkeypatch):
        with events.run_scope("job", "inject"):
            with tracing.TraceRange("launch"):
                carrier = events.inject_env({})
                tc = events.current_trace()
                assert carrier[events.TRACE_ID_ENV] == tc.trace_id
                assert (
                    carrier[events.TRACE_PARENT_ENV]
                    == tracing.current_span_id()
                )
        for k, v in carrier.items():
            monkeypatch.setenv(k, v)
        got = events.extract_env()
        assert got.trace_id == tc.trace_id
        assert got.span_id == carrier[events.TRACE_PARENT_ENV]

    def test_inject_without_ambient_begins_trace(self):
        carrier = events.inject_env({})
        assert carrier[events.TRACE_ID_ENV]
        assert events.TRACE_PARENT_ENV not in carrier

    def test_env_trace_joins_spawned_process_records(
        self, telemetry, monkeypatch
    ):
        monkeypatch.setenv(events.TRACE_ID_ENV, "feedfacefeedface")
        events.configure()  # the spawned-member path: re-read the carrier
        events.emit("fault", action="arm")
        monkeypatch.delenv(events.TRACE_ID_ENV)
        events.configure()
        shard = next(Path(telemetry).glob("events-*.jsonl"))
        recs = [json.loads(l) for l in open(shard) if l.strip()]
        faults = [r for r in recs if r["event"] == "fault"]
        assert faults and faults[-1]["trace"] == "feedfacefeedface"

    def test_trace_scope_carries_across_threads(self, telemetry):
        seen = {}

        def dispatcher(tc):
            with events.trace_scope(tc):
                with tracing.TraceRange("remote work"):
                    pass
                seen["trace"] = events.current_trace().trace_id

        with events.run_scope("job", "hop"):
            with tracing.TraceRange("submit"):
                tc = events.current_trace_context()
                t = threading.Thread(target=dispatcher, args=(tc,))
                t.start()
                t.join()
            assert seen["trace"] == events.current_trace().trace_id
        shard = next(Path(telemetry).glob("events-*.jsonl"))
        spans = [
            json.loads(l) for l in open(shard) if l.strip()
        ]
        spans = [r for r in spans if r["event"] == "span"]
        remote = next(s for s in spans if s["name"] == "remote work")
        submit = next(s for s in spans if s["name"] == "submit")
        # The remote thread's root span parents to the SUBMITTING span.
        assert remote["parent"] == submit["span"]


# --- shards + manifests -------------------------------------------------


class TestTelemetryShards:
    def test_shard_manifest_and_metrics_snapshot(self, telemetry):
        with events.run_scope("job", "shards") as ctx:
            tracing.bump_counter("tracetest.shard.counter", 3)
            with tracing.TraceRange("work"):
                pass
            trace_id = events.current_trace().trace_id
        manifest_path = events.flush_telemetry()
        assert manifest_path is not None
        manifest = json.load(open(manifest_path))
        assert manifest["pid"] == os.getpid()
        assert manifest["shard"] == f"events-{os.getpid()}.jsonl"
        assert trace_id in manifest["trace_roots"]
        assert manifest["emitted"] >= 3
        metrics = json.load(
            open(Path(telemetry) / f"metrics-{os.getpid()}.json")
        )
        assert metrics["counters"]["tracetest.shard.counter"] == 3
        # Every shard record (shard_open included) schema-validates.
        shard = Path(telemetry) / manifest["shard"]
        recs = [json.loads(l) for l in open(shard) if l.strip()]
        assert [p for r in recs for p in events.validate_record(r)] == []
        assert recs[0]["event"] == "telemetry"
        assert ctx.run_id in {r["run_id"] for r in recs}

    def test_telemetry_dir_outranks_event_log(self, tmp_path, monkeypatch):
        monkeypatch.setenv(events.EVENT_LOG_ENV, str(tmp_path / "one.jsonl"))
        monkeypatch.setenv(events.TELEMETRY_DIR_ENV, str(tmp_path / "shards"))
        try:
            dest = events.configure()
            assert dest == str(
                tmp_path / "shards" / f"events-{os.getpid()}.jsonl"
            )
        finally:
            monkeypatch.delenv(events.EVENT_LOG_ENV)
            monkeypatch.delenv(events.TELEMETRY_DIR_ENV)
            _restore_sink()

    def test_validate_flags_malformed_shard(self, telemetry):
        events.emit("fault", action="arm")
        events.flush_telemetry()
        shard = next(Path(telemetry).glob("events-*.jsonl"))
        with open(shard, "a") as f:
            f.write('{"event": "span"}\nnot json\n')
        merged = tracelib.assemble(str(telemetry))
        assert len(merged["problems"]) >= 2
        r = _validate_cli(telemetry)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "INVALID" in r.stderr


# --- the stub-spark barrier gang ---------------------------------------


class TestStubGangTrace:
    def test_gang_fit_assembles_into_one_trace(
        self, telemetry, stub_spark, monkeypatch
    ):
        monkeypatch.setenv("TPUML_GANG_HEARTBEAT_EVERY", "0.02")
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        df = stub_spark.createDataFrame(
            [(float(i),) for i in range(8)], ["v"], numPartitions=2
        )

        def task(ctx, it):
            with tracing.TraceRange("member compute"):
                time.sleep(0.05)
                return [sum(r.v for r in it)]

        out = barrier_gang_run(df.rdd, task)
        assert sum(out) == sum(range(8))
        events.flush_telemetry()

        merged = tracelib.assemble(str(telemetry))
        assert merged["problems"] == []
        assert merged["orphan_problems"] == []
        # ONE trace joins the driver stage span and both members' work.
        assert len(merged["traces"]) == 1
        (cell,) = merged["traces"].values()
        assert cell["orphans"] == []
        assert cell["spans"] >= 3  # barrier gang + 2 member computes
        names = {
            s["name"]
            for s in merged["trace_cells"][cell["trace_id"]]["spans"]
        }
        assert {"barrier gang", "member compute"} <= names
        assert cell["critical_path"], "critical path must be reported"
        # Heartbeats from both members joined the same trace.
        beats = [
            r
            for r in merged["trace_cells"][cell["trace_id"]]["events"]
            if r["event"] == "heartbeat"
        ]
        assert {r["process"] for r in beats} == {0, 1}
        # The CLI oracle agrees, strictly.
        r = _validate_cli(telemetry)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_completed_gang_leaves_no_stale_heartbeat_gauges(
        self, telemetry, stub_spark, monkeypatch
    ):
        monkeypatch.setenv("TPUML_GANG_HEARTBEAT_EVERY", "0.02")
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        df = stub_spark.createDataFrame(
            [(float(i),) for i in range(4)], ["v"], numPartitions=2
        )
        barrier_gang_run(df.rdd, lambda ctx, it: [sum(r.v for r in it)])
        stale = [
            name
            for name in default_registry.snapshot()["gauges"]
            if name.startswith("gang.heartbeat.age_seconds")
        ]
        assert stale == [], f"finished members left gauges: {stale}"


# --- the 16-thread serving burst ---------------------------------------


class TestServingBurstTrace:
    def test_burst_traces_join_across_dispatcher_hop(self, telemetry):
        from spark_rapids_ml_tpu.models.kmeans import KMeansModel
        from spark_rapids_ml_tpu.serving import ServingRuntime

        d = 6
        rng = np.random.default_rng(3)
        model = KMeansModel(
            "trace-km", rng.integers(-8, 8, size=(3, d)).astype(np.float64)
        )
        n_threads = 16
        results = [None] * n_threads

        with ServingRuntime(max_delay_ms=20.0) as rt:
            rt.register("km", model)

            def client(i):
                x = rng.integers(-8, 8, size=(1, d)).astype(np.float64)
                results[i] = rt.submit("km", x).result(timeout=30)

            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert all(r is not None for r in results)
        events.flush_telemetry()

        merged = tracelib.assemble(str(telemetry))
        assert merged["problems"] == []
        assert merged["orphan_problems"] == []
        # One trace per request, each a tree with no orphan spans.
        serving_traces = {
            r["trace"]
            for cell in merged["trace_cells"].values()
            for r in cell["events"]
            if r["event"] == "serving" and r.get("action") == "enqueue"
        }
        assert len(serving_traces) == n_threads
        # Each request's enqueue and complete share ITS trace — the
        # submit → dispatcher-thread hop joined via the request carrier.
        by_run = {}
        for cell in merged["trace_cells"].values():
            for r in cell["events"]:
                if r["event"] == "serving" and r.get("run_id"):
                    by_run.setdefault(r["run_id"], set()).add(r["trace"])
        completed = [
            rid for rid, traces in by_run.items() if len(traces) != 1
        ]
        assert completed == [], f"requests spanning >1 trace: {completed}"
        r = _validate_cli(telemetry)
        assert r.returncode == 0, r.stdout + r.stderr


# --- the acceptance case: a REAL multiprocess gang ----------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestMultiprocessGangTrace:
    def test_two_process_gang_fit_merges_to_one_trace(self, tmp_path):
        """ISSUE 7 acceptance: a >=2-process gang fit yields shards that
        merge into exactly one trace — one trace_id across all members,
        every span parent resolvable, critical path reported, Chrome
        JSON renders, merged counters == per-member sums."""
        tdir = tmp_path / "telemetry"
        n_proc = 2
        port = _free_port()
        carrier = events.inject_env({})
        procs = []
        for pid in range(n_proc):
            env = {
                **os.environ,
                **carrier,
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "TPUML_COORDINATOR": f"127.0.0.1:{port}",
                "TPUML_NUM_PROCESSES": str(n_proc),
                "TPUML_PROCESS_ID": str(pid),
                "TPUML_TELEMETRY_DIR": str(tdir),
                "TPUML_TEST_ROWS": "403",
            }
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        str(REPO / "tests" / "multiproc_pca_worker.py"),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                    cwd=str(REPO),
                )
            )
        outs = [p.communicate(timeout=300) for p in procs]
        for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {pid} failed:\n{err[-3000:]}"

        merged = tracelib.assemble(str(tdir))
        assert merged["problems"] == [], merged["problems"]
        assert merged["orphan_problems"] == [], merged["orphan_problems"]
        assert len(merged["manifests"]) == n_proc

        # Exactly ONE trace, spanning every member process.
        assert len(merged["traces"]) == 1
        (cell,) = merged["traces"].values()
        assert cell["trace_id"] == carrier[events.TRACE_ID_ENV]
        assert cell["processes"] == [0, 1]
        assert len(cell["pids"]) == n_proc
        assert cell["spans"] >= 2 and cell["orphans"] == []
        assert cell["critical_path"], "critical path must be reported"

        # Chrome trace-event JSON renders, one row per member process.
        chrome = tracelib.chrome_trace(merged["records"])
        span_events = [
            e for e in chrome["traceEvents"] if e.get("ph") == "X"
        ]
        assert span_events
        assert {e["pid"] for e in span_events} == {
            m["pid"] for m in merged["manifests"]
        }

        # Merged counter totals equal the per-member sums.
        members = merged["metrics"]["members"]
        assert len(members) == n_proc
        summed = {}
        for m in members:
            for k, v in m["snapshot"]["counters"].items():
                summed[k] = summed.get(k, 0) + v
        assert merged["metrics"]["merged"]["counters"] == summed
        assert any(v > 0 for v in summed.values())
        # Both members retried through the shared bring-up policy.
        assert (
            summed.get("retry.distributed.initialize.attempts", 0) == n_proc
        )

        # gang_report carries the per-member breakdown + merged view.
        rep = gang_report(str(tdir))
        assert {m["process"] for m in rep["members"]} == {0, 1}
        assert rep["merged"]["counters"] == summed
        assert rep["problems"] == []

        # The CLI is the oracle: strict validation + both renders.
        r = _validate_cli(
            tdir,
            "--out", str(tmp_path / "trace.json"),
            "--metrics-out", str(tmp_path / "metrics.json"),
        )
        assert r.returncode == 0, r.stdout + r.stderr
        rendered = json.load(open(tmp_path / "trace.json"))
        assert rendered["traceEvents"]
        merged_metrics = json.load(open(tmp_path / "metrics.json"))
        assert merged_metrics["counters"] == summed


# --- satellite: RF host-label validation under setNumClasses ------------


class TestRFHostLabelValidation:
    def test_negative_host_label_raises_with_declared_classes(self, rng):
        from spark_rapids_ml_tpu.models.random_forest import (
            RandomForestClassifier,
        )

        x = rng.normal(size=(32, 4))
        y = rng.integers(0, 3, size=32).astype(np.float64)
        y[7] = -1.0  # pre-fix: silently wrapped into the LAST class column
        est = RandomForestClassifier().setNumTrees(3).setNumClasses(3)
        with pytest.raises(ValueError, match=">= 0"):
            est.fit((x, y))

    def test_valid_host_labels_still_fit_with_declared_classes(self, rng):
        from spark_rapids_ml_tpu.models.random_forest import (
            RandomForestClassifier,
        )

        x = rng.normal(size=(48, 4))
        y = rng.integers(0, 3, size=48).astype(np.float64)
        model = (
            RandomForestClassifier()
            .setNumTrees(3)
            .setNumClasses(3)
            .fit((x, y))
        )
        assert model.numClasses == 3
        assert model.predict(x[:5]).shape == (5,)
