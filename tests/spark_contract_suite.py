"""The pyspark adapter CONTRACT SUITE — one set of assertions, two
runners (VERDICT r2 #5b):

  - ``tests/test_spark_adapter.py`` runs it against ``tests/pyspark_stub``
    (the CI image has no pyspark; the stub implements the exact surface
    the adapter consumes with real partition semantics and cloudpickle
    serialization boundaries).
  - ``tests/test_spark_real.py`` runs the SAME classes against genuine
    pyspark when it is installed (``pytest.importorskip``), so the day an
    environment has pyspark the proof is one command.

Each runner module provides its own ``spark_env`` fixture; the helpers
here stay portable across both (e.g. partition counts via the documented
``repartition`` API when ``createDataFrame`` lacks the stub's
``numPartitions`` convenience).
"""

import numpy as np
import pytest


def _vector_df(spark, x, extra=None, n_parts=3):
    from pyspark.ml.linalg import Vectors

    cols = ["features"] + (list(extra) if extra else [])
    rows = []
    for i in range(x.shape[0]):
        row = [Vectors.dense(x[i])]
        if extra:
            row += [extra[c][i] for c in extra]
        rows.append(row)
    try:
        return spark.createDataFrame(rows, cols, numPartitions=n_parts)
    except TypeError:
        # Real pyspark: no numPartitions kwarg — repartition after.
        return spark.createDataFrame(rows, cols).repartition(n_parts)


class TestTpuPCA:
    def test_fit_transform_save_load(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        x = rng.normal(size=(300, 6)) * np.linspace(1, 2, 6) + 5.0
        df = _vector_df(spark, x)
        est = adapter.TpuPCA(k=2, inputCol="features", outputCol="pca")
        model = est.fit(df)

        # Oracle: numpy eigh of the covariance, sign-invariant.
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        cov = np.cov(x, rowvar=False)
        w, v = np.linalg.eigh(cov)
        v = v[:, ::-1]
        pc = np.asarray(model.pc.toArray())
        assert_components_close(pc, v[:, :2], 1e-9)

        out = model.transform(df)
        proj = np.stack([np.asarray(r.pca.toArray()) for r in out.collect()])
        np.testing.assert_allclose(proj, x @ pc, atol=1e-9)

        path = str(tmp_path / "tpupca_model")
        model._save_impl(path)
        loaded = adapter.TpuPCAModel.load(path)
        np.testing.assert_allclose(np.asarray(loaded.pc.toArray()), pc)
        out2 = loaded.transform(df)
        proj2 = np.stack([np.asarray(r.pca.toArray()) for r in out2.collect()])
        np.testing.assert_allclose(proj2, proj)

    def test_estimator_persistence(self, spark_env, tmp_path):
        adapter, spark = spark_env
        est = adapter.TpuPCA(k=3, inputCol="features").setGpuId(0)
        path = str(tmp_path / "tpupca_est")
        est._save_impl(path)
        loaded = adapter.TpuPCA.load(path)
        assert loaded.getOrDefault(loaded.k) == 3
        assert loaded.getOrDefault(loaded.gpuId) == 0


class TestTpuKMeans:
    def test_distributed_lloyd_clusters(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        centers_true = np.array([[0.0, 0.0], [8.0, 8.0], [0.0, 8.0]])
        x = np.concatenate(
            [c + rng.normal(scale=0.4, size=(80, 2)) for c in centers_true]
        )
        df = _vector_df(spark, x)
        model = adapter.TpuKMeans(k=3).setSeed(1).setMaxIter(20).fit(df)
        found = np.stack(model.clusterCenters())
        # Each true center has a found center within a small radius.
        for c in centers_true:
            assert np.min(np.linalg.norm(found - c, axis=1)) < 0.3

        out = model.transform(df)
        preds = np.asarray([r.prediction for r in out.collect()])
        # Points from one blob share a label.
        for g in range(3):
            blob = preds[g * 80 : (g + 1) * 80]
            assert len(np.unique(blob)) == 1

        path = str(tmp_path / "kmeans_model")
        model._save_impl(path)
        loaded = adapter.TpuKMeansModel.load(path)
        np.testing.assert_allclose(np.stack(loaded.clusterCenters()), found)


class TestTpuLinearRegression:
    def test_distributed_normal_equations(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        d = 5
        x = rng.normal(size=(400, d)) + 10.0
        beta = np.arange(1.0, d + 1.0)
        y = x @ beta + 2.5 + 0.01 * rng.normal(size=400)
        df = _vector_df(spark, x, extra={"label": list(y)})
        model = adapter.TpuLinearRegression().fit(df)

        xi = np.concatenate([x, np.ones((400, 1))], axis=1)
        ref = np.linalg.lstsq(xi, y, rcond=None)[0]
        np.testing.assert_allclose(
            np.asarray(model.coefficients.toArray()), ref[:d], atol=1e-6
        )
        assert model.intercept == pytest.approx(ref[d], abs=1e-4)

        out = model.transform(df)
        preds = np.asarray([r.prediction for r in out.collect()])
        np.testing.assert_allclose(preds, xi @ ref, atol=1e-3)

        path = str(tmp_path / "linreg_model")
        model._save_impl(path)
        loaded = adapter.TpuLinearRegressionModel.load(path)
        np.testing.assert_allclose(
            np.asarray(loaded.coefficients.toArray()),
            np.asarray(model.coefficients.toArray()),
        )

    def test_rejects_elastic_net(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(20, 2))
        df = _vector_df(spark, x, extra={"label": list(x.sum(axis=1))})
        with pytest.raises(ValueError, match="elasticNetParam"):
            adapter.TpuLinearRegression().setElasticNetParam(0.5).fit(df)


class TestTpuLogisticRegression:
    def test_fit_transform_save_load(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        x = rng.normal(size=(300, 4))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)})
        model = adapter.TpuLogisticRegression().setMaxIter(60).fit(df)

        out = model.transform(df)
        rows = out.collect()
        preds = np.asarray([r.prediction for r in rows])
        assert np.mean(preds == y) > 0.95
        probs = np.stack([np.asarray(r.probability.toArray()) for r in rows])
        assert probs.shape == (300, 2)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)
        raw = np.stack([np.asarray(r.rawPrediction.toArray()) for r in rows])
        assert raw.shape[0] == 300

        path = str(tmp_path / "logreg_model")
        model._save_impl(path)
        loaded = adapter.TpuLogisticRegressionModel.load(path)
        np.testing.assert_allclose(
            np.asarray(loaded.coefficients.toArray()),
            np.asarray(model.coefficients.toArray()),
            atol=1e-12,
        )
        out2 = loaded.transform(df)
        preds2 = np.asarray([r.prediction for r in out2.collect()])
        np.testing.assert_array_equal(preds2, preds)


class TestExecutorMath:
    """The numpy-only executor forwards must agree with the core (JAX)
    models bit-for-tolerance — they are what transform ships to executors
    that have no JAX at all."""

    def test_logistic_forward_matches_core(self, rng):
        from spark_rapids_ml_tpu.classification import LogisticRegression
        from spark_rapids_ml_tpu.spark.executor_math import logistic_forward

        x = rng.normal(size=(200, 4))
        y = (x[:, 0] - x[:, 2] > 0).astype(float)
        core = LogisticRegression().setMaxIter(40).fit((x, y))
        raw, probs, pred = logistic_forward(
            np.asarray(core.weights, dtype=np.float64),
            np.asarray(core.intercepts, dtype=np.float64),
            core.getThreshold(),
            x,
        )
        np.testing.assert_allclose(probs, core.predictProbability(x), atol=1e-6)
        np.testing.assert_allclose(raw, core.predictRaw(x), atol=1e-6)
        np.testing.assert_array_equal(pred, core.predict(x).astype(float))
        # raw really is margins: symmetric around zero for binomial.
        np.testing.assert_allclose(raw[:, 0], -raw[:, 1], atol=1e-12)

    def test_forest_forward_matches_core(self, rng):
        from spark_rapids_ml_tpu.classification import RandomForestClassifier
        from spark_rapids_ml_tpu.models.random_forest import _forest_depth
        from spark_rapids_ml_tpu.spark.executor_math import forest_forward

        x = rng.normal(size=(200, 5))
        y = ((x[:, 0] > 0) & (x[:, 1] > 0)).astype(float)
        core = RandomForestClassifier().setNumTrees(8).setMaxDepth(4).setSeed(3).fit((x, y))
        f = core._forest
        raw, probs, pred = forest_forward(
            np.asarray(f.feature),
            np.asarray(f.threshold, dtype=np.float64),
            np.asarray(f.is_leaf),
            np.asarray(f.leaf_value, dtype=np.float64),
            _forest_depth(f),
            x,
        )
        np.testing.assert_allclose(probs, core.predictProbability(x), atol=1e-6)
        np.testing.assert_allclose(raw, core.predictRaw(x), atol=1e-5)
        np.testing.assert_array_equal(pred, core.predict(x).astype(float))

    def test_executor_math_imports_no_jax(self):
        """Executors must be able to import the module without JAX: verify
        in a subprocess that blocks the jax import outright."""
        import os
        import subprocess
        import sys as _sys

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = (
            "import sys; sys.path.insert(0, %r); "
            "sys.modules['jax'] = None; "  # any jax import -> ImportError
            "import spark_rapids_ml_tpu.spark.executor_math as m; "
            "import numpy as np; "
            "r, p, y = m.logistic_forward(np.ones((3, 1)), np.zeros(1), 0.5, np.ones((2, 3))); "
            "print('NOJAX_OK', p.shape)"
        ) % repo_root
        out = subprocess.run(
            [_sys.executable, "-c", code], capture_output=True, text=True
        )
        assert out.returncode == 0, out.stderr[-1500:]
        assert "NOJAX_OK" in out.stdout


class TestTpuRandomForest:
    def test_fit_transform_save_load(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        x = rng.normal(size=(300, 4))
        y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(float)  # XOR: needs depth
        df = _vector_df(spark, x, extra={"label": list(y)})
        model = (
            adapter.TpuRandomForestClassifier()
            .setNumTrees(15)
            .setMaxDepth(5)
            .setSeed(0)
            .fit(df)
        )
        assert model.numClasses == 2
        out = model.transform(df)
        rows = out.collect()
        preds = np.asarray([r.prediction for r in rows])
        assert np.mean(preds == y) > 0.9
        probs = np.stack([np.asarray(r.probability.toArray()) for r in rows])
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)

        path = str(tmp_path / "rf_model")
        model._save_impl(path)
        loaded = adapter.TpuRandomForestClassificationModel.load(path)
        out2 = loaded.transform(df)
        preds2 = np.asarray([r.prediction for r in out2.collect()])
        np.testing.assert_array_equal(preds2, preds)


class TestTpuRandomForestRegressor:
    def test_fit_transform_save_load(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        x = rng.uniform(0, 1, size=(300, 3))
        y = 3.0 * x[:, 0] - 2.0 * x[:, 1]
        df = _vector_df(spark, x, extra={"label": list(y)})
        model = (
            adapter.TpuRandomForestRegressor()
            .setNumTrees(20)
            .setMaxDepth(6)
            .setSeed(0)
            .fit(df)
        )
        out = model.transform(df)
        preds = np.asarray([r.prediction for r in out.collect()])
        rmse = float(np.sqrt(np.mean((preds - y) ** 2)))
        assert rmse < 0.4, rmse
        # Executor forward must equal the core (JAX) model's predictions.
        np.testing.assert_allclose(preds, model._core.predict(x), atol=1e-6)

        path = str(tmp_path / "rfr_model")
        model._save_impl(path)
        loaded = adapter.TpuRandomForestRegressionModel.load(path)
        preds2 = np.asarray(
            [r.prediction for r in loaded.transform(df).collect()]
        )
        np.testing.assert_allclose(preds2, preds)


class TestDistributedLogistic:
    def test_distributed_matches_core_optimum(self, spark_env, rng):
        """The per-iteration executor loss/grad fit (scipy L-BFGS-B on the
        driver, numpy treeReduce on executors) must land on the same
        convex optimum as the core single-machine solver."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.classification import LogisticRegression

        x = rng.normal(size=(400, 5)) + 2.0
        y = (x[:, 0] - x[:, 1] > 2.0).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=4)
        m_dist = (
            adapter.TpuLogisticRegression()
            .setMaxIter(200)
            .setRegParam(0.01)
            .fit(df)
        )
        m_core = (
            LogisticRegression().setMaxIter(400).setRegParam(0.01).fit((x, y))
        )
        # Tight: both optimize the identical objective (population-std
        # standardization matches the core scaler exactly).
        np.testing.assert_allclose(
            np.asarray(m_dist.coefficients.toArray()),
            m_core.coefficients,
            atol=5e-4,
        )
        assert m_dist.intercept == pytest.approx(m_core.intercept, abs=5e-3)

    def test_multinomial_distributed(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(450, 4))
        y = np.argmax(x[:, :3] + 0.3 * rng.normal(size=(450, 3)), axis=1).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=3)
        model = adapter.TpuLogisticRegression().setMaxIter(150).fit(df)
        preds = np.asarray([r.prediction for r in model.transform(df).collect()])
        assert np.mean(preds == y) > 0.8

    def test_elastic_net_distributed_quality(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(200, 4))
        y = (x[:, 0] > 0).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)})
        model = (
            adapter.TpuLogisticRegression()
            .setMaxIter(100)
            .setRegParam(0.05)
            .setElasticNetParam(0.5)
            .fit(df)
        )
        preds = np.asarray([r.prediction for r in model.transform(df).collect()])
        assert np.mean(preds == y) > 0.9

    def test_elastic_net_distributed_matches_core_optimum(self, spark_env, rng):
        """Driver-side FISTA over executor gradient sums optimizes the
        same strictly convex objective as the core solver — coefficients
        must agree to optimizer tolerance (VERDICT r2 #3)."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.classification import LogisticRegression

        x = rng.normal(size=(300, 5))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=4)
        m_dist = (
            adapter.TpuLogisticRegression()
            .setMaxIter(500)
            .setRegParam(0.1)
            .setElasticNetParam(0.5)
            .fit(df)
        )
        m_core = (
            LogisticRegression()
            .setMaxIter(500)
            .setRegParam(0.1)
            .setElasticNetParam(0.5)
            .fit((x, y))
        )
        np.testing.assert_allclose(
            np.asarray(m_dist.coefficients.toArray()),
            m_core.coefficients,
            atol=2e-3,
        )
        assert m_dist.intercept == pytest.approx(m_core.intercept, abs=5e-3)
        # L1 sparsity must survive the distributed route: both solvers
        # zero the same noise features (or neither does).
        dist_zero = np.asarray(m_dist.coefficients.toArray()) == 0
        core_zero = np.asarray(m_core.coefficients) == 0
        np.testing.assert_array_equal(dist_zero, core_zero)

    def test_fractional_label_raises(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(60, 3))
        y = np.where(np.arange(60) == 7, 1.5, (x[:, 0] > 0).astype(float))
        df = _vector_df(spark, x, extra={"label": list(y)})
        with pytest.raises(ValueError, match="non-negative integers"):
            adapter.TpuLogisticRegression().fit(df)


class TestPySparkPinnedBehaviors:
    """Behaviors the stub pins to pyspark 3.5 documentation (VERDICT r2
    #5a). Run against the stub these guard the pins; run against genuine
    pyspark (tests/test_spark_real.py) they validate that the pins match
    the real thing — the same assertions either way."""

    def test_tree_aggregate_semantics(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(20, 2))
        df = _vector_df(
            spark, x, extra={"label": [float(v) for v in range(20, 40)]},
            n_parts=3,
        )
        rdd = df.select("label").rdd
        total = rdd.treeAggregate(
            0.0, lambda acc, row: acc + float(row[0]), lambda a, b: a + b
        )
        assert total == pytest.approx(sum(range(20, 40)))
        # Each partition folds from its OWN zero: a shared mutable zero
        # would multiply-count across partitions.
        appended = rdd.treeAggregate(
            [], lambda acc, row: acc + [float(row[0])], lambda a, b: a + b
        )
        assert sorted(appended) == [float(v) for v in range(20, 40)]

    def test_params_are_instance_owned(self, spark_env):
        adapter, spark = spark_env
        a = adapter.TpuPCA(k=2, inputCol="features")
        b = adapter.TpuPCA(k=3, inputCol="features")
        # pyspark 3.5 Params.__init__ copies class params per instance.
        assert a.getParam("k").parent == a.uid
        assert b.getParam("k").parent == b.uid
        assert a.getParam("k") != b.getParam("k")
        # A foreign instance's Param fails ownership validation with the
        # documented 'does not belong to' ValueError (Params._shouldOwn).
        with pytest.raises(ValueError):
            a.getOrDefault(b.getParam("k"))

    def test_resolve_param_accepts_name_or_owned_param(self, spark_env):
        adapter, spark = spark_env
        est = adapter.TpuPCA(k=2, inputCol="features")
        assert est._resolveParam("k") is est.getParam("k")
        assert est._resolveParam(est.getParam("k")) is est.getParam("k")
        with pytest.raises(TypeError):
            est._resolveParam(42)

    def test_reset_uid_reparents_params(self, spark_env):
        adapter, spark = spark_env
        est = adapter.TpuPCA(k=4, inputCol="features")
        est._resetUid("TpuPCA_restored")
        assert est.uid == "TpuPCA_restored"
        assert est.getParam("k").parent == "TpuPCA_restored"
        assert est.getOrDefault(est.getParam("k")) == 4

    def test_pandas_udf_receives_arrow_typed_series(self, spark_env, rng):
        adapter, spark = spark_env
        from pyspark.ml.functions import vector_to_array
        from pyspark.sql.functions import col, pandas_udf

        x = rng.normal(size=(12, 3))
        df = _vector_df(spark, x, extra={"label": [1.0] * 12}, n_parts=2)

        # Observations must travel back through the udf's RETURN column:
        # on a real cluster the udf runs in a separate worker process, so
        # driver-side closure mutation would be silently discarded.
        @pandas_udf("double")
        def probe_array(series):
            import numpy as _np
            import pandas as pd

            def code(v):
                # pyspark 3.5 Arrow serializer pin: array<double>
                # elements arrive as numpy float64 ndarrays, never lists.
                is_nd = isinstance(v, _np.ndarray)
                is_f64 = is_nd and v.dtype == _np.float64
                return float(len(v)) + 0.25 * is_nd + 0.5 * is_f64

            return pd.Series([code(v) for v in series])

        out = df.withColumn("n", probe_array(vector_to_array(col("features"))))
        codes = [float(r.n) for r in out.collect()]
        assert codes == [3.75] * 12, codes  # len 3, ndarray, float64

        @pandas_udf("double")
        def probe_scalar(series):
            # A double column arrives as a float64-dtype Series, not
            # object; encode the dtype check into the returned values.
            ok = str(series.dtype) == "float64"
            return series + (0.5 if ok else -100.0)

        out2 = df.withColumn("lbl2", probe_scalar(col("label")))
        assert [float(r.lbl2) for r in out2.collect()] == [1.5] * 12


class TestNoDriverCollect:
    """VERDICT r2 #3 done-criterion: instrument the stub RDD and assert
    the forest / elastic-net fits never collect the dataset to the driver
    (only the bounded quantile sample for forests)."""

    def _fetch_counter(self):
        try:
            from pyspark.sql import FETCHED_ROWS
        except ImportError:
            pytest.skip("driver-fetch instrumentation is stub-only")
        return FETCHED_ROWS

    def test_forest_fit_fetches_only_bounded_sample(
        self, spark_env, rng, monkeypatch
    ):
        adapter, spark = spark_env
        monkeypatch.setattr(adapter, "_QUANTILE_SAMPLE_CAP", 64)
        n = 600
        x = rng.normal(size=(n, 4))
        y = (x[:, 0] > 0).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=4)
        counter = self._fetch_counter()
        counter["rows"] = 0
        model = (
            adapter.TpuRandomForestClassifier()
            .setNumTrees(8)
            .setMaxDepth(3)
            .fit(df)
        )
        # The inflated Bernoulli draw crosses ~1.2×cap rows (+1 for the
        # first() width probe); the RETAINED sample is strictly <= cap.
        # A 2× wire bound still proves no full collect (600 would fail).
        assert counter["rows"] <= 128, counter["rows"]
        preds = np.asarray(
            [r.prediction for r in model.transform(df).collect()]
        )
        assert np.mean(preds == y) > 0.9

    def test_forest_regressor_fit_fetches_only_bounded_sample(
        self, spark_env, rng, monkeypatch
    ):
        adapter, spark = spark_env
        monkeypatch.setattr(adapter, "_QUANTILE_SAMPLE_CAP", 64)
        n = 500
        x = rng.uniform(0, 1, size=(n, 3))
        y = 2.0 * x[:, 0] - x[:, 1]
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=4)
        counter = self._fetch_counter()
        counter["rows"] = 0
        adapter.TpuRandomForestRegressor().setNumTrees(10).setMaxDepth(4).fit(df)
        assert counter["rows"] <= 128, counter["rows"]

    def test_elastic_net_fit_fetches_no_rows(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(400, 4))
        y = (x[:, 0] > 0).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=4)
        counter = self._fetch_counter()
        counter["rows"] = 0
        adapter.TpuLogisticRegression().setMaxIter(50).setRegParam(
            0.05
        ).setElasticNetParam(0.5).fit(df)
        # The only driver fetch allowed is first() probing the width.
        assert counter["rows"] <= 2, counter["rows"]


class TestForestDistributedMatchesCore:
    def test_no_bootstrap_matches_core_predictions(self, spark_env, rng):
        """bootstrap=False at rate 1.0 makes the sample weights all-ones
        on both sides, the quantile sample covers the full (small)
        dataset, and split selection is literally shared
        (ops.trees.split_level) — so the distributed adapter fit and the
        core fit must agree on every training prediction."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.classification import RandomForestClassifier

        x = rng.normal(size=(240, 4))
        y = ((x[:, 0] > 0.3) | (x[:, 1] < -0.5)).astype(float)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=3)
        m_dist = (
            adapter.TpuRandomForestClassifier()
            .setNumTrees(6)
            .setMaxDepth(4)
            .setBootstrap(False)
            .setFeatureSubsetStrategy("all")
            .setSeed(3)
            .fit(df)
        )
        m_core = (
            RandomForestClassifier()
            .setNumTrees(6)
            .setMaxDepth(4)
            .setBootstrap(False)
            .setFeatureSubsetStrategy("all")
            .setSeed(3)
            .fit((x, y))
        )
        preds = np.asarray(
            [r.prediction for r in m_dist.transform(df).collect()]
        )
        np.testing.assert_array_equal(preds, m_core.predict(x))

    def test_regressor_no_bootstrap_matches_core(self, spark_env, rng):
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.regression import RandomForestRegressor

        x = rng.uniform(0, 1, size=(200, 3))
        y = 3.0 * x[:, 0] - 2.0 * x[:, 1] + 0.1 * rng.normal(size=200)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=3)
        m_dist = (
            adapter.TpuRandomForestRegressor()
            .setNumTrees(5)
            .setMaxDepth(4)
            .setBootstrap(False)
            .setFeatureSubsetStrategy("all")
            .setSeed(1)
            .fit(df)
        )
        m_core = (
            RandomForestRegressor()
            .setNumTrees(5)
            .setMaxDepth(4)
            .setBootstrap(False)
            .setFeatureSubsetStrategy("all")
            .setSeed(1)
            .fit((x, y))
        )
        preds = np.asarray(
            [r.prediction for r in m_dist.transform(df).collect()]
        )
        np.testing.assert_allclose(preds, m_core.predict(x), atol=1e-4)


class TestNeighborsAdapters:
    def test_nearest_neighbors(self, spark_env, rng):
        adapter, spark = spark_env
        items = rng.normal(size=(200, 6))
        df = _vector_df(spark, items)
        model = adapter.TpuNearestNeighbors(k=4).fit(df)
        out = model.kneighbors(df)
        rows = out.collect()
        idx = np.stack([np.asarray(r.indices) for r in rows]).astype(int)
        dist = np.stack([np.asarray(r.distances) for r in rows])
        assert idx.shape == (200, 4)
        np.testing.assert_array_equal(idx[:, 0], np.arange(200))  # self first
        np.testing.assert_allclose(dist[:, 0], 0.0, atol=1e-5)
        # Oracle check on a handful of rows.
        d2 = ((items[:10, None, :] - items[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(idx[:10], np.argsort(d2, axis=1)[:, :4])

    def test_approximate_nearest_neighbors(self, spark_env, rng):
        adapter, spark = spark_env
        items = rng.normal(size=(300, 8))
        df = _vector_df(spark, items)
        model = (
            adapter.TpuApproximateNearestNeighbors(k=3)
            .setAlgorithm("ivfflat")
            .setAlgoParams({"nlist": 6, "nprobe": 6})
            .fit(df)
        )
        out = model.kneighbors(df)
        rows = out.collect()
        idx = np.stack([np.asarray(r.indices) for r in rows]).astype(int)
        assert idx.shape == (300, 3)
        # nprobe == nlist: exhaustive, so self must be the first hit.
        np.testing.assert_array_equal(idx[:, 0], np.arange(300))

    def test_ann_brute_approx_algorithm(self, spark_env, rng):
        adapter, spark = spark_env
        items = rng.normal(size=(200, 6))
        df = _vector_df(spark, items)
        model = (
            adapter.TpuApproximateNearestNeighbors(k=3)
            .setAlgorithm("brute_approx")
            .fit(df)
        )
        rows = model.kneighbors(df).collect()
        idx = np.stack([np.asarray(r.indices) for r in rows]).astype(int)
        np.testing.assert_array_equal(idx[:, 0], np.arange(200))

    def test_sharded_index_matches_collected(self, spark_env, rng):
        """indexMode='sharded' (VERDICT r3 #5): executor-local shards +
        treeReduce merge must return exactly the collected path's
        neighbors."""
        adapter, spark = spark_env
        items = rng.normal(size=(240, 6))
        df = _vector_df(spark, items)
        queries = rng.normal(size=(30, 6))
        qdf = _vector_df(spark, queries)
        collected = adapter.TpuNearestNeighbors(k=5).fit(df)
        sharded = (
            adapter.TpuNearestNeighbors(k=5).setIndexMode("sharded").fit(df)
        )
        rows_c = collected.kneighbors(qdf).collect()
        rows_s = sharded.kneighbors(qdf).collect()
        idx_c = np.stack([np.asarray(r.indices) for r in rows_c]).astype(int)
        idx_s = np.stack([np.asarray(r.indices) for r in rows_s]).astype(int)
        np.testing.assert_array_equal(idx_s, idx_c)
        d_c = np.stack([np.asarray(r.distances) for r in rows_c])
        d_s = np.stack([np.asarray(r.distances) for r in rows_s])
        np.testing.assert_allclose(d_s, d_c, atol=1e-9)

    def test_sharded_fit_never_collects_items(self, spark_env, rng):
        """The point of sharded mode: the ITEM SET never crosses
        executor->driver. The stub's fetch counter sees only the
        per-partition count rows during fit."""
        adapter, spark = spark_env
        try:
            from pyspark.sql import FETCHED_ROWS
        except ImportError:
            import pytest as _pytest

            _pytest.skip("fetch instrumentation is stub-only")
        items = rng.normal(size=(300, 5))
        df = _vector_df(spark, items)
        FETCHED_ROWS["rows"] = 0
        model = (
            adapter.TpuNearestNeighbors(k=3).setIndexMode("sharded").fit(df)
        )
        # Fit fetches one (partition, count) row per partition — never
        # an item row.
        n_parts = df.rdd.getNumPartitions()
        assert FETCHED_ROWS["rows"] <= n_parts, FETCHED_ROWS["rows"]
        # The search fetches the QUERY vectors (the small side), still
        # never the item set.
        queries = rng.normal(size=(20, 5))
        qdf = _vector_df(spark, queries)
        FETCHED_ROWS["rows"] = 0
        model.kneighbors(qdf).collect()
        assert FETCHED_ROWS["rows"] < 300, FETCHED_ROWS["rows"]

    def test_sharded_ann_brute_matches_collected(self, spark_env, rng):
        adapter, spark = spark_env
        items = rng.normal(size=(200, 6))
        df = _vector_df(spark, items)
        collected = (
            adapter.TpuApproximateNearestNeighbors(k=4)
            .setAlgorithm("brute")
            .fit(df)
        )
        sharded = (
            adapter.TpuApproximateNearestNeighbors(k=4)
            .setAlgorithm("brute")
            .setIndexMode("sharded")
            .fit(df)
        )
        idx_c = np.stack(
            [np.asarray(r.indices) for r in collected.kneighbors(df).collect()]
        ).astype(int)
        idx_s = np.stack(
            [np.asarray(r.indices) for r in sharded.kneighbors(df).collect()]
        ).astype(int)
        np.testing.assert_array_equal(idx_s, idx_c)

    def test_sharded_empty_query_dataset(self, spark_env, rng):
        """Regression (r4 review): an all-filtered query set must come
        back empty, not crash in np.stack."""
        adapter, spark = spark_env
        from pyspark.sql import DataFrame as StubDF

        items = rng.normal(size=(80, 4))
        df = _vector_df(spark, items)
        model = adapter.TpuNearestNeighbors(k=3).setIndexMode("sharded").fit(df)
        empty = StubDF(["features"], [[]])
        assert model.kneighbors(empty).collect() == []

    def test_sharded_ann_rejects_inverted_lists(self, spark_env, rng):
        adapter, spark = spark_env
        items = rng.normal(size=(60, 4))
        df = _vector_df(spark, items)
        with pytest.raises(ValueError, match="sharded"):
            adapter.TpuApproximateNearestNeighbors(k=3).setAlgorithm(
                "ivfflat"
            ).setIndexMode("sharded").fit(df)

    def test_kneighbors_empty_partition(self, spark_env, rng):
        """Empty query partitions (routine after filter/repartition) must
        not kill the kneighbors job (r2 review)."""
        adapter, spark = spark_env
        from pyspark.ml.linalg import Vectors
        from pyspark.sql import DataFrame as StubDF, Row

        items = rng.normal(size=(50, 4))
        df = _vector_df(spark, items)
        model = adapter.TpuNearestNeighbors(k=3).fit(df)
        rows = [Row(["features"], [Vectors.dense(v)]) for v in items[:10]]
        lopsided = StubDF(["features"], [rows[:7], [], rows[7:]])
        out = model.kneighbors(lopsided).collect()
        assert len(out) == 10
        idx = np.stack([np.asarray(r.indices) for r in out])
        assert idx.dtype.kind in "iu" or np.all(idx == idx.astype(int))
        np.testing.assert_array_equal(idx[:, 0].astype(int), np.arange(10))


class TestTpuDBSCANAndUMAP:
    def test_transform_closure_broadcast_once(self, spark_env, rng):
        """VERDICT r3 #7: the training matrix + fitted values ship as ONE
        broadcast serialization, not one per task closure — the stub's
        torrent-broadcast counter proves it across a multi-partition
        transform and a REPEATED transform (the handle is cached)."""
        adapter, spark = spark_env
        try:
            from pyspark import BROADCAST_VALUE_PICKLES
        except ImportError:
            pytest.skip("broadcast instrumentation is stub-only")
        x = np.concatenate(
            [rng.normal(scale=0.2, size=(40, 3)) + c for c in ([0, 0, 0], [5, 5, 0])]
        )
        df = _vector_df(spark, x)
        model = adapter.TpuDBSCAN().setEps(0.7).setMinSamples(4).fit(df)
        BROADCAST_VALUE_PICKLES["count"] = 0
        model.transform(df).collect()
        model.transform(df).collect()  # cached handle: still one broadcast
        assert BROADCAST_VALUE_PICKLES["count"] == 1, BROADCAST_VALUE_PICKLES

    def test_dbscan(self, spark_env, rng):
        adapter, spark = spark_env
        x = np.concatenate(
            [rng.normal(scale=0.2, size=(50, 3)) + c for c in ([0, 0, 0], [4, 4, 0])]
            + [rng.uniform(-2, 6, size=(8, 3))]
        )
        df = _vector_df(spark, x)
        model = adapter.TpuDBSCAN().setEps(0.7).setMinSamples(4).fit(df)
        preds = np.asarray(
            [r.prediction for r in model.transform(df).collect()]
        ).astype(int)
        # Two dense blobs become two clusters; blob labels are uniform.
        assert len(set(preds[:50])) == 1 and len(set(preds[50:100])) == 1
        assert preds[0] != preds[50]
        np.testing.assert_array_equal(preds, model.labels_)

    def test_umap_build_algo_passthrough(self, spark_env, rng):
        adapter, spark = spark_env
        x = rng.normal(size=(60, 5))
        df = _vector_df(spark, x)
        model = (
            adapter.TpuUMAP()
            .setNEpochs(20)
            .setBuildAlgo("brute_approx")
            .fit(df)
        )
        emb = np.stack(
            [np.asarray(r.embedding.toArray()) for r in model.transform(df).collect()]
        )
        assert emb.shape == (60, 2) and np.isfinite(emb).all()

    def test_umap(self, spark_env, rng):
        adapter, spark = spark_env
        x = np.concatenate(
            [rng.normal(size=(40, 6)) + off for off in (0.0, 12.0)]
        )
        df = _vector_df(spark, x)
        model = (
            adapter.TpuUMAP()
            .setNNeighbors(8)
            .setNEpochs(200)
            .setSeed(0)
            .fit(df)
        )
        rows = model.transform(df).collect()
        emb = np.stack([np.asarray(r.embedding.toArray()) for r in rows])
        assert emb.shape == (80, 2)
        labels = np.repeat([0, 1], 40)
        c0, c1 = emb[labels == 0].mean(0), emb[labels == 1].mean(0)
        spread = np.mean(np.linalg.norm(emb[labels == 0] - c0, axis=1)) + 1e-9
        assert np.linalg.norm(c0 - c1) / spread > 2.0
        # Training rows return their FITTED coordinates exactly
        # (fit_transform semantics through per-partition Arrow batches).
        np.testing.assert_allclose(emb, model.embedding, atol=1e-12)

    def test_dbscan_umap_persistence(self, spark_env, rng, tmp_path):
        adapter, spark = spark_env
        x = np.concatenate(
            [rng.normal(scale=0.2, size=(40, 3)) + c for c in ([0, 0, 0], [4, 4, 0])]
        )
        df = _vector_df(spark, x)
        db = adapter.TpuDBSCAN().setEps(0.7).setMinSamples(4).fit(df)
        p1 = str(tmp_path / "dbscan")
        db._save_impl(p1)
        loaded = adapter.TpuDBSCANModel.load(p1)
        np.testing.assert_array_equal(loaded.labels_, db.labels_)
        preds = np.asarray([r.prediction for r in loaded.transform(df).collect()])
        np.testing.assert_array_equal(preds, db.labels_)

        um = adapter.TpuUMAP().setNNeighbors(8).setNEpochs(50).setSeed(0).fit(df)
        p2 = str(tmp_path / "umap")
        um._save_impl(p2)
        lu = adapter.TpuUMAPModel.load(p2)
        np.testing.assert_allclose(lu.embedding, um.embedding)

    def test_dbscan_lookup_matches_f32_core_storage(self, spark_env, rng, monkeypatch):
        """The fitted-row lookup hashes at the CORE dtype: a core model
        storing f32 (no-x64 platforms) must still match incoming f64 rows
        (r2 review — with x64 on in tests, simulate by downcasting)."""
        adapter, spark = spark_env
        x = np.concatenate(
            [rng.normal(scale=0.2, size=(30, 3)) + c for c in ([0, 0, 0], [4, 4, 0])]
        )
        df = _vector_df(spark, x)
        model = adapter.TpuDBSCAN().setEps(0.7).setMinSamples(4).fit(df)
        # Force the f32 storage a no-x64 platform would produce.
        from spark_rapids_ml_tpu.models.dbscan import DBSCANModel

        # Swap in a core whose STORAGE is genuinely f32 — the ctor casts
        # to the platform dtype (f64 under the x64 test harness), so the
        # f32 array is assigned post-construction to emulate the no-x64
        # platform exactly. The cache keys on core identity, so the swap
        # rebuilds the lookup.
        core32 = DBSCANModel(
            None,
            model._core.fitted,
            model._core.labels_,
            model._core.core_mask_,
        )
        core32.fitted = np.asarray(model._core.fitted, dtype=np.float32)
        assert core32.fitted.dtype == np.float32
        model._core = core32
        preds = np.asarray([r.prediction for r in model.transform(df).collect()])
        np.testing.assert_array_equal(preds, model.labels_)


class TestEstimatorPersistence:
    def test_every_estimator_roundtrips(self, spark_env, tmp_path):
        """Nine estimator classes round-trip their params here (the
        DefaultParamsWritable contract); TpuPCA's round-trip is covered by
        TestTpuPCA.test_estimator_persistence — ten families total."""
        adapter, spark = spark_env
        cases = [
            (adapter.TpuKMeans(k=4).setSeed(7), "k", 4),
            (adapter.TpuLinearRegression().setRegParam(0.5), "regParam", 0.5),
            (adapter.TpuLogisticRegression().setMaxIter(33), "maxIter", 33),
            (adapter.TpuRandomForestClassifier().setNumTrees(9), "numTrees", 9),
            (adapter.TpuRandomForestRegressor().setMaxDepth(7), "maxDepth", 7),
            (adapter.TpuDBSCAN().setEps(0.9), "eps", 0.9),
            (adapter.TpuUMAP().setNNeighbors(11), "nNeighbors", 11),
            (adapter.TpuNearestNeighbors(k=6), "k", 6),
            (adapter.TpuApproximateNearestNeighbors(k=7), "k", 7),
        ]
        for i, (est, pname, expected) in enumerate(cases):
            path = str(tmp_path / f"est_{i}")
            est._save_impl(path)
            loaded = type(est).load(path)
            assert loaded.getOrDefault(loaded.getParam(pname)) == expected, type(est)

    def test_model_picklable_after_transform(self, spark_env, rng):
        """Caching the fitted-row lookup must not break model pickling
        (Spark broadcasts models to executors) — r2 review."""
        adapter, spark = spark_env
        x = np.concatenate(
            [rng.normal(scale=0.2, size=(30, 3)) + c for c in ([0, 0, 0], [4, 4, 0])]
        )
        df = _vector_df(spark, x)
        model = adapter.TpuDBSCAN().setEps(0.7).setMinSamples(4).fit(df)
        model.transform(df).collect()  # builds + caches the lookup
        import cloudpickle

        clone = cloudpickle.loads(cloudpickle.dumps(model))
        preds = np.asarray([r.prediction for r in clone.transform(df).collect()])
        np.testing.assert_array_equal(preds, model.labels_)

    def test_estimator_load_restores_uid(self, spark_env, tmp_path):
        adapter, spark = spark_env
        est = adapter.TpuKMeans(k=3)
        path = str(tmp_path / "uid_est")
        est._save_impl(path)
        loaded = adapter.TpuKMeans.load(path)
        assert loaded.uid == est.uid

    def test_roundtrip_preserves_default_vs_set(self, spark_env, tmp_path):
        """Defaults must come back as DEFAULTS (isSet False) after a
        save/load round trip — DefaultParamsReader semantics (r2 review)."""
        adapter, spark = spark_env
        est = adapter.TpuKMeans(k=3)  # k set explicitly; maxIter a default
        path = str(tmp_path / "def_est")
        est._save_impl(path)
        loaded = adapter.TpuKMeans.load(path)
        assert loaded.isSet(loaded.k)
        assert not loaded.isSet(loaded.maxIter)
        assert loaded.getOrDefault(loaded.maxIter) == 20


class TestBarrierGangRecovery:
    """VERDICT r4 #3: the documented barrier-stage gang-relaunch recipe
    (docs/PARITY.md "Failure detection / recovery"), EXECUTED — a
    partition task is killed mid-fit on its first attempt; the barrier
    stage must relaunch the WHOLE gang (not just the dead task) and the
    refit must come out correct. Fault injection is a filesystem sentinel
    (attempt state must live outside the task closure: every attempt
    re-deserializes the closure, exactly like a real cluster)."""

    @staticmethod
    def _moments_task(sentinel, log_dir, fail_pid):
        """Per-partition normal-equation moments with a one-shot injected
        failure on partition ``fail_pid``; records every launch."""

        def task(ctx, it):
            import os

            import numpy as _np

            pid = 0 if ctx is None else ctx.partitionId()
            with open(os.path.join(log_dir, f"launches_p{pid}"), "a") as fh:
                fh.write("launch\n")
            xs, ys = [], []
            for r in it:
                xs.append(_np.asarray(r.features.toArray(), dtype=float))
                ys.append(float(r.label))
            xs = _np.asarray(xs)
            ys = _np.asarray(ys)
            if pid == fail_pid and not os.path.exists(sentinel):
                open(sentinel, "w").close()
                raise RuntimeError("injected device failure mid-fit")
            yield (xs.T @ xs, xs.T @ ys)

        return task

    @staticmethod
    def _launch_counts(log_dir, n_parts):
        import os

        counts = []
        for pid in range(n_parts):
            p = os.path.join(log_dir, f"launches_p{pid}")
            counts.append(
                sum(1 for _ in open(p)) if os.path.exists(p) else 0
            )
        return counts

    def test_task_failure_relaunches_gang_and_refits(
        self, spark_env, rng, tmp_path
    ):
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        n, d = 200, 4
        x = rng.normal(size=(n, d))
        w_true = rng.normal(size=d)
        y = x @ w_true + 0.01 * rng.normal(size=n)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=2)

        task = self._moments_task(
            str(tmp_path / "fault_fired"), str(tmp_path), fail_pid=1
        )
        parts = barrier_gang_run(df.select("features", "label").rdd, task)

        # The refit after the gang relaunch is CORRECT.
        xtx = sum(p[0] for p in parts)
        xty = sum(p[1] for p in parts)
        w_fit = np.linalg.solve(xtx, xty)
        w_ref = np.linalg.lstsq(x, y, rcond=None)[0]
        np.testing.assert_allclose(w_fit, w_ref, atol=1e-8)

        # The fault really fired, and EVERY gang member relaunched — the
        # healthy partition too (stage-level retry, not per-task).
        import os

        assert os.path.exists(str(tmp_path / "fault_fired"))
        counts = self._launch_counts(str(tmp_path), 2)
        assert counts[1] >= 2, counts  # the killed task retried
        assert counts[0] >= 2, counts  # the healthy task ALSO relaunched

    def test_persistent_failure_escalates_to_driver(
        self, spark_env, rng, tmp_path
    ):
        """A fault that survives every relaunch fails the JOB — the
        escalation end of the reference's throw -> task-fail -> retry
        story (SURVEY §5, rapidsml_jni.cu:101-153 pattern)."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        x = rng.normal(size=(40, 3))
        df = _vector_df(spark, x, extra={"label": list(x[:, 0])}, n_parts=2)
        log_dir = str(tmp_path)

        def always_fails(ctx, it):
            import os

            pid = 0 if ctx is None else ctx.partitionId()
            with open(os.path.join(log_dir, f"launches_p{pid}"), "a") as fh:
                fh.write("launch\n")
            raise RuntimeError("unrecoverable injected failure")
            yield  # pragma: no cover - generator marker

        with pytest.raises(Exception):
            barrier_gang_run(df.select("features", "label").rdd, always_fails)

        # Stub-only: the scheduler burned its full stage-attempt budget.
        try:
            from pyspark.sql import BARRIER_MAX_ATTEMPTS
        except ImportError:
            pytest.skip("attempt-budget instrumentation is stub-only")
        assert self._launch_counts(log_dir, 1)[0] == BARRIER_MAX_ATTEMPTS

    def test_gang_relaunch_instrumentation_stub(self, spark_env, rng, tmp_path):
        """Stub-only: the barrier scheduler's launch log shows attempt 0
        touching both partitions, then attempt 1 relaunching both — the
        gang-as-a-unit schedule itself, not just its side effects."""
        adapter, spark = spark_env
        try:
            from pyspark.sql import BARRIER_TASK_LAUNCHES
        except ImportError:
            pytest.skip("barrier launch instrumentation is stub-only")
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        x = rng.normal(size=(60, 3))
        df = _vector_df(spark, x, extra={"label": list(x[:, 0])}, n_parts=2)
        BARRIER_TASK_LAUNCHES.clear()
        task = self._moments_task(
            str(tmp_path / "fault2"), str(tmp_path), fail_pid=0
        )
        barrier_gang_run(df.select("features", "label").rdd, task)
        assert BARRIER_TASK_LAUNCHES == [(0, 0), (1, 0), (1, 1)]

    def test_gang_coordinates_derivation(self, spark_env, rng):
        """Each barrier task derives jax.distributed coordinates from the
        gang roster: same coordinator everywhere, process_id = partition,
        num_processes = gang size."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.spark.barrier import (
            barrier_gang_run,
            gang_coordinates,
        )

        x = rng.normal(size=(40, 3))
        df = _vector_df(spark, x, n_parts=2)

        def task(ctx, it):
            list(it)
            if ctx is None:
                return
            yield gang_coordinates(ctx)

        coords = barrier_gang_run(df.select("features").rdd, task)
        assert len(coords) == 2
        assert {c["process_id"] for c in coords} == {0, 1}
        assert all(c["num_processes"] == 2 for c in coords)
        assert len({c["coordinator_address"] for c in coords}) == 1
        assert coords[0]["coordinator_address"].endswith(":8476")

    def test_relaunched_gang_gets_fresh_coordinator_port(
        self, spark_env, rng, tmp_path
    ):
        """The attempt number offsets the coordinator port: a RELAUNCHED
        gang (attempt 1) must derive a different coordinator address than
        the attempt it replaces, so it can never rejoin the dead cohort's
        coordination service (which may outlive its tasks by up to the
        heartbeat timeout while still bound to the old port)."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.spark.barrier import (
            barrier_gang_run,
            gang_coordinates,
        )

        x = rng.normal(size=(40, 3))
        df = _vector_df(spark, x, n_parts=2)
        sentinel = str(tmp_path / "port_fault")
        log_dir = str(tmp_path)

        def task(ctx, it):
            import os

            list(it)
            if ctx is None:
                return
            coords = gang_coordinates(ctx)
            attempt = int(ctx.attemptNumber())
            with open(
                os.path.join(log_dir, f"addr_a{attempt}_p{ctx.partitionId()}"),
                "w",
            ) as fh:
                fh.write(coords["coordinator_address"])
            if not os.path.exists(sentinel):
                open(sentinel, "w").close()
                raise RuntimeError("injected failure on the first attempt")
            yield coords

        coords = barrier_gang_run(df.select("features").rdd, task)

        import os

        with open(os.path.join(log_dir, "addr_a0_p0")) as fh:
            addr_attempt0 = fh.read()
        addrs_final = {c["coordinator_address"] for c in coords}
        assert len(addrs_final) == 1  # the relaunched gang agrees
        addr_attempt1 = addrs_final.pop()
        assert addr_attempt1 != addr_attempt0
        host0, _, port0 = addr_attempt0.rpartition(":")
        host1, _, port1 = addr_attempt1.rpartition(":")
        assert host1 == host0
        assert int(port1) == int(port0) + 1  # port + attempt


class TestGangFitPublicAPI:
    """The hand-written per-partition moments gangs above, MIGRATED to
    the public API: ``spark.barrier.gang_fit`` runs one barrier stage
    whose members each call the ordinary ``Estimator.fit`` with
    ``deployMode='gang'``. The stub (and local-master pyspark) runs
    barrier tasks sequentially in one process, so these drive
    SINGLE-member gangs (one partition) — the full member lifecycle
    (coordinate derivation, deploy-mode switch, carrier/telemetry
    propagation, whole-stage relaunch) minus the cross-process
    collectives; tests/multiproc_gang_fit_worker.py proves those.
    Single-member merges are order-deterministic, so parity with the
    single-process fit holds to near-machine tolerance (1e-12 — the
    member's rows arrive as re-stacked partition blocks, whose GEMM
    blocking differs from the monolithic array in the last bit)."""

    def test_gang_fit_linear_matches_single_process(self, spark_env, rng):
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.regression import LinearRegression
        from spark_rapids_ml_tpu.spark.barrier import gang_fit

        n, d = 120, 5
        x = rng.normal(size=(n, d))
        y = x @ rng.normal(size=d) + 0.01 * rng.normal(size=n)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=1)

        models = gang_fit(
            LinearRegression(), df.select("features", "label").rdd,
            labeled=True,
        )
        assert len(models) == 1
        ref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(
            np.asarray(models[0].coefficients),
            np.asarray(ref.coefficients), atol=1e-12, rtol=0,
        )
        np.testing.assert_allclose(
            models[0].intercept, ref.intercept, atol=1e-12, rtol=0
        )

    def test_gang_fit_pca_merged_trace_strict_clean(
        self, spark_env, rng, tmp_path, monkeypatch
    ):
        """One gang fit through the public API leaves ONE merged trace
        that assembles strict-clean (no problems, no orphans): the
        barrier stage span, the member's fit run, and the gang_fit join
        events all share the driver's trace id."""
        adapter, spark = spark_env
        from spark_rapids_ml_tpu.feature import PCA
        from spark_rapids_ml_tpu.observability import events
        from spark_rapids_ml_tpu.observability import trace as tracelib
        from spark_rapids_ml_tpu.spark.barrier import gang_fit

        tdir = tmp_path / "telemetry"
        monkeypatch.setenv(events.TELEMETRY_DIR_ENV, str(tdir))
        events.configure()
        try:
            x = rng.normal(size=(90, 6)) * np.linspace(1, 2, 6)
            df = _vector_df(spark, x, n_parts=1)
            models = gang_fit(PCA().setK(2), df.select("features").rdd)
            events.flush_telemetry()
        finally:
            monkeypatch.delenv(events.TELEMETRY_DIR_ENV)
            events.configure()

        ref = PCA().setK(2).fit([x])
        np.testing.assert_allclose(
            np.asarray(models[0].pc), np.asarray(ref.pc),
            atol=1e-12, rtol=0,
        )

        merged = tracelib.assemble(str(tdir))
        assert merged["problems"] == []
        assert merged["orphan_problems"] == []
        assert len(merged["traces"]) == 1
        (cell,) = merged["traces"].values()
        names = {
            s["name"] for s in merged["trace_cells"][cell["trace_id"]]["spans"]
        }
        assert "barrier gang" in names
        joins = [
            r for r in merged["trace_cells"][cell["trace_id"]]["events"]
            if r["event"] == "gang_fit"
        ]
        assert any(r.get("action") == "join" for r in joins)
        # The tpuml_trace CLI itself is exercised against a gang-fit shard
        # set (strict, as a subprocess) by the 2-process acceptance test
        # in tests/test_gang_fit.py and by the CI "Gang fit" step; no need
        # to pay a second interpreter bring-up here.

    def test_gang_fit_relaunches_whole_stage_and_refits(
        self, spark_env, rng, tmp_path
    ):
        """The recovery story of TestBarrierGangRecovery, through the
        public surface: a member that dies on its first attempt relaunches
        the whole stage and the REFIT through fit() comes out correct."""
        import os

        adapter, spark = spark_env
        from spark_rapids_ml_tpu.regression import LinearRegression
        from spark_rapids_ml_tpu.spark.barrier import _gang_extract, gang_fit

        n, d = 100, 4
        x = rng.normal(size=(n, d))
        y = x @ rng.normal(size=d)
        df = _vector_df(spark, x, extra={"label": list(y)}, n_parts=1)
        sentinel = str(tmp_path / "gang_fit_fault")

        def extract(it):
            if not os.path.exists(sentinel):
                open(sentinel, "w").close()
                raise RuntimeError("injected member death mid-extract")
            return _gang_extract(it, labeled=True)

        models = gang_fit(
            LinearRegression(), df.select("features", "label").rdd,
            extract=extract,
        )
        assert os.path.exists(sentinel)
        ref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(
            np.asarray(models[0].coefficients),
            np.asarray(ref.coefficients), atol=1e-12, rtol=0,
        )
