"""Chaos suite — deterministic fault injection × representative fits.

The recovery paths (robustness.retry / robustness.degrade, the gang
relaunch, the atomic model writer) are first-class code; this suite is
what keeps them that way. Every instrumented site is provoked through a
REAL fit with a schedule that fails the first attempt(s), and the
recovered result is asserted BIT-IDENTICAL to a no-fault run — retries
must re-execute deterministic work, not approximately redo it. Exhausting
the budget must surface exactly one classified error (RetryExhaustedError
with the cause chained) or, under ``TPUML_DEGRADE=cpu``, the documented
CPU degradation with a structured warning — never a hang, never a
half-written artifact.

Representative fits per the r6 issue: PCA (the distributed-moments
family), KMeans warm-restart (the checkpoint-resume family), logistic
regression (the iterative-solver family); the barrier site runs a
moments fit under the pyspark stub's stage-level gang retry.
"""

import glob
import os
import sys
import warnings

import numpy as np
import pytest

import jax

from spark_rapids_ml_tpu.robustness import (
    DegradationWarning,
    InjectedFault,
    RetryExhaustedError,
    RetryPolicy,
    classify,
    inject,
)
from spark_rapids_ml_tpu.robustness.faults import disarm, parse_spec
from spark_rapids_ml_tpu.utils.envknobs import EnvKnobError, env_int

_STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pyspark_stub")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """A test that dies mid-inject must not poison its neighbors."""
    yield
    disarm()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    """Zero backoff: the chaos matrix retries dozens of times per run."""
    monkeypatch.setenv("TPUML_RETRY_BASE_DELAY", "0")


@pytest.fixture
def data(rng):
    return rng.normal(size=(120, 5))


def _pca_state(x):
    from spark_rapids_ml_tpu.models.pca import PCA

    m = PCA().setK(2).fit(x)
    return m, (m.pc.tobytes(), m.explainedVariance.tobytes())


def _kmeans_warm_state(x):
    """The warm-restart path: a short cold fit checkpoints centers, the
    measured fit resumes from them (mllib setInitialModel semantics)."""
    from spark_rapids_ml_tpu.models.kmeans import KMeans

    cold = KMeans().setK(3).setMaxIter(2).setSeed(7).fit(x)
    warm = (
        KMeans()
        .setK(3)
        .setMaxIter(5)
        .setSeed(7)
        .setInitialModel(cold)
        .fit(x)
    )
    return warm, (np.asarray(warm.clusterCenters()).tobytes(),)


def _logistic_state(x):
    from spark_rapids_ml_tpu.models.logistic_regression import (
        LogisticRegression,
    )

    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    m = LogisticRegression().setMaxIter(40).fit((x, y))
    return m, (
        np.asarray(m.coefficients).tobytes(),
        np.asarray(m.intercept).tobytes(),
    )


_FITS = {
    "pca": _pca_state,
    "kmeans_warm": _kmeans_warm_state,
    "logistic": _logistic_state,
}


class TestSpecParsing:
    def test_known_sites_only(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            parse_spec("no.such.site=1")

    def test_counts_and_always_and_fatal(self):
        plan = parse_spec(
            "ingest.device_put=2; barrier.attempt=always:fatal,"
            "persistence.write=0"
        )
        assert plan["ingest.device_put"].count == 2
        assert not plan["ingest.device_put"].fatal
        assert plan["barrier.attempt"].fatal
        assert plan["barrier.attempt"].should_fail(10**6)
        assert not plan["persistence.write"].should_fail(0)

    def test_malformed_entry_raises(self):
        with pytest.raises(ValueError, match="malformed"):
            parse_spec("ingest.device_put")
        with pytest.raises(ValueError, match="malformed schedule"):
            parse_spec("ingest.device_put=soon")

    def test_env_spec_arms_without_code_changes(self, monkeypatch):
        """TPUML_FAULTS arms a plan through the same entry the import
        runs — the launcher path, no code changes in the process."""
        from spark_rapids_ml_tpu.robustness import faults

        monkeypatch.setenv("TPUML_FAULTS", "ingest.device_put=1")
        plan = faults.arm_from_env()
        assert plan is not None and faults.active_plan() is plan
        with pytest.raises(InjectedFault):
            faults.fault_point("ingest.device_put")
        faults.fault_point("ingest.device_put")  # schedule spent

    def test_zero_overhead_when_disarmed(self):
        from spark_rapids_ml_tpu.robustness.faults import fault_point

        assert fault_point("ingest.device_put") is None  # plain no-op


class TestRetryPolicy:
    def test_classification(self):
        assert classify(ValueError("bug")) == "fatal"
        assert classify(TypeError("bug")) == "fatal"
        assert classify(OSError("io")) == "retryable"
        assert classify(RuntimeError("heartbeat lost")) == "retryable"
        assert classify(InjectedFault("s", 0)) == "retryable"
        assert classify(InjectedFault("s", 0, fatal=True)) == "fatal"

    def test_fatal_reraises_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("caller bug")

        with pytest.raises(ValueError, match="caller bug"):
            RetryPolicy(max_attempts=5, base_delay=0).run(fn, "t")
        assert len(calls) == 1

    def test_retryable_retries_then_succeeds(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert RetryPolicy(max_attempts=3, base_delay=0).run(fn, "t") == "ok"
        assert len(calls) == 3

    def test_exhaustion_is_one_classified_error(self):
        def fn():
            raise OSError("forever")

        with pytest.raises(RetryExhaustedError) as ei:
            RetryPolicy(max_attempts=2, base_delay=0).run(fn, "unit")
        assert ei.value.attempts == 2
        assert isinstance(ei.value.__cause__, OSError)

    def test_deadline(self):
        import itertools

        clock = itertools.count()

        def fn():
            # Each attempt "takes" long via the monotonic mock below.
            raise OSError("slow")

        policy = RetryPolicy(max_attempts=100, base_delay=0, deadline=3.0)
        import spark_rapids_ml_tpu.robustness.retry as retry_mod

        real = retry_mod.time.monotonic
        retry_mod.time.monotonic = lambda: float(next(clock))
        try:
            with pytest.raises(RetryExhaustedError, match="deadline"):
                policy.run(fn, "slowpoke")
        finally:
            retry_mod.time.monotonic = real

    def test_jitter_is_deterministic(self):
        p = RetryPolicy(max_attempts=3, base_delay=0.1, max_delay=1.0)
        assert p.backoff("x", 1) == p.backoff("x", 1)
        assert p.backoff("x", 1) != p.backoff("y", 1)  # spread across names
        assert p.backoff("x", 2) <= 1.0

    def test_attempts_emit_trace_ranges(self):
        from spark_rapids_ml_tpu.utils.tracing import (
            clear_events,
            recent_events,
        )

        clear_events()
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("once")
            return 1

        RetryPolicy(max_attempts=3, base_delay=0).run(fn, "traced")
        names = [n for n, _, _ in recent_events()]
        assert "retry:traced#0" in names and "retry:traced#1" in names

    def test_env_knobs_reach_policy(self, monkeypatch):
        monkeypatch.setenv("TPUML_RETRY_MAX_ATTEMPTS", "7")
        monkeypatch.setenv("TPUML_RETRY_DEADLINE", "12.5")
        p = RetryPolicy.from_env()
        assert p.max_attempts == 7 and p.deadline == 12.5
        monkeypatch.setenv("TPUML_RETRY_MAX_ATTEMPTS", "many")
        with pytest.raises(EnvKnobError, match="TPUML_RETRY_MAX_ATTEMPTS"):
            RetryPolicy.from_env()


class TestEnvKnobHardening:
    """Satellite: every TPUML_* int knob parses through one helper that
    names the variable, the offending value, and the expected form."""

    def test_env_int_names_the_variable(self, monkeypatch):
        monkeypatch.setenv("TPUML_HEARTBEAT_TIMEOUT", "ten")
        with pytest.raises(EnvKnobError) as ei:
            env_int("TPUML_HEARTBEAT_TIMEOUT")
        msg = str(ei.value)
        assert "TPUML_HEARTBEAT_TIMEOUT" in msg
        assert "'ten'" in msg
        assert "integer" in msg

    def test_initialize_surfaces_named_error(self, monkeypatch):
        from spark_rapids_ml_tpu.parallel import distributed as dist

        monkeypatch.setenv("TPUML_HEARTBEAT_TIMEOUT", "100s")
        monkeypatch.setattr(dist, "_initialized", False)
        monkeypatch.setattr(
            jax.distributed, "initialize", lambda **kw: None
        )
        with pytest.raises(EnvKnobError, match="TPUML_HEARTBEAT_TIMEOUT"):
            dist.initialize(
                coordinator_address="127.0.0.1:1", num_processes=1, process_id=0
            )
        assert dist._initialized is False

    def test_minimum_enforced(self, monkeypatch):
        monkeypatch.setenv("TPUML_NUM_PROCESSES", "0")
        with pytest.raises(EnvKnobError, match=">= 1"):
            env_int("TPUML_NUM_PROCESSES", minimum=1)


class TestIngestSiteRecovery:
    """ingest.device_put: fail the first placement, assert the retried
    fit is bit-identical to a no-fault run — for every representative
    fit family that routes through the shared funnel."""

    @pytest.mark.parametrize("family", ["kmeans_warm", "logistic"])
    def test_fail_first_then_bit_identical(self, family, data):
        _, want = _FITS[family](data)
        with inject("ingest.device_put=1") as plan:
            _, got = _FITS[family](data)
        assert plan.fired == [("ingest.device_put", 0)]
        assert got == want

    @pytest.mark.parametrize("family", ["kmeans_warm", "logistic"])
    def test_exhaustion_surfaces_classified_error(self, family, data):
        with inject("ingest.device_put=always"):
            with pytest.raises(RetryExhaustedError) as ei:
                _FITS[family](data)
        assert isinstance(ei.value.__cause__, InjectedFault)

    def test_fatal_fault_skips_retry(self, data):
        with inject("ingest.device_put=always:fatal") as plan:
            with pytest.raises(InjectedFault):
                _FITS["kmeans_warm"](data)
        # fatal = classified unretryable: exactly one invocation consumed.
        assert plan.invocations("ingest.device_put") == 1


class TestCollectiveSiteRecovery:
    """collective.psum: the cross-process moment merge re-runs exactly."""

    def _moments(self, blocks, mesh):
        from spark_rapids_ml_tpu.parallel.distributed import (
            streaming_covariance_process_local,
        )

        mean, cov, n = streaming_covariance_process_local(
            iter(blocks), mesh=mesh, merge="psum"
        )
        return mean.tobytes(), cov.tobytes(), n

    def test_fail_first_then_bit_identical(self, rng):
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh((len(jax.devices()), 1))
        blocks = [rng.normal(size=(40, 6)) for _ in range(3)]
        want = self._moments(blocks, mesh)
        with inject("collective.psum=1") as plan:
            got = self._moments(blocks, mesh)
        assert plan.fired == [("collective.psum", 0)]
        assert got == want

    def test_exhaustion_classified(self, rng):
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh((len(jax.devices()), 1))
        blocks = [rng.normal(size=(40, 6)) for _ in range(2)]
        with inject("collective.psum=always"):
            with pytest.raises(RetryExhaustedError):
                self._moments(blocks, mesh)


class TestInitializeSiteRecovery:
    """distributed.initialize: bring-up retries under the shared policy
    (the real jax.distributed.initialize is mocked — a unit process must
    not actually bind a coordination service mid-suite)."""

    @pytest.fixture
    def mocked_dist(self, monkeypatch):
        from spark_rapids_ml_tpu.parallel import distributed as dist

        calls = []
        monkeypatch.setattr(dist, "_initialized", False)
        monkeypatch.setattr(
            jax.distributed, "initialize", lambda **kw: calls.append(kw)
        )
        return dist, calls

    def test_fail_first_then_initialized(self, mocked_dist):
        dist, calls = mocked_dist
        with inject("distributed.initialize=1") as plan:
            dist.initialize(
                coordinator_address="127.0.0.1:1", num_processes=2, process_id=1
            )
        assert plan.fired == [("distributed.initialize", 0)]
        assert len(calls) == 1  # the retry reached the real bring-up once
        assert calls[0]["num_processes"] == 2 and calls[0]["process_id"] == 1
        assert dist._initialized

    def test_exhaustion_leaves_uninitialized(self, mocked_dist):
        dist, calls = mocked_dist
        with inject("distributed.initialize=always"):
            with pytest.raises(RetryExhaustedError) as ei:
                dist.initialize(
                    coordinator_address="127.0.0.1:1",
                    num_processes=2,
                    process_id=1,
                )
        assert isinstance(ei.value.__cause__, InjectedFault)
        assert calls == [] and dist._initialized is False


class TestPersistenceSiteRecovery:
    """persistence.write + the atomic writer: a killed/faulted save never
    leaves a half-written model where load() can find it."""

    @pytest.mark.parametrize("family", sorted(_FITS))
    def test_fail_first_then_roundtrip_identical(self, family, data, tmp_path):
        model, _ = _FITS[family](data)
        path = str(tmp_path / "m")
        with inject("persistence.write=1") as plan:
            model.write.overwrite().save(path)
        assert plan.fired == [("persistence.write", 0)]
        loaded = type(model).load(path)
        assert _state_bytes(loaded) == _state_bytes(model)

    def test_exhaustion_leaves_no_artifact(self, data, tmp_path):
        model, _ = _FITS["pca"](data)
        path = str(tmp_path / "m")
        with inject("persistence.write=always"):
            with pytest.raises(RetryExhaustedError):
                model.write.save(path)
        assert not os.path.exists(path)
        assert glob.glob(str(tmp_path / ".*tmp-save*")) == []

    def test_kill_mid_save_is_invisible_to_load(self, data, tmp_path):
        """A FATAL fault models SIGKILL mid-write: no retry, no cleanup
        beyond the temp dir — the target path must simply not exist."""
        model, _ = _FITS["pca"](data)
        path = str(tmp_path / "m")
        with inject("persistence.write=always:fatal"):
            with pytest.raises(InjectedFault):
                model.write.save(path)
        assert not os.path.exists(path)
        with pytest.raises(FileNotFoundError):
            type(model).load(path)

    def test_failed_overwrite_keeps_previous_model(self, data, tmp_path):
        model, _ = _FITS["pca"](data)
        path = str(tmp_path / "m")
        model.write.save(path)
        before = _state_bytes(type(model).load(path))
        with inject("persistence.write=always"):
            with pytest.raises(RetryExhaustedError):
                model.write.overwrite().save(path)
        assert _state_bytes(type(model).load(path)) == before


def _state_bytes(model):
    """The fitted arrays of any chaos-suite model family, as bytes."""
    if hasattr(model, "pc"):
        return [model.pc.tobytes(), model.explainedVariance.tobytes()]
    if hasattr(model, "clusterCenters"):
        return [np.asarray(model.clusterCenters()).tobytes()]
    return [
        np.asarray(model.coefficients).tobytes(),
        np.asarray(model.intercept).tobytes(),
    ]


@pytest.fixture
def stub_spark():
    """The pyspark stub installed as ``pyspark`` (the contract-suite
    arrangement, trimmed: the chaos tests need the session + barrier
    scheduler, not the adapter)."""
    saved = {
        n: m for n, m in sys.modules.items() if n.startswith("pyspark")
    }
    for n in list(saved):
        del sys.modules[n]
    sys.path.insert(0, _STUB)
    try:
        from pyspark.sql import SparkSession

        yield SparkSession.builder.master("local[2]").getOrCreate()
    finally:
        sys.path.remove(_STUB)
        for n in [n for n in sys.modules if n.startswith("pyspark")]:
            del sys.modules[n]
        sys.modules.update(saved)


def _moments_task(ctx, it):
    """Per-partition normal-equation moments (the contract-suite fit)."""
    xs = [np.asarray(r.features.toArray(), dtype=float) for r in it]
    x = np.asarray(xs)
    yield x.T @ x


def _gang_fit(spark, x):
    import spark_contract_suite as suite

    from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

    df = suite._vector_df(spark, x, n_parts=2)
    parts = barrier_gang_run(df.select("features").rdd, _moments_task)
    return sum(p for p in parts)


class TestBarrierSiteRecovery:
    """barrier.attempt: a gang member dies on attempt 0, the stub's
    stage-level retry relaunches the WHOLE gang, and the refit matches
    the no-fault run bit-for-bit."""

    def test_fail_first_then_bit_identical(self, stub_spark, rng):
        x = rng.normal(size=(80, 4))
        want = _gang_fit(stub_spark, x)
        with inject("barrier.attempt=1") as plan:
            got = _gang_fit(stub_spark, x)
        assert plan.fired == [("barrier.attempt", 0)]
        assert got.tobytes() == want.tobytes()

    def test_exhaustion_is_one_classified_error(self, stub_spark, rng):
        x = rng.normal(size=(40, 4))
        with inject("barrier.attempt=always"):
            with pytest.raises(RetryExhaustedError) as ei:
                _gang_fit(stub_spark, x)
        assert isinstance(ei.value.__cause__, InjectedFault)

    def test_stage_resubmit_knob(self, stub_spark, rng, monkeypatch):
        """TPUML_BARRIER_RESUBMITS=2 gives the stage a second driver-side
        submission after the scheduler's own budget burns out."""
        from pyspark.sql import BARRIER_MAX_ATTEMPTS

        x = rng.normal(size=(40, 4))
        monkeypatch.setenv("TPUML_BARRIER_RESUBMITS", "2")
        # Fail every task of every attempt of the FIRST submission only.
        with inject(f"barrier.attempt={BARRIER_MAX_ATTEMPTS}") as plan:
            got = _gang_fit(stub_spark, x)
        assert plan.invocations("barrier.attempt") > BARRIER_MAX_ATTEMPTS
        assert got.tobytes() == _gang_fit(stub_spark, x).tobytes()

    def test_degrades_to_driver_local_run(self, stub_spark, rng, monkeypatch):
        x = rng.normal(size=(40, 4))
        want = _gang_fit(stub_spark, x)
        monkeypatch.setenv("TPUML_DEGRADE", "cpu")
        with inject("barrier.attempt=always"):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                got = _gang_fit(stub_spark, x)
        assert got.tobytes() == want.tobytes()
        degraded = [w for w in caught if isinstance(w.message, DegradationWarning)]
        assert degraded and "barrier gang fit" in str(degraded[0].message)


class TestDegradation:
    """TPUML_DEGRADE=cpu: single-process fits finish on the CPU path with
    a structured warning instead of raising."""

    def test_ingest_degrades_to_cpu(self, data, monkeypatch):
        monkeypatch.setenv("TPUML_DEGRADE", "cpu")
        _, want = _FITS["kmeans_warm"](data)
        with inject("ingest.device_put=always"):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                _, got = _FITS["kmeans_warm"](data)
        degraded = [w for w in caught if isinstance(w.message, DegradationWarning)]
        assert degraded, "expected a structured DegradationWarning"
        msg = degraded[0].message
        assert msg.fallback == "the CPU path"
        assert "ingest.device_put" in msg.why
        # On the CPU test platform the fallback device IS the accelerator
        # device, so the degraded fit is bit-identical.
        assert got == want

    def test_degrade_off_raises(self, data, monkeypatch):
        monkeypatch.setenv("TPUML_DEGRADE", "off")
        with inject("ingest.device_put=always"):
            with pytest.raises(RetryExhaustedError):
                _FITS["kmeans_warm"](data)

    def test_malformed_mode_is_named(self, monkeypatch):
        from spark_rapids_ml_tpu.robustness.degrade import degrade_mode

        monkeypatch.setenv("TPUML_DEGRADE", "gpu")
        with pytest.raises(EnvKnobError, match="TPUML_DEGRADE"):
            degrade_mode()

    def test_fatal_errors_never_degrade(self, data, monkeypatch):
        """Wrong arguments are wrong on the CPU too: ValueError must
        propagate untouched even in degrade mode."""
        from spark_rapids_ml_tpu.models.kmeans import KMeans

        monkeypatch.setenv("TPUML_DEGRADE", "cpu")
        with pytest.raises(ValueError, match="exceeds number of rows"):
            KMeans().setK(10**6).fit(data)
