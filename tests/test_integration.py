"""Kitchen-sink integration: every estimator family in one scenario.

Guards cross-component wiring (shared data dispatch, persistence layer,
param system, namespaces) rather than per-model numerics — each model's
own suite covers those.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import LogisticRegression, RandomForestClassifier
from spark_rapids_ml_tpu.clustering import DBSCAN, KMeans
from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.manifold import UMAP
from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors, NearestNeighbors
from spark_rapids_ml_tpu.pipeline import Pipeline, PipelineModel
from spark_rapids_ml_tpu.regression import LinearRegression, RandomForestRegressor
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder


@pytest.fixture(scope="module")
def scenario():
    rng = np.random.default_rng(7)
    centers = np.zeros((3, 12))
    centers[0, 0] = centers[1, 1] = centers[2, 2] = 8.0
    x = np.concatenate([rng.normal(size=(60, 12)) + c for c in centers])
    labels = np.repeat(np.arange(3), 60).astype(float)
    return x, labels


def test_every_family_round_trips(scenario, tmp_path):
    x, labels = scenario
    df = DataFrame({"features": list(x), "label": list(labels)})

    # Feature reduction -> clustering pipeline, persisted and reloaded.
    pipe_model = Pipeline(
        stages=[
            PCA().setK(4).setInputCol("features").setOutputCol("pca"),
            KMeans().setK(3).setFeaturesCol("pca").setSeed(0),
        ]
    ).fit(df)
    pipe_model.save(str(tmp_path / "pipe"))
    out = PipelineModel.load(str(tmp_path / "pipe")).transform(df)
    preds = np.asarray(out.select("prediction"))
    # 3 blobs recovered (up to relabeling).
    for c in range(3):
        blok = preds[labels == c]
        assert np.mean(blok == np.bincount(blok).argmax()) > 0.9

    # Supervised: CV selects a logistic model that classifies the blobs.
    lr = LogisticRegression()
    cv = (
        CrossValidator()
        .setEstimator(lr)
        .setEstimatorParamMaps(ParamGridBuilder().addGrid(lr.regParam, [0.0, 1.0]).build())
        .setEvaluator(MulticlassClassificationEvaluator())
        .setNumFolds(3)
        .fit(df)
    )
    acc = np.mean(np.asarray(cv.transform(df).select("prediction")) == labels)
    assert acc > 0.95

    # Forests, both flavors.
    assert np.mean(
        RandomForestClassifier().setNumTrees(8).setSeed(1).fit((x, labels)).predict(x)
        == labels
    ) > 0.95
    y_reg = x[:, 0] - x[:, 1]
    rf_reg = RandomForestRegressor().setNumTrees(8).setFeatureSubsetStrategy("all").setSeed(2)
    assert np.sqrt(np.mean((rf_reg.fit((x, y_reg)).predict(x) - y_reg) ** 2)) < 1.5

    # Regression + streaming blocks.
    lin = LinearRegression().fit((list(np.array_split(x, 4)), y_reg))
    assert np.sqrt(np.mean((lin.predict(x) - y_reg) ** 2)) < 1e-6

    # Neighbors: exact and approximate agree on the nearest neighbor.
    d_nn, i_nn = NearestNeighbors().setK(3).fit(x).kneighbors(x[:10])
    d_ann, i_ann = (
        ApproximateNearestNeighbors()
        .setAlgoParams({"nlist": 4, "nprobe": 4})
        .setK(3)
        .fit(x)
        .kneighbors(x[:10])
    )
    np.testing.assert_array_equal(i_nn[:, 0], i_ann[:, 0])

    # Density clustering finds the 3 blobs (eps ~ the 12-d intra-blob
    # pairwise distance scale, sqrt(2d) ~ 4.9).
    db = DBSCAN().setEps(4.5).setMinSamples(5).fit(x)
    assert len(set(db.labels_[db.labels_ >= 0])) == 3

    # Manifold embedding separates them.
    emb = UMAP().setNNeighbors(10).setNEpochs(60).setSeed(3).fit(x).embedding
    cents = np.stack([emb[labels == c].mean(0) for c in range(3)])
    spread = np.mean(np.linalg.norm(emb[labels == 0] - cents[0], axis=1))
    assert np.linalg.norm(cents[0] - cents[1]) > 2 * spread
