"""Lifecycle × serving: response freshness attribution, version-pure
micro-batches across a live hot swap, replicated rollback through the
router, and the drift-triggered closed-loop refit→swap cycle.

Freshness is the satellite-3 contract: every serving response carries
the concrete ``(name, version)`` that computed it — stamped on the
future by the batcher (single-process) and by the router's reply path
(replicated) — so the loadgen can report WHICH model generation served
each request while a refit loop flips versions underneath the load.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu.lifecycle import DriftMonitor, LifecycleController
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.serving import RoutingRuntime, ServingRuntime

D = 6


def dyadic(rng, shape, scale=4):
    return rng.integers(-4 * scale, 4 * scale, size=shape).astype(np.float64) / 4.0


def _km_score(model, x, y):
    centers = np.asarray(model.clusterCenters())
    d = np.linalg.norm(x[:, None, :] - centers[None], axis=2).min(axis=1)
    return -float(d.mean())


@pytest.fixture
def runtime():
    rt = ServingRuntime(max_delay_ms=1.0)
    try:
        yield rt
    finally:
        rt.close()


class TestFreshnessAttribution:
    def test_single_process_future_carries_name_and_version(self, runtime, rng):
        m = KMeansModel("fr-km", dyadic(rng, (3, D)))
        runtime.register("fr-km", m, alias="prod")
        fut = runtime.submit("fr-km@prod", dyadic(rng, (4, D)))
        fut.result(timeout=30)
        assert fut.model_name == "fr-km"
        assert fut.model_version == 1

    def test_attribution_tracks_the_flip(self, runtime, rng):
        m1 = KMeansModel("fl-km", dyadic(rng, (3, D)))
        m2 = KMeansModel("fl-km", dyadic(rng, (3, D)))
        runtime.register("fl-km", m1, alias="prod")
        f1 = runtime.submit("fl-km@prod", dyadic(rng, (2, D)))
        mv2 = runtime.register("fl-km", m2)
        runtime.set_alias("fl-km", "prod", mv2.version)
        f2 = runtime.submit("fl-km@prod", dyadic(rng, (2, D)))
        f1.result(timeout=30), f2.result(timeout=30)
        assert f1.model_version == 1 and f2.model_version == 2

    def test_loadgen_freshness_table(self, runtime, rng):
        from tools.tpuml_loadgen import FreshnessTable

        m = KMeansModel("lg-km", dyadic(rng, (3, D)))
        runtime.register("lg-km", m, alias="prod")
        table = FreshnessTable()
        futs = [
            runtime.submit("lg-km@prod", dyadic(rng, (1, D)))
            for _ in range(8)
        ]
        for f in futs:
            f.result(timeout=30)
            table.note(f)
        rows = table.report()
        assert len(rows) == 1
        assert rows[0]["model"] == "lg-km" and rows[0]["version"] == 1
        assert rows[0]["requests"] == 8
        assert rows[0]["last_seen_s"] >= rows[0]["first_seen_s"]


class TestVersionPureBatches:
    def test_no_mixed_version_batch_across_live_swap(self, runtime, rng):
        """Distinct centers per version make contamination observable:
        every response must equal ITS attributed version's prediction
        for exactly the submitted rows. The batcher keys micro-batches
        by (name, version, width, dtype), so rows from different
        versions can never share a kernel launch — this asserts the
        observable consequence under a mid-stream flip."""
        c1 = dyadic(rng, (3, D))
        m1 = KMeansModel("vp-km", c1)
        m2 = KMeansModel("vp-km", c1 + 100.0)  # wildly different assignments
        runtime.register("vp-km", m1, alias="prod")
        xs = [dyadic(rng, (2, D)) for _ in range(40)]
        futs = []
        flip_at = 20

        for i, x in enumerate(xs):
            if i == flip_at:
                mv = runtime.register("vp-km", m2)
                runtime.set_alias("vp-km", "prod", mv.version)
            futs.append(runtime.submit("vp-km@prod", x))

        by_version = {1: m1, 2: m2}
        seen = set()
        for x, f in zip(xs, futs):
            out = np.asarray(f.result(timeout=30))
            assert f.model_version in by_version
            seen.add(f.model_version)
            np.testing.assert_array_equal(
                out, by_version[f.model_version].predict(x)
            )
        assert seen == {1, 2}  # the flip really happened mid-stream

    def test_submit_many_is_version_consistent(self, runtime, rng):
        """submit_many resolves once: even if a flip lands mid-iteration
        the whole set is served by ONE version."""
        m1 = KMeansModel("vc-km", dyadic(rng, (3, D)))
        runtime.register("vc-km", m1, alias="prod")
        futs = runtime.submit_many(
            "vc-km@prod", [dyadic(rng, (1, D)) for _ in range(10)]
        )
        mv = runtime.register("vc-km", KMeansModel("vc-km", dyadic(rng, (3, D))))
        runtime.set_alias("vc-km", "prod", mv.version)
        for f in futs:
            f.result(timeout=30)
        assert {f.model_version for f in futs} == {1}


class TestRouterRollback:
    @pytest.fixture(scope="class")
    def gang(self):
        rt = RoutingRuntime(workers=2, launch="spawn", max_delay_ms=1.0)
        yield rt
        rt.close()

    def test_replicated_rollback_and_attribution(self, gang, rng):
        c = dyadic(rng, (3, D))
        m1, m2 = KMeansModel("rb-km", c), KMeansModel("rb-km", c + 100.0)
        gang.register("rb-km", m1, alias="prod")
        gang.register("rb-km", m2, alias="prod")
        f2 = gang.submit("rb-km@prod", dyadic(rng, (2, D)))
        f2.result(timeout=60)
        assert f2.model_version == 2  # router reply path attribution
        v = gang.rollback("rb-km")
        assert v == 1
        assert gang.registry.aliases("rb-km") == {"prod": 1}
        x = dyadic(rng, (2, D))
        f1 = gang.submit("rb-km@prod", x)
        np.testing.assert_array_equal(
            np.asarray(f1.result(timeout=60)), m1.predict(x)
        )
        assert f1.model_version == 1

    def test_rollback_is_zero_shed_under_load(self, gang, rng):
        """Requests in flight across the rollback all succeed — the
        two-phase (warm target everywhere, flip the router's alias last)
        never sheds or errors a request."""
        c = dyadic(rng, (3, D))
        gang.register("zs-km", KMeansModel("zs-km", c), alias="prod")
        gang.register("zs-km", KMeansModel("zs-km", c + 50.0), alias="prod")
        stop = threading.Event()
        errors = []
        served = []

        def pound():
            r = np.random.default_rng(77)
            while not stop.is_set():
                try:
                    f = gang.submit("zs-km@prod", dyadic(r, (1, D)))
                    f.result(timeout=60)
                    served.append(f.model_version)
                except Exception as exc:  # noqa: BLE001 - the assertion IS "none"
                    errors.append(exc)

        threads = [threading.Thread(target=pound) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            v = gang.rollback("zs-km")
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert v == 1 and not errors
        assert len(served) > 0
        assert set(served) <= {1, 2}


class TestDriftTriggeredCycle:
    def test_closed_loop_drift_refit_swap(self, tmp_path, rng):
        """The whole loop, in-process: serve → observe → drift fires →
        refit (warm-seeded) → gate → register → warm → flip — while
        requests keep flowing, every response version-attributed, none
        shed."""
        x0 = rng.normal(size=(300, D))
        x0[:150] += 4.0
        with ServingRuntime(max_delay_ms=1.0) as rt:
            est = KMeans(uid="cl-km").setK(2).setSeed(3)
            ctrl = LifecycleController(
                est, rt, "km", score_fn=_km_score, directory=str(tmp_path),
            )
            out0 = ctrl.run_cycle(x0)
            assert out0.action == "flipped" and out0.version == 1
            dm = DriftMonitor("km", threshold=0.25, min_count=200)

            def serve_and_observe(batch):
                """Closed loop: submit, attribute, score against the
                ATTRIBUTED version's centers, feed the monitor."""
                futs = [rt.submit("km@prod", row) for row in batch]
                versions = set()
                for row, f in zip(batch, futs):
                    f.result(timeout=30)
                    versions.add(f.model_version)
                    centers = np.asarray(
                        rt.registry.resolve("km", f.model_version)
                        .model.clusterCenters()
                    )
                    dm.observe(
                        float(np.linalg.norm(centers - row, axis=1).min())
                    )
                return versions

            # Steady traffic from the training distribution: baseline,
            # then a quiet tick.
            assert serve_and_observe(x0[:220]) == {1}
            assert dm.tick() is None  # bootstraps the reference
            serve_and_observe(x0[:220])
            assert dm.tick() is None  # stable

            # The world moves: assignment distances blow out, the
            # monitor fires, and THAT (not a timer) runs the cycle.
            x1 = x0 + 3.0
            serve_and_observe(x1[:220])
            psi = dm.tick()
            assert psi is not None and psi > 0.25
            out1 = ctrl.run_cycle(x1)
            assert out1.action == "flipped" and out1.version == 2
            dm.rebaseline()

            # Post-flip traffic is served by the new generation.
            assert serve_and_observe(x1[:50]) == {2}
