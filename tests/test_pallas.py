"""Pallas fused covariance kernel tests (interpreter mode on CPU; the same
kernel compiles for TPU via pallas_call with interpret=False)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.pallas.covariance import centered_gram_pallas


class TestCenteredGramPallas:
    def test_matches_numpy(self, rng):
        x = rng.normal(size=(300, 200)).astype(np.float32)
        mean = x.mean(0)
        ref = (x - mean).T @ (x - mean)
        out = np.asarray(
            centered_gram_pallas(jnp.asarray(x), jnp.asarray(mean), block_rows=128, interpret=True)
        )
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-3)

    def test_row_and_lane_padding(self, rng):
        """n not a tile multiple AND d not a 128 multiple."""
        x = rng.normal(size=(77, 50)).astype(np.float32)
        mean = x.mean(0)
        ref = (x - mean).T @ (x - mean)
        out = np.asarray(
            centered_gram_pallas(jnp.asarray(x), jnp.asarray(mean), block_rows=32, interpret=True)
        )
        assert out.shape == (50, 50)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-3)

    def test_single_block(self, rng):
        x = rng.normal(size=(16, 128)).astype(np.float32)
        mean = np.zeros(128, dtype=np.float32)
        out = np.asarray(
            centered_gram_pallas(jnp.asarray(x), jnp.asarray(mean), block_rows=64, interpret=True)
        )
        np.testing.assert_allclose(out, x.T @ x, rtol=2e-5, atol=1e-3)

    def test_empty_rows(self):
        out = centered_gram_pallas(
            jnp.zeros((0, 8), dtype=jnp.float32), jnp.zeros(8, dtype=jnp.float32), interpret=True
        )
        np.testing.assert_allclose(np.asarray(out), np.zeros((8, 8)))
