"""Pallas fused covariance kernel tests (interpreter mode on CPU; the same
kernel compiles for TPU via pallas_call with interpret=False)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.pallas.covariance import centered_gram_pallas


class TestCenteredGramPallas:
    def test_matches_numpy(self, rng):
        x = rng.normal(size=(300, 200)).astype(np.float32)
        mean = x.mean(0)
        ref = (x - mean).T @ (x - mean)
        out = np.asarray(
            centered_gram_pallas(jnp.asarray(x), jnp.asarray(mean), block_rows=128, interpret=True)
        )
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-3)

    def test_row_and_lane_padding(self, rng):
        """n not a tile multiple AND d not a 128 multiple."""
        x = rng.normal(size=(77, 50)).astype(np.float32)
        mean = x.mean(0)
        ref = (x - mean).T @ (x - mean)
        out = np.asarray(
            centered_gram_pallas(jnp.asarray(x), jnp.asarray(mean), block_rows=32, interpret=True)
        )
        assert out.shape == (50, 50)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-3)

    def test_single_block(self, rng):
        x = rng.normal(size=(16, 128)).astype(np.float32)
        mean = np.zeros(128, dtype=np.float32)
        out = np.asarray(
            centered_gram_pallas(jnp.asarray(x), jnp.asarray(mean), block_rows=64, interpret=True)
        )
        np.testing.assert_allclose(out, x.T @ x, rtol=2e-5, atol=1e-3)

    def test_empty_rows(self):
        out = centered_gram_pallas(
            jnp.zeros((0, 8), dtype=jnp.float32), jnp.zeros(8, dtype=jnp.float32), interpret=True
        )
        np.testing.assert_allclose(np.asarray(out), np.zeros((8, 8)))


class TestPallasBackendSelection:
    """The kernel is a selectable covariance backend (VERDICT r1 item 4),
    not dead code: PCA(covarianceBackend='pallas') must produce the same
    model as the default XLA fusion."""

    def test_pca_backend_matches_xla(self, rng):
        from spark_rapids_ml_tpu.feature import PCA
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        x = rng.normal(size=(600, 20)) * np.linspace(1, 2, 20)
        m_xla = PCA().setK(3).fit(x)
        m_pal = PCA().setK(3).setCovarianceBackend("pallas").fit(x)
        assert_components_close(m_pal.pc, m_xla.pc, 1e-8)
        np.testing.assert_allclose(
            m_pal.explainedVariance, m_xla.explainedVariance, atol=1e-10
        )

    def test_rowmatrix_backend(self, rng):
        from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix

        x = rng.normal(size=(300, 12)) + 7.0
        cov_xla = np.asarray(RowMatrix([x]).compute_covariance())
        cov_pal = np.asarray(RowMatrix([x], backend="pallas").compute_covariance())
        np.testing.assert_allclose(cov_pal, cov_xla, atol=1e-9)

    def test_invalid_combinations(self, rng):
        from spark_rapids_ml_tpu.feature import PCA
        from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix
        from spark_rapids_ml_tpu.parallel.mesh import make_mesh

        x = rng.normal(size=(50, 4))
        with pytest.raises(ValueError, match="backend"):
            RowMatrix([x], backend="triton")
        with pytest.raises(ValueError, match="covarianceBackend"):
            PCA().setCovarianceBackend("triton")
        with pytest.raises(ValueError, match="pallas"):
            PCA(mesh=make_mesh()).setK(2).setCovarianceBackend("pallas").fit(x)
        with pytest.raises(ValueError, match="pallas"):
            PCA().setK(2).setCovarianceBackend("pallas").fit(iter([x]))
        with pytest.raises(ValueError, match="dd"):
            RowMatrix([x], backend="pallas", precision="dd")
        with pytest.raises(ValueError, match="pallas"):
            PCA().setK(2).setSolver("randomized").setCovarianceBackend("pallas")\
                .fit(rng.normal(size=(50, 4)))
        with pytest.raises(ValueError, match="pallas"):
            RowMatrix([x], backend="pallas", use_gemm=False)

    def test_auto_precision_yields_to_pallas(self, rng, monkeypatch):
        """auto precision must not route fp64 input to dd under the
        explicit pallas (fp32-kernel) choice — it falls back to highest
        (r2 review: the combination crashed on real TPUs). Simulated by
        forcing the no-x64 resolution the real chip would produce."""
        import spark_rapids_ml_tpu.linalg.row_matrix as rm_mod
        from spark_rapids_ml_tpu.linalg.row_matrix import RowMatrix
        from spark_rapids_ml_tpu.ops.linalg import resolve_precision

        monkeypatch.setattr(
            rm_mod,
            "resolve_precision",
            lambda req, input_dtype=None: resolve_precision(
                req, input_dtype=input_dtype, x64_enabled=False, platform="tpu"
            ),
        )
        x = rng.normal(size=(60, 4))  # float64 input on a "no-x64 platform"
        assert (
            RowMatrix([x], precision="auto", input_dtype=np.float64).precision
            == "dd"
        )  # the monkeypatched resolution does produce dd...
        rm = RowMatrix(
            [x], backend="pallas", precision="auto", input_dtype=np.float64
        )
        assert rm.precision == "highest"  # ...but pallas downgrades it
        with pytest.raises(ValueError, match="dd"):
            RowMatrix([x], backend="pallas", precision="dd")
