"""Device-resident input path (VERDICT r2 #1): a jax.Array fed to the
public estimator runs the whole fit as one XLA program with no host
round-trip, and the model converts to host float64 lazily. Also covers the
self-selecting eigensolver (ops.eigh.eigh_auto, VERDICT r2 #2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.ops.eigh import eigh_auto, eigh_descending_host


def _oracle(xh, k):
    xc = xh.astype(np.float64) - xh.mean(0, dtype=np.float64)
    cov = xc.T @ xc / (xh.shape[0] - 1)
    w, v = np.linalg.eigh(cov)
    w, v = w[::-1], v[:, ::-1]
    return v[:, :k], (w / w.sum())[:k]


@pytest.fixture(scope="module")
def decaying():
    rng = np.random.default_rng(7)
    d = 48
    scales = np.exp(-np.arange(d) / 6.0)
    return (rng.standard_normal((1500, d)) * scales).astype(np.float32)


class TestDeviceInputFit:
    def test_matches_oracle_sign_invariant(self, decaying):
        x = jnp.asarray(decaying)
        model = PCA().setK(5).fit(x)
        pc_o, ev_o = _oracle(decaying, 5)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-4
        assert np.abs(model.explainedVariance - ev_o).max() < 1e-5

    def test_matches_host_partition_path(self, decaying):
        x = jnp.asarray(decaying)
        dev = PCA().setK(4).fit(x)
        host = PCA().setK(4).fit(decaying.astype(np.float64))
        assert np.abs(np.abs(dev.pc) - np.abs(host.pc)).max() < 1e-4

    def test_model_stays_on_device_until_read(self, decaying):
        model = PCA().setK(3).fit(jnp.asarray(decaying))
        assert isinstance(model._pc_raw, jax.Array)
        assert model._pc_np is None  # no host conversion yet
        pc = model.pc
        assert pc.dtype == np.float64 and pc.shape == (decaying.shape[1], 3)
        assert model.pc is pc  # cached, converted once

    def test_device_transform_returns_device_array(self, decaying):
        x = jnp.asarray(decaying)
        model = PCA().setK(3).fit(x)
        proj = model.transform(x)
        assert isinstance(proj, jax.Array)
        assert proj.shape == (decaying.shape[0], 3)
        # Matches the host projection contract X @ pc.
        expect = decaying.astype(np.float64) @ model.pc
        assert np.abs(np.asarray(proj, dtype=np.float64) - expect).max() < 1e-3

    def test_copy_preserves_lazy_state(self, decaying):
        model = PCA().setK(3).fit(jnp.asarray(decaying))
        dup = model.copy()
        assert np.allclose(dup.pc, model.pc)

    def test_randomized_solver_accepts_device_input(self, decaying):
        x = jnp.asarray(decaying)
        model = PCA().setK(3).setSolver("randomized").fit(x)
        pc_o, _ = _oracle(decaying, 3)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-3

    def test_randomized_solver_device_input_honors_mesh(self, decaying):
        # ADVICE r3: a device array + explicit mesh must reshard onto the
        # mesh (never silently compute single-device), matching the
        # covariance path's _device_array_on_mesh stance.
        from jax.sharding import Mesh
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        n_dev = len(jax.devices())
        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        n = (decaying.shape[0] // n_dev) * n_dev
        xh = decaying[:n]
        model = (
            PCA(mesh=mesh).setK(3).setSolver("randomized").fit(jnp.asarray(xh))
        )
        pc_o, _ = _oracle(xh, 3)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-3

    def test_randomized_solver_device_input_mesh_indivisible_raises(
        self, decaying
    ):
        from jax.sharding import Mesh
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        n_dev = len(jax.devices())
        if n_dev < 2:
            pytest.skip("needs a multi-device mesh")
        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        n = (decaying.shape[0] // n_dev) * n_dev + 1
        with pytest.raises(ValueError, match="divisible"):
            PCA(mesh=mesh).setK(2).setSolver("randomized").fit(
                jnp.asarray(decaying[:n])
            )

    def test_randomized_solver_host_partitions_on_1axis_mesh(self, decaying):
        # The error path above recommends "pass host partitions" — that
        # route must WORK on the same data-only mesh (it used to KeyError
        # on mesh.shape['model'] inside shard_rows_from_partitions).
        from jax.sharding import Mesh
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        model = (
            PCA(mesh=mesh).setK(3).setSolver("randomized").fit(decaying)
        )
        pc_o, _ = _oracle(decaying, 3)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-3

    def test_device_fitted_model_pickles_host_state(self, decaying):
        # ADVICE r3: pickling a device-fitted model (Spark broadcast,
        # cloudpickle closure) must ship host float64, not live device
        # buffers.
        cloudpickle = pytest.importorskip("cloudpickle")

        model = PCA().setK(3).fit(jnp.asarray(decaying))
        state = model.__getstate__()
        assert isinstance(state["_pc_raw"], np.ndarray)
        assert isinstance(state["_ev_raw"], np.ndarray)
        assert state["_pc_dev_cache"] == {}
        dup = cloudpickle.loads(cloudpickle.dumps(model))
        assert np.allclose(dup.pc, model.pc)
        assert np.allclose(dup.explainedVariance, model.explainedVariance)

    def test_dd_precision_rejected(self, decaying):
        with pytest.raises(ValueError, match="dd"):
            PCA().setK(3).setPrecision("dd").fit(jnp.asarray(decaying))

    def test_packed_path_rejected(self, decaying):
        with pytest.raises(ValueError, match="useGemm"):
            PCA().setK(3).setUseGemm(False).fit(jnp.asarray(decaying))

    def test_1d_array_rejected(self):
        with pytest.raises(ValueError, match="2-D"):
            PCA().setK(1).fit(jnp.ones((8,), dtype=jnp.float32))

    def test_zero_variance_input_yields_zero_ev_not_nan(self):
        model = PCA().setK(2).fit(jnp.ones((10, 4), dtype=jnp.float32))
        assert np.all(model.explainedVariance == 0)
        assert np.all(np.isfinite(model.pc))

    def test_mesh_device_input_runs_sharded_and_matches_oracle(self, decaying):
        from jax.sharding import Mesh
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        n_dev = len(jax.devices())
        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        n = (decaying.shape[0] // n_dev) * n_dev
        xh = decaying[:n]
        model = PCA(mesh=mesh).setK(4).fit(jnp.asarray(xh))
        pc_o, ev_o = _oracle(xh, 4)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-4
        assert np.abs(model.explainedVariance - ev_o).max() < 1e-5

    def test_mesh_device_input_indivisible_rows_raises(self, decaying):
        from jax.sharding import Mesh
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        n_dev = len(jax.devices())
        if n_dev < 2:
            pytest.skip("needs a multi-device mesh")
        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        n = (decaying.shape[0] // n_dev) * n_dev + 1
        with pytest.raises(ValueError, match="divisible"):
            PCA(mesh=mesh).setK(2).fit(jnp.asarray(decaying[:n]))

    def test_pallas_backend_device_input(self, decaying):
        model = PCA().setK(3).setCovarianceBackend("pallas").fit(
            jnp.asarray(decaying)
        )
        pc_o, _ = _oracle(decaying, 3)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-3

    def test_host_svd_optout_still_works(self, decaying):
        # useCuSolverSVD=False falls back to the generic path: device
        # covariance + host LAPACK SVD (the breeze branch).
        model = PCA().setK(3).setUseCuSolverSVD(False).fit(jnp.asarray(decaying))
        pc_o, _ = _oracle(decaying, 3)
        assert np.abs(np.abs(model.pc) - np.abs(pc_o)).max() < 1e-4


class TestEighAuto:
    def test_decaying_spectrum_accepted_not_promoted(self):
        d = 96
        w_true = 0.5 ** np.arange(d)
        rng = np.random.default_rng(3)
        q, _ = np.linalg.qr(rng.standard_normal((d, d)))
        a = (q * w_true) @ q.T
        w, v, promoted = eigh_auto(jnp.asarray(a, dtype=jnp.float32), 4)
        assert not bool(promoted)
        w_o, v_o = eigh_descending_host(a)
        assert np.abs(np.asarray(w) - w_o[:4]).max() < 1e-5
        assert np.abs(np.abs(np.asarray(v)) - np.abs(v_o[:, :4])).max() < 1e-3

    def test_slow_spectrum_promotes_to_full(self):
        # lambda_i = 0.99^i: the subspace-iteration convergence ratio
        # (lambda_{l+1}/lambda_k) is ~0.91 — neither stagnates within the
        # iteration budget nor passes the residual check, so the solver
        # must promote itself to the full eigh and return exact pairs.
        d = 100
        w_true = 0.99 ** np.arange(d)
        rng = np.random.default_rng(4)
        q, _ = np.linalg.qr(rng.standard_normal((d, d)))
        a = (q * w_true) @ q.T
        w, v, promoted = eigh_auto(jnp.asarray(a, dtype=jnp.float32), 4, max_iters=6)
        w_o, v_o = eigh_descending_host(a)
        assert bool(promoted)
        assert np.abs(np.asarray(w) - w_o[:4]).max() < 1e-4

    def test_mp_noise_spectrum_keeps_cluster_guarantees(self):
        # d/n = 64/4000 Marchenko-Pastur noise: whichever branch the
        # runtime check picks, the promises hold — orthonormal basis,
        # eigenvalues within cluster_tol relative of the truth, captured
        # variance within 2*cluster_tol of the optimal top-6 sum.
        rng = np.random.default_rng(5)
        x = rng.standard_normal((4000, 64)).astype(np.float32)
        a = x.T @ x / 4000.0
        w, v, promoted = eigh_auto(jnp.asarray(a), 6)
        w = np.asarray(w, dtype=np.float64)
        v = np.asarray(v, dtype=np.float64)
        assert np.abs(v.T @ v - np.eye(6)).max() < 1e-4
        w_o, _ = eigh_descending_host(a)
        assert np.abs(w - w_o[:6]).max() <= 0.05 * w_o[0]
        assert w.sum() > (1 - 0.1) * w_o[:6].sum()

    def test_tight_degenerate_cluster_accepted(self):
        # Eigenvalues within a 2% band: below cluster_tol=5%, so the
        # solver accepts without promoting — every exact solver's vectors
        # are equally arbitrary inside such a cluster; the promised
        # deliverables are orthonormality, per-eigenvalue accuracy to
        # cluster_tol relative, and near-optimal captured variance.
        rng = np.random.default_rng(8)
        d = 128
        w_true = 1.0 + 0.02 * rng.random(d)
        q, _ = np.linalg.qr(rng.standard_normal((d, d)))
        a = ((q * w_true) @ q.T).astype(np.float32)
        w, v, promoted = eigh_auto(jnp.asarray(a), 8)
        assert not bool(promoted)
        v = np.asarray(v, dtype=np.float64)
        assert np.abs(v.T @ v - np.eye(8)).max() < 1e-4
        w_sorted = np.sort(w_true)[::-1]
        assert np.abs(np.asarray(w) - w_sorted[:8]).max() < 0.05 * w_sorted[0]
        assert np.asarray(w).sum() > (1 - 0.1) * w_sorted[:8].sum()

    def test_adversarial_spectrum_sweep_accept_guarantees(self):
        # The acceptance rule's promises, validated across adversarial
        # spectra (geometric ratios through the slow regime, steps,
        # clusters, flat): whenever eigh_auto ACCEPTS (no promotion),
        # (1) eigenvalues are within cluster_tol relative of the truth,
        # (2) captured variance >= (1 - 2*cluster_tol) * optimal,
        # (3) the basis is orthonormal. Promoted cases are exact by
        # construction (full eigh).
        rng = np.random.default_rng(11)
        d, k, tol = 96, 6, 0.05
        spectra = [
            0.3 ** np.arange(d),
            0.7 ** np.arange(d),
            0.9 ** np.arange(d),
            0.97 ** np.arange(d),
            0.995 ** np.arange(d),
            np.ones(d),
            np.concatenate([np.full(3, 10.0), np.ones(d - 3)]),
            np.concatenate([np.full(k, 2.0), np.full(d - k, 1.9)]),
            np.concatenate([np.full(2, 5.0), np.full(8, 4.9), np.ones(d - 10)]),
            1.0 + 0.5 * rng.random(d),
        ]
        for idx, w_true in enumerate(spectra):
            w_true = np.sort(w_true)[::-1]
            q, _ = np.linalg.qr(rng.standard_normal((d, d)))
            a = ((q * w_true) @ q.T).astype(np.float32)
            w, v, promoted = eigh_auto(jnp.asarray(a), k)
            w, v = np.asarray(w, dtype=np.float64), np.asarray(v, dtype=np.float64)
            label = f"spectrum #{idx} promoted={bool(promoted)}"
            assert np.abs(v.T @ v - np.eye(k)).max() < 1e-3, label
            assert np.abs(w - w_true[:k]).max() <= tol * w_true[0] + 1e-4, label
            assert w.sum() >= (1 - 2 * tol) * w_true[:k].sum() - 1e-4, label

    def test_k_equals_d_runs_full(self):
        rng = np.random.default_rng(6)
        x = rng.standard_normal((200, 8)).astype(np.float32)
        a = x.T @ x / 200.0
        w, v, promoted = eigh_auto(jnp.asarray(a), 8)
        assert bool(promoted)
        w_o, _ = eigh_descending_host(a)
        assert np.abs(np.asarray(w) - w_o).max() < 1e-4
