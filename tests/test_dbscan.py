"""DBSCAN vs the sklearn oracle (sklearn.cluster.DBSCAN, exact algorithm).

Cluster structure of core points must match sklearn exactly up to label
permutation; border points may differ on ties (documented in
ops/dbscan.py), so datasets here keep clusters separated by > eps.
"""

import numpy as np
import pytest
from sklearn.cluster import DBSCAN as SkDBSCAN

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.models.dbscan import DBSCAN, DBSCANModel
from spark_rapids_ml_tpu.ops.dbscan import (
    core_point_mask,
    dbscan_labels,
    relabel_consecutive,
)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def blobs(rng, centers, n_per=60, scale=0.08):
    pts = np.concatenate(
        [rng.normal(c, scale, size=(n_per, len(c))) for c in centers]
    ).astype(np.float32)
    perm = rng.permutation(len(pts))
    return pts[perm]


def same_partition(a, b):
    """Labels agree as set partitions (incl. noise = -1 matching exactly)."""
    assert a.shape == b.shape
    assert np.array_equal(a == -1, b == -1)
    mapping = {}
    for x, y in zip(a, b):
        if x == -1:
            continue
        if x in mapping:
            assert mapping[x] == y
        else:
            assert y not in mapping.values()
            mapping[x] = y


class TestOps:
    def test_core_mask_matches_sklearn(self, rng):
        x = blobs(rng, [[0, 0], [3, 3], [6, 0]])
        sk = SkDBSCAN(eps=0.3, min_samples=8).fit(x)
        sk_core = np.zeros(len(x), bool)
        sk_core[sk.core_sample_indices_] = True
        core = np.asarray(core_point_mask(x, 0.3, 8))
        np.testing.assert_array_equal(core, sk_core)

    def test_labels_match_sklearn(self, rng):
        x = blobs(rng, [[0, 0], [3, 3], [6, 0]])
        sk = SkDBSCAN(eps=0.3, min_samples=8).fit(x)
        labels, _ = dbscan_labels(x, 0.3, 8)
        ours = relabel_consecutive(np.asarray(labels))
        same_partition(ours, sk.labels_)

    def test_noise_points(self, rng):
        x = blobs(rng, [[0, 0], [5, 5]], n_per=50)
        outliers = rng.uniform(10, 20, size=(10, 2)).astype(np.float32)
        x = np.concatenate([x, outliers])
        sk = SkDBSCAN(eps=0.3, min_samples=8).fit(x)
        labels, _ = dbscan_labels(x, 0.3, 8)
        same_partition(relabel_consecutive(np.asarray(labels)), sk.labels_)
        assert np.sum(np.asarray(labels) == -1) >= 10

    def test_all_noise(self, rng):
        x = rng.uniform(0, 100, size=(40, 3)).astype(np.float32)
        labels, core = dbscan_labels(x, 0.01, 3)
        assert np.all(np.asarray(labels) == -1)
        assert not np.any(np.asarray(core))

    def test_single_cluster(self, rng):
        x = rng.normal(0, 0.05, size=(100, 4)).astype(np.float32)
        labels, core = dbscan_labels(x, 0.5, 5)
        assert np.all(np.asarray(labels) == np.asarray(labels)[0])
        assert np.all(np.asarray(core))

    def test_blocked_matches_unblocked(self, rng):
        x = blobs(rng, [[0, 0], [4, 4]], n_per=70)
        l1, _ = dbscan_labels(x, 0.3, 5, block_q=32, block_i=64)
        l2, _ = dbscan_labels(x, 0.3, 5)
        same_partition(
            relabel_consecutive(np.asarray(l1)), relabel_consecutive(np.asarray(l2))
        )

    def test_chain_cluster_long_diameter(self, rng):
        # A long chain: worst case for naive propagation; pointer-jumping
        # must still converge and agree with sklearn.
        t = np.linspace(0, 10, 200)
        x = np.stack([t, np.zeros_like(t)], axis=1).astype(np.float32)
        x += rng.normal(0, 0.005, x.shape).astype(np.float32)
        sk = SkDBSCAN(eps=0.12, min_samples=3).fit(x)
        labels, _ = dbscan_labels(x, 0.12, 3)
        same_partition(relabel_consecutive(np.asarray(labels)), sk.labels_)

    def test_chain_sweep_count_logarithmic(self):
        # Adversarial topology (VERDICT r4 #5): a 4096-point chain has
        # cluster diameter ~n, which the old one-jump-per-sweep diffusion
        # resolved in O(n) expensive eps sweeps. With full path
        # compression between sweeps the EXPENSIVE sweep count is O(log n)
        # — for a pure chain the min label reaches every point's neighbor
        # list after one sweep and compression collapses the chain, so the
        # bound here is a small constant, far under log2(n) = 12.
        n = 4096
        x = np.stack(
            [np.arange(n) * 0.5, np.zeros(n)], axis=1
        ).astype(np.float32)
        labels, core, sweeps = dbscan_labels(
            x, 0.6, 2, return_sweeps=True, block_q=512, block_i=1024
        )
        assert np.all(np.asarray(core))
        assert np.all(np.asarray(labels) == 0)  # one cluster, rep = row 0
        assert int(sweeps) <= 6, int(sweeps)

    def test_two_chains_parity_with_sklearn(self):
        # Two parallel chains separated by > eps: compression must not
        # merge distinct components.
        n = 512
        t = np.arange(n) * 0.5
        a = np.stack([t, np.zeros(n)], axis=1)
        b = np.stack([t, np.full(n, 10.0)], axis=1)
        x = np.concatenate([a, b]).astype(np.float32)
        sk = SkDBSCAN(eps=0.6, min_samples=2).fit(x)
        labels, _ = dbscan_labels(x, 0.6, 2)
        same_partition(relabel_consecutive(np.asarray(labels)), sk.labels_)


class TestEstimator:
    def test_fit_transform(self, rng):
        x = blobs(rng, [[0, 0], [3, 3]])
        model = DBSCAN().setEps(0.3).setMinSamples(8).fit(x)
        sk = SkDBSCAN(eps=0.3, min_samples=8).fit(x)
        same_partition(model.labels_, sk.labels_)
        pred = model.transform(x)
        np.testing.assert_array_equal(pred, model.labels_)

    def test_out_of_sample(self, rng):
        x = blobs(rng, [[0, 0], [5, 5]])
        model = DBSCAN().setEps(0.3).setMinSamples(8).fit(x)
        lab_near0 = model.labels_[np.argmin(np.linalg.norm(x, axis=1))]
        q = np.array([[0.05, 0.0], [50.0, 50.0]], dtype=np.float32)
        pred = model.transform(q)
        assert pred[0] == lab_near0
        assert pred[1] == -1

    def test_dataframe_shim(self, rng):
        x = blobs(rng, [[0, 0], [3, 3]], n_per=30)
        df = DataFrame({"features": list(x)})
        model = DBSCAN().setEps(0.3).setMinSamples(5).fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        np.testing.assert_array_equal(np.asarray(out.select("prediction")), model.labels_)

    def test_param_validation(self):
        with pytest.raises(ValueError):
            DBSCAN().setEps(-1.0)
        with pytest.raises(ValueError):
            DBSCAN().setMinSamples(0)
        with pytest.raises(ValueError):
            DBSCAN().setMetric("manhattan")

    def test_defaults(self):
        est = DBSCAN()
        assert est.getEps() == 0.5
        assert est.getMinSamples() == 5
        assert est.getMetric() == "euclidean"

    def test_read_write(self, tmp_path, rng):
        x = blobs(rng, [[0, 0], [3, 3]], n_per=30)
        model = DBSCAN().setEps(0.3).setMinSamples(5).fit(x)
        path = str(tmp_path / "dbscan_model")
        model.save(path)
        loaded = DBSCANModel.load(path)
        np.testing.assert_array_equal(loaded.labels_, model.labels_)
        np.testing.assert_array_equal(loaded.core_mask_, model.core_mask_)
        np.testing.assert_allclose(loaded.fitted, model.fitted)
        assert loaded.getEps() == 0.3
        assert loaded.getMinSamples() == 5
        # loaded model predicts out-of-sample identically
        q = np.array([[0.0, 0.0]], dtype=np.float32)
        np.testing.assert_array_equal(loaded.transform(q), model.transform(q))

    def test_copy(self, rng):
        x = blobs(rng, [[0, 0]], n_per=30)
        model = DBSCAN().setEps(0.3).fit(x)
        c = model.copy()
        assert c.uid == model.uid
        np.testing.assert_array_equal(c.labels_, model.labels_)
