"""LogisticRegression suite. Oracle: scikit-learn's lbfgs solver — its
objective sum_i logloss + 1/(2C) ||w||^2 equals this framework's
(1/n) sum logloss + regParam/2 ||w||^2 at C = 1/(n*regParam) — plus
optimality-condition (gradient ~ 0) checks that need no external solver."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import LogisticRegression, LogisticRegressionModel
from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


def make_binary(rng, n=400, d=5, sep=1.5):
    w = rng.normal(size=d)
    x = rng.normal(size=(n, d))
    logits = x @ w * sep
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.int64)
    # ensure both classes present
    y[0], y[1] = 0, 1
    return x, y


def make_multiclass(rng, n=600, d=6, c=4):
    centers = rng.normal(size=(c, d)) * 2.0
    y = rng.integers(0, c, size=n)
    x = centers[y] + rng.normal(size=(n, d))
    for j in range(c):
        y[j] = j
    return x, y


def sklearn_logreg(x, y, reg, fit_intercept=True, multi=False):
    from sklearn.linear_model import LogisticRegression as SkLR

    n = len(y)
    c_val = 1.0 / (n * reg) if reg > 0 else 1e12
    clf = SkLR(
        C=c_val,
        fit_intercept=fit_intercept,
        solver="lbfgs",
        max_iter=5000,
        tol=1e-10,
    )
    clf.fit(x, y)
    return clf


class TestBinomial:
    def test_matches_sklearn_regularized(self, rng):
        x, y = make_binary(rng)
        reg = 0.1
        # standardization off => plain L2 in original space == sklearn's
        model = (
            LogisticRegression()
            .setRegParam(reg)
            .setStandardization(False)
            .setTol(1e-10)
            .setMaxIter(500)
            .fit((x, y))
        )
        clf = sklearn_logreg(x, y, reg)
        np.testing.assert_allclose(model.coefficients, clf.coef_[0], atol=2e-4)
        assert model.intercept == pytest.approx(clf.intercept_[0], abs=2e-4)

    def test_gradient_zero_at_solution(self, rng):
        """KKT check: gradient of the objective vanishes at the fit."""
        x, y = make_binary(rng)
        reg = 0.05
        model = (
            LogisticRegression()
            .setRegParam(reg)
            .setStandardization(False)
            .setTol(1e-10)
            .setMaxIter(500)
            .fit((x, y))
        )
        w, b = model.coefficients, model.intercept
        p = 1 / (1 + np.exp(-(x @ w + b)))
        grad_w = x.T @ (p - y) / len(y) + reg * w
        grad_b = np.mean(p - y)
        assert np.abs(grad_w).max() < 1e-6
        assert abs(grad_b) < 1e-6

    def test_standardization_matches_sklearn_on_scaled(self, rng):
        """standardization=True == sklearn trained on scaled features with
        coefficients mapped back."""
        x, y = make_binary(rng)
        x = x * np.array([10.0, 0.1, 1.0, 5.0, 0.5])  # wild scales
        reg = 0.1
        model = (
            LogisticRegression().setRegParam(reg).setTol(1e-10).setMaxIter(500).fit((x, y))
        )
        mu, sd = x.mean(0), x.std(0)
        clf = sklearn_logreg((x - mu) / sd, y, reg)
        coef_back = clf.coef_[0] / sd
        b_back = clf.intercept_[0] - (clf.coef_[0] * mu / sd).sum()
        np.testing.assert_allclose(model.coefficients, coef_back, atol=2e-4)
        assert model.intercept == pytest.approx(b_back, abs=2e-4)

    def test_no_intercept_standardized_matches_sklearn(self, rng):
        """fitIntercept=False must scale but NOT center (no intercept to
        absorb the shift): equals sklearn on x/sigma with coef mapped back."""
        x, y = make_binary(rng)
        x = x + 3.0  # nonzero means make centering bugs visible
        reg = 0.1
        model = (
            LogisticRegression()
            .setFitIntercept(False)
            .setRegParam(reg)
            .setTol(1e-10)
            .setMaxIter(500)
            .fit((x, y))
        )
        sd = x.std(0)
        clf = sklearn_logreg(x / sd, y, reg, fit_intercept=False)
        np.testing.assert_allclose(model.coefficients, clf.coef_[0] / sd, atol=2e-4)
        assert model.intercept == 0.0

    def test_separable_unregularized_predicts_perfectly(self, rng):
        x = rng.normal(size=(100, 3))
        y = (x[:, 0] > 0).astype(np.int64)
        model = LogisticRegression().setMaxIter(200).fit((x, y))
        assert (model.predict(x) == y).mean() == 1.0

    def test_threshold(self, rng):
        x, y = make_binary(rng)
        model = LogisticRegression().setRegParam(0.1).fit((x, y))
        p = model.predictProbability(x)
        assert p.shape == (len(y), 2)
        np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-6)
        model.setThreshold(0.0)
        assert (model.predict(x) == 1).all()
        model.setThreshold(1.0)
        assert (model.predict(x) == 0).all()

    def test_probability_calibration_vs_sklearn(self, rng):
        x, y = make_binary(rng)
        model = (
            LogisticRegression().setRegParam(0.2).setStandardization(False).fit((x, y))
        )
        clf = sklearn_logreg(x, y, 0.2)
        np.testing.assert_allclose(
            model.predictProbability(x), clf.predict_proba(x), atol=1e-3
        )


class TestMultinomial:
    def test_matches_sklearn_multinomial(self, rng):
        x, y = make_multiclass(rng)
        reg = 0.1
        model = (
            LogisticRegression()
            .setRegParam(reg)
            .setStandardization(False)
            .setTol(1e-10)
            .setMaxIter(500)
            .fit((x, y))
        )
        clf = sklearn_logreg(x, y, reg, multi=True)
        # sklearn's multinomial softmax is also over-parameterized + L2 =>
        # same unique solution.
        np.testing.assert_allclose(model.coefficientMatrix, clf.coef_, atol=5e-4)
        np.testing.assert_allclose(model.interceptVector, clf.intercept_, atol=5e-4)

    def test_family_auto_picks_multinomial(self, rng):
        x, y = make_multiclass(rng, c=3)
        model = LogisticRegression().setRegParam(0.1).fit((x, y))
        assert model.numClasses == 3
        assert model.coefficientMatrix.shape == (3, x.shape[1])
        assert model.interceptVector.shape == (3,)
        with pytest.raises(AttributeError):
            model.coefficients

    def test_multinomial_two_class_consistent_with_binomial(self, rng):
        """Unregularized: the 2-class softmax and the sigmoid have the same
        optimum in probability space."""
        x, y = make_binary(rng)
        m_bin = LogisticRegression().setTol(1e-9).fit((x, y))
        m_mult = (
            LogisticRegression().setFamily("multinomial").setTol(1e-9).fit((x, y))
        )
        np.testing.assert_allclose(
            m_bin.predictProbability(x), m_mult.predictProbability(x), atol=1e-3
        )

    def test_multinomial_two_class_l2_relation(self, rng):
        """Under L2 the softmax splits the penalty across both class columns:
        in difference space D = w1 - w0 the softmax objective is
        logloss(D) + (reg/4)||D||^2, so multinomial(2*reg) == binomial(reg)
        in probability space."""
        x, y = make_binary(rng)
        m_bin = (
            LogisticRegression()
            .setRegParam(0.1)
            .setStandardization(False)
            .setTol(1e-10)
            .fit((x, y))
        )
        m_mult = (
            LogisticRegression()
            .setFamily("multinomial")
            .setRegParam(0.2)
            .setStandardization(False)
            .setTol(1e-10)
            .fit((x, y))
        )
        np.testing.assert_allclose(
            m_bin.predictProbability(x), m_mult.predictProbability(x), atol=1e-4
        )
        # and the softmax solution is antisymmetric: w0 = -w1
        cm = m_mult.coefficientMatrix
        np.testing.assert_allclose(cm[0], -cm[1], atol=1e-5)

    def test_unregularized_centered(self, rng):
        x, y = make_multiclass(rng, c=3)
        model = LogisticRegression().setMaxIter(100).fit((x, y))
        # identifiability pivot: class-axis mean of coefficients ~ 0
        np.testing.assert_allclose(
            model.coefficientMatrix.mean(axis=0), 0.0, atol=1e-6
        )

    def test_accuracy_on_separated_clusters(self, rng):
        x, y = make_multiclass(rng, c=4)
        model = LogisticRegression().setRegParam(0.01).fit((x, y))
        assert model.evaluate((x, y))["accuracy"] > 0.8


class TestAPI:
    def test_errors(self, rng):
        x, y = make_binary(rng)
        with pytest.raises(ValueError):
            LogisticRegression().setRegParam(-1.0)
        with pytest.raises(ValueError):
            LogisticRegression().setFamily("gaussian")
        with pytest.raises(ValueError):
            # In-range values route to FISTA (tests/test_elastic_net.py).
            LogisticRegression().setElasticNetParam(2.0)
        with pytest.raises(ValueError):
            LogisticRegression().fit((x, y + 0.5))  # non-integer labels
        with pytest.raises(ValueError):
            LogisticRegression().setFamily("binomial").fit(
                (x, np.arange(len(y)) % 3)
            )

    def test_dataframe_transform_columns(self, rng):
        x, y = make_binary(rng, n=50)
        df = DataFrame({"features": list(x), "label": list(y.astype(float))})
        model = LogisticRegression().setRegParam(0.1).fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        assert "probability" in out.columns
        assert "rawPrediction" in out.columns

    def test_persistence_roundtrip(self, rng, tmp_path):
        x, y = make_multiclass(rng, c=3)
        model = LogisticRegression().setRegParam(0.1).fit((x, y))
        path = str(tmp_path / "lr")
        model.write.save(path)
        loaded = LogisticRegressionModel.load(path)
        np.testing.assert_array_equal(loaded.weights, model.weights)
        np.testing.assert_array_equal(loaded.intercepts, model.intercepts)
        assert loaded.numClasses == model.numClasses
        assert loaded.getRegParam() == 0.1
        np.testing.assert_array_equal(loaded.predict(x), model.predict(x))

    def test_copy_preserves_state(self, rng):
        x, y = make_binary(rng)
        model = LogisticRegression().setRegParam(0.1).fit((x, y))
        clone = model.copy() if hasattr(model, "copy") else model
        np.testing.assert_array_equal(clone.weights, model.weights)


class TestDistributed:
    def test_mesh_fit_matches_single_device(self, rng):
        x, y = make_binary(rng, n=203)  # not divisible by mesh
        mesh = make_mesh((4, 2))
        single = LogisticRegression().setRegParam(0.1).setTol(1e-10).fit((x, y))
        dist = (
            LogisticRegression(mesh=mesh).setRegParam(0.1).setTol(1e-10).fit((x, y))
        )
        np.testing.assert_allclose(dist.coefficients, single.coefficients, atol=1e-5)
        assert dist.intercept == pytest.approx(single.intercept, abs=1e-5)

    def test_mesh_multinomial(self, rng):
        x, y = make_multiclass(rng, n=301, c=3)
        mesh = make_mesh((8, 1))
        single = LogisticRegression().setRegParam(0.1).setTol(1e-10).fit((x, y))
        dist = LogisticRegression(mesh=mesh).setRegParam(0.1).setTol(1e-10).fit((x, y))
        np.testing.assert_allclose(
            dist.coefficientMatrix, single.coefficientMatrix, atol=1e-5
        )


class TestWarmStart:
    def test_resume_reaches_same_optimum_faster(self, rng):
        """A warm start from a near-converged model must reproduce the
        cold optimum in (far) fewer iterations — the resume/path-sweep
        semantics."""
        from spark_rapids_ml_tpu.classification import LogisticRegression

        x = rng.normal(size=(400, 6))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(float)
        cold = LogisticRegression().setMaxIter(200).setTol(1e-9).fit((x, y))
        warm = (
            LogisticRegression()
            .setMaxIter(200)
            .setTol(1e-9)
            .setInitialModel(cold)
            .fit((x, y))
        )
        np.testing.assert_allclose(warm.weights, cold.weights, atol=1e-4)
        assert warm.numIter < cold.numIter / 2

    def test_rejects_elastic_net_path(self, rng):
        from spark_rapids_ml_tpu.classification import LogisticRegression

        x = rng.normal(size=(60, 3))
        y = (x[:, 0] > 0).astype(float)
        cold = LogisticRegression().setMaxIter(20).fit((x, y))
        with pytest.raises(ValueError, match="L-BFGS"):
            (
                LogisticRegression()
                .setRegParam(0.1)
                .setElasticNetParam(0.5)
                .setInitialModel(cold)
                .fit((x, y))
            )

    def test_shape_validation(self, rng):
        from spark_rapids_ml_tpu.classification import LogisticRegression

        x = rng.normal(size=(60, 3))
        y = (x[:, 0] > 0).astype(float)
        cold = LogisticRegression().setMaxIter(5).fit((x, y))
        with pytest.raises(ValueError, match="initial model weights"):
            LogisticRegression().setInitialModel(cold).fit((x[:, :2], y))

    def test_no_intercept_warm_start_drops_stale_intercepts(self, rng):
        """fitIntercept=False never optimizes b — a warm start must not
        leak the initial model's intercepts into predictions (r2 review)."""
        from spark_rapids_ml_tpu.classification import LogisticRegression

        x = rng.normal(size=(200, 4))
        y = (x[:, 0] > 0).astype(float)
        with_b = LogisticRegression().setMaxIter(100).fit((x, y))
        assert abs(with_b.intercept) > 0  # a nonzero intercept to leak
        warm = (
            LogisticRegression()
            .setFitIntercept(False)
            .setMaxIter(100)
            .setInitialModel(with_b)
            .fit((x, y))
        )
        np.testing.assert_allclose(warm.intercepts, 0.0, atol=1e-12)


class TestFusedObjective:
    """Fused one-pass loss+grad (VERDICT r5 #4): the custom_vjp objective
    streams X once per evaluation instead of saving the standardized
    design as an AD residual. Fused and legacy must agree to float
    tolerance on every driver — monolithic, blocked, streaming — and the
    knob must be honored at the estimator layer."""

    def _ops_fit(self, x, y, n_classes, fused, multinomial=False):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.logistic import fit_logistic

        return fit_logistic(
            jnp.asarray(x, jnp.float64),
            jnp.asarray(y),
            jnp.ones(len(y)),
            n_classes,
            reg_param=0.01,
            multinomial=multinomial,
            fused=fused,
        )

    def test_binomial_fused_matches_legacy(self, rng):
        x, y = make_binary(rng)
        f = self._ops_fit(x, y, 2, fused=True)
        g = self._ops_fit(x, y, 2, fused=False)
        np.testing.assert_allclose(f.weights, g.weights, atol=1e-6)
        np.testing.assert_allclose(f.intercepts, g.intercepts, atol=1e-6)
        assert f.n_iter == g.n_iter  # same objective -> same L-BFGS path

    def test_multinomial_fused_matches_legacy(self, rng):
        x, y = make_multiclass(rng)
        f = self._ops_fit(x, y, 4, fused=True, multinomial=True)
        g = self._ops_fit(x, y, 4, fused=False, multinomial=True)
        np.testing.assert_allclose(f.weights, g.weights, atol=1e-6)
        assert f.n_iter == g.n_iter

    @pytest.mark.parametrize("c,fit_intercept", [(1, True), (3, True), (3, False)])
    def test_blocked_value_and_grad_matches_autodiff(
        self, rng, monkeypatch, c, fit_intercept
    ):
        """The analytic one-pass gradient — including the fori_loop
        slide-back blocking — must equal autodiff of the plain objective,
        and the custom_vjp must expose the same gradient to jax.grad."""
        import jax
        import jax.numpy as jnp

        import spark_rapids_ml_tpu.ops.logistic as lg

        n, d = 301, 6
        x = jnp.asarray(rng.normal(size=(n, d)))
        mask = jnp.asarray((rng.uniform(size=n) < 0.9).astype(np.float64))
        if c == 1:
            y_t = jnp.asarray(rng.integers(0, 2, n).astype(np.float64))
        else:
            y_t = jnp.asarray(np.eye(c)[rng.integers(0, c, n)])
        offset = jnp.asarray(rng.normal(size=d))
        scale = jnp.asarray(rng.uniform(0.5, 2.0, d))
        w = jnp.asarray(rng.normal(size=(d, c)) * 0.1)
        b = jnp.asarray(rng.normal(size=c) * 0.1)
        args = (x, y_t, mask, offset, scale, float(mask.sum()), 0.05, c,
                fit_intercept, "highest")

        legacy = lg._make_logistic_loss(*args, fused=False)
        val_ref, grad_ref = jax.value_and_grad(legacy)((w, b))

        # Force the multi-block path: 301 rows over 64-row blocks needs
        # the slide-back + keep-mask for the ragged final block.
        monkeypatch.setattr(lg, "_FUSED_BLOCK_ROWS", 64)
        fused = lg._make_logistic_loss(*args, fused=True)
        val, (gw, gb) = fused.value_and_grad((w, b))
        assert float(val) == pytest.approx(float(val_ref), rel=1e-12)
        np.testing.assert_allclose(gw, grad_ref[0], atol=1e-12)
        np.testing.assert_allclose(gb, grad_ref[1], atol=1e-12)

        # The custom_vjp route (what optax linesearch trial points hit).
        _, grad_vjp = jax.value_and_grad(fused)((w, b))
        np.testing.assert_allclose(grad_vjp[0], gw, atol=1e-12)
        np.testing.assert_allclose(grad_vjp[1], gb, atol=1e-12)

    def test_streaming_fused_matches_legacy(self, rng):
        from spark_rapids_ml_tpu.ops.logistic import (
            fit_logistic_streaming,
            streaming_label_feature_stats,
        )

        x, y = make_binary(rng, n=500)
        blocks = [
            (x[i : i + 120], y[i : i + 120].astype(np.float64))
            for i in range(0, 500, 120)
        ]
        n, mean, sigma, y_max, ok = streaming_label_feature_stats(iter(blocks))
        assert ok and y_max == 1

        def fit(fused):
            return fit_logistic_streaming(
                lambda: iter(blocks), 2, n=n, mean=mean, sigma=sigma,
                reg_param=0.02, fused=fused,
            )

        f, g = fit(True), fit(False)
        np.testing.assert_allclose(f.weights, g.weights, atol=1e-5)
        np.testing.assert_allclose(f.intercepts, g.intercepts, atol=1e-5)

    def test_estimator_knob_parity(self, rng, monkeypatch):
        """TPUML_LOGISTIC_FUSED=0 restores the legacy two-pass objective
        through the public estimator — same fitted model either way."""
        x, y = make_binary(rng)

        def fit(knob):
            monkeypatch.setenv("TPUML_LOGISTIC_FUSED", knob)
            est = LogisticRegression().setRegParam(0.01).setMaxIter(50)
            return est.fit((x, y.astype(np.float64)))

        m1, m0 = fit("1"), fit("0")
        np.testing.assert_allclose(m1.coefficients, m0.coefficients, atol=1e-6)
        assert m1.intercept == pytest.approx(m0.intercept, abs=1e-6)

    def test_elastic_net_fused_matches_legacy(self, rng, monkeypatch):
        """FISTA's smooth part shares the fused builder: the knob must
        not move the elastic-net optimum."""
        x, y = make_binary(rng)

        def fit(knob):
            monkeypatch.setenv("TPUML_LOGISTIC_FUSED", knob)
            est = (
                LogisticRegression()
                .setRegParam(0.05)
                .setElasticNetParam(0.5)
                .setMaxIter(200)
            )
            return est.fit((x, y.astype(np.float64)))

        m1, m0 = fit("1"), fit("0")
        np.testing.assert_allclose(m1.coefficients, m0.coefficients, atol=1e-5)
