"""NearestNeighbors suite. Oracle: numpy/scipy exact distances + argsort."""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.neighbors import NearestNeighbors, NearestNeighborsModel
from spark_rapids_ml_tpu.ops.knn import knn, knn_sharded
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


def numpy_knn(q, x, k, metric="euclidean"):
    d = cdist(q, x, metric="cosine" if metric == "cosine" else "euclidean")
    if metric == "sqeuclidean":
        d = d * d
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


class TestOps:
    def test_exact_vs_numpy(self, rng):
        q = rng.normal(size=(30, 8))
        x = rng.normal(size=(500, 8))
        d, idx = knn(q, x, k=7)
        d_ref, idx_ref = numpy_knn(q, x, 7)
        np.testing.assert_array_equal(idx, idx_ref)
        np.testing.assert_allclose(d, d_ref, atol=1e-10)

    def test_approx_topk_matches_exact_on_cpu(self, rng):
        # lax.approx_min_k is exact on the CPU backend, so the approx path
        # must reproduce the exact kernel bit-for-bit here; on TPU it is
        # the hardware partial-reduce (recall ~0.995 measured, BASELINE.md).
        q = rng.normal(size=(20, 8))
        x = rng.normal(size=(700, 8))
        d_ex, i_ex = knn(q, x, k=6)
        d_ap, i_ap = knn(q, x, k=6, approx=True)
        np.testing.assert_array_equal(np.asarray(i_ap), np.asarray(i_ex))
        np.testing.assert_allclose(np.asarray(d_ap), np.asarray(d_ex), atol=1e-10)

    def test_approx_blocked_masked(self, rng):
        q = rng.normal(size=(8, 4))
        x = rng.normal(size=(300, 4))
        import jax.numpy as jnp

        mask = jnp.asarray((np.arange(300) < 250).astype(np.float64))
        d, idx = knn(q, x, k=5, item_mask=mask, block_items=64, approx=True)
        assert np.all(np.asarray(idx) < 250)  # masked items never surface

    def test_blocked_matches_unblocked(self, rng):
        q = rng.normal(size=(10, 4))
        x = rng.normal(size=(1000, 4))
        d1, i1 = knn(q, x, k=9, block_items=64)
        d2, i2 = knn(q, x, k=9, block_items=100000)
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(d1, d2, atol=1e-12)

    def test_metrics(self, rng):
        q = rng.normal(size=(5, 6))
        x = rng.normal(size=(50, 6))
        for metric in ("euclidean", "sqeuclidean", "cosine"):
            d, idx = knn(q, x, k=3, metric=metric)
            d_ref, idx_ref = numpy_knn(q, x, 3, metric)
            np.testing.assert_array_equal(idx, idx_ref)
            np.testing.assert_allclose(d, d_ref, atol=1e-9)

    def test_item_mask_excludes_padding(self, rng):
        q = rng.normal(size=(4, 3))
        x = rng.normal(size=(20, 3))
        x_pad = np.vstack([x, np.zeros((5, 3))])
        mask = np.concatenate([np.ones(20), np.zeros(5)])
        import jax.numpy as jnp

        d, idx = knn(jnp.asarray(q), jnp.asarray(x_pad), k=5, item_mask=jnp.asarray(mask))
        _, idx_ref = numpy_knn(q, x, 5)
        np.testing.assert_array_equal(idx, idx_ref)
        assert (np.asarray(idx) < 20).all()

    def test_self_query_returns_self_first(self, rng):
        x = rng.normal(size=(40, 5))
        d, idx = knn(x, x, k=1)
        np.testing.assert_array_equal(np.asarray(idx)[:, 0], np.arange(40))
        np.testing.assert_allclose(d, 0.0, atol=1e-6)

    def test_bad_k(self, rng):
        x = rng.normal(size=(10, 2))
        with pytest.raises(ValueError):
            knn(x, x, k=11)
        with pytest.raises(ValueError):
            knn(x, x, k=0)

    def test_sharded_matches_single(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.knn import shard_items

        mesh = make_mesh((4, 2))
        q = rng.normal(size=(12, 6)).astype(np.float64)
        x = rng.normal(size=(203, 6)).astype(np.float64)  # not divisible
        xs, mask = shard_items(x, mesh)
        d2, idx = knn_sharded(jnp.asarray(q), xs, mask, mesh, k=5)
        d_ref, idx_ref = numpy_knn(q, x, 5)
        np.testing.assert_allclose(np.sqrt(np.asarray(d2)), d_ref, atol=1e-8)
        # shard_items pads only at the end, preserving row order: global
        # indices are directly comparable to the unsharded oracle.
        np.testing.assert_array_equal(np.asarray(idx), idx_ref)

    def test_sharded_approx_matches_exact_on_cpu(self, rng):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.knn import shard_items

        mesh = make_mesh((8, 1))
        q = rng.normal(size=(9, 5)).astype(np.float64)
        x = rng.normal(size=(170, 5)).astype(np.float64)
        xs, mask = shard_items(x, mesh)
        d_ex, i_ex = knn_sharded(jnp.asarray(q), xs, mask, mesh, k=4)
        d_ap, i_ap = knn_sharded(jnp.asarray(q), xs, mask, mesh, k=4, approx=True)
        np.testing.assert_array_equal(np.asarray(i_ap), np.asarray(i_ex))
        np.testing.assert_allclose(np.asarray(d_ap), np.asarray(d_ex), atol=1e-10)


class TestEstimator:
    def test_fit_kneighbors(self, rng):
        items = rng.normal(size=(300, 10))
        queries = rng.normal(size=(20, 10))
        model = NearestNeighbors().setK(6).fit(items)
        d, idx = model.kneighbors(queries)
        d_ref, idx_ref = numpy_knn(queries, items, 6)
        np.testing.assert_array_equal(idx, idx_ref)
        np.testing.assert_allclose(d, d_ref, atol=1e-9)

    def test_k_override(self, rng):
        items = rng.normal(size=(50, 4))
        model = NearestNeighbors().setK(3).fit(items)
        d, idx = model.kneighbors(items[:5], k=10)
        assert d.shape == (5, 10)

    def test_id_mapping(self, rng):
        items = rng.normal(size=(40, 3))
        ids = np.array([f"row{i}" for i in range(40)])
        df = DataFrame({"features": list(items), "rid": list(ids)})
        model = NearestNeighbors().setK(2).setIdCol("rid").fit(df)
        d, out_ids = model.kneighbors_ids(items[:3])
        _, idx_ref = numpy_knn(items[:3], items, 2)
        np.testing.assert_array_equal(out_ids, ids[idx_ref])

    def test_dataframe_transform(self, rng):
        items = rng.normal(size=(30, 4))
        df = DataFrame({"features": list(items)})
        model = NearestNeighbors().setK(3).fit(df)
        out = model.transform(df)
        assert "knn_indices" in out.columns and "knn_distances" in out.columns

    def test_errors(self, rng):
        items = rng.normal(size=(10, 2))
        with pytest.raises(ValueError):
            NearestNeighbors().setMetric("manhattan")
        with pytest.raises(ValueError):
            NearestNeighbors().setK(11).fit(items)
        model = NearestNeighbors().setK(3).fit(items)
        with pytest.raises(ValueError):
            model.kneighbors(items, k=0)
        # idCol set but not extractable must raise, not silently fall back
        # to positional indices.
        with pytest.raises(ValueError):
            NearestNeighbors().setIdCol("rid").fit(items)

    def test_pandas_fit_and_query(self, rng):
        import pandas as pd

        items = rng.normal(size=(40, 3))
        df = pd.DataFrame(
            {"features": list(items), "rid": [f"r{i}" for i in range(40)]}
        )
        model = NearestNeighbors().setK(2).setIdCol("rid").fit(df)
        d, ids = model.kneighbors_ids(df)
        _, idx_ref = numpy_knn(items, items, 2)
        np.testing.assert_array_equal(ids, np.asarray(df["rid"])[idx_ref])
        out = model.transform(df)
        assert "knn_indices" in out.columns

    def test_persistence_roundtrip(self, rng, tmp_path):
        items = rng.normal(size=(25, 5))
        ids = np.arange(100, 125)
        df = DataFrame({"features": list(items), "rid": list(ids)})
        model = NearestNeighbors().setK(4).setIdCol("rid").fit(df)
        path = str(tmp_path / "nn")
        model.write.save(path)
        loaded = NearestNeighborsModel.load(path)
        np.testing.assert_allclose(loaded.items, model.items)
        np.testing.assert_array_equal(loaded.ids, model.ids)
        assert loaded.getK() == 4
        d1, i1 = model.kneighbors(items[:4])
        d2, i2 = loaded.kneighbors(items[:4])
        np.testing.assert_array_equal(i1, i2)

    def test_mesh_model_matches_single(self, rng):
        mesh = make_mesh((8, 1))
        items = rng.normal(size=(101, 7))
        queries = rng.normal(size=(9, 7))
        single = NearestNeighbors().setK(4).fit(items)
        dist = NearestNeighbors(mesh=mesh).setK(4).fit(items)
        d1, _ = single.kneighbors(queries)
        d2, _ = dist.kneighbors(queries)
        np.testing.assert_allclose(np.sort(d1, axis=1), np.sort(d2, axis=1), atol=1e-8)
