"""Multi-process distributed execution: N OS processes, each owning a
slice of the data and a share of the (virtual CPU) devices, brought up via
jax.distributed and fitting through the ordinary estimator API — the
executor-per-chip deployment shape (VERDICT r1 missing item 2; the
reference's per-partition compute + cross-process reduce,
RapidsRowMatrix.scala:170-201)."""

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.parallel.mesh import make_mesh, shard_rows, shard_rows_from_partitions

REPO = Path(__file__).resolve().parents[1]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _concat_oracle(x, mesh):
    """Independent concat-then-pad placement oracle (shard_rows itself is
    now a wrapper over the partition version, so the oracle is built from
    raw numpy here)."""
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    n, d = x.shape
    dp, mp = mesh.shape[DATA_AXIS], mesh.shape[MODEL_AXIS]
    xp = np.pad(x, ((0, (-n) % dp), (0, (-d) % mp)))
    mask = np.zeros(xp.shape[0], dtype=x.dtype)
    mask[:n] = 1.0
    return xp, mask


class TestShardRowsFromPartitions:
    """The no-host-concat placement must be indistinguishable from a
    concat-then-shard placement."""

    def test_matches_concat_oracle(self, rng):
        x = rng.normal(size=(1003, 12))
        parts = [x[:100], x[100:700], x[700:]]
        mesh = make_mesh()
        xs, mask, n = shard_rows_from_partitions(parts, mesh)
        exp_x, exp_mask = _concat_oracle(x, mesh)
        assert n == 1003
        np.testing.assert_array_equal(np.asarray(xs), exp_x)
        np.testing.assert_array_equal(np.asarray(mask), exp_mask)

    def test_2d_mesh_with_feature_padding(self, rng):
        x = rng.normal(size=(65, 7))  # d=7 pads to 8 on a model axis of 2
        parts = [x[:30], x[30:]]
        mesh = make_mesh((4, 2))
        xs, mask, _ = shard_rows_from_partitions(parts, mesh)
        exp_x, exp_mask = _concat_oracle(x, mesh)
        np.testing.assert_array_equal(np.asarray(xs), exp_x)
        np.testing.assert_array_equal(np.asarray(mask), exp_mask)

    def test_wrapper_shard_rows_identical(self, rng):
        x = rng.normal(size=(37, 5))
        mesh = make_mesh()
        xs, mask, n = shard_rows(x, mesh)
        exp_x, exp_mask = _concat_oracle(x, mesh)
        assert n == 37
        np.testing.assert_array_equal(np.asarray(xs), exp_x)
        np.testing.assert_array_equal(np.asarray(mask), exp_mask)

    def test_mesh_pca_fit_unchanged(self, rng):
        from spark_rapids_ml_tpu.feature import PCA
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        x = rng.normal(size=(500, 6)) * np.linspace(1, 2, 6)
        parts = [x[:200], x[200:]]
        m_mesh = PCA(mesh=make_mesh()).setK(2).fit(parts)
        m_single = PCA().setK(2).fit(x)
        assert_components_close(m_mesh.pc, m_single.pc, 1e-9)


class TestMultiProcess:
    def _run(self, n_proc, extra_env=None):
        port = _free_port()
        procs = []
        for pid in range(n_proc):
            env = {
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "TPUML_COORDINATOR": f"127.0.0.1:{port}",
                "TPUML_NUM_PROCESSES": str(n_proc),
                "TPUML_PROCESS_ID": str(pid),
                **(extra_env or {}),  # extra_env wins (e.g. x64 off)
            }
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(REPO / "tests" / "multiproc_pca_worker.py")],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                    cwd=str(REPO),
                )
            )
        outs = [p.communicate(timeout=300) for p in procs]
        for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {pid} failed:\n{err[-3000:]}"
            assert f"OK process {pid}/{n_proc}" in out, out

    # The heaviest gang spawns (3-8 python+jax bring-ups each, fully
    # serialized on a single-core host) are slow-marked: tier-1 keeps the
    # 2/3-process streaming + empty-executor + x64-off cases plus the
    # real 2-process gang fit in tests/test_gang_fit.py, and the CI
    # "Multi-process fits" step runs this whole file unmarked.
    @pytest.mark.slow
    def test_4_process_distributed_pca(self):
        """4 OS processes x 2 virtual CPU devices = an 8-way data-parallel
        fit through PCA(mesh=...).fit(local_blocks), checked against the
        full-dataset oracle in every process."""
        self._run(4)

    @pytest.mark.slow
    def test_4x2_data_model_mesh(self):
        """VERDICT r2 #4: a 4-process x 2-device fit on a (4, 2)
        data x model mesh — features sharded across each process's own
        devices, rows across processes — must match the oracle in every
        process. d=13 does NOT divide the model axis, so the zero-pad +
        strip path is genuinely exercised."""
        self._run(
            4,
            extra_env={"TPUML_TEST_MESH_SHAPE": "4,2", "TPUML_TEST_D": "13"},
        )

    @pytest.mark.slow
    def test_streaming_psum_merge(self):
        """Streamed multi-process fit with the device-collective moment
        merge (merge='auto' routes non-dd + mesh to the psum backend)."""
        self._run(
            3,
            extra_env={
                "TPUML_TEST_STREAMING": "1",
                "TPUML_TEST_MESH_SHAPE": "6,1",
            },
        )

    @pytest.mark.slow  # ~9 s spawn; runs full-file in CI's Multi-process step
    def test_empty_executor_does_not_strand_peers(self):
        """One process holds zero local rows; the fit must still complete
        on every process with the identical oracle-checked model (the
        asymmetric-failure/deadlock case)."""
        self._run(3, extra_env={"TPUML_TEST_EMPTY_LAST": "1"})

    @pytest.mark.slow  # ~8 s spawn; runs full-file in CI's Multi-process step
    def test_streaming_executors(self):
        """Each process STREAMS its local rows (one-shot block generator):
        per-process shifted scans merge through one allgather of the
        O(d^2) moments — the full executor deployment loop, checked
        against the full-dataset oracle in every process."""
        self._run(3, extra_env={"TPUML_TEST_STREAMING": "1"})

    @pytest.mark.slow  # ~8 s spawn; runs full-file in CI's Multi-process step
    def test_streaming_with_empty_executor(self):
        self._run(
            3,
            extra_env={
                "TPUML_TEST_STREAMING": "1",
                "TPUML_TEST_EMPTY_LAST": "1",
            },
        )

    @pytest.mark.slow
    def test_worker_death_fails_fast_on_survivors_no_hang(self):
        """VERDICT r2 #7 fault path: one executor hard-dies mid-stream
        (os._exit inside its block generator, before the merge
        collective). Survivors must FAIL FAST within the tightened
        heartbeat window — no hang, no wrong model. jax's coordination
        service propagates the peer death as a fatal distributed-runtime
        error ('task died' / 'stopped sending heartbeats') that
        terminates the surviving processes; a Python-level raise (rc 3)
        is also accepted if the collective errors before the fail-fast
        shutdown lands. The recovery recipe (relaunch-and-refit, the
        Spark barrier-task retry analogue) is documented in
        docs/PARITY.md §5."""
        import time

        port = _free_port()
        n_proc = 3
        procs = []
        for pid in range(n_proc):
            env = {
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "TPUML_COORDINATOR": f"127.0.0.1:{port}",
                "TPUML_NUM_PROCESSES": str(n_proc),
                "TPUML_PROCESS_ID": str(pid),
                "TPUML_TEST_FAULT_VICTIM": "2",
                "TPUML_HEARTBEAT_TIMEOUT": "10",
            }
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(REPO / "tests" / "multiproc_pca_worker.py")],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                    cwd=str(REPO),
                )
            )
        t0 = time.monotonic()
        # Bounded wait: detection rides the 10 s heartbeat — a hang past
        # 120 s is the failure mode this test exists to rule out. The
        # finally-kill keeps a genuine hang from leaking three spinning
        # jax workers onto this 1-CPU box.
        try:
            outs = [p.communicate(timeout=120) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        elapsed = time.monotonic() - t0
        assert procs[2].returncode == 42, outs[2][1][-500:]  # victim died
        for pid in (0, 1):
            rc = procs[pid].returncode
            out, err = outs[pid]
            assert rc not in (0, 42), f"survivor {pid} rc={rc}\n{err[-2000:]}"
            clear_error = (
                "SURVIVOR_RAISED" in out  # collective raised first
                or "task died" in err  # fail-fast shutdown
                or "unhealthy" in err
                or "stopped sending heartbeats" in err
            )
            assert clear_error, f"survivor {pid} died without a clear error:\n{err[-2000:]}"
        assert elapsed < 110, f"survivors took {elapsed:.0f}s — effectively a hang"

    @pytest.mark.slow
    def test_8_process_north_star_8x1(self):
        """VERDICT r4 #4: the EXACT north-star software topology — 8
        processes, one (virtual) device each, streamed per-executor
        blocks, psum moment merge on an (8, 1) mesh. The BASELINE config
        5 ×8 projection's software preconditions (bring-up, wire format,
        collective schedule at 8 members) all execute here; only the
        chips are virtual."""
        self._run(
            8,
            extra_env={
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "TPUML_TEST_STREAMING": "1",
                "TPUML_TEST_MESH_SHAPE": "8,1",
            },
        )

    @pytest.mark.slow
    def test_8_device_north_star_4x2_streamed(self):
        """The same 8 mesh members on a (4, 2) data x model mesh — rows
        over 4 executor groups, features over 2 — STREAMED, with d=13
        exercising the model-axis zero-pad + strip path. Runs as 4
        processes x 2 devices: the placement layer requires the model
        axis to divide each process's local device count (a process's
        addressable shards must span whole mesh rows —
        parallel/distributed.shard_rows_process_local), so a
        model-sharded deployment pairs chips within an executor, it does
        not split one chip's features across executors."""
        self._run(
            4,
            extra_env={
                "TPUML_TEST_STREAMING": "1",
                "TPUML_TEST_MESH_SHAPE": "4,2",
                "TPUML_TEST_D": "13",
            },
        )

    @pytest.mark.slow  # ~5 s spawn; runs full-file in CI's Multi-process step
    def test_streaming_without_x64(self):
        """The real-TPU configuration: fp32 compute, and the fp64 moment
        payload crosses the allgather as a double-float (hi, lo) pair —
        the wire must not silently squash it (r2 review)."""
        self._run(
            2,
            extra_env={
                "TPUML_TEST_STREAMING": "1",
                "TPUML_TEST_NO_X64": "1",
                "JAX_ENABLE_X64": "0",
            },
        )


class TestProcessLocalStreamingCovariance:
    """Direct unit coverage of the per-executor streaming merge (the
    process_count()==1 degenerate case exercises the same code: local
    scan, hi/lo or fp64 wire, ShiftedMoments merge)."""

    def test_matches_oracle_highest(self, rng):
        from spark_rapids_ml_tpu.parallel.distributed import (
            streaming_covariance_process_local,
        )

        x = rng.normal(size=(4_000, 6)) * np.linspace(1, 2, 6) + 1e3
        gen = (x[i : i + 512] for i in range(0, 4_000, 512))
        mean, cov, n = streaming_covariance_process_local(gen)
        assert n == 4_000
        np.testing.assert_allclose(mean, x.mean(axis=0), rtol=1e-12)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-6)

    def test_matches_oracle_dd(self, rng):
        from spark_rapids_ml_tpu.parallel.distributed import (
            streaming_covariance_process_local,
        )

        x = 1e4 * (1 + np.arange(5)) + np.linspace(1, 2, 5) * rng.normal(
            size=(4_000, 5)
        )
        gen = (x[i : i + 700] for i in range(0, 4_000, 700))
        _, cov, _ = streaming_covariance_process_local(gen, precision="dd")
        assert np.max(np.abs(cov - np.cov(x, rowvar=False))) < 1e-5
