"""Host-streamed kNN/ANN indexes (VERDICT r3 #4): item sets beyond HBM
stream through a running top-k merge; results must match the resident
path exactly (the merge math is shared)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors, NearestNeighbors
from spark_rapids_ml_tpu.ops.knn import knn, knn_host_streamed


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(17)
    items = rng.normal(size=(3000, 24)).astype(np.float32)
    queries = rng.normal(size=(50, 24)).astype(np.float32)
    return items, queries


def _blocks_of(items, bs):
    return [items[i : i + bs] for i in range(0, items.shape[0], bs)]


class TestStreamedOps:
    @pytest.mark.parametrize("metric", ["euclidean", "sqeuclidean", "cosine"])
    def test_matches_resident(self, corpus, metric):
        items, queries = corpus
        d_r, i_r = knn(jnp.asarray(queries), jnp.asarray(items), 7, metric=metric)
        d_s, i_s = knn_host_streamed(
            jnp.asarray(queries), _blocks_of(items, 700), 7, metric=metric
        )
        assert np.array_equal(np.asarray(i_r), np.asarray(i_s))
        assert np.allclose(np.asarray(d_r), np.asarray(d_s), atol=1e-5)

    def test_ragged_final_block_and_generator_source(self, corpus):
        items, queries = corpus
        d_r, i_r = knn(jnp.asarray(queries), jnp.asarray(items), 5, metric="sqeuclidean")
        gen = (b for b in _blocks_of(items, 999))  # one-shot is fine at the ops level
        d_s, i_s = knn_host_streamed(jnp.asarray(queries), gen, 5, metric="sqeuclidean")
        assert np.array_equal(np.asarray(i_r), np.asarray(i_s))

    def test_k_exceeds_count_raises(self, corpus):
        _, queries = corpus
        with pytest.raises(ValueError, match="exceeds"):
            knn_host_streamed(
                jnp.asarray(queries), [np.ones((3, 24), np.float32)], 5
            )

    def test_approx_with_blocks_smaller_than_k(self, corpus):
        # Regression (r4 review): approx_min_k on a block narrower than k
        # used to crash; small blocks must merge whole instead.
        items, queries = corpus
        d_r, i_r = knn(
            jnp.asarray(queries), jnp.asarray(items[:70]), 10,
            metric="sqeuclidean",
        )
        d_s, i_s = knn_host_streamed(
            jnp.asarray(queries), _blocks_of(items[:70], 7), 10,
            metric="sqeuclidean", approx=True,
        )
        # approx per-block selection is exact on CPU; order may differ
        # only among equal distances.
        assert np.allclose(np.sort(d_s, axis=1), np.sort(d_r, axis=1), atol=1e-5)


class TestStreamedEstimators:
    def test_nn_streamed_matches_resident(self, corpus):
        items, queries = corpus

        def factory():
            return iter(_blocks_of(items, 800))

        streamed = NearestNeighbors().setK(6).fit(factory)
        resident = NearestNeighbors().setK(6).fit(items.astype(np.float64))
        d_s, i_s = streamed.kneighbors(queries.astype(np.float64))
        d_r, i_r = resident.kneighbors(queries.astype(np.float64))
        assert np.array_equal(i_s, i_r)
        assert np.allclose(d_s, d_r, atol=1e-5)

    def test_ann_streamed_brute_approx_matches(self, corpus):
        items, queries = corpus

        def factory():
            return iter(_blocks_of(items, 800))

        streamed = (
            ApproximateNearestNeighbors()
            .setK(6)
            .setAlgorithm("brute_approx")
            .fit(factory)
        )
        resident = (
            ApproximateNearestNeighbors()
            .setK(6)
            .setAlgorithm("brute_approx")
            .fit(items.astype(np.float64))
        )
        d_s, i_s = streamed.kneighbors(queries.astype(np.float64))
        d_r, i_r = resident.kneighbors(queries.astype(np.float64))
        # approx_min_k is exact on CPU; block boundaries differ between
        # the streamed (800) and resident (auto) paths, so compare sets.
        agree = np.mean([
            len(set(i_s[q]) & set(i_r[q])) / 6 for q in range(i_s.shape[0])
        ])
        assert agree > 0.99

    def test_one_shot_generator_rejected(self, corpus):
        items, _ = corpus
        gen = (b for b in _blocks_of(items, 500))
        with pytest.raises(ValueError, match="RE-ITERABLE"):
            NearestNeighbors().setK(3).fit(gen)

    def test_ivf_streamed_rejected(self, corpus):
        items, _ = corpus

        def factory():
            return iter(_blocks_of(items, 500))

        with pytest.raises(ValueError, match="brute"):
            ApproximateNearestNeighbors().setAlgorithm("ivfflat").fit(factory)

    def test_streamed_model_does_not_persist(self, corpus, tmp_path):
        items, _ = corpus

        def factory():
            return iter(_blocks_of(items, 500))

        model = NearestNeighbors().setK(3).fit(factory)
        with pytest.raises(ValueError, match="persist"):
            model.write.overwrite().save(str(tmp_path / "m"))

    def test_streamed_model_does_not_pickle(self, corpus):
        # ADVICE r4: cloudpickling a streamed model (Spark broadcast, UDF
        # closure) must fail with the same clear contract as _save_impl,
        # not ship the whole item set through the iterator factory.
        import pickle

        items, _ = corpus

        def factory():
            return iter(_blocks_of(items, 500))

        for est in (
            NearestNeighbors().setK(3),
            ApproximateNearestNeighbors().setK(3).setAlgorithm("brute"),
        ):
            model = est.fit(factory)
            with pytest.raises(ValueError, match="pickle"):
                pickle.dumps(model)
