"""Pipeline fusion contract suite (`spark_rapids_ml_tpu/pipeline_fusion/`).

The claims under test:

- FUSED == STAGED, bitwise, for every fusable 2-/3-stage chain: the
  composite program and the stage-at-a-time loop are the same math.
- An unfusable chain degrades LOUDLY (one structured
  ``FusionFallbackWarning``) and CORRECTLY (staged results).
- The fused program's ledgered bytes are STRICTLY below the staged sum
  (each stage's transform-contract selection runs inside the program, so
  dead stage outputs are never materialized) — the whole point.
- A fused pipeline is a first-class servable: it registers, warms,
  round-trips by path alone, and hot-swaps version-atomically under
  threaded load.
- ``Pipeline.fit`` / CrossValidator / TrainValidationSplit run pipelines
  on device-resident data with no host hop, and fit the same models.
"""

import os
import threading
from contextlib import contextmanager

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import (
    LogisticRegression,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.pipeline import Pipeline, PipelineModel
from spark_rapids_ml_tpu.pipeline_fusion import (
    CompositeSignature,
    FusionFallbackWarning,
    fuse_pipeline_stages,
)
from spark_rapids_ml_tpu.regression import (
    LinearRegression,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.serving.server import ServingRuntime
from spark_rapids_ml_tpu.tuning import (
    CrossValidator,
    ParamGridBuilder,
    TrainValidationSplit,
    _device_fold_prep,
)

D = 12  # input feature width shared by the chain fixtures


@contextmanager
def fusion_off():
    """Force the staged path (the in-test reference for parity checks)."""
    prev = os.environ.get("TPUML_PIPELINE_FUSION")
    os.environ["TPUML_PIPELINE_FUSION"] = "off"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("TPUML_PIPELINE_FUSION", None)
        else:
            os.environ["TPUML_PIPELINE_FUSION"] = prev


@pytest.fixture
def data(rng):
    x = rng.normal(size=(96, D)).astype(np.float64)
    y = (x[:, 0] + x[:, 1] - x[:, 2] > 0).astype(np.int64)
    return x, y


CHAINS = {
    "pca-kmeans": lambda: [PCA().setK(4), KMeans().setK(3).setSeed(7)],
    "pca-logistic": lambda: [PCA().setK(4), LogisticRegression().setMaxIter(25)],
    "pca-linreg": lambda: [PCA().setK(4), LinearRegression()],
    "pca-rf-classifier": lambda: [
        PCA().setK(4),
        RandomForestClassifier().setNumTrees(5).setMaxDepth(4).setSeed(3),
    ],
    "pca-rf-regressor": lambda: [
        PCA().setK(4),
        RandomForestRegressor().setNumTrees(5).setMaxDepth(4).setSeed(3),
    ],
    "pca-pca-kmeans": lambda: [
        PCA().setK(6),
        PCA().setK(3),
        KMeans().setK(3).setSeed(7),
    ],
}


class TestFusedParity:
    """Fused transform == staged transform, bitwise, per fusable chain."""

    @pytest.mark.parametrize("chain", sorted(CHAINS), ids=sorted(CHAINS))
    def test_chain_parity(self, chain, data):
        x, y = data
        model = Pipeline(stages=CHAINS[chain]()).fit((x, y))
        fused = np.asarray(model.transform(x))
        with fusion_off():
            staged = np.asarray(model.transform(x))
        np.testing.assert_array_equal(fused, staged)
        assert fused.shape[0] == x.shape[0]

    def test_fused_path_engages(self, data):
        from spark_rapids_ml_tpu.utils.tracing import counter_value

        x, y = data
        model = Pipeline(stages=CHAINS["pca-logistic"]()).fit((x, y))
        before = counter_value("pipeline.fusion.fused")
        model.transform(x)
        assert counter_value("pipeline.fusion.fused") == before + 1

    def test_device_array_in_device_array_out(self, data):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.core.data import is_device_array

        x, y = data
        model = Pipeline(stages=CHAINS["pca-logistic"]()).fit((x, y))
        xd = jnp.asarray(x)
        out = model.transform(xd)
        assert is_device_array(out)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(model.transform(x)))

    def test_serving_signature_is_composite(self, data):
        x, y = data
        model = Pipeline(stages=CHAINS["pca-logistic"]()).fit((x, y))
        sig = model.serving_signature()
        assert isinstance(sig, CompositeSignature)
        assert sig.n_features == D
        assert sig.stage_names == ("pca.transform", "logreg.predict")
        assert sig.name == "fused:pca.transform+logreg.predict"
        # Every stage's static config is part of the composite program
        # key, stage-prefixed.
        assert any(k.startswith("s0_") for k in sig.static)
        assert any(k.startswith("s1_") for k in sig.static)

    def test_composite_kernel_identity_is_stable(self, data):
        """Two signature builds share ONE kernel object — the AOT program
        cache keys on function identity; a fresh closure per call would
        recompile every serve."""
        x, y = data
        model = Pipeline(stages=CHAINS["pca-logistic"]()).fit((x, y))
        assert model.serving_signature().kernel is model.serving_signature().kernel


class TestFallback:
    """Unfusable chains degrade loudly and correctly."""

    class _Opaque:
        """A transformer with no serving_signature()."""

        uid = "opaque-stage"

        def transform(self, x):
            return np.asarray(x) * 1.0

    def test_non_signature_stage_warns_and_matches_staged(self, data):
        x, y = data
        pca = PCA().setK(4).fit(x)
        model = PipelineModel("pm-opaque", [pca, self._Opaque()])
        with pytest.warns(FusionFallbackWarning) as rec:
            out = np.asarray(model.transform(x))
        w = rec[0].message
        assert w.pipeline == "pm-opaque"
        assert w.stage == 1
        assert "serving_signature" in w.reason
        np.testing.assert_array_equal(
            out, self._Opaque().transform(pca.transform(x))
        )

    def test_width_mismatch_warns(self, data):
        x, y = data
        pca = PCA().setK(3).fit(x)  # emits width 3
        lr = LogisticRegression().setMaxIter(5).fit((x[:, :5], y))  # wants 5
        with pytest.warns(FusionFallbackWarning) as rec:
            assert fuse_pipeline_stages([pca, lr], pipeline="pm-width") is None
        assert "width" in rec[0].message.reason
        assert rec[0].message.stage == 0

    def test_strict_signature_raises(self, data):
        x, _ = data
        pca = PCA().setK(4).fit(x)
        model = PipelineModel("pm-strict", [pca, self._Opaque()])
        with pytest.raises(TypeError, match="not fusable"):
            model.serving_signature()

    def test_off_knob_never_fuses(self, data, monkeypatch):
        from spark_rapids_ml_tpu.utils.tracing import counter_value

        x, y = data
        model = Pipeline(stages=CHAINS["pca-kmeans"]()).fit((x, y))
        monkeypatch.setenv("TPUML_PIPELINE_FUSION", "off")
        before = counter_value("pipeline.fusion.fused")
        model.transform(x)
        assert counter_value("pipeline.fusion.fused") == before

    def test_dataframe_keeps_column_contract(self, rng):
        """DataFrames NEVER take the fused path: each stage appends its
        output column (the Spark contract)."""
        from spark_rapids_ml_tpu.core.data import DataFrame

        x = rng.normal(size=(40, D))
        df = DataFrame({"features": list(x)})
        model = Pipeline(
            stages=[
                PCA().setK(3).setInputCol("features").setOutputCol("pca"),
                KMeans().setK(3).setFeaturesCol("pca").setSeed(0),
            ]
        ).fit(df)
        out = model.transform(df)
        assert "pca" in out.columns and "prediction" in out.columns


class TestLedgerProof:
    """The acceptance criterion: fused bytes STRICTLY below staged sum,
    with bit parity, in the same test."""

    def test_fused_bytes_strictly_below_staged_sum(self, data):
        from spark_rapids_ml_tpu.core.serving import clear_program_cache
        from spark_rapids_ml_tpu.observability import costs

        x, y = data
        model = Pipeline(
            stages=[PCA().setK(7), LogisticRegression().setMaxIter(25)]
        ).fit((x, y))
        ledger = costs.configure(enable=True)
        try:
            clear_program_cache()
            with fusion_off():
                staged = np.asarray(model.transform(x))
            fused = np.asarray(model.transform(x))
            np.testing.assert_array_equal(fused, staged)

            doc = ledger.snapshot()
            fused_bytes = staged_bytes = 0
            for e in doc["entries"]:
                fam = e.get("family") or ""
                b = int(e.get("bytes_accessed") or 0)
                if fam.startswith("fused:"):
                    fused_bytes += b
                elif fam in ("pca.transform", "logreg.predict"):
                    staged_bytes += b
            assert fused_bytes > 0 and staged_bytes > 0
            # The logistic forward kernel materializes (labels, probs,
            # raw); the pipeline contract exposes labels only. In the
            # composite the selection happens in-program, so the unused
            # outputs are dead code to XLA: strictly fewer bytes than
            # the staged stages' total.
            assert fused_bytes < staged_bytes
        finally:
            costs.reset_for_tests()


class TestServingIntegration:
    """A fused pipeline is one versioned servable."""

    def test_register_warm_submit(self, data):
        x, y = data
        model = Pipeline(stages=CHAINS["pca-logistic"]()).fit((x, y))
        rt = ServingRuntime()
        try:
            mv = rt.register("pipe", model, alias="prod", warm_buckets=(8, 32))
            assert isinstance(mv.signature, CompositeSignature)
            out = rt.submit("pipe@prod", x[:20]).result(timeout=60)
            np.testing.assert_array_equal(
                np.asarray(out), np.asarray(model.transform(x[:20]))
            )
        finally:
            rt.close()

    def test_registry_load_by_path_alone(self, data, tmp_path):
        """satellite: ModelRegistry.load with model_cls omitted resolves
        the class from the persisted metadata — a saved PipelineModel
        round-trips into the registry by path alone."""
        x, y = data
        model = Pipeline(stages=CHAINS["pca-logistic"]()).fit((x, y))
        path = str(tmp_path / "fused_pipe")
        model.save(path)
        rt = ServingRuntime()
        try:
            mv = rt.load("pipe", path, alias="prod", warm_buckets=(8,))
            assert isinstance(mv.model, PipelineModel)
            out = rt.submit("pipe@prod", x[:16]).result(timeout=60)
            np.testing.assert_array_equal(
                np.asarray(out), np.asarray(model.transform(x[:16]))
            )
        finally:
            rt.close()

    def test_hot_swap_fused_pipeline_version_pure(self, data):
        """Swap prod from fused v1 to fused v2 under threaded load: every
        answer is bitwise v1's or v2's, the freshness table shows both
        versions serving with v2 strictly after v1 first appears."""
        from tools.tpuml_loadgen import FreshnessTable

        x, y = data
        m1 = Pipeline(stages=[PCA().setK(4), KMeans().setK(3).setSeed(7)]).fit((x, y))
        m2 = Pipeline(stages=[PCA().setK(5), KMeans().setK(4).setSeed(11)]).fit((x, y))
        exp1 = np.asarray(m1.transform(x))
        exp2 = np.asarray(m2.transform(x))

        rt = ServingRuntime(max_batch=16, max_delay_ms=2.0)
        fresh = FreshnessTable()
        collected = []
        lock = threading.Lock()
        try:
            v1 = rt.register("pipe", m1, alias="prod")

            def worker(tid):
                local = []
                for j in range(20):
                    i = (tid * 20 + j) % x.shape[0]
                    fut = rt.submit("pipe@prod", x[i])
                    out = np.asarray(fut.result(timeout=60))
                    fresh.note(fut)
                    local.append((i, out))
                with lock:
                    collected.extend(local)

            threads = [
                threading.Thread(target=worker, args=(t,)) for t in range(4)
            ]
            for t in threads:
                t.start()
            v2 = rt.register("pipe", m2)
            rt.set_alias("pipe", "prod", v2.version)
            for t in threads:
                t.join()
        finally:
            rt.close()

        for i, out in collected:
            ok = np.array_equal(out, exp1[i : i + 1]) or np.array_equal(
                out, exp2[i : i + 1]
            )
            assert ok, f"row {i} matches neither pipeline version"
        report = {r["version"]: r for r in fresh.report()}
        assert v2.version in report, "swap target never served"
        if v1.version in report:  # v1 may drain before any completion lands
            assert (
                report[v1.version]["first_seen_s"]
                <= report[v2.version]["first_seen_s"]
            )


class TestFitFusion:
    """Fit-side fusion: device-resident datasets through whole pipelines."""

    def test_fit_device_ingest_matches_host_fit(self, data, monkeypatch):
        x, y = data
        pipe = Pipeline(stages=[PCA().setK(4), LogisticRegression().setMaxIter(25)])
        fused_model = pipe.fit((x, y))
        monkeypatch.setenv("TPUML_PIPELINE_FUSION_FIT", "off")
        host_model = pipe.fit((x, y))
        with fusion_off():
            np.testing.assert_array_equal(
                np.asarray(fused_model.transform(x)),
                np.asarray(host_model.transform(x)),
            )

    def test_pipeline_is_device_foldable(self, data):
        x, y = data
        pipe = Pipeline(stages=[PCA().setK(3), LogisticRegression()])
        assert pipe._device_foldable
        prep = _device_fold_prep((x, y), pipe)
        assert prep is not None
        xs, ys = prep.slice(np.arange(16))
        from spark_rapids_ml_tpu.core.data import is_device_array

        assert is_device_array(xs) and is_device_array(ys)

    def test_opaque_stage_disables_device_folds(self, data):
        x, y = data
        pipe = Pipeline(stages=[TestFallback._Opaque(), LogisticRegression()])
        assert not pipe._device_foldable
        assert _device_fold_prep((x, y), pipe) is None

    def test_cv_over_pipeline_with_inner_grid(self, data):
        """CrossValidator tunes params of INNER pipeline stages on
        device-resident folds; Pipeline.copy routes each grid entry to
        the stage that owns it."""
        x, y = data
        pca = PCA().setK(4)
        lr = LogisticRegression().setMaxIter(20)
        pipe = Pipeline(stages=[pca, lr])
        grid = (
            ParamGridBuilder()
            .addGrid(pca.k, [3, 4])
            .addGrid(lr.regParam, [0.0, 0.1])
            .build()
        )
        cvm = (
            CrossValidator()
            .setEstimator(pipe)
            .setEstimatorParamMaps(grid)
            .setEvaluator(MulticlassClassificationEvaluator())
            .setNumFolds(3)
            .fit((x, y))
        )
        assert len(cvm.avgMetrics) == 4
        assert all(np.isfinite(m) for m in cvm.avgMetrics)
        best = cvm.bestModel
        assert isinstance(best, PipelineModel)
        assert best.stages[0].getK() in (3, 4)
        preds = np.asarray(best.transform(x))
        assert (preds == y).mean() > 0.6

    def test_tvs_over_pipeline_with_inner_grid(self, data):
        x, y = data
        pca = PCA().setK(4)
        pipe = Pipeline(stages=[pca, LogisticRegression().setMaxIter(20)])
        grid = ParamGridBuilder().addGrid(pca.k, [2, 4]).build()
        tvm = (
            TrainValidationSplit()
            .setEstimator(pipe)
            .setEstimatorParamMaps(grid)
            .setEvaluator(MulticlassClassificationEvaluator())
            .setTrainRatio(0.75)
            .fit((x, y))
        )
        assert len(tvm.validationMetrics) == 2
        assert isinstance(tvm.bestModel, PipelineModel)

    def test_pipeline_copy_routes_inner_extra(self):
        pca = PCA().setK(4)
        lr = LogisticRegression().setMaxIter(20)
        pipe = Pipeline(stages=[pca, lr])
        clone = pipe.copy({pca.k: 2, lr.regParam: 0.5})
        assert clone.stages[0].getK() == 2
        assert clone.stages[1].getRegParam() == 0.5
        # Originals untouched; stage objects are copies, not aliases.
        assert pca.getK() == 4 and lr.getRegParam() == 0.0
        assert clone.stages[0] is not pca

    def test_pipeline_model_copy_keeps_stages(self, data):
        x, y = data
        model = Pipeline(stages=CHAINS["pca-kmeans"]()).fit((x, y))
        clone = model.copy()
        assert len(clone.stages) == 2
        np.testing.assert_array_equal(
            np.asarray(clone.transform(x)), np.asarray(model.transform(x))
        )


class TestFuserUnit:
    def test_fuse_empty_chain_warns_none(self):
        with pytest.warns(FusionFallbackWarning):
            assert fuse_pipeline_stages([], pipeline="empty") is None

    def test_static_prefix_roundtrip(self):
        from spark_rapids_ml_tpu.pipeline_fusion.fuser import _demux_static

        per = _demux_static(
            {"s0_precision": "f32", "s1_n_classes": 3, "s1_threshold": 0.5},
            2,
        )
        assert per == [{"precision": "f32"}, {"n_classes": 3, "threshold": 0.5}]
