"""LinearRegression/Ridge suite. Oracle: closed-form numpy OLS/ridge with
Spark's standardization semantics, plus recovery of known ground-truth
coefficients from noiseless synthetic data."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.parallel.mesh import make_mesh
from spark_rapids_ml_tpu.regression import LinearRegression, LinearRegressionModel


def make_regression(rng, n=200, d=6, noise=0.0, intercept=2.5):
    x = rng.normal(size=(n, d))
    beta = rng.normal(size=d)
    y = x @ beta + intercept + noise * rng.normal(size=n)
    return x, y, beta, intercept


def numpy_ridge(x, y, reg, fit_intercept=True, standardization=True):
    """Spark WeightedLeastSquares semantics (see ops/linear.py docstring)."""
    n = len(y)
    if fit_intercept:
        xm, ym = x.mean(0), y.mean()
        xc, yc = x - xm, y - ym
    else:
        xm, ym = np.zeros(x.shape[1]), 0.0
        xc, yc = x, y
    a = xc.T @ xc
    if standardization:
        pen = np.diag(np.maximum(np.diag(a) / max(n - 1, 1), 0))
    else:
        pen = np.eye(x.shape[1])
    coef = np.linalg.solve(a + n * reg * pen, xc.T @ yc)
    b0 = ym - xm @ coef if fit_intercept else 0.0
    return coef, b0


class TestOLS:
    def test_exact_recovery_noiseless(self, rng):
        x, y, beta, b0 = make_regression(rng)
        model = LinearRegression().fit((x, y))
        np.testing.assert_allclose(model.coefficients, beta, atol=1e-8)
        assert model.intercept == pytest.approx(b0, abs=1e-8)

    def test_no_intercept(self, rng):
        x, y, beta, _ = make_regression(rng, intercept=0.0)
        model = LinearRegression().setFitIntercept(False).fit((x, y))
        np.testing.assert_allclose(model.coefficients, beta, atol=1e-8)
        assert model.intercept == 0.0

    def test_noisy_matches_numpy_lstsq(self, rng):
        x, y, _, _ = make_regression(rng, noise=0.5)
        model = LinearRegression().fit((x, y))
        a = np.column_stack([x, np.ones(len(y))])
        ref = np.linalg.lstsq(a, y, rcond=None)[0]
        np.testing.assert_allclose(model.coefficients, ref[:-1], atol=1e-6)
        assert model.intercept == pytest.approx(ref[-1], abs=1e-6)

    def test_rank_deficient_falls_back(self, rng):
        x = rng.normal(size=(50, 4))
        x = np.column_stack([x, x[:, 0]])  # duplicated column -> singular
        y = x[:, 0] * 2.0
        model = LinearRegression().fit((x, y))
        pred = model.predict(x)
        np.testing.assert_allclose(pred, y, atol=1e-6)  # fits despite singularity


class TestRidge:
    @pytest.mark.parametrize("standardization", [True, False])
    def test_matches_spark_semantics(self, rng, standardization):
        x, y, _, _ = make_regression(rng, noise=1.0)
        x = x * rng.uniform(0.1, 10.0, size=x.shape[1])  # heteroscale features
        reg = 0.3
        model = (
            LinearRegression()
            .setRegParam(reg)
            .setStandardization(standardization)
            .fit((x, y))
        )
        ref_coef, ref_b0 = numpy_ridge(x, y, reg, standardization=standardization)
        np.testing.assert_allclose(model.coefficients, ref_coef, rtol=1e-6)
        assert model.intercept == pytest.approx(ref_b0, rel=1e-6)

    def test_regularization_shrinks(self, rng):
        x, y, _, _ = make_regression(rng, noise=1.0)
        m0 = LinearRegression().fit((x, y))
        m1 = LinearRegression().setRegParam(10.0).fit((x, y))
        assert np.linalg.norm(m1.coefficients) < np.linalg.norm(m0.coefficients)

    def test_elasticnet_out_of_range_rejected(self):
        # In-range elasticNetParam now routes to the FISTA solver
        # (tests/test_elastic_net.py); only out-of-range values reject.
        with pytest.raises(ValueError):
            LinearRegression().setElasticNetParam(1.5)

    def test_negative_regparam_rejected(self):
        with pytest.raises(ValueError):
            LinearRegression().setRegParam(-1.0)


class TestModelSurface:
    def test_transform_dataframe(self, rng):
        x, y, _, _ = make_regression(rng, n=50)
        df = DataFrame({"features": list(x), "label": list(y)})
        model = LinearRegression().fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        np.testing.assert_allclose(np.asarray(out.select("prediction")), y, atol=1e-6)

    def test_transform_pandas(self, rng):
        import pandas as pd

        x, y, _, _ = make_regression(rng, n=50, d=3)
        df = pd.DataFrame(x, columns=["a", "b", "c"])
        df["label"] = y
        model = LinearRegression().fit(df)
        out = model.transform(df)
        np.testing.assert_allclose(out["prediction"], y, atol=1e-6)

    def test_evaluate_metrics(self, rng):
        x, y, _, _ = make_regression(rng, noise=0.5)
        model = LinearRegression().fit((x, y))
        m = model.evaluate((x, y))
        pred = model.predict(x)
        np.testing.assert_allclose(m["meanSquaredError"], ((y - pred) ** 2).mean(), rtol=1e-6)
        assert 0.8 < m["r2"] <= 1.0
        assert m["rootMeanSquaredError"] == pytest.approx(np.sqrt(m["meanSquaredError"]))

    def test_read_write(self, tmp_path, rng):
        x, y, _, _ = make_regression(rng)
        model = LinearRegression().setRegParam(0.1).fit((x, y))
        path = str(tmp_path / "lr")
        model.save(path)
        loaded = LinearRegressionModel.load(path)
        np.testing.assert_allclose(loaded.coefficients, model.coefficients)
        assert loaded.intercept == pytest.approx(model.intercept)
        assert loaded.getRegParam() == pytest.approx(0.1)
        np.testing.assert_allclose(loaded.predict(x), model.predict(x))


class TestDistributed:
    def test_mesh_fit_matches_local(self, rng):
        mesh = make_mesh((8, 1))
        x, y, beta, b0 = make_regression(rng, n=203)  # not divisible by 8
        m_mesh = LinearRegression(mesh=mesh).fit((x, y))
        np.testing.assert_allclose(m_mesh.coefficients, beta, atol=1e-7)
        assert m_mesh.intercept == pytest.approx(b0, abs=1e-7)

    def test_mesh_2d(self, rng):
        mesh = make_mesh((4, 2))
        x, y, beta, b0 = make_regression(rng, n=100, d=7)  # d=7 pads to 8
        m = LinearRegression(mesh=mesh).fit((x, y))
        np.testing.assert_allclose(m.coefficients, beta, atol=1e-7)


class TestReviewRegressions:
    def test_standardization_penalty_without_intercept(self, rng):
        """fitIntercept=False must still penalize by TRUE feature variance,
        not the raw second moment (features with mean >> std would otherwise
        be shrunk ~(mean/std)^2 too hard)."""
        x = rng.normal(size=(300, 4)) + 10.0  # mean 10, std 1
        beta = rng.normal(size=4)
        y = x @ beta
        reg = 0.3
        model = (
            LinearRegression().setFitIntercept(False).setRegParam(reg).fit((x, y))
        )
        n = len(y)
        var = x.var(axis=0, ddof=1)
        ref = np.linalg.solve(x.T @ x + n * reg * np.diag(var), x.T @ y)
        np.testing.assert_allclose(model.coefficients, ref, rtol=1e-6)


class TestStreamingBlocks:
    """Block-streamed sufficient statistics: list or generator of 2-D blocks
    must fit identically to the in-memory path, without concatenation."""

    def test_list_of_blocks_matches_dense(self, rng):
        x = rng.normal(size=(500, 6))
        y = x @ np.arange(1.0, 7.0) + 0.1 * rng.normal(size=500)
        blocks = list(np.array_split(x, 4))
        m_stream = LinearRegression().setRegParam(0.1).fit((blocks, y))
        m_dense = LinearRegression().setRegParam(0.1).fit((x, y))
        np.testing.assert_allclose(m_stream.coefficients, m_dense.coefficients, atol=1e-10)
        assert abs(m_stream.intercept - m_dense.intercept) < 1e-10

    def test_generator_consumed_lazily(self, rng):
        x = rng.normal(size=(300, 4))
        y = x @ np.array([1.0, -1.0, 2.0, 0.5])
        consumed = []

        def gen():
            for b in np.array_split(x, 3):
                consumed.append(len(b))
                yield b

        m = LinearRegression().fit((gen(), y))
        assert consumed == [100, 100, 100]
        ref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m.coefficients, ref.coefficients, atol=1e-10)

    def test_per_block_labels_and_elastic_net(self, rng):
        x = rng.normal(size=(400, 8))
        beta = np.zeros(8); beta[:2] = [3.0, -2.0]
        y = x @ beta + 0.05 * rng.normal(size=400)
        xb = list(np.array_split(x, 5))
        yb = list(np.array_split(y, 5))
        m = (
            LinearRegression()
            .setRegParam(0.2)
            .setElasticNetParam(1.0)
            .setStandardization(False)
            .fit((xb, yb))
        )
        ref = (
            LinearRegression()
            .setRegParam(0.2)
            .setElasticNetParam(1.0)
            .setStandardization(False)
            .fit((x, y))
        )
        np.testing.assert_allclose(m.coefficients, ref.coefficients, atol=1e-8)
        assert np.sum(np.abs(m.coefficients) > 1e-6) <= 4

    def test_npy_reader_integration(self, tmp_path, rng):
        from spark_rapids_ml_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        x = rng.normal(size=(600, 5))
        y = x @ np.arange(1.0, 6.0)
        path = str(tmp_path / "x.npy")
        np.save(path, x)
        with native.NpyBlockReader(path, block_rows=128) as r:
            m = LinearRegression().fit((r.iter_blocks(), y))
        ref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m.coefficients, ref.coefficients, atol=1e-8)

    def test_block_row_mismatch_raises(self, rng):
        x = rng.normal(size=(100, 3))
        with pytest.raises(ValueError, match="mismatch"):
            LinearRegression().fit((list(np.array_split(x, 2)), np.zeros(80)))

    def test_sparse_blocks_stream(self, rng):
        import scipy.sparse as sp

        x = rng.normal(size=(200, 5)) * (rng.uniform(size=(200, 5)) > 0.6)
        y = x @ np.arange(1.0, 6.0)
        blocks = [sp.csr_matrix(b) for b in np.array_split(x, 4)]
        m = LinearRegression().fit((blocks, y))
        ref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m.coefficients, ref.coefficients, atol=1e-10)

    def test_iterator_input(self, rng):
        x = rng.normal(size=(120, 3))
        y = x @ np.array([1.0, 2.0, 3.0])
        m = LinearRegression().fit((map(np.asarray, np.array_split(x, 3)), y))
        ref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m.coefficients, ref.coefficients, atol=1e-10)

    def test_mismatches_raise(self, rng):
        x = rng.normal(size=(100, 3))
        blocks = list(np.array_split(x, 4))
        with pytest.raises(ValueError, match="different lengths"):
            LinearRegression().fit((blocks, [np.zeros(25)] * 3))
        with pytest.raises(ValueError, match="blocks supplied"):
            LinearRegression().fit((blocks, np.zeros(120)))
        bad = [np.ones((10, 3)), np.ones((10, 4))]
        with pytest.raises(ValueError, match="inconsistent feature dims"):
            LinearRegression().fit((bad, np.zeros(20)))


def test_streaming_empty_blocks_skipped(rng):
    """Empty blocks in a streamed (X, y) fit contribute nothing instead of
    raising an inconsistent-dims error (r2 review)."""
    from spark_rapids_ml_tpu.regression import LinearRegression

    x = rng.normal(size=(600, 3))
    y = x @ np.array([1.0, -2.0, 0.5]) + 1.0
    blocks = [x[:200], np.zeros((0, 3)), x[200:]]
    model = LinearRegression().fit((blocks, y))
    np.testing.assert_allclose(model.coefficients, [1.0, -2.0, 0.5], atol=1e-8)
