"""Online serving runtime (spark_rapids_ml_tpu/serving/) contracts.

The ISSUE 5 acceptance surface: concurrent submitters coalesce into
shared AOT executions (counter-asserted), results are bitwise what the
sequential model calls produce, deadlines and overload shed with
STRUCTURED errors instead of queueing without bound, hot swap under load
is version-atomic, and every request's events join one run_id in the
JSONL log.

Float parity notes: batch coalescing changes the PADDED program shape a
row executes in, so float parity across paths is only guaranteed when
the row-wise reductions are EXACT. The fixtures use dyadic-rational
inputs and weights (integers / 4) whose dot products are exactly
representable in float64 — any accumulation order produces the same
bits, making "bitwise parity with sequential transform" a theorem
rather than a tolerance.
"""

from __future__ import annotations

import json
import threading
import time
import warnings

import numpy as np
import pytest

from spark_rapids_ml_tpu.core import serving as core_serving
from spark_rapids_ml_tpu.models.kmeans import KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import LinearRegressionModel
from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegressionModel
from spark_rapids_ml_tpu.models.pca import PCAModel
from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.serving import (
    DeadlineExceeded,
    ModelRegistry,
    Overloaded,
    ServingRuntime,
)
from spark_rapids_ml_tpu.serving import admission
from spark_rapids_ml_tpu.utils.tracing import counter_value

D = 8  # feature width shared by every fixture model


def dyadic(rng, shape, scale=4):
    """Arrays of integers/4 — dot products exact in f64, so results are
    bitwise identical across program shapes (module docstring)."""
    return rng.integers(-4 * scale, 4 * scale, size=shape).astype(np.float64) / 4.0


@pytest.fixture(scope="module")
def models():
    rng = np.random.default_rng(7)
    km = KMeansModel("srv-km", dyadic(rng, (4, D)))
    lr = LinearRegressionModel("srv-lr", dyadic(rng, (D,)), 0.25)
    logreg = LogisticRegressionModel(
        "srv-logreg", dyadic(rng, (D, 1)), np.asarray([0.5]), numClasses=2
    )
    pca = PCAModel("srv-pca", dyadic(rng, (D, 3)), np.full(3, 1.0 / 3))
    return {"km": km, "lr": lr, "logreg": logreg, "pca": pca}


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_versioning_aliases_and_retire(models):
    reg = ModelRegistry()
    v1 = reg.register("km", models["km"])
    v2 = reg.register("km", models["km"])
    assert (v1.version, v2.version) == (1, 2)
    assert reg.resolve("km").version == 2

    reg.set_alias("km", "prod", 1)
    assert reg.resolve("km", "prod").version == 1
    assert reg.resolve("km@prod").version == 1
    assert reg.resolve("km@2").version == 2
    assert reg.resolve("km", 1).version == 1

    reg.retire("km", 2)
    assert reg.resolve("km").version == 1
    # A retired version number is never reissued to a different model.
    v3 = reg.register("km", models["km"])
    assert v3.version == 3
    assert reg.versions("km") == [1, 3]

    with pytest.raises(KeyError):
        reg.resolve("km@canary")
    with pytest.raises(KeyError):
        reg.resolve("km", 2)
    with pytest.raises(KeyError):
        reg.resolve("missing")
    with pytest.raises(TypeError):
        reg.register("bad", object())


def test_registry_load_from_mlwriter_path_and_warmup(models, tmp_path):
    path = str(tmp_path / "km_model")
    models["km"].write.overwrite().save(path)

    core_serving.clear_program_cache()
    reg = ModelRegistry()
    mv = reg.load(
        "km", path, KMeansModel, alias="prod", warm_buckets=(5, 64),
        warm_dtype=np.float64,
    )
    stats = core_serving.program_cache_stats()
    # 5 rounds up to bucket 8; 64 is its own bucket -> exactly 2 programs.
    assert stats["compiles"] == 2
    assert reg.resolve("km@prod").version == mv.version

    # The warmed bucket serves real traffic compile-free.
    rng = np.random.default_rng(0)
    x = dyadic(rng, (5, D))
    out = core_serving.serve_rows(
        mv.signature.kernel, x, mv.signature.weights,
        static=mv.signature.static, name=mv.signature.name,
    )
    assert core_serving.program_cache_stats()["compiles"] == 2
    np.testing.assert_array_equal(out, models["km"].predict(x))


def test_retire_invalidates_device_caches():
    rng = np.random.default_rng(3)
    km = KMeansModel("retire-km", dyadic(rng, (4, D)))
    km.predict(dyadic(rng, (3, D)))  # populates _centers_dev
    assert km._centers_dev is not None
    reg = ModelRegistry()
    mv = reg.register("km", km)
    before = counter_value("serving.device_cache.invalidate")
    reg.retire("km", mv.version)
    assert km._centers_dev is None
    assert counter_value("serving.device_cache.invalidate") > before


def test_clear_program_cache_drops_model_device_caches():
    rng = np.random.default_rng(4)
    km = KMeansModel("clear-km", dyadic(rng, (4, D)))
    lr = LinearRegressionModel("clear-lr", dyadic(rng, (D,)), 0.0)
    km.predict(dyadic(rng, (3, D)))
    lr.predict(dyadic(rng, (3, D)))
    assert km._centers_dev is not None and lr._coef_dev is not None
    core_serving.clear_program_cache()
    assert km._centers_dev is None
    assert lr._coef_dev is None
    # Predictions after the sweep rebuild lazily and still agree.
    x = dyadic(rng, (3, D))
    np.testing.assert_array_equal(km.predict(x), km.predict(x))


# ---------------------------------------------------------------------------
# micro-batching: coalescing + parity
# ---------------------------------------------------------------------------


def test_coalescing_many_callers_share_one_program(models):
    """16 threads x 16 single rows: >= 4x fewer device programs than
    requests, exactly one AOT execution per dispatched batch, and every
    request's rows come back bitwise-identical to sequential predict."""
    rng = np.random.default_rng(11)
    rows = dyadic(rng, (256, D))
    rt = ServingRuntime(max_batch=64, max_delay_ms=5.0, start=False)
    rt.register("km", models["km"])

    results = {}
    lock = threading.Lock()

    def worker(tid):
        futs = [
            (tid * 16 + j, rt.submit("km", rows[tid * 16 + j]))
            for j in range(16)
        ]
        with lock:
            results.update(futs)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rt.queue_depth() == 256

    d0 = counter_value("serving.batch.dispatch")
    s0 = core_serving.program_cache_stats()
    rt.start()
    got = {i: np.asarray(f.result(timeout=60)) for i, f in results.items()}
    rt.close()
    dispatches = counter_value("serving.batch.dispatch") - d0
    s1 = core_serving.program_cache_stats()
    programs = (s1["hits"] + s1["misses"]) - (s0["hits"] + s0["misses"])

    assert dispatches * 4 <= 256, f"only {256 / dispatches:.1f}x coalescing"
    assert programs == dispatches, "more device programs than batches"

    expected = models["km"].predict(rows)
    for i, out in got.items():
        assert out.shape == (1,)
        np.testing.assert_array_equal(out, expected[i : i + 1])


@pytest.mark.parametrize("family", ["km", "lr", "logreg", "pca"])
def test_single_family_parity(models, family):
    rng = np.random.default_rng(21)
    block = dyadic(rng, (6, D))
    with ServingRuntime(max_batch=32, max_delay_ms=2.0) as rt:
        rt.register(family, models[family])
        out = rt.submit(family, block).result(timeout=30)
    expected_kernel = models[family].serving_signature()
    direct = core_serving.serve_rows(
        expected_kernel.kernel, block, expected_kernel.weights,
        static=expected_kernel.static, name=expected_kernel.name,
    )
    import jax

    for got, want in zip(
        jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(direct)
    ):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_concurrent_mixed_families_bitwise_parity(models):
    """>= 16 submitter threads x mixed families, blocks of varying size,
    all against one runtime — bitwise parity with sequential calls."""
    import jax

    families = ["km", "lr", "logreg", "pca"]
    rng = np.random.default_rng(31)
    jobs = []  # (family, block)
    for t in range(16):
        fam = families[t % len(families)]
        jobs.append((fam, dyadic(rng, (1 + (t % 5), D))))

    rt = ServingRuntime(max_batch=64, max_delay_ms=5.0)
    for fam in families:
        rt.register(fam, models[fam])
    outs = [None] * len(jobs)

    def worker(i):
        fam, block = jobs[i]
        outs[i] = rt.submit(fam, block).result(timeout=60)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(jobs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rt.close()

    for (fam, block), out in zip(jobs, outs):
        sig = models[fam].serving_signature()
        direct = core_serving.serve_rows(
            sig.kernel, block, sig.weights, static=sig.static, name=sig.name
        )
        for got, want in zip(
            jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(direct)
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# deadlines + admission
# ---------------------------------------------------------------------------


def test_deadline_expiry_is_structured(models):
    rt = ServingRuntime(start=False)  # parked: nothing dispatches
    rt.register("km", models["km"])
    fut = rt.submit("km", np.zeros(D), timeout=0.01)
    time.sleep(0.05)
    c0 = counter_value("serving.deadline.expired")
    rt.start()
    with pytest.raises(DeadlineExceeded) as err:
        fut.result(timeout=30)
    assert err.value.model == "km"
    assert err.value.waited_ms >= 10.0
    assert counter_value("serving.deadline.expired") == c0 + 1
    rt.close()


def test_shed_on_queue_overload(models):
    rt = ServingRuntime(queue_limit=3, start=False)
    rt.register("km", models["km"])
    for _ in range(3):
        rt.submit("km", np.zeros(D))
    c0 = counter_value("serving.shed.queue")
    with pytest.raises(Overloaded) as err:
        rt.submit("km", np.zeros(D))
    assert err.value.reason == "queue"
    assert err.value.queue_depth == 3 and err.value.queue_limit == 3
    assert counter_value("serving.shed.queue") == c0 + 1
    rt.close()  # drains the three queued requests


def test_shed_on_memory_budget_and_release(models):
    sig = models["km"].serving_signature()
    # Price one 8-row-bucket f64 request exactly as admission does.
    from spark_rapids_ml_tpu.serving.signature import spec_bytes

    one = 8 * D * 8 + spec_bytes(sig.output_spec(8, np.dtype(np.float64)))
    rt = ServingRuntime(mem_budget=2 * one, queue_limit=100, start=False)
    rt.register("km", models["km"])
    rt.submit("km", np.zeros(D))
    rt.submit("km", np.zeros(D))
    c0 = counter_value("serving.shed.memory")
    with pytest.raises(Overloaded) as err:
        rt.submit("km", np.zeros(D))
    assert err.value.reason == "memory"
    assert err.value.mem_budget == 2 * one
    assert err.value.reserved_bytes == 2 * one
    assert counter_value("serving.shed.memory") == c0 + 1
    # Completion releases the reservation: after the drain, fresh
    # requests are admitted again.
    rt.start()
    deadline = time.monotonic() + 30.0
    while rt.snapshot()["reserved_bytes"] != 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert rt.snapshot()["reserved_bytes"] == 0
    fut = rt.submit("km", np.zeros(D))
    assert fut.result(timeout=30) is not None
    rt.close()


def test_submit_validation_errors(models):
    rt = ServingRuntime(start=False)
    rt.register("km", models["km"])
    with pytest.raises(ValueError, match="features"):
        rt.submit("km", np.zeros(D + 1))
    with pytest.raises(ValueError, match="2-D"):
        rt.submit("km", np.zeros((2, 2, 2)))
    with pytest.raises(KeyError):
        rt.submit("nope", np.zeros(D))
    rt.close()
    with pytest.raises(RuntimeError, match="closed"):
        rt.submit("km", np.zeros(D))


def test_close_without_drain_fails_pending(models):
    rt = ServingRuntime(start=False)
    rt.register("km", models["km"])
    futs = [rt.submit("km", np.zeros(D)) for _ in range(4)]
    rt.close(drain=False)
    for f in futs:
        with pytest.raises(RuntimeError, match="closed"):
            f.result(timeout=5)


def test_close_with_drain_answers_everyone(models):
    rt = ServingRuntime(start=False)  # never started: close must drain
    rt.register("km", models["km"])
    x = np.zeros((2, D))
    futs = [rt.submit("km", x) for _ in range(5)]
    rt.close(drain=True)
    for f in futs:
        assert np.asarray(f.result(timeout=5)).shape == (2,)


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------


def test_hot_swap_under_load_is_version_atomic(models, tmp_path):
    """Swap ``prod`` from v1 to v2 while 8 threads stream single rows at
    the alias: every result must be bitwise v1's answer or v2's answer,
    and the event log must show every request dispatched on EXACTLY the
    version it was admitted against (no mixed-version batch)."""
    rng = np.random.default_rng(41)
    c1 = dyadic(rng, (4, D))
    c2 = dyadic(rng, (4, D)) + 64.0  # a genuinely different model
    m1 = KMeansModel("swap-v1", c1)
    m2 = KMeansModel("swap-v2", c2)
    probes = dyadic(rng, (240, D))
    exp1 = m1.predict(probes)
    exp2 = m2.predict(probes)

    log = tmp_path / "swap_events.jsonl"
    events.configure(str(log))
    try:
        rt = ServingRuntime(max_batch=16, max_delay_ms=2.0)
        v1 = rt.register("km", m1, alias="prod")
        collected = []
        lock = threading.Lock()

        def worker(tid):
            local = []
            for j in range(30):
                i = tid * 30 + j
                out = rt.submit("km@prod", probes[i]).result(timeout=60)
                local.append((i, np.asarray(out)))
            with lock:
                collected.extend(local)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.02)
        v2 = rt.register("km", m2)
        rt.set_alias("km", "prod", v2.version)
        for t in threads:
            t.join()
        rt.close()
    finally:
        events.configure()

    n_v1 = n_v2 = 0
    for i, out in collected:
        if np.array_equal(out, exp1[i : i + 1]):
            n_v1 += 1
        elif np.array_equal(out, exp2[i : i + 1]):
            n_v2 += 1
        else:  # pragma: no cover - the failure being hunted
            raise AssertionError(f"row {i} matches neither version: {out}")
    assert n_v1 + n_v2 == 240
    assert v1.version == 1 and v2.version == 2

    # Event-log atomicity: a request's admitted version IS the version
    # its batch dispatched and completed on.
    records = [json.loads(line) for line in log.read_text().splitlines()]
    serving_recs = [r for r in records if r["event"] == "serving"]
    admitted = {
        r["run_id"]: r["version"]
        for r in serving_recs
        if r["action"] == "enqueue"
    }
    assert len(admitted) == 240
    for r in serving_recs:
        if r["action"] == "dispatch":
            for rid in r["run_ids"]:
                assert admitted[rid] == r["version"], "mixed-version batch"
        elif r["action"] == "complete":
            assert admitted[r["run_id"]] == r["version"]


# ---------------------------------------------------------------------------
# events / run ids
# ---------------------------------------------------------------------------


def test_every_request_joins_one_run_id(models, tmp_path):
    log = tmp_path / "serve_events.jsonl"
    events.configure(str(log))
    try:
        with ServingRuntime(max_batch=8, max_delay_ms=2.0) as rt:
            rt.register("km", models["km"])
            futs = [rt.submit("km", np.zeros(D)) for _ in range(6)]
            for f in futs:
                f.result(timeout=30)
    finally:
        events.configure()  # back to the env-configured sink

    records = [json.loads(line) for line in log.read_text().splitlines()]
    for rec in records:
        assert events.validate_record(rec) == [], rec

    serving_recs = [r for r in records if r["event"] == "serving"]
    enq = {r["run_id"]: r for r in serving_recs if r["action"] == "enqueue"}
    done = {r["run_id"]: r for r in serving_recs if r["action"] == "complete"}
    dispatched = [
        rid
        for r in serving_recs
        if r["action"] == "dispatch"
        for rid in r["run_ids"]
    ]
    assert len(enq) == 6
    # Every request's lifecycle joins on its one run_id.
    assert set(done) == set(enq)
    assert sorted(dispatched) == sorted(enq)
    for rid, r in done.items():
        assert r["model"] == "km" and "latency_ms" in r


# ---------------------------------------------------------------------------
# degradation
# ---------------------------------------------------------------------------


def test_failing_device_degrades_batch_to_cpu(models, monkeypatch):
    monkeypatch.setenv("TPUML_DEGRADE", "cpu")
    x = dyadic(np.random.default_rng(5), (4, D))
    expected = models["km"].predict(x)

    def broken(*a, **k):
        raise RuntimeError("jax backend: device unavailable")

    monkeypatch.setattr(admission, "serve_rows", broken)
    c0 = counter_value("serving.degraded_batches")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with ServingRuntime() as rt:
            rt.register("km", models["km"])
            out = rt.submit("km", x).result(timeout=30)
    np.testing.assert_array_equal(np.asarray(out), expected)
    assert counter_value("serving.degraded_batches") == c0 + 1


def test_failing_device_without_degrade_errors_the_request(models, monkeypatch):
    monkeypatch.setenv("TPUML_DEGRADE", "off")

    def broken(*a, **k):
        raise RuntimeError("jax backend: device unavailable")

    monkeypatch.setattr(admission, "serve_rows", broken)
    with ServingRuntime() as rt:
        rt.register("km", models["km"])
        fut = rt.submit("km", np.zeros(D))
        with pytest.raises(RuntimeError, match="device unavailable"):
            fut.result(timeout=30)


# ---------------------------------------------------------------------------
# satellite: big host batches stream through serve_stream
# ---------------------------------------------------------------------------


def test_kmeans_big_host_batch_streams(models, monkeypatch):
    rng = np.random.default_rng(51)
    big = dyadic(rng, (1000, D))
    ref = models["km"].predict(big)  # default block: no streaming at 1000
    monkeypatch.setenv("TPUML_SERVE_STREAM_BLOCK", "128")
    c0 = counter_value("serving.stream.blocks")
    out = models["km"].predict(big)
    assert counter_value("serving.stream.blocks") - c0 == 8
    np.testing.assert_array_equal(out, ref)


def test_logreg_big_host_batch_streams(models, monkeypatch):
    rng = np.random.default_rng(52)
    big = dyadic(rng, (600, D))
    ref_labels, ref_probs, ref_raw = models["logreg"]._predict_all(big)
    monkeypatch.setenv("TPUML_SERVE_STREAM_BLOCK", "100")
    c0 = counter_value("serving.stream.blocks")
    labels, probs, raw = models["logreg"]._predict_all(big)
    assert counter_value("serving.stream.blocks") - c0 == 6
    np.testing.assert_array_equal(labels, ref_labels)
    np.testing.assert_array_equal(probs, ref_probs)
    np.testing.assert_array_equal(raw, ref_raw)


# ---------------------------------------------------------------------------
# report integration
# ---------------------------------------------------------------------------


def test_serving_report_runtime_section(models):
    from spark_rapids_ml_tpu.observability.report import serving_report

    with ServingRuntime(max_batch=8, max_delay_ms=1.0) as rt:
        rt.register("km", models["km"], alias="prod")
        rt.submit("km", np.zeros(D)).result(timeout=30)
        rep = serving_report()
    mine = [
        r for r in rep.get("runtimes", []) if r["runtime"] == rt.runtime_id
    ]
    assert mine, "runtime missing from serving_report"
    snap = mine[0]
    assert snap["models"]["km"]["aliases"] == {"prod": 1}
    assert snap["queue_depth"] == 0
    assert rep["request_latency_ms"]["count"] >= 1
    assert rep["batch_fill"]["count"] >= 1


def test_random_forest_serving_roundtrip():
    """RF rides the same runtime: fit a tiny forest, register, and check
    the runtime's class distributions match the model's own."""
    from spark_rapids_ml_tpu.models.random_forest import RandomForestClassifier

    rng = np.random.default_rng(61)
    x = rng.normal(size=(80, 4)).astype(np.float64)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.int64)
    model = (
        RandomForestClassifier()
        .setNumTrees(4)
        .setMaxDepth(3)
        .setSeed(0)
        .fit((x, y))
    )
    probe = rng.normal(size=(5, 4))
    expected = model.predictProbability(probe)
    with ServingRuntime(max_batch=8, max_delay_ms=1.0) as rt:
        rt.register("rf", model)
        out = rt.submit("rf", probe).result(timeout=60)
    np.testing.assert_array_equal(np.asarray(out), expected)
