"""Serving-path program cache (core/serving.py).

The steady-state contract: repeated transform/predict calls are
COMPILE-FREE once their row bucket has been seen — compiles scale with
the number of distinct buckets, never with the number of calls — and
copy-minimal (weights resident across calls, padded scratch donated).
The retrace-regression tests pin this with the serving layer's own
counters AND a ``jax_log_compiles`` capture, so a regression that
sneaks a per-shape retrace into the serving path (the pre-cache
behavior) fails loudly.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.core import serving
from spark_rapids_ml_tpu.core.serving import bucket_rows
from spark_rapids_ml_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _fresh_cache():
    serving.clear_program_cache()
    tracing.clear_counters("serving.")
    yield
    serving.clear_program_cache()


@pytest.fixture(scope="module")
def pca_model():
    from spark_rapids_ml_tpu.feature import PCA

    rng = np.random.default_rng(11)
    return PCA().setK(3).fit(rng.standard_normal((256, 8)))


def _pca_oracle(model, x):
    return np.asarray(x, dtype=np.float64) @ model.pc


class TestBucketPolicy:
    def test_pow2_rounding(self):
        assert bucket_rows(1) == serving.MIN_ROW_BUCKET
        assert bucket_rows(8) == 8
        assert bucket_rows(9) == 16
        assert bucket_rows(100) == 128
        assert bucket_rows(1000) == 1024
        assert bucket_rows(8192) == 8192

    def test_rejects_empty(self):
        with pytest.raises(ValueError, match="at least one row"):
            bucket_rows(0)


class TestRetraceRegression:
    """ISSUE 2 acceptance: compiles == number of distinct buckets."""

    SIZES = (100, 1000, 8192)  # buckets 128 / 1024 / 8192

    def test_compiles_equal_buckets_not_calls(self, pca_model):
        rng = np.random.default_rng(0)
        batches = [rng.standard_normal((n, 8)) for n in self.SIZES]
        base = serving.program_cache_stats()["compiles"]
        # Each size twice, interleaved — 6 calls, 3 buckets.
        for x in batches + batches:
            out = pca_model.transform(x)
            np.testing.assert_allclose(out, _pca_oracle(pca_model, x), atol=1e-8)
        stats = serving.program_cache_stats()
        n_buckets = len({bucket_rows(n) for n in self.SIZES})
        assert stats["compiles"] - base == n_buckets
        assert stats["misses"] == n_buckets
        assert stats["hits"] == 2 * len(self.SIZES) - n_buckets

    def test_warm_path_zero_xla_compiles(self, pca_model, caplog):
        """Second-and-later calls at a seen bucket trigger ZERO XLA
        compiles anywhere in the call — asserted against jax's own
        compile log, not just this layer's counters."""
        rng = np.random.default_rng(1)
        warm = [rng.standard_normal((n, 8)) for n in (100, 90, 1000, 999)]
        for x in warm:
            pca_model.transform(x)  # cold: populate the two buckets
        jax.config.update("jax_log_compiles", True)
        try:
            with caplog.at_level(logging.WARNING, logger="jax._src.dispatch"):
                for x in warm:
                    pca_model.transform(x)
        finally:
            jax.config.update("jax_log_compiles", False)
        compile_lines = [
            r for r in caplog.records if "XLA compilation" in r.getMessage()
        ]
        assert compile_lines == []
        assert serving.program_cache_stats()["compiles"] == 2  # 128 + 1024

    def test_within_bucket_sizes_share_one_program(self, pca_model):
        rng = np.random.default_rng(2)
        for n in (513, 700, 900, 1024):  # all bucket 1024
            pca_model.transform(rng.standard_normal((n, 8)))
        assert serving.program_cache_stats()["compiles"] == 1


class TestServeRows:
    def test_padding_rows_never_leak(self, pca_model):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((5, 8))  # bucket 8, 3 padding rows
        out = pca_model.transform(x)
        assert out.shape == (5, 3)
        np.testing.assert_allclose(out, _pca_oracle(pca_model, x), atol=1e-8)

    def test_device_in_device_out(self, pca_model):
        xd = jnp.asarray(np.random.default_rng(4).standard_normal((33, 8)))
        out = pca_model.transform(xd)
        from spark_rapids_ml_tpu.core.data import is_device_array

        assert is_device_array(out)
        assert out.shape == (33, 3)
        np.testing.assert_allclose(
            np.asarray(out), _pca_oracle(pca_model, np.asarray(xd)), atol=1e-6
        )

    def test_lru_bound_and_evictions(self, pca_model, monkeypatch):
        monkeypatch.setenv("TPUML_SERVING_CACHE_SIZE", "2")
        rng = np.random.default_rng(5)
        for n in (8, 100, 1000, 8192):  # 4 distinct buckets, capacity 2
            pca_model.transform(rng.standard_normal((n, 8)))
        stats = serving.program_cache_stats()
        assert stats["size"] <= 2
        assert stats["evictions"] == 2

    def test_counters_published_via_tracing(self, pca_model):
        pca_model.transform(np.random.default_rng(6).standard_normal((10, 8)))
        snap = tracing.counters("serving.")
        assert snap.get("serving.cache.miss", 0) >= 1
        assert snap.get("serving.compile", 0) >= 1

    def test_donation_only_on_owned_scratch(self, pca_model):
        """A caller's exact-bucket device array must NOT be donated (the
        caller may reuse it); padded/host-ingested scratch may be."""
        xd = jnp.asarray(
            np.random.default_rng(7).standard_normal((16, 8)), dtype=jnp.float32
        )
        out1 = pca_model.transform(xd)
        out2 = pca_model.transform(xd)  # would crash if xd were donated
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))


class TestServeStream:
    def test_double_buffered_stream_matches_batch(self, pca_model):
        rng = np.random.default_rng(8)
        blocks = [rng.standard_normal((n, 8)) for n in (64, 100, 17, 64)]

        def batches():
            yield from blocks

        outs = list(pca_model.transform(batches()))
        assert [o.shape[0] for o in outs] == [64, 100, 17, 64]
        for blk, out in zip(blocks, outs):
            np.testing.assert_allclose(out, _pca_oracle(pca_model, blk), atol=1e-8)
        # 64-row blocks share one program: buckets {64, 128, 32}.
        assert serving.program_cache_stats()["compiles"] == 3
        assert tracing.counter_value("serving.stream.blocks") == 4

    def test_partitioned_host_transform_uses_stream(self, pca_model):
        rng = np.random.default_rng(9)
        parts = [rng.standard_normal((40, 8)), rng.standard_normal((25, 8))]
        out = pca_model.transform(parts)
        assert out.shape == (65, 3)
        np.testing.assert_allclose(
            out, _pca_oracle(pca_model, np.concatenate(parts)), atol=1e-8
        )


class TestFamiliesServed:
    """Every family's predict/transform runs through the program cache and
    stays correct at off-bucket batch sizes."""

    def _assert_cached_call(self, fn, sizes, make_batch, check):
        for n in sizes:
            check(n, fn(make_batch(n)))
        before = serving.program_cache_stats()["compiles"]
        for n in sizes:
            check(n, fn(make_batch(n)))
        assert serving.program_cache_stats()["compiles"] == before

    def test_kmeans_predict(self):
        from spark_rapids_ml_tpu.clustering import KMeans

        rng = np.random.default_rng(10)
        x = np.concatenate([rng.normal(-4, 0.3, (60, 5)), rng.normal(4, 0.3, (60, 5))])
        model = KMeans().setK(2).setSeed(0).fit(x)
        centers = model.clusterCenters()

        def check(n, labels):
            assert labels.shape == (n,)
            batch = self._batches[n]
            d0 = np.linalg.norm(batch - centers[0], axis=1)
            d1 = np.linalg.norm(batch - centers[1], axis=1)
            np.testing.assert_array_equal(np.asarray(labels), (d1 < d0).astype(labels.dtype))

        self._batches = {n: rng.normal(0, 5, (n, 5)) for n in (7, 130)}
        self._assert_cached_call(
            model.predict, (7, 130), lambda n: self._batches[n], check
        )

    def test_logreg_predict_all(self):
        from spark_rapids_ml_tpu.classification import LogisticRegression

        rng = np.random.default_rng(11)
        x = rng.standard_normal((300, 6))
        y = (x @ np.arange(1, 7) > 0).astype(float)
        model = LogisticRegression().setMaxIter(30).fit((x, y))
        batches = {n: rng.standard_normal((n, 6)) for n in (9, 200)}

        def check(n, out):
            labels = out
            assert labels.shape == (n,)
            probs = model.predictProbability(batches[n])
            assert probs.shape == (n, 2)
            np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)

        self._assert_cached_call(model.predict, (9, 200), lambda n: batches[n], check)

    def test_logreg_threshold_inside_program(self):
        from spark_rapids_ml_tpu.classification import LogisticRegression

        rng = np.random.default_rng(12)
        x = rng.standard_normal((200, 4))
        y = (x[:, 0] > 0).astype(float)
        model = LogisticRegression().setMaxIter(25).fit((x, y))
        q = rng.standard_normal((50, 4))
        probs = model.predictProbability(q)
        model.setThreshold(0.9)
        labels = model.predict(q)
        np.testing.assert_array_equal(
            np.asarray(labels), (probs[:, 1] > 0.9).astype(labels.dtype)
        )

    def test_linreg_predict(self):
        from spark_rapids_ml_tpu.regression import LinearRegression

        rng = np.random.default_rng(13)
        x = rng.standard_normal((200, 5))
        coef = np.array([1.0, -2.0, 0.5, 3.0, 0.0])
        model = LinearRegression().fit((x, x @ coef + 0.7))
        batches = {n: rng.standard_normal((n, 5)) for n in (3, 120)}

        def check(n, pred):
            assert pred.shape == (n,)
            np.testing.assert_allclose(pred, batches[n] @ coef + 0.7, atol=1e-5)

        self._assert_cached_call(model.predict, (3, 120), lambda n: batches[n], check)

    def test_random_forest_predict(self):
        from spark_rapids_ml_tpu.classification import RandomForestClassifier

        rng = np.random.default_rng(14)
        x = np.concatenate([rng.normal(-3, 0.5, (80, 4)), rng.normal(3, 0.5, (80, 4))])
        y = np.concatenate([np.zeros(80), np.ones(80)])
        model = (
            RandomForestClassifier().setNumTrees(5).setMaxDepth(3).fit((x, y))
        )
        batches = {
            n: np.concatenate(
                [rng.normal(-3, 0.3, (n // 2, 4)), rng.normal(3, 0.3, (n - n // 2, 4))]
            )
            for n in (10, 70)
        }

        def check(n, pred):
            assert pred.shape == (n,)
            expected = np.concatenate([np.zeros(n // 2), np.ones(n - n // 2)])
            np.testing.assert_array_equal(np.asarray(pred), expected)

        self._assert_cached_call(model.predict, (10, 70), lambda n: batches[n], check)

    def test_mesh_sharded_weights_take_jit_fallback(self):
        """Centers fitted under a mesh keep working through predict (the
        cached-jit path), not a strict-AOT sharding crash."""
        from jax.sharding import Mesh

        from spark_rapids_ml_tpu.clustering import KMeans

        devs = np.array(jax.devices()[:4]).reshape(4, 1)
        mesh = Mesh(devs, ("data", "model"))
        rng = np.random.default_rng(15)
        x = np.concatenate([rng.normal(-4, 0.3, (40, 4)), rng.normal(4, 0.3, (40, 4))])
        model = KMeans(mesh=mesh).setK(2).setSeed(0).fit(x)
        labels = model.predict(rng.normal(0, 5, (23, 4)))
        assert labels.shape == (23,)
        assert tracing.counter_value("serving.fallback") >= 1


class TestCompileCacheKnob:
    def test_env_knob_wires_jax_config(self, tmp_path, monkeypatch):
        calls = {}
        monkeypatch.setattr(
            jax.config, "update", lambda k, v: calls.setdefault(k, v)
        )
        serving._reset_compile_cache_wiring_for_tests()
        try:
            monkeypatch.setenv("TPUML_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
            # force=True stands in for a non-CPU backend (the CPU guard is
            # the point of the next test).
            active = serving.configure_compile_cache(force=True)
            assert active == str(tmp_path / "cc")
            assert calls["jax_compilation_cache_dir"] == str(tmp_path / "cc")
            assert calls["jax_persistent_cache_min_compile_time_secs"] == 0
            assert (tmp_path / "cc").is_dir()
        finally:
            serving._reset_compile_cache_wiring_for_tests()

    def test_cpu_backend_guard(self, tmp_path, monkeypatch):
        """XLA:CPU AOT (de)serialization is unstable on this jaxlib
        (tests/conftest.py) — the knob must be inert on CPU by default."""
        serving._reset_compile_cache_wiring_for_tests()
        try:
            monkeypatch.setenv("TPUML_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
            assert serving.configure_compile_cache() is None
        finally:
            serving._reset_compile_cache_wiring_for_tests()

    def test_unset_knob_is_noop(self, monkeypatch):
        monkeypatch.delenv("TPUML_COMPILE_CACHE_DIR", raising=False)
        serving._reset_compile_cache_wiring_for_tests()
        try:
            assert serving.configure_compile_cache() is None
        finally:
            serving._reset_compile_cache_wiring_for_tests()


class TestIngestWeightMask:
    """Satellite: user weights COMBINE with the padding-validity mask."""

    def test_mesh_padded_rows_never_gain_weight(self):
        from jax.sharding import Mesh

        from spark_rapids_ml_tpu.core.ingest import prepare_rows

        devs = np.array(jax.devices()[:4]).reshape(4, 1)
        mesh = Mesh(devs, ("data", "model"))
        rng = np.random.default_rng(16)
        x = jnp.asarray(rng.standard_normal((10, 4)))  # pads to 12 rows
        w = np.full(10, 2.5)
        prepared = prepare_rows(x, mesh=mesh, weights=w)
        mask = np.asarray(prepared.mask)
        assert prepared.x.shape[0] == 12
        np.testing.assert_allclose(mask[:10], 2.5)
        np.testing.assert_allclose(mask[10:], 0.0)

    def test_weight_length_mismatch_raises(self):
        from spark_rapids_ml_tpu.core.ingest import prepare_rows

        x = np.random.default_rng(17).standard_normal((10, 4))
        with pytest.raises(ValueError, match="weight vector has 7 entries"):
            prepare_rows(x, weights=np.ones(7))

    def test_single_device_weights_preserved(self):
        from spark_rapids_ml_tpu.core.ingest import prepare_rows

        x = np.random.default_rng(18).standard_normal((6, 3))
        w = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
        prepared = prepare_rows(x, weights=w)
        np.testing.assert_allclose(np.asarray(prepared.mask), w)


class TestDeviceFoldTuning:
    """Satellite: CV/TVS place tuning data on device once and reuse
    device-resident fold slices across the param grid."""

    def _data(self):
        rng = np.random.default_rng(19)
        x = rng.standard_normal((90, 5))
        y = x @ np.array([1.0, -1.0, 0.5, 2.0, 0.0]) + 0.3
        return x, y

    def test_prep_gates_on_family_and_container(self):
        from spark_rapids_ml_tpu.regression import LinearRegression
        from spark_rapids_ml_tpu.tuning import _device_fold_prep

        x, y = self._data()
        est = LinearRegression()
        prep = _device_fold_prep((x, y), est)
        assert prep is not None
        from spark_rapids_ml_tpu.core.data import is_device_array

        assert is_device_array(prep.x) and is_device_array(prep.y)

        class NotOurs:
            pass

        assert _device_fold_prep((x, y), NotOurs()) is None
        assert _device_fold_prep("not a dataset", est) is None

    def test_fold_slices_are_device_resident_views(self):
        from spark_rapids_ml_tpu.core.data import is_device_array
        from spark_rapids_ml_tpu.regression import LinearRegression
        from spark_rapids_ml_tpu.tuning import _device_fold_prep

        x, y = self._data()
        prep = _device_fold_prep((x, y), LinearRegression())
        idx = np.array([3, 1, 8])
        xs, ys = prep.slice(idx)
        assert is_device_array(xs) and is_device_array(ys)
        np.testing.assert_allclose(np.asarray(xs), x[idx])
        np.testing.assert_allclose(np.asarray(ys), y[idx])

    def test_cv_metrics_match_host_path(self):
        """Device-resident folds must not change the selected model or the
        per-cell metrics (same values, same fold assignment)."""
        from spark_rapids_ml_tpu.evaluation import RegressionEvaluator
        from spark_rapids_ml_tpu.regression import LinearRegression
        from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

        x, y = self._data()
        lin = LinearRegression()
        grid = ParamGridBuilder().addGrid(lin.regParam, [0.0, 0.5]).build()

        def run(device_foldable):
            est = LinearRegression()
            if not device_foldable:
                est._device_foldable = False
            cv = (
                CrossValidator()
                .setEstimator(est)
                .setEstimatorParamMaps(grid)
                .setEvaluator(RegressionEvaluator())
                .setNumFolds(3)
                .setSeed(42)
            )
            m = cv.fit((x, y))
            return m.bestIndex, np.asarray(m.avgMetrics)

        best_dev, metrics_dev = run(True)
        best_host, metrics_host = run(False)
        assert best_dev == best_host
        np.testing.assert_allclose(metrics_dev, metrics_host, rtol=1e-9)

    def test_tvs_device_folds(self):
        from spark_rapids_ml_tpu.classification import LogisticRegression
        from spark_rapids_ml_tpu.evaluation import (
            MulticlassClassificationEvaluator,
        )
        from spark_rapids_ml_tpu.tuning import (
            ParamGridBuilder,
            TrainValidationSplit,
        )

        rng = np.random.default_rng(20)
        x = rng.standard_normal((120, 4))
        y = (x[:, 0] + 0.2 * x[:, 1] > 0).astype(float)
        lr = LogisticRegression().setMaxIter(25)
        grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 0.1]).build()
        tvs = (
            TrainValidationSplit()
            .setEstimator(lr)
            .setEstimatorParamMaps(grid)
            .setEvaluator(
                MulticlassClassificationEvaluator().setMetricName("accuracy")
            )
            .setSeed(7)
        )
        model = tvs.fit((x, y))
        assert model.bestModel is not None
        assert max(model.validationMetrics) > 0.8


class TestModelPickling:
    """Device-side serving caches never ship in pickles."""

    def test_models_roundtrip_after_serving(self):
        import pickle

        from spark_rapids_ml_tpu.classification import RandomForestClassifier
        from spark_rapids_ml_tpu.regression import LinearRegression

        rng = np.random.default_rng(21)
        x = rng.standard_normal((60, 4))
        y = (x[:, 0] > 0).astype(float)
        rf = RandomForestClassifier().setNumTrees(3).setMaxDepth(2).fit((x, y))
        lin = LinearRegression().fit((x, x[:, 0]))
        q = rng.standard_normal((12, 4))
        rf.predict(q)
        lin.predict(q)  # populate device caches
        rf2 = pickle.loads(pickle.dumps(rf))
        lin2 = pickle.loads(pickle.dumps(lin))
        assert rf2._forest_dev is None
        assert lin2._coef_dev is None
        np.testing.assert_array_equal(np.asarray(rf2.predict(q)), np.asarray(rf.predict(q)))
        np.testing.assert_allclose(
            np.asarray(lin2.predict(q)), np.asarray(lin.predict(q)), atol=1e-12
        )
