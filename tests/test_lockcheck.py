"""Concurrency-sanitizer suite: both halves against the same bugs.

The contract under test is that a seeded race is caught TWICE — by the
static half (``tools/tpuml_lint/locks.py``: interprocedural guarded-by,
acquisition-order cycles, leak detection on fixture source) and by the
dynamic half (``utils/lockcheck.py``: instrumented locks at runtime,
``warn`` emitting structured ``lockcheck`` events, ``strict`` raising
:class:`LockcheckError`). Plus the zero-overhead claim for the default
``off`` mode: the factories return the plain ``threading`` primitives,
byte-for-byte.

No jax import anywhere — the whole suite runs in milliseconds.
"""

import json
import sys
import textwrap
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import tools.tpuml_lint as tl  # noqa: E402
from spark_rapids_ml_tpu.observability import events  # noqa: E402
from spark_rapids_ml_tpu.observability.metrics import (  # noqa: E402
    counter,
    histogram,
)
from spark_rapids_ml_tpu.utils import lockcheck as lc  # noqa: E402
from spark_rapids_ml_tpu.utils.envknobs import env_str  # noqa: E402


def lint_src(tmp_path, src, name="fixture.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(src))
    return tl.lint_file(tmp_path, f, tl.CHECKERS)


def rules_of(findings):
    return {f.rule for f in findings}


_PREV_LOG = env_str(events.EVENT_LOG_ENV)


@pytest.fixture
def clean_state():
    lc.reset()
    try:
        yield
    finally:
        lc.reset()


@pytest.fixture
def event_log(tmp_path):
    path = tmp_path / "events.jsonl"
    events.configure(str(path))
    try:
        yield path
    finally:
        events.configure(_PREV_LOG if _PREV_LOG else None)


def lockcheck_events(path):
    if not path.exists():
        return []
    recs = [json.loads(l) for l in path.read_text().splitlines() if l]
    return [r for r in recs if r.get("event") == "lockcheck"]


# --- off: the factories hand back plain threading primitives ------------


class TestOffMode:
    def test_plain_primitives(self, monkeypatch):
        monkeypatch.setenv(lc.MODE_ENV, "off")
        assert type(lc.make_lock("t.a")) is type(threading.Lock())
        assert type(lc.make_rlock("t.b")) is type(threading.RLock())
        assert isinstance(lc.make_condition("t.c"), threading.Condition)
        assert not lc.is_instrumented(lc.make_lock("t.d"))
        assert not lc.is_instrumented(lc.make_condition("t.e"))

    def test_guarded_is_noop_on_plain(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "off")
        lock = lc.make_lock("t.a")
        lc.guarded(lock, "anything")  # no lock held, still silent
        cond = lc.make_condition("t.c")
        lc.guarded(cond, "anything")
        assert lc.violations() == []

    def test_default_mode_is_off(self, monkeypatch):
        monkeypatch.delenv(lc.MODE_ENV, raising=False)
        assert lc.mode() == "off"
        assert type(lc.make_lock("t.a")) is type(threading.Lock())


# --- guarded(): the runtime half of a guarded-by annotation -------------


class TestGuarded:
    def test_pass_when_held(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        lock = lc.make_lock("t.a")
        with lock:
            lc.guarded(lock, "C._x")  # must not raise
        assert lc.violations() == []

    def test_warn_records_and_emits(self, monkeypatch, clean_state,
                                    event_log):
        monkeypatch.setenv(lc.MODE_ENV, "warn")
        lock = lc.make_lock("t.a")
        lc.guarded(lock, "C._x")  # seeded unguarded access
        vs = lc.violations()
        assert [v["kind"] for v in vs] == ["unguarded"]
        assert vs[0]["lock"] == "t.a"
        recs = lockcheck_events(event_log)
        assert len(recs) == 1
        assert recs[0]["action"] == "unguarded"
        assert recs[0]["lock"] == "t.a"
        assert not events.validate_record(recs[0])

    def test_strict_raises(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        lock = lc.make_lock("t.a")
        with pytest.raises(lc.LockcheckError, match="unguarded"):
            lc.guarded(lock, "C._x")

    def test_condition_unwrap(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        cond = lc.make_condition("t.cond")
        with cond:
            lc.guarded(cond, "Q._dq")
        with pytest.raises(lc.LockcheckError):
            lc.guarded(cond, "Q._dq")

    def test_violation_counter(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "warn")
        before = counter(
            "lockcheck.violations",
            "concurrency invariants the sanitizer saw violated",
        ).value(kind="unguarded")
        lc.guarded(lc.make_lock("t.a"), "C._x")
        after = counter("lockcheck.violations").value(kind="unguarded")
        assert after == before + 1


# --- lock-order cycles: lockdep's trick, no hang required ---------------


class TestOrderCycle:
    def test_inversion_detected_single_thread(self, monkeypatch,
                                              clean_state, event_log):
        monkeypatch.setenv(lc.MODE_ENV, "warn")
        a, b = lc.make_lock("t.A"), lc.make_lock("t.B")
        with a:
            with b:
                pass
        with b:
            with a:  # seeded A->B / B->A inversion
                pass
        kinds = [v["kind"] for v in lc.violations()]
        assert kinds == ["order-cycle"]
        recs = lockcheck_events(event_log)
        assert recs and recs[0]["action"] == "order-cycle"
        assert set(recs[0]["cycle"]) == {"t.A", "t.B"}

    def test_inversion_detected_cross_thread(self, monkeypatch,
                                             clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "warn")
        a, b = lc.make_lock("t.A"), lc.make_lock("t.B")

        def forward():
            with a:
                with b:
                    pass

        t = threading.Thread(target=forward)
        t.start()
        t.join()
        with b:
            with a:
                pass
        assert [v["kind"] for v in lc.violations()] == ["order-cycle"]

    def test_strict_raises_and_releases(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        a, b = lc.make_lock("t.A"), lc.make_lock("t.B")
        with a:
            with b:
                pass
        with pytest.raises(lc.LockcheckError, match="order cycle"):
            with b:
                with a:
                    pass
        # The raise must leave a consistent plane behind: nothing held,
        # the inner lock re-acquirable.
        assert lc.held_locks() == []
        assert a.acquire(timeout=0.5)
        a.release()

    def test_consistent_order_is_clean(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        a, b = lc.make_lock("t.A"), lc.make_lock("t.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lc.violations() == []
        assert lc.order_graph() == {"t.A": ["t.B"]}

    def test_reentrant_is_not_an_edge(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        r = lc.make_rlock("t.R")
        with r:
            with r:
                assert lc.held_locks() == ["t.R"]
        assert lc.held_locks() == []
        assert lc.order_graph() == {}
        assert lc.violations() == []


# --- the other violation kinds ------------------------------------------


class TestViolationKinds:
    def test_self_deadlock_strict(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        lock = lc.make_lock("t.a")
        lock.acquire()
        try:
            with pytest.raises(lc.LockcheckError, match="self-deadlock"):
                lock.acquire()
        finally:
            lock.release()

    def test_bad_release_strict(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        lock = lc.make_lock("t.a")
        with pytest.raises(lc.LockcheckError, match="bad-release"):
            lock.release()

    def test_stall_watchdog(self, monkeypatch, clean_state, event_log):
        monkeypatch.setenv(lc.MODE_ENV, "strict")  # stalls never raise
        monkeypatch.setenv(lc.STALL_ENV, "50")
        lock = lc.make_lock("t.slow")
        release = threading.Event()

        def holder():
            with lock:
                release.wait(5.0)

        t = threading.Thread(target=holder, name="holder")
        t.start()
        while not lock.locked():
            time.sleep(0.001)
        got = lock.acquire()  # blocks past the 50 ms watchdog
        release.set()
        t.join()
        assert got
        lock.release()
        stalls = [v for v in lc.violations() if v["kind"] == "stall"]
        assert len(stalls) == 1
        payload = stalls[0]["threads"]
        assert any(s["waiting"] == "t.slow" for s in payload)
        recs = lockcheck_events(event_log)
        assert [r["action"] for r in recs] == ["stall"]


# --- bookkeeping exactness ----------------------------------------------


class TestBookkeeping:
    def test_condition_wait_notify(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "strict")
        cond = lc.make_condition("t.cond")
        box = []

        def producer():
            with cond:
                box.append(1)
                cond.notify_all()

        with cond:
            assert lc.held_locks() == ["t.cond"]
            threading.Thread(target=producer).start()
            deadline = time.monotonic() + 5.0
            while not box:
                cond.wait(timeout=0.05)
                # Re-acquired after every wait: bookkeeping must agree.
                assert lc.held_locks() == ["t.cond"]
                assert time.monotonic() < deadline
        assert lc.held_locks() == []
        assert lc.violations() == []

    def test_hold_histogram_labelled(self, monkeypatch, clean_state):
        monkeypatch.setenv(lc.MODE_ENV, "warn")
        lock = lc.make_lock("t.timed")
        before = histogram(
            "lockcheck.hold_ms",
            "instrumented-lock hold time per acquisition",
            buckets=lc.HOLD_MS_BUCKETS,
        ).value(lock="t.timed")["count"]
        for _ in range(3):
            with lock:
                pass
        after = histogram("lockcheck.hold_ms").value(lock="t.timed")["count"]
        assert after == before + 3

    def test_graph_dump(self, monkeypatch, clean_state, tmp_path):
        monkeypatch.setenv(lc.MODE_ENV, "warn")
        out = tmp_path / "graph.json"
        monkeypatch.setenv(lc.GRAPH_ENV, str(out))
        a, b = lc.make_lock("t.A"), lc.make_lock("t.B")
        with a:
            with b:
                pass
        lc._dump_graph()
        doc = json.loads(out.read_text())
        assert doc["kind"] == "tpuml-lockcheck-graph"
        assert doc["edges"] == {"t.A": ["t.B"]}
        assert doc["violations"] == []


# --- the static half catches the same seeded bugs -----------------------


class TestStaticHalf:
    def test_unguarded_write_flagged(self, tmp_path):
        findings = lint_src(tmp_path, """
            'fixture.'
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def bump(self):
                    self._n += 1
        """)
        assert rules_of(findings) == {"lock-guarded"}

    def test_interprocedural_helper_is_clean(self, tmp_path):
        # The natural helper shape the runtime half's guarded() mirrors:
        # the helper touches guarded state, every call site holds the
        # lock, the call-graph pass credits it. (This is the exact shape
        # AdmissionQueue._shed / core.serving._publish_cache_size were
        # reverted to.)
        findings = lint_src(tmp_path, """
            'fixture.'
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def bump(self):
                    with self._lock:
                        self._helper()

                def _helper(self):
                    self._n += 1
        """)
        assert findings == []

    def test_inversion_flagged(self, tmp_path):
        findings = lint_src(tmp_path, """
            'fixture.'
            import threading

            _a = threading.Lock()
            _b = threading.Lock()

            def forward():
                with _a:
                    with _b:
                        pass

            def backward():
                with _b:
                    with _a:
                        pass
        """)
        assert rules_of(findings) == {"lock-order"}

    def test_consistent_order_is_clean(self, tmp_path):
        findings = lint_src(tmp_path, """
            'fixture.'
            import threading

            _a = threading.Lock()
            _b = threading.Lock()

            def one():
                with _a:
                    with _b:
                        pass

            def two():
                with _a:
                    with _b:
                        pass
        """)
        assert findings == []

    def test_leak_flagged_and_finally_clean(self, tmp_path):
        findings = lint_src(tmp_path, """
            'fixture.'
            import threading

            _l = threading.Lock()

            def leaky():
                _l.acquire()
                return 1
        """)
        assert rules_of(findings) == {"lock-leak"}
        findings = lint_src(tmp_path, """
            'fixture.'
            import threading

            _l = threading.Lock()

            def safe():
                _l.acquire()
                try:
                    return 1
                finally:
                    _l.release()
        """, name="safe.py")
        assert findings == []

    def test_factory_locks_are_recognized(self, tmp_path):
        # make_lock/make_rlock/make_condition count as lock
        # constructors, so adopting the sanitizer factory keeps every
        # static lock rule armed.
        findings = lint_src(tmp_path, """
            'fixture.'
            from spark_rapids_ml_tpu.utils.lockcheck import make_lock

            class C:
                def __init__(self):
                    self._lock = make_lock("c.lock")
                    self._n = 0  # guarded-by: _lock

                def bump(self):
                    self._n += 1
        """)
        assert rules_of(findings) == {"lock-guarded"}
