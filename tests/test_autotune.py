"""Ledger-driven autotuner contracts (ISSUE 14).

The load-bearing promises, each pinned here:

  - ``TPUML_AUTOTUNE=off`` (the default) is today's behavior bit-for-bit:
    the serving path adds zero compiles (``jax_log_compiles``-asserted),
    zero autotune counters/events, and stays allocation-light;
  - the cost model recovers wall = a·rows + b and bytes = a·rows + b from
    synthetic ledger entries;
  - commit-or-revert NEVER accepts a seeded regression;
  - the serving ladder admits a proven-hot exact batch size — including
    sizes below the 8-row pow-2 minimum — invalidates the program cache,
    and the recompile classifies as a legitimate bucket, not a retrace;
  - the tune store round-trips through JSON and falls back to an empty
    store (counted) on a corrupt file;
  - ``fit_memory_guard`` prices through the fitted bytes model when one
    exists and is bit-identical to the static arithmetic when not;
  - the double-buffered training streams are value- and order-identical
    to the plain loops, with the overlap counter-asserted.
"""

import json
import logging
import os
import tracemalloc

import jax
import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DEFAULT_FIT_BLOCK_ROWS, fit_block_rows
from spark_rapids_ml_tpu.core.serving import (
    bucket_rows,
    clear_program_cache,
    ladder_bucket_rows,
    prefetch_blocks,
    serve_rows,
)
from spark_rapids_ml_tpu.observability import autotune, costs, events
from spark_rapids_ml_tpu.observability.autotune import (
    Autotuner,
    TuneStore,
    fit_cost_models,
)
from spark_rapids_ml_tpu.observability.costs import ProgramCost
from spark_rapids_ml_tpu.utils.tracing import clear_counters, counter_value


def _kernel(x, w):
    return x @ w


@pytest.fixture
def tuner(monkeypatch, tmp_path):
    """An armed tuner (hot_min=3, tmp-file store) over a clean serving
    layer; tears back down to off + disarmed ledger."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_AUTOTUNE_HOT_MIN", "3")
    monkeypatch.setenv("TPUML_TUNE_STORE", str(tmp_path / "tune.json"))
    clear_program_cache()
    clear_counters("autotune.")
    clear_counters("compile.")
    clear_counters("fit.")
    costs.reset_for_tests()
    autotune.reset_for_tests()
    t = autotune.active()
    assert t is not None
    assert costs.active() is not None  # the tuner arms the ledger
    yield t
    autotune.configure(enable=False)
    costs.configure(enable=False)
    clear_program_cache()


@pytest.fixture
def off(monkeypatch):
    monkeypatch.delenv("TPUML_AUTOTUNE", raising=False)
    monkeypatch.delenv("TPUML_COST_LEDGER", raising=False)
    clear_program_cache()
    clear_counters("autotune.")
    clear_counters("compile.")
    costs.reset_for_tests()
    autotune.reset_for_tests()
    assert autotune.active() is None
    yield
    clear_program_cache()


def _inject_entry(
    led, family, rows, *, wall=0.0, invocations=0, arg=None, temp=None,
    out=None,
):
    """Seed one synthetic program entry straight into a live ledger —
    the model-fitting tests need measured-looking evidence without
    compiling one program per data point."""
    key = f"{family}|aot|{rows}x4:float32|{rows:010d}"
    entry = ProgramCost(
        key=key, family=family, kind="aot", static="", rows=int(rows),
        spec=f"{rows}x4:float32", classification="new_program",
        argument_bytes=arg, temp_bytes=temp, output_bytes=out,
        invocations=int(invocations), wall_seconds=float(wall),
    )
    with led._lock:
        led._entries[key] = entry
    return key


# ---------------------------------------------------------------------------
# off mode: bit identity, zero compiles, zero allocation
# ---------------------------------------------------------------------------


class TestOffMode:
    def test_off_serving_bit_identity_zero_compiles(self, off, rng, caplog):
        """Off, the ladder helper IS bucket_rows, results repeat
        bit-for-bit, and the warm path never recompiles."""
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))
        batches = [rng.normal(size=(n, 6)).astype(np.float32)
                   for n in (3, 30, 200)]
        for n in (1, 3, 7, 8, 9, 100):
            assert ladder_bucket_rows(n, name="off.kern", width=6) == bucket_rows(n)
        first = [np.asarray(serve_rows(_kernel, x, (w,), name="off.kern"))
                 for x in batches]
        jax.config.update("jax_log_compiles", True)
        try:
            with caplog.at_level(logging.WARNING, logger="jax._src.dispatch"):
                second = [
                    np.asarray(serve_rows(_kernel, x, (w,), name="off.kern"))
                    for x in batches
                ]
        finally:
            jax.config.update("jax_log_compiles", False)
        assert [
            r for r in caplog.records if "XLA compilation" in r.getMessage()
        ] == []
        for a, b in zip(first, second):
            assert np.array_equal(a, b)
        assert counter_value("autotune.commit") == 0
        assert counter_value("autotune.ladder.grow") == 0

    def test_off_fit_block_rows_is_static_default(self, off):
        assert fit_block_rows() == DEFAULT_FIT_BLOCK_ROWS
        assert fit_block_rows("kmeans", width=64) == DEFAULT_FIT_BLOCK_ROWS

    def test_env_knob_beats_tuner(self, tuner, monkeypatch):
        """An explicitly set TPUML_FIT_BLOCK_ROWS wins even with the
        tuner on — operator overrides are never second-guessed."""
        monkeypatch.setenv("TPUML_FIT_BLOCK_ROWS", "1234")
        assert fit_block_rows("anything", width=8) == 1234

    def test_off_zero_allocation_guard(self, off, rng):
        """Warm off-mode serving stays allocation-light and emits no
        autotune events — the disabled tuner costs one None check."""
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))
        x = rng.normal(size=(16, 6)).astype(np.float32)
        serve_rows(_kernel, x, (w,), name="off.alloc")  # compile outside
        before_events = events.emitted_count()
        n = 50
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        for _ in range(n):
            serve_rows(_kernel, x, (w,), name="off.alloc")
        current, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert events.emitted_count() == before_events
        assert counter_value("autotune.commit") == 0
        assert peak - base < n * 65536


# ---------------------------------------------------------------------------
# the cost model
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_fit_recovers_synthetic_coefficients(self):
        """wall = 2e-6·rows + 5e-4 and bytes = 48·rows + 1000 seeded at
        three row counts come back within 1%."""
        A, B, BA, BB = 2e-6, 5e-4, 48.0, 1000.0
        entries = []
        for rows in (100, 400, 1600):
            entries.append(ProgramCost(
                key=f"m|aot|{rows}", family="m.serve", kind="aot",
                static="", spec="", rows=rows, classification="new_program",
                invocations=4, wall_seconds=4 * (A * rows + B),
                argument_bytes=int(BA * rows + BB), temp_bytes=0,
                output_bytes=0,
            ))
        models = fit_cost_models(entries)
        m = models["m.serve"]
        assert m.wall_a == pytest.approx(A, rel=0.01)
        assert m.wall_b == pytest.approx(B, rel=0.01)
        assert m.bytes_a == pytest.approx(BA, rel=0.01)
        assert m.bytes_b == pytest.approx(BB, rel=0.01)
        assert m.points == 3 and len(m.evidence) == 3
        assert m.predict_wall(1000) == pytest.approx(A * 1000 + B, rel=0.01)
        assert m.predict_bytes(1000) == pytest.approx(BA * 1000 + BB, rel=0.01)

    def test_single_point_and_compile_exclusion(self):
        """One distinct row count degrades to a=y/x, b=0; an entry that
        only ever compiled (zero invocations) contributes no wall point;
        entries without rows contribute nothing at all."""
        entries = [
            ProgramCost(
                key="s|1", family="s", kind="aot", static="", spec="",
                rows=200, classification="new_program", invocations=2,
                wall_seconds=2 * 0.01, compile_seconds=99.0,
            ),
            ProgramCost(
                key="s|2", family="cold", kind="aot", static="", spec="",
                rows=100, classification="new_program", invocations=0,
                wall_seconds=0.0,
            ),
            ProgramCost(
                key="s|3", family="rowless", kind="fallback", static="",
                spec="", rows=None, classification="new_program",
                invocations=5, wall_seconds=1.0,
            ),
        ]
        models = fit_cost_models(entries)
        assert models["s"].wall_a == pytest.approx(0.01 / 200)
        assert models["s"].wall_b == 0.0
        assert "cold" not in models  # no wall AND no bytes points
        assert "rowless" not in models


# ---------------------------------------------------------------------------
# commit-or-revert
# ---------------------------------------------------------------------------


class TestCommitOrRevert:
    def test_seeded_regression_never_accepted(self, tuner):
        assert tuner.record_trial("fit_block_rows", "fam", 16384, 1.0) is True
        assert counter_value("autotune.commit") == 1
        # The seeded regression: slower candidate must be rejected.
        assert tuner.record_trial("fit_block_rows", "fam", 65536, 2.0) is False
        assert counter_value("autotune.revert") == 1
        dec = tuner.store.get("fit_block_rows", "fam")
        assert dec["value"] == 16384 and dec["metric"] == 1.0
        assert dec["rejected"][-1] == {
            "value": 65536, "metric": 2.0, "reason": "regression",
        }
        # And it stays rejected no matter how often it is re-offered.
        assert tuner.record_trial("fit_block_rows", "fam", 65536, 1.5) is False
        assert tuner.store.get("fit_block_rows", "fam")["value"] == 16384

    def test_better_candidate_supersedes(self, tuner):
        tuner.record_trial("fit_block_rows", "fam", 16384, 1.0)
        assert tuner.record_trial("fit_block_rows", "fam", 32768, 0.5) is True
        dec = tuner.store.get("fit_block_rows", "fam")
        assert dec["value"] == 32768
        assert {"value": 16384, "metric": 1.0, "reason": "superseded"} in dec["rejected"]

    def test_measure_and_commit_collects_ledger_evidence(self, tuner, rng):
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32))
        x = rng.normal(size=(64, 4)).astype(np.float32)

        result, metric, committed = tuner.measure_and_commit(
            "fit_block_rows", "mc.fam", 64,
            lambda: serve_rows(_kernel, x, (w,), name="mc.kern"),
            rows=64,
        )
        assert committed is True and metric > 0.0
        dec = tuner.store.get("fit_block_rows", "mc.fam")
        assert any("mc.kern" in e for e in dec["evidence"])
        assert np.asarray(result).shape == (64, 2)

    def test_committed_block_rows_drive_fit_block_rows(self, tuner):
        tuner.record_trial("fit_block_rows", "famx", 16384, 0.1)
        assert tuner.recommend_block_rows("famx", default=DEFAULT_FIT_BLOCK_ROWS) == 16384
        assert fit_block_rows("famx") == 16384


# ---------------------------------------------------------------------------
# the learned serving ladder
# ---------------------------------------------------------------------------


class TestLadder:
    def test_hot_tiny_size_gets_exact_bucket(self, tuner, rng, caplog):
        """A steady 3-row stream pads to the 8-row min bucket until the
        histogram proves it hot; then the ladder admits an exact 3-row
        rung, the cache invalidates, and exactly ONE new program compiles
        at rows=3 — classified as a bucket, not a retrace."""
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))
        x3 = rng.normal(size=(3, 6)).astype(np.float32)
        cold = [np.asarray(serve_rows(_kernel, x3, (w,), name="lad.kern"))
                for _ in range(2)]
        assert tuner.peek_serving_bucket("lad.kern", 6, 3, bucket_rows(3)) == 8
        # The third sighting crosses hot_min: the ladder admits an exact
        # 3-row rung, invalidates the cache, and THIS call compiles the
        # one rows=3 program; the follow-up calls ride the cache.
        jax.config.update("jax_log_compiles", True)
        try:
            with caplog.at_level(logging.WARNING, logger="jax._src.dispatch"):
                grew = np.asarray(serve_rows(_kernel, x3, (w,), name="lad.kern"))
                warm1 = np.asarray(serve_rows(_kernel, x3, (w,), name="lad.kern"))
                warm2 = np.asarray(serve_rows(_kernel, x3, (w,), name="lad.kern"))
        finally:
            jax.config.update("jax_log_compiles", False)
        compiles = [
            r for r in caplog.records if "XLA compilation" in r.getMessage()
        ]
        assert len(compiles) == 1
        assert counter_value("autotune.ladder.grow") == 1
        assert counter_value("compile.retrace") == 0
        assert tuner.peek_serving_bucket("lad.kern", 6, 3, bucket_rows(3)) == 3
        assert tuner.is_ladder_bucket(3)
        # Bit-identical outputs across the ladder transition.
        for out in cold + [grew, warm1, warm2]:
            assert np.array_equal(out, cold[0])
        # Cold sizes still round up through the pow-2 ladder.
        assert tuner.peek_serving_bucket("lad.kern", 6, 5, bucket_rows(5)) == 8

    def test_ladder_decision_persists_and_reloads(self, tuner, tmp_path):
        for _ in range(3):
            tuner.serving_bucket("per.kern", 4, 100, bucket_rows(100))
        dec = tuner.store.get("serving_ladder", "per.kern|4")
        assert dec["value"] == [100]
        # A fresh tuner over the same store starts with the ladder live.
        t2 = Autotuner(TuneStore(tuner.store.path), hot_min=3)
        assert t2.peek_serving_bucket("per.kern", 4, 100, bucket_rows(100)) == 100
        assert t2.is_ladder_bucket(100)

    def test_pricing_peek_agrees_without_observing(self, tuner):
        for _ in range(3):
            tuner.serving_bucket("pr.kern", 4, 37, bucket_rows(37))
        counts_before = dict(tuner._batch_counts[("pr.kern", 4)])
        assert tuner.peek_serving_bucket("pr.kern", 4, 37, bucket_rows(37)) == 37
        assert tuner._batch_counts[("pr.kern", 4)] == counts_before


# ---------------------------------------------------------------------------
# the tune store
# ---------------------------------------------------------------------------


class TestTuneStore:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "store.json")
        s = TuneStore(path)
        s.put({
            "knob": "fit_block_rows", "key": "fam", "value": 8192,
            "metric": 0.25, "metric_name": "seconds_per_row",
            "evidence": ["k|aot|x"], "rejected": [], "trials": 1,
            "updated": 0.0,
        })
        s2 = TuneStore(path)
        assert s2.get("fit_block_rows", "fam")["value"] == 8192
        assert s2.get("fit_block_rows", "fam")["evidence"] == ["k|aot|x"]
        doc = json.load(open(path))
        assert doc["version"] == 1 and "fit_block_rows|fam" in doc["decisions"]
        # Atomic write leaves no tmp droppings.
        assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    def test_corrupt_file_falls_back_empty(self, tmp_path):
        path = str(tmp_path / "store.json")
        with open(path, "w") as f:
            f.write("{this is not json")
        clear_counters("autotune.store")
        s = TuneStore(path)
        assert s.corrupt is True
        assert s.snapshot() == []
        assert counter_value("autotune.store.corrupt") == 1
        # The store still works — and heals the file on the next commit.
        s.put({"knob": "k", "key": "f", "value": 1, "metric": 1.0,
               "metric_name": "m", "evidence": [], "rejected": [],
               "trials": 1, "updated": 0.0})
        assert TuneStore(path).get("k", "f")["value"] == 1

    def test_memory_only_store(self):
        s = TuneStore(None)
        s.put({"knob": "k", "key": "f", "value": 2})
        assert s.get("k", "f")["value"] == 2


# ---------------------------------------------------------------------------
# membudget pricing (decision d)
# ---------------------------------------------------------------------------


class TestMembudgetPricing:
    def _guard(self, family, x):
        from spark_rapids_ml_tpu.core.membudget import fit_memory_guard
        from spark_rapids_ml_tpu.robustness.degrade import DegradationWarning

        # Every guard call here is sized to degrade — the warning is the
        # expected outcome, not noise.
        with pytest.warns(DegradationWarning, match="exceeds the fit memory"):
            return fit_memory_guard(
                family, x, can_stream=True, dtype=np.float32,
            )

    def test_parity_without_model(self, tuner, off_budget_env, rng):
        """Tuner on but NO fitted model for the family: admission prices
        exactly like the static arithmetic (bit-identical needed_bytes)."""
        x = rng.normal(size=(1000, 8)).astype(np.float32)
        on = self._guard("nomodel", x)
        autotune.configure(enable=False)
        try:
            off_adm = self._guard("nomodel", x)
        finally:
            autotune.configure(enable=True)
        assert on.degrade and off_adm.degrade
        assert on.needed_bytes == off_adm.needed_bytes
        assert counter_value("fit.admission.model_priced") == 0

    def test_model_prices_admission(self, tuner, off_budget_env, rng):
        """With byte evidence in the ledger, admission prices through the
        fitted model instead of the padding arithmetic."""
        led = costs.active()
        _inject_entry(
            led, "modfam.solve", 500, arg=5000, temp=2500, out=2500,
        )
        x = rng.normal(size=(1000, 8)).astype(np.float32)
        adm = self._guard("modfam", x)
        assert counter_value("fit.admission.model_priced") == 1
        # Single point: bytes_a = 10000/500 = 20/row -> 20000 at n=1000.
        assert adm.needed_bytes == 20000
        assert adm.degrade  # 20000 > the 15000 budget below

    def test_oom_ceiling_caps_recommendations(self, tuner):
        tuner.record_trial("fit_block_rows", "oomfam", 65536, 0.5)
        tuner.note_oom("oomfam", 65536)
        rec = tuner.recommend_block_rows("oomfam", default=DEFAULT_FIT_BLOCK_ROWS)
        assert rec <= 32768  # never at/above the ledgered-fatal block
        # The ceiling survives a store reload.
        t2 = Autotuner(TuneStore(tuner.store.path), hot_min=3)
        assert t2.recommend_block_rows(
            "oomfam", default=DEFAULT_FIT_BLOCK_ROWS
        ) <= 32768


@pytest.fixture
def off_budget_env(monkeypatch):
    monkeypatch.setenv("TPUML_FIT_MEM_BUDGET", "15000")
    clear_counters("fit.admission")
    yield


# ---------------------------------------------------------------------------
# deadline + shard threshold (decision c)
# ---------------------------------------------------------------------------


class TestMeasuredDeadlines:
    def test_delay_tracks_p95_wall(self, tuner):
        assert tuner.recommend_delay_s("cold.kern", 0.005) == 0.005
        for _ in range(20):
            tuner.observe_wall("hot.kern", 256, 0.020)
        assert tuner.recommend_delay_s("hot.kern", 0.005) == pytest.approx(0.020)

    def test_delay_shrinks_for_fast_programs(self, tuner):
        for _ in range(20):
            tuner.observe_wall("fast.kern", 256, 0.0002)
        assert tuner.recommend_delay_s("fast.kern", 0.005) == pytest.approx(0.0002)

    def test_shard_rows_from_wall_model(self, tuner):
        led = costs.active()
        # 1 ms/1k rows slope, measured at two row counts.
        _inject_entry(led, "sh.kern", 1000, wall=4 * 0.001, invocations=4)
        _inject_entry(led, "sh.kern", 4000, wall=4 * 0.004, invocations=4)
        assert tuner.recommend_shard_rows("sh.kern") is None  # no samples yet
        for _ in range(10):
            tuner.observe_wall("sh.kern", 4000, 0.004)
        rows = tuner.recommend_shard_rows("sh.kern")
        # 4x the p95 wall (0.016s) at 1us/row -> 16000 rows, next pow2.
        assert rows == 16384
        assert rows >= 2 * 4000

    def test_batcher_uses_tuned_delay(self, tuner, monkeypatch):
        """The MicroBatcher's gather deadline derives from the tuner."""
        from spark_rapids_ml_tpu.serving.batcher import MicroBatcher

        class _Sig:
            name = "bat.kern"

        class _MV:
            signature = _Sig()

        class _Req:
            version = _MV()

        mb = MicroBatcher.__new__(MicroBatcher)
        mb.max_delay_s = 0.005
        for _ in range(20):
            tuner.observe_wall("bat.kern", 64, 0.001)
        assert mb._delay_s_for(_Req()) == pytest.approx(0.001)
        autotune.configure(enable=False)
        try:
            assert mb._delay_s_for(_Req()) == 0.005
        finally:
            autotune.configure(enable=True)


# ---------------------------------------------------------------------------
# double-buffered training streams (satellite 1)
# ---------------------------------------------------------------------------


class TestDoubleBuffer:
    def test_prefetch_values_order_and_counter(self, off):
        clear_counters("fit.stream")
        blocks = [np.full((2, 2), i, np.float32) for i in range(5)]
        seen = []

        def prepare(b):
            seen.append(int(b[0, 0]))
            return b * 2.0

        got = list(prefetch_blocks(blocks, prepare))
        assert len(got) == 5
        for g, b in zip(got, blocks):
            assert np.array_equal(g, b * 2.0)
        # prepare ran in order, one block ahead of the yields.
        assert seen == [0, 1, 2, 3, 4]
        assert counter_value("fit.stream.prefetched") == 4

    def test_prefetch_empty_and_single(self, off):
        clear_counters("fit.stream")
        assert list(prefetch_blocks([], lambda b: b)) == []
        assert list(prefetch_blocks([np.ones(2)], lambda b: b)) == [
            pytest.approx(np.ones(2))
        ]
        assert counter_value("fit.stream.prefetched") == 0

    def test_linear_streaming_bit_identical(self, off, rng):
        """normal_eq_stats_streaming (now prefetched) == the plain loop
        it replaced, bit for bit."""
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.linear import (
            normal_eq_stats,
            normal_eq_stats_streaming,
        )

        blocks = [
            (rng.normal(size=(n, 5)), rng.normal(size=(n,)))
            for n in (64, 32, 1, 128)
        ]
        clear_counters("fit.stream")
        got = normal_eq_stats_streaming(blocks, dtype=np.float64)
        assert counter_value("fit.stream.prefetched") == len(blocks) - 1
        # The pre-change loop, replayed verbatim.
        acc = None
        for xb, yb in blocks:
            xj = jnp.asarray(np.ascontiguousarray(xb), dtype=np.float64)
            yj = jnp.asarray(np.ascontiguousarray(yb), dtype=np.float64)
            mask = jnp.ones(xj.shape[0], dtype=xj.dtype)
            stats = normal_eq_stats(xj, yj, mask, precision="highest")
            acc = stats if acc is None else tuple(
                a + s for a, s in zip(acc, stats)
            )
        for g, e in zip(got, acc):
            assert np.array_equal(np.asarray(g), np.asarray(e))

    def test_covariance_streaming_bit_identical(self, off, rng):
        """The prefetched shifted scan == the plain scan, bit for bit."""
        from spark_rapids_ml_tpu.ops.covariance import (
            centered_gram,
            shifted_block_scan,
        )
        import jax.numpy as jnp

        blocks = [rng.normal(size=(n, 4)) for n in (32, 16, 64)]
        zeros = jnp.zeros((4,), dtype=jnp.float64)

        def gram_fn(bs):
            return centered_gram(jnp.asarray(bs, dtype=jnp.float64), zeros)

        shift, gram, s, n = shifted_block_scan(blocks, True, gram_fn)
        # The pre-change loop, replayed verbatim.
        shift2 = gram2 = s2 = None
        n2 = 0
        for b in blocks:
            b = np.asarray(b)
            if shift2 is None:
                shift2 = b.mean(axis=0)
            bs = b - shift2
            g = gram_fn(bs)
            gram2 = g if gram2 is None else gram2 + g
            sb = bs.sum(axis=0)
            s2 = sb if s2 is None else s2 + sb
            n2 += b.shape[0]
        assert np.array_equal(np.asarray(shift), np.asarray(shift2))
        assert np.array_equal(np.asarray(gram), np.asarray(gram2))
        assert np.array_equal(np.asarray(s), np.asarray(s2))
        assert n == n2

    def test_kmeans_streaming_overlap_counted(self, off, rng):
        """lloyd_streaming runs through the prefetch path (overlap
        counter) and stays deterministic across runs."""
        from spark_rapids_ml_tpu.ops.kmeans import lloyd_streaming

        x = rng.normal(size=(200, 3)).astype(np.float64)
        init = x[:4].copy()
        blocks = lambda: (x[i:i + 64] for i in range(0, 200, 64))
        clear_counters("fit.stream")
        c1, cost1, it1 = lloyd_streaming(blocks, init, max_iter=3)
        assert counter_value("fit.stream.prefetched") > 0
        c2, cost2, it2 = lloyd_streaming(blocks, init, max_iter=3)
        assert np.array_equal(np.asarray(c1), np.asarray(c2))
        assert float(cost1) == float(cost2) and it1 == it2


# ---------------------------------------------------------------------------
# the report + prof surfaces
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_serving_report_carries_tuner_section(self, tuner):
        from spark_rapids_ml_tpu.observability.report import serving_report

        tuner.record_trial("fit_block_rows", "rep.fam", 4096, 0.5)
        doc = serving_report()
        assert doc["autotune"]["enabled"] is True
        assert any(
            d["key"] == "rep.fam" for d in doc["autotune"]["decisions"]
        )

    def test_serving_report_omits_section_when_off(self, off):
        from spark_rapids_ml_tpu.observability.report import serving_report

        assert "autotune" not in serving_report()

    def test_prof_tune_subcommand(self, tuner, capsys):
        from tools import tpuml_prof

        tuner.record_trial(
            "fit_block_rows", "prof.fam", 8192, 0.5, evidence=["e|aot|1"],
        )
        tuner.record_trial("fit_block_rows", "prof.fam", 16384, 0.9)
        assert tpuml_prof.main(["tune", tuner.store.path]) == 0
        out = capsys.readouterr().out
        assert "fit_block_rows[prof.fam] = 8192" in out
        assert "rejected 16384" in out and "regression" in out

    def test_prof_tune_explain(self, tuner, tmp_path, capsys):
        from tools import tpuml_prof

        led = costs.active()
        _inject_entry(
            led, "ex.kern", 1000, wall=2 * 0.002, invocations=2,
            arg=4000, temp=100, out=200,
        )
        ledger_path = str(tmp_path / "ledger.json")
        costs.dump_ledger(ledger_path)
        tuner.record_trial("fit_block_rows", "ex.kern", 2048, 0.1)
        assert tpuml_prof.main(
            ["tune", tuner.store.path, "--explain", "ex.kern",
             "--ledger", ledger_path]
        ) == 0
        out = capsys.readouterr().out
        assert "wall(rows)" in out and "bytes(rows)" in out
        assert "fit_block_rows[ex.kern] = 2048" in out

    def test_prof_tune_corrupt_store(self, tmp_path, capsys):
        from tools import tpuml_prof

        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            f.write("]]]")
        assert tpuml_prof.main(["tune", bad]) == 2
