"""Elastic gang membership (ISSUE 16): join, retire, stall, crash —
under live load, and nobody sheds.

The serving-side acceptance surface:

  - **Zero-shed join.** ``RoutingRuntime.add_member`` grows the gang
    mid-traffic: the joiner replays the lsn-ordered op log before it is
    ever selectable, so every in-flight request completes bitwise
    correct and the shed counters never move (event-log proof:
    ``member_join`` carries the replayed op count and final lsn).
  - **Drain-then-detach retire** plus gauge hygiene: after a full
    ramp-up/ramp-down episode the registry holds zero stale
    ``serving.router.member.depth`` series and the merged member shards
    zero ``gang.heartbeat.age_seconds`` series — and the merged trace
    passes ``tools/tpuml_trace.py --validate --strict``.
  - **Stall retire.** A member frozen by ``ipc.recv=always@K:stall``
    keeps its socket open but its frame-loop heartbeat age grows; the
    scaler's liveness check retires it BEFORE any EOF and its orphaned
    requests redispatch losslessly.
  - **Death mid-broadcast.** A member killed by a seeded ``ipc.recv``
    fault while a registry op is in flight is classified SKIPPED
    (``replicate_skip``), the survivors carry the op, and lsn
    continuity holds for every later op.
  - **ElasticScaler votes**: shed pressure scales up through the
    zero-shed join, sustained idle scales down through the drain path,
    bounds hold.

Float parity uses the dyadic-rational posture of the serving suites:
integers/4 make every distance computation exact in f64, so "bitwise
equal to the sequential model call" holds across process hops and
membership changes.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.kmeans import KMeansModel
from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.observability import trace as tracelib
from spark_rapids_ml_tpu.observability.metrics import default_registry
from spark_rapids_ml_tpu.robustness import faults
from spark_rapids_ml_tpu.serving import ElasticScaler, RoutingRuntime
from spark_rapids_ml_tpu.utils.envknobs import env_str
from spark_rapids_ml_tpu.utils.tracing import bump_counter, counter_value

REPO = Path(__file__).resolve().parents[1]
TRACE_CLI = REPO / "tools" / "tpuml_trace.py"

D = 8


def dyadic(rng, shape, scale=4):
    return rng.integers(-4 * scale, 4 * scale, size=shape).astype(np.float64) / 4.0


_PREV_LOG = env_str(events.EVENT_LOG_ENV)


def _restore_sink():
    events.configure(_PREV_LOG if _PREV_LOG else None)


@pytest.fixture
def telemetry(tmp_path):
    """A fresh telemetry dir as the active sink, exported to the
    environment so spawned members inherit it and write their own
    shards (the tests/test_serving_router.py arrangement)."""
    d = str(tmp_path / "telemetry")
    prev = env_str(events.TELEMETRY_DIR_ENV)
    os.environ[events.TELEMETRY_DIR_ENV] = d
    events.configure()
    try:
        yield Path(d)
    finally:
        if prev is None:
            os.environ.pop(events.TELEMETRY_DIR_ENV, None)
        else:
            os.environ[events.TELEMETRY_DIR_ENV] = prev
        _restore_sink()


def _serving_records(telemetry_dir):
    events.flush_telemetry()
    merged = tracelib.assemble(str(telemetry_dir))
    return merged, [r for r in merged["records"] if r.get("event") == "serving"]


# ---------------------------------------------------------------------------
# fault grammar: the @K skip offset and the :stall freeze
# ---------------------------------------------------------------------------


class TestFaultGrammar:
    def test_skip_offset_parses_and_windows(self):
        plan = faults.parse_spec("ipc.recv=2@3")
        sched = plan["ipc.recv"]
        assert (sched.count, sched.skip) == (2, 3)
        assert [sched.should_fail(i) for i in range(6)] == [
            False, False, False, True, True, False,
        ]

    def test_always_with_skip(self):
        sched = faults.parse_spec("ipc.send=always@4")["ipc.send"]
        assert sched.count == faults.ALWAYS and sched.skip == 4
        assert not sched.should_fail(3)
        assert sched.should_fail(4) and sched.should_fail(4000)

    def test_stall_suffix_stacks_with_skip(self):
        sched = faults.parse_spec("ipc.recv=always@3:stall")["ipc.recv"]
        assert sched.stall and sched.skip == 3 and sched.count == faults.ALWAYS
        assert not sched.fatal and not sched.torn

    def test_member_sites_known(self):
        plan = faults.parse_spec("member.launch=1;member.join=1@1")
        assert plan["member.launch"].count == 1
        assert plan["member.join"].skip == 1

    def test_malformed_skip_rejected(self):
        with pytest.raises(ValueError, match="skip offset"):
            faults.parse_spec("ipc.recv=1@x")
        with pytest.raises(ValueError, match="skip offset"):
            faults.parse_spec("ipc.recv=1@-2")

    def test_stall_blocks_until_disarmed(self):
        """The :stall freeze is the stuck-but-alive mode: the site
        parks (no raise) and wakes only when the plan goes away."""
        done = threading.Event()

        def run():
            faults.fault_point("ipc.recv")
            done.set()

        with faults.inject("ipc.recv=always:stall") as plan:
            t = threading.Thread(target=run, daemon=True)
            t.start()
            time.sleep(0.3)
            assert not done.is_set(), ":stall site returned while armed"
        assert done.wait(5.0), ":stall site never woke after disarm"
        assert plan.fired == [("ipc.recv", 0)]


# ---------------------------------------------------------------------------
# the full elastic episode: ramp up -> join -> ramp down -> retire -> drain
# ---------------------------------------------------------------------------


class TestElasticEpisode:
    N_THREADS = 4
    PER_THREAD = 25

    def test_join_retire_episode_sheds_nothing_and_leaves_no_stale_series(
        self, telemetry
    ):
        """One member carries the low phase; the gang grows by one under
        live load (zero shed, event-log join proof), both members carry
        the burst, the joiner retires on ramp-down, and the drained
        episode leaves no stale gauge series anywhere — with the merged
        multi-process trace strict-clean."""
        rng = np.random.default_rng(61)
        centers = dyadic(rng, (4, D))
        model = KMeansModel("elastic-km", centers)
        n = self.N_THREADS * self.PER_THREAD
        probes = dyadic(rng, (n, D))
        expected = model.predict(probes)

        shed0 = counter_value("serving.router.shed")
        rejected0 = counter_value("serving.router.rejected")
        rt = RoutingRuntime(workers=1, launch="spawn", max_delay_ms=1.0)
        rid = rt.router_id
        errors: list = []
        try:
            rt.register("km", model, warm_buckets=(1,))

            # Low phase: the single member carries a trickle.
            for i in range(8):
                out = rt.submit("km", probes[i]).result(timeout=60)
                np.testing.assert_array_equal(
                    np.asarray(out), expected[i : i + 1]
                )

            # Ramp up: 4 threads stream rows while the gang grows.
            collected = []
            lock = threading.Lock()

            def worker(tid):
                local = []
                for j in range(self.PER_THREAD):
                    i = tid * self.PER_THREAD + j
                    try:
                        out = rt.submit("km", probes[i]).result(timeout=120)
                        local.append((i, np.asarray(out)))
                    except Exception as exc:  # noqa: BLE001 - asserted below
                        errors.append((i, repr(exc)))
                with lock:
                    collected.extend(local)

            threads = [
                threading.Thread(target=worker, args=(t,))
                for t in range(self.N_THREADS)
            ]
            for t in threads:
                t.start()
            time.sleep(0.05)
            new_member = rt.add_member()
            assert new_member == 1
            assert rt.live_member_ids() == [0, 1]
            # A post-join burst guarantees the joiner takes traffic even
            # if the threads finished while it was connecting.
            burst = [rt.submit("km", probes[i]) for i in range(8)]
            for i, fut in enumerate(burst):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=60)), expected[i : i + 1]
                )
            for t in threads:
                t.join()

            # Ramp down: retire the joiner through drain-then-detach.
            rt.retire_member(new_member)
            assert rt.live_member_ids() == [0]
            for i in range(8):
                out = rt.submit("km", probes[i]).result(timeout=60)
                np.testing.assert_array_equal(
                    np.asarray(out), expected[i : i + 1]
                )
            snap = rt.snapshot()
        finally:
            rt.close()

        # Nobody shed, nothing failed, every bit correct.
        assert errors == [], errors[:5]
        assert counter_value("serving.router.shed") == shed0
        assert counter_value("serving.router.rejected") == rejected0
        assert len(collected) == n
        for i, out in collected:
            np.testing.assert_array_equal(out, expected[i : i + 1])

        # Both members carried load; the joiner's share came post-join.
        by_id = {m["member"]: m for m in snap["members"]}
        assert by_id[0]["routed"] > 0 and by_id[1]["routed"] > 0
        assert by_id[1]["shed"] == 0

        # Event-log join proof: the member replayed the FULL op log
        # (register + warm) and was admitted at the current lsn; its
        # retirement is drain (member_retire) then down (reason
        # "retired"), never "connection lost".
        merged, recs = _serving_records(telemetry)
        joins = [r for r in recs if r.get("action") == "member_join"]
        assert len(joins) == 1
        assert joins[0]["member"] == new_member
        assert joins[0]["ops_replayed"] == 2
        assert joins[0]["lsn"] == 2
        retires = [r for r in recs if r.get("action") == "member_retire"]
        assert [r["member"] for r in retires] == [new_member]
        downs = {
            r["member"]: r["reason"]
            for r in recs
            # The router's view (workers emit their own reason-less
            # member_down at exit; the classification lives router-side).
            if r.get("action") == "member_down" and r.get("router")
        }
        assert downs[new_member] == "retired"
        assert not any(r.get("action") == "route_shed" for r in recs)

        # Gauge hygiene, this process: the drained episode retired every
        # per-member depth series for this router.
        gsnap = default_registry.snapshot()["gauges"]
        for name in gsnap:
            assert rid not in name, f"stale router gauge series {name!r}"

        # Gauge hygiene, member shards: each worker's heartbeat stop()
        # retired its age series before the shard flushed.
        merged_gauges = merged["metrics"]["merged"]["gauges"]
        stale = [
            name
            for name in merged_gauges
            if name.startswith("gang.heartbeat.age_seconds")
            or name.startswith("serving.router.member.depth")
        ]
        assert stale == [], f"stale gauge series in merged shards: {stale}"

        # The CLI is the oracle: ONE strict-clean merged trace across
        # router + both members, join and retire included.
        r = subprocess.run(
            [sys.executable, str(TRACE_CLI), str(telemetry),
             "--validate", "--strict"],
            capture_output=True, text=True, cwd=str(REPO),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# stall: frozen frame loop, open socket — retired by heartbeat age
# ---------------------------------------------------------------------------


class TestStallRetire:
    def test_stalled_member_retired_before_eof_and_requests_survive(
        self, telemetry, monkeypatch
    ):
        """A member whose frame loop freezes mid-conversation (the
        ``:stall`` fault) keeps its socket open, so EOF detection never
        fires; its reported heartbeat age grows instead, and the
        scaler's liveness tick force-retires it. The submit parked on
        the frozen member redispatches and completes bitwise correct."""
        rng = np.random.default_rng(62)
        model = KMeansModel("stall-km", dyadic(rng, (4, D)))
        probes = dyadic(rng, (12, D))
        expected = model.predict(probes)

        stall0 = counter_value("serving.elastic.stall")
        rt = RoutingRuntime(workers=2, launch="spawn", max_delay_ms=1.0)
        try:
            rt.register("km", model, warm_buckets=(1,))
            # Arm ONLY the joiner: members spawned from here inherit the
            # env and arm at import. Its recv sequence is hello(0),
            # replay register(1), replay warm(2) — so @3 lets the join
            # complete cleanly and freezes on the first routed frame.
            monkeypatch.setenv(faults.FAULTS_ENV, "ipc.recv=always@3:stall")
            stalled_id = rt.add_member()
            monkeypatch.delenv(faults.FAULTS_ENV)
            assert rt.live_member_ids() == [0, 1, stalled_id]

            # A concurrent burst spreads across all three members; the
            # one that lands on the armed member freezes its frame loop.
            futs = [rt.submit("km", probes[i]) for i in range(12)]

            scaler = ElasticScaler(
                rt, min_members=1, max_members=4, hysteresis=1000,
                cooldown_ms=0.0, stall_after_s=1.0,
            )
            deadline = time.monotonic() + 30.0
            action = None
            while action is None and time.monotonic() < deadline:
                action = scaler.tick()
                time.sleep(0.05)
            assert action == "stall_retire"
            assert scaler.decisions == [("stall_retire", (stalled_id,))]
            assert counter_value("serving.elastic.stall") == stall0 + 1

            # No request was lost: the frozen member's orphans
            # redispatched through the lost-member ladder.
            for i, fut in enumerate(futs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=60)), expected[i : i + 1]
                )
            snap = rt.snapshot()
        finally:
            rt.close()

        by_id = {m["member"]: m for m in snap["members"]}
        assert by_id[stalled_id]["dead"]
        assert rt.live_member_ids() == []  # closed

        _, recs = _serving_records(telemetry)
        stalls = [r for r in recs if r.get("action") == "member_stalled"]
        assert [r["member"] for r in stalls] == [stalled_id]
        assert stalls[0]["age_s"] > 1.0
        downs = {
            r["member"]: r["reason"]
            for r in recs
            if r.get("action") == "member_down" and r.get("router")
        }
        assert downs.get(stalled_id) == "stalled"

        # The router-side depth series for the killed member is gone.
        gsnap = default_registry.snapshot()["gauges"]
        for name in gsnap:
            assert rt.router_id not in name, name


# ---------------------------------------------------------------------------
# crash mid-broadcast: the op survives on the survivors, lsn stays dense
# ---------------------------------------------------------------------------


class TestDeadMemberBroadcast:
    def test_member_death_mid_broadcast_is_skipped_not_fatal(
        self, telemetry, monkeypatch
    ):
        """A member seeded to die on its next frame receive takes the
        registry-op broadcast down with it — the router classifies it
        SKIPPED (``replicate_skip``), the survivors ack with the same
        version, and every LATER op still sees dense lsns."""
        rng = np.random.default_rng(63)
        m1 = KMeansModel("bc-v1", dyadic(rng, (4, D)))
        m2 = KMeansModel("bc-v2", dyadic(rng, (4, D)) + 32.0)
        probes = dyadic(rng, (6, D))

        rt = RoutingRuntime(workers=1, launch="spawn", max_delay_ms=1.0)
        try:
            rt.register("a", m1)  # oplog: [register a] -> lsn 1
            # Joiner recv sequence: hello(0), replay register(1); the
            # NEXT frame it receives (the live broadcast) kills it.
            monkeypatch.setenv(faults.FAULTS_ENV, "ipc.recv=1@2")
            victim = rt.add_member()
            monkeypatch.delenv(faults.FAULTS_ENV)
            assert rt.live_member_ids() == [0, victim]

            mv2 = rt.register("b", m2)  # the broadcast the victim dies on
            assert mv2.version == 1

            # The gang shrank but the op landed: the survivor serves the
            # new model bitwise correct, and another op keeps the lsn
            # sequence dense (a discontinuity would raise).
            deadline = time.monotonic() + 10.0
            while victim in rt.live_member_ids():
                assert time.monotonic() < deadline, "victim EOF never seen"
                time.sleep(0.02)
            out = rt.submit("b", probes).result(timeout=60)
            np.testing.assert_array_equal(
                np.asarray(out), m2.predict(probes)
            )
            rt.warm("b", buckets=(6,))
        finally:
            rt.close()

        _, recs = _serving_records(telemetry)
        skips = [r for r in recs if r.get("action") == "replicate_skip"]
        assert len(skips) == 1
        assert skips[0]["member"] == victim
        assert skips[0]["op"] == "register"
        assert skips[0]["lsn"] == 2
        downs = {
            r["member"]: r["reason"]
            for r in recs
            if r.get("action") == "member_down" and r.get("router")
        }
        assert victim in downs


# ---------------------------------------------------------------------------
# the scaler's vote machinery against a live router
# ---------------------------------------------------------------------------


class TestElasticScaler:
    def test_shed_pressure_scales_up_and_sustained_idle_scales_down(
        self, telemetry
    ):
        """Shed deltas vote up (through the zero-shed join), sustained
        idle votes down (through drain-then-detach), hysteresis gates
        both, and the min/max bounds are hard."""
        rng = np.random.default_rng(64)
        model = KMeansModel("scale-km", dyadic(rng, (4, D)))
        up0 = counter_value("serving.elastic.up")
        down0 = counter_value("serving.elastic.down")

        rt = RoutingRuntime(workers=1, launch="spawn", max_delay_ms=1.0)
        try:
            rt.register("km", model, warm_buckets=(1,))
            # Depth thresholds parked out of reach: shed deltas are the
            # ONLY pressure signal, idle the only relief — deterministic.
            scaler = ElasticScaler(
                rt, min_members=1, max_members=2, hysteresis=2,
                cooldown_ms=0.0, high=1e9, low=1e9,
            )

            bump_counter("serving.router.shed")
            assert scaler.tick() is None  # one vote < hysteresis
            bump_counter("serving.router.shed")
            assert scaler.tick() == "scale_up"
            assert rt.live_member_ids() == [0, 1]
            assert counter_value("serving.elastic.up") == up0 + 1

            # At max: pressure can't overshoot the bound.
            bump_counter("serving.router.shed")
            scaler.tick()
            bump_counter("serving.router.shed")
            assert scaler.tick() is None
            assert rt.live_member_ids() == [0, 1]

            # Sustained idle drains one member back out (tie on load:
            # the lowest id retires — member 0).
            assert scaler.tick() is None
            assert scaler.tick() == "scale_down"
            assert rt.live_member_ids() == [1]
            assert counter_value("serving.elastic.down") == down0 + 1

            # At min: idle can't retire the last member.
            assert scaler.tick() is None
            assert scaler.tick() is None
            assert rt.live_member_ids() == [1]
            assert scaler.decisions == [("scale_up", 1), ("scale_down", 0)]
        finally:
            rt.close()


# ---------------------------------------------------------------------------
# loadgen ramp grammar (the CLI that drives these episodes)
# ---------------------------------------------------------------------------


class TestLoadgenRamp:
    def test_parse_ramp(self):
        from tools import tpuml_loadgen

        assert tpuml_loadgen._parse_ramp("50:5,400:10,50:5") == [
            (50.0, 5.0), (400.0, 10.0), (50.0, 5.0),
        ]

    def test_parse_ramp_rejects_garbage(self):
        from tools import tpuml_loadgen

        for bad in ("50", "0:5", "50:0", "x:5", ""):
            with pytest.raises(SystemExit):
                tpuml_loadgen._parse_ramp(bad)

    @pytest.mark.slow
    def test_cli_ramp_reports_per_phase(self, tmp_path):
        import json

        r = subprocess.run(
            [
                sys.executable, str(REPO / "tools" / "tpuml_loadgen.py"),
                "--workers", "2", "--threads", "4", "--rows", "2",
                "--features", "8", "--ramp", "20:1,60:1.5,20:1",
                "--warm", "--json",
            ],
            capture_output=True, text=True, cwd=str(REPO),
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "TPUML_TELEMETRY_DIR": str(tmp_path / "shards"),
            },
            timeout=300,
        )
        assert r.returncode == 0, r.stderr[-3000:]
        summary = json.loads(r.stdout.strip().splitlines()[-1])
        phases = summary["ramp"]
        assert [p["target_rps"] for p in phases] == [20.0, 60.0, 20.0]
        assert all(p["completed"] > 0 for p in phases)
        assert all(p["p95_ms"] >= p["p50_ms"] > 0 for p in phases)
        # The middle phase offered ~3x the edge phases' rate.
        assert phases[1]["offered"] > 2 * phases[0]["offered"]
        assert summary["requests"] == sum(p["offered"] for p in phases)
