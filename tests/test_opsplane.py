"""Live ops plane (ISSUE 19): scrape endpoints, gang /statusz, SLO error
budgets, and the crash flight recorder.

The acceptance surface:

  - **Off by default.** With no ``TPUML_OPS_PORT`` / ``TPUML_SLO`` /
    ``TPUML_FLIGHT`` there is no server, no monitor, no ring — the
    instrumented emit path stays one None-check.
  - **One exposition renderer.** ``/metrics``, ``TPUML_METRICS_DUMP``
    and ``tools/tpuml_metrics.py`` all render through
    :func:`metrics.render_prometheus_snapshot`;
    :func:`metrics.parse_exposition` round-trips it (the conformance
    oracle CI also runs over scraped ``.prom`` artifacts).
  - **Live == post-hoc.** A routed 2-member gang's ``/statusz`` (merged
    with ``trace.merge_metrics``) agrees exactly, counter for counter,
    with ``tpuml_trace``'s post-mortem assemble of the same gang's
    telemetry shards.
  - **/healthz flips before EOF.** A member frozen by the
    ``ipc.recv=...:stall`` fault keeps its socket open; its OWN
    ``/healthz`` goes 503 on heartbeat age (``TPUML_OPS_STALL_S``)
    while the router still counts it live.
  - **SLO burn is a control input.** A declared latency objective under
    injected bad latency fires a breach edge (``slo`` event), the
    ElasticScaler's tick consumes the burn gauge as a scale-up vote, and
    the DriftMonitor's subscription lowers its refit window floor — all
    proven by event-log join.
  - **Flight recorder closes the killed-member hole.** A process that
    dies SIGKILL-adjacent (``os._exit`` — no atexit, no manifest) leaves
    a ``flight-<pid>.json`` that ``tpuml_trace --validate --strict``
    merges with zero orphan spans.
"""

from __future__ import annotations

import importlib.util
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.lifecycle.drift import DriftMonitor
from spark_rapids_ml_tpu.models.kmeans import KMeansModel
from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.observability import flightrec
from spark_rapids_ml_tpu.observability import opsplane
from spark_rapids_ml_tpu.observability import slo as slolib
from spark_rapids_ml_tpu.observability import trace as tracelib
from spark_rapids_ml_tpu.observability.metrics import (
    Registry,
    gauge,
    histogram,
    parse_exposition,
    percentile_from_histogram,
)
from spark_rapids_ml_tpu.robustness import faults
from spark_rapids_ml_tpu.serving import ElasticScaler, RoutingRuntime
from spark_rapids_ml_tpu.utils import tracing
from spark_rapids_ml_tpu.utils.envknobs import env_str
from spark_rapids_ml_tpu.utils.tracing import bump_counter

REPO = Path(__file__).resolve().parents[1]
TRACE_CLI = REPO / "tools" / "tpuml_trace.py"
TOP_CLI = REPO / "tools" / "tpuml_top.py"

D = 8


def dyadic(rng, shape, scale=4):
    return rng.integers(-4 * scale, 4 * scale, size=shape).astype(np.float64) / 4.0


_PREV_LOG = env_str(events.EVENT_LOG_ENV)


def _restore_sink():
    events.configure(_PREV_LOG if _PREV_LOG else None)


@pytest.fixture
def telemetry(tmp_path):
    """A fresh telemetry dir as the active sink, exported to the
    environment so spawned members inherit it and write their own shards
    (the tests/test_serving_router.py arrangement)."""
    d = str(tmp_path / "telemetry")
    prev = env_str(events.TELEMETRY_DIR_ENV)
    os.environ[events.TELEMETRY_DIR_ENV] = d
    events.configure()
    try:
        yield Path(d)
    finally:
        if prev is None:
            os.environ.pop(events.TELEMETRY_DIR_ENV, None)
        else:
            os.environ[events.TELEMETRY_DIR_ENV] = prev
        _restore_sink()


def _http_get(url: str, timeout: float = 10.0):
    """(status, content_type, body) — non-2xx comes back as data, not an
    exception (a 503 /healthz IS the answer under test)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return (resp.status, resp.headers.get("Content-Type", ""),
                    resp.read().decode("utf-8"))
    except urllib.error.HTTPError as exc:
        return exc.code, exc.headers.get("Content-Type", ""), exc.read().decode("utf-8")


def _artifact(name: str, body: str) -> None:
    """Drop a scraped body where CI's conformance gate picks it up."""
    d = env_str("TPUML_TEST_OPS_ARTIFACTS")
    if not d:
        return
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, name), "w") as f:
        f.write(body)


def _shard_records(telemetry_dir) -> list:
    events.flush_telemetry()
    recs = []
    for shard in sorted(Path(telemetry_dir).glob("events-*.jsonl")):
        for line in open(shard):
            if line.strip():
                recs.append(json.loads(line))
    return recs


# ---------------------------------------------------------------------------
# off by default: no port knob -> no server, no monitor, no ring
# ---------------------------------------------------------------------------


class TestOffByDefault:
    @pytest.mark.skipif(
        bool(env_str(opsplane.OPS_PORT_ENV)),
        reason="TPUML_OPS_PORT armed for this run",
    )
    def test_no_server_without_port_knob(self):
        assert opsplane.active() is None
        assert opsplane.active_port() is None
        assert opsplane.maybe_start_from_env() is None

    @pytest.mark.skipif(
        bool(env_str(slolib.SLO_ENV)),
        reason="TPUML_SLO armed for this run",
    )
    def test_no_slo_monitor_without_spec(self):
        assert slolib.active() is None
        assert slolib.maybe_start_from_env() is None

    @pytest.mark.skipif(
        bool(env_str(events.FLIGHT_ENV)),
        reason="TPUML_FLIGHT armed for this run",
    )
    def test_disabled_emit_is_one_none_check(self):
        if events.enabled():
            pytest.skip("an event sink is active in this run")
        assert events.flight_ring() is None
        before = events.emitted_count()
        for _ in range(100):
            events.emit("fault", action="noop")
        assert events.emitted_count() == before


# ---------------------------------------------------------------------------
# percentile_from_histogram: None on no-signal, callers must not divide
# ---------------------------------------------------------------------------


class TestPercentileNone:
    def test_empty_histogram_returns_none(self):
        r = Registry()
        h = r.histogram("t.lat", "empty", buckets=(1.0, 2.0, 4.0))
        assert percentile_from_histogram(h.value(), 0.95) is None
        assert percentile_from_histogram(h.value(), 0.5) is None

    def test_all_mass_in_overflow_returns_none(self):
        r = Registry()
        h = r.histogram("t.lat", "inf-only", buckets=(1.0, 2.0, 4.0))
        for _ in range(3):
            h.observe(100.0)
        assert percentile_from_histogram(h.value(), 0.95) is None

    def test_interpolation_inside_finite_buckets(self):
        r = Registry()
        h = r.histogram("t.lat", "interp", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 3.0):
            h.observe(v)
        assert percentile_from_histogram(h.value(), 0.5) == pytest.approx(1.5)

    def test_overflow_with_finite_mass_reports_top_edge(self):
        r = Registry()
        h = r.histogram("t.lat", "mixed", buckets=(1.0, 2.0, 4.0))
        h.observe(0.5)
        h.observe(100.0)
        assert percentile_from_histogram(h.value(), 0.99) == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# ONE exposition renderer + the parse-back conformance oracle
# ---------------------------------------------------------------------------


class TestExpositionRoundTrip:
    def _registry(self) -> Registry:
        r = Registry()
        # A backslash in the label value exercises render-time escaping
        # (snapshot keys store label values raw; quotes/newlines are not
        # representable there, so the escaping contract covers "\\").
        r.counter("rt.count", "requests served").inc(3, model="a\\c d")
        r.counter("rt.count").inc(4, model="plain")
        r.gauge("rt.gauge", "a level").set(2.5, host="x")
        h = r.histogram("rt.lat", "latency", buckets=(1.0, 2.0, 5.0))
        for v in (0.5, 1.5, 99.0):
            h.observe(v)
        return r

    def test_round_trip_values_types_and_help(self):
        r = self._registry()
        text = r.render_prometheus()
        doc = parse_exposition(text)

        count = doc["tpuml_rt_count"]
        assert count["type"] == "counter"
        assert count["help"] == "requests served"
        assert sorted(count["series"].values()) == [3.0, 4.0]
        # The escaped label value survives the round trip unescaped.
        assert 'tpuml_rt_count{model="a\\c d"}' in count["series"]

        g = doc["tpuml_rt_gauge"]
        assert g["type"] == "gauge"
        assert list(g["series"].values()) == [2.5]

        hist = doc["tpuml_rt_lat"]
        assert hist["type"] == "histogram"
        series = hist["series"]
        assert series["tpuml_rt_lat_count"] == 3.0
        assert series["tpuml_rt_lat_sum"] == pytest.approx(101.0)
        assert series['tpuml_rt_lat_bucket{le="+Inf"}'] == 3.0
        finite = [v for k, v in series.items()
                  if k.startswith("tpuml_rt_lat_bucket") and "+Inf" not in k]
        assert max(finite) == 2.0  # 0.5 and 1.5; 99 only in +Inf

    def test_default_registry_renderer_is_the_shared_one(self):
        """Registry.render_prometheus delegates to the one snapshot
        renderer: rendering its own snapshot must be byte-identical
        (modulo the snapshot's wall-clock ts, which the renderer
        ignores)."""
        from spark_rapids_ml_tpu.observability.metrics import (
            render_prometheus_snapshot,
        )

        r = self._registry()
        helps = {name: m.help for name, m in r.metrics().items() if m.help}
        assert r.render_prometheus() == render_prometheus_snapshot(
            r.snapshot(), helps=helps
        )

    def test_cli_snapshot_renderer_delegates(self, tmp_path):
        """tools/tpuml_metrics.py render path == the library renderer."""
        spec = importlib.util.spec_from_file_location(
            "tpuml_metrics_under_test", REPO / "tools" / "tpuml_metrics.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        snap = self._registry().snapshot()
        text = mod.render_snapshot_prometheus(snap)
        doc = parse_exposition(text)
        assert doc["tpuml_rt_lat"]["series"]["tpuml_rt_lat_count"] == 3.0


# ---------------------------------------------------------------------------
# the per-process ops server: /metrics /healthz /varz /tracez
# ---------------------------------------------------------------------------


@pytest.fixture(scope="class")
def ops_server():
    srv = opsplane.OpsServer(0)
    try:
        yield srv
    finally:
        srv.close()


class TestOpsServerEndpoints:
    def test_metrics_scrape_is_valid_exposition(self, ops_server):
        bump_counter("opsplane.test.scrape")
        status, ctype, body = _http_get(f"{ops_server.url}/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        doc = parse_exposition(body)
        assert "tpuml_opsplane_test_scrape" in doc
        _artifact("endpoints-metrics.prom", body)

    def test_varz_serves_the_live_registry(self, ops_server):
        bump_counter("opsplane.test.varz")
        status, ctype, body = _http_get(f"{ops_server.url}/varz")
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        assert doc["pid"] == os.getpid()
        assert doc["metrics"]["counters"]["opsplane.test.varz"] >= 1
        assert "serving" in doc and "routers" in doc

    def test_tracez_reports_recent_spans(self, ops_server):
        with tracing.TraceRange("opsplane-span"):
            pass
        status, _, body = _http_get(f"{ops_server.url}/tracez")
        assert status == 200
        doc = json.loads(body)
        assert "open" in doc
        assert any(r["name"] == "opsplane-span" for r in doc["recent"])

    def test_healthz_flips_on_failing_probe_and_recovers(self, ops_server):
        status0, _, body0 = _http_get(f"{ops_server.url}/healthz")
        doc0 = json.loads(body0)
        assert status0 == (200 if doc0["ok"] else 503)

        opsplane.add_probe("test.opsplane.flip", lambda: False)
        try:
            status, _, body = _http_get(f"{ops_server.url}/healthz")
            assert status == 503
            doc = json.loads(body)
            assert doc["ok"] is False
            assert doc["checks"]["test.opsplane.flip"]["ok"] is False
        finally:
            opsplane.remove_probe("test.opsplane.flip")
        status2, _, _ = _http_get(f"{ops_server.url}/healthz")
        assert status2 == status0

    def test_raising_probe_is_a_failed_probe(self, ops_server):
        def boom():
            raise RuntimeError("probe died")

        opsplane.add_probe("test.opsplane.boom", boom)
        try:
            status, _, body = _http_get(f"{ops_server.url}/healthz")
            assert status == 503
            assert json.loads(body)["checks"]["test.opsplane.boom"] == {
                "ok": False, "exc": "RuntimeError",
            }
        finally:
            opsplane.remove_probe("test.opsplane.boom")

    def test_unknown_path_404_lists_endpoints(self, ops_server):
        status, _, body = _http_get(f"{ops_server.url}/nope")
        assert status == 404
        assert "/metrics" in json.loads(body)["endpoints"]

    def test_remove_endpoint_identity_guard(self, ops_server):
        """A closing owner must not tear down a path a newer owner has
        since claimed (the stacked-routers /statusz hazard)."""
        fn1 = lambda: (200, "text/plain", "one\n")  # noqa: E731
        fn2 = lambda: (200, "text/plain", "two\n")  # noqa: E731
        opsplane.add_endpoint("/test-guard", fn1)
        opsplane.add_endpoint("/test-guard", fn2)
        try:
            opsplane.remove_endpoint("/test-guard", fn1)  # stale owner
            status, _, body = _http_get(f"{ops_server.url}/test-guard")
            assert (status, body) == (200, "two\n")
        finally:
            opsplane.remove_endpoint("/test-guard")
        status, _, _ = _http_get(f"{ops_server.url}/test-guard")
        assert status == 404


# ---------------------------------------------------------------------------
# /statusz: the live gang-merged view == the post-hoc shard merge
# ---------------------------------------------------------------------------


class TestStatuszLiveEqualsPostHoc:
    N = 24

    def test_live_statusz_matches_posthoc_merge(
        self, telemetry, monkeypatch
    ):
        """Route real traffic across a 2-member spawned gang whose
        members run ops servers (ports learned from contact cards),
        scrape the router's /statusz over HTTP after the traffic
        quiesces, then close the gang and assemble its telemetry shards
        post-hoc: the serving.* counters and histograms must agree
        EXACTLY — same merge function, same answer, live or dead."""
        monkeypatch.setenv(opsplane.OPS_PORT_ENV, "0")
        rng = np.random.default_rng(91)
        model = KMeansModel("ops-km", dyadic(rng, (4, D)))
        probes = dyadic(rng, (self.N, D))
        expected = model.predict(probes)

        local = opsplane.start(0)
        rt = RoutingRuntime(workers=2, launch="spawn", max_delay_ms=1.0)
        try:
            rt.register("km", model, warm_buckets=(1,))
            for i in range(self.N):
                out = rt.submit("km", probes[i]).result(timeout=60)
                np.testing.assert_array_equal(
                    np.asarray(out), expected[i : i + 1]
                )

            # Traffic quiesced: scrape the gang through the HTTP surface
            # the operator would use.
            status, ctype, body = _http_get(f"{local.url}/statusz")
            assert status == 200 and ctype.startswith("application/json")
            live = json.loads(body)

            members = live["members"]
            assert len(members) == 2
            for cell in members.values():
                assert cell["ok"] is True, cell
                assert isinstance(cell["ops_port"], int)
                assert cell["pid"] != os.getpid()

            # Member /metrics scrapes are valid exposition (CI re-parses
            # the dropped artifacts through the same oracle).
            for mid, cell in sorted(members.items()):
                ms, _, mbody = _http_get(
                    f"http://127.0.0.1:{cell['ops_port']}/metrics"
                )
                assert ms == 200
                mdoc = parse_exposition(mbody)
                assert "tpuml_serving_worker_ops" in mdoc
                _artifact(f"member-{mid}.prom", mbody)

            # The new CLI renders the same document.
            spec = importlib.util.spec_from_file_location(
                "tpuml_top_under_test", TOP_CLI
            )
            top = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(top)
            assert top.normalize_url("8321") == (
                "http://127.0.0.1:8321/statusz"
            )
            frame = top.render_frame(live)
            assert live["router"]["router"] in frame
            assert "gang:" in frame and "live" in frame
        finally:
            rt.close()
            opsplane.stop()

        live_counters = {
            k: v for k, v in live["merged"]["counters"].items()
            if k.startswith("serving.")
        }
        live_hists = {
            k: v for k, v in live["merged"]["histograms"].items()
            if k.startswith("serving.")
        }

        events.flush_telemetry()
        merged = tracelib.assemble(str(telemetry))
        assert merged["problems"] == []
        post = merged["metrics"]["merged"]
        post_counters = {
            k: v for k, v in post["counters"].items()
            if k.startswith("serving.")
        }
        post_hists = {
            k: v for k, v in post["histograms"].items()
            if k.startswith("serving.")
        }

        # Counter for counter: the live merge and the post-mortem merge
        # are the same function over the same state.
        assert live_counters == post_counters
        assert live_counters["serving.requests"] >= self.N

        assert sorted(live_hists) == sorted(post_hists)
        for name, series in live_hists.items():
            for skey, cell in series.items():
                other = post_hists[name][skey]
                assert cell["buckets"] == other["buckets"], (name, skey)
                assert cell["count"] == other["count"], (name, skey)
                assert cell["sum"] == pytest.approx(other["sum"])


# ---------------------------------------------------------------------------
# /healthz flips 503 on a wedged member BEFORE its socket ever EOFs
# ---------------------------------------------------------------------------


class TestHealthzStallFlip:
    def test_stalled_member_healthz_flips_before_eof(
        self, telemetry, monkeypatch
    ):
        """Freeze a member's frame loop with the ``:stall`` fault: its
        manual heartbeat stops beating, so its OWN /healthz goes 503 on
        heartbeat age (TPUML_OPS_STALL_S) while its socket is still open
        and the router still counts it live — the wedge is visible from
        the outside before any EOF. The stall-retire ladder then
        recovers every parked request bitwise intact."""
        monkeypatch.setenv(opsplane.OPS_PORT_ENV, "0")
        monkeypatch.setenv(opsplane.OPS_STALL_ENV, "1.0")
        rng = np.random.default_rng(92)
        model = KMeansModel("healthz-km", dyadic(rng, (4, D)))
        probes = dyadic(rng, (12, D))
        expected = model.predict(probes)

        rt = RoutingRuntime(workers=1, launch="spawn", max_delay_ms=1.0)
        try:
            rt.register("km", model, warm_buckets=(1,))
            # Arm ONLY the joiner; its recv sequence is hello(0), replay
            # register(1), replay warm(2) — @3 freezes on the first
            # routed frame, after a clean join.
            monkeypatch.setenv(faults.FAULTS_ENV, "ipc.recv=always@3:stall")
            stalled_id = rt.add_member()
            monkeypatch.delenv(faults.FAULTS_ENV)

            card = rt.statusz()["members"][str(stalled_id)]
            assert card["ok"] is True
            url = f"http://127.0.0.1:{card['ops_port']}/healthz"

            # Healthy first: the select-gated frame loop beats every
            # 0.2 s, well inside the 1 s limit.
            deadline = time.monotonic() + 10.0
            status = None
            while time.monotonic() < deadline:
                status, _, _ = _http_get(url)
                if status == 200:
                    break
                time.sleep(0.1)
            assert status == 200

            # The burst lands at least one frame on the armed member and
            # freezes its loop.
            futs = [rt.submit("km", probes[i]) for i in range(12)]

            deadline = time.monotonic() + 30.0
            doc = None
            while time.monotonic() < deadline:
                status, _, body = _http_get(url)
                if status == 503:
                    doc = json.loads(body)
                    break
                time.sleep(0.1)
            assert doc is not None, "stalled member /healthz never flipped"
            hb = doc["checks"]["heartbeat"]
            assert hb["ok"] is False
            assert hb["max_age_s"] > 1.0

            # ... and at flip time the router has seen NO EOF: the
            # member is still in the selection set, socket open.
            by_id = {m["member"]: m for m in rt.snapshot()["members"]}
            assert by_id[stalled_id]["dead"] is False

            # Recovery: the liveness ladder retires the wedge and every
            # parked request redispatches losslessly.
            deadline = time.monotonic() + 30.0
            retired: list = []
            while stalled_id not in retired:
                assert time.monotonic() < deadline, "stall retire never fired"
                retired += rt.retire_stalled(1.0)
                time.sleep(0.05)
            for i, fut in enumerate(futs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=60)), expected[i : i + 1]
                )
        finally:
            rt.close()

        recs = _shard_records(telemetry)
        stalls = [r for r in recs if r.get("action") == "member_stalled"]
        assert [r["member"] for r in stalls] == [stalled_id]


# ---------------------------------------------------------------------------
# SLO error budgets: burn-rate gauges, breach edges, scale/refit votes
# ---------------------------------------------------------------------------


class TestSloSpec:
    def test_parse_spec(self):
        objs = slolib.parse_slo(
            "serving.p95_ms<=50;shed.rate<=0.01;freshness.age_s<=600"
        )
        assert [(o.name, o.op, o.threshold) for o in objs] == [
            ("serving.p95_ms", "<=", 50.0),
            ("shed.rate", "<=", 0.01),
            ("freshness.age_s", "<=", 600.0),
        ]
        assert objs[0].spec() == "serving.p95_ms<=50"
        assert slolib.parse_slo("") == []
        assert slolib.parse_slo(" ; ") == []

    def test_malformed_spec_refused_loudly(self):
        with pytest.raises(slolib.SloSpecError, match="malformed"):
            slolib.parse_slo("serving.p95_ms<50")
        with pytest.raises(slolib.SloSpecError, match="malformed"):
            slolib.parse_slo("p95==nope")


class _FakeRouter:
    """The ElasticScaler's whole view of a gang, minus the gang."""

    def __init__(self):
        self.added = 0

    def snapshot(self):
        return {
            "members": [
                {"member": 0, "dead": False, "joining": False,
                 "retiring": False, "depth": 0, "outstanding": 0}
            ]
        }

    def add_member(self, **kwargs):
        self.added += 1
        return self.added

    def retire_member(self, member_id, **kwargs):  # pragma: no cover
        raise AssertionError("scaler must not retire under SLO pressure")

    def retire_stalled(self, max_age):
        return []


class TestSloControlLoop:
    def test_latency_breach_edge_scaler_and_drift_votes(self, telemetry):
        """The flagship joined path: injected bad latency burns the
        declared p95 budget -> breach edge (``slo`` event + burn gauge),
        the ElasticScaler's next tick votes scale-up on an otherwise
        idle gang, and the subscribed DriftMonitor lowers its refit
        window floor — every hop visible in the event log."""
        monitor = slolib.SloMonitor("serving.p95_ms<=5")
        edges: list = []
        dm = DriftMonitor("slo-ops", threshold=10.0, min_count=50)
        hist = histogram(
            "serving.router.latency_ms", "router-observed request latency"
        )
        try:
            monitor.tick()  # absorb whatever history this process has
            for _ in range(40):
                hist.observe(1.0)  # a good window: tail mass 0
            out = monitor.tick()
            assert out["serving.p95_ms"]["breached"] is False
            # Only NOW wire the consumers: the process is provably in
            # the non-breached state, so the next edge is the breach.
            monitor.subscribe(edges.append)
            monitor.subscribe(dm.on_slo_breach)

            for _ in range(40):
                hist.observe(100.0)  # the injected latency fault
            out = monitor.tick()
            cell = out["serving.p95_ms"]
            assert cell["breached"] is True
            assert cell["burn"] == pytest.approx(20.0)  # 100%/5% budget
            assert [e["action"] for e in edges] == ["breach"]
            assert slolib.burn_rates()["serving.p95_ms"] > 1.0

            # The scaler consumes the burn gauge: an idle gang under a
            # burning SLO still scales up.
            fake = _FakeRouter()
            scaler = ElasticScaler(
                fake, min_members=1, max_members=4, hysteresis=1,
                cooldown_ms=0.0, stall_after_s=0.0,
            )
            assert scaler.tick() == "scale_up"
            assert fake.added == 1
            assert scaler.decisions == [("scale_up", 1)]

            # The drift monitor's vote drops its window floor: 10
            # observations evaluate NOW instead of waiting out 50.
            assert dm._slo_votes == 1
            dm.observe_many(np.linspace(0.0, 1.0, 10))
            assert dm.tick() is None  # bootstrap tick -> baseline
            assert dm._window == []   # ... which proves it evaluated

            # Recovery edge on the next good window.
            for _ in range(40):
                hist.observe(1.0)
            out = monitor.tick()
            assert out["serving.p95_ms"]["breached"] is False
            assert edges[-1]["action"] == "recover"
        finally:
            gauge(slolib.BURN_GAUGE).remove(objective="serving.p95_ms")

        # Event-log join: breach -> scale_up(slo_burn) -> slo_vote.
        # (The absorb tick may have emitted an extra breach/recover pair
        # out of whatever latency history this process carries, so the
        # assertion anchors on the LAST edge pair — the injected one.)
        recs = _shard_records(telemetry)
        slo_recs = [r for r in recs if r.get("event") == "slo"]
        assert [r["action"] for r in slo_recs[-2:]] == ["breach", "recover"]
        breach = slo_recs[-2]
        assert breach["objective"] == "serving.p95_ms"
        assert breach["burn"] > 1.0

        ups = [r for r in recs
               if r.get("event") == "elastic" and r.get("action") == "scale_up"]
        assert len(ups) == 1
        assert ups[0]["slo_burn"] == pytest.approx(20.0)

        votes = [r for r in recs if r.get("action") == "slo_vote"]
        assert len(votes) == 1
        assert votes[0]["objective"] == "serving.p95_ms"
        assert votes[0]["votes"] == 1
        baselined = [r for r in recs if r.get("action") == "drift_baseline"]
        assert [r["count"] for r in baselined] == [10]

    def test_shed_rate_objective_windows_counter_deltas(self):
        monitor = slolib.SloMonitor("shed.rate<=0.01")
        try:
            monitor.tick()  # baseline the cumulative counters
            bump_counter("serving.router.shed", 5)
            bump_counter("serving.router.requests", 5)
            cell = monitor.tick()["shed.rate"]
            assert cell["value"] == pytest.approx(0.5)  # 5 shed / 10 offered
            assert cell["burn"] == pytest.approx(50.0)
            assert cell["breached"] is True

            # A clean follow-up window recovers.
            bump_counter("serving.router.requests", 100)
            cell = monitor.tick()["shed.rate"]
            assert cell["value"] == pytest.approx(0.0)
            assert cell["breached"] is False
        finally:
            gauge(slolib.BURN_GAUGE).remove(objective="shed.rate")

    def test_value_objective_uses_registered_source(self):
        monitor = slolib.SloMonitor("freshness.age_s<=600")
        age = {"v": 1200.0}
        monitor.set_source("freshness.age_s", lambda: age["v"])
        try:
            cell = monitor.tick()["freshness.age_s"]
            assert cell["burn"] == pytest.approx(2.0)
            assert cell["breached"] is True
            age["v"] = 60.0
            cell = monitor.tick()["freshness.age_s"]
            assert cell["burn"] == pytest.approx(0.1)
            assert cell["breached"] is False
        finally:
            gauge(slolib.BURN_GAUGE).remove(objective="freshness.age_s")

    def test_recover_records_are_not_refit_votes(self):
        dm = DriftMonitor("slo-ignore", threshold=10.0, min_count=50)
        dm.on_slo_breach({"action": "recover", "objective": "x"})
        assert dm._slo_votes == 0
        dm.on_slo_breach({"action": "breach", "objective": "x", "burn": 2.0})
        assert dm._slo_votes == 1


# ---------------------------------------------------------------------------
# flight recorder: the crash dump that survives a skipped atexit
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_captures_without_any_sink(self, tmp_path):
        """TPUML_FLIGHT arms the bounded ring even with NO event sink:
        the crash dump works where no event log was ever configured."""
        prev_flight = env_str(events.FLIGHT_ENV)
        prev_dir = env_str(events.TELEMETRY_DIR_ENV)
        os.environ[events.FLIGHT_ENV] = "8"
        os.environ[events.TELEMETRY_DIR_ENV] = ""
        os.environ[events.EVENT_LOG_ENV] = ""
        events.configure()
        try:
            assert not events.enabled()
            ring = events.flight_ring()
            assert ring is not None and ring.maxlen == 8
            before = events.emitted_count()
            for i in range(20):
                events.emit("fault", action="arm", seq=i)
            assert events.emitted_count() == before  # no sink: not written
            assert len(ring) == 8
            assert [r["seq"] for r in ring] == list(range(12, 20))

            flightrec.reset()
            dest = str(tmp_path / "flight-ring.json")
            assert flightrec.dump("test-ring", path=dest) == dest
            doc = json.load(open(dest))
            assert doc["kind"] == flightrec.DOC_KIND
            assert doc["pid"] == os.getpid()
            assert [r["seq"] for r in doc["ring"]] == list(range(12, 20))
            assert doc["threads"]  # all-thread stacks rode along
            assert isinstance(doc["metrics"], dict)

            # once=True dedupes a dump storm per reason.
            assert flightrec.dump("test-ring", path=dest) is None
        finally:
            if prev_flight is None:
                os.environ.pop(events.FLIGHT_ENV, None)
            else:
                os.environ[events.FLIGHT_ENV] = prev_flight
            if prev_dir is None:
                os.environ.pop(events.TELEMETRY_DIR_ENV, None)
            else:
                os.environ[events.TELEMETRY_DIR_ENV] = prev_dir
            if _PREV_LOG is None:
                os.environ.pop(events.EVENT_LOG_ENV, None)
            else:
                os.environ[events.EVENT_LOG_ENV] = _PREV_LOG
            flightrec.reset()
            events.configure()

    def test_sigterm_flush_publishes_manifest_and_flight(self, telemetry):
        """The SIGTERM handler the serving worker and barrier members
        install: flight dump + telemetry flush BEFORE SystemExit(143),
        so a TERM'd member never leaves a manifest-less shard."""
        flightrec.reset()
        undo = events.install_sigterm_flush()
        try:
            with pytest.raises(SystemExit) as excinfo:
                signal.raise_signal(signal.SIGTERM)
            assert excinfo.value.code == 143
        finally:
            undo()
            flightrec.reset()

        pid = os.getpid()
        manifest = json.load(open(telemetry / f"manifest-{pid}.json"))
        assert manifest["pid"] == pid
        assert (telemetry / f"metrics-{pid}.json").exists()
        flight = json.load(open(telemetry / f"flight-{pid}.json"))
        assert flight["reason"] == "sigterm"

    def test_install_off_main_thread_degrades_to_noop(self):
        out: dict = {}

        def _t():
            out["undo"] = events.install_sigterm_flush()

        t = threading.Thread(target=_t)
        t.start()
        t.join()
        out["undo"]()  # callable, and a no-op
        # The main-thread SIGTERM disposition was never touched.
        assert signal.getsignal(signal.SIGTERM) != 143

    def test_crash_dump_merges_into_the_posthoc_trace(self, telemetry):
        """A SIGKILL-adjacent death (os._exit: no atexit, no manifest,
        in-registry metrics lost) leaves flight-<pid>.json; the merge
        accepts it as manifest + metrics stand-in and the strict
        validation gate passes with zero orphan spans."""
        code = textwrap.dedent(
            """
            import os
            from spark_rapids_ml_tpu.observability import events, flightrec
            from spark_rapids_ml_tpu.utils import tracing

            with events.run_scope("job", "crash-test"):
                with tracing.TraceRange("doomed-work"):
                    events.emit("fault", action="arm", site="flight-crash")
                    flightrec.dump("test-crash")
                    os._exit(1)
            """
        )
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            events.TELEMETRY_DIR_ENV: str(telemetry),
            events.FLIGHT_ENV: "64",
        }
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, cwd=str(REPO), env=env,
        )
        assert r.returncode == 1, r.stdout + r.stderr

        flights = list(Path(telemetry).glob("flight-*.json"))
        assert len(flights) == 1
        doc = json.load(open(flights[0]))
        crash_pid = doc["pid"]
        assert doc["reason"] == "test-crash"
        assert any(rec.get("site") == "flight-crash" for rec in doc["ring"])
        assert not (telemetry / f"manifest-{crash_pid}.json").exists()

        events.flush_telemetry()
        merged = tracelib.assemble(str(telemetry))
        assert merged["problems"] == []
        assert merged["orphan_problems"] == []
        assert [os.path.basename(f) for f in merged["flights"]] == [
            f"flight-{crash_pid}.json"
        ]
        # The synthesized manifest stands in for the lost atexit flush.
        stand_in = [m for m in merged["manifests"] if m.get("pid") == crash_pid]
        assert len(stand_in) == 1
        assert stand_in[0]["flight"] == "test-crash"
        # ... and the dump's metrics snapshot joined the gang merge.
        assert any(
            m["file"] == f"flight-{crash_pid}.json"
            for m in merged["metrics"]["members"]
        )

        cli = subprocess.run(
            [sys.executable, str(TRACE_CLI), str(telemetry),
             "--validate", "--strict"],
            capture_output=True, text=True, cwd=str(REPO),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert cli.returncode == 0, cli.stdout + cli.stderr
        assert "flight recorder dump merged" in cli.stdout
