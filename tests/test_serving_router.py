"""Distributed serving tier (serving/router.py + serving/worker.py).

The ISSUE 13 acceptance surface: a RoutingRuntime with the ServingRuntime
façade spreading micro-batches across worker member processes, with
backpressure-weighted member selection, an lsn-ordered replicated
registry whose hot swap is version-atomic ACROSS members (result bits
AND the merged event-log join prove it), a mesh-sharded path for
requests too big for any one member, and a drained gang that leaves no
stale gauges behind.

Float parity uses the same dyadic-rational posture as
tests/test_serving_runtime.py: integers/4 make every dot product exact
in f64, so "bitwise equal to the sequential model call" holds across
process and sharding boundaries.

The small tests here run a 2-member gang (module-scoped — one spawn for
the lot). The 4-worker/8-thread stress cases are slow-marked; CI's
"Distributed serving tier" step runs them explicitly under telemetry
shards + strict lockcheck.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.kmeans import KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import LinearRegressionModel
from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.observability import trace as tracelib
from spark_rapids_ml_tpu.observability.metrics import default_registry
from spark_rapids_ml_tpu.serving import (
    Overloaded,
    RoutingRuntime,
    ServingRuntime,
    router_snapshots,
)
from spark_rapids_ml_tpu.serving import ipc
from spark_rapids_ml_tpu.serving.admission import DeadlineExceeded
from spark_rapids_ml_tpu.serving.worker import (
    decode_error,
    encode_error,
    serve_member,
)
from spark_rapids_ml_tpu.utils.envknobs import env_str
from spark_rapids_ml_tpu.utils.tracing import counter_value

REPO = Path(__file__).resolve().parents[1]

D = 8


def dyadic(rng, shape, scale=4):
    return rng.integers(-4 * scale, 4 * scale, size=shape).astype(np.float64) / 4.0


_PREV_LOG = env_str(events.EVENT_LOG_ENV)


def _restore_sink():
    events.configure(_PREV_LOG if _PREV_LOG else None)


@pytest.fixture
def telemetry(tmp_path):
    """A fresh telemetry dir as the active sink — exported to the
    ENVIRONMENT too, so spawned members inherit it and write their own
    shards (the tests/test_tracing_gang.py arrangement)."""
    d = str(tmp_path / "telemetry")
    prev = env_str(events.TELEMETRY_DIR_ENV)
    os.environ[events.TELEMETRY_DIR_ENV] = d
    events.configure()
    try:
        yield Path(d)
    finally:
        if prev is None:
            os.environ.pop(events.TELEMETRY_DIR_ENV, None)
        else:
            os.environ[events.TELEMETRY_DIR_ENV] = prev
        _restore_sink()


@pytest.fixture(scope="module")
def gang():
    """One 2-member spawned gang shared by the small tests (distinct
    model names keep them independent)."""
    rt = RoutingRuntime(workers=2, launch="spawn", max_delay_ms=1.0)
    yield rt
    rt.close()


# ---------------------------------------------------------------------------
# wire framing + error codecs (no processes)
# ---------------------------------------------------------------------------


class TestIpc:
    def test_framing_roundtrip_and_eof(self):
        a, b = socket.socketpair()
        try:
            msg = {"t": "submit", "x": np.arange(6).reshape(2, 3), "id": 7}
            ipc.send_msg(a, msg)
            got = ipc.recv_msg(b)
            assert got["t"] == "submit" and got["id"] == 7
            np.testing.assert_array_equal(got["x"], msg["x"])
            a.close()
            assert ipc.recv_msg(b) is None  # orderly EOF
        finally:
            b.close()

    def test_oversized_frame_refused(self):
        a, b = socket.socketpair()
        try:
            a.sendall((ipc.MAX_FRAME_BYTES + 1).to_bytes(4, "big"))
            with pytest.raises(ValueError, match="exceeds"):
                ipc.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_model_serialization_roundtrip(self):
        rng = np.random.default_rng(3)
        m = KMeansModel("ipc-km", dyadic(rng, (4, D)))
        clone = ipc.loads_model(ipc.dumps_model(m))
        x = dyadic(rng, (5, D))
        np.testing.assert_array_equal(clone.predict(x), m.predict(x))

    def test_error_codec_roundtrip(self):
        ov = Overloaded(
            "memory", "m", queue_depth=3, queue_limit=8,
            reserved_bytes=100, request_bytes=50, mem_budget=120,
            retry_after_ms=12.5,
        )
        back = decode_error(encode_error(ov))
        assert isinstance(back, Overloaded)
        assert back.reason == "memory" and back.retry_after_ms == 12.5
        assert back.request_bytes == 50 and back.mem_budget == 120

        dl = decode_error(encode_error(DeadlineExceeded("m", 9.0, 5.0)))
        assert isinstance(dl, DeadlineExceeded) and dl.deadline_ms == 5.0

        other = decode_error(encode_error(ValueError("boom")))
        assert isinstance(other, RuntimeError) and "boom" in str(other)

    def test_rendezvous_cards(self, tmp_path):
        assert ipc.read_member(str(tmp_path), 0) is None
        ipc.publish_member(str(tmp_path), 0, "127.0.0.1", 4242)
        card = ipc.read_member(str(tmp_path), 0)
        assert card["port"] == 4242 and card["pid"] == os.getpid()


# ---------------------------------------------------------------------------
# the routed request path
# ---------------------------------------------------------------------------


class TestRoutedRequests:
    def test_roundtrip_is_bitwise_model_output(self, gang):
        rng = np.random.default_rng(11)
        m = KMeansModel("rt-km", dyadic(rng, (4, D)))
        gang.register("rt-km", m)
        x = dyadic(rng, (12, D))
        out = gang.submit("rt-km", x).result(timeout=60)
        np.testing.assert_array_equal(np.asarray(out), m.predict(x))

    def test_submit_many_spreads_across_members(self, gang):
        rng = np.random.default_rng(12)
        m = LinearRegressionModel("rt-lr", dyadic(rng, (D,)), 0.25)
        gang.register("rt-lr", m)
        xs = [dyadic(rng, (1, D)) for _ in range(12)]
        futs = gang.submit_many("rt-lr", xs)
        for x, f in zip(xs, futs):
            np.testing.assert_array_equal(
                np.asarray(f.result(timeout=60)), m.predict(x)
            )
        snap = gang.snapshot()
        assert sum(mm["routed"] for mm in snap["members"]) >= 12
        # Least-loaded selection: nobody got ALL the traffic.
        assert all(mm["routed"] > 0 for mm in snap["members"])

    def test_input_validation_is_local(self, gang):
        rng = np.random.default_rng(13)
        gang.register("rt-val", KMeansModel("rt-val", dyadic(rng, (4, D))))
        with pytest.raises(ValueError, match="features"):
            gang.submit("rt-val", np.zeros((2, D + 1)))
        with pytest.raises(KeyError):
            gang.submit("rt-missing", np.zeros((1, D)))

    def test_router_appears_in_serving_report(self, gang):
        from spark_rapids_ml_tpu.observability.report import serving_report

        assert any(s["router"] == gang.router_id for s in router_snapshots())
        rep = serving_report()
        routers = rep.get("routers", [])
        assert any(s["router"] == gang.router_id for s in routers)
        mine = next(s for s in routers if s["router"] == gang.router_id)
        assert len(mine["members"]) == 2
        assert "routed_latency_ms" in rep


# ---------------------------------------------------------------------------
# backpressure-driven member selection
# ---------------------------------------------------------------------------


class TestBackpressure:
    def test_backed_off_member_is_skipped(self, gang):
        members = list(gang._members.values())
        try:
            with gang._lock:
                members[0].backoff_until = time.monotonic() + 60.0
            for _ in range(6):
                picked = gang._pick_member(set())
                assert picked.id == members[1].id
                with gang._lock:
                    picked.outstanding -= 1
                    picked.routed -= 1
        finally:
            with gang._lock:
                members[0].backoff_until = 0.0

    def test_least_loaded_pick_reads_depth_and_outstanding(self, gang):
        members = list(gang._members.values())
        try:
            with gang._lock:
                members[0].last_depth = 50
            picked = gang._pick_member(set())
            assert picked.id == members[1].id
            with gang._lock:
                picked.outstanding -= 1
                picked.routed -= 1
        finally:
            with gang._lock:
                members[0].last_depth = 0

    def test_all_members_backed_off_sheds_with_soonest_hint(self, gang):
        rng = np.random.default_rng(14)
        gang.register("rt-shed", KMeansModel("rt-shed", dyadic(rng, (4, D))))
        before = counter_value("serving.router.rejected")
        try:
            with gang._lock:
                for m in gang._members.values():
                    m.backoff_until = time.monotonic() + 60.0
            with pytest.raises(Overloaded) as exc:
                gang.submit("rt-shed", np.zeros((1, D)))
            # The aggregate hint is the SOONEST recovery, ~60s here.
            assert 0.0 < exc.value.retry_after_ms <= 61_000.0
            assert exc.value.retry_after_ms > 55_000.0
        finally:
            with gang._lock:
                for m in gang._members.values():
                    m.backoff_until = 0.0
        assert counter_value("serving.router.rejected") == before + 1
        assert gang.snapshot()["rejected"] >= 1

    def test_member_shed_sets_backoff_and_retries_elsewhere(self, telemetry):
        """A genuinely shedding member: queue_limit=1 forces Overloaded
        replies under a burst; the router must retry them on the other
        member (or surface a structured Overloaded), never hang, and a
        shed member's advertised backoff must land in its handle."""
        rng = np.random.default_rng(15)
        m = KMeansModel("bp-km", dyadic(rng, (4, D)))
        shed0 = counter_value("serving.router.shed")
        rejected0 = counter_value("serving.router.rejected")
        rt = RoutingRuntime(
            workers=2, launch="spawn", queue_limit=1, max_delay_ms=20.0
        )
        try:
            rt.register("bp-km", m)
            xs = dyadic(rng, (64, D))
            outcomes = {"ok": 0, "overloaded": 0}
            futs = []
            for i in range(64):
                try:
                    futs.append((i, rt.submit("bp-km", xs[i])))
                except Overloaded as exc:
                    # Synchronous rejection: every member inside its
                    # advertised backoff window when the request arrived.
                    assert exc.retry_after_ms >= 0.0
                    outcomes["overloaded"] += 1
            for i, f in futs:
                try:
                    out = np.asarray(f.result(timeout=120))
                    np.testing.assert_array_equal(out, m.predict(xs[i : i + 1]))
                    outcomes["ok"] += 1
                except Overloaded as exc:
                    assert exc.retry_after_ms >= 0.0
                    outcomes["overloaded"] += 1
            assert outcomes["ok"] >= 1
            snap = rt.snapshot()
            total_shed = sum(mm["shed"] for mm in snap["members"])
        finally:
            rt.close()
        if outcomes["overloaded"]:
            # Every surfaced Overloaded is accounted for by a member
            # shed (retried then exhausted) or a router-level rejection;
            # the counters agree with the member handles.
            shed = counter_value("serving.router.shed") - shed0
            rejected = counter_value("serving.router.rejected") - rejected0
            assert shed + rejected > 0
            assert shed >= total_shed


# ---------------------------------------------------------------------------
# replicated registry
# ---------------------------------------------------------------------------


class TestReplicatedRegistry:
    def test_versions_agree_across_members(self, gang):
        rng = np.random.default_rng(21)
        m1 = KMeansModel("rep-km-a", dyadic(rng, (4, D)))
        m2 = KMeansModel("rep-km-b", dyadic(rng, (4, D)))
        v1 = gang.register("rep-km", m1)
        v2 = gang.register("rep-km", m2)
        assert (v1.version, v2.version) == (1, 2)
        for st in gang.member_status():
            models = st["snapshot"]["models"]
            assert models["rep-km"]["versions"] == [1, 2]

    def test_alias_swap_and_retire_replicate(self, gang):
        rng = np.random.default_rng(22)
        gang.register("rep-alias", KMeansModel("a1", dyadic(rng, (4, D))))
        gang.register("rep-alias", KMeansModel("a2", dyadic(rng, (4, D))))
        gang.set_alias("rep-alias", "prod", 2)
        assert gang.registry.resolve("rep-alias@prod").version == 2
        for st in gang.member_status():
            assert st["snapshot"]["models"]["rep-alias"]["aliases"] == {
                "prod": 2
            }
        gang.retire("rep-alias", 1)
        for st in gang.member_status():
            assert st["snapshot"]["models"]["rep-alias"]["versions"] == [2]

    def test_warm_reaches_every_member(self, gang):
        rng = np.random.default_rng(23)
        gang.register("rep-warm", KMeansModel("w", dyadic(rng, (4, D))))
        # 1 rounds up to the floor bucket (8); 64 is its own bucket.
        warmed = gang.warm("rep-warm", buckets=(1, 64))
        assert warmed == 2


# ---------------------------------------------------------------------------
# oversized requests: the mesh-sharded path
# ---------------------------------------------------------------------------


class TestMeshSharded:
    def test_oversized_request_shards_bitwise(self, gang):
        rng = np.random.default_rng(31)
        m = KMeansModel("mesh-km", dyadic(rng, (4, D)))
        gang.register("mesh-km", m)
        before = counter_value("serving.router.oversized")
        member_completed = sum(
            mm["completed"] for mm in gang.snapshot()["members"]
        )
        old = gang.shard_rows
        gang.shard_rows = 8
        try:
            # 13 rows: NOT a multiple of the 8-device data axis, so the
            # pad-and-slice path is exercised too.
            x = dyadic(rng, (13, D))
            out = gang.submit("mesh-km", x).result(timeout=120)
            np.testing.assert_array_equal(np.asarray(out), m.predict(x))
        finally:
            gang.shard_rows = old
        assert counter_value("serving.router.oversized") == before + 1
        # The request never touched a member.
        assert (
            sum(mm["completed"] for mm in gang.snapshot()["members"])
            == member_completed
        )

    def test_member_budget_floor_drives_oversizing(self, gang):
        members = list(gang._members.values())
        saved = [m.mem_budget for m in members]
        rng = np.random.default_rng(32)
        m = KMeansModel("mesh-bud", dyadic(rng, (4, D)))
        mv = gang.register("mesh-bud", m)
        try:
            with gang._lock:
                for mm in members:
                    mm.mem_budget = 1  # one byte: everything is oversized
            assert gang._is_oversized(mv, 4, np.dtype(np.float64))
            with gang._lock:
                for mm in members:
                    mm.mem_budget = 0  # no budget: the gate is off
            assert not gang._is_oversized(mv, 4, np.dtype(np.float64))
        finally:
            with gang._lock:
                for mm, s in zip(members, saved):
                    mm.mem_budget = s


# ---------------------------------------------------------------------------
# lifecycle: gauges retire, members drain, worker orphan timeout
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_runtime_close_retires_queue_and_inflight_gauges(self):
        rt = ServingRuntime(start=False)
        gsnap = default_registry.snapshot()["gauges"]
        assert any(
            rt.runtime_id in name
            for name in gsnap
            if name.startswith("serving.queue.depth")
        )
        rt.close()
        gsnap = default_registry.snapshot()["gauges"]
        for name in gsnap:
            assert rt.runtime_id not in name, name

    def test_router_close_retires_member_depth_gauges(self):
        rt = RoutingRuntime(workers=1, launch="spawn")
        rid = rt.router_id
        gsnap = default_registry.snapshot()["gauges"]
        assert any(
            rid in name
            for name in gsnap
            if name.startswith("serving.router.member.depth")
        )
        rt.close()
        gsnap = default_registry.snapshot()["gauges"]
        for name in gsnap:
            assert rid not in name, name
        assert rt.snapshot()["closed"]
        # Idempotent.
        rt.close()

    def test_orphaned_member_times_out_instead_of_parking(self, tmp_path):
        before = {
            name
            for name in default_registry.snapshot()["gauges"]
            if name.startswith(("serving.queue.depth", "serving.inflight"))
        }
        with pytest.raises(TimeoutError, match="TPUML_ROUTER_CONNECT_TIMEOUT"):
            serve_member(0, str(tmp_path), accept_timeout=1.0)
        # Even the orphan retired its gauges on the way out.
        after = {
            name
            for name in default_registry.snapshot()["gauges"]
            if name.startswith(("serving.queue.depth", "serving.inflight"))
        }
        assert after <= before
        # And its member card was published (a router arriving late can
        # still see what happened).
        assert ipc.read_member(str(tmp_path), 0) is not None


# ---------------------------------------------------------------------------
# barrier-mode launch (pyspark stub runs barrier tasks sequentially, so
# only a single-member gang is testable here; spawn covers N>1)
# ---------------------------------------------------------------------------

_STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pyspark_stub")


@pytest.fixture
def stub_spark():
    saved = {n: m for n, m in sys.modules.items() if n.startswith("pyspark")}
    for n in list(saved):
        del sys.modules[n]
    sys.path.insert(0, _STUB)
    try:
        from pyspark.sql import SparkSession

        yield SparkSession.builder.master("local[2]").getOrCreate()
    finally:
        sys.path.remove(_STUB)
        for n in [n for n in sys.modules if n.startswith("pyspark")]:
            del sys.modules[n]
        sys.modules.update(saved)


class TestBarrierLaunch:
    def test_single_member_barrier_gang_serves(self, stub_spark, tmp_path):
        from pyspark.sql import RDD

        rng = np.random.default_rng(41)
        m = KMeansModel("bar-km", dyadic(rng, (4, D)))
        rdd = RDD([[0]])  # one partition, one member id
        rt = RoutingRuntime(
            workers=1, launch="barrier", rdd=rdd,
            rendezvous=str(tmp_path / "rdv"),
        )
        try:
            rt.register("bar-km", m)
            x = dyadic(rng, (6, D))
            out = rt.submit("bar-km", x).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out), m.predict(x))
        finally:
            rt.close()
        # The barrier stage returned each member's summary.
        assert rt._barrier_result and rt._barrier_result[0][0]["drain"]


# ---------------------------------------------------------------------------
# the acceptance stress: cross-member version-atomic hot swap (slow; CI's
# "Distributed serving tier" step runs it across 4 workers explicitly)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestCrossMemberHotSwap:
    N_WORKERS = 4
    N_THREADS = 8
    PER_THREAD = 25

    def test_hot_swap_under_load_is_version_atomic_across_members(
        self, telemetry
    ):
        """8 threads stream single rows at ``km@prod`` against a 4-member
        gang while v2 registers and the alias flips: every result is
        bitwise v1's or v2's answer, ZERO requests shed anywhere during
        the swap, and the merged per-process event log joins every
        request to exactly the version it was admitted against — with
        the strict orphan gate green over the merged shards."""
        rng = np.random.default_rng(51)
        c1 = dyadic(rng, (4, D))
        c2 = dyadic(rng, (4, D)) + 64.0
        m1 = KMeansModel("swap-v1", c1)
        m2 = KMeansModel("swap-v2", c2)
        n = self.N_THREADS * self.PER_THREAD
        probes = dyadic(rng, (n, D))
        exp1 = m1.predict(probes)
        exp2 = m2.predict(probes)

        shed0 = counter_value("serving.router.shed")
        rejected0 = counter_value("serving.router.rejected")
        rt = RoutingRuntime(
            workers=self.N_WORKERS, launch="spawn",
            max_batch=16, max_delay_ms=2.0,
        )
        errors = []
        try:
            v1 = rt.register("km", m1, alias="prod", warm_buckets=(1,))
            collected = []
            lock = threading.Lock()

            def worker(tid):
                local = []
                for j in range(self.PER_THREAD):
                    i = tid * self.PER_THREAD + j
                    try:
                        out = rt.submit("km@prod", probes[i]).result(
                            timeout=120
                        )
                        local.append((i, np.asarray(out)))
                    except Exception as exc:  # noqa: BLE001 - asserted below
                        errors.append((i, repr(exc)))
                with lock:
                    collected.extend(local)

            threads = [
                threading.Thread(target=worker, args=(t,))
                for t in range(self.N_THREADS)
            ]
            for t in threads:
                t.start()
            time.sleep(0.05)
            v2 = rt.register("km", m2)
            rt.set_alias("km", "prod", v2.version, warm_buckets=(1,))
            for t in threads:
                t.join()
            snap = rt.snapshot()
        finally:
            rt.close()
            events.flush_telemetry()

        # Zero shed/failed requests during the swap.
        assert errors == [], errors[:5]
        assert counter_value("serving.router.shed") == shed0
        assert counter_value("serving.router.rejected") == rejected0
        assert sum(m["shed"] for m in snap["members"]) == 0

        # Result bits: every answer is exactly one version's answer.
        assert len(collected) == n
        n_v1 = n_v2 = 0
        for i, out in collected:
            if np.array_equal(out, exp1[i : i + 1]):
                n_v1 += 1
            elif np.array_equal(out, exp2[i : i + 1]):
                n_v2 += 1
            else:  # pragma: no cover - the failure being hunted
                raise AssertionError(f"row {i} matches neither version")
        assert n_v1 + n_v2 == n
        assert (v1.version, v2.version) == (1, 2)

        # All members took traffic (the whole point of the tier).
        assert all(m["routed"] > 0 for m in snap["members"])

        # Merged event-log join across EVERY process's shard: a request's
        # admitted version IS the version its batch dispatched and
        # completed on — on whichever member it landed.
        merged = tracelib.assemble(str(telemetry))
        assert merged["problems"] == [], merged["problems"][:3]
        assert merged["orphan_problems"] == [], merged["orphan_problems"][:3]
        recs = [
            r
            for r in merged["records"]
            if r.get("event") == "serving"
        ]
        admitted = {
            r["run_id"]: r["version"]
            for r in recs
            if r.get("action") == "enqueue"
        }
        assert len(admitted) == n
        dispatches = 0
        for r in recs:
            if r.get("action") == "dispatch":
                dispatches += 1
                for rid in r["run_ids"]:
                    assert admitted[rid] == r["version"], "mixed-version batch"
            elif r.get("action") == "complete" and r.get("run_id") in admitted:
                assert admitted[r["run_id"]] == r["version"]
        assert dispatches >= 1

        # One merged trace per routed request across the router hop: the
        # router's route event and the member's enqueue/complete for the
        # same request share a trace id.
        route_traces = {
            r["trace"]: r
            for r in recs
            if r.get("action") == "route" and r.get("trace")
        }
        enqueue_traces = [
            r["trace"] for r in recs if r.get("action") == "enqueue"
        ]
        assert len(route_traces) == n
        for t in enqueue_traces:
            assert t in route_traces, "member events left the request trace"
        # Members spread the trace across processes: the dispatching pids
        # differ from the router's.
        member_pids = {
            r["pid"] for r in recs if r.get("action") == "dispatch"
        }
        assert member_pids and os.getpid() not in member_pids


@pytest.mark.slow
class TestLoadgenWorkersMode:
    def test_cli_reports_per_member_rows(self, tmp_path):
        r = subprocess.run(
            [
                sys.executable, str(REPO / "tools" / "tpuml_loadgen.py"),
                "--workers", "2", "--threads", "4", "--requests", "10",
                "--warm", "--json",
            ],
            capture_output=True, text=True, cwd=str(REPO),
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "TPUML_TELEMETRY_DIR": str(tmp_path / "shards"),
            },
            timeout=300,
        )
        assert r.returncode == 0, r.stderr[-3000:]
        summary = json.loads(r.stdout.strip().splitlines()[-1])
        assert summary["workers"] == 2
        assert summary["completed"] == 40
        assert len(summary["per_member"]) == 2
        assert sum(m["completed"] for m in summary["per_member"]) == 40
        # Merged-shard percentiles came back as real numbers.
        assert summary["p50_ms"] > 0
