"""Native host runtime tests (libtpuml_host.so via ctypes).

Covers the C++ layer's three roles (native/src/tpuml_host.cpp): fp64 packed
covariance accumulation, CSR batch assembly, fused center+scale — each vs a
numpy oracle — plus merge semantics (the treeAggregate combOp) and the
graceful-fallback contract when the library is absent.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu import native


requires_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable and unbuildable"
)


def test_library_builds_and_loads():
    # The image has g++; the library must either preexist or build on demand.
    assert native.available()


@requires_native
class TestSprAccumulator:
    def test_matches_numpy_cov(self, rng):
        x = rng.normal(size=(500, 12))
        acc = native.SprAccumulator(12)
        for blk in np.array_split(x, 7):
            acc.add_block(blk)
        cov, mean = acc.finalize(center=True)
        np.testing.assert_allclose(mean, x.mean(axis=0), atol=1e-12)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)

    def test_uncentered(self, rng):
        x = rng.normal(size=(100, 6))
        acc = native.SprAccumulator(6).add_block(x)
        cov, _ = acc.finalize(center=False)
        np.testing.assert_allclose(cov, x.T @ x / 99, atol=1e-10)

    def test_merge_is_treeaggregate_combop(self, rng):
        x = rng.normal(size=(200, 8))
        a = native.SprAccumulator(8).add_block(x[:80])
        b = native.SprAccumulator(8).add_block(x[80:])
        a.merge(b)
        assert a.n_rows == 200
        cov, _ = a.finalize()
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)

    def test_kahan_beats_naive_on_adversarial_input(self, rng):
        # large offset + tiny signal: naive fp64 summation loses digits
        x = rng.normal(size=(200_00, 3)) * 1e-3 + 1e6
        acc = native.SprAccumulator(3).add_block(x)
        cov, _ = acc.finalize()
        expected = np.cov(x.astype(np.longdouble), rowvar=False).astype(np.float64)
        np.testing.assert_allclose(cov, expected, rtol=1e-6)

    def test_too_few_rows(self):
        acc = native.SprAccumulator(4).add_block(np.zeros((1, 4)))
        with pytest.raises(ValueError):
            acc.finalize()

    def test_bad_cols(self):
        with pytest.raises(ValueError):
            native.SprAccumulator(0)
        with pytest.raises(ValueError):
            native.SprAccumulator(70000)  # reference n<=65535 cap

    def test_shape_mismatch(self):
        acc = native.SprAccumulator(4)
        with pytest.raises(ValueError):
            acc.add_block(np.zeros((3, 5)))


@requires_native
class TestCsrToDense:
    def test_matches_scipy(self, rng):
        import scipy.sparse as sp

        x = rng.normal(size=(40, 9))
        x[x < 0.5] = 0
        csr = sp.csr_matrix(x)
        out = native.csr_to_dense(csr.indptr, csr.indices, csr.data, 9)
        np.testing.assert_allclose(out, x, atol=0)

    def test_f32_output(self, rng):
        import scipy.sparse as sp

        x = rng.normal(size=(10, 5))
        csr = sp.csr_matrix(x)
        out = native.csr_to_dense(csr.indptr, csr.indices, csr.data, 5, dtype=np.float32)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, x.astype(np.float32), atol=0)

    def test_bad_column_index(self):
        with pytest.raises(ValueError):
            native.csr_to_dense([0, 1], [7], [1.0], 5)


@requires_native
class TestCenterScale:
    def test_matches_numpy(self, rng):
        x = rng.normal(size=(50, 6))
        mean = x.mean(axis=0)
        out = native.center_scale_f32(x, mean, 0.5)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, ((x - mean) * 0.5).astype(np.float32), atol=0)


@requires_native
def test_trace_push_pop_no_crash():
    native.trace_push("native range")
    native.trace_pop()
    native.trace_pop()  # underflow is a no-op, not a crash


class TestNpyBlockReader:
    def test_roundtrip_f32_and_f64(self, tmp_path, rng):
        from spark_rapids_ml_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        for dtype in (np.float32, np.float64):
            x = rng.normal(size=(1003, 7)).astype(dtype)
            path = str(tmp_path / f"x_{dtype.__name__}.npy")
            np.save(path, x)
            with native.NpyBlockReader(path, block_rows=256) as r:
                assert r.shape == (1003, 7)
                assert r.dtype == dtype
                blocks = list(r.iter_blocks())
            assert [b.shape[0] for b in blocks] == [256, 256, 256, 235]
            np.testing.assert_array_equal(np.concatenate(blocks), x)

    def test_feeds_estimator_as_partitions(self, tmp_path, rng):
        from spark_rapids_ml_tpu import native
        from spark_rapids_ml_tpu.feature import PCA

        if not native.available():
            pytest.skip("native library unavailable")
        x = rng.normal(size=(600, 12))
        path = str(tmp_path / "x.npy")
        np.save(path, x)
        with native.NpyBlockReader(path, block_rows=200) as r:
            model = PCA().setK(3).fit(list(r.iter_blocks()))
        ref = PCA().setK(3).fit(x)
        np.testing.assert_allclose(model.pc, ref.pc, atol=1e-8)

    def test_1d_file(self, tmp_path, rng):
        from spark_rapids_ml_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        v = rng.normal(size=500).astype(np.float64)
        path = str(tmp_path / "v.npy")
        np.save(path, v)
        with native.NpyBlockReader(path) as r:
            assert r.shape == (500, 1)
            np.testing.assert_array_equal(
                np.concatenate(list(r.iter_blocks())).ravel(), v
            )

    def test_rejects_bad_inputs(self, tmp_path, rng):
        from spark_rapids_ml_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        with pytest.raises(ValueError):
            native.NpyBlockReader(str(tmp_path / "missing.npy"))
        # Fortran-order and unsupported dtypes must be refused.
        xf = np.asfortranarray(rng.normal(size=(10, 4)))
        pf = str(tmp_path / "f.npy")
        np.save(pf, xf)
        with pytest.raises(ValueError):
            native.NpyBlockReader(pf)
        xi = rng.integers(0, 5, size=(10, 4))
        pi = str(tmp_path / "i.npy")
        np.save(pi, xi)
        with pytest.raises(ValueError):
            native.NpyBlockReader(pi)
