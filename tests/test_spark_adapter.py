"""End-to-end pyspark adapter tests against the contract stub.

The CI image has no pyspark; ``tests/pyspark_stub`` implements the exact
API surface the adapter consumes (with real partition semantics and
cloudpickle serialization boundaries), so every line of
``spark_rapids_ml_tpu.spark.adapter`` executes here — fit on an RDD with
mapPartitions/treeReduce, Arrow-batch pandas_udf transforms, and
save/load round-trips (VERDICT r1 item 1, stub alternative). The test
classes live in ``tests/spark_contract_suite.py`` and are shared with
``tests/test_spark_real.py``, which runs the same assertions against
genuine pyspark when installed.
"""

import importlib
import os
import sys

import pytest

import spark_contract_suite as _suite

# Pull EVERY Test* class from the shared suite into this module's
# namespace so pytest collects it here — programmatic, so a class added
# to the suite can never be silently dropped by a stale import list.
for _name in dir(_suite):
    if _name.startswith("Test"):
        globals()[_name] = getattr(_suite, _name)

_STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pyspark_stub")

pytestmark = pytest.mark.spark


@pytest.fixture(scope="module")
def spark_env():
    """Install the pyspark stub, (re)import the adapter against it, and
    hand back (adapter_module, SparkSession). Restores sys state after."""
    had_real = "pyspark" in sys.modules
    saved = {
        name: mod for name, mod in sys.modules.items() if name.startswith("pyspark")
    }
    for name in list(saved):
        del sys.modules[name]
    sys.path.insert(0, _STUB)
    adapter_was = sys.modules.pop("spark_rapids_ml_tpu.spark.adapter", None)
    try:
        adapter = importlib.import_module("spark_rapids_ml_tpu.spark.adapter")
        assert adapter.HAS_PYSPARK, "stub failed to import as pyspark"
        from pyspark.sql import SparkSession

        yield adapter, SparkSession.builder.master("local[2]").getOrCreate()
    finally:
        sys.path.remove(_STUB)
        for name in [n for n in sys.modules if n.startswith("pyspark")]:
            del sys.modules[name]
        sys.modules.update(saved)
        if adapter_was is not None and not had_real:
            sys.modules["spark_rapids_ml_tpu.spark.adapter"] = adapter_was
        else:
            sys.modules.pop("spark_rapids_ml_tpu.spark.adapter", None)
