"""ApproximateNearestNeighbors: IVF-Flat vs the exact brute-force oracle.

Key oracle: probing ALL lists (n_probe = n_lists) makes IVF-Flat exact, so
it must reproduce brute-force kNN bit-for-bit on indices (away from
distance ties). Partial probing is checked via recall.
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
)
from spark_rapids_ml_tpu.ops.ann import build_ivf_index, ivf_search
from spark_rapids_ml_tpu.ops.knn import knn


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def recall(approx_idx, exact_idx):
    hits = sum(
        len(set(a.tolist()) & set(e.tolist())) for a, e in zip(approx_idx, exact_idx)
    )
    return hits / exact_idx.size


class TestOps:
    def test_full_probe_is_exact(self, rng):
        items = rng.normal(size=(500, 16)).astype(np.float32)
        q = rng.normal(size=(40, 16)).astype(np.float32)
        index = build_ivf_index(items, n_lists=10, seed=0)
        d2, idx = ivf_search(index, q, k=5, n_probe=10)
        d2_ref, idx_ref = knn(q, items, k=5, metric="sqeuclidean")
        np.testing.assert_allclose(np.asarray(d2), np.asarray(d2_ref), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(idx), np.asarray(idx_ref))

    def test_partial_probe_recall(self, rng):
        # Clustered data: probing a few lists still finds the neighbors.
        centers = rng.normal(size=(20, 8)) * 10
        items = (centers[rng.integers(0, 20, 2000)] + rng.normal(size=(2000, 8))).astype(
            np.float32
        )
        q = items[rng.integers(0, 2000, 100)] + 0.01
        index = build_ivf_index(items, n_lists=20, seed=0)
        _, idx = ivf_search(index, q, k=10, n_probe=5)
        _, idx_ref = knn(q, items, k=10, metric="sqeuclidean")
        assert recall(np.asarray(idx), np.asarray(idx_ref)) >= 0.9

    def test_index_covers_all_items(self, rng):
        items = rng.normal(size=(257, 4)).astype(np.float32)
        index = build_ivf_index(items, n_lists=7, seed=1)
        ids = np.asarray(index.list_ids)
        real = ids[ids >= 0]
        assert sorted(real.tolist()) == list(range(257))
        mask = np.asarray(index.list_mask)
        np.testing.assert_array_equal(mask > 0, ids >= 0)

    def test_unfilled_slots_minus_one(self, rng):
        # k exceeds candidates in the single probed list.
        items = rng.normal(size=(50, 4)).astype(np.float32) * 10
        index = build_ivf_index(items, n_lists=10, seed=0)
        d2, idx = ivf_search(index, items[:3], k=40, n_probe=1)
        d2, idx = np.asarray(d2), np.asarray(idx)
        assert np.any(idx == -1)
        assert np.all(np.isinf(d2[idx == -1]))

    def test_query_blocking_matches(self, rng):
        items = rng.normal(size=(300, 8)).astype(np.float32)
        q = rng.normal(size=(70, 8)).astype(np.float32)
        index = build_ivf_index(items, n_lists=6, seed=0)
        d_a, i_a = ivf_search(index, q, k=4, n_probe=6, block_q=16)
        d_b, i_b = ivf_search(index, q, k=4, n_probe=6, block_q=1024)
        np.testing.assert_allclose(np.asarray(d_a), np.asarray(d_b), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(i_a), np.asarray(i_b))

    def test_validation(self, rng):
        items = rng.normal(size=(20, 4)).astype(np.float32)
        with pytest.raises(ValueError):
            build_ivf_index(items, n_lists=21)
        index = build_ivf_index(items, n_lists=4)
        with pytest.raises(ValueError):
            ivf_search(index, items, k=3, n_probe=5)


class TestEstimator:
    def test_fit_kneighbors_exact_mode(self, rng):
        items = rng.normal(size=(400, 8)).astype(np.float32)
        model = (
            ApproximateNearestNeighbors()
            .setK(5)
            .setAlgoParams({"nlist": 8, "nprobe": 8})
            .fit(items)
        )
        d, idx = model.kneighbors(items[:20])
        _, idx_ref = knn(items[:20], items, k=5, metric="sqeuclidean")
        np.testing.assert_array_equal(idx, np.asarray(idx_ref))
        # euclidean metric: self-distance 0, self first
        np.testing.assert_array_equal(idx[:, 0], np.arange(20))
        np.testing.assert_allclose(d[:, 0], 0.0, atol=1e-3)

    def test_brute_algorithm(self, rng):
        items = rng.normal(size=(100, 6)).astype(np.float32)
        m = ApproximateNearestNeighbors().setK(3).setAlgorithm("brute").fit(items)
        d, idx = m.kneighbors(items[:10])
        np.testing.assert_array_equal(idx[:, 0], np.arange(10))

    def test_brute_approx_algorithm(self, rng):
        # Dense MXU scoring + hardware approximate top-k — exact on the
        # CPU backend, so it must agree with brute here.
        items = rng.normal(size=(300, 6)).astype(np.float32)
        ma = (
            ApproximateNearestNeighbors()
            .setK(4)
            .setAlgorithm("brute_approx")
            .fit(items)
        )
        mb = ApproximateNearestNeighbors().setK(4).setAlgorithm("brute").fit(items)
        da, ia = ma.kneighbors(items[:25])
        db, ib = mb.kneighbors(items[:25])
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_allclose(da, db, atol=1e-6)

    def test_cosine_metric(self, rng):
        items = rng.normal(size=(200, 8)).astype(np.float32)
        m = (
            ApproximateNearestNeighbors()
            .setK(4)
            .setMetric("cosine")
            .setAlgoParams({"nlist": 4, "nprobe": 4})
            .fit(items)
        )
        d, idx = m.kneighbors(items[:15])
        # cosine distance to self is 0; scaled copies are also at 0
        np.testing.assert_allclose(d[:, 0], 0.0, atol=1e-5)
        b = ApproximateNearestNeighbors().setK(4).setMetric("cosine").setAlgorithm(
            "brute"
        ).fit(items)
        d_b, idx_b = b.kneighbors(items[:15])
        np.testing.assert_array_equal(idx, idx_b)
        np.testing.assert_allclose(d, d_b, atol=1e-5)

    def test_id_col_mapping(self, rng):
        import pandas as pd

        x = rng.normal(size=(60, 5))
        df = pd.DataFrame(x, columns=[f"c{i}" for i in range(5)])
        df["rid"] = np.arange(1000, 1060)
        m = (
            ApproximateNearestNeighbors()
            .setK(3)
            .setIdCol("rid")
            .setAlgoParams({"nlist": 4, "nprobe": 4})
            .fit(df)
        )
        d, ids = m.kneighbors_ids(df)
        np.testing.assert_array_equal(ids[:, 0], df["rid"].to_numpy())

    def test_dataframe_transform(self, rng):
        x = rng.normal(size=(50, 4))
        df = DataFrame({"features": list(x)})
        m = ApproximateNearestNeighbors().setK(2).setAlgoParams(
            {"nlist": 2, "nprobe": 2}
        ).fit(df)
        out = m.transform(df)
        assert "ann_indices" in out.columns and "ann_distances" in out.columns

    def test_param_validation(self):
        with pytest.raises(ValueError):
            ApproximateNearestNeighbors().setAlgorithm("hnsw")
        with pytest.raises(ValueError):
            ApproximateNearestNeighbors().setMetric("manhattan")
        with pytest.raises(ValueError):
            ApproximateNearestNeighbors().setAlgoParams({"bogus": 1})
        with pytest.raises(ValueError):
            ApproximateNearestNeighbors().setK(0)

    def test_defaults_and_auto_nlist(self, rng):
        est = ApproximateNearestNeighbors()
        assert est.getK() == 5
        assert est.getAlgorithm() == "ivfflat"
        items = rng.normal(size=(400, 4)).astype(np.float32)
        m = est.fit(items)
        # auto nlist ~ sqrt(400) = 20
        assert m._index is not None
        assert m._index.n_lists == 20

    def test_read_write_round_trip(self, tmp_path, rng):
        items = rng.normal(size=(120, 6)).astype(np.float32)
        m = (
            ApproximateNearestNeighbors()
            .setK(4)
            .setSeed(3)
            .setAlgoParams({"nlist": 6, "nprobe": 3})
            .fit(items)
        )
        q = rng.normal(size=(10, 6)).astype(np.float32)
        d, idx = m.kneighbors(q)
        path = str(tmp_path / "ann")
        m.save(path)
        loaded = ApproximateNearestNeighborsModel.load(path)
        assert loaded.getAlgoParams() == {"nlist": 6, "nprobe": 3}
        assert loaded.getSeed() == 3
        d2, idx2 = loaded.kneighbors(q)
        np.testing.assert_array_equal(idx, idx2)
        np.testing.assert_allclose(d, d2, rtol=1e-6)


class TestIVFPQ:
    """IVF-PQ: quantized distances trade exactness for memory; recall on
    probe-all must stay high, and the ADC distance must approximate the
    true squared distance at codebook resolution."""

    def test_recall_probe_all(self, rng):
        from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
            ApproximateNearestNeighbors,
        )
        from spark_rapids_ml_tpu.ops.knn import knn
        import jax.numpy as jnp

        items = rng.normal(size=(400, 16))
        queries = rng.normal(size=(25, 16))
        model = (
            ApproximateNearestNeighbors()
            .setAlgorithm("ivfpq")
            .setAlgoParams({"nlist": 8, "nprobe": 8, "M": 8, "n_bits": 6})
            .setK(10)
            .setSeed(0)
            .fit(items)
        )
        d_pq, i_pq = model.kneighbors(queries)
        _, i_true = knn(jnp.asarray(queries), jnp.asarray(items), 10,
                        metric="sqeuclidean")
        i_true = np.asarray(i_true)
        recall = np.mean([
            len(set(i_pq[q]) & set(i_true[q])) / 10 for q in range(len(queries))
        ])
        assert recall >= 0.7  # quantized at 6 bits x 8 subspaces
        assert np.all(np.diff(d_pq, axis=1) >= -1e-5)  # ascending distances

    def test_adc_distance_accuracy(self, rng):
        from spark_rapids_ml_tpu.ops.ann import build_ivfpq_index, ivfpq_search
        import jax.numpy as jnp

        items = rng.normal(size=(300, 8)).astype(np.float32)
        queries = rng.normal(size=(10, 8)).astype(np.float32)
        index = build_ivfpq_index(items, n_lists=4, m_subspaces=4, n_bits=8, seed=1)
        d2, idx = ivfpq_search(index, jnp.asarray(queries), k=5, n_probe=4)
        d2, idx = np.asarray(d2), np.asarray(idx)
        # ADC distance within quantization error of the true distance.
        for q in range(10):
            for j in range(5):
                true = np.sum((queries[q] - items[idx[q, j]]) ** 2)
                assert abs(d2[q, j] - true) < max(1.0, 0.5 * true)

    def test_refine_improves_recall(self, rng):
        from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
            ApproximateNearestNeighbors,
        )
        from spark_rapids_ml_tpu.ops.knn import knn
        import jax.numpy as jnp

        items = rng.normal(size=(600, 32))
        queries = rng.normal(size=(40, 32))
        _, i_true = knn(jnp.asarray(queries), jnp.asarray(items), 10,
                        metric="sqeuclidean")
        i_true = np.asarray(i_true)

        def recall(ap):
            m = (
                ApproximateNearestNeighbors()
                .setAlgorithm("ivfpq")
                .setAlgoParams(ap)
                .setK(10)
                .setSeed(0)
                .fit(items)
            )
            _, i = m.kneighbors(queries)
            return np.mean([len(set(i[q]) & set(i_true[q])) / 10 for q in range(40)])

        base = {"nlist": 6, "nprobe": 6, "M": 8, "n_bits": 4}
        r_plain = recall(base)
        r_refined = recall({**base, "refine_ratio": 8})
        # Probe-all isolates quantization loss; exact re-ranking of an 8x
        # shortlist must recover most of it (4-bit codes are deliberately
        # coarse, so the unrefined ranking is far from exact).
        assert r_refined >= r_plain + 0.05
        assert r_refined >= 0.85

    def test_bad_params(self, rng):
        from spark_rapids_ml_tpu.ops.ann import build_ivfpq_index

        items = rng.normal(size=(50, 10))
        with pytest.raises(ValueError):
            build_ivfpq_index(items, n_lists=4, m_subspaces=3)  # 10 % 3 != 0
        with pytest.raises(ValueError):
            build_ivfpq_index(items, n_lists=4, m_subspaces=2, n_bits=9)

    def test_m_auto_divides(self):
        from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
            ApproximateNearestNeighborsModel,
        )

        m = ApproximateNearestNeighborsModel()
        assert 10 % m._effective_m(10) == 0
        assert 16 % m._effective_m(16) == 0
        assert m._effective_m(7) == 1

    def test_explicit_bad_m_raises(self, rng):
        # An explicit M that does not divide d must raise, not be retuned.
        from spark_rapids_ml_tpu.models.approximate_nearest_neighbors import (
            ApproximateNearestNeighbors,
        )

        items = rng.normal(size=(50, 10))
        with pytest.raises(ValueError, match="not divisible"):
            (
                ApproximateNearestNeighbors()
                .setAlgorithm("ivfpq")
                .setAlgoParams({"nlist": 4, "M": 3})
                .fit(items)
            )

    def test_codes_are_uint8(self, rng):
        from spark_rapids_ml_tpu.ops.ann import build_ivfpq_index
        import jax.numpy as jnp

        index = build_ivfpq_index(
            rng.normal(size=(100, 8)), n_lists=4, m_subspaces=4, n_bits=8
        )
        assert index.codes.dtype == jnp.uint8
