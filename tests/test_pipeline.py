"""Pipeline tests — sequential composition of this package's estimators."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.pipeline import Pipeline, PipelineModel


def _clustered_data(rng, n_per=40, d=8):
    centers = np.zeros((3, d))
    centers[0, 0] = 10
    centers[1, 1] = 10
    centers[2, 2] = 10
    x = np.concatenate([rng.normal(size=(n_per, d)) + c for c in centers])
    return x, np.repeat(np.arange(3), n_per)


class TestPipeline:
    def test_pca_then_kmeans(self, rng):
        x, labels = _clustered_data(rng)
        df = DataFrame({"features": list(x)})
        pipe = Pipeline(
            stages=[
                PCA().setK(3).setInputCol("features").setOutputCol("pca"),
                KMeans().setK(3).setFeaturesCol("pca").setSeed(0),
            ]
        )
        model = pipe.fit(df)
        assert isinstance(model, PipelineModel)
        assert len(model.stages) == 2
        out = model.transform(df)
        assert "pca" in out.columns and "prediction" in out.columns
        preds = np.asarray(out.select("prediction"))
        # Clustering in PCA space must recover the 3 blobs (up to relabeling).
        for c in range(3):
            blok = preds[labels == c]
            assert np.mean(blok == np.bincount(blok).argmax()) > 0.95

    def test_transformer_stage_passthrough(self, rng):
        # A fitted model used directly as a pipeline stage (pure transformer).
        x, _ = _clustered_data(rng, n_per=20)
        df = DataFrame({"features": list(x)})
        pca_model = PCA().setK(2).setInputCol("features").setOutputCol("pca").fit(df)
        pipe = Pipeline(stages=[pca_model, KMeans().setK(3).setFeaturesCol("pca")])
        model = pipe.fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns

    def test_bad_stage_type(self):
        with pytest.raises(TypeError):
            Pipeline(stages=["not a stage"]).fit(None)

    def test_unfitted_pipeline_roundtrip(self, tmp_path):
        pipe = Pipeline(
            stages=[
                PCA().setK(2).setInputCol("features").setOutputCol("pca"),
                KMeans().setK(3).setFeaturesCol("pca").setSeed(1),
            ]
        )
        path = str(tmp_path / "pipe_unfitted")
        pipe.save(path)
        loaded = Pipeline.load(path)
        assert len(loaded.stages) == 2
        assert loaded.stages[0].getK() == 2
        assert loaded.stages[1].getK() == 3
        assert loaded.stages[1].getFeaturesCol() == "pca"

    def test_persistence_roundtrip(self, tmp_path, rng):
        x, _ = _clustered_data(rng, n_per=20)
        df = DataFrame({"features": list(x)})
        model = Pipeline(
            stages=[
                PCA().setK(2).setInputCol("features").setOutputCol("pca"),
                KMeans().setK(3).setFeaturesCol("pca").setSeed(1),
            ]
        ).fit(df)
        path = str(tmp_path / "pipe")
        model.save(path)
        loaded = PipelineModel.load(path)
        assert len(loaded.stages) == 2
        out_a = model.transform(df)
        out_b = loaded.transform(df)
        np.testing.assert_array_equal(
            np.asarray(out_a.select("prediction")),
            np.asarray(out_b.select("prediction")),
        )

    def test_load_rejects_foreign_class(self, tmp_path):
        """Metadata naming a class outside this package must not be imported
        (ADVICE r1: untrusted model dirs as import gadgets)."""
        import json

        pipe = Pipeline(stages=[PCA().setK(2)])
        path = str(tmp_path / "pipe_evil")
        pipe.save(path)
        meta_file = tmp_path / "pipe_evil" / "metadata" / "part-00000"
        meta = json.loads(meta_file.read_text())
        meta["stageClasses"] = ["os.system"]
        meta_file.write_text(json.dumps(meta) + "\n")
        with pytest.raises(ValueError, match="refusing to import"):
            Pipeline.load(path)
        # A path inside the package that resolves to a re-exported foreign
        # attribute (e.g. a numpy module alias) must be rejected too.
        meta["stageClasses"] = ["spark_rapids_ml_tpu.tuning.np"]
        meta_file.write_text(json.dumps(meta) + "\n")
        with pytest.raises(ValueError, match="refusing to load"):
            Pipeline.load(path)

    def test_allow_persisted_package_escape_hatch(self):
        """Extension libraries register their root package to make their
        custom stages loadable (the restriction is a default, not a wall)."""
        from spark_rapids_ml_tpu.core.persistence import (
            _LOADABLE_PACKAGES,
            allow_persisted_package,
            resolve_persisted_class,
        )

        with pytest.raises(ValueError, match="refusing to import"):
            resolve_persisted_class("collections.OrderedDict")
        allow_persisted_package("collections")
        try:
            import collections

            assert resolve_persisted_class("collections.OrderedDict") is collections.OrderedDict
        finally:
            _LOADABLE_PACKAGES.discard("collections")
        with pytest.raises(ValueError, match="bare top-level"):
            allow_persisted_package("a.b")
