"""Structured run telemetry (observability/): the metrics registry, the
JSONL event log, fit/serve reports, heartbeats, and the compat shim.

The acceptance case (TestAcceptance) is the ISSUE 4 contract: one
``LogisticRegression.fit`` + ``transform`` on the fault-injection
harness — one injected retry, checkpointing enabled — yields one JSONL
stream from which this suite reconstructs the stage-timing tree, the
retry attempt count (matching the ``retry.*.attempts`` counters), every
checkpoint write, and the serving cache hit/miss totals, all sharing one
``run_id``; with the knob unset, zero events are emitted and the range
path stays allocation-light (the budget test).
"""

import importlib.util
import json
import os
import threading
import time
import tracemalloc

import numpy as np
import pytest

from spark_rapids_ml_tpu.core import serving
from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegression
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.observability.heartbeat import (
    GangHeartbeat,
    heartbeat_scope,
)
from spark_rapids_ml_tpu.observability.metrics import (
    MetricError,
    Registry,
    default_registry,
    dump_snapshot,
)
from spark_rapids_ml_tpu.observability.report import build_stage_tree
from spark_rapids_ml_tpu.robustness.checkpoint import FitCheckpointer
from spark_rapids_ml_tpu.robustness.faults import inject
from spark_rapids_ml_tpu.robustness.retry import RetryExhaustedError, RetryPolicy
from spark_rapids_ml_tpu.utils import tracing
from spark_rapids_ml_tpu.utils.envknobs import env_str


# --- sink plumbing ------------------------------------------------------

_PREV_LOG = env_str(events.EVENT_LOG_ENV)


def _restore_sink():
    # Re-wire whatever the session started with: the explicit path when
    # TPUML_EVENT_LOG was set, else re-resolve from env so a session-wide
    # TPUML_TELEMETRY_DIR shard resumes (CI runs tier-1 under one).
    events.configure(_PREV_LOG if _PREV_LOG else None)


@pytest.fixture
def event_log(tmp_path):
    """A fresh per-test event-log file wired as the active sink."""
    path = tmp_path / "events.jsonl"
    events.configure(str(path))
    try:
        yield path
    finally:
        _restore_sink()


@pytest.fixture
def no_event_log():
    events.configure("")
    try:
        yield
    finally:
        _restore_sink()


_STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pyspark_stub")


@pytest.fixture
def stub_spark():
    """The pyspark stub installed as ``pyspark`` (the contract-suite
    arrangement — see tests/test_chaos.py)."""
    import sys

    saved = {n: m for n, m in sys.modules.items() if n.startswith("pyspark")}
    for n in list(saved):
        del sys.modules[n]
    sys.path.insert(0, _STUB)
    try:
        from pyspark.sql import SparkSession

        yield SparkSession.builder.master("local[2]").getOrCreate()
    finally:
        sys.path.remove(_STUB)
        for n in [n for n in sys.modules if n.startswith("pyspark")]:
            del sys.modules[n]
        sys.modules.update(saved)


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "tpuml_metrics",
        os.path.join(os.path.dirname(__file__), "..", "tools", "tpuml_metrics.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _double_kernel(x):
    return x * 2.0


# --- the typed registry -------------------------------------------------


class TestMetricsRegistry:
    def test_counter_inc_value_and_labels(self):
        r = Registry()
        c = r.counter("c.hits")
        c.inc()
        c.inc(4)
        assert c.value() == 5
        c.inc(2, site="a")
        assert c.value(site="a") == 2
        assert c.value() == 5  # unlabeled series untouched

    def test_gauge_set_and_callable(self):
        r = Registry()
        g = r.gauge("g.size")
        g.set(7)
        assert g.value() == 7
        g.set_function(lambda: 1.25, process="3")
        assert g.value(process="3") == 1.25
        snap = r.snapshot()
        assert snap["gauges"]["g.size"] == 7
        assert snap["gauges"]['g.size{process="3"}'] == 1.25

    def test_histogram_buckets_sum_count(self):
        r = Registry()
        h = r.histogram("h.lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        out = h.value()
        assert out["count"] == 4
        assert out["sum"] == pytest.approx(55.55)
        assert out["buckets"][0.1] == 1
        assert out["buckets"][1.0] == 2
        assert out["buckets"][10.0] == 3
        assert out["buckets"][float("inf")] == 4

    def test_kind_clash_raises(self):
        r = Registry()
        r.counter("x")
        with pytest.raises(MetricError):
            r.gauge("x")

    def test_prometheus_exposition(self):
        r = Registry()
        r.counter("serving.cache.hit", "hits").inc(3)
        r.gauge("cache.size").set(2)
        r.histogram("lat", buckets=(1.0,)).observe(0.5, solver="k")
        text = r.render_prometheus()
        assert "# TYPE tpuml_serving_cache_hit counter" in text
        assert "tpuml_serving_cache_hit 3.0" in text
        assert "# HELP tpuml_serving_cache_hit hits" in text
        assert "tpuml_cache_size 2.0" in text
        assert 'tpuml_lat_bucket{le="1.0",solver="k"} 1' in text
        assert 'tpuml_lat_bucket{le="+Inf",solver="k"} 1' in text
        assert 'tpuml_lat_count{solver="k"} 1' in text

    def test_snapshot_is_json_ready(self):
        r = Registry()
        r.counter("a").inc()
        r.histogram("h", buckets=(1.0,)).observe(2.0)
        json.dumps(r.snapshot())  # must not raise

    def test_clear_by_prefix_and_kind(self):
        r = Registry()
        r.counter("p.a").inc()
        r.gauge("p.b").set(1)
        r.clear("p.", kinds=("counter",))
        names = set(r.metrics())
        assert "p.a" not in names and "p.b" in names

    def test_bump_counter_alias_is_registry_backed(self):
        tracing.clear_counters("alias.")
        tracing.bump_counter("alias.x", 3)
        assert default_registry.counter("alias.x").value() == 3
        assert tracing.counters("alias.") == {"alias.x": 3}
        assert tracing.counter_value("alias.x") == 3
        tracing.clear_counters("alias.")
        assert tracing.counters("alias.") == {}

    def test_dump_snapshot_formats(self, tmp_path):
        default_registry.counter("dump.test").inc()
        j = tmp_path / "m.json"
        p = tmp_path / "m.prom"
        dump_snapshot(str(j))
        dump_snapshot(str(p))
        assert "dump.test" in json.load(open(j))["counters"]
        assert "tpuml_dump_test" in open(p).read()


# --- TraceRange satellite: exception opacity + stage tree ---------------


class TestTraceRangeSpans:
    def test_ok_and_exception_type_recorded(self, event_log):
        with pytest.raises(ValueError):
            with tracing.TraceRange("boom"):
                raise ValueError("x")
        recs = [r for r in _records(event_log) if r["event"] == "span"]
        assert recs[-1]["name"] == "boom"
        assert recs[-1]["ok"] is False
        assert recs[-1]["exc"] == "ValueError"

    def test_depth_parent_rebuild_stage_tree(self, event_log):
        with events.run_scope("job", "tree"):
            with tracing.TraceRange("outer"):
                with tracing.TraceRange("mid"):
                    with tracing.TraceRange("leaf"):
                        pass
                with tracing.TraceRange("sibling"):
                    pass
        spans = [r for r in _records(event_log) if r["event"] == "span"]
        tree = build_stage_tree(spans)
        outer = next(n for n in tree if n["name"] == "outer")
        assert [c["name"] for c in outer["children"]] == ["mid", "sibling"]
        assert outer["children"][0]["children"][0]["name"] == "leaf"
        depths = {r["name"]: r["depth"] for r in spans}
        assert depths["outer"] == 0 and depths["mid"] == 1 and depths["leaf"] == 2

    def test_ring_buffer_keeps_3tuple_shape(self):
        tracing.clear_events()
        with tracing.TraceRange("compat"):
            pass
        (name, start, end), = tracing.recent_events()[-1:]
        assert name == "compat" and end >= start


# --- event log ----------------------------------------------------------


class TestEventLog:
    def test_every_record_type_schema_validates(self, event_log, tmp_path):
        # Drive the real emitters for each record type in SCHEMA's core.
        with events.run_scope("job", "schema"):          # run start/end
            with tracing.TraceRange("a span"):           # span
                pass
            policy = RetryPolicy(max_attempts=2, base_delay=0.0)
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] == 1:
                    raise OSError("transient")
                return 1

            policy.run(flaky, name="obs.unit")           # retry
            with inject("persistence.write=0"):          # fault arm/disarm
                pass
            ck = FitCheckpointer(
                str(tmp_path / "ck"), uid="u", param_hash="p", data_fp="d",
                every=1,
            )
            ck.save_async(3, (np.zeros(2),))             # checkpoint write
            ck.wait()
            ck.restore_latest(template=(np.zeros(2),))   # checkpoint restore
            GangHeartbeat(process_id=9, interval=10).beat()  # heartbeat
            serving.serve_rows(                          # serving hit/miss
                _double_kernel, np.ones((4, 3)), name="obs.schema"
            )
            # counters flush + report ride the fit recorder.
            PCA().setK(2).fit(np.random.default_rng(0).standard_normal((24, 5)))
        recs = _records(event_log)
        problems = [p for r in recs for p in events.validate_record(r)]
        assert problems == []
        seen = {r["event"] for r in recs}
        for required in ("run", "span", "retry", "fault", "checkpoint",
                         "heartbeat", "serving", "counters", "report"):
            assert required in seen, f"no {required} record emitted"

    def test_degrade_and_persistence_records(self, event_log, tmp_path, monkeypatch):
        from spark_rapids_ml_tpu.models.logistic_regression import (
            LogisticRegressionModel,
        )
        from spark_rapids_ml_tpu.robustness.degrade import (
            DegradationWarning,
            run_degradable,
        )

        monkeypatch.setenv("TPUML_DEGRADE", "cpu")

        def accel():
            raise RetryExhaustedError("site.x", 2, OSError("gone"), "why")

        with pytest.warns(DegradationWarning):
            assert run_degradable(accel, lambda: 42, what="unit") == 42
        m = LogisticRegressionModel("u", np.zeros((3, 1)), np.zeros(1))
        m.save(str(tmp_path / "model"))
        recs = _records(event_log)
        assert problems_free(recs)
        kinds = {r["event"] for r in recs}
        assert "degrade" in kinds and "persistence" in kinds

    def test_stderr_sink(self, capsys):
        events.configure("stderr")
        try:
            events.emit("fault", action="arm")
        finally:
            _restore_sink()
        err = capsys.readouterr().err
        assert '"event": "fault"' in err

    def test_run_id_joins_across_threads_async_writer(self, event_log, tmp_path):
        ck = FitCheckpointer(
            str(tmp_path / "ck"), uid="u2", param_hash="p", data_fp="d",
            every=1,
        )
        main_thread = threading.get_ident()
        with events.run_scope("fit", "threaded") as ctx:
            with tracing.TraceRange("driver side"):
                ck.save_async(1, (np.arange(4.0),))
                ck.wait()
            rid = ctx.run_id
        recs = _records(event_log)
        writes = [r for r in recs if r["event"] == "checkpoint"
                  and r["action"] == "write"]
        assert writes and all(w["run_id"] == rid for w in writes)
        spans = [r for r in recs if r["event"] == "span"]
        assert {s["run_id"] for s in spans} == {rid}
        # The checkpoint-write span landed from the WRITER thread yet
        # carries the fit's run_id — the copied-context contract.
        writer_spans = [s for s in spans if s["name"] == "checkpoint write"]
        assert writer_spans and writer_spans[0]["thread"] != main_thread

    def test_zero_events_when_unset(self, no_event_log):
        before = events.emitted_count()
        assert not events.enabled()
        with tracing.TraceRange("silent"):
            pass
        tracing.bump_counter("silent.counter")
        with inject("persistence.write=0"):
            pass
        assert events.emitted_count() == before

    def test_range_path_allocation_budget(self, no_event_log):
        n = 300
        with tracing.TraceRange("warmup"):
            pass
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        for _ in range(n):
            with tracing.TraceRange("budget"):
                pass
        current, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # Disabled path: a range object, an annotation, one ring tuple —
        # nowhere near 4 KiB each. A span-record dict per range would
        # blow this bound, which is the regression the test pins.
        assert peak - base < n * 4096


# --- heartbeats ---------------------------------------------------------


class TestHeartbeat:
    def test_beats_emit_and_gauge_reads_age(self, event_log):
        with heartbeat_scope(process_id=3, interval=0.02) as hb:
            time.sleep(0.12)
            assert hb.age_seconds() < 1.0
            # Live member: the age gauge reads as a CURRENT age.
            g = default_registry.gauge("gang.heartbeat.age_seconds")
            assert g.value(process="3") >= 0.0
            snap = default_registry.snapshot()
            assert 'gang.heartbeat.age_seconds{process="3"}' in snap["gauges"]
        recs = [r for r in _records(event_log) if r["event"] == "heartbeat"]
        assert len(recs) >= 3
        seqs = [r["seq"] for r in recs]
        assert seqs == sorted(seqs) and seqs[0] == 1
        assert all(r["interval"] == 0.02 for r in recs)
        # Finished member: the series is retired, not left to grow.
        snap = default_registry.snapshot()
        assert 'gang.heartbeat.age_seconds{process="3"}' not in snap["gauges"]

    def test_zero_interval_disables_thread(self, no_event_log):
        hb = GangHeartbeat(process_id=1, interval=0).start()
        assert hb._thread is None
        hb.stop()

    def test_barrier_worker_heartbeats(self, event_log, stub_spark, monkeypatch):
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        monkeypatch.setenv("TPUML_GANG_HEARTBEAT_EVERY", "0.01")
        df = stub_spark.createDataFrame(
            [(float(i),) for i in range(4)], ["v"], numPartitions=2
        )

        def task(ctx, it):
            time.sleep(0.05)
            return [sum(r.v for r in it)]

        out = barrier_gang_run(df.rdd, task)
        assert sum(out) == sum(range(4))
        beats = [r for r in _records(event_log) if r["event"] == "heartbeat"]
        assert beats and all(r["what"] == "barrier" for r in beats)
        assert {r["process"] for r in beats} == {0, 1}  # one stream per member


# --- reports ------------------------------------------------------------


class TestReports:
    def test_fit_report_stage_tree_and_counters(self, no_event_log):
        rng = np.random.default_rng(1)
        X = rng.standard_normal((48, 4))
        y = (X[:, 0] > 0).astype(int)
        model = LogisticRegression().setMaxIter(4).fit((X, y))
        rep = model.fit_report()
        assert rep is not None and rep.ok
        assert rep.kind == "fit" and rep.label == "LogisticRegression"
        totals = rep.stage_totals()
        assert "logreg fit" in totals and "ingest" in totals
        tree = rep.stage_tree()
        fit_node = next(n for n in tree if n["name"] == "logreg fit")
        assert any(c["name"] == "ingest" for c in fit_node["children"])
        text = str(rep)
        assert "logreg fit" in text and rep.run_id in text
        assert rep.wall_seconds > 0
        json.dumps(rep.summary())  # picklable/serializable shape

    def test_pca_fit_report(self, no_event_log):
        rng = np.random.default_rng(2)
        model = PCA().setK(2).fit(rng.standard_normal((32, 6)))
        rep = model.fit_report()
        assert rep is not None and rep.label == "PCA"

    def test_nested_fit_joins_outer_run(self, no_event_log):
        rng = np.random.default_rng(3)
        X = rng.standard_normal((32, 4))
        with events.run_scope("job", "outer") as ctx:
            model = PCA().setK(2).fit(X)
        assert model.fit_report().run_id == ctx.run_id

    def test_serving_report(self, no_event_log):
        from spark_rapids_ml_tpu.observability.report import serving_report

        serving.serve_rows(_double_kernel, np.ones((6, 2)), name="obs.rep")
        rep = serving_report()
        assert rep["cache"]["size"] >= 1
        assert rep["cache_size_gauge"] == rep["cache"]["size"]
        assert rep["batch_rows"]["count"] >= 1

    def test_profile_dir_knob(self, no_event_log, tmp_path, monkeypatch):
        prof = tmp_path / "profile"
        monkeypatch.setenv("TPUML_PROFILE_DIR", str(prof))
        rng = np.random.default_rng(4)
        PCA().setK(2).fit(rng.standard_normal((24, 5)))
        # jax writes a plugins/ or .trace dir tree under the profile dir.
        assert prof.exists() and any(prof.rglob("*"))


# --- serving cache-size gauge (satellite) -------------------------------


class TestServingCacheGauge:
    def test_size_gauge_tracks_cache_under_lock(self, no_event_log):
        serving.clear_program_cache()
        g = default_registry.gauge("serving.cache.size")
        assert g.value() == 0
        serving.serve_rows(_double_kernel, np.ones((4, 2)), name="obs.gauge")
        assert g.value() == serving.program_cache_stats()["size"] >= 1
        serving.clear_program_cache()
        assert g.value() == 0


# --- the acceptance scenario -------------------------------------------


class TestAcceptance:
    def test_fit_transform_one_stream_one_run_id(
        self, event_log, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TPUML_CHECKPOINT_DIR", str(tmp_path / "ck"))
        monkeypatch.setenv("TPUML_CHECKPOINT_EVERY", "2")
        rng = np.random.default_rng(7)
        X = rng.standard_normal((96, 5))
        y = (X @ np.arange(1.0, 6.0) > 0).astype(int)

        c0 = {
            k: tracing.counter_value(k)
            for k in (
                "retry.ingest.device_put.attempts",
                "checkpoint.write",
                "serving.cache.hit",
                "serving.cache.miss",
            )
        }
        with events.run_scope("job", "acceptance") as ctx:
            with inject("ingest.device_put=1") as plan:  # ONE injected retry
                model = LogisticRegression().setMaxIter(8).fit((X, y))
            assert plan.fired == [("ingest.device_put", 0)]
            model.predict(X[:10])   # miss + compile
            model.predict(X[:10])   # hit
            rid = ctx.run_id
        delta = {
            k: tracing.counter_value(k) - v for k, v in c0.items()
        }

        recs = _records(event_log)
        assert problems_free(recs)
        # ONE run_id across the whole episode — fit spans, retry, fault,
        # checkpoint writes (async thread included), serving traffic.
        assert {r["run_id"] for r in recs} == {rid}

        # Stage-timing tree reconstructs from the stream alone.
        spans = [r for r in recs if r["event"] == "span"]
        tree = build_stage_tree(spans)
        fit_node = next(n for n in tree if n["name"] == "logreg fit")
        ingest = next(c for c in fit_node["children"] if c["name"] == "ingest")
        retry_nodes = [
            c for c in ingest["children"] if c["name"].startswith("retry:")
        ]
        # Attempt 0 dies at the injected fault (before H2D); attempt 1
        # carries the actual placement.
        assert len(retry_nodes) == 2
        assert any(
            g["name"] == "ingest H2D" for rn in retry_nodes
            for g in rn["children"]
        )
        assert any(s["name"] == "checkpoint write" for s in spans)

        # Retry attempts in the stream == the counters.
        retries = [r for r in recs if r["event"] == "retry"
                   and r["site"] == "ingest.device_put"]
        assert len(retries) == delta["retry.ingest.device_put.attempts"] == 2
        assert {r["outcome"] for r in retries} == {"retry", "ok"}
        fires = [r for r in recs if r["event"] == "fault"
                 and r.get("action") == "fire"]
        assert len(fires) == 1 and fires[0]["site"] == "ingest.device_put"

        # Every checkpoint write is in the stream.
        writes = [r for r in recs if r["event"] == "checkpoint"
                  and r["action"] == "write"]
        assert len(writes) == delta["checkpoint.write"] >= 1
        assert all(os.path.basename(w["path"]).startswith("ckpt-")
                   for w in writes)

        # Serving cache hit/miss totals match the counters.
        hits = [r for r in recs if r["event"] == "serving"
                and r["action"] == "hit"]
        misses = [r for r in recs if r["event"] == "serving"
                  and r["action"] == "miss"]
        assert len(hits) == delta["serving.cache.hit"] >= 1
        assert len(misses) == delta["serving.cache.miss"] >= 1

        # The fit report rides the same run and counts the activity.
        rep = model.fit_report()
        assert rep.run_id == rid
        assert rep.checkpoint_activity().get("checkpoint.write", 0) >= 1


# --- the CLI ------------------------------------------------------------


class TestMetricsCLI:
    def test_events_summary_and_validation(self, event_log, tmp_path, capsys):
        with events.run_scope("job", "cli") as ctx:
            with tracing.TraceRange("cli span"):
                pass
        cli = _load_cli()
        recs, problems = cli.parse_lines(open(event_log))
        assert problems == [] and recs
        summary = cli.summarize(recs)
        assert ctx.run_id in summary["runs"]
        assert summary["runs"][ctx.run_id]["spans"] >= 1
        assert cli.main(["events", str(event_log), "--validate"]) == 0
        out = capsys.readouterr().out
        assert ctx.run_id in out

    def test_validate_flags_malformed_lines(self, tmp_path, capsys):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"event": "span"}\nnot json\n')
        cli = _load_cli()
        assert cli.main(["events", str(bad), "--validate"]) == 1
        err = capsys.readouterr().err
        assert "INVALID" in err

    def test_snapshot_prometheus_rendering(self, tmp_path, capsys):
        default_registry.counter("cli.test").inc(2)
        snap = tmp_path / "m.json"
        dump_snapshot(str(snap))
        cli = _load_cli()
        assert cli.main(["snapshot", str(snap), "--format", "prom"]) == 0
        out = capsys.readouterr().out
        assert "tpuml_cli_test 2.0" in out


def problems_free(recs):
    problems = [p for r in recs for p in events.validate_record(r)]
    assert problems == [], problems
    return True
