"""Unit tests for the ops layer — the XLA replacements for the reference's
JNI kernels (rapidsml_jni.cu), each checked against a numpy oracle."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.ops import (
    cal_svd,
    covariance,
    eigh_descending,
    gemm_project,
    gemm_syrk,
    mean_and_covariance,
    sign_flip,
    spr,
    triu_to_full,
)
from spark_rapids_ml_tpu.ops.covariance import (
    centered_gram,
    centered_gram_blocked,
    centered_gram_packed,
    welford_add_block,
    welford_init,
    welford_merge,
)


class TestGemm:
    def test_syrk(self, rng):
        b = rng.normal(size=(50, 8))
        np.testing.assert_allclose(gemm_syrk(b), b.T @ b, atol=1e-10)

    def test_project(self, rng):
        a = rng.normal(size=(8, 50))
        b = rng.normal(size=(8, 3))
        np.testing.assert_allclose(gemm_project(a, b), a.T @ b, atol=1e-10)


class TestPacked:
    def test_spr_matches_blas_layout(self, rng):
        """Packed upper, column-major — cublasDspr/Spark BLAS.spr layout."""
        n = 5
        x = rng.normal(size=(n,))
        packed = np.zeros(n * (n + 1) // 2)
        result = np.asarray(spr(x, packed))
        outer = np.outer(x, x)
        expected = np.concatenate([outer[: j + 1, j] for j in range(n)])
        np.testing.assert_allclose(result, expected, atol=1e-12)

    def test_triu_to_full_roundtrip(self, rng):
        a = rng.normal(size=(6, 6))
        sym = a + a.T
        packed = np.concatenate([sym[: j + 1, j] for j in range(6)])
        np.testing.assert_allclose(triu_to_full(packed), sym, atol=1e-12)

    def test_triu_to_full_rejects_bad_length(self):
        with pytest.raises(ValueError):
            triu_to_full(np.zeros(7))


class TestEigh:
    def test_sign_flip(self):
        u = np.array([[0.9, -0.2], [-0.1, -0.8]])
        flipped = np.asarray(sign_flip(u))
        # col 0: max-|.| elem is 0.9 (positive) -> unchanged
        np.testing.assert_allclose(flipped[:, 0], u[:, 0])
        # col 1: max-|.| elem is -0.8 (negative) -> negated
        np.testing.assert_allclose(flipped[:, 1], -u[:, 1])

    def test_sign_flip_idempotent(self, rng):
        u = rng.normal(size=(10, 10))
        once = np.asarray(sign_flip(u))
        twice = np.asarray(sign_flip(once))
        np.testing.assert_allclose(once, twice)

    def test_eigh_descending(self, rng):
        a = rng.normal(size=(12, 12))
        sym = a @ a.T
        w, v = eigh_descending(sym)
        w, v = np.asarray(w), np.asarray(v)
        assert np.all(np.diff(w) <= 1e-9)  # descending
        np.testing.assert_allclose(sym @ v, v * w, atol=1e-8)

    def test_cal_svd_psd(self, rng):
        """Full calSVD contract: U orthonormal, s = sqrt(eigvals) descending."""
        a = rng.normal(size=(15, 15))
        cov = a @ a.T / 15
        u, s = cal_svd(cov)
        u, s = np.asarray(u), np.asarray(s)
        expected_s = np.sqrt(np.sort(np.linalg.eigvalsh(cov))[::-1])
        np.testing.assert_allclose(s, expected_s, atol=1e-8)
        np.testing.assert_allclose(u.T @ u, np.eye(15), atol=1e-8)

    def test_cal_svd_clamps_negative_eigs(self):
        """Near-singular PSD input must not produce NaN singular values."""
        cov = np.outer([1.0, 1.0], [1.0, 1.0])  # rank-1, eigvals {2, 0±eps}
        _, s = cal_svd(cov)
        assert not np.any(np.isnan(np.asarray(s)))


class TestCovariance:
    def test_mean_and_covariance(self, rng):
        x = rng.normal(size=(100, 10))
        mean, cov = mean_and_covariance(x)
        np.testing.assert_allclose(mean, x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)

    def test_covariance_normalization_is_n_minus_1(self, rng):
        """Both paths normalize by (n-1) — the reference GEMM path's
        1/sqrt(numCols-1) mis-scaling (RapidsRowMatrix.scala:169) is fixed."""
        x = rng.normal(size=(40, 6))
        np.testing.assert_allclose(covariance(x), np.cov(x, rowvar=False), atol=1e-10)

    def test_blocked_matches_dense(self, rng):
        x = rng.normal(size=(1000, 16))
        mean = x.mean(axis=0)
        dense = centered_gram(x, mean)
        blocked = centered_gram_blocked(x, mean, block_rows=128)
        np.testing.assert_allclose(blocked, dense, atol=1e-8)

    def test_blocked_padding_is_exact_zero_contribution(self, rng):
        """n not a multiple of block_rows: mean-padding adds nothing."""
        x = rng.normal(size=(130, 4))
        mean = x.mean(axis=0)
        np.testing.assert_allclose(
            centered_gram_blocked(x, mean, block_rows=64),
            centered_gram(x, mean),
            atol=1e-10,
        )

    def test_packed_matches_dense(self, rng):
        x = rng.normal(size=(30, 5))
        mean = x.mean(axis=0)
        full = np.asarray(centered_gram(x, mean))
        packed = np.asarray(centered_gram_packed(x, mean))
        expected = np.concatenate([full[: j + 1, j] for j in range(5)])
        np.testing.assert_allclose(packed, expected, atol=1e-10)

    def test_welford_streaming_mean(self, rng):
        x = rng.normal(size=(500, 8)) * 3 + 7
        state = welford_init(8)
        for blk in np.array_split(x, 7):
            state = welford_add_block(state, blk)
        count, mean, m2 = state
        assert int(count) == 500
        np.testing.assert_allclose(mean, x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(m2 / (500 - 1), x.var(axis=0, ddof=1), atol=1e-9)

    def test_welford_merge_associative(self, rng):
        x = rng.normal(size=(100, 4))
        a = welford_add_block(welford_init(4), x[:30])
        b = welford_add_block(welford_init(4), x[30:])
        merged = welford_merge(a, b)
        np.testing.assert_allclose(merged[1], x.mean(axis=0), atol=1e-10)
