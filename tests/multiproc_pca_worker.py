"""Worker for the multi-process distributed PCA integration test.

Launched N times by tests/test_multiprocess.py with TPUML_COORDINATOR /
TPUML_NUM_PROCESSES / TPUML_PROCESS_ID in the environment — the same
contract a Spark/SLURM/GKE launcher would use in production (one process
per chip). Each worker loads only ITS slice of the dataset, fits through
the ordinary library API with a global mesh, and checks the fitted model
against the full-dataset numpy oracle.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# Interpreter-level site customization may have pre-imported jax and forced
# a real-accelerator platform; override BOTH (env is inherited, config wins
# over the captured env) before the distributed runtime comes up.
import jax

jax.config.update("jax_platforms", "cpu")
# Cross-process collectives on the CPU backend need an explicit transport
# on older jaxlibs (the default "none" raises "Multiprocess computations
# aren't implemented on the CPU backend").
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:  # newer jax: gloo is the default, the knob may be gone
    pass
# Default x64 for tight oracle tolerances; TPUML_TEST_NO_X64 exercises the
# real-TPU configuration (fp32 compute, double-float moment wire format).
_x64 = os.environ.get("TPUML_TEST_NO_X64") != "1"
jax.config.update("jax_enable_x64", _x64)

from spark_rapids_ml_tpu.parallel import distributed as dist
from spark_rapids_ml_tpu.utils.envknobs import env_int

dist.initialize()  # from TPUML_* env

from spark_rapids_ml_tpu.feature import PCA


def main() -> None:
    pid = jax.process_index()
    n_proc = jax.process_count()
    assert n_proc == env_int("TPUML_NUM_PROCESSES"), n_proc

    # Deterministic global dataset; every worker derives the same one and
    # takes a DIFFERENT (deliberately uneven) slice as its local data.
    rng = np.random.default_rng(0)
    n = int(os.environ.get("TPUML_TEST_ROWS", "1003"))
    d = int(os.environ.get("TPUML_TEST_D", "12"))
    x = rng.normal(size=(n, d)) * np.linspace(1.0, 2.0, d) + 100.0
    if os.environ.get("TPUML_TEST_EMPTY_LAST") == "1" and n_proc > 1:
        # Deployment reality: one executor may hold no rows; the fit must
        # neither crash it nor strand its peers in a collective.
        bounds = np.linspace(0, n, n_proc).astype(int).tolist() + [n]
    else:
        bounds = np.linspace(0, n, n_proc + 1).astype(int)
    local = x[bounds[pid] : bounds[pid + 1]]

    shape_env = os.environ.get("TPUML_TEST_MESH_SHAPE")
    shape = tuple(int(v) for v in shape_env.split(",")) if shape_env else None
    mesh = dist.global_mesh(shape)
    victim = os.environ.get("TPUML_TEST_FAULT_VICTIM")
    if victim is not None and int(victim) == pid:
        # Fault injection: this executor dies mid-stream (after two
        # blocks, before the merge collective) — the hard-kill an OOM
        # or preemption delivers, with no cleanup.
        def dying_blocks():
            for i, start in enumerate(range(0, local.shape[0], 97)):
                if i == 2:
                    os._exit(42)
                yield local[start : start + 97]

        PCA(mesh=mesh).setK(3).fit(dying_blocks())
        raise AssertionError("victim must have exited")  # pragma: no cover
    if victim is not None:
        # Survivor of the fault-injection run: the fit must RAISE a
        # distributed-runtime error within the (tightened) heartbeat
        # window — not hang, not return a wrong model.
        import time

        blocks = (local[i : i + 97] for i in range(0, local.shape[0], 97))
        t0 = time.monotonic()
        try:
            PCA(mesh=mesh).setK(3).fit(blocks)
        except Exception as e:  # noqa: BLE001 - the assertion IS the raise
            elapsed = time.monotonic() - t0
            print(
                f"SURVIVOR_RAISED {type(e).__name__} after {elapsed:.1f}s: "
                f"{(str(e).splitlines() or [''])[0][:200]}"
            )
            sys.exit(3)
        print("SURVIVOR_COMPLETED_UNEXPECTEDLY")
        sys.exit(4)
    import time

    t0 = time.monotonic()
    if os.environ.get("TPUML_TEST_STREAMING") == "1":
        # Stream the local rows as a one-shot generator of small blocks —
        # per-process constant-memory scan + cross-process moment merge.
        blocks = (local[i : i + 97] for i in range(0, local.shape[0], 97))
        model = PCA(mesh=mesh).setK(3).fit(blocks)
    else:
        model = PCA(mesh=mesh).setK(3).fit([local] if local.shape[0] else [])
    # Fit wall (post-bringup, incl. compile + collectives): the
    # weak-scaling record in BASELINE.md config 5 reads these lines.
    print(f"FIT_WALL {time.monotonic() - t0:.3f}")

    from spark_rapids_ml_tpu.utils.testing import assert_components_close

    cov = np.cov(x, rowvar=False)
    w, v = np.linalg.eigh(cov)
    w, v = w[::-1], v[:, ::-1]
    tol = 1e-6 if _x64 else 1e-3  # fp32 compute floor on +100-offset data
    assert_components_close(model.pc, v[:, :3], tol)
    np.testing.assert_allclose(
        model.explainedVariance, (w / w.sum())[:3], atol=tol
    )
    print(f"OK process {pid}/{n_proc}")


if __name__ == "__main__":
    main()
