"""Worker for the multi-process distributed PCA integration test.

Launched N times by tests/test_multiprocess.py with TPUML_COORDINATOR /
TPUML_NUM_PROCESSES / TPUML_PROCESS_ID in the environment — the same
contract a Spark/SLURM/GKE launcher would use in production (one process
per chip). Each worker loads only ITS slice of the dataset, fits through
the ordinary library API with a global mesh, and checks the fitted model
against the full-dataset numpy oracle.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# Interpreter-level site customization may have pre-imported jax and forced
# a real-accelerator platform; override BOTH (env is inherited, config wins
# over the captured env) before the distributed runtime comes up.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from spark_rapids_ml_tpu.parallel import distributed as dist

dist.initialize()  # from TPUML_* env

from spark_rapids_ml_tpu.feature import PCA


def main() -> None:
    pid = jax.process_index()
    n_proc = jax.process_count()
    assert n_proc == int(os.environ["TPUML_NUM_PROCESSES"]), n_proc

    # Deterministic global dataset; every worker derives the same one and
    # takes a DIFFERENT (deliberately uneven) slice as its local data.
    rng = np.random.default_rng(0)
    n, d = 1003, 12
    x = rng.normal(size=(n, d)) * np.linspace(1.0, 2.0, d) + 100.0
    if os.environ.get("TPUML_TEST_EMPTY_LAST") == "1" and n_proc > 1:
        # Deployment reality: one executor may hold no rows; the fit must
        # neither crash it nor strand its peers in a collective.
        bounds = np.linspace(0, n, n_proc).astype(int).tolist() + [n]
    else:
        bounds = np.linspace(0, n, n_proc + 1).astype(int)
    local = x[bounds[pid] : bounds[pid + 1]]

    mesh = dist.global_mesh()
    model = PCA(mesh=mesh).setK(3).fit([local] if local.shape[0] else [])

    from spark_rapids_ml_tpu.utils.testing import assert_components_close

    cov = np.cov(x, rowvar=False)
    w, v = np.linalg.eigh(cov)
    w, v = w[::-1], v[:, ::-1]
    assert_components_close(model.pc, v[:, :3], 1e-6)
    np.testing.assert_allclose(
        model.explainedVariance, (w / w.sum())[:3], atol=1e-8
    )
    print(f"OK process {pid}/{n_proc}")


if __name__ == "__main__":
    main()
