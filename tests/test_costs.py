"""Program cost ledger contracts (ISSUE 8).

The load-bearing promises, each pinned here:

  - every compile at a chokepoint == one ledger entry with XLA's cost
    AND memory analyses (or an explicit ``unavailable`` marker) —
    counter- and ``jax_log_compiles``-asserted;
  - with the ledger DISABLED (the default), the compile and serve paths
    add zero events, zero ledger state, and stay allocation-light;
  - the retrace watchdog classifies compiles and fires (structured
    warning + ``compile.retrace`` counter) on a seeded bucket bypass;
  - admission pricing switches from the declared-spec estimate to the
    program's measured temp+output bytes after its first compile;
  - ``tpuml_prof --diff`` gates a seeded flops regression non-zero;
  - gang shards merge: run counters sum, HBM watermarks max;
  - segmented fits under the ledger are BIT-IDENTICAL to the plain
    jitted path (the ledger observes, never perturbs).
"""

import json
import logging
import os
import tracemalloc
import warnings

import jax
import numpy as np
import pytest

from spark_rapids_ml_tpu.core import serving
from spark_rapids_ml_tpu.core.serving import clear_program_cache, serve_rows
from spark_rapids_ml_tpu.observability import costs, events
from spark_rapids_ml_tpu.observability.costs import (
    HbmSampler,
    RetraceStormWarning,
    attribute_hbm_growth,
    merge_ledger_docs,
    validate_ledger,
)
from spark_rapids_ml_tpu.utils.tracing import clear_counters, counter_value

from tools import tpuml_prof


def _kernel(x, w):
    return x @ w


def _kernel2(x, w):
    return x @ w + 1.0


@pytest.fixture
def ledger(monkeypatch):
    """An armed, empty ledger with clean chokepoint caches + counters."""
    monkeypatch.setenv("TPUML_COST_LEDGER", "1")
    clear_program_cache()
    clear_counters("compile.")
    clear_counters("serving.admission")
    costs.reset_for_tests()
    led = costs.active()
    assert led is not None
    yield led
    costs.configure(enable=False)
    clear_program_cache()


@pytest.fixture
def no_ledger(monkeypatch):
    monkeypatch.delenv("TPUML_COST_LEDGER", raising=False)
    clear_program_cache()
    clear_counters("compile.")
    costs.reset_for_tests()
    assert costs.active() is None
    yield
    clear_program_cache()


class TestLedgerCapture:
    def test_compiles_equal_ledger_entries(self, ledger, rng, caplog):
        """Three distinct buckets -> three compiles -> three AOT ledger
        entries, each carrying cost+memory analyses (or explicit
        markers); the warm repeat adds invocations but neither compiles
        (jax's own log asserts it) nor entries."""
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))
        batches = [rng.normal(size=(n, 6)).astype(np.float32)
                   for n in (4, 30, 200)]
        for x in batches:
            serve_rows(_kernel, x, (w,), name="costs.kernel")
        doc = costs.ledger_snapshot()
        assert validate_ledger(doc) == []
        aot = [e for e in doc["entries"] if e["kind"] == "aot"]
        assert len(aot) == 3
        assert serving.program_cache_stats()["compiles"] == 3
        assert (
            counter_value("compile.new_program")
            + counter_value("compile.new_bucket")
            == 3
        )
        for e in aot:
            # CPU reports both analyses; the contract either way is
            # "values or an explicit marker", never silently absent.
            if "cost_analysis" not in e["unavailable"]:
                assert e["flops"] > 0 and e["bytes_accessed"] > 0
            if "memory_analysis" not in e["unavailable"]:
                assert e["output_bytes"] > 0
            assert e["compiles"] == 1 and e["invocations"] == 1

        jax.config.update("jax_log_compiles", True)
        try:
            with caplog.at_level(logging.WARNING, logger="jax._src.dispatch"):
                for x in batches:
                    serve_rows(_kernel, x, (w,), name="costs.kernel")
        finally:
            jax.config.update("jax_log_compiles", False)
        assert [
            r for r in caplog.records if "XLA compilation" in r.getMessage()
        ] == []
        doc2 = costs.ledger_snapshot()
        aot2 = [e for e in doc2["entries"] if e["kind"] == "aot"]
        assert len(aot2) == 3
        assert all(e["invocations"] == 2 for e in aot2)
        assert sum(e["rows_served"] for e in aot2) == 2 * (4 + 30 + 200)

    def test_segment_entries_and_bit_identity(self, rng, tmp_path, monkeypatch):
        """A segmented KMeans fit under the ledger records a `segment`
        entry — and produces BIT-IDENTICAL centers to the same fit with
        the ledger off (same XLA program, different bookkeeping)."""
        from spark_rapids_ml_tpu.clustering import KMeans

        x = rng.normal(size=(120, 8)).astype(np.float32)
        monkeypatch.setenv("TPUML_CHECKPOINT_EVERY", "3")
        monkeypatch.setenv("TPUML_CHECKPOINT_DIR", str(tmp_path / "ck"))

        monkeypatch.delenv("TPUML_COST_LEDGER", raising=False)
        costs.reset_for_tests()
        plain = KMeans().setK(3).setSeed(5).fit(x)
        assert costs.ledger_snapshot() is None

        monkeypatch.setenv("TPUML_COST_LEDGER", "1")
        monkeypatch.setenv("TPUML_CHECKPOINT_DIR", str(tmp_path / "ck2"))
        costs.reset_for_tests()
        ledgered = KMeans().setK(3).setSeed(5).fit(x)
        doc = costs.ledger_snapshot()
        segs = [e for e in doc["entries"] if e["kind"] == "segment"]
        assert len(segs) == 1
        assert segs[0]["family"] == "kmeans.lloyd.segment"
        assert segs[0]["invocations"] >= 1
        np.testing.assert_array_equal(
            np.asarray(plain.clusterCenters()),
            np.asarray(ledgered.clusterCenters()),
        )
        # The fit report renders the per-stage flops/bytes table.
        rep = ledgered.fit_report()
        fams = [r["family"] for r in rep.cost_table()]
        assert "kmeans.lloyd.segment" in fams
        assert "costs" in rep.summary()
        assert "where the FLOPs and bytes went" in str(rep)
        costs.configure(enable=False)

    def test_fallback_entry_for_sharded_weights(self, ledger, rng):
        """Mesh-sharded weights route through the plain-jit fallback,
        which is ledgered from the LOWERING: cost analysis present,
        memory explicitly unavailable (never compiled twice)."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        if len(jax.devices()) < 2:
            pytest.skip("needs the 8-device test mesh")
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("m",))
        w = jax.device_put(
            jnp.asarray(rng.normal(size=(6, 2)).astype(np.float32)),
            NamedSharding(mesh, PartitionSpec("m", None)),
        )
        out = serve_rows(
            _kernel, rng.normal(size=(5, 6)).astype(np.float32), (w,),
            name="costs.sharded",
        )
        assert np.shape(out) == (5, 2)
        doc = costs.ledger_snapshot()
        fb = [e for e in doc["entries"] if e["kind"] == "fallback"]
        assert len(fb) == 1
        assert "memory_analysis" in fb[0]["unavailable"]
        assert fb[0]["invocations"] == 1
        assert validate_ledger(doc) == []


class TestDisabledPath:
    def test_disabled_zero_events_entries_allocations(self, no_ledger, rng):
        """Ledger off: no ledger document, no compile-classification
        counters, no events, and the WARM serve path stays within a
        tight per-call allocation budget (a ledger row or exe-key dict
        per call would blow it)."""
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32))
        x = rng.normal(size=(5, 4)).astype(np.float32)
        serve_rows(_kernel, x, (w,), name="costs.disabled")  # warm the bucket
        before_events = events.emitted_count()

        n = 200
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        for _ in range(n):
            serve_rows(_kernel, x, (w,), name="costs.disabled")
        current, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()

        assert costs.ledger_snapshot() is None
        assert counter_value("compile.new_program") == 0
        assert counter_value("compile.retrace") == 0
        assert events.emitted_count() == before_events
        # Warm host-path serve: pad scratch + device_put + slice — well
        # under 64 KiB/call; ledger bookkeeping leaking into the
        # disabled path would add per-call dict/list growth.
        assert peak - base < n * 65536


class TestRetraceWatchdog:
    def test_seeded_bucket_bypass_fires(self, ledger, rng):
        """Shapes INSIDE an existing bucket, forced through the AOT
        chokepoint: classified `retrace`, counted, and the storm warning
        fires at the TPUML_RETRACE_STORM'th strike."""
        import jax.numpy as jnp

        w = jnp.asarray(np.ones((4, 2), np.float32))

        def spec(rows):
            return jax.ShapeDtypeStruct((rows, 4), jnp.float32)

        serving._get_program(_kernel, spec(16), (w,), {}, donate=False,
                             name="costs.bypass")
        assert counter_value("compile.new_program") == 1
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for rows in (12, 11, 10):
                serving._get_program(_kernel, spec(rows), (w,), {},
                                     donate=False, name="costs.bypass")
        assert counter_value("compile.retrace") == 3
        storms = [w_ for w_ in caught
                  if issubclass(w_.category, RetraceStormWarning)]
        assert len(storms) == 1
        assert "costs.bypass" in str(storms[0].message)
        doc = costs.ledger_snapshot()
        assert doc["retraces"]["total"] == 3
        assert doc["retraces"]["families"] == {"costs.bypass": 3}

    def test_new_bucket_is_not_a_retrace(self, ledger, rng):
        """Pow-2 buckets in ANY order are the contract working: a big
        batch first and a small one later compiles the small bucket —
        that is a new program, not a retrace (the misfire a real
        fit-then-serve sequence exposed: transform 5000 rows, then 7)."""
        import jax.numpy as jnp

        w = jnp.asarray(np.ones((4, 2), np.float32))
        for rows in (8, 16, 32):  # growing pow-2 buckets
            serving._get_program(
                _kernel, jax.ShapeDtypeStruct((rows, 4), jnp.float32), (w,),
                {}, donate=False, name="costs.buckets",
            )
        for rows in (8192, 128):  # descending buckets after a big one
            serving._get_program(
                _kernel2, jax.ShapeDtypeStruct((rows, 4), jnp.float32), (w,),
                {}, donate=False, name="costs.buckets.desc",
            )
        assert counter_value("compile.retrace") == 0
        # 16, 32 for the first family; 128 (after 8192) for the second —
        # a smaller bucket following a bigger one is still just a bucket.
        assert counter_value("compile.new_bucket") == 3

    def test_eviction_refill_classified(self, ledger, rng, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("TPUML_SERVING_CACHE_SIZE", "1")
        w = jnp.asarray(np.ones((4, 2), np.float32))
        s8 = jax.ShapeDtypeStruct((8, 4), jnp.float32)
        s16 = jax.ShapeDtypeStruct((16, 4), jnp.float32)
        serving._get_program(_kernel, s8, (w,), {}, donate=False, name="c.ev")
        serving._get_program(_kernel, s16, (w,), {}, donate=False, name="c.ev")
        # s8 was evicted by s16 (capacity 1): recompiling it is a refill,
        # not a retrace.
        serving._get_program(_kernel, s8, (w,), {}, donate=False, name="c.ev")
        assert counter_value("compile.eviction_refill") == 1
        assert counter_value("compile.retrace") == 0


class TestMeasuredAdmission:
    def test_switch_to_measured_after_first_compile(self, ledger, rng):
        from spark_rapids_ml_tpu.clustering import KMeans
        from spark_rapids_ml_tpu.serving.server import ServingRuntime

        x = rng.normal(size=(64, 8)).astype(np.float32)
        model = KMeans().setK(3).setSeed(1).fit(x)
        d0 = counter_value("serving.admission.declared")
        m0 = counter_value("serving.admission.measured")
        with ServingRuntime() as rt:
            rt.register("km", model)
            rt.submit("km", x[:5]).result(timeout=30)
            d1 = counter_value("serving.admission.declared")
            m1 = counter_value("serving.admission.measured")
            rt.submit("km", x[:5]).result(timeout=30)
            d2 = counter_value("serving.admission.declared")
            m2 = counter_value("serving.admission.measured")
        # First submit of the bucket: priced from the declared spec
        # (nothing compiled yet). After its dispatch compiled the
        # program, the SAME bucket prices from measured bytes.
        assert (d1 - d0, m1 - m0) == (1, 0)
        assert (d2 - d1, m2 - m1) == (0, 1)

    def test_measured_bytes_are_temp_plus_output(self, ledger, rng):
        import jax.numpy as jnp

        w = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))
        x = rng.normal(size=(5, 6)).astype(np.float32)
        serve_rows(_kernel, x, (w,), name="costs.price")
        [entry] = [e for e in costs.ledger_snapshot()["entries"]
                   if e["family"] == "costs.price"]
        measured = costs.measured_request_bytes(
            _kernel, {}, 8, 6, np.float32, (w,)
        )
        if "memory_analysis" in entry["unavailable"]:
            assert measured is None  # pragma: no cover - non-CPU backends
        else:
            assert measured == entry["temp_bytes"] + entry["output_bytes"]

    def test_unpriced_before_compile(self, ledger):
        assert costs.measured_request_bytes(_kernel, {}, 8, 6, np.float32, ()) is None


class TestProfCLI:
    def _doc(self, flops=100.0, invocations=4):
        return {
            "version": costs.LEDGER_VERSION,
            "ts": 0.0,
            "pid": 1,
            "entries": [
                {
                    "key": "fam.a|aot|8x4:float32|abc",
                    "family": "fam.a",
                    "kind": "aot",
                    "static": "",
                    "spec": "8x4:float32",
                    "rows": 8,
                    "classification": "new_program",
                    "flops": flops,
                    "transcendentals": 0.0,
                    "bytes_accessed": 10.0 * flops,
                    "argument_bytes": 128,
                    "output_bytes": 64,
                    "temp_bytes": 32,
                    "alias_bytes": 0,
                    "generated_code_bytes": 0,
                    "unavailable": [],
                    "compiles": 1,
                    "compile_seconds": 0.1,
                    "invocations": invocations,
                    "wall_seconds": 0.5,
                    "rows_served": invocations * 5,
                }
            ],
            "watermarks": {"0": {"in_use": 100, "peak_bytes": 200}},
            "retraces": {"total": 0, "families": {}},
            "peaks": {"flops_per_sec": None, "bytes_per_sec": None},
        }

    def test_diff_gates_seeded_regression(self, tmp_path, capsys):
        old, new = tmp_path / "old.json", tmp_path / "new.json"
        old.write_text(json.dumps(self._doc(flops=100.0)))
        new.write_text(json.dumps(self._doc(flops=200.0)))  # seeded 2x
        assert tpuml_prof.main(
            ["--diff", str(old), str(new), "--max-regress", "50"]
        ) == 1
        assert "REGRESSION" in capsys.readouterr().err
        # Identical ledgers pass the same gate.
        assert tpuml_prof.main(
            ["--diff", str(old), str(old), "--max-regress", "50"]
        ) == 0

    def test_diff_new_family_is_note_not_failure(self, tmp_path):
        old, new = tmp_path / "old.json", tmp_path / "new.json"
        doc_new = self._doc()
        doc_new["entries"][0]["family"] = "fam.b"
        doc_new["entries"][0]["key"] = "fam.b|aot|8x4:float32|abc"
        old.write_text(json.dumps(self._doc()))
        new.write_text(json.dumps(doc_new))
        assert tpuml_prof.main(
            ["--diff", str(old), str(new), "--max-regress", "10"]
        ) == 0

    def test_validate_gates_malformed(self, tmp_path, capsys):
        bad = self._doc()
        del bad["entries"][0]["flops"]
        p = tmp_path / "bad.json"
        p.write_text(json.dumps(bad))
        assert tpuml_prof.main([str(p), "--validate"]) == 1
        assert "INVALID" in capsys.readouterr().err

    def test_dump_renders(self, tmp_path, capsys):
        p = tmp_path / "led.json"
        p.write_text(json.dumps(self._doc()))
        assert tpuml_prof.main([str(p), "--sort", "flops"]) == 0
        out = capsys.readouterr().out
        assert "fam.a" in out and "per-family rollup" in out
        assert "peak 200 bytes" in out

    def test_missing_unavailable_marker_rejected(self):
        doc = self._doc()
        doc["entries"][0]["flops"] = None  # no marker either -> invalid
        assert any(
            "unavailable marker" in p for p in validate_ledger(doc)
        )


class TestGangMerge:
    def test_shards_merge_sum_counters_max_watermarks(self, tmp_path):
        a = TestProfCLI()._doc(invocations=3)
        b = TestProfCLI()._doc(invocations=5)
        b["watermarks"]["0"]["peak_bytes"] = 999
        b["retraces"] = {"total": 2, "families": {"fam.a": 2}}
        merged = merge_ledger_docs([a, b])
        [entry] = merged["entries"]
        assert entry["invocations"] == 8
        assert entry["compiles"] == 2
        assert entry["flops"] == 100.0  # analyzed cost: agree, not sum
        assert merged["watermarks"]["0"]["peak_bytes"] == 999
        assert merged["watermarks"]["0"]["in_use"] == 100
        assert merged["retraces"]["total"] == 2
        # And through the CLI's directory loader.
        (tmp_path / "costs-1.json").write_text(json.dumps(a))
        (tmp_path / "costs-2.json").write_text(json.dumps(b))
        doc, problems = tpuml_prof.load_ledger(str(tmp_path))
        assert problems == []
        assert doc["merged_from"] == 2
        assert doc["entries"][0]["invocations"] == 8

    def test_telemetry_shard_and_manifest(self, ledger, rng, tmp_path,
                                          monkeypatch):
        """flush_telemetry writes costs-<pid>.json beside the event
        shard and names it in the manifest; gang_report merges it."""
        import jax.numpy as jnp

        monkeypatch.setenv("TPUML_TELEMETRY_DIR", str(tmp_path))
        events.configure()
        try:
            w = jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32))
            serve_rows(_kernel, rng.normal(size=(3, 4)).astype(np.float32),
                       (w,), name="costs.gang")
            manifest_path = events.flush_telemetry()
            assert manifest_path is not None
            manifest = json.loads(open(manifest_path).read())
            assert manifest["costs"] == f"costs-{os.getpid()}.json"
            shard = json.load(open(tmp_path / manifest["costs"]))
            assert validate_ledger(shard) == []

            from spark_rapids_ml_tpu.observability.report import gang_report

            rep = gang_report(str(tmp_path))
            assert rep["costs"]["members"] == 1
            fams = [e["family"] for e in rep["costs"]["merged"]["entries"]]
            assert "costs.gang" in fams
        finally:
            monkeypatch.delenv("TPUML_TELEMETRY_DIR")
            events.configure()


class TestHbmSampler:
    def test_sampler_gauges_watermarks_and_attribution(self, ledger):
        seq = iter([
            {"0": {"bytes_in_use": 100, "peak_bytes_in_use": 100}},
            {"0": {"bytes_in_use": 300, "peak_bytes_in_use": 400}},
            {"0": {"bytes_in_use": 200, "peak_bytes_in_use": 650}},
        ])
        smp = HbmSampler(period_ms=1000.0, stats_fn=lambda: next(seq))
        samples = [smp.sample_once() for _ in range(3)]
        assert all(s is not None for s in samples)
        from spark_rapids_ml_tpu.observability.metrics import default_registry

        assert default_registry.gauge("device.memory.peak_bytes").value(
            device="0"
        ) == 650
        doc = costs.ledger_snapshot()
        assert doc["watermarks"]["0"] == {"in_use": 300, "peak_bytes": 650}

        # Growth between samples attributes to the deepest covering span.
        t0, t1, t2 = (s[0] for s in samples)
        spans = [
            {"name": "fit", "start": t0 - 1, "end": t2 + 1, "depth": 0},
            {"name": "solver segment", "start": (t0 + t1) / 2,
             "end": (t1 + t2) / 2, "depth": 1},
        ]
        hbm = attribute_hbm_growth(samples, spans)
        assert hbm["delta"] == 550
        assert hbm["by_span"]["solver segment"] == 300
        assert hbm["by_span"]["fit"] == 250

    def test_sampler_knob_starts_thread(self, monkeypatch):
        monkeypatch.setenv("TPUML_COST_LEDGER", "1")
        monkeypatch.setenv("TPUML_HBM_SAMPLE_EVERY_MS", "5")
        costs.reset_for_tests()
        try:
            smp = costs.sampler()
            assert smp is not None and smp.alive()
        finally:
            # Drop BOTH knobs before re-reading them: resetting with
            # TPUML_COST_LEDGER still in the env would re-arm the ledger
            # and leak it into every later test module.
            monkeypatch.delenv("TPUML_HBM_SAMPLE_EVERY_MS")
            monkeypatch.delenv("TPUML_COST_LEDGER")
            costs.reset_for_tests()
            assert costs.sampler() is None
            assert costs.active() is None


class TestTopHotSpot:
    """The roofline table flags the costliest residual family — every
    fit report answers "what pays the most to optimize next"."""

    def _report(self, costs_rows):
        from spark_rapids_ml_tpu.observability.report import RunReport

        return RunReport(
            run_id="r1", kind="fit", label="t", wall_seconds=1.0,
            spans=[], counters={}, device_memory={}, ok=True,
            costs=costs_rows,
        )

    def test_flags_largest_wall_share(self):
        rep = self._report([
            {"family": "a.small", "kind": "aot", "invocations": 1,
             "wall_seconds": 0.1},
            {"family": "b.big", "kind": "segment", "invocations": 4,
             "wall_seconds": 0.3},
        ])
        hot = rep.top_hot_spot()
        assert hot["family"] == "b.big"
        assert hot["wall_share"] == pytest.approx(0.75)
        rendered = str(rep)
        assert "<< hot spot (75% of wall)" in rendered
        # Only the hot row carries the marker.
        assert rendered.count("<< hot spot") == 1

    def test_no_costs_no_flag(self):
        rep = self._report([])
        assert rep.top_hot_spot() is None
        assert "hot spot" not in str(rep)

    def test_zero_wall_rows_ignored(self):
        rep = self._report([
            {"family": "compiled.never.ran", "kind": "aot",
             "invocations": 0, "wall_seconds": 0.0},
        ])
        assert rep.top_hot_spot() is None
