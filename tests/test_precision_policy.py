"""Mixed-precision MXU policy layer contracts (ISSUE 17).

The load-bearing promises, each pinned here:

  - the default policy is TODAY'S numerics bit-for-bit: ``f32`` is the
    same primitive sequence as ``lax.Precision.HIGHEST``, and every op's
    default-precision output is unchanged;
  - ``bf16x3`` (3-pass compensated GEMM, arXiv:2112.09017) stays within
    its documented GEMM-level bound (``REL_TOL``) on every hot-path op
    family, and plain ``bf16`` within its own, on this backend — the
    hi/lo splits are bf16-representable so the parity bars are
    backend-portable, which is what makes them CPU-CI-testable;
  - the packed KMeans kernel's unused-slot sentinel and the compensated
    split are bf16-safe: finite sentinels survive the hi/lo split
    (``split_hi_lo(inf)`` manufactures NaN — the hazard the finite
    ``_UNUSED_SCORE`` guards against), pinned at config17's exact
    geometry;
  - policy layering is explicit > per-family env > global env >
    committed autotune decision > family default, and the autotuner is
    the ONLY path that can change numerics without an operator setting
    a knob — so with ``TPUML_AUTOTUNE`` off, resolution is pure and
    allocation-light, adds zero compiles, and fits are bit-identical;
  - the autotuner gate NEVER commits a parity-violating mode: a seeded
    fast-but-wrong GEMM is recorded ``rejected`` with reason
    ``parity`` and the incumbent stands;
  - segmented/checkpoint-resumable fits under a fixed non-default
    policy remain bit-identical to the monolithic fit.
"""

import logging
import tracemalloc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.observability import autotune, costs
from spark_rapids_ml_tpu.ops import precision as prec
from spark_rapids_ml_tpu.ops.precision import (
    FAMILIES,
    PASSES,
    REL_TOL,
    as_dot,
    active_mode,
    active_modes,
    make_dot,
    pallas_precision,
    pdot,
    register_test_mode,
    resolve_policy,
    roofline_peak_scale,
    split_hi_lo,
    tune_precision,
    validate_mode,
)
from spark_rapids_ml_tpu.utils.tracing import clear_counters, counter_value


@pytest.fixture(autouse=True)
def _clean_registry():
    prec.reset_for_tests()
    yield
    prec.reset_for_tests()


@pytest.fixture
def tuner(monkeypatch, tmp_path):
    """Armed tuner over a tmp-file store (mirrors test_autotune.py)."""
    monkeypatch.setenv("TPUML_AUTOTUNE", "on")
    monkeypatch.setenv("TPUML_TUNE_STORE", str(tmp_path / "tune.json"))
    clear_counters("autotune.")
    costs.reset_for_tests()
    autotune.reset_for_tests()
    t = autotune.active()
    assert t is not None
    yield t
    autotune.configure(enable=False)
    costs.configure(enable=False)


@pytest.fixture
def off(monkeypatch):
    monkeypatch.delenv("TPUML_AUTOTUNE", raising=False)
    monkeypatch.delenv("TPUML_PRECISION", raising=False)
    clear_counters("autotune.")
    costs.reset_for_tests()
    autotune.reset_for_tests()
    assert autotune.active() is None
    yield


def _rel_err(got, ref):
    ref = np.asarray(ref, dtype=np.float64)
    got = np.asarray(got, dtype=np.float64)
    scale = np.max(np.abs(ref)) or 1.0
    return float(np.max(np.abs(got - ref))) / scale


# ---------------------------------------------------------------------------
# vocabulary and the dot chokepoint
# ---------------------------------------------------------------------------


class TestVocabulary:
    def test_modes_and_legacy_validate(self):
        for m in ("f32", "bf16x3", "bf16", "highest", "high", "default"):
            assert validate_mode(m) == m

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="precision mode"):
            validate_mode("fp8")

    def test_registered_test_mode_extends_vocabulary(self):
        register_test_mode("unittest_mode", jnp.matmul, rel_tol=1.0)
        assert validate_mode("unittest_mode") == "unittest_mode"
        prec.clear_test_modes()
        with pytest.raises(ValueError):
            validate_mode("unittest_mode")

    def test_pass_counts(self):
        # The roofline scaling hangs off these: f32 = 6 bf16 passes on
        # the MXU, compensated = 3, plain bf16 = 1.
        assert PASSES["f32"] == PASSES["highest"] == 6
        assert PASSES["bf16x3"] == PASSES["high"] == 3
        assert PASSES["bf16"] == PASSES["default"] == 1

    def test_pallas_mapping(self):
        # The pallas kernels' "high" emulation IS the 3-pass split.
        assert pallas_precision("f32") == "highest"
        assert pallas_precision("bf16x3") == "high"
        assert pallas_precision("bf16") == "default"
        assert pallas_precision("highest") == "highest"  # legacy passthrough

    def test_as_dot_coerces_every_historical_spelling(self, rng):
        a = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(5, 7)).astype(np.float32))
        ref = jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
        for spelling in ("highest", "f32", jax.lax.Precision.HIGHEST,
                         make_dot("f32")):
            np.testing.assert_array_equal(
                np.asarray(as_dot(spelling)(a, b)), np.asarray(ref)
            )


class TestSplitHiLo:
    def test_exact_decomposition(self, rng):
        a = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 100)
        hi, lo = split_hi_lo(a)
        np.testing.assert_array_equal(np.asarray(hi + lo), np.asarray(a))
        # hi is exactly the bf16 rounding (round-trip identity) and lo is
        # the residual carrying the next mantissa bits — at most half a
        # bf16 ulp of each element (<= 2^-8 |a| elementwise).
        np.testing.assert_array_equal(
            np.asarray(hi), np.asarray(hi.astype(jnp.bfloat16).astype(jnp.float32))
        )
        assert bool(jnp.all(jnp.abs(lo) <= 2.0 ** -8 * jnp.abs(a)))

    def test_inf_manufactures_nan(self):
        # The documented hazard: hi(inf)=inf, lo = inf - inf = NaN. This
        # is WHY compensated-path sentinels must stay finite.
        _, lo = split_hi_lo(jnp.asarray([jnp.inf], dtype=jnp.float32))
        assert np.isnan(np.asarray(lo))[0]

    def test_sentinel_and_clamp_constants_are_bf16_exact(self):
        from spark_rapids_ml_tpu.ops.pallas.kmeans import _UNUSED_SCORE

        for c in (_UNUSED_SCORE, 4.0):
            v = jnp.asarray(c, dtype=jnp.float32)
            assert np.isfinite(float(v))
            assert float(v.astype(jnp.bfloat16).astype(jnp.float32)) == float(v)
            hi, lo = split_hi_lo(v)
            assert float(hi) == float(v) and float(lo) == 0.0


# ---------------------------------------------------------------------------
# parity: per-family GEMM-level bounds, f32 bit identity
# ---------------------------------------------------------------------------


class TestDotParity:
    def test_f32_is_highest_bit_for_bit(self, rng):
        a = jnp.asarray(rng.normal(size=(96, 48)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(48, 33)).astype(np.float32))
        ref = jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
        np.testing.assert_array_equal(
            np.asarray(pdot(a, b, "f32")), np.asarray(ref)
        )

    @pytest.mark.parametrize("mode", ["bf16x3", "bf16"])
    def test_raw_gemm_within_documented_bound(self, rng, mode):
        a = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
        ref = pdot(a, b, "f32")
        assert _rel_err(pdot(a, b, mode), ref) <= REL_TOL[mode]

    @pytest.mark.parametrize("mode", ["bf16x3", "bf16"])
    def test_covariance_family(self, rng, mode):
        from spark_rapids_ml_tpu.ops.covariance import centered_gram

        x = jnp.asarray(rng.normal(size=(400, 32)).astype(np.float32))
        mean = jnp.mean(x, axis=0)
        ref = centered_gram(x, mean, precision="f32")
        assert _rel_err(centered_gram(x, mean, precision=mode), ref) <= REL_TOL[mode]

    @pytest.mark.parametrize("mode", ["bf16x3", "bf16"])
    def test_linear_family(self, rng, mode):
        from spark_rapids_ml_tpu.ops.linear import normal_eq_stats, predict_linear

        x = jnp.asarray(rng.normal(size=(300, 16)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(300,)).astype(np.float32))
        ref = normal_eq_stats(x, y, None, precision="f32")
        got = normal_eq_stats(x, y, None, precision=mode)
        assert _rel_err(got[0], ref[0]) <= REL_TOL[mode]  # xtx
        assert _rel_err(got[1], ref[1]) <= REL_TOL[mode]  # xty
        coef = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
        pref = predict_linear(x, coef, 0.5, precision="f32")
        assert _rel_err(predict_linear(x, coef, 0.5, precision=mode), pref) <= REL_TOL[mode]

    @pytest.mark.parametrize("mode", ["bf16x3", "bf16"])
    def test_logistic_family_forward(self, rng, mode):
        from spark_rapids_ml_tpu.ops.logistic import predict_logistic

        x = jnp.asarray(rng.normal(size=(200, 24)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(24, 4)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(4,)).astype(np.float32))
        _, _, ref = predict_logistic(x, w, b, 4, precision="f32")
        _, _, raw = predict_logistic(x, w, b, 4, precision=mode)
        assert _rel_err(raw, ref) <= REL_TOL[mode]

    @pytest.mark.parametrize("mode", ["bf16x3", "bf16"])
    def test_kmeans_family_assignment(self, rng, mode):
        from spark_rapids_ml_tpu.ops.kmeans import assign_clusters

        # Well-separated clusters: the assignment itself must be
        # mode-invariant, and the distances within the GEMM bound.
        k, d = 8, 16
        centers = jnp.asarray((rng.normal(size=(k, d)) * 10).astype(np.float32))
        x = jnp.asarray(
            (np.repeat(np.asarray(centers), 50, axis=0)
             + rng.normal(size=(k * 50, d)).astype(np.float32) * 0.1)
        )
        lref, dref = assign_clusters(x, centers, precision="f32")
        lgot, dgot = assign_clusters(x, centers, precision=mode)
        np.testing.assert_array_equal(np.asarray(lgot), np.asarray(lref))
        # Distances go through x2 - 2 x·c + c2 with cancellation; allow
        # the bound on the GEMM term (scale = max |x·c|).
        scale = float(np.max(np.abs(np.asarray(x) @ np.asarray(centers).T)))
        assert float(np.max(np.abs(np.asarray(dgot - dref)))) / scale <= 2 * REL_TOL[mode]

    def test_pca_family_randomized_sketch(self, rng):
        from spark_rapids_ml_tpu.ops.randomized import randomized_pca

        x = jnp.asarray(
            (rng.normal(size=(200, 24)) * np.linspace(1, 4, 24)).astype(np.float32)
        )
        key = jax.random.PRNGKey(0)
        ref = randomized_pca(x, 3, key, precision="f32")
        got = randomized_pca(x, 3, key, precision="bf16x3")
        # Subspace agreement (eigvectors sign-free); the power iterations
        # amplify GEMM error, so the bar is looser than the raw bound.
        for a, b in zip(np.asarray(got[0]).T, np.asarray(ref[0]).T):
            assert abs(float(np.dot(a, b))) > 1 - 1e-4


class TestPackedKernelConfig17:
    """Satellite 2: the 128-lane packed kernel at config17's exact shape
    pair (d=16, k=16) must stay NaN-free and reference-exact under the
    compensated mapping — the finite ``_UNUSED_SCORE`` sentinel is what
    makes the bf16 hi/lo split safe in the unused lane-group slots."""

    @pytest.mark.parametrize("mode", ["f32", "bf16x3", "bf16"])
    def test_packed_stats_finite_and_match_unpacked(self, mode):
        from spark_rapids_ml_tpu.ops.pallas.kmeans import (
            assign_stats_fused,
            assign_stats_packed,
            pad_transposed,
        )

        n, d, k = 777, 16, 16  # config17 geometry (D17=16, K17=16)
        rng = np.random.default_rng(17)
        x = jnp.asarray(
            (rng.normal(size=(n, d)) + rng.integers(0, k, n)[:, None]).astype(
                np.float32
            )
        )
        centers = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        xt, _ = pad_transposed(x, block_n=256)
        cpad = jnp.pad(centers, ((0, 0), (0, xt.shape[0] - d)))
        sums, counts, cost, c2 = assign_stats_packed(
            xt, cpad, block_n=256, precision=mode, interpret=True
        )
        # The finite _UNUSED_SCORE sentinel keeps every output finite
        # even when the hi/lo split runs over the unused lane-group
        # slots — an inf sentinel would manufacture NaN there.
        for arr in (sums, counts, cost, c2):
            assert np.all(np.isfinite(np.asarray(arr)))
        # Unpacked fused reference at the SAME mode: identical
        # assignments, accumulation-order epsilon on the sums.
        sf, cf, costf, c2f = assign_stats_fused(
            xt, cpad, block_n=256, precision=mode, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(counts), np.asarray(cf))
        np.testing.assert_allclose(sums, sf, rtol=1e-5, atol=1e-4)
        assert float(cost) == pytest.approx(float(costf), rel=1e-5)
        np.testing.assert_allclose(c2, c2f, rtol=1e-6)

    def test_packed_bf16x3_tracks_f32_stats(self):
        """Cross-mode sanity at the same geometry: the compensated stats
        stay close to the f32 stats (assignment flips aside, the bound
        is the GEMM tolerance amortized over the accumulation)."""
        from spark_rapids_ml_tpu.ops.pallas.kmeans import (
            assign_stats_packed,
            pad_transposed,
        )

        n, d, k = 777, 16, 16
        rng = np.random.default_rng(18)
        centers = jnp.asarray((rng.normal(size=(k, d)) * 8).astype(np.float32))
        x = jnp.asarray(
            np.repeat(np.asarray(centers), n // k + 1, axis=0)[:n]
            + rng.normal(size=(n, d)).astype(np.float32) * 0.05
        )
        xt, _ = pad_transposed(x, block_n=256)
        cpad = jnp.pad(centers, ((0, 0), (0, xt.shape[0] - d)))
        ref = assign_stats_packed(xt, cpad, block_n=256, precision="f32",
                                  interpret=True)
        got = assign_stats_packed(xt, cpad, block_n=256, precision="bf16x3",
                                  interpret=True)
        np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(ref[1]))
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-3, atol=1e-2)


# ---------------------------------------------------------------------------
# policy resolution layering
# ---------------------------------------------------------------------------


class TestResolvePolicy:
    def test_default_when_nothing_set(self, off):
        assert resolve_policy("kmeans") == "highest"
        assert resolve_policy("covariance", default="auto") == "auto"

    def test_explicit_beats_env(self, off, monkeypatch):
        monkeypatch.setenv("TPUML_PRECISION_KMEANS", "bf16")
        assert resolve_policy("kmeans", "f32") == "f32"

    def test_family_env_beats_global_env(self, off, monkeypatch):
        monkeypatch.setenv("TPUML_PRECISION", "bf16")
        monkeypatch.setenv("TPUML_PRECISION_KMEANS", "bf16x3")
        assert resolve_policy("kmeans") == "bf16x3"
        assert resolve_policy("logistic") == "bf16"

    def test_dd_passes_through_untouched(self, off, monkeypatch):
        monkeypatch.setenv("TPUML_PRECISION", "bf16")
        assert resolve_policy("linear", "dd") == "dd"

    def test_invalid_env_value_raises(self, off, monkeypatch):
        from spark_rapids_ml_tpu.utils.envknobs import EnvKnobError

        monkeypatch.setenv("TPUML_PRECISION", "fp8")
        with pytest.raises(EnvKnobError):
            resolve_policy("kmeans")

    def test_unknown_family_rejected(self, off):
        with pytest.raises(ValueError, match="family"):
            resolve_policy("umap")

    def test_resolution_feeds_roofline_registry(self, off, monkeypatch):
        monkeypatch.setenv("TPUML_PRECISION_KMEANS", "bf16x3")
        resolve_policy("kmeans")
        assert active_mode("kmeans") == "bf16x3"
        # Ledger program families carry dotted suffixes.
        assert active_mode("kmeans.lloyd") == "bf16x3"
        assert roofline_peak_scale("kmeans.lloyd") == 2.0
        assert roofline_peak_scale("never.resolved") == 1.0
        resolve_policy("serving", "bf16")
        assert roofline_peak_scale("serving") == 6.0
        assert active_modes()["serving"] == "bf16"
        # Forward-pass ledger families run under the SERVING policy,
        # not the fit family their prefix suggests.
        assert active_mode("kmeans.predict") == "bf16"
        assert active_mode("pca.transform") == "bf16"
        assert roofline_peak_scale("kmeans.predict") == 6.0

    def test_families_registry_is_closed(self):
        assert set(FAMILIES) == {
            "covariance", "pca", "kmeans", "logistic", "linear", "serving"
        }


# ---------------------------------------------------------------------------
# off mode: bit identity, zero compiles, zero allocation
# ---------------------------------------------------------------------------


class TestOffBitIdentity:
    def test_kmeans_default_fit_is_f32_fit(self, off, rng):
        from spark_rapids_ml_tpu.clustering import KMeans

        x = (rng.normal(size=(240, 5)) + rng.integers(0, 3, 240)[:, None]).astype(
            np.float32
        )
        m_default = KMeans().setK(3).setSeed(7).fit(x)
        m_f32 = KMeans().setK(3).setSeed(7).setPrecision("f32").fit(x)
        np.testing.assert_array_equal(
            m_default.clusterCenters(), m_f32.clusterCenters()
        )
        assert float(m_default.trainingCost) == float(m_f32.trainingCost)

    def test_resolution_adds_zero_compiles_and_stays_allocation_light(
        self, off, rng, caplog
    ):
        a = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32))

        @jax.jit
        def kern(a, b):
            return make_dot(resolve_policy("serving"))(a, b)

        first = np.asarray(kern(a, b))  # compile outside the window
        jax.config.update("jax_log_compiles", True)
        try:
            with caplog.at_level(logging.WARNING, logger="jax._src.dispatch"):
                second = np.asarray(kern(a, b))
        finally:
            jax.config.update("jax_log_compiles", False)
        assert [
            r for r in caplog.records if "XLA compilation" in r.getMessage()
        ] == []
        np.testing.assert_array_equal(first, second)
        assert counter_value("autotune.commit") == 0
        # Off-mode resolution itself is allocation-light: no tuner, no
        # probes, no store IO.
        n = 200
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        for _ in range(n):
            resolve_policy("serving")
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert peak - base < n * 4096


# ---------------------------------------------------------------------------
# the autotuner gate
# ---------------------------------------------------------------------------


class TestAutotunerGate:
    def test_off_tuner_never_probes(self, off):
        assert tune_precision("kmeans") is None

    def test_cpu_probe_keeps_f32_and_memoizes(self, tuner, monkeypatch):
        """On CPU the compensated mode is measurably SLOWER than native
        f32, so the gate must keep the f32 incumbent — this is the
        mechanism that makes default-mode CI runs bit-identical. The
        decision memoizes: the second resolution never re-probes."""
        mode = tune_precision("kmeans", tuner=tuner)
        assert mode == "f32"
        decision = tuner.store.get("precision_mode", "kmeans")
        assert decision["value"] == "f32"

        def boom(*a, **k):  # pragma: no cover - must not run
            raise AssertionError("re-probed a memoized decision")

        monkeypatch.setattr(prec, "_time_probe", boom)
        assert tune_precision("kmeans", tuner=tuner) == "f32"

    def test_gate_rejects_seeded_parity_violating_mode(self, tuner):
        """A fast-but-wrong GEMM (plain bf16 math sold with a 1e-7
        parity bar) must be recorded rejected with reason ``parity`` and
        never displace the incumbent."""
        register_test_mode(
            "seeded_wrong_17",
            lambda a, b: jnp.matmul(
                a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            ),
            rel_tol=1e-7,
        )
        before = counter_value("autotune.revert")
        mode = tune_precision(
            "covariance", tuner=tuner, candidates=("seeded_wrong_17",)
        )
        assert mode == "f32"  # incumbent stands
        decision = tuner.store.get("precision_mode", "covariance")
        assert decision["value"] == "f32"
        rejected = decision.get("rejected", [])
        assert any(
            r["value"] == "seeded_wrong_17" and r["reason"] == "parity"
            for r in rejected
        )
        assert counter_value("autotune.revert") > before

    def test_record_trial_ok_false_contract(self, tuner):
        """ok=False records the rejection (reason preserved), bumps the
        revert counter, and returns False — even with an empty store."""
        before = counter_value("autotune.revert")
        committed = tuner.record_trial(
            "precision_mode", "unit", "bf16", 1e-9, ok=False, reason="parity"
        )
        assert committed is False
        entry = tuner.store.get("precision_mode", "unit")
        assert entry["value"] is None  # placeholder, nothing committed
        assert entry["rejected"][0]["reason"] == "parity"
        assert counter_value("autotune.revert") == before + 1

    def test_resolve_policy_consults_committed_decision(self, tuner):
        """With the tuner armed and no explicit/env setting, resolution
        goes through the gate and lands on the committed mode."""
        assert resolve_policy("logistic") == "f32"
        assert tuner.store.get("precision_mode", "logistic")["value"] == "f32"


# ---------------------------------------------------------------------------
# segmented / resumable bit identity under a fixed policy
# ---------------------------------------------------------------------------


class TestSegmentedBitIdentity:
    def test_lloyd_resumable_matches_monolithic_under_bf16x3(self, off, tmp_path, rng):
        from spark_rapids_ml_tpu.ops.kmeans import lloyd, lloyd_resumable, random_init
        from spark_rapids_ml_tpu.robustness.checkpoint import FitCheckpointer

        x = jnp.asarray(
            (rng.normal(size=(300, 6)) + rng.integers(0, 4, 300)[:, None]).astype(
                np.float32
            )
        )
        mask = jnp.ones(300, dtype=jnp.float32)
        init = random_init(x, mask, jax.random.PRNGKey(0), 4)
        c_ref, cost_ref, it_ref = lloyd(
            x, mask, init, max_iter=8, precision="bf16x3"
        )
        ck = FitCheckpointer(
            str(tmp_path / "run"), uid="u", param_hash="p", data_fp="d", every=2
        )
        c_seg, cost_seg, it_seg = lloyd_resumable(
            x, mask, init, ck, max_iter=8, precision="bf16x3"
        )
        np.testing.assert_array_equal(np.asarray(c_seg), np.asarray(c_ref))
        assert float(cost_seg) == float(cost_ref)
        assert int(it_seg) == int(it_ref)
