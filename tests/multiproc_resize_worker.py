"""Worker for the gang-RESIZE acceptance test (elastic gang membership).

Launched twice by tests/test_gang_fit.py::TestGangResize with the
jax.distributed coordinates, ``TPUML_GANG_FIT=1`` (the env twin — an
explicit ``setDeployMode`` would change the param hash and orphan the
checkpoint stream), the SHARED ``TPUML_CHECKPOINT_*`` knobs, and
``TPUML_FAULTS=checkpoint.segment=1@2`` armed at import: each member
feeds its slice of a deterministic dyadic dataset into one segmented
KMeans gang fit and DIES at the third segment boundary, after the
step-6 snapshot has landed in the shared dir. The launcher then resumes
the same fit single-process over ALL rows — the dataset is dyadic
(integers/4) so every cross-member sum is exact and the resumed model
must match a cold single-process refit bit for bit.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:  # newer jax: gloo is the default, the knob may be gone
    pass
jax.config.update("jax_enable_x64", True)

from spark_rapids_ml_tpu.utils.envknobs import env_int


def main() -> None:
    n_proc = env_int("TPUML_NUM_PROCESSES")
    pid = env_int("TPUML_PROCESS_ID")

    # The SAME dataset/estimator the launcher's cold and resumed refits
    # use — the checkpoint identity (uid + params + data fingerprint)
    # must line up across the member-count change.
    rng = np.random.default_rng(7)
    n, d = 160, 5
    x = (rng.integers(-64, 64, size=(n, d)) / 4.0).astype(np.float64)
    bounds = np.linspace(0, n, n_proc + 1).astype(int)
    local = x[bounds[pid] : bounds[pid + 1]]
    init = x[:4].copy()

    from spark_rapids_ml_tpu.models.kmeans import KMeans

    (
        KMeans(uid="resize-gang")
        .setK(4)
        .setMaxIter(10)
        .setTol(0.0)
        .setSeed(1)
        .setInitialModel(init)
        .fit(local)
    )
    # The seeded fault must kill the fit mid-solve; completing is a
    # test bug (e.g. the solver converged before the third boundary).
    print(f"UNEXPECTED_COMPLETE {pid}")


if __name__ == "__main__":
    main()
