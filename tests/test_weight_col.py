"""weightCol (per-row sample weights) — oracle: sklearn sample_weight and
the duplicate-row equivalence (weight w == the row repeated w times)."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.classification import LogisticRegression, RandomForestClassifier
from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.regression import LinearRegression, RandomForestRegressor


def _wdf(x, y=None, w=None):
    cols = {"features": list(x)}
    if y is not None:
        cols["label"] = list(y)
    if w is not None:
        cols["weight"] = list(w)
    return DataFrame(cols)


class TestLinearWeights:
    def test_matches_sklearn_sample_weight(self, rng):
        linear_model = pytest.importorskip("sklearn.linear_model")
        x = rng.normal(size=(200, 5))
        y = x @ np.arange(1.0, 6.0) + 0.3 * rng.normal(size=200)
        w = rng.uniform(0.1, 3.0, size=200)
        model = LinearRegression().setWeightCol("weight").fit(_wdf(x, y, w))
        skl = linear_model.LinearRegression().fit(x, y, sample_weight=w)
        np.testing.assert_allclose(model.coefficients, skl.coef_, atol=1e-8)
        assert abs(model.intercept - skl.intercept_) < 1e-8

    def test_duplicate_row_equivalence(self, rng):
        x = rng.normal(size=(50, 3))
        y = x @ np.array([1.0, -2.0, 0.5])
        w = np.ones(50)
        w[:10] = 3.0  # first ten rows triple-weighted
        m_w = LinearRegression().setRegParam(0.1).setWeightCol("weight").fit(_wdf(x, y, w))
        x_dup = np.concatenate([x, x[:10], x[:10]])
        y_dup = np.concatenate([y, y[:10], y[:10]])
        m_dup = LinearRegression().setRegParam(0.1).fit((x_dup, y_dup))
        np.testing.assert_allclose(m_w.coefficients, m_dup.coefficients, atol=1e-6)

    def test_weight_validation(self, rng):
        x = rng.normal(size=(20, 3))
        y = x[:, 0]
        with pytest.raises(ValueError, match="non-negative"):
            LinearRegression().setWeightCol("weight").fit(
                _wdf(x, y, -np.ones(20))
            )
        with pytest.raises(TypeError, match="named columns"):
            LinearRegression().setWeightCol("weight").fit((x, y))
        # No weightCol set: tuples keep working.
        LinearRegression().fit((x, y))


class TestLogisticWeights:
    def test_matches_sklearn_sample_weight(self, rng):
        linear_model = pytest.importorskip("sklearn.linear_model")
        x = rng.normal(size=(300, 4))
        y = (x[:, 0] - x[:, 1] > 0).astype(float)
        w = rng.uniform(0.2, 2.0, size=300)
        n, reg = len(y), 0.1
        model = (
            LogisticRegression()
            .setRegParam(reg)
            .setStandardization(False)
            .setWeightCol("weight")
            .setTol(1e-12)
            .fit(_wdf(x, y, w))
        )
        # sklearn C maps through the WEIGHT SUM (our 1/n is 1/sum(w)).
        skl = linear_model.LogisticRegression(
            C=1.0 / (reg * w.sum()), tol=1e-12, max_iter=10_000
        ).fit(x, y, sample_weight=w)
        np.testing.assert_allclose(
            model.coefficients, skl.coef_.ravel(), atol=1e-4
        )

    def test_standardized_duplicate_equivalence(self, rng):
        # With standardization ON (the default) and L2, integer weights must
        # equal row duplication — this exercises the weighted feature
        # moments (a squared mask in the variance would break it).
        x = rng.normal(size=(120, 3)) * np.array([1.0, 10.0, 0.1])
        y = (x[:, 0] + 0.1 * x[:, 1] > 0).astype(float)
        w = np.ones(120)
        w[:30] = 2.0
        m_w = (
            LogisticRegression()
            .setRegParam(0.2)
            .setWeightCol("weight")
            .setTol(1e-12)
            .fit(_wdf(x, y, w))
        )
        x_dup = np.concatenate([x, x[:30]])
        y_dup = np.concatenate([y, y[:30]])
        m_dup = LogisticRegression().setRegParam(0.2).setTol(1e-12).fit((x_dup, y_dup))
        np.testing.assert_allclose(m_w.coefficients, m_dup.coefficients, atol=1e-6)

    def test_weights_shift_boundary(self, rng):
        # Upweighting one class pushes the decision boundary toward recall
        # on that class.
        x = rng.normal(size=(400, 2))
        y = (x[:, 0] > 0.3).astype(float)
        w_pos = np.where(y == 1, 10.0, 1.0)
        m_plain = LogisticRegression().fit((x, y))
        m_wpos = LogisticRegression().setWeightCol("weight").fit(_wdf(x, y, w_pos))
        recall_plain = np.mean(m_plain.predict(x)[y == 1] == 1)
        recall_w = np.mean(m_wpos.predict(x)[y == 1] == 1)
        assert recall_w >= recall_plain


class TestKMeansWeights:
    def test_weights_pull_centers(self, rng):
        # Two blobs; massively upweighting one point of blob A drags its
        # center toward that point.
        x = np.concatenate([rng.normal(size=(50, 2)), rng.normal(size=(50, 2)) + 8])
        w = np.ones(100)
        x[0] = [-5.0, -5.0]
        w[0] = 50.0
        model = KMeans().setK(2).setSeed(0).setWeightCol("weight").fit(_wdf(x, w=w))
        centers = model.clusterCenters()
        # One center must sit near the heavy point's pull direction.
        d_heavy = np.min(np.linalg.norm(centers - np.array([-5.0, -5.0]), axis=1))
        assert d_heavy < 4.0

    def test_duplicate_row_equivalence(self, rng):
        x = np.concatenate([rng.normal(size=(40, 3)), rng.normal(size=(40, 3)) + 6])
        w = np.ones(80)
        w[:5] = 4.0
        m_w = KMeans().setK(2).setSeed(1).setWeightCol("weight").fit(_wdf(x, w=w))
        x_dup = np.concatenate([x] + [x[:5]] * 3)
        m_dup = KMeans().setK(2).setSeed(1).fit(x_dup)
        # Same blobs recovered: centers agree up to ordering.
        c1 = np.asarray(sorted(m_w.clusterCenters().tolist()))
        c2 = np.asarray(sorted(m_dup.clusterCenters().tolist()))
        np.testing.assert_allclose(c1, c2, atol=0.5)


class TestForestWeights:
    def test_weighted_classes_change_leaves(self, rng):
        x = rng.normal(size=(300, 4))
        y = (x[:, 0] > 1.0).astype(float)  # imbalanced: ~16% positives
        w = np.where(y == 1, 8.0, 1.0)
        m_plain = RandomForestClassifier().setNumTrees(10).setSeed(0).fit((x, y))
        m_w = (
            RandomForestClassifier()
            .setNumTrees(10)
            .setSeed(0)
            .setWeightCol("weight")
            .fit(_wdf(x, y, w))
        )
        recall_plain = np.mean(m_plain.predict(x)[y == 1] == 1)
        recall_w = np.mean(m_w.predict(x)[y == 1] == 1)
        assert recall_w >= recall_plain

    def test_regressor_weighted_mean_leaves(self, rng):
        # Weight 0 rows are invisible: fitting with poisoned rows at weight
        # 0 equals fitting without them.
        x = rng.uniform(0, 1, size=(200, 2))
        y = 2.0 * x[:, 0]
        x_poison = np.concatenate([x, rng.uniform(0, 1, size=(50, 2))])
        y_poison = np.concatenate([y, np.full(50, 100.0)])
        w = np.concatenate([np.ones(200), np.zeros(50)])
        m_w = (
            RandomForestRegressor()
            .setNumTrees(5)
            .setSeed(2)
            .setBootstrap(False)
            .setWeightCol("weight")
            .fit(_wdf(x_poison, y_poison, w))
        )
        preds = m_w.predict(x)
        assert np.sqrt(np.mean((preds - y) ** 2)) < 0.3  # poison ignored

    def test_fractional_weights_route_to_exact_histograms(self):
        """bf16 one-pass histograms are only used when the full histogram
        operand — sample_weight * stat — survives bf16 rounding (ADVICE r1:
        fractional weightCol could flip near-tie splits under DEFAULT
        precision; the bound must cover the bootstrap multiplicity too)."""
        from spark_rapids_ml_tpu.models.random_forest import _hist_exact_in_bf16

        onehot = np.eye(3, dtype=np.float32)[np.array([0, 1, 2, 1])]
        assert _hist_exact_in_bf16(onehot, np.ones(4))  # integer counts: exact
        assert _hist_exact_in_bf16(onehot * 8.0, np.full(4, 4.0))  # 32 <= 256
        assert not _hist_exact_in_bf16(onehot * 0.3, np.ones(4))  # fractional
        # bf16-exact stats whose product with a bootstrap draw of 3 exceeds
        # the bf16 odd-integer range (129 * 3 = 387 > 256): lossy.
        assert not _hist_exact_in_bf16(onehot * 129.0, np.full(4, 3.0))
        # fractional sample weights (not produced today) must also disqualify
        assert not _hist_exact_in_bf16(onehot, np.full(4, 0.3))
