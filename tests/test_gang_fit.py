"""Gang deploy mode (gang-parallel fit through the public estimator API).

Three tiers of proof:

  - SINGLE-member gangs in-process: ``setDeployMode("gang")`` (and its
    ``TPUML_GANG_FIT`` env twin) routes through the same fit path and
    must reproduce the single-deploy model exactly — no jax.distributed
    bring-up for a gang of one;
  - the autotuner TUNE-STORE under gangs: N members persisting through
    one path lose commits to the whole-file atomic rewrite (the race,
    demonstrated), so ``autotune.configure`` gives every non-zero rank
    its own ``.p<rank>`` store (the fix, counter-asserted);
  - the ACCEPTANCE case: a REAL 2-process gang (jax.distributed over
    gloo) where each member feeds only ITS rows to the public ``fit()``
    and the fitted PCA / linear / logistic / KMeans models match the
    single-process full-data fit at the documented tolerances, with the
    members' telemetry shards merging into one strict-clean trace.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
TRACE_CLI = REPO / "tools" / "tpuml_trace.py"


# --- single-member gangs (the in-process contract) ----------------------


class TestSingleMemberGang:
    def test_deploy_mode_param_and_env_twin(self, monkeypatch):
        from spark_rapids_ml_tpu.feature import PCA

        est = PCA()
        assert est.getDeployMode() == "single"
        est.setDeployMode("gang")
        assert est.getDeployMode() == "gang"
        with pytest.raises(ValueError):
            est.setDeployMode("fleet")
        # The env twin covers estimators the caller can't reach (inside
        # pipelines/tuners); an explicit param outranks it.
        monkeypatch.setenv("TPUML_GANG_FIT", "1")
        assert PCA().getDeployMode() == "gang"
        assert PCA().setDeployMode("single").getDeployMode() == "single"

    def test_gang_of_one_matches_single_deploy(self, rng):
        """deployMode='gang' without gang env is a gang of one: same
        model to near-machine tolerance (the gang path computes over the
        local mesh, whose GEMM blocking differs in the last bit), and no
        jax.distributed bring-up (process_count stays 1)."""
        import jax

        from spark_rapids_ml_tpu.clustering import KMeans
        from spark_rapids_ml_tpu.feature import PCA
        from spark_rapids_ml_tpu.regression import LinearRegression

        x = rng.normal(size=(80, 6))
        m = PCA().setK(3).setDeployMode("gang").fit([x[:30], x[30:]])
        ref = PCA().setK(3).fit([x[:30], x[30:]])
        np.testing.assert_allclose(
            np.asarray(m.pc), np.asarray(ref.pc), atol=1e-12, rtol=0
        )
        assert jax.process_count() == 1

        y = x @ np.arange(1.0, 7.0)
        lm = LinearRegression().setDeployMode("gang").fit((x, y))
        lref = LinearRegression().fit((x, y))
        np.testing.assert_allclose(
            np.asarray(lm.coefficients), np.asarray(lref.coefficients),
            atol=1e-12, rtol=0,
        )

        km = KMeans().setK(2).setSeed(0).setDeployMode("gang").fit(x)
        kref = KMeans().setK(2).setSeed(0).fit(x)
        np.testing.assert_allclose(
            np.asarray(km.clusterCenters()),
            np.asarray(kref.clusterCenters()), atol=1e-12, rtol=0,
        )

    def test_deploy_mode_not_copied_onto_model(self, rng):
        """deployMode is an ESTIMATOR param: _copyValues must not push it
        onto the fitted model (Spark only copies params the target has)."""
        from spark_rapids_ml_tpu.feature import PCA

        x = rng.normal(size=(40, 5))
        model = PCA().setK(2).setDeployMode("gang").fit([x])
        assert not model.hasParam("deployMode")
        model.copy()  # and the model stays copyable

    def test_copy_preserves_deploy_mode(self):
        """Tuners/pipelines fit COPIES — the gang switch must survive."""
        from spark_rapids_ml_tpu.feature import PCA

        est = PCA().setK(2).setDeployMode("gang")
        assert est.copy().getDeployMode() == "gang"


# --- the tune-store under gangs (the race + the fix) --------------------


class TestGangTuneStore:
    def _decision(self, knob, ident, value):
        return {"knob": knob, "key": ident, "value": value}

    def test_shared_path_loses_commits_last_writer_wins(self, tmp_path):
        """The RACE, demonstrated: two members (two TuneStore instances,
        as two processes would hold) committing through ONE path — each
        loaded the store before the other's commit, so the second
        whole-file rewrite drops the first member's decision."""
        from spark_rapids_ml_tpu.observability.autotune import TuneStore

        path = str(tmp_path / "tune.json")
        member0 = TuneStore(path)
        member1 = TuneStore(path)
        member0.put(self._decision("batch", "pca/f64", 256))
        member1.put(self._decision("batch", "kmeans/f64", 512))
        persisted = json.load(open(path))["decisions"]
        assert len(persisted) == 1  # member0's commit is GONE

    def test_configure_gives_each_rank_its_own_store(
        self, tmp_path, monkeypatch
    ):
        """The FIX, counter-asserted: under gang env, configure() routes
        every non-zero rank to <path>.p<rank> (member 0 keeps the bare
        path the file tooling reads), so N members' commits all survive —
        total persisted decisions equals total commits."""
        from spark_rapids_ml_tpu.observability import autotune
        from spark_rapids_ml_tpu.observability import costs

        path = str(tmp_path / "tune.json")
        monkeypatch.setenv(autotune.AUTOTUNE_ENV, "on")
        monkeypatch.setenv(autotune.TUNE_STORE_ENV, path)
        stores = {}
        try:
            for rank in ("0", "1"):
                monkeypatch.setenv("TPUML_PROCESS_ID", rank)
                autotune.reset_for_tests()
                stores[rank] = autotune.active().store
        finally:
            monkeypatch.delenv("TPUML_PROCESS_ID")
            monkeypatch.delenv(autotune.AUTOTUNE_ENV)
            monkeypatch.delenv(autotune.TUNE_STORE_ENV)
            autotune.reset_for_tests()
            # Arming the tuner armed the cost ledger as a side effect
            # (autotune.configure -> costs.configure(enable=True));
            # resetting autotune does NOT disarm it, and a live ledger
            # flips serving admission from declared-spec to measured
            # pricing for every later test in the process.
            costs.configure(enable=False)

        assert stores["0"].path == path
        assert stores["1"].path == f"{path}.p1"
        stores["0"].put(self._decision("batch", "pca/f64", 256))
        stores["1"].put(self._decision("batch", "kmeans/f64", 512))
        committed = 2
        persisted = sum(
            len(json.load(open(p))["decisions"])
            for p in (path, f"{path}.p1")
        )
        assert persisted == committed  # nobody's commit was dropped


# --- the acceptance case: a REAL 2-process gang fit ---------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestTwoProcessGangFit:
    @pytest.mark.slow  # ~15 s, 2 jax bring-ups; runs full-file in CI's Gang fit step
    def test_two_process_gang_fit_matches_single_process(self, tmp_path):
        """ISSUE 15 acceptance: 2 OS processes (jax.distributed, gloo on
        CPU), each feeding only ITS slice through the PUBLIC fit() with
        deployMode='gang', produce PCA / linear / logistic / KMeans
        models matching the single-process full-data fit — and their
        telemetry shards merge into ONE strict-clean trace."""
        from spark_rapids_ml_tpu.observability import events
        from spark_rapids_ml_tpu.observability import trace as tracelib

        tdir = tmp_path / "telemetry"
        n_proc = 2
        port = _free_port()
        carrier = events.inject_env({})
        procs = []
        for pid in range(n_proc):
            env = {
                **os.environ,
                **carrier,
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "TPUML_COORDINATOR": f"127.0.0.1:{port}",
                "TPUML_NUM_PROCESSES": str(n_proc),
                "TPUML_PROCESS_ID": str(pid),
                "TPUML_TELEMETRY_DIR": str(tdir),
            }
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        str(REPO / "tests" / "multiproc_gang_fit_worker.py"),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                    cwd=str(REPO),
                )
            )
        outs = [p.communicate(timeout=500) for p in procs]
        for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {pid} failed:\n{err[-3000:]}"
            for family in ("PCA", "LINEAR", "LOGISTIC", "KMEANS"):
                assert f"{family}_OK {pid}" in out, out
            assert f"OK process {pid}/{n_proc}" in out

        # The members' shards merge into ONE trace (the driver carrier),
        # every span's parent resolvable, both processes represented.
        merged = tracelib.assemble(str(tdir))
        assert merged["problems"] == [], merged["problems"]
        assert merged["orphan_problems"] == [], merged["orphan_problems"]
        assert len(merged["manifests"]) == n_proc
        assert len(merged["traces"]) == 1
        (cell,) = merged["traces"].values()
        assert cell["trace_id"] == carrier[events.TRACE_ID_ENV]
        assert cell["processes"] == [0, 1]
        assert cell["orphans"] == []
        # Every family's fit ran AS a gang on both members.
        joins = [
            r
            for r in merged["trace_cells"][cell["trace_id"]]["events"]
            if r["event"] == "gang_fit" and r.get("action") == "join"
        ]
        assert {r["process"] for r in joins} == {0, 1}
        assert len(joins) >= 2 * 4  # 4 gang fits per member

        # The CLI is the oracle: strict validation stays green.
        r = subprocess.run(
            [sys.executable, str(TRACE_CLI), str(tdir),
             "--validate", "--strict"],
            capture_output=True, text=True, cwd=str(REPO),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == 0, r.stdout + r.stderr


# --- elastic resize: resume a gang fit on a DIFFERENT member count ------


class TestGangResize:
    """ISSUE 16 training-side acceptance: a 2-process gang fit killed
    mid-solve resumes SINGLE-process over all rows. The checkpoint's
    sharding-invariant data fingerprint carries the identity across the
    member-count change, ``restore_latest`` flags the resize
    (``gang_resize`` event + counter), and the resumed fit lands centers
    bit-identical to a cold single-process refit while executing
    strictly fewer solver iterations — the restored mid-solve state did
    real work."""

    def _estimator(self, init):
        from spark_rapids_ml_tpu.models.kmeans import KMeans

        return (
            KMeans(uid="resize-gang")
            .setK(4)
            .setMaxIter(10)
            .setTol(0.0)
            .setSeed(1)
            .setInitialModel(init)
        )

    def test_gang_fit_resumes_on_smaller_world(self, tmp_path, monkeypatch):
        import glob as globlib
        import json

        from spark_rapids_ml_tpu.observability import events
        from spark_rapids_ml_tpu.utils.tracing import (
            clear_counters,
            counter_value,
        )

        rng = np.random.default_rng(7)
        n, d = 160, 5
        # Dyadic rows (integers/4): every cross-member sum is exact in
        # f64, so the 2-process segments and the 1-process segments walk
        # bit-identical center iterates — the precondition for the
        # resumed model matching the cold refit bitwise.
        x = (rng.integers(-64, 64, size=(n, d)) / 4.0).astype(np.float64)
        init = x[:4].copy()
        gang_dir = tmp_path / "ckpt-gang"

        # Phase A: the 2-member gang, checkpointing into the shared dir,
        # dies at the third segment boundary (skip-offset fault grammar)
        # — AFTER the step-6 snapshot flushed, BEFORE the fit finished.
        port = _free_port()
        procs = []
        for pid in range(2):
            env = {
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "TPUML_COORDINATOR": f"127.0.0.1:{port}",
                "TPUML_NUM_PROCESSES": "2",
                "TPUML_PROCESS_ID": str(pid),
                "TPUML_GANG_FIT": "1",
                "TPUML_CHECKPOINT_DIR": str(gang_dir),
                "TPUML_CHECKPOINT_EVERY": "2",
                "TPUML_FAULTS": "checkpoint.segment=1@2",
            }
            env.pop("TPUML_TELEMETRY_DIR", None)
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        str(REPO / "tests" / "multiproc_resize_worker.py"),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                    cwd=str(REPO),
                )
            )
        outs = [p.communicate(timeout=500) for p in procs]
        for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode != 0, f"member {pid} survived the fault"
            assert "InjectedFault" in err, f"member {pid}:\n{err[-3000:]}"
            assert "UNEXPECTED_COMPLETE" not in out
        snaps = globlib.glob(str(gang_dir / "*" / "ckpt-*.npz"))
        assert snaps, "the dead gang left no shared mid-solve state"

        # Phase B: cold single-process refit (fresh dir) — the iteration
        # budget a from-scratch fit pays, and the bit-exact reference.
        monkeypatch.setenv("TPUML_CHECKPOINT_EVERY", "2")
        monkeypatch.setenv("TPUML_CHECKPOINT_DIR", str(tmp_path / "ckpt-cold"))
        clear_counters("checkpoint")
        cold = self._estimator(init).fit(x)
        cold_iters = counter_value("checkpoint.solver_iters")
        assert cold.numIter == 10 and cold_iters == 10

        # Phase C: resume from the GANG's dir, world 2 -> 1, over ALL
        # rows this time.
        monkeypatch.setenv("TPUML_CHECKPOINT_DIR", str(gang_dir))
        clear_counters("checkpoint")
        log = tmp_path / "events.jsonl"
        events.configure(str(log))
        try:
            warm = self._estimator(init).fit(x)
        finally:
            events.configure()  # back to the ambient (env-derived) sink
        assert counter_value("checkpoint.restore") >= 1
        assert counter_value("checkpoint.gang_resize") == 1
        warm_iters = counter_value("checkpoint.solver_iters")
        assert 0 < warm_iters < cold_iters
        assert warm.numIter == cold.numIter
        assert (
            np.asarray(warm.clusterCenters()).tobytes()
            == np.asarray(cold.clusterCenters()).tobytes()
        )
        assert (
            np.float64(warm.trainingCost).tobytes()
            == np.float64(cold.trainingCost).tobytes()
        )
        resizes = [
            json.loads(line)
            for line in open(log)
            if '"gang_resize"' in line
        ]
        assert [
            (r["event"], r["action"], r["from_members"], r["to_members"])
            for r in resizes
        ] == [("gang_resize", "resume", 2, 1)]
