"""Device-side evaluator kernels must agree with the host evaluators —
the scale path (jax-array or >=1M-row tuples) vs the validation-fold path
(VERDICT r1 weak item 7: the AUC sort no longer collects to host)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.ops.metrics import (
    binary_auc_device,
    confusion_matrix_device,
    multiclass_metrics_device,
    regression_metrics_device,
)


class TestRegressionDevice:
    def test_matches_host(self, rng):
        y = rng.normal(size=5000) * 3 + 1
        p = y + 0.3 * rng.normal(size=5000)
        for m in ("rmse", "mse", "mae", "r2"):
            ev = RegressionEvaluator().setMetricName(m)
            host = ev.evaluate((y, p))
            dev = ev.evaluate((jnp.asarray(y), jnp.asarray(p)))  # device route
            assert dev == pytest.approx(host, rel=1e-9)
        rmse, mse, mae, r2 = regression_metrics_device(jnp.asarray(y), jnp.asarray(p))
        assert float(rmse) == pytest.approx(np.sqrt(np.mean((y - p) ** 2)))


class TestMulticlassDevice:
    def test_matches_host(self, rng):
        y = rng.integers(0, 4, 3000).astype(float)
        p = np.where(rng.uniform(size=3000) < 0.7, y, rng.integers(0, 4, 3000)).astype(float)
        for m in ("accuracy", "f1", "weightedPrecision", "weightedRecall"):
            ev = MulticlassClassificationEvaluator().setMetricName(m)
            host = ev.evaluate((y, p))
            dev = ev.evaluate((jnp.asarray(y), jnp.asarray(p)))
            assert dev == pytest.approx(host, rel=1e-9), m

    def test_confusion_matrix(self, rng):
        y = rng.integers(0, 3, 500)
        p = rng.integers(0, 3, 500)
        cm = np.asarray(confusion_matrix_device(jnp.asarray(y), jnp.asarray(p), 3))
        for a in range(3):
            for b in range(3):
                assert cm[a, b] == np.sum((y == a) & (p == b))

    def test_single_class_predictions(self):
        """All predictions one class: precision of empty classes is 0."""
        y = jnp.asarray([0, 1, 2, 1])
        p = jnp.asarray([1, 1, 1, 1])
        out = multiclass_metrics_device(y, p, 3)
        assert out["accuracy"] == pytest.approx(0.5)
        assert 0.0 <= out["weightedPrecision"] <= 1.0


class TestBinaryAUCDevice:
    def test_matches_host(self, rng):
        y = rng.integers(0, 2, 4000).astype(float)
        s = y * 0.8 + rng.normal(size=4000)
        for m in ("areaUnderROC", "areaUnderPR"):
            ev = BinaryClassificationEvaluator().setMetricName(m)
            host = ev.evaluate((y, s))
            dev = ev.evaluate((jnp.asarray(y), jnp.asarray(s)))
            assert dev == pytest.approx(host, rel=1e-6), m

    def test_ties_match_host(self, rng):
        """Heavy score ties: the tie-grouped curve must agree exactly."""
        y = rng.integers(0, 2, 1000).astype(float)
        s = np.round(y * 0.5 + rng.normal(size=1000), 1)  # many ties
        for m in ("areaUnderROC", "areaUnderPR"):
            ev = BinaryClassificationEvaluator().setMetricName(m)
            host = ev.evaluate((y, s))
            dev = float(binary_auc_device(jnp.asarray(y), jnp.asarray(s), metric=m))
            assert dev == pytest.approx(host, rel=1e-6), m

    def test_degenerate_single_class(self):
        y = jnp.zeros(50)
        s = jnp.linspace(0, 1, 50)
        assert float(binary_auc_device(y, s)) == 0.0

    def test_perfect_separation(self):
        y = jnp.asarray([0.0] * 50 + [1.0] * 50)
        s = jnp.concatenate([jnp.linspace(0, 0.4, 50), jnp.linspace(0.6, 1.0, 50)])
        assert float(binary_auc_device(y, s)) == pytest.approx(1.0)


class TestPrecisionRouting:
    def test_host_f64_not_demoted_without_x64(self, rng, monkeypatch):
        """A big host float64 tuple must stay on the exact host path when
        the device would compute it at f32 (r2 review: 8% rmse error on
        large-offset targets)."""
        import spark_rapids_ml_tpu.evaluation as ev_mod

        monkeypatch.setattr(ev_mod, "_DEVICE_THRESHOLD", 100)
        y = rng.normal(size=1_000) + 1e6
        p = y + 0.01 * rng.normal(size=1_000)

        import jax

        # Simulate the no-x64 platform decision without flipping the
        # global flag mid-suite: patch the config object the router reads.
        class _Cfg:
            jax_enable_x64 = False

        real_config = jax.config
        monkeypatch.setattr(ev_mod, "_device_pair", ev_mod._device_pair)
        # Directly check the routing decision instead.
        monkeypatch.setattr(jax, "config", _Cfg)
        try:
            routed = ev_mod._device_pair((y, p))
        finally:
            monkeypatch.setattr(jax, "config", real_config)
        assert routed is None  # stays host-side: exact f64

        # f32 host input of the same size IS routed (no precision loss).
        monkeypatch.setattr(jax, "config", _Cfg)
        try:
            routed32 = ev_mod._device_pair(
                (y.astype(np.float32), p.astype(np.float32))
            )
        finally:
            monkeypatch.setattr(jax, "config", real_config)
        assert routed32 is not None

    def test_multiclass_fallback_keeps_original_columns(self, rng, monkeypatch):
        """Labels failing the bincount gate must evaluate from the ORIGINAL
        columns, not a device round-trip (r2 review)."""
        import spark_rapids_ml_tpu.evaluation as ev_mod

        monkeypatch.setattr(ev_mod, "_DEVICE_THRESHOLD", 100)
        # Sparse large IDs: gate rejects; host np.unique handles exactly.
        y = rng.choice([7.0, 123456.0], size=500)
        p = np.where(rng.uniform(size=500) < 0.8, y, 7.0)
        ev = MulticlassClassificationEvaluator().setMetricName("accuracy")
        assert ev.evaluate((y, p)) == pytest.approx(np.mean(y == p))


class TestAUCSortAttack:
    """The sort-attack rewrite (BASELINE.md "AUC sort shoot-out") has two
    code paths: the packed-uint64 single sort (f32 scores under x64) and
    the variadic key+label sort (everything else). Both must reproduce
    the host tie-grouped curve; the packed path must survive the exact
    hazards that killed the pack32 candidate (tie splitting, -0.0)."""

    def _host(self, y, s, m):
        ev = BinaryClassificationEvaluator().setMetricName(m)
        return ev.evaluate((y.astype(np.float64), s.astype(np.float64)))

    @pytest.mark.parametrize("metric", ["areaUnderROC", "areaUnderPR"])
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_ties_10k_both_branches(self, rng, metric, dtype):
        """f32 under x64 dispatches the packed sort, f64 the variadic
        sort — same 10k heavy-ties fixture, same host oracle."""
        y = rng.integers(0, 2, 10_000).astype(np.float64)
        s = np.round(y * 0.5 + rng.normal(size=10_000), 1).astype(dtype)
        dev = float(
            binary_auc_device(jnp.asarray(y), jnp.asarray(s), metric=metric)
        )
        assert dev == pytest.approx(self._host(y, s, metric), rel=1e-6)

    def test_branches_agree(self, rng):
        """The two dispatch branches compute one definition: f32 scores
        (packed) vs their f64 copy (variadic) on ties-free data."""
        y = rng.integers(0, 2, 10_000).astype(np.float64)
        s32 = (y * 0.3 + rng.normal(size=10_000)).astype(np.float32)
        for m in ("areaUnderROC", "areaUnderPR"):
            a32 = float(binary_auc_device(jnp.asarray(y), jnp.asarray(s32), metric=m))
            a64 = float(
                binary_auc_device(
                    jnp.asarray(y), jnp.asarray(s32.astype(np.float64)), metric=m
                )
            )
            assert a32 == pytest.approx(a64, rel=1e-6), m

    def test_negative_zero_one_tie_group(self):
        """-0.0 and +0.0 compare equal but have different bit patterns:
        the packed path must canonicalize before the bit transform or the
        zeros split into two tie groups (the bug XLA's `s + 0.0` folding
        would resurrect — see the kernel comment)."""
        y = np.array([1.0, 0.0, 1.0, 0.0])
        s_zero = np.array([-0.0, 0.0, 0.5, -0.25], dtype=np.float32)
        s_tied = np.array([0.0, 0.0, 0.5, -0.25], dtype=np.float32)
        for m in ("areaUnderROC", "areaUnderPR"):
            a_zero = float(binary_auc_device(jnp.asarray(y), jnp.asarray(s_zero), metric=m))
            a_tied = float(binary_auc_device(jnp.asarray(y), jnp.asarray(s_tied), metric=m))
            assert a_zero == a_tied, m
            # rel 1e-6: the packed path divides in the score's f32 dtype.
            assert a_zero == pytest.approx(self._host(y, s_zero, m), rel=1e-6), m

    def test_adjacent_floats_stay_distinct(self):
        """The pack32 candidate collapsed adjacent f32 scores with even
        keys (label stole the LSB) — the exactness probe that rejected
        it. The shipped pack64 keeps all 32 key bits: a one-ULP score gap
        must still separate the curve points."""
        lo = np.float32(0.5)
        hi = np.nextafter(lo, np.float32(1.0), dtype=np.float32)
        y = np.array([0.0, 1.0])
        s = np.array([lo, hi], dtype=np.float32)
        assert float(binary_auc_device(jnp.asarray(y), jnp.asarray(s))) == 1.0

    @pytest.mark.parametrize("n", [1, 2, 17, 1000])
    def test_sizes_vs_host(self, rng, n):
        y = rng.integers(0, 2, n).astype(np.float64)
        s = rng.normal(size=n).astype(np.float32)
        for m in ("areaUnderROC", "areaUnderPR"):
            dev = float(binary_auc_device(jnp.asarray(y), jnp.asarray(s), metric=m))
            if np.all(y == y[0]):  # degenerate: device defines 0.0
                assert dev == 0.0
            else:
                assert dev == pytest.approx(self._host(y, s, m), rel=1e-6), m
