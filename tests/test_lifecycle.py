"""Continuous-training lifecycle: incremental refit + the journaled
refit→swap controller + drift triggers + gate/rollback.

The contracts under test, per the r18 issue:

- **Zero-state bit-identity**: ``partial_fit(data)`` with no previous
  model IS a from-scratch fit — byte-equal solutions for all three
  solver families (the PR 3 segmented ≡ monolithic invariant carries
  the whole claim).
- **Warm seeding converges measurably faster**: seeding from the
  previous solution runs STRICTLY fewer solver segments, asserted
  through the ``checkpoint.solver_iters`` counter, per family.
- **PCA accumulates exactly**: split-and-merge moments reproduce the
  single-shot covariance to fp64 exactness; parity with ``fit`` is
  bounded only by the fit path's fp32 covariance GEMM.
- **The controller never flips on a loser** and rolls back one-op when
  live traffic regresses after a flip; both surface as structured
  ``lifecycle`` events.
- **Every stage is a named fault site in a RetryPolicy**: transient
  faults retry invisibly; fatal ones leave a journal that resumes the
  SAME cycle with no duplicate registry versions.
"""

import json
import threading

import numpy as np
import pytest

from spark_rapids_ml_tpu.lifecycle import (
    DriftMonitor,
    LifecycleController,
)
from spark_rapids_ml_tpu.lifecycle.journal import CycleJournal
from spark_rapids_ml_tpu.models.kmeans import KMeans
from spark_rapids_ml_tpu.models.linear_regression import LinearRegression
from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegression
from spark_rapids_ml_tpu.models.pca import PCA
from spark_rapids_ml_tpu.observability import events
from spark_rapids_ml_tpu.robustness import InjectedFault, inject
from spark_rapids_ml_tpu.robustness.faults import disarm
from spark_rapids_ml_tpu.serving.server import ServingRuntime
from spark_rapids_ml_tpu.utils.tracing import clear_counters, counter_value


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    disarm()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setenv("TPUML_RETRY_BASE_DELAY", "0")


@pytest.fixture
def clusters(rng):
    x = rng.normal(size=(240, 6))
    x[:120] += 4.0
    return x


@pytest.fixture
def labeled(rng):
    x = rng.normal(size=(240, 6))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0.2).astype(float)
    return x, y


def _km_score(model, x, y):
    centers = np.asarray(model.clusterCenters())
    d = np.linalg.norm(x[:, None, :] - centers[None], axis=2).min(axis=1)
    return -float(d.mean())


def _runtime():
    return ServingRuntime(start=False)


# --- zero-state bit-identity (all three solver families) ----------------


class TestZeroStateBitIdentity:
    def test_kmeans(self, clusters):
        cold = KMeans(uid="zs-km").setK(3).setSeed(7).fit(clusters)
        pf = KMeans(uid="zs-km").setK(3).setSeed(7).partial_fit(clusters)
        assert np.array_equal(
            np.asarray(cold.clusterCenters()), np.asarray(pf.clusterCenters())
        )

    def test_logistic(self, labeled):
        x, y = labeled
        cold = LogisticRegression(uid="zs-lr").setMaxIter(50).fit((x, y))
        pf = LogisticRegression(uid="zs-lr").setMaxIter(50).partial_fit((x, y))
        assert np.array_equal(
            np.asarray(cold.coefficients), np.asarray(pf.coefficients)
        )
        assert np.array_equal(
            np.asarray(cold.intercepts), np.asarray(pf.intercepts)
        )

    def test_linear(self, rng):
        x = rng.normal(size=(200, 6))
        y = x @ rng.normal(size=6) + 0.1 * rng.normal(size=200)
        est = lambda: (
            LinearRegression(uid="zs-ln").setRegParam(0.05).setElasticNetParam(0.5)
        )
        cold = est().fit((x, y))
        pf = est().partial_fit((x, y))
        assert np.array_equal(
            np.asarray(cold.coefficients), np.asarray(pf.coefficients)
        )

    def test_unsupported_family_raises(self, clusters):
        from spark_rapids_ml_tpu.models.random_forest import (
            RandomForestClassifier,
        )

        with pytest.raises(TypeError, match="partial_fit supports"):
            RandomForestClassifier().partial_fit(clusters)


# --- warm seeding: strictly fewer solver segments -----------------------


class TestWarmSeedIterations:
    def _delta(self, fn):
        before = counter_value("checkpoint.solver_iters")
        fn()
        return counter_value("checkpoint.solver_iters") - before

    def test_kmeans_warm_fewer_iters(self, clusters):
        est = KMeans(uid="ws-km").setK(3).setSeed(7).setMaxIter(40)
        prev = est.partial_fit(clusters)
        cold = self._delta(lambda: est.partial_fit(clusters))
        warm = self._delta(lambda: est.partial_fit(clusters, model=prev))
        assert 0 < warm < cold

    def test_logistic_warm_fewer_iters(self, labeled):
        x, y = labeled
        est = LogisticRegression(uid="ws-lr").setMaxIter(80)
        prev = est.partial_fit((x, y))
        cold = self._delta(lambda: est.partial_fit((x, y)))
        warm = self._delta(lambda: est.partial_fit((x, y), model=prev))
        assert 0 < warm < cold

    def test_linear_warm_fewer_iters(self, rng):
        x = rng.normal(size=(200, 6))
        y = x @ rng.normal(size=6) + 0.05 * rng.normal(size=200)
        est = LinearRegression(uid="ws-ln").setRegParam(0.02).setElasticNetParam(0.5)
        prev = est.partial_fit((x, y))
        cold = self._delta(lambda: est.partial_fit((x, y)))
        warm = self._delta(lambda: est.partial_fit((x, y), model=prev))
        assert 0 < warm < cold

    def test_warm_result_matches_cold_solution(self, clusters):
        """Fewer segments, same fixed point: the warm-seeded solution
        converges to the cold one (same data, converged tolerance)."""
        est = KMeans(uid="ws-eq").setK(3).setSeed(7).setMaxIter(100)
        prev = est.partial_fit(clusters)
        cold = est.partial_fit(clusters)
        warm = est.partial_fit(clusters, model=prev)
        assert np.allclose(
            np.sort(np.asarray(warm.clusterCenters()), axis=0),
            np.sort(np.asarray(cold.clusterCenters()), axis=0),
            atol=1e-5,
        )


# --- PCA: exact streaming-moment accumulation ---------------------------


class TestPCAStreamingMerge:
    def test_split_merge_matches_single_shot(self, rng):
        x = rng.normal(size=(300, 8))
        x[:150] += 2.0
        est = PCA(uid="sm-pca").setK(3)
        m1 = est.partial_fit(x[:100])
        m2 = est.partial_fit(x[100:], model=m1)
        one = est.partial_fit(x)
        # The merge is algebraically exact but re-bases about each
        # block's own shift, so fp64 rounding differs in the last ulps —
        # tight-tolerance equality, far below the fit path's fp32 gap.
        assert np.allclose(m2.pc, one.pc, atol=1e-9)
        assert np.allclose(m2.explainedVariance, one.explainedVariance, atol=1e-12)
        assert m2._moments.n_rows == 300

    def test_parity_with_fit_within_fp32_covariance(self, rng):
        x = rng.normal(size=(300, 8))
        est = PCA(uid="pp-pca").setK(3)
        m1 = est.partial_fit(x[:130])
        m2 = est.partial_fit(x[130:], model=m1)
        full = est.fit(x)
        assert np.allclose(np.abs(m2.pc), np.abs(full.pc), atol=1e-4)
        assert np.allclose(
            m2.explainedVariance, full.explainedVariance, atol=1e-6
        )

    def test_previous_model_not_mutated(self, rng):
        x = rng.normal(size=(120, 5))
        est = PCA(uid="im-pca").setK(2)
        m1 = est.partial_fit(x[:60])
        n_before = m1._moments.n_rows
        est.partial_fit(x[60:], model=m1)
        assert m1._moments.n_rows == n_before

    def test_plain_fit_model_rejected(self, rng):
        x = rng.normal(size=(120, 5))
        est = PCA(uid="rj-pca").setK(2)
        plain = est.fit(x)
        with pytest.raises(ValueError, match="streaming moments"):
            est.partial_fit(x, model=plain)

    def test_width_change_rejected(self, rng):
        est = PCA(uid="wc-pca").setK(2)
        m1 = est.partial_fit(rng.normal(size=(60, 5)))
        with pytest.raises(ValueError, match="width changed"):
            est.partial_fit(rng.normal(size=(60, 7)), model=m1)


# --- the controller ------------------------------------------------------


class TestController:
    def test_first_cycle_registers_and_flips(self, clusters, tmp_path):
        rt = _runtime()
        est = KMeans(uid="ct-km").setK(2).setSeed(3)
        ctrl = LifecycleController(
            est, rt, "km", score_fn=_km_score, directory=str(tmp_path)
        )
        out = ctrl.run_cycle(clusters)
        assert out.action == "flipped" and out.version == 1
        assert rt.registry.aliases("km") == {"prod": 1}

    def test_second_cycle_warm_seeds_and_flips(self, clusters, rng, tmp_path):
        rt = _runtime()
        est = KMeans(uid="ct2-km").setK(2).setSeed(3)
        ctrl = LifecycleController(
            est, rt, "km", score_fn=_km_score, directory=str(tmp_path)
        )
        ctrl.run_cycle(clusters)
        # A genuine shift: the incumbent's centers miss the new modes,
        # the refit adapts — the gate must prefer the candidate.
        out = ctrl.run_cycle(clusters + 2.0)
        assert out.action == "flipped" and out.version == 2
        assert out.incumbent_score is not None
        assert rt.registry.aliases("km") == {"prod": 2}

    def test_gate_rejection_keeps_incumbent(self, clusters, tmp_path, event_log):
        rt = _runtime()
        est = KMeans(uid="gr-km").setK(2).setSeed(3)
        ctrl = LifecycleController(
            est, rt, "km", score_fn=_km_score, directory=str(tmp_path)
        )
        ctrl.run_cycle(clusters)
        # An impossible margin turns the next candidate into a loser.
        ctrl.gate_margin = 1e9
        out = ctrl.run_cycle(clusters)
        assert out.action == "rejected" and out.version is None
        assert rt.registry.aliases("km") == {"prod": 1}
        assert len(rt.registry.versions("km")) == 1
        recs = _events(event_log)
        assert any(
            r["event"] == "lifecycle" and r["action"] == "gate_reject"
            for r in recs
        )

    def test_watch_triggers_auto_rollback(self, clusters, rng, tmp_path, event_log):
        rt = _runtime()
        est = KMeans(uid="ar-km").setK(2).setSeed(3)
        ctrl = LifecycleController(
            est, rt, "km", score_fn=_km_score, directory=str(tmp_path),
            regress_tol=0.1,
        )
        ctrl.run_cycle(clusters)
        out = ctrl.run_cycle(clusters + 2.0)
        assert out.version == 2
        healthy = ctrl.watch(out.candidate_score)
        assert healthy is None
        rolled = ctrl.watch(out.candidate_score - 10.0)
        assert rolled == 1
        assert rt.registry.aliases("km") == {"prod": 1}
        # one rollback per flip: the trigger disarms itself
        assert ctrl.watch(-1e9) is None
        recs = _events(event_log)
        assert any(
            r["event"] == "lifecycle" and r["action"] == "auto_rollback"
            for r in recs
        )
        assert any(r["event"] == "registry_rollback" for r in recs)

    def test_transient_faults_at_every_site_retry_through(
        self, clusters, tmp_path
    ):
        """Non-fatal injections at each lifecycle site are absorbed by
        the named RetryPolicy — the cycle completes as if unfaulted."""
        rt = _runtime()
        est = KMeans(uid="tf-km").setK(2).setSeed(3)
        ctrl = LifecycleController(
            est, rt, "km", score_fn=_km_score, directory=str(tmp_path)
        )
        clear_counters("retry")
        with inject("refit.ingest=1;refit.quality_gate=1;refit.swap=1"):
            out = ctrl.run_cycle(clusters)
        assert out.action == "flipped" and out.version == 1
        assert counter_value("retry.refit.ingest.attempts") >= 2
        assert counter_value("retry.refit.quality_gate.attempts") >= 2
        assert counter_value("retry.refit.swap.attempts") >= 2

    def test_fatal_fault_then_resume_same_cycle_no_duplicates(
        self, clusters, tmp_path
    ):
        """In-process crash/resume at every stage boundary: the resumed
        controller finishes the SAME cycle and the registry holds exactly
        one version."""
        for spec in (
            "refit.ingest=1:fatal",      # before ingest commits
            "refit.ingest=2:fatal",      # before refit commits
            "refit.quality_gate=1:fatal",
            "refit.swap=1:fatal",        # before register
            "refit.swap=2:fatal",        # between register and warm
            "refit.swap=3:fatal",        # between warm and flip
        ):
            d = tmp_path / spec.replace(":", "_").replace("=", "_")
            rt = _runtime()
            est = KMeans(uid="ff-km").setK(2).setSeed(3)
            ctrl = LifecycleController(
                est, rt, "km", score_fn=_km_score, directory=str(d)
            )
            with inject(spec):
                with pytest.raises(InjectedFault):
                    ctrl.run_cycle(clusters)
            resumed = LifecycleController(
                est, rt, "km", score_fn=_km_score, directory=str(d)
            )
            out = resumed.run_cycle(clusters)
            assert out.action == "flipped" and out.cycle == 0, spec
            assert rt.registry.versions("km") == [1], spec
            assert rt.registry.aliases("km") == {"prod": 1}, spec

    def test_requires_directory(self, clusters, monkeypatch):
        monkeypatch.delenv("TPUML_LIFECYCLE_DIR", raising=False)
        with pytest.raises(ValueError, match="TPUML_LIFECYCLE_DIR"):
            LifecycleController(
                KMeans().setK(2), _runtime(), "km", score_fn=_km_score
            )


# --- drift monitor -------------------------------------------------------


class TestDriftMonitor:
    def test_bootstrap_then_stable_then_fire(self, rng, event_log):
        dm = DriftMonitor("dm", threshold=0.25, min_count=300)
        dm.observe_many(rng.normal(size=400))
        assert dm.tick() is None  # first window bootstraps the reference
        dm.observe_many(rng.normal(size=400))
        assert dm.tick() is None  # same distribution: quiet
        dm.observe_many(rng.normal(size=400) + 3.0)
        psi = dm.tick()
        assert psi is not None and psi > 0.25
        recs = _events(event_log)
        assert any(
            r["event"] == "lifecycle" and r["action"] == "drift_fire"
            for r in recs
        )

    def test_small_window_never_fires(self, rng):
        dm = DriftMonitor("dm-sm", threshold=0.25, min_count=300)
        dm.observe_many(rng.normal(size=299) + 50.0)
        assert dm.tick() is None

    def test_rebaseline_forgets_reference(self, rng):
        dm = DriftMonitor("dm-rb", threshold=0.25, min_count=100)
        dm.observe_many(rng.normal(size=200))
        dm.tick()
        dm.rebaseline()
        dm.observe_many(rng.normal(size=200) + 5.0)
        assert dm.tick() is None  # shifted window is the NEW baseline
        dm.observe_many(rng.normal(size=200) + 5.0)
        assert dm.tick() is None  # and stable against itself

    def test_tick_transient_fault_retries(self, rng):
        dm = DriftMonitor("dm-ft", threshold=0.25, min_count=100)
        dm.observe_many(rng.normal(size=200))
        clear_counters("retry")
        with inject("drift.tick=1"):
            assert dm.tick() is None  # bootstrap, after one retry
        assert counter_value("retry.drift.tick.attempts") >= 2

    def test_tick_stall_wakes_on_disarm(self, rng):
        """The stuck-but-alive mode: an armed :stall freezes the tick;
        disarming releases it and the tick completes."""
        from spark_rapids_ml_tpu.robustness import faults

        dm = DriftMonitor("dm-st", threshold=0.25, min_count=10)
        dm.observe_many(rng.normal(size=20))
        done = threading.Event()
        with faults.inject("drift.tick=always:stall"):
            t = threading.Thread(target=lambda: (dm.tick(), done.set()))
            t.start()
            assert not done.wait(0.3), "stalled tick returned while armed"
        assert done.wait(5.0), "stalled tick never woke after disarm"
        t.join()


# --- registry rollback (satellite 2 unit surface) ------------------------


class TestRegistryRollback:
    def _two_versions(self, clusters):
        rt = _runtime()
        m = KMeans(uid="rb-km").setK(2).setSeed(3).fit(clusters)
        rt.register("km", m, alias="prod")
        rt.register("km", m, alias="prod")
        return rt

    def test_rollback_swaps_and_double_rollback_returns(self, clusters):
        rt = self._two_versions(clusters)
        assert rt.registry.aliases("km") == {"prod": 2}
        assert rt.rollback("km") == 1
        assert rt.registry.aliases("km") == {"prod": 1}
        assert rt.rollback("km") == 2
        assert rt.registry.aliases("km") == {"prod": 2}

    def test_rollback_without_history_raises(self, clusters):
        rt = _runtime()
        m = KMeans(uid="rb1-km").setK(2).setSeed(3).fit(clusters)
        rt.register("km", m, alias="prod")
        with pytest.raises(KeyError):
            rt.rollback("km")

    def test_rollback_unknown_alias_raises(self, clusters):
        rt = self._two_versions(clusters)
        with pytest.raises(KeyError):
            rt.rollback("km", alias="canary")

    def test_rollback_emits_event_and_counter(self, clusters, event_log):
        rt = self._two_versions(clusters)
        clear_counters("serving.registry")
        rt.rollback("km")
        assert counter_value("serving.registry.rollback") == 1
        recs = _events(event_log)
        ev = [r for r in recs if r["event"] == "registry_rollback"]
        assert ev and ev[0]["version"] == 1 and ev[0]["previous"] == 2


# --- journal unit surface (the process-death matrix lives in
# test_lifecycle_journal.py) ---------------------------------------------


class TestJournalUnit:
    ID = {"name": "m", "estimator": "KMeans"}

    def test_fresh_then_resume(self, tmp_path):
        j = CycleJournal.resume_or_start(str(tmp_path), self.ID, 4)
        j.mark("ingest", {"data": "p"})
        j2 = CycleJournal.resume_or_start(str(tmp_path), self.ID, 99)
        assert j2.cycle == 4 and j2.done("ingest")
        assert j2.payload("ingest") == {"data": "p"}

    def test_finished_journal_starts_fresh(self, tmp_path):
        j = CycleJournal.resume_or_start(str(tmp_path), self.ID, 0)
        j.mark("ingest", {})
        j.finish()
        j2 = CycleJournal.resume_or_start(str(tmp_path), self.ID, 1)
        assert j2.cycle == 1 and not j2.done("ingest")

    def test_double_mark_raises(self, tmp_path):
        j = CycleJournal.resume_or_start(str(tmp_path), self.ID, 0)
        j.mark("ingest", {})
        with pytest.raises(RuntimeError, match="already journaled"):
            j.mark("ingest", {})

    def test_unknown_stage_rejected(self, tmp_path):
        j = CycleJournal.resume_or_start(str(tmp_path), self.ID, 0)
        with pytest.raises(ValueError, match="unknown stage"):
            j.mark("deploy", {})


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.fixture
def event_log(tmp_path):
    path = tmp_path / "events.jsonl"
    events.configure(str(path))
    try:
        yield path
    finally:
        from spark_rapids_ml_tpu.utils.envknobs import env_str

        prev = env_str(events.EVENT_LOG_ENV)
        events.configure(prev if prev else None)
