"""Regression tests for review findings on the round-1 core slice."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA, PCAModel
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


def test_mesh_honors_mean_centering_false(rng):
    """Mesh path must respect meanCentering=False like the local path."""
    mesh = make_mesh((8, 1))
    x = rng.normal(size=(64, 6)) + 3.0
    m_mesh = PCA(mesh=mesh).setK(3).setMeanCentering(False).fit(x)
    m_local = PCA().setK(3).setMeanCentering(False).setUseCuSolverSVD(False).fit(x)
    np.testing.assert_allclose(np.abs(m_mesh.pc), np.abs(m_local.pc), atol=1e-6)
    # and differs from the centered fit (sanity that the flag had an effect)
    m_centered = PCA(mesh=mesh).setK(3).fit(x)
    assert not np.allclose(np.abs(m_mesh.pc), np.abs(m_centered.pc), atol=1e-3)


def test_empty_partition_does_not_nan(rng):
    x = rng.normal(size=(20, 5))
    parts = [np.zeros((0, 5)), x[:10], np.zeros((0, 5)), x[10:]]
    model = PCA().setK(2).setUseCuSolverSVD(False).fit(parts)
    assert not np.any(np.isnan(model.pc))
    ref = PCA().setK(2).setUseCuSolverSVD(False).fit(x)
    np.testing.assert_allclose(model.pc, ref.pc, atol=1e-8)


def test_pandas_without_input_col_uses_rows(rng):
    import pandas as pd

    x = rng.normal(size=(30, 4))
    model = PCA().setK(2).setUseCuSolverSVD(False).fit(pd.DataFrame(x))
    ref = PCA().setK(2).setUseCuSolverSVD(False).fit(x)
    np.testing.assert_allclose(model.pc, ref.pc, atol=1e-10)


def test_model_copy_preserves_fitted_state(rng):
    x = rng.normal(size=(20, 5))
    model = PCA().setK(2).setInputCol("f").setUseCuSolverSVD(False).fit(
        DataFrame({"f": list(x)})
    )
    clone = model.copy()
    np.testing.assert_allclose(clone.pc, model.pc)
    out = clone.setOutputCol("o").transform(DataFrame({"f": list(x)}))
    assert "o" in out.columns


def test_setters_accept_numpy_ints():
    model = PCA().setK(np.int64(3))
    assert model.getK() == 3
    model.setGpuId(np.int32(0))
    assert model.getGpuId() == 0


def test_generic_load_keeps_params_reachable(tmp_path):
    """After load(), default params must still resolve (hash stability)."""
    path = str(tmp_path / "est")
    PCA().setK(5).save(path)
    loaded = PCA.load(path)
    # defaults reachable
    assert loaded.getMeanCentering() is True
    assert loaded.getUseGemm() is True
    assert loaded.getK() == 5
    # no duplicate keys: setting again overrides cleanly
    loaded.setMeanCentering(False)
    assert loaded.getMeanCentering() is False
    assert len([p for p in loaded._paramMap if p.name == "meanCentering"]) == 1
