"""Regression tests for review findings on the round-1 core slice."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


def test_mesh_honors_mean_centering_false(rng):
    """Mesh path must respect meanCentering=False like the local path."""
    mesh = make_mesh((8, 1))
    x = rng.normal(size=(64, 6)) + 3.0
    m_mesh = PCA(mesh=mesh).setK(3).setMeanCentering(False).fit(x)
    m_local = PCA().setK(3).setMeanCentering(False).setUseCuSolverSVD(False).fit(x)
    np.testing.assert_allclose(np.abs(m_mesh.pc), np.abs(m_local.pc), atol=1e-6)
    # and differs from the centered fit (sanity that the flag had an effect)
    m_centered = PCA(mesh=mesh).setK(3).fit(x)
    assert not np.allclose(np.abs(m_mesh.pc), np.abs(m_centered.pc), atol=1e-3)


def test_empty_partition_does_not_nan(rng):
    x = rng.normal(size=(20, 5))
    parts = [np.zeros((0, 5)), x[:10], np.zeros((0, 5)), x[10:]]
    model = PCA().setK(2).setUseCuSolverSVD(False).fit(parts)
    assert not np.any(np.isnan(model.pc))
    ref = PCA().setK(2).setUseCuSolverSVD(False).fit(x)
    np.testing.assert_allclose(model.pc, ref.pc, atol=1e-8)


def test_pandas_without_input_col_uses_rows(rng):
    import pandas as pd

    x = rng.normal(size=(30, 4))
    model = PCA().setK(2).setUseCuSolverSVD(False).fit(pd.DataFrame(x))
    ref = PCA().setK(2).setUseCuSolverSVD(False).fit(x)
    np.testing.assert_allclose(model.pc, ref.pc, atol=1e-10)


def test_model_copy_preserves_fitted_state(rng):
    x = rng.normal(size=(20, 5))
    model = PCA().setK(2).setInputCol("f").setUseCuSolverSVD(False).fit(
        DataFrame({"f": list(x)})
    )
    clone = model.copy()
    np.testing.assert_allclose(clone.pc, model.pc)
    out = clone.setOutputCol("o").transform(DataFrame({"f": list(x)}))
    assert "o" in out.columns


def test_setters_accept_numpy_ints():
    model = PCA().setK(np.int64(3))
    assert model.getK() == 3
    model.setGpuId(np.int32(0))
    assert model.getGpuId() == 0


def test_generic_load_keeps_params_reachable(tmp_path):
    """After load(), default params must still resolve (hash stability)."""
    path = str(tmp_path / "est")
    PCA().setK(5).save(path)
    loaded = PCA.load(path)
    # defaults reachable
    assert loaded.getMeanCentering() is True
    assert loaded.getUseGemm() is True
    assert loaded.getK() == 5
    # no duplicate keys: setting again overrides cleanly
    loaded.setMeanCentering(False)
    assert loaded.getMeanCentering() is False
    assert len([p for p in loaded._paramMap if p.name == "meanCentering"]) == 1


def test_kneighbors_drops_id_col_from_pandas_queries(rng):
    """Bare-matrix pandas frame with an id column: fit() strips it, and
    kneighbors() on the same frame must strip it too (review finding)."""
    import pandas as pd

    from spark_rapids_ml_tpu.neighbors import NearestNeighbors

    x = rng.normal(size=(40, 5))
    df = pd.DataFrame(x, columns=[f"c{i}" for i in range(5)])
    df["rid"] = np.arange(100, 140)
    model = NearestNeighbors().setK(3).setIdCol("rid").fit(df)
    d, ids = model.kneighbors_ids(df)
    assert d.shape == (40, 3)
    # each row's nearest neighbor is itself, reported via the id column
    np.testing.assert_array_equal(ids[:, 0], df["rid"].to_numpy())


def test_fit_id_col_missing_in_dataframe_shim_raises_value_error(rng):
    from spark_rapids_ml_tpu.neighbors import NearestNeighbors

    x = rng.normal(size=(10, 3))
    with pytest.raises(ValueError, match="idCol"):
        NearestNeighbors().setK(2).setIdCol("rid").fit(DataFrame({"features": list(x)}))


def test_knn_masked_overflow_slots_carry_minus_one(rng):
    """k > real (masked) item count: unfilled slots must be (inf, -1), not
    indices of padding rows (review finding)."""
    from spark_rapids_ml_tpu.ops.knn import knn_sq_euclidean

    q = rng.normal(size=(4, 3)).astype(np.float32)
    items = np.zeros((8, 3), dtype=np.float32)
    items[:5] = rng.normal(size=(5, 3))
    mask = np.array([1.0] * 5 + [0.0] * 3, dtype=np.float32)
    d, i = knn_sq_euclidean(q, items, k=7, item_mask=mask)
    d, i = np.asarray(d), np.asarray(i)
    assert np.all(np.isinf(d[:, 5:]))
    assert np.all(i[:, 5:] == -1)
    assert np.all(i[:, :5] >= 0) and np.all(i[:, :5] < 5)
