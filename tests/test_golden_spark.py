"""Golden-file cross-compat: model directories in the EXACT shape upstream
Spark writes them must load through this framework, and directories this
framework writes must carry the exact structural schema Spark reads.

No pyspark/JVM exists in this image, so the golden directories are
byte-constructed here from Spark's documented on-disk contract
(DefaultParamsWriter metadata JSON + snappy parquet with Spark's
row-metadata key and MatrixUDT/VectorUDT structs — RapidsPCA.scala:218-254,
SURVEY §3.4 "must keep this exact on-disk format"): Spark-style part file
names, sparkVersion stamps, JVM class names, and nullable struct fields.
"""

import json
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from spark_rapids_ml_tpu.clustering import KMeansModel  # noqa: E402
from spark_rapids_ml_tpu.feature import PCA, PCAModel  # noqa: E402
from spark_rapids_ml_tpu.regression import LinearRegressionModel  # noqa: E402

# Spark's MatrixUDT / VectorUDT arrow-side schemas, nullable like Spark's.
_SPARK_MATRIX = pa.struct(
    [
        ("type", pa.int8()),
        ("numRows", pa.int32()),
        ("numCols", pa.int32()),
        ("colPtrs", pa.list_(pa.int32())),
        ("rowIndices", pa.list_(pa.int32())),
        ("values", pa.list_(pa.float64())),
        ("isTransposed", pa.bool_()),
    ]
)
_SPARK_VECTOR = pa.struct(
    [
        ("type", pa.int8()),
        ("size", pa.int32()),
        ("indices", pa.list_(pa.int32())),
        ("values", pa.list_(pa.float64())),
    ]
)


def _write_spark_metadata(path, class_name, uid, param_map, default_map=None):
    """DefaultParamsWriter.saveMetadata byte shape: single JSON line in
    metadata/part-00000 + empty _SUCCESS."""
    meta_dir = os.path.join(path, "metadata")
    os.makedirs(meta_dir)
    payload = {
        "class": class_name,
        "timestamp": 1714456800000,
        "sparkVersion": "3.5.1",
        "uid": uid,
        "paramMap": param_map,
        "defaultParamMap": default_map or {},
    }
    with open(os.path.join(meta_dir, "part-00000"), "w") as f:
        f.write(json.dumps(payload) + "\n")
    open(os.path.join(meta_dir, "_SUCCESS"), "w").close()


def _write_spark_parquet(path, schema, rows, spark_schema_json):
    """Spark executor part-file shape: snappy parquet named
    part-00000-<uuid>-c000.snappy.parquet with Spark's row-metadata keys."""
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir)
    arrays = [
        pa.array([r[name] for r in rows], type=schema.field(name).type)
        for name in schema.names
    ]
    table = pa.Table.from_arrays(arrays, schema=schema).replace_schema_metadata(
        {
            "org.apache.spark.version": "3.5.1",
            "org.apache.spark.sql.parquet.row.metadata": spark_schema_json,
        }
    )
    pq.write_table(
        table,
        os.path.join(
            data_dir,
            "part-00000-2fc4f2c3-0d5e-4a52-9b3e-77a312345678-c000.snappy.parquet",
        ),
        compression="snappy",
    )
    open(os.path.join(data_dir, "_SUCCESS"), "w").close()


def _matrix_struct(m):
    m = np.asarray(m, dtype=np.float64)
    return {
        "type": 1,
        "numRows": m.shape[0],
        "numCols": m.shape[1],
        "colPtrs": None,
        "rowIndices": None,
        "values": m.ravel(order="F").tolist(),
        "isTransposed": False,
    }


def _vector_struct(v):
    return {
        "type": 1,
        "size": len(v),
        "indices": None,
        "values": np.asarray(v, dtype=np.float64).tolist(),
    }


class TestLoadSparkWrittenModels:
    def test_pca_model(self, tmp_path, rng):
        pc = rng.normal(size=(5, 2))
        ev = np.array([0.7, 0.2])
        path = str(tmp_path / "spark_pca")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.feature.PCAModel",
            "PCAModel_4b1c2d3e4f50",
            {"k": 2, "inputCol": "features", "outputCol": "pca"},
        )
        schema = pa.schema([("pc", _SPARK_MATRIX), ("explainedVariance", _SPARK_VECTOR)])
        _write_spark_parquet(
            path,
            schema,
            [{"pc": _matrix_struct(pc), "explainedVariance": _vector_struct(ev)}],
            '{"type":"struct","fields":[{"name":"pc","type":{"type":"udt",'
            '"class":"org.apache.spark.ml.linalg.MatrixUDT"},"nullable":true,'
            '"metadata":{}},{"name":"explainedVariance","type":{"type":"udt",'
            '"class":"org.apache.spark.ml.linalg.VectorUDT"},"nullable":true,'
            '"metadata":{}}]}',
        )

        model = PCAModel.load(path)
        np.testing.assert_allclose(model.pc, pc)
        np.testing.assert_allclose(model.explainedVariance, ev)
        assert model.getK() == 2
        assert model.getInputCol() == "features"
        # And it transforms.
        out = model.transform(rng.normal(size=(10, 5)))
        assert out.shape == (10, 2)

    def test_pca_model_is_transposed_layout(self, tmp_path, rng):
        """Spark may store matrices row-major (isTransposed=True)."""
        pc = rng.normal(size=(4, 2))
        path = str(tmp_path / "spark_pca_t")
        os.makedirs(path)
        _write_spark_metadata(
            path, "org.apache.spark.ml.feature.PCAModel", "PCAModel_x", {"k": 2}
        )
        struct = _matrix_struct(pc)
        struct["values"] = pc.ravel(order="C").tolist()
        struct["isTransposed"] = True
        schema = pa.schema([("pc", _SPARK_MATRIX), ("explainedVariance", _SPARK_VECTOR)])
        _write_spark_parquet(
            path,
            schema,
            [{"pc": struct, "explainedVariance": _vector_struct([0.9, 0.1])}],
            "{}",
        )
        model = PCAModel.load(path)
        np.testing.assert_allclose(model.pc, pc)

    def test_kmeans_model(self, tmp_path, rng):
        centers = rng.normal(size=(3, 4))
        path = str(tmp_path / "spark_kmeans")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.clustering.KMeansModel",
            "KMeansModel_abc",
            {"k": 3, "featuresCol": "features", "predictionCol": "prediction"},
        )
        schema = pa.schema(
            [("clusterIdx", pa.int32()), ("clusterCenter", _SPARK_VECTOR)]
        )
        _write_spark_parquet(
            path,
            schema,
            [
                {"clusterIdx": i, "clusterCenter": _vector_struct(c)}
                for i, c in enumerate(centers)
            ],
            "{}",
        )
        model = KMeansModel.load(path)
        np.testing.assert_allclose(model.clusterCenters(), centers)

    def test_linear_regression_model(self, tmp_path, rng):
        coef = rng.normal(size=6)
        path = str(tmp_path / "spark_lr")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.LinearRegressionModel",
            "LinearRegressionModel_q",
            {"featuresCol": "features", "labelCol": "label"},
        )
        schema = pa.schema(
            [("intercept", pa.float64()), ("coefficients", _SPARK_VECTOR)]
        )
        _write_spark_parquet(
            path,
            schema,
            [{"intercept": 2.5, "coefficients": _vector_struct(coef)}],
            "{}",
        )
        model = LinearRegressionModel.load(path)
        np.testing.assert_allclose(model.coefficients, coef)
        assert model.intercept == pytest.approx(2.5)

    def test_sparse_vector_struct(self, tmp_path):
        """Spark VectorUDT type=0 is sparse; loaders must densify it."""
        path = str(tmp_path / "spark_lr_sparse")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.LinearRegressionModel",
            "LinearRegressionModel_s",
            {},
        )
        schema = pa.schema(
            [("intercept", pa.float64()), ("coefficients", _SPARK_VECTOR)]
        )
        sparse = {"type": 0, "size": 5, "indices": [1, 3], "values": [2.0, -1.0]}
        _write_spark_parquet(
            path, schema, [{"intercept": 0.0, "coefficients": sparse}], "{}"
        )
        model = LinearRegressionModel.load(path)
        np.testing.assert_allclose(model.coefficients, [0.0, 2.0, 0.0, -1.0, 0.0])


class TestWrittenFormatIsSparkShaped:
    """The reverse direction: what this framework writes must be exactly
    the structural schema Spark's readers parse."""

    def test_pca_written_schema(self, tmp_path, rng):
        x = rng.normal(size=(50, 4))
        model = PCA().setK(2).fit(x)
        path = str(tmp_path / "ours")
        model.write.overwrite().save(path)

        # metadata: single-line JSON with DefaultParamsReader's keys.
        with open(os.path.join(path, "metadata", "part-00000")) as f:
            lines = f.read().splitlines()
        assert len(lines) == 1
        meta = json.loads(lines[0])
        for key in ("class", "timestamp", "sparkVersion", "uid", "paramMap", "defaultParamMap"):
            assert key in meta, key
        assert meta["class"].endswith("PCAModel")
        assert os.path.exists(os.path.join(path, "metadata", "_SUCCESS"))

        # data: parquet whose struct fields match MatrixUDT/VectorUDT
        # name-for-name, type-for-type.
        files = [
            f
            for f in os.listdir(os.path.join(path, "data"))
            if f.endswith(".parquet")
        ]
        assert files
        table = pq.read_table(os.path.join(path, "data", files[0]))
        assert table.num_rows == 1
        assert table.schema.field("pc").type == _SPARK_MATRIX
        assert table.schema.field("explainedVariance").type == _SPARK_VECTOR
        assert os.path.exists(os.path.join(path, "data", "_SUCCESS"))

    def test_roundtrip_through_spark_shape(self, tmp_path, rng):
        """Write with our writer, re-read the raw structs as a Spark reader
        would (column-major values + struct fields), and compare."""
        x = rng.normal(size=(60, 5)) * np.linspace(1, 2, 5)
        model = PCA().setK(3).fit(x)
        path = str(tmp_path / "ours_rt")
        model.write.overwrite().save(path)
        files = [
            f
            for f in os.listdir(os.path.join(path, "data"))
            if f.endswith(".parquet")
        ]
        row = pq.read_table(os.path.join(path, "data", files[0])).to_pylist()[0]
        pc_struct = row["pc"]
        pc = np.asarray(pc_struct["values"]).reshape(
            pc_struct["numCols"], pc_struct["numRows"]
        ).T  # column-major, as Spark's DenseMatrix stores
        np.testing.assert_allclose(pc, model.pc)
