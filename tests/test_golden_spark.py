"""Golden-file cross-compat: model directories in the EXACT shape upstream
Spark writes them must load through this framework, and directories this
framework writes must carry the exact structural schema Spark reads.

No pyspark/JVM exists in this image, so the golden directories are
byte-constructed here from Spark's documented on-disk contract
(DefaultParamsWriter metadata JSON + snappy parquet with Spark's
row-metadata key and MatrixUDT/VectorUDT structs — RapidsPCA.scala:218-254,
SURVEY §3.4 "must keep this exact on-disk format"): Spark-style part file
names, sparkVersion stamps, JVM class names, and nullable struct fields.
"""

import json
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from spark_rapids_ml_tpu.classification import (  # noqa: E402
    LogisticRegressionModel,
    RandomForestClassificationModel,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.clustering import KMeansModel  # noqa: E402
from spark_rapids_ml_tpu.feature import PCA, PCAModel  # noqa: E402
from spark_rapids_ml_tpu.regression import (  # noqa: E402
    LinearRegressionModel,
    RandomForestRegressionModel,
    RandomForestRegressor,
)

# Spark's MatrixUDT / VectorUDT arrow-side schemas, nullable like Spark's.
_SPARK_MATRIX = pa.struct(
    [
        ("type", pa.int8()),
        ("numRows", pa.int32()),
        ("numCols", pa.int32()),
        ("colPtrs", pa.list_(pa.int32())),
        ("rowIndices", pa.list_(pa.int32())),
        ("values", pa.list_(pa.float64())),
        ("isTransposed", pa.bool_()),
    ]
)
_SPARK_VECTOR = pa.struct(
    [
        ("type", pa.int8()),
        ("size", pa.int32()),
        ("indices", pa.list_(pa.int32())),
        ("values", pa.list_(pa.float64())),
    ]
)


def _write_spark_metadata(path, class_name, uid, param_map, default_map=None):
    """DefaultParamsWriter.saveMetadata byte shape: single JSON line in
    metadata/part-00000 + empty _SUCCESS."""
    meta_dir = os.path.join(path, "metadata")
    os.makedirs(meta_dir)
    payload = {
        "class": class_name,
        "timestamp": 1714456800000,
        "sparkVersion": "3.5.1",
        "uid": uid,
        "paramMap": param_map,
        "defaultParamMap": default_map or {},
    }
    with open(os.path.join(meta_dir, "part-00000"), "w") as f:
        f.write(json.dumps(payload) + "\n")
    open(os.path.join(meta_dir, "_SUCCESS"), "w").close()


def _write_spark_parquet(path, schema, rows, spark_schema_json, parts=1):
    """Spark executor part-file shape: snappy parquet named
    part-0000N-<uuid>-c000.snappy.parquet with Spark's row-metadata keys.

    ``parts > 1`` splits ``rows`` round-robin across that many part
    files — the multi-task layout a genuine distributed write produces
    (a part may come out EMPTY, exactly like a Spark task that owned no
    rows)."""
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir)
    chunks = [rows[i::parts] for i in range(parts)]
    for n, chunk in enumerate(chunks):
        arrays = [
            pa.array([r[name] for r in chunk], type=schema.field(name).type)
            for name in schema.names
        ]
        table = pa.Table.from_arrays(arrays, schema=schema).replace_schema_metadata(
            {
                "org.apache.spark.version": "3.5.1",
                "org.apache.spark.sql.parquet.row.metadata": spark_schema_json,
            }
        )
        pq.write_table(
            table,
            os.path.join(
                data_dir,
                f"part-{n:05d}-2fc4f2c3-0d5e-4a52-9b3e-77a312345678"
                "-c000.snappy.parquet",
            ),
            compression="snappy",
        )
    open(os.path.join(data_dir, "_SUCCESS"), "w").close()


def _matrix_struct(m):
    m = np.asarray(m, dtype=np.float64)
    return {
        "type": 1,
        "numRows": m.shape[0],
        "numCols": m.shape[1],
        "colPtrs": None,
        "rowIndices": None,
        "values": m.ravel(order="F").tolist(),
        "isTransposed": False,
    }


def _vector_struct(v):
    return {
        "type": 1,
        "size": len(v),
        "indices": None,
        "values": np.asarray(v, dtype=np.float64).tolist(),
    }


class TestLoadSparkWrittenModels:
    def test_pca_model(self, tmp_path, rng):
        pc = rng.normal(size=(5, 2))
        ev = np.array([0.7, 0.2])
        path = str(tmp_path / "spark_pca")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.feature.PCAModel",
            "PCAModel_4b1c2d3e4f50",
            {"k": 2, "inputCol": "features", "outputCol": "pca"},
        )
        schema = pa.schema([("pc", _SPARK_MATRIX), ("explainedVariance", _SPARK_VECTOR)])
        _write_spark_parquet(
            path,
            schema,
            [{"pc": _matrix_struct(pc), "explainedVariance": _vector_struct(ev)}],
            '{"type":"struct","fields":[{"name":"pc","type":{"type":"udt",'
            '"class":"org.apache.spark.ml.linalg.MatrixUDT"},"nullable":true,'
            '"metadata":{}},{"name":"explainedVariance","type":{"type":"udt",'
            '"class":"org.apache.spark.ml.linalg.VectorUDT"},"nullable":true,'
            '"metadata":{}}]}',
        )

        model = PCAModel.load(path)
        np.testing.assert_allclose(model.pc, pc)
        np.testing.assert_allclose(model.explainedVariance, ev)
        assert model.getK() == 2
        assert model.getInputCol() == "features"
        # And it transforms.
        out = model.transform(rng.normal(size=(10, 5)))
        assert out.shape == (10, 2)

    def test_pca_model_is_transposed_layout(self, tmp_path, rng):
        """Spark may store matrices row-major (isTransposed=True)."""
        pc = rng.normal(size=(4, 2))
        path = str(tmp_path / "spark_pca_t")
        os.makedirs(path)
        _write_spark_metadata(
            path, "org.apache.spark.ml.feature.PCAModel", "PCAModel_x", {"k": 2}
        )
        struct = _matrix_struct(pc)
        struct["values"] = pc.ravel(order="C").tolist()
        struct["isTransposed"] = True
        schema = pa.schema([("pc", _SPARK_MATRIX), ("explainedVariance", _SPARK_VECTOR)])
        _write_spark_parquet(
            path,
            schema,
            [{"pc": struct, "explainedVariance": _vector_struct([0.9, 0.1])}],
            "{}",
        )
        model = PCAModel.load(path)
        np.testing.assert_allclose(model.pc, pc)

    def test_kmeans_model(self, tmp_path, rng):
        centers = rng.normal(size=(3, 4))
        path = str(tmp_path / "spark_kmeans")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.clustering.KMeansModel",
            "KMeansModel_abc",
            {"k": 3, "featuresCol": "features", "predictionCol": "prediction"},
        )
        schema = pa.schema(
            [("clusterIdx", pa.int32()), ("clusterCenter", _SPARK_VECTOR)]
        )
        _write_spark_parquet(
            path,
            schema,
            [
                {"clusterIdx": i, "clusterCenter": _vector_struct(c)}
                for i, c in enumerate(centers)
            ],
            "{}",
        )
        model = KMeansModel.load(path)
        np.testing.assert_allclose(model.clusterCenters(), centers)

    def test_linear_regression_model(self, tmp_path, rng):
        coef = rng.normal(size=6)
        path = str(tmp_path / "spark_lr")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.LinearRegressionModel",
            "LinearRegressionModel_q",
            {"featuresCol": "features", "labelCol": "label"},
        )
        schema = pa.schema(
            [("intercept", pa.float64()), ("coefficients", _SPARK_VECTOR)]
        )
        _write_spark_parquet(
            path,
            schema,
            [{"intercept": 2.5, "coefficients": _vector_struct(coef)}],
            "{}",
        )
        model = LinearRegressionModel.load(path)
        np.testing.assert_allclose(model.coefficients, coef)
        assert model.intercept == pytest.approx(2.5)

    def test_sparse_vector_struct(self, tmp_path):
        """Spark VectorUDT type=0 is sparse; loaders must densify it."""
        path = str(tmp_path / "spark_lr_sparse")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.LinearRegressionModel",
            "LinearRegressionModel_s",
            {},
        )
        schema = pa.schema(
            [("intercept", pa.float64()), ("coefficients", _SPARK_VECTOR)]
        )
        sparse = {"type": 0, "size": 5, "indices": [1, 3], "values": [2.0, -1.0]}
        _write_spark_parquet(
            path, schema, [{"intercept": 0.0, "coefficients": sparse}], "{}"
        )
        model = LinearRegressionModel.load(path)
        np.testing.assert_allclose(model.coefficients, [0.0, 2.0, 0.0, -1.0, 0.0])


def _node(nid, pred, imp, stats, raw, gain=-1.0, left=-1, right=-1,
          feat=-1, thr=None):
    """Spark NodeData dict (leaf by default; pass children for a split)."""
    return {
        "id": nid,
        "prediction": float(pred),
        "impurity": float(imp),
        "impurityStats": [float(s) for s in stats],
        "rawCount": int(raw),
        "gain": float(gain),
        "leftChild": left,
        "rightChild": right,
        "split": {
            "featureIndex": feat,
            "leftCategoriesOrThreshold": [] if thr is None else [float(thr)],
            "numCategories": -1,
        },
    }


def _nodedata_schema():
    split_t = pa.struct(
        [
            ("featureIndex", pa.int32()),
            ("leftCategoriesOrThreshold", pa.list_(pa.float64())),
            ("numCategories", pa.int32()),
        ]
    )
    node_t = pa.struct(
        [
            ("id", pa.int32()),
            ("prediction", pa.float64()),
            ("impurity", pa.float64()),
            ("impurityStats", pa.list_(pa.float64())),
            ("rawCount", pa.int64()),
            ("gain", pa.float64()),
            ("leftChild", pa.int32()),
            ("rightChild", pa.int32()),
            ("split", split_t),
        ]
    )
    return pa.schema([("treeID", pa.int32()), ("nodeData", node_t)])


class TestLoadSparkWrittenForests:
    """Spark's EnsembleModelReadWrite on-disk shape (treeID + NodeData
    struct rows, preorder ids, explicit child pointers, leaf sentinels)
    must load into the heap-array Forest and predict correctly
    (VERDICT r4 #6 — the RF families joined the golden suite in r5)."""

    def test_rf_classifier_golden(self, tmp_path, rng):
        path = str(tmp_path / "spark_rfc")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.classification.RandomForestClassificationModel",
            "RandomForestClassificationModel_g",
            {"numTrees": 2, "featuresCol": "features"},
        )
        # Tree 0: split on feature 0 at 0.5 -> class-count leaves;
        # tree 1: a single root leaf (50/50).
        rows = [
            (0, _node(0, 1.0, 0.495, [9, 11], 20, gain=0.3, left=1, right=2,
                      feat=0, thr=0.5)),
            (0, _node(1, 0.0, 0.32, [8, 2], 10)),
            (0, _node(2, 1.0, 0.18, [1, 9], 10)),
            (1, _node(0, 0.0, 0.5, [5, 5], 10)),
        ]
        schema = _nodedata_schema()
        _write_spark_parquet(
            path,
            schema,
            [{"treeID": t, "nodeData": nd} for t, nd in rows],
            "{}",
        )
        model = RandomForestClassificationModel.load(path)
        probs = model.predictProbability(
            np.array([[0.0, 0.0], [1.0, 0.0]], dtype=np.float64)
        )
        # Mean of tree leaf distributions: ((.8,.2)+(.5,.5))/2, ((.1,.9)+(.5,.5))/2
        np.testing.assert_allclose(probs, [[0.65, 0.35], [0.3, 0.7]], atol=1e-6)
        preds = np.asarray(
            model.predict(np.array([[0.0, 0.0], [1.0, 0.0]], dtype=np.float64))
        )
        np.testing.assert_array_equal(preds, [0, 1])
        assert model.totalNumNodes == 4

    def test_rf_regressor_golden(self, tmp_path):
        path = str(tmp_path / "spark_rfr")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.RandomForestRegressionModel",
            "RandomForestRegressionModel_g",
            {"numTrees": 1},
        )
        # Variance stats [count, sum, sumSq]; prediction = mean.
        rows = [
            (0, _node(0, 0.8, 2.1, [10, 8, 30.0], 10, gain=1.5, left=1,
                      right=2, feat=1, thr=0.0)),
            (0, _node(1, -1.0, 0.1, [4, -4.0, 4.4], 4)),
            (0, _node(2, 2.0, 0.1, [6, 12.0, 24.6], 6)),
        ]
        _write_spark_parquet(
            path,
            _nodedata_schema(),
            [{"treeID": t, "nodeData": nd} for t, nd in rows],
            "{}",
        )
        model = RandomForestRegressionModel.load(path)
        pred = model.predict(np.array([[0.0, -1.0], [0.0, 1.0]], dtype=np.float64))
        np.testing.assert_allclose(pred, [-1.0, 2.0], atol=1e-6)

    def test_rf_classifier_multipart_golden(self, tmp_path):
        """A genuine Spark-written model dir has one part file PER WRITE
        TASK; NodeData split across two parts (tree 1 entirely in
        part-00001) must load every tree — the pre-r6 reader took only
        ``parquets[0]`` and silently dropped the rest of the forest
        (ROADMAP 5a / ADVICE.md medium)."""
        rows = [
            (0, _node(0, 1.0, 0.495, [9, 11], 20, gain=0.3, left=1, right=2,
                      feat=0, thr=0.5)),
            (0, _node(1, 0.0, 0.32, [8, 2], 10)),
            (0, _node(2, 1.0, 0.18, [1, 9], 10)),
            (1, _node(0, 0.0, 0.5, [5, 5], 10)),
        ]
        expected = {}
        for parts in (1, 2):
            path = str(tmp_path / f"spark_rfc_p{parts}")
            os.makedirs(path)
            _write_spark_metadata(
                path,
                "org.apache.spark.ml.classification."
                "RandomForestClassificationModel",
                "RandomForestClassificationModel_mp",
                {"numTrees": 2, "featuresCol": "features"},
            )
            # Round-robin with parts=2 puts tree 0's nodes in part-00000
            # and tree 1's single root in part-00001.
            ordered = [rows[0], rows[3], rows[1], rows[2]]
            _write_spark_parquet(
                path,
                _nodedata_schema(),
                [{"treeID": t, "nodeData": nd} for t, nd in ordered],
                "{}",
                parts=parts,
            )
            model = RandomForestClassificationModel.load(path)
            assert model.totalNumNodes == 4, f"parts={parts} lost nodes"
            expected[parts] = np.asarray(
                model.predictProbability(
                    np.array([[0.0, 0.0], [1.0, 0.0]], dtype=np.float64)
                )
            )
        # The split layout decodes to the identical forest.
        np.testing.assert_allclose(expected[2], expected[1])
        np.testing.assert_allclose(expected[2], [[0.65, 0.35], [0.3, 0.7]],
                                   atol=1e-6)

    def test_single_row_model_with_empty_leading_part(self, tmp_path):
        """Spark tasks that owned no rows still write a part file; the
        model row may therefore live in part-00001 behind an EMPTY
        part-00000. load_data must read past the empty part."""
        path = str(tmp_path / "spark_lr_empty_part")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.LinearRegressionModel",
            "LinearRegressionModel_ep",
            {},
        )
        schema = pa.schema(
            [("intercept", pa.float64()), ("coefficients", _SPARK_VECTOR)]
        )
        row = {"intercept": 1.5, "coefficients": _vector_struct([2.0, -1.0])}
        _write_spark_parquet(path, schema, [], "{}")  # empty part-00000
        data_dir = os.path.join(path, "data")
        arrays = [
            pa.array([row[name]], type=schema.field(name).type)
            for name in schema.names
        ]
        pq.write_table(
            pa.Table.from_arrays(arrays, schema=schema),
            os.path.join(data_dir, "part-00001-aaaa-c000.snappy.parquet"),
            compression="snappy",
        )
        model = LinearRegressionModel.load(path)
        assert model.intercept == 1.5
        np.testing.assert_allclose(model.coefficients, [2.0, -1.0])

    def test_legacy_flattened_forest_layout_loads(self, tmp_path):
        """Pre-r5 model directories (the flattened treeID/nodeID scalar
        columns) must still load (code-review r5: the Spark-schema
        rewrite must not strand existing checkpoints)."""
        from spark_rapids_ml_tpu.core.persistence import save_metadata, save_rows

        path = str(tmp_path / "legacy_rf")
        shell = RandomForestClassificationModel()
        save_metadata(
            shell,
            path,
            class_name=(
                "org.apache.spark.ml.classification."
                "RandomForestClassificationModel"
            ),
            extra_metadata={"numFeatures": 1, "numClasses": 2},
        )
        # One depth-1 tree: root splits feature 0 at 0.5.
        save_rows(
            path,
            {
                "treeID": ("scalar", [0, 0, 0]),
                "nodeID": ("scalar", [0, 1, 2]),
                "feature": ("scalar", [0, -1, -1]),
                "threshold": ("scalar", [0.5, 0.0, 0.0]),
                "isLeaf": ("scalar", [False, True, True]),
                "leafValue": ("vector", [[0.5, 0.5], [0.8, 0.2], [0.1, 0.9]]),
                "nodeWeight": ("scalar", [20.0, 10.0, 10.0]),
                "nodeGain": ("scalar", [0.3, 0.0, 0.0]),
            },
        )
        model = RandomForestClassificationModel.load(path)
        probs = model.predictProbability(np.array([[0.0], [1.0]]))
        np.testing.assert_allclose(probs, [[0.8, 0.2], [0.1, 0.9]], atol=1e-6)

    def test_logistic_regression_golden(self, tmp_path, rng):
        coef = rng.normal(size=4)
        path = str(tmp_path / "spark_logreg")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.classification.LogisticRegressionModel",
            "LogisticRegressionModel_g",
            {"featuresCol": "features", "threshold": 0.5},
        )
        schema = pa.schema(
            [
                ("numClasses", pa.int32()),
                ("numFeatures", pa.int32()),
                ("interceptVector", _SPARK_VECTOR),
                ("coefficientMatrix", _SPARK_MATRIX),
                ("isMultinomial", pa.bool_()),
            ]
        )
        _write_spark_parquet(
            path,
            schema,
            [
                {
                    "numClasses": 2,
                    "numFeatures": 4,
                    "interceptVector": _vector_struct([0.25]),
                    "coefficientMatrix": _matrix_struct(coef[None, :]),
                    "isMultinomial": False,
                }
            ],
            "{}",
        )
        model = LogisticRegressionModel.load(path)
        np.testing.assert_allclose(model.coefficients, coef)
        assert model.intercept == pytest.approx(0.25)
        x = rng.normal(size=(5, 4))
        expect = 1.0 / (1.0 + np.exp(-(x @ coef + 0.25)))
        np.testing.assert_allclose(
            model.predictProbability(x)[:, 1], expect, atol=1e-6
        )

    def test_logistic_regression_multinomial_golden(self, tmp_path, rng):
        cm = rng.normal(size=(3, 4))  # (numClasses, d), Spark orientation
        iv = rng.normal(size=3)
        path = str(tmp_path / "spark_logreg_mn")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.classification.LogisticRegressionModel",
            "LogisticRegressionModel_mn",
            {},
        )
        schema = pa.schema(
            [
                ("numClasses", pa.int32()),
                ("numFeatures", pa.int32()),
                ("interceptVector", _SPARK_VECTOR),
                ("coefficientMatrix", _SPARK_MATRIX),
                ("isMultinomial", pa.bool_()),
            ]
        )
        _write_spark_parquet(
            path,
            schema,
            [
                {
                    "numClasses": 3,
                    "numFeatures": 4,
                    "interceptVector": _vector_struct(iv),
                    "coefficientMatrix": _matrix_struct(cm),
                    "isMultinomial": True,
                }
            ],
            "{}",
        )
        model = LogisticRegressionModel.load(path)
        np.testing.assert_allclose(model.coefficientMatrix, cm)
        np.testing.assert_allclose(model.interceptVector, iv)
        x = rng.normal(size=(6, 4))
        z = x @ cm.T + iv
        expect = np.exp(z) / np.exp(z).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(model.predictProbability(x), expect, atol=1e-6)


class TestCompositeGoldenLayouts:
    """Upstream Spark's COMPOSITE writers (Pipeline.SharedReadWrite,
    CrossValidatorModel) record no python class paths: ``stageUids``
    lives inside ``paramMap``, stage type information exists only as
    each nested directory's own JVM metadata class, and the winning
    model sits bare under ``bestModel/``. Directories byte-constructed
    in that exact shape must load here (ROADMAP item 5c)."""

    def _golden_pca_stage(self, path, pc, ev, uid="PCAModel_stage0"):
        os.makedirs(path)
        _write_spark_metadata(
            path, "org.apache.spark.ml.feature.PCAModel", uid, {"k": pc.shape[1]}
        )
        schema = pa.schema(
            [("pc", _SPARK_MATRIX), ("explainedVariance", _SPARK_VECTOR)]
        )
        _write_spark_parquet(
            path,
            schema,
            [{"pc": _matrix_struct(pc), "explainedVariance": _vector_struct(ev)}],
            "{}",
        )

    def _golden_linreg_stage(self, path, coef, intercept, uid="LinearRegressionModel_stage1"):
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.regression.LinearRegressionModel",
            uid,
            {},
        )
        schema = pa.schema(
            [("intercept", pa.float64()), ("coefficients", _SPARK_VECTOR)]
        )
        _write_spark_parquet(
            path,
            schema,
            [{"intercept": float(intercept), "coefficients": _vector_struct(coef)}],
            "{}",
        )

    def test_pipeline_model_golden(self, tmp_path, rng):
        """A Spark-written PipelineModel dir — paramMap.stageUids, no
        stageClasses, JVM class names in the stage metadata — loads and
        transforms end to end."""
        from spark_rapids_ml_tpu.pipeline import PipelineModel

        pc = rng.normal(size=(5, 2))
        ev = np.array([0.7, 0.2])
        coef = rng.normal(size=2)
        path = str(tmp_path / "spark_pipeline")
        os.makedirs(path)
        uids = ["PCAModel_stage0", "LinearRegressionModel_stage1"]
        # Spark's SharedReadWrite: stageUids INSIDE paramMap, nothing else.
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.PipelineModel",
            "PipelineModel_golden",
            {"stageUids": uids},
        )
        self._golden_pca_stage(
            os.path.join(path, "stages", f"0_{uids[0]}"), pc, ev, uid=uids[0]
        )
        self._golden_linreg_stage(
            os.path.join(path, "stages", f"1_{uids[1]}"), coef, 1.5, uid=uids[1]
        )

        model = PipelineModel.load(path)
        assert len(model.stages) == 2
        x = rng.normal(size=(8, 5))
        out = np.asarray(model.transform(x))
        # PCA projection then the linear head, exactly as Spark composes.
        np.testing.assert_allclose(out, x @ pc @ coef + 1.5, atol=1e-6)

    def test_pipeline_model_roundtrip_ours(self, tmp_path, rng):
        """Our own writer's layout keeps loading too (stageClasses path),
        and the written metadata carries the stage bookkeeping Spark's
        reader keys on."""
        from spark_rapids_ml_tpu.pipeline import PipelineModel
        from spark_rapids_ml_tpu.regression import LinearRegression

        x = rng.normal(size=(60, 5))
        pca_model = PCA().setK(3).fit(x)
        y = np.asarray(pca_model.transform(x)) @ rng.normal(size=3) + 2.0
        lr_model = LinearRegression().fit((np.asarray(pca_model.transform(x)), y))
        model = PipelineModel(None, [pca_model, lr_model])
        path = str(tmp_path / "ours_pipeline")
        model.write.overwrite().save(path)
        with open(os.path.join(path, "metadata", "part-00000")) as f:
            meta = json.loads(f.readline())
        assert meta["stageUids"] == [s.uid for s in model.stages]
        assert len(meta["stageClasses"]) == 2

        loaded = PipelineModel.load(path)
        np.testing.assert_allclose(
            np.asarray(loaded.transform(x)), np.asarray(model.transform(x)),
            atol=1e-6,
        )

    def test_cross_validator_model_golden(self, tmp_path, rng):
        """A Spark-written CrossValidatorModel dir — avgMetrics in the
        metadata, the winner bare under bestModel/ with only its JVM
        class — loads with metrics intact and a servable bestModel."""
        from spark_rapids_ml_tpu.tuning import CrossValidatorModel

        coef = rng.normal(size=4)
        path = str(tmp_path / "spark_cv")
        os.makedirs(path)
        _write_spark_metadata(
            path,
            "org.apache.spark.ml.tuning.CrossValidatorModel",
            "CrossValidatorModel_golden",
            {"numFolds": 3},
        )
        # avgMetrics land top-level (Spark's extraMetadata), not in paramMap.
        meta_file = os.path.join(path, "metadata", "part-00000")
        with open(meta_file) as f:
            meta = json.loads(f.readline())
        meta["avgMetrics"] = [0.81, 0.93, 0.77]
        meta["bestIndex"] = 1
        with open(meta_file, "w") as f:
            f.write(json.dumps(meta) + "\n")

        best = os.path.join(path, "bestModel")
        os.makedirs(best)
        _write_spark_metadata(
            best,
            "org.apache.spark.ml.classification.LogisticRegressionModel",
            "LogisticRegressionModel_best",
            {"threshold": 0.5},
        )
        schema = pa.schema(
            [
                ("numClasses", pa.int32()),
                ("numFeatures", pa.int32()),
                ("interceptVector", _SPARK_VECTOR),
                ("coefficientMatrix", _SPARK_MATRIX),
                ("isMultinomial", pa.bool_()),
            ]
        )
        _write_spark_parquet(
            best,
            schema,
            [
                {
                    "numClasses": 2,
                    "numFeatures": 4,
                    "interceptVector": _vector_struct([0.25]),
                    "coefficientMatrix": _matrix_struct(coef[None, :]),
                    "isMultinomial": False,
                }
            ],
            "{}",
        )

        model = CrossValidatorModel.load(path)
        assert model.avgMetrics == [0.81, 0.93, 0.77]
        assert model.bestIndex == 1
        np.testing.assert_allclose(model.bestModel.coefficients, coef)
        x = rng.normal(size=(6, 4))
        expect = 1.0 / (1.0 + np.exp(-(x @ coef + 0.25)))
        np.testing.assert_allclose(
            model.bestModel.predictProbability(x)[:, 1], expect, atol=1e-6
        )

    def test_cross_validator_model_roundtrip_ours(self, tmp_path, rng):
        """write -> load through our own layout: metrics, bestIndex, and
        bit-equal bestModel predictions survive."""
        from spark_rapids_ml_tpu.classification import LogisticRegression
        from spark_rapids_ml_tpu.tuning import CrossValidatorModel

        x = rng.normal(size=(80, 3))
        y = (x[:, 0] > 0).astype(float)
        best = LogisticRegression().setMaxIter(40).fit((x, y))
        model = CrossValidatorModel(
            None, best, avgMetrics=[0.5, 0.9], bestIndex=1
        )
        path = str(tmp_path / "ours_cv")
        model.write.overwrite().save(path)
        loaded = CrossValidatorModel.load(path)
        assert loaded.avgMetrics == [0.5, 0.9]
        assert loaded.bestIndex == 1
        np.testing.assert_allclose(
            loaded.bestModel.predictProbability(x),
            best.predictProbability(x),
            atol=1e-8,
        )


class TestWrittenFormatIsSparkShaped:
    """The reverse direction: what this framework writes must be exactly
    the structural schema Spark's readers parse."""

    def test_pca_written_schema(self, tmp_path, rng):
        x = rng.normal(size=(50, 4))
        model = PCA().setK(2).fit(x)
        path = str(tmp_path / "ours")
        model.write.overwrite().save(path)

        # metadata: single-line JSON with DefaultParamsReader's keys.
        with open(os.path.join(path, "metadata", "part-00000")) as f:
            lines = f.read().splitlines()
        assert len(lines) == 1
        meta = json.loads(lines[0])
        for key in ("class", "timestamp", "sparkVersion", "uid", "paramMap", "defaultParamMap"):
            assert key in meta, key
        assert meta["class"].endswith("PCAModel")
        assert os.path.exists(os.path.join(path, "metadata", "_SUCCESS"))

        # data: parquet whose struct fields match MatrixUDT/VectorUDT
        # name-for-name, type-for-type.
        files = [
            f
            for f in os.listdir(os.path.join(path, "data"))
            if f.endswith(".parquet")
        ]
        assert files
        table = pq.read_table(os.path.join(path, "data", files[0]))
        assert table.num_rows == 1
        assert table.schema.field("pc").type == _SPARK_MATRIX
        assert table.schema.field("explainedVariance").type == _SPARK_VECTOR
        assert os.path.exists(os.path.join(path, "data", "_SUCCESS"))

    def test_rf_written_schema_and_roundtrip(self, tmp_path, rng):
        """Forests persist in Spark's EnsembleModelReadWrite shape:
        (treeID, nodeData struct) rows + treesMetadata, and round-trip to
        identical predictions."""
        x = rng.normal(size=(150, 5))
        y = ((x[:, 0] + x[:, 2]) > 0).astype(float)
        model = (
            RandomForestClassifier().setNumTrees(4).setMaxDepth(3).setSeed(1)
            .fit((x, y))
        )
        path = str(tmp_path / "ours_rfc")
        model.write.overwrite().save(path)

        files = [
            f for f in os.listdir(os.path.join(path, "data"))
            if f.endswith(".parquet")
        ]
        table = pq.read_table(os.path.join(path, "data", files[0]))
        assert table.schema.equals(_nodedata_schema()), table.schema
        # Leaf sentinels and preorder roots, as Spark writes them.
        first = table.to_pylist()[0]
        assert first["nodeData"]["id"] == 0
        leaves = [
            r["nodeData"] for r in table.to_pylist()
            if r["nodeData"]["leftChild"] < 0
        ]
        assert leaves and all(nd["gain"] == -1.0 for nd in leaves)
        assert all(nd["split"]["featureIndex"] == -1 for nd in leaves)
        # treesMetadata: one row per tree with uniform weights.
        tm_files = [
            f for f in os.listdir(os.path.join(path, "treesMetadata"))
            if f.endswith(".parquet")
        ]
        tm = pq.read_table(os.path.join(path, "treesMetadata", tm_files[0]))
        assert tm.column_names == ["treeID", "metadata", "weights"]
        assert tm.num_rows == 4

        loaded = RandomForestClassificationModel.load(path)
        np.testing.assert_allclose(
            loaded.predictProbability(x), model.predictProbability(x), atol=1e-6
        )
        np.testing.assert_allclose(
            loaded.featureImportances, model.featureImportances, atol=1e-6
        )

    def test_rf_regressor_roundtrip_exact(self, tmp_path, rng):
        """Regression round trip: the variance-triplet encoding must be
        lossless (sumSq reconstructed from the stored node impurity)."""
        x = rng.normal(size=(120, 4))
        y = 2.0 * x[:, 0] - x[:, 3] + 0.1 * rng.normal(size=120) + 5.0
        model = (
            RandomForestRegressor().setNumTrees(3).setMaxDepth(3).setSeed(2)
            .fit((x, y))
        )
        path = str(tmp_path / "ours_rfr")
        model.write.overwrite().save(path)
        loaded = RandomForestRegressionModel.load(path)
        np.testing.assert_allclose(loaded.predict(x), model.predict(x), atol=1e-5)
        f0, f1 = model._forest, loaded._forest
        np.testing.assert_allclose(
            np.asarray(f1.node_impurity), np.asarray(f0.node_impurity), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(f1.node_weight), np.asarray(f0.node_weight), atol=1e-5
        )

    def test_logreg_written_schema(self, tmp_path, rng):
        x = rng.normal(size=(100, 3))
        y = (x[:, 0] > 0).astype(float)
        from spark_rapids_ml_tpu.classification import LogisticRegression

        model = LogisticRegression().setMaxIter(30).fit((x, y))
        path = str(tmp_path / "ours_lr")
        model.write.overwrite().save(path)
        files = [
            f for f in os.listdir(os.path.join(path, "data"))
            if f.endswith(".parquet")
        ]
        table = pq.read_table(os.path.join(path, "data", files[0]))
        assert table.schema.field("coefficientMatrix").type == _SPARK_MATRIX
        assert table.schema.field("interceptVector").type == _SPARK_VECTOR
        row = table.to_pylist()[0]
        assert row["numClasses"] == 2
        assert row["numFeatures"] == 3
        assert row["isMultinomial"] is False
        loaded = LogisticRegressionModel.load(path)
        np.testing.assert_allclose(
            loaded.predictProbability(x), model.predictProbability(x), atol=1e-8
        )

    def test_roundtrip_through_spark_shape(self, tmp_path, rng):
        """Write with our writer, re-read the raw structs as a Spark reader
        would (column-major values + struct fields), and compare."""
        x = rng.normal(size=(60, 5)) * np.linspace(1, 2, 5)
        model = PCA().setK(3).fit(x)
        path = str(tmp_path / "ours_rt")
        model.write.overwrite().save(path)
        files = [
            f
            for f in os.listdir(os.path.join(path, "data"))
            if f.endswith(".parquet")
        ]
        row = pq.read_table(os.path.join(path, "data", files[0])).to_pylist()[0]
        pc_struct = row["pc"]
        pc = np.asarray(pc_struct["values"]).reshape(
            pc_struct["numCols"], pc_struct["numRows"]
        ).T  # column-major, as Spark's DenseMatrix stores
        np.testing.assert_allclose(pc, model.pc)
