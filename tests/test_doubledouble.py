"""Double-float extended-precision GEMM tests.

Run with f32 hardware semantics (x64 disabled inside the ops; the oracle is
host numpy fp64). The headline assertion: double-float accumulation beats
plain f32-HIGHEST by orders of magnitude on long contractions.
"""

import numpy as np

import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.doubledouble import (
    centered_gram_dd,
    covariance_dd,
    dd_to_f64,
    matmul_dd,
    split_f64,
)


def test_split_roundtrip(rng):
    """hi+lo carries ~48 mantissa bits of the f64 input (2^-48 ≈ 4e-15)."""
    x = rng.normal(size=(50, 10)) * 1e3
    hi, lo = split_f64(x)
    np.testing.assert_allclose(
        hi.astype(np.float64) + lo.astype(np.float64), x, rtol=1e-14
    )


def test_matmul_dd_error_flat_in_k(rng):
    """The contract: dd relative error sits at the f32-eps floor and does
    NOT grow with contraction length (plain f32 accumulation does)."""
    errs = {}
    for k in (1_000, 100_000):
        a = rng.normal(size=(8, k))
        b = rng.normal(size=(k, 8))
        exact = a @ b
        a_hi, a_lo = split_f64(a)
        b_hi, b_lo = split_f64(b)
        hi, lo = matmul_dd(
            jnp.asarray(a_hi), jnp.asarray(a_lo), jnp.asarray(b_hi), jnp.asarray(b_lo), chunk=512
        )
        scale = np.abs(a).max() * np.abs(b).max() * np.sqrt(k)
        errs[k] = np.abs(dd_to_f64(hi, lo) - exact).max() / scale
    assert errs[1_000] < 1e-7
    assert errs[100_000] < 1e-7  # no growth with K


def test_matmul_dd_beats_f32_on_positive_sums(rng):
    """Positive accumulation (Gram-diagonal-like) is where plain f32 loses
    digits linearly; dd must win by >= 10x at K=200k."""
    k = 200_000
    a = np.abs(rng.normal(size=(4, k)))
    b = np.abs(rng.normal(size=(k, 4)))
    exact = a @ b
    a_hi, a_lo = split_f64(a)
    b_hi, b_lo = split_f64(b)
    hi, lo = matmul_dd(
        jnp.asarray(a_hi), jnp.asarray(a_lo), jnp.asarray(b_hi), jnp.asarray(b_lo), chunk=512
    )
    dd_rel = np.abs((dd_to_f64(hi, lo) - exact) / exact).max()
    f32_rel = np.abs(
        ((a.astype(np.float32) @ b.astype(np.float32)).astype(np.float64) - exact) / exact
    ).max()
    assert dd_rel < 1e-7
    assert dd_rel < f32_rel / 10


def test_matmul_dd_k_not_chunk_multiple(rng):
    a = rng.normal(size=(4, 700))
    b = rng.normal(size=(700, 4))
    a_hi, a_lo = split_f64(a)
    b_hi, b_lo = split_f64(b)
    hi, lo = matmul_dd(jnp.asarray(a_hi), jnp.asarray(a_lo), jnp.asarray(b_hi), jnp.asarray(b_lo), chunk=256)
    exact = a @ b
    # error is relative to the MATRIX scale (norm-wise), not per-element
    np.testing.assert_allclose(dd_to_f64(hi, lo), exact, atol=1e-6 * np.abs(exact).max())


def test_centered_gram_dd(rng):
    x = rng.normal(size=(5000, 16)) + 100.0  # offset stresses centering
    mean = x.mean(0)
    exact = (x - mean).T @ (x - mean)
    got = centered_gram_dd(x, mean, chunk=1024)
    np.testing.assert_allclose(got, exact, rtol=1e-6, atol=1e-6 * np.abs(exact).max())


def test_covariance_dd_meets_reference_bar(rng):
    """The reference oracle bar: 1e-5 absolute vs fp64 — dd clears it by
    orders of magnitude even where plain f32 would not."""
    x = rng.normal(size=(30_000, 8)) * 1e-2 + 50.0
    mean, cov = covariance_dd(x, chunk=4096)
    exact = np.cov(x, rowvar=False)
    # reference bar is 1e-5 ABSOLUTE; dd lands ~5 orders below it
    assert np.abs(cov - exact).max() < 1e-10
