"""Tuning + evaluation tests — CrossValidator / TrainValidationSplit over
real estimators, evaluator metrics vs sklearn oracles."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.regression import LinearRegression
from spark_rapids_ml_tpu.classification import RandomForestClassifier
from spark_rapids_ml_tpu.tuning import (
    CrossValidator,
    ParamGridBuilder,
    TrainValidationSplit,
)


class TestEvaluators:
    def test_regression_metrics(self):
        y = np.array([1.0, 2.0, 3.0, 4.0])
        p = np.array([1.1, 1.9, 3.2, 3.8])
        ev = RegressionEvaluator()
        assert ev.evaluate((y, p)) == pytest.approx(np.sqrt(np.mean((y - p) ** 2)))
        assert ev.setMetricName("mae").evaluate((y, p)) == pytest.approx(
            np.mean(np.abs(y - p))
        )
        r2 = ev.setMetricName("r2").evaluate((y, p))
        sklearn_metrics = pytest.importorskip("sklearn.metrics")
        assert r2 == pytest.approx(sklearn_metrics.r2_score(y, p))
        assert ev.isLargerBetter()
        assert not ev.setMetricName("rmse").isLargerBetter()

    def test_multiclass_metrics(self):
        sklearn_metrics = pytest.importorskip("sklearn.metrics")
        rng = np.random.default_rng(0)
        y = rng.integers(0, 3, 200).astype(float)
        p = np.where(rng.uniform(size=200) < 0.7, y, rng.integers(0, 3, 200)).astype(float)
        ev = MulticlassClassificationEvaluator()
        # Spark's default metric is f1 (weighted), not accuracy.
        assert ev.getMetricName() == "f1"
        assert ev.evaluate((y, p)) == pytest.approx(
            sklearn_metrics.f1_score(y, p, average="weighted")
        )
        assert ev.setMetricName("accuracy").evaluate((y, p)) == pytest.approx(
            np.mean(y == p)
        )
        assert ev.setMetricName("f1").evaluate((y, p)) == pytest.approx(
            sklearn_metrics.f1_score(y, p, average="weighted")
        )
        assert ev.setMetricName("weightedPrecision").evaluate((y, p)) == pytest.approx(
            sklearn_metrics.precision_score(y, p, average="weighted")
        )
        assert ev.setMetricName("weightedRecall").evaluate((y, p)) == pytest.approx(
            sklearn_metrics.recall_score(y, p, average="weighted")
        )

    def test_binary_auc(self):
        sklearn_metrics = pytest.importorskip("sklearn.metrics")
        rng = np.random.default_rng(1)
        y = rng.integers(0, 2, 300).astype(float)
        s = y * 0.5 + rng.normal(size=300)
        ev = BinaryClassificationEvaluator()
        assert ev.evaluate((y, s)) == pytest.approx(
            sklearn_metrics.roc_auc_score(y, s), abs=1e-9
        )
        pr = ev.setMetricName("areaUnderPR").evaluate((y, s))
        # Trapezoidal PR-AUC differs slightly from sklearn's step-wise AP.
        assert pr == pytest.approx(sklearn_metrics.average_precision_score(y, s), abs=0.02)

    def test_binary_auc_vector_raw(self):
        # Vector-valued rawPrediction column: positive class = last component.
        y = [0.0, 1.0, 1.0, 0.0]
        raw = [np.array([0.8, 0.2]), np.array([0.1, 0.9]),
               np.array([0.3, 0.7]), np.array([0.6, 0.4])]
        df = DataFrame({"label": y, "rawPrediction": raw})
        assert BinaryClassificationEvaluator().evaluate(df) == 1.0

    def test_binary_auc_ties(self):
        sklearn_metrics = pytest.importorskip("sklearn.metrics")
        # All-tied scores: AUC must be exactly 0.5 regardless of row order.
        y = np.array([1.0, 0.0, 1.0, 0.0])
        s = np.full(4, 0.5)
        assert BinaryClassificationEvaluator().evaluate((y, s)) == pytest.approx(0.5)
        # Mixed ties agree with sklearn's tie-grouped AUC.
        rng = np.random.default_rng(3)
        y2 = rng.integers(0, 2, 100).astype(float)
        s2 = np.round(y2 * 0.5 + rng.normal(size=100), 1)  # heavy ties
        assert BinaryClassificationEvaluator().evaluate((y2, s2)) == pytest.approx(
            sklearn_metrics.roc_auc_score(y2, s2), abs=1e-9
        )

    def test_degenerate_single_class(self):
        assert BinaryClassificationEvaluator().evaluate(
            (np.ones(5), np.arange(5.0))
        ) == 0.0


class TestParamGridBuilder:
    def test_cartesian_product(self):
        lr = LinearRegression()
        grid = (
            ParamGridBuilder()
            .addGrid(lr.regParam, [0.0, 0.1, 1.0])
            .addGrid(lr.fitIntercept, [True, False])
            .build()
        )
        assert len(grid) == 6
        assert {pm[lr.regParam] for pm in grid} == {0.0, 0.1, 1.0}

    def test_base_on(self):
        lr = LinearRegression()
        grid = (
            ParamGridBuilder()
            .baseOn({lr.fitIntercept: False})
            .addGrid(lr.regParam, [0.0, 0.5])
            .build()
        )
        assert len(grid) == 2
        assert all(pm[lr.fitIntercept] is False for pm in grid)


def _ridge_data(rng, n=120, d=5):
    x = rng.normal(size=(n, d))
    beta = np.arange(1, d + 1, dtype=float)
    y = x @ beta + 0.1 * rng.normal(size=n)
    return x, y


class TestCrossValidator:
    def test_selects_low_regularization(self, rng):
        # True model is linear and nearly noiseless: heavy L2 must lose.
        x, y = _ridge_data(rng)
        lr = LinearRegression()
        grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 100.0]).build()
        cv = (
            CrossValidator()
            .setEstimator(lr)
            .setEstimatorParamMaps(grid)
            .setEvaluator(RegressionEvaluator())
            .setNumFolds(3)
            .setSeed(0)
        )
        model = cv.fit((x, y))
        assert model.bestIndex == 0
        assert len(model.avgMetrics) == 2
        assert model.avgMetrics[0] < model.avgMetrics[1]
        # Best model was refit on the full data and predicts well.
        preds = model.transform(x)
        assert np.sqrt(np.mean((preds - y) ** 2)) < 0.2

    def test_classifier_grid_dataframe(self, rng):
        x = rng.normal(size=(150, 4))
        y = (x[:, 0] + x[:, 1] > 0).astype(float)
        df = DataFrame({"features": list(x), "label": list(y)})
        rf = RandomForestClassifier().setNumTrees(5)
        grid = ParamGridBuilder().addGrid(rf.maxDepth, [1, 4]).build()
        cv = (
            CrossValidator()
            .setEstimator(rf)
            .setEstimatorParamMaps(grid)
            .setEvaluator(MulticlassClassificationEvaluator())
            .setNumFolds(3)
            .setSeed(1)
        )
        model = cv.fit(df)
        # Depth 4 beats a decision stump on a 2-feature interaction.
        assert model.bestIndex == 1
        out = model.transform(df)
        acc = np.mean(np.asarray(out.select("prediction")) == y)
        assert acc > 0.9

    def test_model_persistence_roundtrip(self, tmp_path, rng):
        from spark_rapids_ml_tpu.tuning import CrossValidatorModel

        x, y = _ridge_data(rng)
        lr = LinearRegression()
        grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 1.0]).build()
        model = (
            CrossValidator()
            .setEstimator(lr)
            .setEstimatorParamMaps(grid)
            .setEvaluator(RegressionEvaluator())
            .setSeed(0)
            .fit((x, y))
        )
        path = str(tmp_path / "cvm")
        model.save(path)
        loaded = CrossValidatorModel.load(path)
        assert loaded.bestIndex == model.bestIndex
        np.testing.assert_allclose(loaded.avgMetrics, model.avgMetrics)
        np.testing.assert_allclose(loaded.transform(x), model.transform(x), atol=1e-10)

    def test_binary_evaluator_gets_scores_not_labels(self, rng):
        """AUC on a tuple dataset must rank by continuous probabilities —
        hard 0/1 labels would tie whole grid cells (ADVICE r1, medium)."""
        from spark_rapids_ml_tpu.classification import LogisticRegression
        from spark_rapids_ml_tpu.tuning import _eval_dataset

        x = rng.normal(size=(200, 4))
        y = (x[:, 0] + 0.5 * x[:, 1] + 0.3 * rng.normal(size=200) > 0).astype(float)
        model = LogisticRegression().setMaxIter(50).fit((x, y))
        ev = BinaryClassificationEvaluator()
        y_out, scores = _eval_dataset(model, (x, y), ev)
        # Scores are continuous probabilities, not a handful of hard labels.
        assert len(np.unique(scores)) > 10
        np.testing.assert_array_equal(y_out, y)
        auc_scores = ev.evaluate((y_out, scores))
        auc_labels = ev.evaluate((y, model.predict(x).astype(float)))
        # Probability ranking must dominate the degenerate two-point ROC.
        assert auc_scores >= auc_labels
        assert auc_scores > 0.9

    def test_binary_evaluator_rejects_scoreless_model(self, rng):
        from spark_rapids_ml_tpu.tuning import _eval_dataset

        x, y = _ridge_data(rng)
        model = LinearRegression().fit((x, y))
        with pytest.raises(TypeError, match="predictProbability"):
            _eval_dataset(model, (x, y), BinaryClassificationEvaluator())

    def test_copy_preserves_mesh(self):
        rf = RandomForestClassifier(mesh="sentinel-mesh")
        assert rf.copy({}).mesh == "sentinel-mesh"

    def test_validation_errors(self):
        cv = CrossValidator()
        with pytest.raises(ValueError):
            cv.fit((np.zeros((10, 2)), np.zeros(10)))
        with pytest.raises(ValueError):
            CrossValidator().setNumFolds(1)
        lr = LinearRegression()
        cv = (
            CrossValidator()
            .setEstimator(lr)
            .setEstimatorParamMaps([{}])
            .setEvaluator(RegressionEvaluator())
            .setNumFolds(5)
        )
        with pytest.raises(ValueError):
            cv.fit((np.zeros((3, 2)), np.zeros(3)))


class TestTrainValidationSplit:
    def test_selects_best(self, rng):
        x, y = _ridge_data(rng)
        lr = LinearRegression()
        grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 100.0]).build()
        tvs = (
            TrainValidationSplit()
            .setEstimator(lr)
            .setEstimatorParamMaps(grid)
            .setEvaluator(RegressionEvaluator())
            .setTrainRatio(0.7)
            .setSeed(2)
        )
        model = tvs.fit((x, y))
        assert model.bestIndex == 0
        assert len(model.validationMetrics) == 2

    def test_ratio_validation(self):
        with pytest.raises(ValueError):
            TrainValidationSplit().setTrainRatio(1.0)
        with pytest.raises(ValueError):
            TrainValidationSplit().setTrainRatio(0.0)
