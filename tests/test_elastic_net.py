"""Elastic-net solvers — oracle is scikit-learn's coordinate descent/liblinear.

With ``standardization=False`` the objectives match sklearn's exactly:
  linear:   1/(2n)||y - Xb - b0||^2 + reg*(alpha*||b||_1 + (1-alpha)/2*||b||^2)
  logistic: (1/n) sum logloss + reg*(alpha*||w||_1 + (1-alpha)/2*||w||^2)
so fitted coefficients must agree to optimization tolerance.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.ops.linear import normal_eq_stats, solve_elastic_net
from spark_rapids_ml_tpu.regression import LinearRegression


def _sparse_problem(rng, n=300, d=12, informative=4, noise=0.05):
    x = rng.normal(size=(n, d))
    beta = np.zeros(d)
    beta[:informative] = np.array([3.0, -2.0, 1.5, 1.0])[:informative]
    y = x @ beta + 2.0 + noise * rng.normal(size=n)
    return x, y, beta


class TestLinearElasticNet:
    def test_lasso_matches_sklearn(self, rng):
        linear_model = pytest.importorskip("sklearn.linear_model")
        x, y, _ = _sparse_problem(rng)
        reg = 0.1
        stats = normal_eq_stats(jnp.asarray(x), jnp.asarray(y), jnp.ones(len(y)))
        coef, intercept, n_iter = solve_elastic_net(
            *stats[:4], stats[5], reg_param=reg, elastic_net_param=1.0,
            standardization=False,
        )
        skl = linear_model.Lasso(alpha=reg, max_iter=50_000, tol=1e-10).fit(x, y)
        np.testing.assert_allclose(np.asarray(coef), skl.coef_, atol=1e-4)
        assert abs(float(intercept) - skl.intercept_) < 1e-4

    def test_elastic_net_matches_sklearn(self, rng):
        linear_model = pytest.importorskip("sklearn.linear_model")
        x, y, _ = _sparse_problem(rng, n=400, d=10)
        reg, l1_ratio = 0.2, 0.5
        stats = normal_eq_stats(jnp.asarray(x), jnp.asarray(y), jnp.ones(len(y)))
        coef, intercept, _ = solve_elastic_net(
            *stats[:4], stats[5], reg_param=reg, elastic_net_param=l1_ratio,
            standardization=False,
        )
        skl = linear_model.ElasticNet(
            alpha=reg, l1_ratio=l1_ratio, max_iter=50_000, tol=1e-10
        ).fit(x, y)
        np.testing.assert_allclose(np.asarray(coef), skl.coef_, atol=1e-4)

    def test_alpha_zero_equals_ridge(self, rng):
        from spark_rapids_ml_tpu.ops.linear import solve_normal

        x, y, _ = _sparse_problem(rng)
        stats = normal_eq_stats(jnp.asarray(x), jnp.asarray(y), jnp.ones(len(y)))
        c_enet, i_enet, _ = solve_elastic_net(
            *stats[:4], stats[5], reg_param=0.3, elastic_net_param=0.0,
        )
        c_ridge, i_ridge = solve_normal(*stats[:4], stats[5], reg_param=0.3)
        np.testing.assert_allclose(np.asarray(c_enet), np.asarray(c_ridge), atol=1e-5)
        assert abs(float(i_enet) - float(i_ridge)) < 1e-5

    def test_l1_produces_sparsity(self, rng):
        x, y, beta = _sparse_problem(rng, d=20, informative=3)
        model = (
            LinearRegression()
            .setRegParam(0.5)
            .setElasticNetParam(1.0)
            .setStandardization(False)
            .fit((x, y))
        )
        coef = model.coefficients
        # Noise features must be zeroed; informative ones survive.
        assert np.sum(np.abs(coef) > 1e-6) <= 6
        assert np.all(np.abs(coef[:3]) > 0.1)

    def test_estimator_path_no_intercept(self, rng):
        linear_model = pytest.importorskip("sklearn.linear_model")
        x, y, _ = _sparse_problem(rng)
        model = (
            LinearRegression()
            .setRegParam(0.1)
            .setElasticNetParam(1.0)
            .setFitIntercept(False)
            .setStandardization(False)
            .fit((x, y))
        )
        skl = linear_model.Lasso(
            alpha=0.1, fit_intercept=False, max_iter=50_000, tol=1e-10
        ).fit(x, y)
        np.testing.assert_allclose(model.coefficients, skl.coef_, atol=1e-4)
        assert model.intercept == 0.0

    def test_param_validation(self):
        with pytest.raises(ValueError):
            LinearRegression().setElasticNetParam(1.5)
        with pytest.raises(ValueError):
            LogisticRegression().setElasticNetParam(-0.1)

    def test_normal_solver_rejects_l1(self, rng):
        x, y, _ = _sparse_problem(rng)
        with pytest.raises(ValueError, match="solver='normal'"):
            (
                LinearRegression()
                .setSolver("normal")
                .setElasticNetParam(0.5)
                .setRegParam(0.1)
                .fit((x, y))
            )

    def test_zero_regparam_uses_exact_solve(self, rng):
        # enet > 0 with regParam == 0 is a zero penalty: must match the
        # exact unregularized solve, not a proximal approximation of it.
        x, y, _ = _sparse_problem(rng)
        m_enet = LinearRegression().setElasticNetParam(0.7).fit((x, y))
        m_ols = LinearRegression().fit((x, y))
        np.testing.assert_allclose(m_enet.coefficients, m_ols.coefficients, atol=1e-12)


class TestLogisticElasticNet:
    def test_l1_matches_sklearn(self, rng):
        linear_model = pytest.importorskip("sklearn.linear_model")
        x = rng.normal(size=(500, 8))
        logits = 2.0 * x[:, 0] - 1.5 * x[:, 1] + 0.5
        y = (rng.uniform(size=500) < 1 / (1 + np.exp(-logits))).astype(float)
        n, reg = len(y), 0.02
        model = (
            LogisticRegression()
            .setRegParam(reg)
            .setElasticNetParam(1.0)
            .setStandardization(False)
            .setMaxIter(3000)
            .setTol(1e-10)
            .fit((x, y))
        )
        # sklearn: min ||w||_1 + C sum logloss  <=>  ours with reg = 1/(C n).
        # saga, not liblinear: liblinear penalizes the intercept. penalty
        # must be EXPLICIT: without it sklearn keeps the default l2 and
        # silently ignores l1_ratio — the oracle would be a different
        # optimization problem.
        skl = linear_model.LogisticRegression(
            penalty="elasticnet", l1_ratio=1.0, C=1.0 / (reg * n),
            solver="saga", tol=1e-12, max_iter=100_000,
        ).fit(x, y)
        np.testing.assert_allclose(
            model.coefficients, skl.coef_.ravel(), atol=1e-4
        )
        assert abs(model.intercept - skl.intercept_[0]) < 1e-4

    def test_l1_sparsity_and_accuracy(self, rng):
        x = rng.normal(size=(400, 15))
        y = (x[:, 0] + x[:, 1] > 0).astype(float)
        model = (
            LogisticRegression()
            .setRegParam(0.05)
            .setElasticNetParam(1.0)
            .setMaxIter(2000)
            .fit((x, y))
        )
        coef = model.coefficients
        assert np.sum(np.abs(coef) > 1e-5) <= 6  # noise features pruned
        assert np.mean(model.predict(x) == y) > 0.9

    def test_multinomial_elastic_net(self, rng):
        x = rng.normal(size=(450, 6))
        y = np.argmax(x[:, :3], axis=1).astype(float)
        model = (
            LogisticRegression()
            .setRegParam(0.01)
            .setElasticNetParam(0.5)
            .setFamily("multinomial")
            .setMaxIter(2000)
            .fit((x, y))
        )
        assert np.mean(model.predict(x) == y) > 0.85
        assert model.coefficientMatrix.shape == (3, 6)
