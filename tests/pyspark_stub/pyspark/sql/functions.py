"""pyspark.sql.functions subset."""

from __future__ import annotations

import functools

from pyspark.sql import Column


def col(name: str) -> Column:
    return Column("ref", name=name)


def lit(value) -> Column:
    def constant(series):
        import pandas as pd

        return pd.Series([value] * len(series), dtype=object)

    return Column("udf", name="lit", fn=constant, args=[Column("ref", name="__first__")])


def pandas_udf(returnType):
    def decorate(fn):
        @functools.wraps(fn)
        def apply(*cols):
            return Column("udf", name=fn.__name__, fn=fn, args=cols)

        apply.__is_pandas_udf__ = True
        return apply

    return decorate
