"""Local pyspark.sql stand-in: Row / Column expressions / DataFrame /
SparkSession over partitioned Python lists, with the RDD ops the adapter
uses (map / first / take / mapPartitions / treeReduce / toLocalIterator)
running the adapter's own callables through a pickle round-trip, like a
real cluster would."""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

from pyspark import _pickle_roundtrip


class Row(tuple):
    """Named tuple-alike: field access by name or index."""

    def __new__(cls, fields: Sequence[str], values: Sequence[Any]):
        row = super().__new__(cls, values)
        row._fields = list(fields)
        return row

    def __getattr__(self, name):
        try:
            return self[self._fields.index(name)]
        except ValueError as e:
            raise AttributeError(name) from e

    def asDict(self):
        return dict(zip(self._fields, self))


class Column:
    """Expression node: a column reference or a function of columns."""

    def __init__(self, kind: str, name: str = "", fn: Callable = None, args=None):
        self.kind = kind  # "ref" | "udf"
        self.name = name
        self.fn = fn
        self.args = list(args or [])


# Driver-fetch instrumentation for the no-full-collect contract tests:
# every row that crosses executor->driver through a row-materializing op
# (collect / toLocalIterator / take / takeSample) is counted here. Reset
# with FETCHED_ROWS.clear(); treeReduce is NOT counted — merged
# accumulators are the point of the distributed paths.
FETCHED_ROWS = {"rows": 0}


def _count_fetch(n: int) -> None:
    FETCHED_ROWS["rows"] = FETCHED_ROWS.get("rows", 0) + n


class RDD:
    def __init__(self, partitions: List[list]):
        self._parts = [list(p) for p in partitions]

    def map(self, f) -> "RDD":
        f = _pickle_roundtrip(f)
        return RDD([[f(x) for x in p] for p in self._parts])

    def mapPartitions(self, f) -> "RDD":
        f = _pickle_roundtrip(f)
        return RDD([list(f(iter(p))) for p in self._parts])

    def mapPartitionsWithIndex(self, f) -> "RDD":
        """pyspark 3.5 RDD.mapPartitionsWithIndex: ``f(index, iterator)``
        with the partition's ordinal as the first argument."""
        f = _pickle_roundtrip(f)
        return RDD([list(f(i, iter(p))) for i, p in enumerate(self._parts)])

    def sample(self, withReplacement: bool, fraction: float, seed: int = None) -> "RDD":
        """pyspark 3.5 RDD.sample: per-element Bernoulli(fraction) without
        replacement / Poisson(fraction) draws with replacement; the result
        size is random, NOT exactly fraction * count (documented pyspark
        behavior). Seeded per partition for determinism."""
        import numpy as _np

        base = 17 if seed is None else int(seed)
        out = []
        for i, p in enumerate(self._parts):
            rng = _np.random.default_rng((base << 16) ^ (i + 1))
            if withReplacement:
                counts = rng.poisson(fraction, len(p))
                out.append([x for x, c in zip(p, counts) for _ in range(c)])
            else:
                keep = rng.random(len(p)) < fraction
                out.append([x for x, k in zip(p, keep) if k])
        return RDD(out)

    def persist(self, *_) -> "RDD":
        return self  # local lists are already materialized

    def cache(self) -> "RDD":
        return self

    def unpersist(self, *_) -> "RDD":
        return self

    def first(self):
        for p in self._parts:
            if p:
                _count_fetch(1)  # first() materializes one row at the driver
                return p[0]
        raise ValueError("empty RDD")

    def take(self, n: int) -> list:
        out = []
        for p in self._parts:
            for x in p:
                if len(out) >= n:
                    _count_fetch(len(out))
                    return out
                out.append(x)
        _count_fetch(len(out))
        return out

    def takeSample(self, withReplacement: bool, num: int, seed: int = 0) -> list:
        """pyspark 3.5 RDD.takeSample: a UNIFORM draw of min(num, count)
        rows (without replacement). Only the sampled rows cross to the
        driver — Spark's implementation samples executor-side with an
        inflated Bernoulli fraction and retries until >= num arrive, so
        the driver fetch is O(num), never O(count); the fetch counter
        reflects that."""
        import numpy as _np

        all_rows = [x for p in self._parts for x in p]
        rng = _np.random.default_rng(seed)
        if not all_rows:
            return []
        idx = rng.choice(
            len(all_rows), size=min(num, len(all_rows)) if not withReplacement else num,
            replace=withReplacement,
        )
        out = [all_rows[i] for i in idx]
        _count_fetch(len(out))
        return out

    def collect(self) -> list:
        out = [x for p in self._parts for x in p]
        _count_fetch(len(out))
        return out

    def toLocalIterator(self):
        for p in self._parts:
            for x in p:
                _count_fetch(1)
                yield x

    def count(self) -> int:
        return sum(len(p) for p in self._parts)

    def treeReduce(self, op, depth: int = 2):
        op = _pickle_roundtrip(op)
        partials = []
        for p in self._parts:
            acc = None
            for x in p:
                # Values crossing the executor->driver boundary are
                # serialized on a real cluster.
                acc = x if acc is None else op(acc, x)
            if acc is not None:
                partials.append(_pickle_roundtrip(acc))
        if not partials:
            raise ValueError("empty RDD")
        acc = partials[0]
        for x in partials[1:]:
            acc = op(acc, x)
        return acc

    def treeAggregate(self, zeroValue, seqOp, combOp, depth: int = 2):
        """pyspark 3.5 RDD.treeAggregate(zeroValue, seqOp, combOp,
        depth=2): 'Aggregates the elements of this RDD in a multi-level
        tree pattern' — each partition folds from its OWN copy of
        zeroValue with seqOp, partials tree-merge with combOp, and an
        empty RDD returns zeroValue (unlike treeReduce, which raises
        ValueError('Cannot reduce() empty RDD')). Zero value and the ops
        cross the serialization boundary like any closure."""
        seqOp = _pickle_roundtrip(seqOp)
        combOp = _pickle_roundtrip(combOp)
        partials = []
        for p in self._parts:
            acc = _pickle_roundtrip(zeroValue)  # fresh copy per partition
            for x in p:
                acc = seqOp(acc, x)
            partials.append(_pickle_roundtrip(acc))
        if not partials:
            return zeroValue
        acc = partials[0]
        for x in partials[1:]:
            acc = combOp(acc, x)
        return acc

    def coalesce(self, numPartitions: int, shuffle: bool = False) -> "RDD":
        """pyspark 3.5 RDD.coalesce(numPartitions): shrink to at most
        numPartitions WITHOUT a shuffle — contiguous parent partitions
        merge executor-side (order preserved, no driver fetch); asking
        for more partitions than exist without shuffle=True keeps the
        current partitioning (documented pyspark behavior)."""
        n = max(1, int(numPartitions))
        if n >= len(self._parts) and not shuffle:
            return RDD(self._parts)
        groups: List[list] = [[] for _ in range(n)]
        for i, p in enumerate(self._parts):
            groups[i * n // len(self._parts)].extend(p)
        return RDD(groups)

    def getNumPartitions(self) -> int:
        return len(self._parts)

    def barrier(self) -> "RDDBarrier":
        """pyspark 3.5 RDD.barrier(): mark this stage for barrier
        execution — all tasks launch together and ANY task failure
        relaunches the WHOLE gang (stage-level retry, not per-task)."""
        return RDDBarrier(self)


# Spark's spark.stage.maxConsecutiveAttempts default: a barrier stage is
# retried as a unit at most this many times before the job fails.
BARRIER_MAX_ATTEMPTS = 4

# Gang-relaunch instrumentation for the failure-recovery contract tests:
# every (attempt, partition) task launch inside a barrier stage is
# recorded here. Reset with BARRIER_TASK_LAUNCHES.clear().
BARRIER_TASK_LAUNCHES: List[tuple] = []


class RDDBarrier:
    """pyspark.rdd.RDDBarrier: ``mapPartitions`` with barrier-stage
    semantics. The stub runs the gang sequentially, but retry semantics
    are Spark's barrier-scheduler ones: if ANY partition's task raises,
    results of the whole attempt are discarded and EVERY task relaunches
    from scratch (the relaunch-the-gang semantic a jax.distributed cohort
    needs — an individually retried task would rejoin a dead gang); after
    BARRIER_MAX_ATTEMPTS failed attempts the error propagates to the
    driver, like the reference's JNI-throw -> task-fail -> Spark-retry
    story (SURVEY §5; rapidsml_jni.cu:101-153 pattern) escalating to job
    failure."""

    def __init__(self, rdd: RDD):
        self._rdd = rdd

    def mapPartitions(self, f, preservesPartitioning: bool = False) -> RDD:
        from pyspark import BarrierTaskContext

        f = _pickle_roundtrip(f)
        n = len(self._rdd._parts)
        last_err = None
        for attempt in range(BARRIER_MAX_ATTEMPTS):
            out = []
            try:
                for i, p in enumerate(self._rdd._parts):
                    BARRIER_TASK_LAUNCHES.append((attempt, i))
                    BarrierTaskContext._current = BarrierTaskContext(i, n, attempt)
                    try:
                        out.append(list(f(iter(p))))
                    finally:
                        BarrierTaskContext._current = None
            except Exception as e:  # gang relaunch: discard, restart all
                last_err = e
                continue
            return RDD(out)
        raise last_err


def _arrow_series(values: list):
    """pyspark 3.5 pandas_udf input typing (the SQL Arrow serializer,
    ``pyspark.sql.pandas.serializers.ArrowStreamPandasUDFSerializer``):
    a ``double`` column arrives as a float64-dtype Series; an
    ``array<double>`` column arrives as an object-dtype Series whose
    ELEMENTS are numpy float64 ndarrays (never Python lists) — udf code
    that assumes list elements passes a naive stub and breaks on a real
    cluster, so the stub pins Arrow's actual typing."""
    import numpy as _np
    import pandas as pd

    if values and all(
        isinstance(v, (int, float, _np.integer, _np.floating))
        and not isinstance(v, bool)
        for v in values
    ):
        return pd.Series(_np.asarray(values, dtype=_np.float64))
    out = [
        _np.asarray(v, dtype=_np.float64)
        if isinstance(v, (list, tuple, _np.ndarray))
        else v
        for v in values
    ]
    return pd.Series(out, dtype=object)


class DataFrame:
    def __init__(self, schema: List[str], partitions: List[List[Row]]):
        self._schema = list(schema)
        self._parts = partitions

    @property
    def sparkSession(self) -> "SparkSession":
        """pyspark 3.5 DataFrame.sparkSession — the owning session (the
        stub's sessions are interchangeable singletons)."""
        return SparkSession()

    @property
    def columns(self) -> List[str]:
        return list(self._schema)

    @property
    def rdd(self) -> RDD:
        return RDD(self._parts)

    def count(self) -> int:
        return sum(len(p) for p in self._parts)

    def collect(self) -> List[Row]:
        return [r for p in self._parts for r in p]

    def select(self, *cols_) -> "DataFrame":
        names = [c if isinstance(c, str) else c.name for c in cols_]
        idx = [self._schema.index(n) for n in names]
        parts = [
            [Row(names, [r[i] for i in idx]) for r in p] for p in self._parts
        ]
        return DataFrame(names, parts)

    def _eval_column(self, column: Column, part: List[Row]) -> list:
        if column.kind == "ref":
            i = self._schema.index(column.name)
            return [r[i] for r in part]
        args = [
            _arrow_series(self._eval_column(a, part)) for a in column.args
        ]
        out = column.fn(*args)
        return list(out)

    def drop(self, *names) -> "DataFrame":
        keep = [c for c in self._schema if c not in names]
        return self.select(*keep)

    def withColumn(self, name: str, column: Column) -> "DataFrame":
        schema = self._schema + ([name] if name not in self._schema else [])
        parts = []
        for p in self._parts:
            vals = self._eval_column(column, p)
            rows = []
            for r, v in zip(p, vals):
                d = list(r)
                if name in self._schema:
                    d[self._schema.index(name)] = v
                    rows.append(Row(schema, d))
                else:
                    rows.append(Row(schema, d + [v]))
            parts.append(rows)
        return DataFrame(schema, parts)


class SparkSession:
    class Builder:
        def master(self, _):
            return self

        def appName(self, _):
            return self

        def config(self, *_, **__):
            return self

        def getOrCreate(self) -> "SparkSession":
            return SparkSession()

    builder = Builder()

    @property
    def sparkContext(self):
        from pyspark import _SC

        return _SC

    def createDataFrame(self, data, schema, numPartitions: int = 2) -> DataFrame:
        rows = [Row(schema, list(r)) for r in data]
        if not rows:
            return DataFrame(list(schema), [[]])
        per = max(1, -(-len(rows) // numPartitions))
        parts = [rows[i : i + per] for i in range(0, len(rows), per)]
        return DataFrame(list(schema), parts)

    def stop(self) -> None:
        pass
