"""pyspark.ml stand-in: Estimator/Model/Transformer with the _fit/_transform
dispatch contract."""

from __future__ import annotations

from pyspark.ml.param.shared import Params


class Transformer(Params):
    def transform(self, dataset):
        return self._transform(dataset)


class Estimator(Params):
    def fit(self, dataset):
        return self._fit(dataset)


class Model(Transformer):
    pass
