"""pyspark.ml.linalg subset: DenseVector / SparseVector / DenseMatrix /
Vectors with the toArray contracts the adapter relies on."""

from __future__ import annotations

import numpy as np


class DenseVector:
    def __init__(self, values):
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        return self.values

    def __len__(self) -> int:
        return len(self.values)

    def __repr__(self) -> str:
        return f"DenseVector({self.values.tolist()})"


class SparseVector:
    def __init__(self, size, indices, values):
        self.size = int(size)
        self.indices = np.asarray(indices, dtype=np.int32)
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        out = np.zeros(self.size, dtype=np.float64)
        out[self.indices] = self.values
        return out

    def __len__(self) -> int:
        return self.size


class DenseMatrix:
    """Column-major storage, like Spark's."""

    def __init__(self, numRows, numCols, values, isTransposed=False):
        self.numRows = int(numRows)
        self.numCols = int(numCols)
        self.values = np.asarray(values, dtype=np.float64)
        self.isTransposed = bool(isTransposed)

    def toArray(self) -> np.ndarray:
        order = "C" if self.isTransposed else "F"
        return self.values.reshape((self.numRows, self.numCols), order=order)


class Vectors:
    @staticmethod
    def dense(*values):
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            return DenseVector(values[0])
        return DenseVector(values)

    @staticmethod
    def sparse(size, indices, values):
        return SparseVector(size, indices, values)
