from pyspark.ml.param.shared import Param, Params, TypeConverters

__all__ = ["Param", "Params", "TypeConverters"]
