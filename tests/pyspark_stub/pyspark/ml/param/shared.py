"""pyspark.ml.param machinery subset: Param descriptors declared on the
class with ``Params._dummy()`` parents, per-instance value/default maps,
TypeConverters applied on ``_set``."""

from __future__ import annotations

import uuid
from typing import Any, Dict


class TypeConverters:
    @staticmethod
    def toInt(v) -> int:
        return int(v)

    @staticmethod
    def toFloat(v) -> float:
        return float(v)

    @staticmethod
    def toString(v) -> str:
        return str(v)

    @staticmethod
    def toBoolean(v) -> bool:
        if isinstance(v, bool):
            return v
        raise TypeError(f"Boolean Param requires value of type bool, got {v!r}")

    @staticmethod
    def toList(v) -> list:
        return list(v)

    @staticmethod
    def identity(v):
        return v


class Param:
    def __init__(self, parent, name: str, doc: str, typeConverter=None):
        self.parent = getattr(parent, "uid", parent)
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or TypeConverters.identity

    # Value semantics like real pyspark (param.py __eq__/__hash__ on
    # str(parent) + name): maps keyed by Param must survive pickling,
    # where keys are recreated as new objects.
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Param)
            and self.parent == other.parent
            and self.name == other.name
        )

    def __hash__(self) -> int:
        return hash(f"{self.parent}__{self.name}")

    def __repr__(self) -> str:
        return f"Param({self.parent}__{self.name})"


class Params:
    """Like pyspark, the value maps (`_paramMap` / `_defaultParamMap`) are
    keyed by the Param OBJECTS (shared class attributes), not by name —
    consumers such as persistence writers iterate `p.name for p in map`."""

    def __init__(self):
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}

    @classmethod
    def _dummy(cls) -> "Params":
        dummy = object.__new__(Params)
        dummy.uid = "undefined"
        return dummy

    def _params_by_name(self) -> Dict[str, Param]:
        out = {}
        for klass in type(self).__mro__:
            for name, value in vars(klass).items():
                if isinstance(value, Param) and name not in out:
                    out[name] = value
        return out

    def hasParam(self, name: str) -> bool:
        return name in self._params_by_name()

    def getParam(self, name: str) -> Param:
        try:
            return self._params_by_name()[name]
        except KeyError as e:
            raise AttributeError(f"no param {name}") from e

    def _resolve(self, param) -> Param:
        return param if isinstance(param, Param) else self.getParam(param)

    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            param = self.getParam(name)
            self._paramMap[param] = param.typeConverter(value)
        return self

    def _setDefault(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            param = self.getParam(name)
            self._defaultParamMap[param] = param.typeConverter(value)
        return self

    def isSet(self, param) -> bool:
        return self._resolve(param) in self._paramMap

    def isDefined(self, param) -> bool:
        p = self._resolve(param)
        return p in self._paramMap or p in self._defaultParamMap

    def getOrDefault(self, param):
        p = self._resolve(param)
        if p in self._paramMap:
            return self._paramMap[p]
        return self._defaultParamMap[p]
