"""pyspark.ml.param machinery subset: Param descriptors declared on the
class with ``Params._dummy()`` parents, per-instance value/default maps,
TypeConverters applied on ``_set``."""

from __future__ import annotations

import uuid
from typing import Any, Dict


class TypeConverters:
    @staticmethod
    def toInt(v) -> int:
        return int(v)

    @staticmethod
    def toFloat(v) -> float:
        return float(v)

    @staticmethod
    def toString(v) -> str:
        return str(v)

    @staticmethod
    def toBoolean(v) -> bool:
        if isinstance(v, bool):
            return v
        raise TypeError(f"Boolean Param requires value of type bool, got {v!r}")

    @staticmethod
    def toList(v) -> list:
        return list(v)

    @staticmethod
    def identity(v):
        return v


class Param:
    def __init__(self, parent, name: str, doc: str, typeConverter=None):
        self.parent = getattr(parent, "uid", parent)
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or TypeConverters.identity

    # Value semantics like real pyspark (param.py __eq__/__hash__ on
    # str(parent) + name): maps keyed by Param must survive pickling,
    # where keys are recreated as new objects.
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Param)
            and self.parent == other.parent
            and self.name == other.name
        )

    def __hash__(self) -> int:
        return hash(f"{self.parent}__{self.name}")

    def __repr__(self) -> str:
        return f"Param({self.parent}__{self.name})"


class Params:
    """Like pyspark, the value maps (`_paramMap` / `_defaultParamMap`) are
    keyed by the Param OBJECTS, not by name — consumers such as
    persistence writers iterate `p.name for p in map`.

    Pinned to pyspark 3.5 ``pyspark/ml/param/__init__.py`` semantics
    (VERDICT r2 #5a): ``Params.__init__`` COPIES every class-level Param
    onto the instance with ``parent = self.uid`` (``_copy_params``), so
    ``TpuPCA().k is not TpuPCA.k`` and ``param.parent == instance.uid`` —
    adapter code that assumed shared class-level Param identity would
    pass a naive stub and break on a real cluster. Param equality stays
    VALUE equality on (parent, name) (pyspark's ``__eq__``/``__hash__``
    on ``str(parent) + name``), which is what makes pickled maps work.
    """

    def __init__(self):
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        self._copy_params()

    def _copy_params(self) -> None:
        """pyspark 3.5 Params.__init__ behavior: instance-owned copies of
        the class-level Param declarations (parent = this uid)."""
        for name, cls_param in self._class_params().items():
            setattr(
                self,
                name,
                Param(self, cls_param.name, cls_param.doc, cls_param.typeConverter),
            )

    @classmethod
    def _dummy(cls) -> "Params":
        dummy = object.__new__(Params)
        dummy.uid = "undefined"
        return dummy

    @classmethod
    def _class_params(cls) -> Dict[str, Param]:
        out = {}
        for klass in cls.__mro__:
            for name, value in vars(klass).items():
                if isinstance(value, Param) and name not in out:
                    out[name] = value
        return out

    def _params_by_name(self) -> Dict[str, Param]:
        # Instance-owned params (getattr resolves the per-instance copy).
        return {
            name: getattr(self, name) for name in self._class_params()
        }

    def hasParam(self, name: str) -> bool:
        return name in self._class_params()

    def getParam(self, name: str) -> Param:
        try:
            return self._params_by_name()[name]
        except KeyError as e:
            raise AttributeError(f"no param {name}") from e

    def _shouldOwn(self, param: "Param") -> None:
        """pyspark 3.5 Params._shouldOwn: 'Validates that the input param
        belongs to this Params instance' — parent must equal this uid."""
        if not (param.parent == self.uid and self.hasParam(param.name)):
            raise ValueError(f"Param {param} does not belong to {self.uid}.")

    def _resolveParam(self, param) -> Param:
        """pyspark 3.5 Params._resolveParam: a Param is ownership-checked
        and resolved to the INSTANCE copy; a string goes through
        getParam; anything else is a TypeError."""
        if isinstance(param, Param):
            self._shouldOwn(param)
            return getattr(self, param.name)
        if isinstance(param, str):
            return self.getParam(param)
        raise TypeError(f"Cannot resolve {param!r} as a param.")

    def _resolve(self, param) -> Param:
        return self._resolveParam(param)

    def _resetUid(self, newUid: str) -> "Params":
        """pyspark 3.5 Params._resetUid: 'Changes the uid of this
        instance. This updates both the stored uid and the parent uid of
        params and param maps' — the maps must be REBUILT because Param
        hash/equality include the parent. DefaultParamsReader restores a
        persisted uid through this, never by assigning ``.uid``."""
        newUid = str(newUid)
        self.uid = newUid
        new_default: Dict[Param, Any] = {}
        new_map: Dict[Param, Any] = {}
        for name, param in self._params_by_name().items():
            new_param = Param(self, param.name, param.doc, param.typeConverter)
            if param in self._defaultParamMap:
                new_default[new_param] = self._defaultParamMap[param]
            if param in self._paramMap:
                new_map[new_param] = self._paramMap[param]
            setattr(self, name, new_param)
        self._defaultParamMap = new_default
        self._paramMap = new_map
        return self

    def _set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            param = self.getParam(name)
            self._paramMap[param] = param.typeConverter(value)
        return self

    def _setDefault(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            param = self.getParam(name)
            self._defaultParamMap[param] = param.typeConverter(value)
        return self

    def isSet(self, param) -> bool:
        return self._resolve(param) in self._paramMap

    def isDefined(self, param) -> bool:
        p = self._resolve(param)
        return p in self._paramMap or p in self._defaultParamMap

    def getOrDefault(self, param):
        p = self._resolve(param)
        if p in self._paramMap:
            return self._paramMap[p]
        return self._defaultParamMap[p]
