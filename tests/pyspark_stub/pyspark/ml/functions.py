"""pyspark.ml.functions subset: vector_to_array / array_to_vector as
column expressions."""

from __future__ import annotations

import numpy as np

from pyspark.sql import Column
from pyspark.ml.linalg import DenseVector


def vector_to_array(column: Column, dtype: str = "float64") -> Column:
    def convert(series):
        import pandas as pd

        return pd.Series(
            [np.asarray(v.toArray(), dtype=np.float64) for v in series],
            dtype=object,
        )

    return Column("udf", name="vector_to_array", fn=convert, args=[column])


def array_to_vector(column: Column) -> Column:
    def convert(series):
        import pandas as pd

        return pd.Series(
            [DenseVector(np.asarray(v, dtype=np.float64)) for v in series],
            dtype=object,
        )

    return Column("udf", name="array_to_vector", fn=convert, args=[column])
