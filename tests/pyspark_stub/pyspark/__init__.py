"""Test-only pyspark API stub (contract-testing shim).

The CI image has no pyspark, so the adapter layer
(``spark_rapids_ml_tpu.spark.adapter``) could never execute (VERDICT r1
missing item 1 / weak item 1). This package implements the EXACT surface
the adapter consumes — local, single-process, but with real partition
semantics (mapPartitions / treeReduce run the same callables Spark would
ship to executors, including a pickle round-trip to catch closure bugs) —
so the adapter's code paths run for real under pytest.

It deliberately mirrors pyspark's public API shapes (keyword_only,
Params._dummy(), TypeConverters, Estimator._fit / Model._transform,
pandas_udf columns) rather than inventing friendlier ones: drift against
these shapes is exactly what the tests exist to catch.
"""

from __future__ import annotations

import functools
import pickle
from typing import Optional


def keyword_only(func):
    """pyspark.keyword_only: capture the kwargs of a method call into
    ``self._input_kwargs`` (positional args are disallowed)."""

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        if args:
            raise TypeError(
                f"Method {func.__name__} forces keyword arguments."
            )
        self._input_kwargs = kwargs
        return func(self, **kwargs)

    return wrapper


class TaskContext:
    """Driver-side stand-in: no task context outside executor code."""

    @staticmethod
    def get() -> Optional["TaskContext"]:
        return None


def _pickle_roundtrip(obj):
    """Simulate the executor serialization boundary: every function and
    accumulator the adapter hands to an RDD op must survive serialization,
    as it would on a real cluster. Spark serializes closures with
    cloudpickle, so the stub does too (falling back to stdlib pickle)."""
    try:
        import cloudpickle as _cp

        return _cp.loads(_cp.dumps(obj))
    except ImportError:  # pragma: no cover
        return pickle.loads(pickle.dumps(obj))


# Torrent-broadcast analogue: values serialize ONCE at broadcast() time
# (counted, for the one-serialization contract tests); the Broadcast
# handle that rides task closures pickles as a registry id only —
# exactly the cost model of Spark's TorrentBroadcast.
import itertools as _itertools

_BROADCAST_REGISTRY = {}
_BROADCAST_IDS = _itertools.count()  # monotonic: destroy() must not free ids
BROADCAST_VALUE_PICKLES = {"count": 0}


def _broadcast_from_id(bid: int) -> "Broadcast":
    b = Broadcast.__new__(Broadcast)
    b._bid = bid
    return b


class Broadcast:
    """pyspark.broadcast.Broadcast: read-only shared variable, one
    serialization per broadcast, ``.value`` on executors."""

    def __init__(self, value):
        bid = next(_BROADCAST_IDS)
        BROADCAST_VALUE_PICKLES["count"] += 1
        _BROADCAST_REGISTRY[bid] = _pickle_roundtrip(value)
        self._bid = bid

    @property
    def value(self):
        return _BROADCAST_REGISTRY[self._bid]

    def __reduce__(self):
        # Task closures ship the HANDLE, never the value.
        return (_broadcast_from_id, (self._bid,))

    def unpersist(self, blocking: bool = False) -> None:
        pass

    def destroy(self, blocking: bool = False) -> None:
        _BROADCAST_REGISTRY.pop(self._bid, None)


class SparkContext:
    """Driver-side context stub: the adapter touches only broadcast()."""

    def broadcast(self, value) -> Broadcast:
        return Broadcast(value)


_SC = SparkContext()


class BarrierTaskInfo:
    """pyspark.taskcontext.BarrierTaskInfo: the per-task descriptor
    ``BarrierTaskContext.getTaskInfos()`` returns (``address`` attr)."""

    def __init__(self, address: str):
        self.address = address


class BarrierTaskContext(TaskContext):
    """pyspark.BarrierTaskContext: the task context inside a barrier
    stage. ``get()`` is only valid in a task launched by
    ``RDDBarrier.mapPartitions`` (returns None elsewhere, like the plain
    TaskContext stub); ``barrier()`` is the global sync point (a no-op in
    the stub's sequential gang execution — ordering IS the sync);
    ``getTaskInfos()`` lists all gang members, the handle a launcher uses
    to derive jax.distributed coordinates."""

    _current: Optional["BarrierTaskContext"] = None

    def __init__(self, partition_id: int, num_tasks: int, attempt: int):
        self._pid = partition_id
        self._num = num_tasks
        self._attempt = attempt

    @classmethod
    def get(cls) -> Optional["BarrierTaskContext"]:
        return cls._current

    def barrier(self) -> None:
        pass

    def partitionId(self) -> int:
        return self._pid

    def attemptNumber(self) -> int:
        return self._attempt

    def getTaskInfos(self):
        return [BarrierTaskInfo("localhost:0") for _ in range(self._num)]


__all__ = [
    "keyword_only",
    "TaskContext",
    "BarrierTaskContext",
    "BarrierTaskInfo",
    "Broadcast",
    "SparkContext",
    "BROADCAST_VALUE_PICKLES",
]
