"""Checkpoint/resume suite — preemption-tolerant segmented fits.

The robustness/checkpoint.py contract, counter-asserted end to end:

  (a) segmented solvers with the ``TPUML_CHECKPOINT_*`` knobs OFF are
      bit-identical to seed behavior and add ZERO compiles (the disabled
      path never leaves the monolithic single-program solvers);
  (b) a fit killed mid-solve — injected fault in-process, or a worker
      process dying on a fatal fault — then refit RESUMES from the last
      checkpoint, matches the uninterrupted model bit-for-bit, and
      executes strictly fewer solver iterations than an iteration-0
      restart (asserted via the counter registry, not logs);
  (c) stale checkpoints (foreign params, foreign data) are ignored, and
      truncated/torn/corrupt files fall back to the previous snapshot;
  (d) the elastic gang path: a barrier gang killed mid-fit relaunches
      and resumes from the shared checkpoint dir instead of iteration 0.
"""

import glob
import logging
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.robustness import (
    FitCheckpointer,
    InjectedFault,
    RetryExhaustedError,
    RetryPolicy,
    inject,
)
from spark_rapids_ml_tpu.robustness.checkpoint import DIR_ENV, EVERY_ENV, UMAP_ENV
from spark_rapids_ml_tpu.robustness.faults import disarm, parse_spec
from spark_rapids_ml_tpu.utils.tracing import (
    clear_counters,
    counter_value,
    counters,
)

_STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pyspark_stub")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    disarm()


@pytest.fixture(autouse=True)
def _fresh_counters():
    clear_counters("checkpoint")
    clear_counters("gang")
    yield


@pytest.fixture(autouse=True)
def _clean_ckpt_env(monkeypatch):
    """Each test starts from the disabled default; ``ckpt_dir`` arms the
    knobs on top (autouse fixtures instantiate first)."""
    for var in (DIR_ENV, EVERY_ENV, UMAP_ENV):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture
def ckpt_dir(tmp_path, monkeypatch):
    """A per-test checkpoint base dir with the knobs armed. CI points
    ``TPUML_TEST_CHECKPOINT_DIR`` at an artifact path so a failing run
    uploads the actual checkpoint files."""
    base = os.environ.get("TPUML_TEST_CHECKPOINT_DIR")
    if base:
        root = os.path.join(base, tmp_path.name)
        os.makedirs(root, exist_ok=True)
    else:
        root = str(tmp_path / "ckpts")
    monkeypatch.setenv(DIR_ENV, root)
    monkeypatch.setenv(EVERY_ENV, "2")
    return root


@pytest.fixture
def data(rng):
    return rng.normal(size=(200, 5))


def _kmeans_fit(x, uid="ck-kmeans", max_iter=16, tol=0.0):
    from spark_rapids_ml_tpu.models.kmeans import KMeans

    m = (
        KMeans(uid=uid).setK(6).setMaxIter(max_iter).setTol(tol).setSeed(3).fit(x)
    )
    return m, (np.asarray(m.clusterCenters()).tobytes(),
               np.float64(m.trainingCost).tobytes(), m.numIter)


def _logistic_fit(x, uid="ck-logreg"):
    from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegression

    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    m = LogisticRegression(uid=uid).setMaxIter(40).fit((x, y))
    return m, (np.asarray(m.coefficients).tobytes(),
               np.float64(m.intercept).tobytes(), m.numIter)


def _linreg_enet_fit(x, uid="ck-linreg"):
    from spark_rapids_ml_tpu.models.linear_regression import LinearRegression

    y = x @ np.arange(1.0, 6.0) + 0.5
    m = (
        LinearRegression(uid=uid)
        .setRegParam(0.1)
        .setElasticNetParam(0.5)
        .fit((x, y))
    )
    return m, (np.asarray(m.coefficients).tobytes(),
               np.float64(m.intercept).tobytes())


_FITS = {
    "kmeans": _kmeans_fit,
    "logistic": _logistic_fit,
    "linreg_enet": _linreg_enet_fit,
}


class TestDisabledIsSeedBehavior:
    """(a) knobs off → the monolithic path, bit-identical, zero extra
    compiles, zero checkpoint activity."""

    @pytest.mark.parametrize("family", sorted(_FITS))
    def test_partial_knobs_stay_disabled(self, family, data, tmp_path, monkeypatch):
        _, want = _FITS[family](data)  # both knobs unset
        monkeypatch.setenv(DIR_ENV, str(tmp_path / "c"))  # dir without EVERY
        _, got_dir_only = _FITS[family](data)
        monkeypatch.delenv(DIR_ENV)
        monkeypatch.setenv(EVERY_ENV, "2")  # EVERY without dir
        _, got_every_only = _FITS[family](data)
        assert got_dir_only == want and got_every_only == want
        assert counters("checkpoint") == {}
        assert not os.path.exists(str(tmp_path / "c"))

    def test_disabled_warm_fit_zero_compiles(self, data, caplog):
        """The acceptance bar: with checkpointing disabled (default) the
        warm fit path compiles NOTHING new — asserted against jax's own
        compile log, the serving-suite discipline."""
        _FITS["kmeans"](data)  # cold: populate the jit caches
        jax.config.update("jax_log_compiles", True)
        try:
            with caplog.at_level(logging.WARNING, logger="jax._src.dispatch"):
                _FITS["kmeans"](data)
        finally:
            jax.config.update("jax_log_compiles", False)
        compile_lines = [
            r for r in caplog.records if "compil" in r.getMessage().lower()
        ]
        assert compile_lines == []
        assert counter_value("checkpoint.segments") == 0


class TestSegmentedParity:
    """(a) continued: knobs ON, uninterrupted — segmented solvers are
    bit-identical to the monolithic programs they replace."""

    @pytest.mark.parametrize("family", sorted(_FITS))
    def test_segmented_equals_monolithic(self, family, data, ckpt_dir, monkeypatch):
        monkeypatch.setenv(EVERY_ENV, "0")
        _, want = _FITS[family](data)
        monkeypatch.setenv(EVERY_ENV, "3")
        _, got = _FITS[family](data)
        assert got == want
        assert counter_value("checkpoint.segments") >= 1
        assert counter_value("checkpoint.write") >= 1
        # A completed fit retires its own snapshots.
        assert counter_value("checkpoint.completed") == 1
        assert glob.glob(os.path.join(ckpt_dir, "*", "ckpt-*.npz")) == []

    def test_umap_is_opt_in(self, rng, ckpt_dir, monkeypatch):
        from spark_rapids_ml_tpu.models.umap import UMAP

        x = rng.normal(size=(50, 4)).astype(np.float32)

        def fit():
            return np.asarray(
                UMAP(uid="ck-umap").setNComponents(2).setSeed(1).fit(x).embedding
            )

        monkeypatch.setenv(EVERY_ENV, "0")
        want = fit()
        # Global knobs alone do NOT checkpoint UMAP …
        monkeypatch.setenv(EVERY_ENV, "64")
        assert fit().tobytes() == want.tobytes()
        assert counter_value("checkpoint.segments") == 0
        # … the opt-in env does, bit-identically.
        monkeypatch.setenv(UMAP_ENV, "1")
        assert fit().tobytes() == want.tobytes()
        assert counter_value("checkpoint.segments") >= 1


class TestCrashResume:
    """(b) kill mid-solve, refit, resume: bit-identical and strictly
    fewer solver iterations than an iteration-0 restart — on counters."""

    @pytest.mark.parametrize("family", ["kmeans", "logistic"])
    def test_fatal_fault_mid_fit_then_resume(self, family, data, ckpt_dir):
        _, want = _FITS[family](data)  # uninterrupted, checkpointing ON
        full_iters = counter_value("checkpoint.solver_iters")
        assert full_iters > 0

        clear_counters("checkpoint")
        with inject("checkpoint.segment=always:fatal"):
            with pytest.raises(InjectedFault):
                _FITS[family](data)
        # The kill left committed snapshot(s) behind …
        assert counter_value("checkpoint.write") >= 1
        assert glob.glob(os.path.join(ckpt_dir, "*", "ckpt-*.npz"))

        clear_counters("checkpoint")
        _, got = _FITS[family](data)
        assert got == want  # bit-identical to the uninterrupted fit
        assert counter_value("checkpoint.restore") == 1
        assert counter_value("checkpoint.restore.steps") > 0
        resumed_iters = counter_value("checkpoint.solver_iters")
        assert resumed_iters < full_iters  # strictly fewer than restart-at-0
        assert resumed_iters + counter_value("checkpoint.restore.steps") == full_iters

    def test_resumed_matches_checkpointing_off(self, data, ckpt_dir, monkeypatch):
        """The resumed model also matches the plain (knobs-off) fit —
        resume parity is against SEED behavior, not merely against the
        segmented driver."""
        monkeypatch.setenv(EVERY_ENV, "0")
        _, want = _FITS["kmeans"](data)
        monkeypatch.setenv(EVERY_ENV, "2")
        with inject("checkpoint.segment=1:fatal"):
            with pytest.raises(InjectedFault):
                _FITS["kmeans"](data)
        _, got = _FITS["kmeans"](data)
        assert got == want


@pytest.mark.slow
class TestWorkerKillResume:
    """(b) the multiproc form: a WORKER PROCESS dies mid-fit (fatal
    injected fault via TPUML_FAULTS — the launcher-style, code-free
    injection), the driver refits in a fresh interpreter state and
    resumes from the dead worker's checkpoints."""

    _SCRIPT = """
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from spark_rapids_ml_tpu.models.kmeans import KMeans

x = np.random.default_rng(7).normal(size=(200, 5))
m = KMeans(uid="ck-worker").setK(6).setMaxIter(16).setTol(0.0).setSeed(3).fit(x)
print("UNEXPECTED-COMPLETION")
"""

    def test_killed_worker_then_resume(self, ckpt_dir, tmp_path, monkeypatch):
        script = tmp_path / "worker.py"
        script.write_text(self._SCRIPT)
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
                DIR_ENV: ckpt_dir,
                EVERY_ENV: "2",
                # the worker dies at the first segment boundary, mid-solve
                "TPUML_FAULTS": "checkpoint.segment=always:fatal",
            }
        )
        proc = subprocess.run(
            [sys.executable, str(script)],
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode != 0, proc.stdout + proc.stderr
        assert "UNEXPECTED-COMPLETION" not in proc.stdout
        assert "checkpoint.segment" in proc.stderr
        assert glob.glob(os.path.join(ckpt_dir, "*", "ckpt-*.npz"))

        # The driver-side refit: same uid/params/data → resumes.
        x = np.random.default_rng(7).normal(size=(200, 5))
        monkeypatch.setenv(EVERY_ENV, "0")
        _, want = _kmeans_fit(x, uid="ck-worker")
        monkeypatch.setenv(EVERY_ENV, "2")
        clear_counters("checkpoint")
        _, got = _kmeans_fit(x, uid="ck-worker")
        assert got == want
        assert counter_value("checkpoint.restore") == 1
        assert counter_value("checkpoint.restore.steps") > 0


class TestStaleAndCorrupt:
    """(c) restore validation: stale identities are ignored; torn and
    truncated files fall back to the previous snapshot."""

    def _crash_kmeans(self, x):
        with inject("checkpoint.segment=always:fatal"):
            with pytest.raises(InjectedFault):
                _kmeans_fit(x)

    def test_changed_params_never_resume(self, data, ckpt_dir):
        self._crash_kmeans(data)
        clear_counters("checkpoint")
        # Different tol → different param hash → fresh solve, and the
        # result matches a from-scratch fit of those params.
        m, got = _kmeans_fit(data, tol=1e-3)
        assert counter_value("checkpoint.restore") == 0
        for f in glob.glob(os.path.join(ckpt_dir, "*", "ckpt-*.npz")):
            os.remove(f)
        _, want = _kmeans_fit(data, tol=1e-3)
        assert got == want

    def test_changed_data_is_stale(self, data, rng, ckpt_dir):
        self._crash_kmeans(data)
        clear_counters("checkpoint")
        other = rng.normal(size=(200, 5))
        _, got = _kmeans_fit(other)
        assert counter_value("checkpoint.restore") == 0
        assert counter_value("checkpoint.skipped_stale") >= 1

    def test_torn_write_lands_truncated_and_is_rejected(self, tmp_path):
        ck = FitCheckpointer(
            str(tmp_path / "run"), uid="u", param_hash="p", data_fp="d", every=1
        )
        s1 = (jnp.arange(4.0), jnp.asarray(1))
        s2 = (jnp.arange(4.0) * 2, jnp.asarray(2))
        ck.save_async(1, s1)
        ck.wait()
        with inject("checkpoint.write=1:torn") as plan:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                ck.save_async(2, s2)
                ck.wait()
        assert plan.fired == [("checkpoint.write", 0)]
        assert any("checkpoint write" in str(w.message) for w in caught)
        # The torn file IS on disk at the final path …
        files = sorted(os.listdir(tmp_path / "run"))
        assert files == ["ckpt-00000001.npz", "ckpt-00000002.npz"]
        # … and restore rejects it, falling back to the previous one.
        clear_counters("checkpoint")
        step, state = ck.restore_latest(template=s1)
        assert step == 1
        assert counter_value("checkpoint.corrupt") == 1
        assert counter_value("checkpoint.restore") == 1
        np.testing.assert_array_equal(np.asarray(state[0]), np.arange(4.0))

    def test_manually_truncated_file_falls_back(self, tmp_path):
        ck = FitCheckpointer(
            str(tmp_path / "run"), uid="u", param_hash="p", data_fp="d", every=1
        )
        s = (jnp.arange(3.0),)
        ck.save_async(1, s)
        ck.wait()
        ck.save_async(2, (jnp.arange(3.0) * 5,))
        ck.wait()
        newest = str(tmp_path / "run" / "ckpt-00000002.npz")
        raw = open(newest, "rb").read()
        with open(newest, "wb") as f:
            f.write(raw[: len(raw) // 2])
        step, state = ck.restore_latest(template=s)
        assert step == 1
        assert counter_value("checkpoint.corrupt") == 1

    def test_restore_fault_site_skips_newest(self, tmp_path):
        ck = FitCheckpointer(
            str(tmp_path / "run"), uid="u", param_hash="p", data_fp="d", every=1
        )
        for i in (1, 2):
            ck.save_async(i, (jnp.arange(3.0) * i,))
            ck.wait()
        with inject("checkpoint.restore=1"):
            step, _ = ck.restore_latest(template=(jnp.arange(3.0),))
        assert step == 1

    def test_retention_keeps_last_k(self, tmp_path):
        ck = FitCheckpointer(
            str(tmp_path / "run"), uid="u", param_hash="p", data_fp="d",
            every=1, keep=2,
        )
        for i in range(1, 6):
            ck.save_async(i, (jnp.arange(2.0) * i,))
            ck.wait()
        assert sorted(os.listdir(tmp_path / "run")) == [
            "ckpt-00000004.npz", "ckpt-00000005.npz",
        ]

    def test_torn_spec_parses(self):
        plan = parse_spec("checkpoint.write=1:torn; checkpoint.restore=2")
        assert plan["checkpoint.write"].torn
        assert not plan["checkpoint.write"].fatal
        assert not plan["checkpoint.restore"].torn


class TestRetryCounters:
    """Satellite: per-site retry attempts/exhaustions ride the counter
    registry, not the logs."""

    def test_attempts_counted_per_site(self):
        clear_counters("retry.ckunit")
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        RetryPolicy(max_attempts=5, base_delay=0).run(fn, "ckunit")
        assert counter_value("retry.ckunit.attempts") == 3
        assert counter_value("retry.ckunit.exhausted") == 0

    def test_exhaustion_counted(self):
        clear_counters("retry.ckunit2")

        def fn():
            raise OSError("forever")

        with pytest.raises(RetryExhaustedError):
            RetryPolicy(max_attempts=2, base_delay=0).run(fn, "ckunit2")
        assert counter_value("retry.ckunit2.attempts") == 2
        assert counter_value("retry.ckunit2.exhausted") == 1


class TestReinitWarning:
    """Satellite: a second initialize() with different coordinates is no
    longer silent — a structured warning names both values."""

    @pytest.fixture
    def initialized(self, monkeypatch):
        from spark_rapids_ml_tpu.parallel import distributed as dist

        monkeypatch.setattr(dist, "_initialized", True)
        monkeypatch.setattr(
            dist,
            "_init_record",
            {
                "coordinator_address": "10.0.0.1:8476",
                "num_processes": 4,
                "process_id": 0,
            },
        )
        return dist

    def test_mismatch_warns_naming_both_values(self, initialized):
        dist = initialized
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            dist.initialize(
                coordinator_address="10.0.0.2:8476", num_processes=4, process_id=1
            )
        got = [w.message for w in caught if isinstance(w.message, dist.GangReinitWarning)]
        fields = {w.field for w in got}
        assert fields == {"coordinator_address", "process_id"}
        addr = next(w for w in got if w.field == "coordinator_address")
        assert "10.0.0.1:8476" in str(addr) and "10.0.0.2:8476" in str(addr)

    def test_same_coordinates_stay_silent(self, initialized):
        dist = initialized
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            dist.initialize(
                coordinator_address="10.0.0.1:8476", num_processes=4, process_id=0
            )
        assert [w for w in caught if isinstance(w.message, dist.GangReinitWarning)] == []


@pytest.fixture
def stub_spark():
    saved = {n: m for n, m in sys.modules.items() if n.startswith("pyspark")}
    for n in list(saved):
        del sys.modules[n]
    sys.path.insert(0, _STUB)
    try:
        from pyspark.sql import SparkSession

        yield SparkSession.builder.master("local[2]").getOrCreate()
    finally:
        sys.path.remove(_STUB)
        for n in [n for n in sys.modules if n.startswith("pyspark")]:
            del sys.modules[n]
        sys.modules.update(saved)


class TestElasticGangResume:
    """(d) a barrier gang killed mid-fit relaunches (the stub's stage
    retry) and the relaunched tasks RESUME from the shared checkpoint
    dir instead of refitting from iteration 0."""

    def _gang_fit(self, spark, x, ckdir):
        import spark_contract_suite as suite

        from spark_rapids_ml_tpu.models.kmeans import KMeans
        from spark_rapids_ml_tpu.spark.barrier import barrier_gang_run

        df = suite._vector_df(spark, x, n_parts=2)

        def task(ctx, it):
            rows = np.asarray(
                [np.asarray(r.features.toArray(), dtype=float) for r in it]
            )
            m = (
                KMeans(uid="ck-gang")
                .setK(5)
                .setMaxIter(12)
                .setTol(0.0)
                .setSeed(1)
                .fit(rows)
            )
            yield np.asarray(m.clusterCenters())

        return barrier_gang_run(
            df.select("features").rdd, task, checkpoint_dir=ckdir
        )

    def test_gang_kill_resumes_from_checkpoint(
        self, stub_spark, rng, ckpt_dir, monkeypatch
    ):
        monkeypatch.setenv("TPUML_RETRY_BASE_DELAY", "0")
        x = rng.normal(size=(160, 5))
        want = [p.tobytes() for p in self._gang_fit(stub_spark, x, ckpt_dir)]
        clear_counters("checkpoint")
        # Transient faults kill BOTH tasks of attempt 0 mid-solve; the
        # stub's stage retry relaunches the whole gang.
        with inject("checkpoint.segment=3") as plan:
            got = [p.tobytes() for p in self._gang_fit(stub_spark, x, ckpt_dir)]
        assert len(plan.fired) == 3
        assert got == want
        # The relaunched tasks restored mid-solve state instead of
        # starting at iteration 0.
        assert counter_value("checkpoint.restore") >= 1
        assert counter_value("checkpoint.restore.steps") >= 1
