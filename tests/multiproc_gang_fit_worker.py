"""Worker for the REAL 2-process gang-fit acceptance test (gang deploy
mode through the public estimator API).

Launched N times by tests/test_gang_fit.py with TPUML_COORDINATOR /
TPUML_NUM_PROCESSES / TPUML_PROCESS_ID in the environment — the member
coordinates a barrier stage (spark/barrier.py::gang_fit) exports. Unlike
tests/multiproc_pca_worker.py this worker never calls dist.initialize()
itself: ``setDeployMode("gang")`` on a plain estimator must do the whole
bring-up (join the gang, build the global mesh, shard the LOCAL rows into
the global batch) inside ``fit()``. Each member holds a different slice
of a deterministic global dataset; the fitted models must match the
single-process full-data fit at the documented tolerances:

  - PCA / LinearRegression: deterministic merges (moment psum order is
    fixed) — 1e-6 under x64;
  - KMeans with a pinned initial model: assignments are stable on
    separated blobs — 1e-6;
  - LogisticRegression: L-BFGS amplifies summation-order noise — 1e-3.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:  # newer jax: gloo is the default, the knob may be gone
    pass
_x64 = os.environ.get("TPUML_TEST_NO_X64") != "1"
jax.config.update("jax_enable_x64", _x64)

from spark_rapids_ml_tpu.utils.envknobs import env_int


def main() -> None:
    n_proc = env_int("TPUML_NUM_PROCESSES")
    pid = env_int("TPUML_PROCESS_ID")

    # Deterministic global dataset; every member derives the same one and
    # takes a DIFFERENT (deliberately uneven) slice as its local data.
    rng = np.random.default_rng(0)
    n = int(os.environ.get("TPUML_TEST_ROWS", "403"))
    d = int(os.environ.get("TPUML_TEST_D", "8"))
    dtype = np.float64 if _x64 else np.float32
    x = (rng.normal(size=(n, d)) * np.linspace(1.0, 2.0, d)).astype(dtype)
    bounds = np.linspace(0, n, n_proc + 1).astype(int)
    local = x[bounds[pid] : bounds[pid + 1]]

    tol = 1e-6 if _x64 else 1e-3
    iter_tol = 1e-3 if _x64 else 3e-2  # L-BFGS amplifies sum-order noise

    from spark_rapids_ml_tpu.classification import LogisticRegression
    from spark_rapids_ml_tpu.clustering import KMeans
    from spark_rapids_ml_tpu.feature import PCA
    from spark_rapids_ml_tpu.regression import LinearRegression
    from spark_rapids_ml_tpu.utils.testing import assert_components_close

    # --- PCA: the FIRST gang fit does the entire bring-up ---------------
    model = PCA().setK(3).setDeployMode("gang").fit([local])
    assert jax.process_count() == n_proc, jax.process_count()
    assert jax.process_index() == pid, jax.process_index()
    ref = PCA().setK(3).setDeployMode("single").fit([x])
    assert_components_close(model.pc, np.asarray(ref.pc), tol)
    np.testing.assert_allclose(
        model.explainedVariance, ref.explainedVariance, atol=tol
    )
    print(f"PCA_OK {pid}")

    # --- LinearRegression via the TPUML_GANG_FIT env twin ---------------
    beta = np.arange(1.0, d + 1.0, dtype=dtype)
    y = x @ beta + 0.01 * rng.normal(size=n).astype(dtype)
    y_local = y[bounds[pid] : bounds[pid + 1]]
    os.environ["TPUML_GANG_FIT"] = "1"
    try:
        lm = LinearRegression().fit((local, y_local))
    finally:
        del os.environ["TPUML_GANG_FIT"]
    lref = LinearRegression().setDeployMode("single").fit((x, y))
    np.testing.assert_allclose(
        np.asarray(lm.coefficients), np.asarray(lref.coefficients), atol=tol
    )
    np.testing.assert_allclose(lm.intercept, lref.intercept, atol=tol)
    print(f"LINEAR_OK {pid}")

    # --- LogisticRegression: psum'd fused loss+grad ----------------------
    y_cls = (x[:, 0] + 0.25 * x[:, 1] > 0).astype(dtype)
    clf = (
        LogisticRegression()
        .setMaxIter(60)
        .setDeployMode("gang")
        .fit((local, y_cls[bounds[pid] : bounds[pid + 1]]))
    )
    cref = (
        LogisticRegression().setMaxIter(60).setDeployMode("single")
        .fit((x, y_cls))
    )
    np.testing.assert_allclose(
        np.asarray(clf.coefficients), np.asarray(cref.coefficients),
        atol=iter_tol,
    )
    assert np.array_equal(np.asarray(clf.predict(x)), np.asarray(cref.predict(x)))
    print(f"LOGISTIC_OK {pid}")

    # --- KMeans: per-member assign+stats, psum'd centers ------------------
    blobs = np.concatenate(
        [
            rng.normal(loc=-4.0, scale=0.3, size=(n // 2, d)),
            rng.normal(loc=4.0, scale=0.3, size=(n - n // 2, d)),
        ]
    ).astype(dtype)
    perm = rng.permutation(n)  # interleave so every slice sees both blobs
    blobs = blobs[perm]
    init = np.stack([blobs[0], blobs[1]])  # pinned: init is row-position
    km = (
        KMeans().setK(2).setMaxIter(10).setInitialModel(init)
        .setDeployMode("gang").fit(blobs[bounds[pid] : bounds[pid + 1]])
    )
    kref = (
        KMeans().setK(2).setMaxIter(10).setInitialModel(init)
        .setDeployMode("single").fit(blobs)
    )
    np.testing.assert_allclose(
        np.asarray(km.clusterCenters()), np.asarray(kref.clusterCenters()),
        atol=tol,
    )
    print(f"KMEANS_OK {pid}")

    print(f"OK process {pid}/{n_proc}")


if __name__ == "__main__":
    main()
