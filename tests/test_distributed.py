"""Distributed/mesh tests — the multi-chip coverage the reference lacks
(SURVEY.md §4 implication: add a multi-partition -> multi-chip integration
test). Runs on the 8-device virtual CPU mesh from conftest."""

import jax
import numpy as np
import pytest

from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.parallel.distributed_cov import (
    distributed_covariance_shard_map,
    distributed_mean_and_covariance,
)
from spark_rapids_ml_tpu.parallel.mesh import make_mesh, shard_rows

from conftest import numpy_pca_oracle


@pytest.fixture(scope="module")
def mesh_8x1():
    return make_mesh((8, 1))


@pytest.fixture(scope="module")
def mesh_4x2():
    return make_mesh((4, 2))


def test_eight_devices_available():
    assert len(jax.devices()) == 8


class TestShardRows:
    def test_padding_and_mask(self, rng, mesh_8x1):
        x = rng.normal(size=(13, 4))  # 13 % 8 != 0
        xs, mask, n = shard_rows(x, mesh_8x1)
        assert n == 13
        assert xs.shape == (16, 4)
        assert float(np.asarray(mask).sum()) == 13.0

    def test_data_only_mesh(self, rng):
        # A 1-axis (pure-DP) mesh must work end to end: shard_rows,
        # shard_rows_process_local, and the PCA mesh fit all used to
        # KeyError/ValueError on mesh.shape['model'].
        from jax.sharding import Mesh

        from spark_rapids_ml_tpu.parallel.distributed import (
            shard_rows_process_local,
        )
        from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

        mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))
        x = rng.normal(size=(13, 4))
        xs, mask, n = shard_rows(x, mesh)
        assert n == 13 and xs.shape == (16, 4)
        xs2, mask2, n2, d2 = shard_rows_process_local([x], mesh)
        assert n2 == 13 and d2 == 4
        model = PCA(mesh=mesh).setK(2).fit(x)
        oracle = PCA().setK(2).fit(x)
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        assert_components_close(model.pc, oracle.pc, 1e-8)


class TestDistributedCovariance:
    def test_gspmd_matches_numpy(self, rng, mesh_8x1):
        x = rng.normal(size=(200, 12))
        xs, mask, _ = shard_rows(x, mesh_8x1)
        mean, cov = distributed_mean_and_covariance(xs, mask, mesh_8x1)
        np.testing.assert_allclose(mean, x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)

    def test_gspmd_2d_mesh(self, rng, mesh_4x2):
        """Rows AND features sharded (dp x mp)."""
        x = rng.normal(size=(100, 10))
        xs, mask, _ = shard_rows(x, mesh_4x2)
        mean, cov = distributed_mean_and_covariance(xs, mask, mesh_4x2)
        np.testing.assert_allclose(mean, x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)

    def test_shard_map_explicit_collectives(self, rng, mesh_4x2):
        """Hand-written psum/all_gather path agrees with numpy."""
        x = rng.normal(size=(64, 8))
        xs, mask, _ = shard_rows(x, mesh_4x2)
        mean, cov = distributed_covariance_shard_map(xs, mask, mesh_4x2)
        np.testing.assert_allclose(np.asarray(mean), x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(np.asarray(cov), np.cov(x, rowvar=False), atol=1e-10)

    def test_padded_rows_do_not_pollute(self, rng, mesh_8x1):
        x = rng.normal(size=(19, 5))  # heavy padding: 19 -> 24
        xs, mask, _ = shard_rows(x, mesh_8x1)
        _, cov = distributed_mean_and_covariance(xs, mask, mesh_8x1)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-10)


class TestDistributedPCA:
    def test_mesh_fit_matches_oracle(self, rng, mesh_8x1):
        x = rng.normal(size=(300, 16))
        expected_pc, expected_var = numpy_pca_oracle(x, 5)
        model = PCA(mesh=mesh_8x1).setK(5).fit(x)
        np.testing.assert_allclose(np.abs(model.pc), np.abs(expected_pc), atol=1e-6)
        np.testing.assert_allclose(model.explainedVariance, expected_var, atol=1e-6)

    def test_mesh_fit_matches_single_device_fit(self, rng, mesh_4x2):
        x = rng.normal(size=(120, 9))
        m_mesh = PCA(mesh=mesh_4x2).setK(4).fit(x)
        m_single = PCA().setK(4).fit(x)
        np.testing.assert_allclose(np.abs(m_mesh.pc), np.abs(m_single.pc), atol=1e-6)


class TestDistributedRandomForest:
    """Rows sharded over the data axis; per-level histograms psum over the
    mesh. Classification counts are small integers (exact in fp32), so the
    sharded fit must produce the IDENTICAL forest to the single-device fit."""

    def test_sharded_classifier_identical(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.classification import RandomForestClassifier

        x = rng.normal(size=(203, 6))  # deliberately not divisible by 8
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(float)
        kw = dict(numTrees=5, maxDepth=4, seed=3)
        m_single = RandomForestClassifier()._set(**kw).fit((x, y))
        m_mesh = RandomForestClassifier(mesh=mesh_8x1)._set(**kw).fit((x, y))
        np.testing.assert_array_equal(
            np.asarray(m_single._forest.feature), np.asarray(m_mesh._forest.feature)
        )
        np.testing.assert_allclose(
            np.asarray(m_single._forest.threshold),
            np.asarray(m_mesh._forest.threshold),
            atol=1e-6,
        )
        np.testing.assert_array_equal(m_single.predict(x), m_mesh.predict(x))

    def test_sharded_regressor_quality(self, rng, mesh_4x2):
        from spark_rapids_ml_tpu.regression import RandomForestRegressor

        x = rng.normal(size=(240, 4))
        y = 2.0 * x[:, 0] - x[:, 2]
        model = (
            RandomForestRegressor(mesh=mesh_4x2)
            .setNumTrees(8)
            .setMaxDepth(6)
            .setFeatureSubsetStrategy("all")
            .setSeed(1)
            .fit((x, y))
        )
        rmse = np.sqrt(np.mean((model.predict(x) - y) ** 2))
        assert rmse < 0.6


class TestDistributedUMAP:
    def test_sharded_knn_graph_matches(self, rng, mesh_8x1):
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.umap import _knn_excluding_self

        x = jnp.asarray(rng.normal(size=(101, 6)), dtype=jnp.float32)
        d_s, i_s = _knn_excluding_self(x, 8, "euclidean", mesh_8x1)
        d_u, i_u = _knn_excluding_self(x, 8, "euclidean", None)
        np.testing.assert_array_equal(np.asarray(i_s), np.asarray(i_u))
        np.testing.assert_allclose(np.asarray(d_s), np.asarray(d_u), atol=1e-5)

    def test_mesh_umap_fit(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.manifold import UMAP

        x = np.concatenate(
            [rng.normal(size=(40, 8)) + off for off in (0.0, 10.0)]
        )
        model = UMAP(mesh=mesh_8x1).setNNeighbors(8).setNEpochs(60).setSeed(0).fit(x)
        emb = model.embedding
        assert emb.shape == (80, 2)
        labels = np.repeat([0, 1], 40)
        c0, c1 = emb[labels == 0].mean(0), emb[labels == 1].mean(0)
        spread = np.mean(np.linalg.norm(emb[labels == 0] - c0, axis=1))
        assert np.linalg.norm(c0 - c1) > 2 * spread


class TestDistributedKnnMetrics:
    def test_mesh_cosine_matches_single(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.neighbors import NearestNeighbors

        items = rng.normal(size=(150, 8))
        q = rng.normal(size=(11, 8))
        m_mesh = NearestNeighbors().setK(5).setMetric("cosine").fit(items)
        m_mesh.setMesh(mesh_8x1)
        m_single = NearestNeighbors().setK(5).setMetric("cosine").fit(items)
        d_m, i_m = m_mesh.kneighbors(q)
        d_s, i_s = m_single.kneighbors(q)
        np.testing.assert_array_equal(i_m, i_s)
        np.testing.assert_allclose(d_m, d_s, atol=1e-6)


class TestDistributedDBSCAN:
    def test_sharded_matches_single(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.clustering import DBSCAN

        # Three blobs + scattered noise; n not divisible by 8.
        x = np.concatenate(
            [rng.normal(size=(45, 3)) * 0.2 + c for c in ([0, 0, 0], [3, 3, 0], [0, 3, 3])]
            + [rng.uniform(-2, 5, size=(10, 3))]
        )
        m_single = DBSCAN().setEps(0.7).setMinSamples(4).fit(x)
        m_mesh = DBSCAN(mesh=mesh_8x1).setEps(0.7).setMinSamples(4).fit(x)
        np.testing.assert_array_equal(m_single.labels_, m_mesh.labels_)
        np.testing.assert_array_equal(m_single.core_mask_, m_mesh.core_mask_)
        assert len(set(m_single.labels_[m_single.labels_ >= 0])) == 3


class TestDistributedANN:
    def test_sharded_search_matches_single(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors

        items = rng.normal(size=(300, 10))
        queries = rng.normal(size=(21, 10))  # deliberately not divisible by 8
        m = (
            ApproximateNearestNeighbors()
            .setAlgorithm("ivfflat")
            .setAlgoParams({"nlist": 8, "nprobe": 8})
            .setK(5)
            .setSeed(0)
            .fit(items)
        )
        d_single, i_single = m.kneighbors(queries)
        m.setMesh(mesh_8x1)
        d_mesh, i_mesh = m.kneighbors(queries)
        np.testing.assert_array_equal(i_single, i_mesh)
        np.testing.assert_allclose(d_single, d_mesh, atol=1e-6)

    def test_sharded_ivfpq_with_refine(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors

        items = rng.normal(size=(240, 8))
        queries = rng.normal(size=(13, 8))
        kwargs = dict(
            algorithm="ivfpq",
            algoParams={"nlist": 6, "nprobe": 6, "M": 4, "n_bits": 6,
                        "refine_ratio": 4},
            k=5, seed=1,
        )
        m = ApproximateNearestNeighbors()._set(**kwargs).fit(items)
        d_single, i_single = m.kneighbors(queries)
        m.setMesh(mesh_8x1)
        d_mesh, i_mesh = m.kneighbors(queries)
        np.testing.assert_array_equal(i_single, i_mesh)
        np.testing.assert_allclose(d_single, d_mesh, atol=1e-6)

    def test_sharded_brute_matches_single(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors

        items = rng.normal(size=(150, 6))
        queries = rng.normal(size=(9, 6))
        m = ApproximateNearestNeighbors().setAlgorithm("brute").setK(4).fit(items)
        d_single, i_single = m.kneighbors(queries)
        m.setMesh(mesh_8x1)
        d_mesh, i_mesh = m.kneighbors(queries)
        np.testing.assert_array_equal(i_single, i_mesh)
        np.testing.assert_allclose(d_single, d_mesh, atol=1e-6)

    def test_estimator_mesh_propagates(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors

        items = rng.normal(size=(100, 5))
        m = (
            ApproximateNearestNeighbors(mesh=mesh_8x1)
            .setAlgorithm("ivfflat")
            .setAlgoParams({"nlist": 4, "nprobe": 4})
            .setK(3)
            .fit(items)
        )
        assert m.mesh is mesh_8x1
        d, i = m.kneighbors(rng.normal(size=(7, 5)))
        assert d.shape == (7, 3)


class TestDistributedIndexBuild:
    """The ANN index BUILD is mesh-sharded now, not just the search:
    coarse quantizer + PQ codebook Lloyds run over sharded rows with
    psum-merged stats (VERDICT r1 missing item 6)."""

    def test_ivf_build_parity(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.ops.ann import build_ivf_index, ivf_search
        import jax.numpy as jnp

        items = rng.normal(size=(512, 16)).astype(np.float32)
        idx_s = build_ivf_index(items, n_lists=8, seed=0, mesh=mesh_8x1)
        idx_u = build_ivf_index(items, n_lists=8, seed=0)
        # Same seeded init + deterministic Lloyd: centroids agree to fp
        # reduction-order tolerance. NOTE this parity holds because the
        # shapes here divide the mesh evenly — row/feature padding changes
        # the array length the seeded k-means++ draws its Gumbel noise
        # over, legitimately diverging the init (both builds stay correct;
        # only the exact-equality comparison would break).
        np.testing.assert_allclose(
            np.asarray(idx_s.centroids), np.asarray(idx_u.centroids), atol=1e-4
        )
        # Search through both indexes returns overwhelmingly the same
        # neighbors (boundary items may flip lists at fp tolerance).
        q = jnp.asarray(items[:64])
        _, i_s = ivf_search(idx_s, q, k=5, n_probe=8)
        _, i_u = ivf_search(idx_u, q, k=5, n_probe=8)
        overlap = np.mean(
            [
                len(set(a) & set(b)) / 5.0
                for a, b in zip(np.asarray(i_s), np.asarray(i_u))
            ]
        )
        assert overlap > 0.95, overlap

    def test_ivfpq_build_parity(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.ops.ann import build_ivfpq_index, ivfpq_search
        import jax.numpy as jnp

        items = rng.normal(size=(512, 16)).astype(np.float32)
        idx_s = build_ivfpq_index(items, n_lists=4, m_subspaces=4, seed=0, mesh=mesh_8x1)
        idx_u = build_ivfpq_index(items, n_lists=4, m_subspaces=4, seed=0)
        np.testing.assert_allclose(
            np.asarray(idx_s.centroids), np.asarray(idx_u.centroids), atol=1e-4
        )
        assert idx_s.codebooks.shape == idx_u.codebooks.shape
        assert idx_s.codes.dtype == idx_u.codes.dtype
        # Both indexes must retrieve true neighbors with similar quality.
        from spark_rapids_ml_tpu.ops.knn import knn as _  # noqa: F401

        q = jnp.asarray(items[:32])
        d2 = ((items[:32, None, :] - items[None]) ** 2).sum(-1)
        true_nn = np.argsort(d2, axis=1)[:, :5]
        for idx in (idx_s, idx_u):
            _, i_got = ivfpq_search(idx, q, k=5, n_probe=4)
            recall = np.mean(
                [
                    len(set(a) & set(b)) / 5.0
                    for a, b in zip(np.asarray(i_got), true_nn)
                ]
            )
            assert recall > 0.6, recall

    def test_model_level_sharded_build(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.neighbors import ApproximateNearestNeighbors

        items = rng.normal(size=(256, 8))
        m = (
            ApproximateNearestNeighbors(mesh=mesh_8x1)
            .setAlgorithm("ivfpq")
            .setAlgoParams({"nlist": 4, "nprobe": 4, "M": 2})
            .setK(3)
            .fit(items)
        )
        d, i = m.kneighbors(items[:10])
        assert i.shape == (10, 3)
        assert np.all(i[:, 0] == np.arange(10))  # self is nearest


class TestDistributedUMAPOptimize:
    def test_sharded_epochs_separate_clusters(self, rng, mesh_8x1):
        """The mesh fit shards the SGD epochs (edges over the data axis,
        one delta psum per epoch), not only the kNN stage; cluster
        separation quality must match the single-device optimizer."""
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.umap import (
            find_ab_params,
            fuzzy_simplicial_set,
            optimize_layout,
            optimize_layout_sharded,
        )
        from spark_rapids_ml_tpu.models.umap import _knn_excluding_self

        x = jnp.asarray(
            np.concatenate(
                [rng.normal(size=(48, 6)) + off for off in (0.0, 12.0)]
            ),
            dtype=jnp.float32,
        )
        dists, idx = _knn_excluding_self(x, 8, "euclidean", None)
        graph = fuzzy_simplicial_set(idx, dists)
        a, b = find_ab_params(1.0, 0.1)
        emb0 = 10.0 * jax.random.uniform(
            jax.random.key(0), (96, 2), minval=-1.0, maxval=1.0
        ).astype(jnp.float32)

        def separation(emb):
            labels = np.repeat([0, 1], 48)
            c0, c1 = emb[labels == 0].mean(0), emb[labels == 1].mean(0)
            spread = np.mean(np.linalg.norm(emb[labels == 0] - c0, axis=1)) + 1e-9
            return np.linalg.norm(c0 - c1) / spread

        kw = dict(n_epochs=80, neg_rate=5, learning_rate=1.0, repulsion=1.0, a=a, b=b)
        emb_s = np.asarray(
            optimize_layout_sharded(mesh_8x1, emb0, graph, jax.random.key(1), **kw)
        )
        emb_u = np.asarray(optimize_layout(emb0, graph, jax.random.key(1), **kw))
        assert separation(emb_s) > 2.0, separation(emb_s)
        # 1.8 (not 2.0): the r4 structured-head epoch changes only the
        # float reduction ORDER of the gradient sums — same math, a
        # slightly different SGD trajectory on this 96-point toy; the
        # clusters must still clearly separate.
        assert separation(emb_u) > 1.8, separation(emb_u)

    def test_sharded_pooled_epoch_matches_unsharded(self, rng, mesh_8x1):
        """Pooled mode draws the shared pool from the replicated key, so
        the sharded epoch computes the SAME update as the single-device
        one (only psum reduction order differs) — checked over one epoch,
        before float drift can amplify through the SGD trajectory."""
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.models.umap import _knn_excluding_self
        from spark_rapids_ml_tpu.ops.umap import (
            fuzzy_simplicial_set,
            optimize_layout,
            optimize_layout_sharded,
        )

        x = jnp.asarray(rng.normal(size=(96, 6)), dtype=jnp.float32)
        d, i = _knn_excluding_self(x, 8, "euclidean")
        graph = fuzzy_simplicial_set(i, d)
        emb0 = jnp.asarray(rng.normal(size=(96, 2)), dtype=jnp.float32)
        kw = dict(n_epochs=1, neg_rate=5, neg_pool=64, a=1.577, b=0.895)
        e_s = np.asarray(
            optimize_layout_sharded(mesh_8x1, emb0, graph, jax.random.key(3), **kw)
        )
        e_u = np.asarray(optimize_layout(emb0, graph, jax.random.key(3), **kw))
        np.testing.assert_allclose(e_s, e_u, atol=1e-5)


class TestStreamedMeshCovariance:
    """Streaming + mesh — the north-star loop: blocks stream in, each is
    row-sharded over the data axis, the Gram accumulates replicated with
    one psum per block (BASELINE config 5, now a real code path rather
    than a projection)."""

    def test_streamed_mesh_pca_matches_materialized(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        x = rng.normal(size=(5_003, 8)) * np.linspace(1, 2, 8) + 50.0
        gen = (x[i : i + 1024] for i in range(0, x.shape[0], 1024))
        m_stream = PCA(mesh=mesh_8x1).setK(3).fit(gen)
        m_mat = PCA().setK(3).fit(x)
        assert_components_close(m_stream.pc, m_mat.pc, 1e-8)
        np.testing.assert_allclose(
            m_stream.explainedVariance, m_mat.explainedVariance, atol=1e-10
        )

    def test_streamed_mesh_covariance_oracle(self, rng, mesh_8x1):
        from spark_rapids_ml_tpu.ops.covariance import (
            streaming_mean_and_covariance_mesh,
        )

        x = rng.normal(size=(3_000, 6)) + 1e3
        gen = (x[i : i + 500] for i in range(0, 3_000, 500))
        mean, cov, n = streaming_mean_and_covariance_mesh(gen, mesh_8x1)
        assert n == 3_000
        np.testing.assert_allclose(mean, x.mean(axis=0), rtol=1e-12)
        np.testing.assert_allclose(cov, np.cov(x, rowvar=False), atol=1e-6)

    def test_reader_streamed_mesh(self, rng, mesh_8x1, tmp_path):
        from spark_rapids_ml_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        x = rng.normal(size=(2_048, 6)).astype(np.float64)
        path = str(tmp_path / "m.npy")
        np.save(path, x)
        reader = native.NpyBlockReader(path, block_rows=300)
        try:
            model = PCA(mesh=mesh_8x1).setK(2).fit(reader)
        finally:
            reader.close()
        oracle = PCA().setK(2).fit(x)
        from spark_rapids_ml_tpu.utils.testing import assert_components_close

        assert_components_close(model.pc, oracle.pc, 1e-8)
