"""KMeans suite. Oracle: exact Lloyd in numpy from the same init (the
framework's own init is deterministic given a seed), plus recovery of
well-separated synthetic clusters — the test strategy the reference family
uses for its kmeans (cuML/RAFT): cluster-recovery + cost monotonicity."""

import numpy as np
import pytest

from spark_rapids_ml_tpu.clustering import KMeans, KMeansModel
from spark_rapids_ml_tpu.core.data import DataFrame
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


def make_blobs(rng, n=300, d=8, k=4, sep=10.0):
    centers = rng.normal(size=(k, d)) * sep
    labels = rng.integers(0, k, size=n)
    x = centers[labels] + rng.normal(size=(n, d))
    return x, centers, labels


def numpy_lloyd(x, init, max_iter=20, tol=1e-4):
    centers = init.copy()
    for _ in range(max_iter):
        d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
        labels = d2.argmin(1)
        new = np.stack(
            [x[labels == j].mean(0) if (labels == j).any() else centers[j] for j in range(len(centers))]
        )
        moved = ((new - centers) ** 2).sum(1).max()
        centers = new
        if moved <= tol * tol:
            break
    d2 = ((x[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    return centers, d2.min(1).sum()


class TestKMeansFit:
    def test_recovers_separated_blobs(self, rng):
        x, true_centers, _ = make_blobs(rng)
        model = KMeans().setK(4).setSeed(1).fit(x)
        got = model.clusterCenters()
        # each true center has a fitted center within ~noise distance
        for c in true_centers:
            assert np.min(np.linalg.norm(got - c, axis=1)) < 1.0
        assert model.numIter >= 1
        assert np.isfinite(model.trainingCost)

    def test_matches_numpy_lloyd_from_same_init(self, rng):
        """Seeded framework init fed to a numpy Lloyd oracle must converge to
        the same centers (exact algorithm equivalence, not just quality)."""
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.kmeans import kmeans_plusplus_init, lloyd

        x, _, _ = make_blobs(rng, n=200, d=5, k=3)
        import jax

        key = jax.random.key(7)
        mask = jnp.ones(200, dtype=x.dtype)
        init = np.asarray(kmeans_plusplus_init(jnp.asarray(x), mask, key, 3))
        ours, cost, _ = lloyd(jnp.asarray(x), mask, jnp.asarray(init), max_iter=50, tol=1e-6)
        theirs, ref_cost = numpy_lloyd(x, init, max_iter=50, tol=1e-6)
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-6)
        np.testing.assert_allclose(float(cost), ref_cost, rtol=1e-8)

    def test_cost_decreases_vs_init(self, rng):
        import jax
        import jax.numpy as jnp

        from spark_rapids_ml_tpu.ops.kmeans import kmeans_plusplus_init, lloyd, lloyd_step
        from spark_rapids_ml_tpu.ops.linalg import _dot_precision

        x, _, _ = make_blobs(rng, n=150, d=4, k=5, sep=2.0)
        xs = jnp.asarray(x)
        mask = jnp.ones(150, dtype=x.dtype)
        init = kmeans_plusplus_init(xs, mask, jax.random.key(0), 5)
        _, init_cost = lloyd_step(xs, mask, init, jnp.sum(xs * xs, 1), _dot_precision("highest"))
        _, final_cost, _ = lloyd(xs, mask, init, max_iter=30)
        assert float(final_cost) <= float(init_cost) + 1e-9

    def test_random_init_mode(self, rng):
        x, _, _ = make_blobs(rng)
        model = KMeans().setK(4).setInitMode("random").setSeed(3).fit(x)
        assert model.clusterCenters().shape == (4, 8)

    def test_cosine_distance(self, rng):
        # two directions, different magnitudes: cosine must cluster by angle
        a = np.array([1.0, 0.0]) * rng.uniform(0.5, 5.0, size=(50, 1))
        b = np.array([0.0, 1.0]) * rng.uniform(0.5, 5.0, size=(50, 1))
        x = np.concatenate([a, b])
        model = KMeans().setK(2).setDistanceMeasure("cosine").setSeed(0).fit(x)
        pred = model.predict(x)
        assert len(set(pred[:50])) == 1
        assert len(set(pred[50:])) == 1
        assert pred[0] != pred[50]

    def test_k_exceeds_rows(self, rng):
        with pytest.raises(ValueError):
            KMeans().setK(10).fit(rng.normal(size=(5, 3)))

    def test_invalid_params(self):
        with pytest.raises(ValueError):
            KMeans().setInitMode("zzz")
        with pytest.raises(ValueError):
            KMeans().setDistanceMeasure("manhattan")
        with pytest.raises((TypeError, ValueError)):
            KMeans().setK(1)  # k must be > 1


class TestKMeansModel:
    def test_transform_dataframe(self, rng):
        x, _, _ = make_blobs(rng, n=100)
        df = DataFrame({"features": list(x)})
        model = KMeans().setK(4).setSeed(0).fit(df)
        out = model.transform(df)
        assert "prediction" in out.columns
        labels = out.select("prediction")
        assert len(labels) == 100
        assert all(0 <= l < 4 for l in labels)

    def test_predict_consistent_with_centers(self, rng):
        x, _, _ = make_blobs(rng, n=80)
        model = KMeans().setK(4).setSeed(0).fit(x)
        pred = model.predict(x)
        d2 = ((x[:, None, :] - model.clusterCenters()[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(pred, d2.argmin(1))

    def test_compute_cost(self, rng):
        x, _, _ = make_blobs(rng, n=80)
        model = KMeans().setK(4).setSeed(0).fit(x)
        d2 = ((x[:, None, :] - model.clusterCenters()[None]) ** 2).sum(-1)
        np.testing.assert_allclose(model.computeCost(x), d2.min(1).sum(), rtol=1e-6)

    def test_read_write(self, tmp_path, rng):
        x, _, _ = make_blobs(rng, n=60)
        model = KMeans().setK(3).setSeed(0).setPredictionCol("cluster").fit(x)
        path = str(tmp_path / "km")
        model.save(path)
        loaded = KMeansModel.load(path)
        np.testing.assert_allclose(loaded.clusterCenters(), model.clusterCenters())
        assert loaded.getPredictionCol() == "cluster"
        assert loaded.trainingCost == pytest.approx(model.trainingCost)
        np.testing.assert_array_equal(loaded.predict(x), model.predict(x))


class TestDistributed:
    def test_mesh_fit_matches_local(self, rng):
        x, true_centers, _ = make_blobs(rng, n=256, d=6, k=3)
        mesh = make_mesh((8, 1))
        m_mesh = KMeans(mesh=mesh).setK(3).setSeed(5).fit(x)
        m_local = KMeans().setK(3).setSeed(5).fit(x)
        # same seed but different row layouts may pick different inits; check
        # cluster QUALITY parity instead of exact centers
        assert m_mesh.computeCost(x) <= m_local.computeCost(x) * 1.05 + 1e-6
        for c in true_centers:
            assert np.min(np.linalg.norm(m_mesh.clusterCenters() - c, axis=1)) < 1.0

    def test_mesh_padding_rows_ignored(self, rng):
        x, true_centers, _ = make_blobs(rng, n=251, d=6, k=3)  # 251 % 8 != 0
        mesh = make_mesh((8, 1))
        model = KMeans(mesh=mesh).setK(3).setSeed(5).fit(x)
        for c in true_centers:
            assert np.min(np.linalg.norm(model.clusterCenters() - c, axis=1)) < 1.0


class TestReviewRegressions:
    def test_2d_mesh_feature_padding_sliced(self, rng):
        """d=7 on a (4,2) mesh pads features to 8; centers must come back (k,7)."""
        x, true_centers, _ = make_blobs(rng, n=128, d=7, k=3)
        mesh = make_mesh((4, 2))
        model = KMeans(mesh=mesh).setK(3).setSeed(1).fit(x)
        assert model.clusterCenters().shape == (3, 7)
        pred = model.predict(x)  # must not shape-mismatch
        assert pred.shape == (128,)

    def test_cosine_training_consistent_with_predict(self, rng):
        """Training assignments/cost must agree with the fitted model's own
        predict/computeCost (centers renormalized every Lloyd iteration)."""
        x = rng.normal(size=(200, 5)) + 2.0
        model = KMeans().setK(3).setDistanceMeasure("cosine").setSeed(0).fit(x)
        # centers are unit-norm
        np.testing.assert_allclose(
            np.linalg.norm(model.clusterCenters(), axis=1), 1.0, atol=1e-5
        )
        # trainingCost equals recomputed cosine cost on the training data
        assert model.computeCost(x) == pytest.approx(model.trainingCost, rel=1e-5)

    def test_model_persistence_is_per_cluster_rows(self, tmp_path, rng):
        """Spark KMeansModel on-disk layout: k rows of (clusterIdx, VectorUDT)."""
        pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        x, _, _ = make_blobs(rng, n=60, k=3)
        model = KMeans().setK(3).setSeed(0).fit(x)
        path = str(tmp_path / "km_rows")
        model.save(path)
        table = pq.read_table(f"{path}/data/part-00000.parquet")
        assert table.num_rows == 3
        assert set(table.column_names) == {"clusterIdx", "clusterCenter"}
        row0 = table.to_pylist()[0]
        assert row0["clusterCenter"]["type"] == 1  # dense VectorUDT struct


class TestWarmStart:
    """setInitialModel: resume/refine from an existing model's centers —
    the recovery path for interrupted long fits (mllib setInitialModel /
    cuML init-array semantics)."""

    def test_resume_converges_from_checkpoint(self, rng):
        from spark_rapids_ml_tpu.clustering import KMeans

        centers_true = np.array([[0.0, 0.0], [8.0, 8.0], [0.0, 8.0]])
        x = np.concatenate(
            [c + rng.normal(scale=0.4, size=(60, 2)) for c in centers_true]
        )
        # "Interrupted" fit: only 1 Lloyd iteration.
        partial = KMeans().setK(3).setSeed(0).setMaxIter(1).fit((x,))
        # Resume from its centers; a converged result must match a full fit.
        resumed = (
            KMeans().setK(3).setMaxIter(50).setInitialModel(partial).fit((x,))
        )
        full = KMeans().setK(3).setSeed(0).setMaxIter(50).fit((x,))
        assert resumed.trainingCost == pytest.approx(full.trainingCost, rel=1e-6)

    def test_shape_validation(self, rng):
        from spark_rapids_ml_tpu.clustering import KMeans

        x = rng.normal(size=(30, 4))
        with pytest.raises(ValueError, match="centers but k"):
            KMeans().setK(3).setInitialModel(np.zeros((2, 4))).fit((x,))
        with pytest.raises(ValueError, match="features"):
            KMeans().setK(2).setInitialModel(np.zeros((2, 3))).fit((x,))

    def test_copy_preserves_warm_start(self):
        from spark_rapids_ml_tpu.clustering import KMeans

        est = KMeans().setK(2).setInitialModel(np.zeros((2, 3)))
        assert est.copy({})._initial_centers.shape == (2, 3)

    def test_setter_raise_leaves_estimator_clean(self):
        from spark_rapids_ml_tpu.clustering import KMeans

        est = KMeans().setK(3)
        with pytest.raises(ValueError):
            est.setInitialModel(np.zeros(3))
        assert est._initial_centers is None  # no corrupted state
