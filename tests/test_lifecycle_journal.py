"""Crash-safety of the lifecycle journal: genuine ``SIGKILL`` mid-cycle
at every stage, then a fresh interpreter resumes the SAME cycle and
lands the SAME model — plus the torn/stale-journal rejection paths.

The kill harness is real: a subprocess arms a fatal fault at one stage
boundary and converts the injected fault into ``os.kill(getpid(),
SIGKILL)`` — no atexit handlers, no flushes, exactly the torn-state
shape a preempted node leaves behind. The restart path is exercised the
way an operator would run it: rebuild the (in-memory) serving runtime,
construct a controller over the surviving journal directory, call
``run_cycle`` again. Acceptance, per stage:

- the resumed cycle id equals the killed cycle's id (same cycle, not a
  new one);
- the registry ends with exactly ONE version — the fence makes register
  idempotent across the kill;
- the final incumbent is bit-identical to an uninterrupted run of the
  same cycle (deterministic solvers + journaled ingest split).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_ml_tpu.lifecycle import LifecycleController
from spark_rapids_ml_tpu.lifecycle.journal import CycleJournal
from spark_rapids_ml_tpu.models.kmeans import KMeans
from spark_rapids_ml_tpu.serving.server import ServingRuntime
from spark_rapids_ml_tpu.utils.tracing import clear_counters, counter_value

UID = "jk-km"
SEED = 3


def _data():
    rng = np.random.default_rng(11)
    x = rng.normal(size=(240, 5))
    x[:120] += 4.0
    return x


def _km_score(model, x, y):
    centers = np.asarray(model.clusterCenters())
    d = np.linalg.norm(x[:, None, :] - centers[None], axis=2).min(axis=1)
    return -float(d.mean())


def _controller(directory):
    est = KMeans(uid=UID).setK(2).setSeed(SEED)
    return LifecycleController(
        est, ServingRuntime(start=False), "km",
        score_fn=_km_score, directory=str(directory),
    )


_SCRIPT = r"""
import os, signal, sys
import jax
jax.config.update("jax_enable_x64", True)  # match the pytest session
import numpy as np
from spark_rapids_ml_tpu.lifecycle import LifecycleController
from spark_rapids_ml_tpu.models.kmeans import KMeans
from spark_rapids_ml_tpu.robustness import InjectedFault, faults
from spark_rapids_ml_tpu.serving.server import ServingRuntime

rng = np.random.default_rng(11)
x = rng.normal(size=(240, 5)); x[:120] += 4.0

def km_score(model, X, y):
    c = np.asarray(model.clusterCenters())
    return -float(np.linalg.norm(X[:, None, :] - c[None], axis=2).min(axis=1).mean())

ctrl = LifecycleController(
    KMeans(uid="jk-km").setK(2).setSeed(3),
    ServingRuntime(start=False), "km",
    score_fn=km_score, directory=os.environ["LIFE_DIR"],
)
with faults.inject(os.environ["LIFE_FAULT"]):
    try:
        ctrl.run_cycle(x)
    except InjectedFault:
        # the real thing: no unwind, no flush, no atexit
        os.kill(os.getpid(), signal.SIGKILL)
print("UNEXPECTED-COMPLETION")
"""

STAGE_SPECS = [
    ("ingest", "refit.ingest=1:fatal"),
    ("refit", "refit.ingest=2:fatal"),
    ("quality_gate", "refit.quality_gate=1:fatal"),
    ("register", "refit.swap=1:fatal"),
    ("warm", "refit.swap=2:fatal"),
    ("flip", "refit.swap=3:fatal"),
]


class TestKillEveryStage:
    @pytest.fixture(scope="class")
    def reference_centers(self, tmp_path_factory):
        """The uninterrupted run this whole matrix must reproduce."""
        d = tmp_path_factory.mktemp("ref")
        ctrl = _controller(d)
        out = ctrl.run_cycle(_data())
        assert out.action == "flipped" and out.version == 1
        return np.asarray(ctrl.model.clusterCenters())

    @pytest.mark.parametrize("stage,spec", STAGE_SPECS)
    def test_sigkill_then_resume_same_cycle(
        self, stage, spec, tmp_path, reference_centers
    ):
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
            "LIFE_DIR": str(tmp_path),
            "LIFE_FAULT": spec,
            "TPUML_RETRY_BASE_DELAY": "0",
        })
        script = tmp_path / "killer.py"
        script.write_text(_SCRIPT)
        proc = subprocess.run(
            [sys.executable, str(script)], env=env,
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == -signal.SIGKILL, (
            stage, proc.returncode, proc.stdout, proc.stderr,
        )
        assert "UNEXPECTED-COMPLETION" not in proc.stdout

        # Fresh interpreter (this one), empty registry — the operator's
        # restart. Same directory, same cycle.
        ctrl = _controller(tmp_path)
        out = ctrl.run_cycle(_data())
        assert out.action == "flipped", (stage, out)
        assert out.cycle == 0, f"{stage}: resumed a DIFFERENT cycle"
        assert ctrl.runtime.registry.versions("km") == [1], (
            f"{stage}: duplicate registration across the kill"
        )
        assert ctrl.runtime.registry.aliases("km") == {"prod": 1}
        got = np.asarray(ctrl.model.clusterCenters())
        assert np.array_equal(got, reference_centers), (
            f"{stage}: resumed cycle diverged from the uninterrupted run"
        )
        # the finished journal survives as the cycle's audit record
        j = json.loads((tmp_path / "cycle.json").read_text())
        assert j["finished"] and j["cycle"] == 0


class TestRegisterFence:
    def test_kill_between_register_and_mark_adopts_version(self, tmp_path):
        """The narrowest idempotency window: the registry accepted the
        candidate but the journal never heard — re-entry must ADOPT the
        version above the fence, not register a duplicate. Simulated
        in-process (the registry must survive the 'crash' for the
        version to still exist: the controller-only-death shape)."""
        ctrl = _controller(tmp_path)
        x = _data()
        clear_counters("lifecycle")

        # Run the cycle normally up to the gate, then hand-play register
        # without marking the journal — the pre-mark crash state.
        from spark_rapids_ml_tpu.lifecycle.journal import CycleJournal as CJ
        from spark_rapids_ml_tpu.robustness import InjectedFault, faults

        with faults.inject("refit.swap=1:fatal"):
            with pytest.raises(InjectedFault):
                ctrl.run_cycle(x)
        journal = CJ.resume_or_start(
            str(tmp_path), ctrl._identity, 99
        )
        assert journal.done("quality_gate") and not journal.done("register")
        candidate = ctrl.model  # None — load the journaled candidate
        from spark_rapids_ml_tpu.lifecycle.controller import _load_pickle

        candidate = _load_pickle(journal.payload("refit")["model"])
        ctrl.runtime.register("km", candidate)  # landed, never journaled

        resumed = LifecycleController(
            KMeans(uid=UID).setK(2).setSeed(SEED), ctrl.runtime, "km",
            score_fn=_km_score, directory=str(tmp_path),
        )
        out = resumed.run_cycle(x)
        assert out.action == "flipped" and out.version == 1
        assert ctrl.runtime.registry.versions("km") == [1]
        assert counter_value("lifecycle.register.adopted") == 1


class TestTornAndStaleJournal:
    ID = {"name": "km", "estimator": "KMeans"}

    def _write_valid(self, d, cycle=0):
        j = CycleJournal.resume_or_start(str(d), self.ID, cycle)
        j.mark("ingest", {"data": "x"})
        return j

    def test_torn_journal_rejected_with_fallback(self, tmp_path):
        self._write_valid(tmp_path)
        path = tmp_path / "cycle.json"
        raw = path.read_bytes()
        path.write_bytes(raw[: len(raw) // 2])  # torn mid-record
        clear_counters("lifecycle")
        j = CycleJournal.resume_or_start(str(tmp_path), self.ID, 7)
        assert j.cycle == 7 and not j.done("ingest")  # fresh fallback
        assert counter_value("lifecycle.journal.rejected") == 1
        assert (tmp_path / "cycle.json.rejected").exists()  # evidence kept

    def test_stale_identity_rejected(self, tmp_path):
        self._write_valid(tmp_path)
        clear_counters("lifecycle")
        other = {"name": "km", "estimator": "LogisticRegression"}
        j = CycleJournal.resume_or_start(str(tmp_path), other, 3)
        assert j.cycle == 3 and not j.done("ingest")
        assert counter_value("lifecycle.journal.rejected") == 1

    def test_unknown_schema_rejected(self, tmp_path):
        (tmp_path / "cycle.json").write_text(
            json.dumps({"schema": 999, "cycle": 0, "stages": {},
                        "identity": self.ID, "finished": False})
        )
        clear_counters("lifecycle")
        j = CycleJournal.resume_or_start(str(tmp_path), self.ID, 2)
        assert j.cycle == 2
        assert counter_value("lifecycle.journal.rejected") == 1

    def test_rejected_journal_never_resumes_controller(self, tmp_path):
        """End to end: a torn journal must not wedge the controller —
        it starts a fresh cycle and completes."""
        (tmp_path / "cycle.json").write_text('{"schema": 1, "cyc')
        ctrl = _controller(tmp_path)
        out = ctrl.run_cycle(_data())
        assert out.action == "flipped" and out.version == 1
