"""Wide-feature regime (VERDICT r2 #6): the randomized sketch now covers
mesh-sharded and re-iterable streaming inputs, so d >= 4096 has a story
that never materializes a (d, d) covariance on one device — beating the
reference's 65535 packed cap (RapidsRowMatrix.scala:66-68) AND its GEMM
path's one-device covariance requirement."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_ml_tpu.feature import PCA
from spark_rapids_ml_tpu.parallel.mesh import make_mesh
from spark_rapids_ml_tpu.utils.testing import assert_components_close

REPO = str(Path(__file__).resolve().parents[1])


def _decaying(rng, n, d, rank=8):
    """Low-rank + noise data with a spectrum the sketch resolves."""
    u = rng.normal(size=(n, rank))
    v = rng.normal(size=(rank, d))
    scales = np.exp(-np.arange(rank) / 2.0)[None, :]
    return (u * scales) @ v + 0.05 * rng.normal(size=(n, d))


def _oracle(x, k):
    xc = x - x.mean(axis=0)
    cov = xc.T @ xc / (x.shape[0] - 1)
    w, v = np.linalg.eigh(cov)
    w, v = w[::-1], v[:, ::-1]
    return v[:, :k], (w / w.sum())[:k]


class TestRandomizedStreaming:
    def test_factory_matches_oracle(self, rng):
        x = _decaying(rng, 2500, 300)
        blocks = [x[i : i + 512] for i in range(0, 2500, 512)]
        model = PCA().setK(4).setSolver("randomized").fit(lambda: iter(blocks))
        pc_o, ev_o = _oracle(x, 4)
        assert_components_close(model.pc, pc_o, 1e-4)
        np.testing.assert_allclose(model.explainedVariance, ev_o, atol=1e-5)

    def test_matches_materialized_sketch_quality(self, rng):
        # Streamed and materialized sketches see the same data; both must
        # land on the oracle (they use different but equivalent algebra).
        x = _decaying(rng, 1500, 200)
        m_stream = (
            PCA().setK(3).setSolver("randomized").fit(lambda: iter([x]))
        )
        m_mat = PCA().setK(3).setSolver("randomized").fit(x)
        pc_o, _ = _oracle(x, 3)
        assert_components_close(m_stream.pc, pc_o, 1e-4)
        assert_components_close(m_mat.pc, pc_o, 1e-4)

    def test_uncentered_stream_matches_materialized_ratios(self, rng):
        # center=False: Ritz values are RAW second moments — the streamed
        # denominator must be the raw trace, not the centered one (r3
        # review: offset data inflated ratios ~25x).
        x = rng.normal(size=(400, 30)) + 5.0
        m_stream = (
            PCA()
            .setK(3)
            .setSolver("randomized")
            .setMeanCentering(False)
            .fit(lambda: iter([x[:250], x[250:]]))
        )
        m_mat = (
            PCA().setK(3).setSolver("randomized").setMeanCentering(False).fit(x)
        )
        # Dominant ratio tight; the near-degenerate tail (~0.002) carries
        # sketch-approximation noise in BOTH solvers — absolute tolerance.
        np.testing.assert_allclose(
            m_stream.explainedVariance, m_mat.explainedVariance, atol=1e-4
        )
        assert m_stream.explainedVariance[0] == pytest.approx(
            m_mat.explainedVariance[0], rel=1e-6
        )
        assert m_stream.explainedVariance[0] <= 1.0 + 1e-6

    def test_ragged_blocks_reuse_compiled_buckets(self, rng):
        # Ragged block heights pad to power-of-two buckets with MEAN rows
        # (which center to zero) — results stay exact.
        x = _decaying(rng, 1000, 120)
        ragged = [x[:333], x[333:700], x[700:999], x[999:]]
        model = PCA().setK(3).setSolver("randomized").fit(lambda: iter(ragged))
        pc_o, _ = _oracle(x, 3)
        assert_components_close(model.pc, pc_o, 1e-4)

    def test_streaming_with_mesh_rejected_loudly(self, rng):
        x = rng.normal(size=(100, 8))
        with pytest.raises(ValueError, match="single-device"):
            PCA(mesh=make_mesh()).setK(2).setSolver("randomized").fit(
                lambda: iter([x])
            )

    def test_one_shot_generator_rejected(self, rng):
        x = rng.normal(size=(100, 8))
        gen = (b for b in [x])
        with pytest.raises(ValueError, match="one-shot"):
            PCA().setK(2).setSolver("randomized").fit(gen)

    def test_one_shot_generator_stays_on_covariance_path_at_any_width(
        self, rng, monkeypatch
    ):
        monkeypatch.setattr(PCA, "_RANDOMIZED_AUTO_DIM", 16)
        x = rng.normal(size=(200, 32))
        gen = (b for b in [x[:100], x[100:]])
        model = PCA().setK(2).fit(gen)  # auto: must NOT try to re-read
        pc_o, _ = _oracle(x, 2)
        assert_components_close(model.pc, pc_o, 1e-6)

    def test_auto_routes_wide_reiterable_stream_to_sketch(
        self, rng, monkeypatch
    ):
        import spark_rapids_ml_tpu.ops.randomized as R

        called = {}
        orig = R.randomized_pca_streaming

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        monkeypatch.setattr(R, "randomized_pca_streaming", spy)
        monkeypatch.setattr(PCA, "_RANDOMIZED_AUTO_DIM", 64)
        x = _decaying(rng, 1200, 128)
        blocks = [x[i : i + 256] for i in range(0, 1200, 256)]
        model = PCA().setK(3).fit(lambda: iter(blocks))
        assert called.get("yes"), "auto did not route to the streaming sketch"
        pc_o, _ = _oracle(x, 3)
        assert_components_close(model.pc, pc_o, 1e-4)


class TestRandomizedMesh:
    def test_mesh_matches_oracle(self, rng):
        x = _decaying(rng, 1100, 160)  # 1100 pads to the 8-device data axis
        parts = [x[:400], x[400:]]
        model = (
            PCA(mesh=make_mesh()).setK(4).setSolver("randomized").fit(parts)
        )
        pc_o, ev_o = _oracle(x, 4)
        assert_components_close(model.pc, pc_o, 1e-4)
        np.testing.assert_allclose(model.explainedVariance, ev_o, atol=1e-5)

    def test_auto_routes_wide_mesh_to_sketch(self, rng, monkeypatch):
        import spark_rapids_ml_tpu.ops.randomized as R

        called = {}
        orig = R.randomized_pca

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        monkeypatch.setattr(R, "randomized_pca", spy)
        monkeypatch.setattr(PCA, "_RANDOMIZED_AUTO_DIM", 64)
        x = _decaying(rng, 900, 96)
        model = PCA(mesh=make_mesh()).setK(3).fit(x)
        assert called.get("yes"), "auto did not route the mesh fit to the sketch"
        pc_o, _ = _oracle(x, 3)
        assert_components_close(model.pc, pc_o, 1e-4)

    def test_model_axis_mesh_divisible_works(self, rng):
        # Features divisible by the model axis: the sketch GEMMs contract
        # over the sharded feature dim (GSPMD inserts the psum) — no
        # padding, no (d, d), correct results.
        x = _decaying(rng, 800, 64)
        model = (
            PCA(mesh=make_mesh((4, 2))).setK(3).setSolver("randomized").fit(x)
        )
        pc_o, _ = _oracle(x, 3)
        assert_components_close(model.pc, pc_o, 1e-4)

    def test_auto_2d_mesh_indivisible_width_falls_back(self, rng, monkeypatch):
        # auto must pick a WORKING path: wide d that the model axis would
        # pad keeps the mesh covariance instead of crashing in the sketch.
        monkeypatch.setattr(PCA, "_RANDOMIZED_AUTO_DIM", 64)
        x = rng.normal(size=(160, 65)) * np.linspace(1, 2, 65)
        model = PCA(mesh=make_mesh((4, 2))).setK(3).fit(x)
        pc_o, _ = _oracle(x, 3)
        assert_components_close(model.pc, pc_o, 1e-6)

    def test_model_axis_padding_rejected(self, rng):
        x = rng.normal(size=(160, 31))  # 31 pads on a model axis of 2
        with pytest.raises(ValueError, match="model axis"):
            PCA(mesh=make_mesh((4, 2))).setK(2).setSolver("randomized").fit(x)


class TestWideBoundedMemory:
    @pytest.mark.slow  # ~21 s; runs full-file in CI's Streamed-fit memory bounds step
    def test_16kx8192_streamed_sketch_bounded_rss(self):
        """A 16384 x 8192 fit (1.0 GB as f64 — the matrix is NEVER
        materialized: blocks are computed on demand) at bounded RSS, with
        an orthonormal result. The two former ValueErrors
        (randomized+streaming, randomized+mesh) are gone; this drives the
        streaming one at a width where the (d, d) covariance (512 MB)
        plus the eigh workspace would dwarf the sketch state (d*l ~ 1 MB).
        """
        script = f"""
import resource, sys
sys.path.insert(0, {REPO!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from spark_rapids_ml_tpu.feature import PCA

n, d, bs = 16384, 8192, 2048
def blocks():
    for i in range(n // bs):
        rng = np.random.default_rng(100 + i)  # per-block, recomputable
        yield rng.normal(size=(bs, d))

base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
model = PCA().setK(4).setSolver("randomized").fit(blocks)
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
pc = model.pc
assert pc.shape == (d, 4), pc.shape
g = pc.T @ pc
assert np.abs(g - np.eye(4)).max() < 1e-4, g
print("GROWTH_KB", peak - base)
"""
        import os

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            env=env,
            cwd=REPO,
            timeout=560,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        growth_kb = int(out.stdout.split("GROWTH_KB")[1].strip())
        # Full matrix is ~1.05 GB f64 (+ an f32 device copy would be
        # another 512 MB); sketch state is O(d*l + one block). Bound is
        # loose for XLA CPU arenas but decisively below materialization.
        assert growth_kb < 600_000, f"peak RSS grew {growth_kb} KB"
